"""PendingEnvelopes — SCP envelope intake: hold envelopes until their
referenced quorum sets and tx sets are available, fetching missing items.

Reference: src/herder/PendingEnvelopes.{h,cpp} — recvSCPEnvelope,
recvSCPQuorumSet, recvTxSet, envelope state machine (FETCHING/READY/
PROCESSED), caches; src/overlay/ItemFetcher.h — hash-addressed fetch
(the fetch transport is a callback here; overlay wires it to peers).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from .. import xdr as X
from ..crypto.sha import sha256
from ..scp.quorum import is_qset_sane, qset_hash
from ..util import logging as slog
from ..util.cache import RandomEvictionCache

log = slog.get("Herder")

# envelope intake verdicts (reference: Herder::EnvelopeStatus)
ENVELOPE_STATUS_DISCARDED = "discarded"
ENVELOPE_STATUS_FETCHING = "fetching"
ENVELOPE_STATUS_READY = "ready"
ENVELOPE_STATUS_PROCESSED = "processed"

QSET_CACHE_SIZE = 10000
TXSET_CACHE_SIZE = 10000


# one source of truth for the pledge-type -> quorumSetHash mapping
# (scp/quorum.py); re-exported here because every herder-layer consumer
# historically imports it from this module
from ..scp.quorum import statement_qset_hash  # noqa: E402,F401


def statement_values(st) -> List[bytes]:
    """All StellarValue blobs referenced by a statement.
    Reference: Slot::getStatementValues."""
    from ..xdr import scp as SX
    pl = st.pledges
    t = pl.type
    if t == SX.SCPStatementType.SCP_ST_NOMINATE:
        return list(pl.nominate.votes) + list(pl.nominate.accepted)
    if t == SX.SCPStatementType.SCP_ST_PREPARE:
        out = [pl.prepare.ballot.value]
        if pl.prepare.prepared is not None:
            out.append(pl.prepare.prepared.value)
        if pl.prepare.preparedPrime is not None:
            out.append(pl.prepare.preparedPrime.value)
        return out
    if t == SX.SCPStatementType.SCP_ST_CONFIRM:
        return [pl.confirm.ballot.value]
    return [pl.externalize.commit.value]


def statement_txset_hashes(st) -> List[bytes]:
    """Tx set hashes referenced by a statement's StellarValues (malformed
    values are reported by validation later, not here)."""
    out = []
    for v in statement_values(st):
        try:
            sv = X.StellarValue.from_xdr(v)
            out.append(sv.txSetHash)
        except X.XdrError:
            pass
    return out


class PendingEnvelopes:
    def __init__(self,
                 fetch_qset: Optional[Callable[[bytes], None]] = None,
                 fetch_txset: Optional[Callable[[bytes], None]] = None):
        # hash -> SCPQuorumSet / (TransactionSet, frames)
        self.qsets = RandomEvictionCache(QSET_CACHE_SIZE)
        self.txsets = RandomEvictionCache(TXSET_CACHE_SIZE)
        self.fetch_qset = fetch_qset or (lambda h: None)
        self.fetch_txset = fetch_txset or (lambda h: None)
        # slot -> list of (env, missing_qset_hashes, missing_txset_hashes)
        self.fetching: Dict[int, List] = {}
        self.ready: Dict[int, List] = {}
        # env xdr hash -> slot, for envelopes already handed to SCP
        # (GC'd with the slot in erase_below)
        self.processed_index: Dict[bytes, int] = {}
        # env xdr hash -> ENVELOPE_STATUS_{FETCHING,READY} for envelopes
        # currently queued (dedups re-received floods before processing)
        self.queued_index: Dict[bytes, str] = {}

    # -- item intake ------------------------------------------------------
    def add_qset(self, qset) -> bool:
        """Reference: PendingEnvelopes::recvSCPQuorumSet (+ sanity gate)."""
        if not is_qset_sane(qset):
            return False
        self.qsets.put(qset_hash(qset), qset)
        self._recheck()
        return True

    def add_txset(self, txset_hash: bytes, txset, frames) -> None:
        """Reference: PendingEnvelopes::recvTxSet."""
        self.txsets.put(txset_hash, (txset, frames))
        self._recheck()

    def get_qset(self, h: bytes):
        return self.qsets.get(h)

    def get_txset(self, h: bytes):
        got = self.txsets.get(h)
        return got if got is not None else None

    # -- envelope intake --------------------------------------------------
    def recv_envelope(self, env) -> str:
        """Returns an ENVELOPE_STATUS_*.  READY envelopes are queued in
        self.ready[slot] for the herder to pop."""
        slot = env.statement.slotIndex
        env_hash = sha256(env.to_xdr())
        if env_hash in self.processed_index:
            # Re-received (flooded or re-requested) envelope already handed
            # to SCP: discard without re-queuing (reference envelope state
            # machine returns PROCESSED for these).
            return ENVELOPE_STATUS_PROCESSED
        queued = self.queued_index.get(env_hash)
        if queued is not None:
            # Duplicate still in flight: report its current state without
            # re-queuing.  A FETCHING duplicate re-issues fetches for the
            # still-missing items — re-floods are the retry path for fetches
            # that found no peer with the item the first time.
            if queued == ENVELOPE_STATUS_FETCHING:
                mq, mt = self._missing(env.statement)
                for h in mq:
                    self.fetch_qset(h)
                for h in mt:
                    self.fetch_txset(h)
                self._recheck()
            return queued
        missing_q, missing_t = self._missing(env.statement)
        if not missing_q and not missing_t:
            self.ready.setdefault(slot, []).append((env, env_hash))
            self.queued_index[env_hash] = ENVELOPE_STATUS_READY
            return ENVELOPE_STATUS_READY
        for h in missing_q:
            self.fetch_qset(h)
        for h in missing_t:
            self.fetch_txset(h)
        self.fetching.setdefault(slot, []).append((env, env_hash))
        self.queued_index[env_hash] = ENVELOPE_STATUS_FETCHING
        return ENVELOPE_STATUS_FETCHING

    def _missing(self, st) -> Tuple[List[bytes], List[bytes]]:
        missing_q = []
        qh = statement_qset_hash(st)
        if self.qsets.get(qh) is None:
            missing_q.append(qh)
        missing_t = [h for h in statement_txset_hashes(st)
                     if self.txsets.get(h) is None]
        return missing_q, missing_t

    def _recheck(self) -> None:
        for slot in list(self.fetching):
            still = []
            for env, env_hash in self.fetching[slot]:
                mq, mt = self._missing(env.statement)
                if not mq and not mt:
                    self.ready.setdefault(slot, []).append((env, env_hash))
                    self.queued_index[env_hash] = ENVELOPE_STATUS_READY
                else:
                    still.append((env, env_hash))
            if still:
                self.fetching[slot] = still
            else:
                del self.fetching[slot]

    def pop_ready(self, slot: int) -> List:
        out = []
        for env, env_hash in self.ready.pop(slot, []):
            self.processed_index[env_hash] = slot
            self.queued_index.pop(env_hash, None)
            out.append(env)
        return out

    def has_ready(self) -> bool:
        return any(self.ready.values())

    def ready_slots(self) -> List[int]:
        return sorted(self.ready)

    # -- slot GC ----------------------------------------------------------
    def erase_below(self, slot: int) -> None:
        """Reference: PendingEnvelopes::eraseBelow (keep caches; drop
        per-slot pending envelopes)."""
        for d in (self.fetching, self.ready):
            for s in [s for s in d if s < slot]:
                for _env, env_hash in d[s]:
                    self.queued_index.pop(env_hash, None)
                del d[s]
        for h in [h for h, s in self.processed_index.items() if s < slot]:  # corelint: disable=iteration-order -- collects keys for keyed deletion; order-free
            del self.processed_index[h]
