"""QuorumTracker — the transitive quorum map observed from SCP traffic.

Reference: src/herder/QuorumTracker.{h,cpp} — rebuild/expand: starting from
the local node, walk quorum sets to find every transitively-referenced
node and its latest known qset; feeds /quorum?transitive=true and the
quorum intersection checker (checkAndMaybeReanalyzeQuorumMap).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Set

from ..scp.quorum import qset_nodes


class QuorumTracker:
    def __init__(self, local_node_id: bytes):
        self.local_node_id = local_node_id
        # node id -> qset (None = referenced but qset unknown yet)
        self.quorum_map: Dict[bytes, Optional[object]] = {local_node_id: None}

    def is_node_definitely_in_quorum(self, node_id: bytes) -> bool:
        return node_id in self.quorum_map

    def expand(self, node_id: bytes, qset) -> bool:
        """Record node_id's qset if node_id is already in the transitive
        quorum; returns False if a rebuild is needed (node unknown or qset
        changed).  Reference: QuorumTracker::expand."""
        cur = self.quorum_map.get(node_id, "absent")
        if cur == "absent":
            return False
        if cur is not None and cur is not qset and cur.to_xdr() != qset.to_xdr():
            return False
        self.quorum_map[node_id] = qset
        for n in qset_nodes(qset):
            if n not in self.quorum_map:
                self.quorum_map[n] = None
        return True

    def rebuild(self, lookup: Callable[[bytes], Optional[object]]) -> None:
        """Recompute the full transitive closure from the local node, using
        `lookup` for the latest known qset of each node.
        Reference: QuorumTracker::rebuild."""
        self.quorum_map = {}
        frontier = [self.local_node_id]
        while frontier:
            nid = frontier.pop()
            if nid in self.quorum_map:
                continue
            q = lookup(nid)
            self.quorum_map[nid] = q
            if q is not None:
                for n in qset_nodes(q):
                    if n not in self.quorum_map:
                        frontier.append(n)

    def known_map(self) -> Dict[bytes, Optional[object]]:
        return dict(self.quorum_map)

    @property
    def node_count(self) -> int:
        return len(self.quorum_map)
