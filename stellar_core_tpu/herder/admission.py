"""AdmissionPipeline — TPU-batched admission verification with back-pressure.

Reference seams: src/herder/TransactionQueue.{h,cpp} (``tryAdd`` is the
admission gate), src/overlay/FlowControl.{h,cpp} (capacity-granted flood
intake — the natural back-pressure valve), src/herder/TxSetUtils (surge
pricing — the eviction economics applied when the node is full).

The reference verifies every live-submitted envelope's signatures one at a
time inside ``tryAdd``'s checkValid.  This subsystem batches that work:
envelopes arriving from ``Application.submit_tx`` and overlay TRANSACTION
floods accumulate into accel-sized batches, the batch is verified through
the SAME dispatch-ahead/race-bounded-collect machinery ``PreverifyPipeline``
proved out for catchup (catchup/catchup.py), and the verified frames are
handed to ``TransactionQueue.try_add`` — whose SignatureChecker then hits
the seeded verify cache instead of calling libsodium per signature.

Latency floor guarantee:

- a batch is flushed on SIZE or DEADLINE, and when the pipeline is idle a
  lone submission is admitted synchronously (no deadline wait at all) —
  at low offered load admission IS the single-sig libsodium path plus a
  few dict operations;
- the race-bounded collect waits for the device no longer than libsodium
  would charge for the batch; a miss skips seeding and ``try_add``
  recomputes on CPU — so admission latency never regresses below the
  single-sig path, it only improves when the device genuinely wins.

Back-pressure, end to end:

- ``depth`` (submitted-but-unverified envelopes) is exported as
  ``herder.admission.depth`` and feeds three valves:
  1. at ``max_backlog`` new submissions answer ``try-again-later``
     (bounded queue, never unbounded growth);
  2. at ``backpressure_high`` the overlay STOPS handing peers fresh
     flow-control capacity (overlay/peer.py defers SEND_MORE grants) until
     the backlog drains to ``backpressure_low`` — hysteresis so the valve
     doesn't chatter;
  3. a full downstream TransactionQueue applies surge-pricing economics
     BEFORE verification: a tx priced under the queue's fee floor is
     rejected without spending any verify compute.
- ``/health`` reports a degraded node while back-pressure is engaged
  (main/status.evaluate_health), and engage/release edges are flight-
  recorded for post-mortems.
"""

from __future__ import annotations

import itertools
import time as _time  # perf_counter only (latency stats); timers use clock
from typing import Callable, Dict, List, Optional

from ..util import eventlog
from ..util import logging as slog
from ..util import tracing
from ..util.clock import VirtualClock, VirtualTimer
from ..util.metrics import registry as _registry
from ..util.racetrace import race_checked
from .tx_queue import AddResult, TransactionQueue

log = slog.get("Herder")

# batch ids share one process-wide counter so two pipelines (tests build
# several) can never collide inside a shared PreverifyPipeline
_BATCH_IDS = itertools.count(1)


class _Pending:
    __slots__ = ("frame", "t0", "origin", "on_result")

    def __init__(self, frame, t0: float, origin: str, on_result):
        self.frame = frame
        self.t0 = t0
        self.origin = origin
        self.on_result = on_result


@race_checked
class AdmissionPipeline:
    """Batched, back-pressured admission in front of a TransactionQueue.

    ``submit(frame)`` is the one entry point.  When the pipeline is idle
    the frame is admitted synchronously (identical observable semantics to
    calling ``try_add`` directly); under load frames accumulate into
    batches that flush on size or deadline, with the final verdict
    delivered through the optional ``on_result`` callback.
    """

    # default knobs (config: ADMISSION_*)
    BATCH_SIZE = 256          # flush when this many signatures are pending
    FLUSH_DELAY_S = 0.05      # deadline flush for a partial batch  # corelint: disable=float-discipline -- local pacing knob, never ledger state
    MAX_BACKLOG = 4096        # pending envelopes before try-again-later
    ACCEL_MIN_SIGS = 64       # below this the device overhead loses; CPU

    def __init__(self, tx_queue: TransactionQueue, lm, clock: VirtualClock,
                 accel: bool = False, accel_chunk: int = 2048,
                 batch_size: int = BATCH_SIZE,
                 flush_delay_s: float = FLUSH_DELAY_S,
                 max_backlog: int = MAX_BACKLOG,
                 accel_min_sigs: int = ACCEL_MIN_SIGS,
                 on_admitted: Optional[Callable] = None):
        self.tx_queue = tx_queue
        self.lm = lm
        self.clock = clock
        self.accel = accel
        self.accel_chunk = accel_chunk
        self.batch_size = batch_size
        self.flush_delay_s = flush_delay_s
        self.max_backlog = max_backlog
        self.accel_min_sigs = accel_min_sigs
        # hysteresis valve thresholds (overlay grant deferral)
        self.backpressure_high = max(1, max_backlog // 2)
        self.backpressure_low = max(0, max_backlog // 4)
        self.backpressured = False
        # fires when back-pressure RELEASES (overlay re-grants deferred
        # flow-control capacity); wired by Application/tests
        self.on_backpressure_release: Callable[[], None] = lambda: None
        # fires per ADMITTED frame (herder wires tx flooding here)
        self.on_admitted = on_admitted or (lambda frame, origin: None)

        # Pipeline state is owned by the main crank loop: submit() runs
        # either on it directly or marshalled there by http_admin, and
        # flush/collect are clock actions.  The depth gauge read from
        # admin threads is a GIL-atomic pair of len()s.
        self._pending: List[_Pending] = []  # corelint: owned-by=main -- submit/flush/collect all run on the crank loop; gauge reads are GIL-atomic
        # hashes of every frame the pipeline owns but try_add hasn't seen
        # yet — pending AND in-flight — so a duplicate submitted while
        # the original's batch is still verifying answers DUPLICATE
        # instead of burning a second verification
        self._tracked_hashes: set = set()
        self._pending_sigs = 0
        # burst detector: a submission arriving within one flush window of
        # the previous one is sustained load and joins a batch; a sparse
        # arrival takes the synchronous single-sig path (latency floor)
        self._last_submit_at = float("-inf")  # corelint: disable=float-discipline -- burst-detector sentinel, monitoring-only
        # batches dispatched to the device but not yet collected:
        # [(batch_id, [_Pending, ...])] in dispatch (collect) order
        self._inflight: List[tuple] = []  # corelint: owned-by=main -- dispatched/collected only by clock actions on the crank loop
        self._inflight_count = 0
        self._flush_timer: Optional[VirtualTimer] = None
        self._collect_posted = False

        self.stats: Dict[str, int] = {
            "submitted": 0, "admitted": 0, "rejected": 0, "overload": 0,
            "prefiltered": 0, "sync_path": 0, "batches": 0,
            "sigs_offloaded": 0,
        }

        # accel: the PreverifyPipeline IS the device machinery — batches
        # are dispatched as synthetic "checkpoints" and collected with the
        # race-bounded wait it proved out for catchup.  The kernel compile
        # happens off the critical path: a warmup batch is dispatched at
        # construction and admission stays on the CPU path until its
        # verdicts materialize (job_done), so no submission ever blocks
        # behind a cold compile or a wedged tunnel.
        self._preverify = None
        self._warm_id: Optional[int] = None
        self._warmed = False
        if accel:
            from ..catchup.catchup import PreverifyPipeline
            # EXPLICIT race profile: unlike catchup replay (which can fall
            # back to verifying during the apply), admission must hold the
            # batch's verdicts in hand to answer each submitter — the
            # bounded race-wait is the right contract here even though
            # catchup's default moved to the never-wait poll profile
            self._preverify = PreverifyPipeline(
                lm.network_id, chunk_size=accel_chunk, stats=self.stats,
                profile=PreverifyPipeline.PROFILE_RACE)
            self._dispatch_warmup()

        _registry().weak_gauge("herder.admission.depth", self,
                               lambda a: a.depth)

    # ------------------------------------------------------------------
    @property
    def depth(self) -> int:
        """Submitted-but-unverified envelopes (the back-pressure signal)."""
        return len(self._pending) + self._inflight_count

    def _set_backpressure(self, engaged: bool) -> None:
        if engaged == self.backpressured:
            return
        self.backpressured = engaged
        eventlog.record("Herder", "WARNING" if engaged else "INFO",
                        "admission back-pressure "
                        + ("engaged" if engaged else "released"),
                        depth=self.depth,
                        high=self.backpressure_high,
                        low=self.backpressure_low)
        if engaged:
            log.warning("admission back-pressure engaged at depth %d "
                        "(high=%d): deferring overlay flood grants",
                        self.depth, self.backpressure_high)
        else:
            self.on_backpressure_release()

    def _update_backpressure(self) -> None:
        d = self.depth
        if not self.backpressured and d >= self.backpressure_high:
            self._set_backpressure(True)
        elif self.backpressured and d <= self.backpressure_low:
            self._set_backpressure(False)

    # ------------------------------------------------------------------
    # intake
    # ------------------------------------------------------------------
    def submit(self, frame, origin: str = "api",
               on_result: Optional[Callable[[AddResult], None]] = None
               ) -> AddResult:
        """Admit one envelope.  Fast-fail gates (ban/duplicate/overload/
        fee floor) answer immediately; an idle pipeline admits
        synchronously (the exact ``try_add`` verdict); otherwise the frame
        joins the current batch and the optimistic ``pending`` answer is
        returned, with the final verdict delivered to ``on_result`` after
        the batch verifies."""
        self.stats["submitted"] += 1
        q = self.tx_queue
        h = frame.content_hash()
        # gates that need no signature verification, in try_add's order
        if q.is_banned(h):
            return self._reject(AddResult(AddResult.STATUS_BANNED),
                                on_result)
        if h in q.by_hash or h in self._tracked_hashes:
            return self._reject(AddResult(AddResult.STATUS_DUPLICATE),
                                on_result)
        if self.depth >= self.max_backlog:
            # bounded intake: overload answers try-again-later instead of
            # growing the backlog without limit
            self.stats["overload"] += 1
            _registry().meter("herder.admission.overload").mark()
            eventlog.record("Herder", "WARNING", "admission overload",
                            depth=self.depth, max_backlog=self.max_backlog)
            return self._reject(
                AddResult(AddResult.STATUS_TRY_AGAIN_LATER), on_result)
        if q.below_fee_floor(frame):
            # surge-pricing economics BEFORE verification: a full queue
            # would evict-or-reject this tx anyway; don't verify it
            self.stats["prefiltered"] += 1
            return self._reject(
                AddResult(AddResult.STATUS_TRY_AGAIN_LATER), on_result)

        t0 = _time.perf_counter()
        now = self.clock.now()
        burst = (now - self._last_submit_at) < self.flush_delay_s
        self._last_submit_at = now
        if not burst and not self._pending and not self._inflight:
            # idle pipeline, sparse arrival: the latency floor.  Verify
            # and admit NOW on the single-sig CPU path — no deadline
            # wait, no batching tax.  Under sustained load (arrivals
            # within one flush window of each other) frames accumulate
            # into batches instead.
            self.stats["sync_path"] += 1
            res = self._admit(frame, t0, origin)
            if on_result is not None:
                on_result(res)
            return res

        self._pending.append(_Pending(frame, t0, origin, on_result))
        self._tracked_hashes.add(h)
        self._pending_sigs += len(frame.signatures)
        self._update_backpressure()
        if self._pending_sigs >= self.batch_size:
            self._flush()
        else:
            self._arm_flush_timer()
        return AddResult(AddResult.STATUS_PENDING)

    def _reject(self, res: AddResult, on_result) -> AddResult:
        self.stats["rejected"] += 1
        _registry().meter("herder.admission.rejected").mark()
        if on_result is not None:
            on_result(res)
        return res

    # ------------------------------------------------------------------
    # flush machinery
    # ------------------------------------------------------------------
    def _arm_flush_timer(self) -> None:
        if self._flush_timer is not None and self._flush_timer.seated:
            return
        t = VirtualTimer(self.clock)
        t.expires_from_now(self.flush_delay_s, self._deadline_flush)
        self._flush_timer = t

    def _deadline_flush(self) -> None:
        if self._pending:
            self._flush()

    def _flush(self) -> None:
        """Form a batch from everything pending and move it to the
        verification stage: device dispatch (accel, warmed, big enough) or
        straight to the CPU finish action."""
        if self._flush_timer is not None:
            self._flush_timer.cancel()
            self._flush_timer = None
        batch, self._pending = self._pending, []
        # _tracked_hashes keeps the batch's hashes until collect: the
        # frames are in flight, not gone
        sigs, self._pending_sigs = self._pending_sigs, 0
        if not batch:
            return
        self.stats["batches"] += 1
        _registry().meter("herder.admission.flush").mark()
        _registry().histogram("herder.admission.batch-size").update(
            len(batch))
        eventlog.record("Herder", "INFO", "admission batch flushed",
                        txs=len(batch), sigs=sigs, depth=self.depth)
        tracing.mark_phase("admission-flush",
                           self.lm.last_closed_ledger_seq + 1,
                           txs=len(batch), sigs=sigs)
        bid = next(_BATCH_IDS)
        self._maybe_collect_warmup()
        if self._preverify is not None and self._warmed \
                and sigs >= self.accel_min_sigs:
            # dispatch-ahead: the device batch is enqueued NOW (no sync);
            # the race-bounded collect runs as a posted action, so batch
            # k+1 can form (and dispatch) while batch k computes
            self._preverify.dispatch({bid: [p.frame for p in batch]},
                                     ledger_state=self.lm.root)
            self.stats["sigs_offloaded"] += sigs
            _registry().counter("herder.admission.sigs-offloaded").inc(sigs)
        else:
            bid = -bid   # CPU batch: no device group to collect
        self._inflight.append((bid, batch))
        self._inflight_count += len(batch)
        self._post_collect()

    def _post_collect(self) -> None:
        if not self._collect_posted and self._inflight:
            self._collect_posted = True
            self.clock.post_action(self._collect_next,
                                   name="admission-collect")

    def _collect_next(self) -> None:
        """Finish the oldest in-flight batch: race-bounded collect of its
        device verdicts (seeds the verify cache; a miss just means
        ``try_add`` recomputes on CPU — verdicts identical), then hand
        every frame to the TransactionQueue."""
        self._collect_posted = False
        if not self._inflight:
            return
        bid, batch = self._inflight.pop(0)
        self._inflight_count -= len(batch)
        if bid > 0:
            self._preverify.collect(bid)
        for p in batch:
            self._tracked_hashes.discard(p.frame.content_hash())
            res = self._admit(p.frame, p.t0, p.origin)
            if p.on_result is not None:
                p.on_result(res)
        self._update_backpressure()
        self._post_collect()

    def _admit(self, frame, t0: float, origin: str) -> AddResult:
        res = self.tx_queue.try_add(frame)
        dt = _time.perf_counter() - t0
        _registry().timer("herder.admission.latency").update(dt)
        if res.code == AddResult.STATUS_PENDING:
            self.stats["admitted"] += 1
            _registry().meter("herder.admission.admitted").mark()
            self.on_admitted(frame, origin)
        else:
            self.stats["rejected"] += 1
            _registry().meter("herder.admission.rejected").mark()
        return res

    # ------------------------------------------------------------------
    # accel warmup
    # ------------------------------------------------------------------
    def _dispatch_warmup(self) -> None:
        """Enqueue a throwaway batch so the device kernel compiles off the
        critical path.  Admission keeps using the CPU path until the warm
        verdicts materialize; a wedged tunnel therefore degrades to CPU
        admission without ever blocking a submission."""
        from ..crypto.keys import SecretKey
        from ..crypto.sha import sha256
        from ..testutils import build_tx, native_payment_op
        from .. import xdr as X
        sk = SecretKey(sha256(b"admission warmup throwaway key"))
        frame = build_tx(self.lm.network_id, sk, 1, [native_payment_op(
            X.AccountID.ed25519(sk.public_key.ed25519), 1)])
        self._warm_id = next(_BATCH_IDS)
        self._preverify.dispatch({self._warm_id: [frame]})

    def _maybe_collect_warmup(self) -> None:
        if self._warmed or self._preverify is None \
                or self._warm_id is None:
            return
        if self._preverify.job_done(self._warm_id):
            # non-blocking: the device event is already set
            self._preverify.collect(self._warm_id)
            self._warm_id = None
            self._warmed = True
            log.info("admission accel path warmed (kernel compiled); "
                     "batches >= %d sigs now dispatch to the device",
                     self.accel_min_sigs)

    # ------------------------------------------------------------------
    def drain(self, max_crank: int = 10_000) -> None:
        """Crank the clock until every submitted envelope has a verdict
        (loadgen/test convenience; the live node just cranks)."""
        n = 0
        while self.depth > 0 and n < max_crank:
            if self._pending and (self._flush_timer is None
                                  or not self._flush_timer.seated):
                self._flush()
            self.clock.crank()
            n += 1

    def close(self) -> None:
        if self._preverify is not None:
            self._preverify.close()
        if self._flush_timer is not None:
            self._flush_timer.cancel()
