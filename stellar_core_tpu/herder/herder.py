"""Herder — drives SCP per ledger and glues consensus to the ledger.

Reference: src/herder/HerderImpl.{h,cpp} — recvSCPEnvelope, recvTransaction,
recvTxSet/recvSCPQuorumSet, triggerNextLedger, valueExternalized,
processSCPQueue, out-of-sync detection; src/herder/HerderSCPDriver.{h,cpp} —
the SCPDriver implementation (validateValue, combineCandidates,
signEnvelope/verifyEnvelope with the network-bound SCP envelope domain,
emitEnvelope, timers).  Merged into one class here: the driver half is the
SCPDriver overrides, the herder half is the public node API — the split in
the reference exists for header-dependency reasons this package doesn't
have.
"""

from __future__ import annotations

import struct
from typing import Callable, Dict, List, Optional, Tuple

from .. import xdr as X
from ..crypto import keys
from ..crypto.keys import SecretKey
from ..crypto.sha import sha256
from ..scp.driver import SCPDriver, ValidationLevel
from ..scp.scp import SCP, EnvelopeState
from ..soroban import (decode_tx_set, tx_set_envelopes,
                       tx_set_previous_hash)
from ..util import detguard
from ..util import eventlog
from ..util import logging as slog
from ..util import tracing
from ..util.clock import VirtualClock, VirtualTimer
from ..util.metrics import registry as _registry
from .pending_envelopes import (ENVELOPE_STATUS_DISCARDED,
                                ENVELOPE_STATUS_FETCHING,
                                ENVELOPE_STATUS_PROCESSED,
                                ENVELOPE_STATUS_READY, PendingEnvelopes)
from .quorum_tracker import QuorumTracker
from .tx_queue import AddResult, TransactionQueue
from .upgrades import Upgrades

log = slog.get("Herder")

# Reference: src/herder/Herder.h
EXP_LEDGER_TIMESPAN_SECONDS = 5
MAX_SCP_TIMEOUT_SECONDS = 240
CONSENSUS_STUCK_TIMEOUT_SECONDS = 35
MAX_TIME_SLIP_SECONDS = 60
NODE_EXPIRATION_SECONDS = 240
LEDGER_VALIDITY_BRACKET = 100        # max slots ahead we accept
MAX_SLOTS_TO_REMEMBER = 12

ENVELOPE_TYPE_SCP = 1  # reference: Stellar-ledger-entries.x — EnvelopeType


class HerderState:
    # Reference: Herder::State
    BOOTING = "booting"
    SYNCING = "syncing"
    TRACKING = "tracking"


class Herder(SCPDriver):
    """One node's consensus engine.

    Wiring: `broadcast` is injected by the overlay (or the in-process
    simulation bus); `out_of_sync_handler` is the catchup handoff.
    """

    def __init__(self, clock: VirtualClock, ledger_manager,
                 secret: SecretKey, qset,
                 is_validator: bool = True,
                 upgrades: Optional[Upgrades] = None):
        self.clock = clock
        self.lm = ledger_manager
        self.secret = secret
        self.node_id = secret.public_key.ed25519
        self.network_id = ledger_manager.network_id
        self.is_validator = is_validator
        self.upgrades = upgrades or Upgrades()

        self.scp = SCP(self, self.node_id, is_validator, qset)
        self.pending = PendingEnvelopes()
        self.tx_queue = TransactionQueue(ledger_manager)
        # node -> last announced qset hash: quorum-tracker maintenance
        # runs only when a node actually CHANGES its quorum set, not once
        # per envelope (the expand walk re-encoded the qset per envelope
        # — measurably hot at 150+ simulated nodes)
        self._node_qset_hash: Dict[bytes, bytes] = {}
        # batched admission (herder/admission.py); None = legacy inline
        # single-sig intake.  Installed via enable_admission().
        self.admission = None
        self.quorum_tracker = QuorumTracker(self.node_id)
        self.pending.add_qset(qset)

        self.state = HerderState.BOOTING
        self.broadcast: Callable[[object], None] = lambda env: None
        self.tx_flood: Callable[[object], None] = lambda frame: None
        self.out_of_sync_handler: Callable[[], None] = lambda: None
        # observability hook (survey lostSyncCount); fires on each
        # tracking -> syncing transition
        self.lost_sync_hook: Callable[[], None] = lambda: None
        self.ledger_closed_hook: Callable[[object], None] = lambda arts: None

        self.db = None  # database.Database; attach_persistence()
        # reference: Config::ARTIFICIALLY_ACCELERATE_TIME_FOR_TESTING
        self.ledger_timespan = EXP_LEDGER_TIMESPAN_SECONDS
        self._timers: Dict[Tuple[int, int], VirtualTimer] = {}
        self._trigger_timer: Optional[VirtualTimer] = None
        self._tracking_timer: Optional[VirtualTimer] = None
        self._last_trigger_at: float = clock.now()
        # slot -> externalized StellarValue waiting for its ledger turn
        self._buffered: Dict[int, X.StellarValue] = {}
        self._processing_ready = False
        # slot -> perf_counter at nomination trigger (scp.slot.externalize
        # timer: nomination start -> value applied)
        self._nominate_started: Dict[int, float] = {}
        # last slot that got a tx-flood phase mark (one mark per slot)
        self._flood_marked_slot = 0
        # recovery bookkeeping: how often this node fell out of sync, how
        # many ledgers it applied from the buffered-externalize queue
        # while catching back up, and how often it had to resync from a
        # history archive — the chaos runner asserts a stalled validator
        # actually exercised these paths after rejoin instead of
        # inferring recovery from the LCL alone
        self.recovery_stats: Dict[str, int] = {"out_of_sync": 0,
                                               "buffered_applied": 0,
                                               "archive_catchups": 0}
        # fires every time the buffered-externalize queue dead-ends (the
        # next needed slot is older than any peer remembers) — the
        # archive-catchup handoff listens here.  Distinct from
        # out_of_sync_handler, which fires only on the TRACKING->SYNCING
        # edge: a node that is already syncing but discovers its gap
        # exceeds the fleet's slot memory must still reach the archive.
        self.sync_gap_hook: Callable[[], None] = lambda: None

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def bootstrap(self) -> None:
        """Go live assuming the LCL is current (standalone/test networks).
        Reference: HerderImpl::bootstrap (FORCE_SCP path)."""
        self._set_state(HerderState.TRACKING, "bootstrap")
        self._last_trigger_at = self.clock.now()
        self._arm_tracking_heartbeat()
        self.trigger_next_ledger(self.tracking_consensus_ledger_index() + 1)

    def start(self) -> None:
        """Go live and wait for consensus traffic before participating.
        Reference: HerderImpl::start/restoreState."""
        self._set_state(HerderState.SYNCING, "start")

    def _set_state(self, state: str, why: str) -> None:
        """State transitions are SCP phase edges — flight-recorded so a
        post-mortem shows when (and why) the node entered/left tracking."""
        if state == self.state:
            return
        old, self.state = self.state, state
        eventlog.record("SCP", "INFO", "herder state transition",
                        old=old, new=state, why=why,
                        lcl=self.tracking_consensus_ledger_index())

    def tracking_consensus_ledger_index(self) -> int:
        return self.lm.last_closed_ledger_seq

    def next_ledger_index(self) -> int:
        return self.tracking_consensus_ledger_index() + 1

    # ------------------------------------------------------------------
    # intake (called by overlay / HTTP / simulation)
    # ------------------------------------------------------------------
    # SCP statement pledge type -> per-phase meter suffix (observability:
    # the nomination/ballot phase mix is how an operator sees where
    # consensus rounds spend their envelopes)
    _PHASE_METERS = {0: "scp.envelope.prepare", 1: "scp.envelope.confirm",
                     2: "scp.envelope.externalize",
                     3: "scp.envelope.nominate"}

    def recv_scp_envelope(self, env) -> str:
        st = env.statement
        slot = st.slotIndex
        lcl = self.tracking_consensus_ledger_index()
        if slot <= lcl - MAX_SLOTS_TO_REMEMBER or \
                slot > lcl + LEDGER_VALIDITY_BRACKET:
            # The silent dead-end of every stuck-node incident: a node
            # whose gap exceeds the slot-memory window throws its peers'
            # (stale-looking) envelopes away and stops externalizing with
            # no externally visible cause.  Meter + flight-record the
            # discard so /dumpflight answers "why did this node stop".
            self._note_envelope_discarded(
                slot, lcl,
                "below-memory-window" if slot <= lcl - MAX_SLOTS_TO_REMEMBER
                else "beyond-validity-bracket")
            return ENVELOPE_STATUS_DISCARDED
        if not self.verify_envelope(env):
            self._note_envelope_discarded(slot, lcl, "bad-signature")
            return ENVELOPE_STATUS_DISCARDED
        _registry().meter("scp.envelope.receive").mark()
        phase = self._PHASE_METERS.get(int(st.pledges.type))
        if phase is not None:
            _registry().meter(phase).mark()
        status = self.pending.recv_envelope(env)
        if status == ENVELOPE_STATUS_READY:
            self._process_scp_queue()
        return status

    def _note_envelope_discarded(self, slot: int, lcl: int,
                                 reason: str) -> None:
        _registry().meter("herder.scp.envelope-discarded").mark()
        eventlog.record("SCP", "WARNING", "scp envelope discarded",
                        slot=slot, lcl=lcl, reason=reason)

    def recv_tx_set(self, txset_hash: bytes, txset) -> bool:
        """Reference: HerderImpl::recvTxSet.  The hash gate runs FIRST so
        no frame-construction work (or exception) can be triggered by a tx
        set whose hash doesn't match what was requested."""
        try:
            if sha256(txset.to_xdr()) != txset_hash:
                return False
        except X.XdrError:
            return False  # unencodable peer tx set == hash mismatch
        try:
            frames = [self.lm.make_frame(e) for e in tx_set_envelopes(txset)]
        except Exception:
            # Hash-correct tx set we cannot build frames for: this is a bug
            # (or unsupported tx shape) worth surfacing, not a peer lying.
            log.exception("frame construction failed for tx set %s",
                          txset_hash.hex()[:16])
            return False
        self.pending.add_txset(txset_hash, txset, frames)
        self._process_scp_queue()
        return True

    def recv_qset(self, qset) -> bool:
        """Reference: HerderImpl::recvSCPQuorumSet."""
        ok = self.pending.add_qset(qset)
        if ok:
            self._process_scp_queue()
        return ok

    def trace_node(self) -> str:
        """Node attribution for phase marks: the fleet-provisioned
        process node id when configured, else this herder's short public
        key — in-process multi-node simulations share one process but
        must still split the merged trace into per-node rows."""
        return slog.node_id() or self.node_id.hex()[:8]

    def _mark_flood(self, slot: int) -> None:
        """First tx flooded toward `slot` gets a phase mark (one per
        slot, not per tx — floods are per-transaction hot)."""
        if self._flood_marked_slot != slot:
            self._flood_marked_slot = slot
            tracing.mark_phase("tx-flood", slot, node=self.trace_node())

    def recv_transaction(self, frame, origin: str = "api") -> AddResult:
        """Reference: HerderImpl::recvTransaction (from /tx or overlay).
        With an admission pipeline enabled, intake is batched: the frame
        joins the current admission batch (verified on the accel path when
        it wins the CPU race) and flooding happens once admitted.  Without
        one, the legacy single-sig path runs inline.  Newly-pending txs
        are flooded to peers either way."""
        if self.admission is not None:
            return self.admission.submit(frame, origin=origin)
        res = self.tx_queue.try_add(frame)
        if res.code == AddResult.STATUS_PENDING:
            self._mark_flood(self.lm.last_closed_ledger_seq + 1)
            self.tx_flood(frame)
        return res

    def enable_admission(self, accel: bool = False, **knobs) -> None:
        """Install the batched admission pipeline in front of the
        tx-queue (herder/admission.py).  Admitted frames flood exactly
        like the legacy path did."""
        from .admission import AdmissionPipeline

        def _flood(frame, origin):
            self._mark_flood(self.lm.last_closed_ledger_seq + 1)
            self.tx_flood(frame)

        self.admission = AdmissionPipeline(
            self.tx_queue, self.lm, self.clock, accel=accel,
            on_admitted=_flood, **knobs)

    def _process_scp_queue(self) -> None:
        if self._processing_ready:
            return
        self._processing_ready = True
        try:
            progressed = True
            while progressed:
                progressed = False
                for slot in self.pending.ready_slots():
                    for env in self.pending.pop_ready(slot):
                        self._track_qset(env.statement)
                        self.scp.receive_envelope(env)
                        progressed = True
        finally:
            self._processing_ready = False

    def _track_qset(self, st) -> None:
        from .pending_envelopes import statement_qset_hash
        qh = statement_qset_hash(st)
        nid = st.nodeID.value
        if self._node_qset_hash.get(nid) == qh:
            return   # same announced qset: the quorum map is unchanged
        q = self.pending.get_qset(qh)
        if q is not None:
            self._node_qset_hash[nid] = qh
            if not self.quorum_tracker.expand(nid, q):
                self.quorum_tracker.rebuild(self._qset_of_node)

    def _qset_of_node(self, node_id: bytes):
        if node_id == self.node_id:
            return self.scp.local_node.qset
        env = None
        for slot_idx in sorted(self.scp.slots, reverse=True):
            env = self.scp.slots[slot_idx].get_latest_message(node_id)
            if env is not None:
                break
        if env is None:
            return None
        from .pending_envelopes import statement_qset_hash
        return self.pending.get_qset(statement_qset_hash(env.statement))

    # ------------------------------------------------------------------
    # consensus drive
    # ------------------------------------------------------------------
    def trigger_next_ledger(self, seq: int) -> None:
        """Nominate a value for `seq`.  Reference:
        HerderImpl::triggerNextLedger."""
        if not self.is_validator or self.state != HerderState.TRACKING:
            return
        if seq != self.next_ledger_index():
            return
        self._last_trigger_at = self.clock.now()
        # clock time, not perf_counter: under a virtual clock the
        # consensus latency IS virtual (timeout-driven); wall time would
        # report crank speed instead
        self._nominate_started.setdefault(seq, self.clock.now())
        with detguard.region("nomination"):
            frames = self.tx_queue.tx_set_frames()
            tracing.mark_phase("nominate", seq, node=self.trace_node(),
                               txs=len(frames))
            tx_set, tx_set_hash, ordered = self.lm.make_tx_set_any(frames)
            self.pending.add_txset(tx_set_hash, tx_set, ordered)

            lcl = self.lm.lcl_header
            close_time = max(self.clock.system_now(),
                             lcl.scpValue.closeTime + 1)
            ups = self.upgrades.create_upgrades_for(lcl, close_time)
            sv = X.StellarValue(txSetHash=tx_set_hash, closeTime=close_time,
                                upgrades=ups)
            prev = lcl.scpValue.to_xdr()
            self.scp.nominate(seq, sv.to_xdr(), prev)

    # ------------------------------------------------------------------
    # SCPDriver: value semantics
    # ------------------------------------------------------------------
    def _decode_value(self, value: bytes) -> Optional[X.StellarValue]:
        try:
            return X.StellarValue.from_xdr(value)
        except X.XdrError:
            return None

    def validate_value(self, slot_index: int, value: bytes,
                       nomination: bool) -> ValidationLevel:
        """Reference: HerderSCPDriver::validateValue/validateValueHelper."""
        sv = self._decode_value(value)
        if sv is None:
            return ValidationLevel.INVALID
        lcl = self.lm.lcl_header
        next_seq = self.next_ledger_index()
        if slot_index == next_seq:
            if sv.closeTime <= lcl.scpValue.closeTime:
                return ValidationLevel.INVALID
            if sv.closeTime > self.clock.system_now() + MAX_TIME_SLIP_SECONDS:
                return ValidationLevel.INVALID
        got = self.pending.get_txset(sv.txSetHash)
        if got is None:
            # can't fully check yet (tx set still fetching)
            return ValidationLevel.MAYBE_VALID
        txset, _frames = got
        if slot_index == next_seq \
                and tx_set_previous_hash(txset) != self.lm.lcl_hash:
            return ValidationLevel.INVALID
        for up in sv.upgrades:
            if not self.upgrades.is_valid(up, lcl, nomination=nomination,
                                          close_time=sv.closeTime):
                if nomination:
                    return ValidationLevel.INVALID
                # ballot: tolerate upgrades we don't want but others voted
                if not self.upgrades.is_valid(up, lcl, nomination=False):
                    return ValidationLevel.INVALID
        return ValidationLevel.FULLY_VALIDATED

    def extract_valid_value(self, slot_index: int,
                            value: bytes) -> Optional[bytes]:
        """Strip invalid upgrades (reference:
        HerderSCPDriver::extractValidValue)."""
        sv = self._decode_value(value)
        if sv is None:
            return None
        lcl = self.lm.lcl_header
        kept = [u for u in sv.upgrades
                if self.upgrades.is_valid(u, lcl, nomination=True,
                                          close_time=sv.closeTime)]
        sv2 = X.StellarValue(txSetHash=sv.txSetHash, closeTime=sv.closeTime,
                             upgrades=kept)
        # Validate the STRIPPED value: its remaining upgrades are all wanted,
        # so this is the reference's validateValueHelper (which skips upgrade
        # checks) applied to the repaired value.  Validating the original
        # would return INVALID exactly when an unwanted upgrade is present —
        # the case this method exists to repair.  MAYBE_VALID (tx set evicted
        # from cache between processing steps) keeps the repaired value:
        # dropping it would stall nomination on the leader's value.
        if self.validate_value(slot_index, sv2.to_xdr(), True) == \
                ValidationLevel.INVALID:
            return None
        return sv2.to_xdr()

    def combine_candidates(self, slot_index: int,
                           candidates: List[bytes]) -> Optional[bytes]:
        """Reference: HerderSCPDriver::combineCandidates — highest
        closeTime; the tx set with most operations (hash tiebreak);
        upgrades merged per type taking the max parameter."""
        best_sv = None
        best_key = None
        max_ct = 0
        upgrades_by_type: Dict[int, bytes] = {}
        for cand in candidates:
            sv = self._decode_value(cand)
            if sv is None:
                continue
            max_ct = max(max_ct, sv.closeTime)
            got = self.pending.get_txset(sv.txSetHash)
            n_ops = 0
            if got is not None:
                _txset, frames = got
                n_ops = sum(f.num_operations() for f in frames)
            key = (n_ops, sv.txSetHash)
            if best_key is None or key > best_key:
                best_key, best_sv = key, sv
            for u in sv.upgrades:
                try:
                    up = X.LedgerUpgrade.from_xdr(u)
                except X.XdrError:
                    continue
                t = int(up.switch)
                cur = upgrades_by_type.get(t)
                if cur is None or X.LedgerUpgrade.from_xdr(cur).value < up.value:
                    upgrades_by_type[t] = u
        if best_sv is None:
            return None
        out = X.StellarValue(
            txSetHash=best_sv.txSetHash, closeTime=max_ct,
            upgrades=[upgrades_by_type[t]
                      for t in sorted(upgrades_by_type)])
        return out.to_xdr()

    # ------------------------------------------------------------------
    # SCPDriver: plumbing
    # ------------------------------------------------------------------
    def get_qset(self, qset_hash: bytes):
        if qset_hash == self.scp.local_node.qset_hash:
            return self.scp.local_node.qset
        return self.pending.get_qset(qset_hash)

    def emit_envelope(self, envelope) -> None:
        self.broadcast(envelope)

    def _envelope_payload(self, statement) -> bytes:
        # Reference: HerderSCPDriver::signEnvelope — sign over
        # (networkID, ENVELOPE_TYPE_SCP, statement)
        return (self.network_id + struct.pack(">i", ENVELOPE_TYPE_SCP)
                + statement.to_xdr())

    def sign_envelope(self, envelope) -> None:
        envelope.signature = self.secret.sign(
            self._envelope_payload(envelope.statement))

    def verify_envelope(self, envelope) -> bool:
        try:
            return keys.verify_sig(
                keys.PublicKey(envelope.statement.nodeID.value),
                envelope.signature,
                self._envelope_payload(envelope.statement))
        except ValueError:
            # malformed nodeID / unencodable statement (XdrError IS-A
            # ValueError): verification fails
            return False

    def setup_timer(self, slot_index: int, timer_id: int, timeout: float,
                    callback) -> None:
        key = (slot_index, timer_id)
        t = self._timers.pop(key, None)
        if t is not None:
            t.cancel()
        if callback is None:
            return
        t = VirtualTimer(self.clock)
        t.expires_from_now(timeout, callback)
        self._timers[key] = t

    # ------------------------------------------------------------------
    # externalization → ledger close
    # ------------------------------------------------------------------
    def value_externalized(self, slot_index: int, value: bytes) -> None:
        """Reference: HerderImpl::valueExternalized →
        LedgerManager::valueExternalized; out-of-order slots are buffered
        (CatchupManager::processLedger) and drained in sequence."""
        sv = self._decode_value(value)
        if sv is None:
            log.error("externalized undecodable value at slot %d", slot_index)
            return
        lcl = self.tracking_consensus_ledger_index()
        if slot_index <= lcl:
            return
        self._buffered[slot_index] = sv
        eventlog.record("SCP", "INFO", "slot externalized",
                        slot=slot_index, lcl=lcl)
        tracing.mark_phase("externalize", slot_index,
                           node=self.trace_node(), lcl=lcl)
        if slot_index == lcl + 1:
            self._set_state(HerderState.TRACKING, "externalized next slot")
        self._drain_buffered()

    def _drain_buffered(self) -> None:
        # Drop stale entries at or below the LCL (catchup may have advanced
        # past them); they would otherwise accumulate and suppress the
        # min(buffered) > lcl+1 out-of-sync check below.
        lcl = self.tracking_consensus_ledger_index()
        for s in [s for s in self._buffered if s <= lcl]:
            del self._buffered[s]
        applied = 0
        while True:
            nxt = self.tracking_consensus_ledger_index() + 1
            sv = self._buffered.pop(nxt, None)
            if sv is None:
                break
            got = self.pending.get_txset(sv.txSetHash)
            if got is None:
                # externalized a tx set we never fetched: must catch up
                self._buffered[nxt] = sv
                self._lost_sync()
                return
            txset, frames = got
            applied += 1
            if applied > 1:
                # second-and-later ledgers in one drain call were sitting
                # in the buffer while this node lagged: that's the
                # buffered-externalize catchup path, not live consensus
                self.recovery_stats["buffered_applied"] += 1
            arts = self.lm.close_ledger(frames, sv.closeTime, tx_set=txset,
                                        stellar_value=sv)
            self._set_state(HerderState.TRACKING, "externalized value applied")
            self._arm_tracking_heartbeat()
            _registry().meter("herder.ledger.externalize").mark()
            t0 = self._nominate_started.pop(nxt, None)
            if t0 is not None:
                # nomination trigger -> externalized value applied (the
                # consensus-round latency an operator tunes timers against)
                _registry().timer("scp.slot.externalize").update(
                    self.clock.now() - t0)
            self._persist_scp_state(nxt, sv, txset)
            self.ledger_closed_hook(arts)
            self.tx_queue.remove_applied(frames)
            self.tx_queue.shift()
            seq = self.lm.last_closed_ledger_seq
            self.scp.purge_slots(seq + 1 - MAX_SLOTS_TO_REMEMBER
                                 if seq + 1 > MAX_SLOTS_TO_REMEMBER else 0,
                                 keep=0)
            self.pending.erase_below(seq + 1 - MAX_SLOTS_TO_REMEMBER
                                     if seq + 1 > MAX_SLOTS_TO_REMEMBER else 0)
            for s in [s for s in self._nominate_started if s <= seq]:
                del self._nominate_started[s]
            self._arm_trigger(seq + 1)
        if self._buffered and min(self._buffered) > \
                self.tracking_consensus_ledger_index() + 1:
            self._lost_sync()
            self.sync_gap_hook()

    def _arm_tracking_heartbeat(self) -> None:
        """Reference: HerderImpl::trackingHeartBeat — while this node
        believes it is tracking consensus, an externalized value must
        arrive within CONSENSUS_STUCK_TIMEOUT_SECONDS.  One-shot: rearmed
        on every applied value, NOT on expiry, so an idle herder arms no
        perpetual timer.  Expiry while still TRACKING means the node is
        stuck (isolated validator, partitioned minority) and must declare
        itself out of sync so the recovery machinery — SCP-state pull
        from peers, archive catchup handoff — takes over instead of
        waiting forever for envelopes that cannot arrive."""
        if self._tracking_timer is not None:
            self._tracking_timer.cancel()
        self._tracking_timer = VirtualTimer(self.clock)
        self._tracking_timer.expires_from_now(
            CONSENSUS_STUCK_TIMEOUT_SECONDS, self._herder_stuck)

    def _herder_stuck(self) -> None:
        if self.state != HerderState.TRACKING:
            return
        log.warning("no ledger externalized for %ds at lcl=%d: "
                    "declaring out of sync",
                    CONSENSUS_STUCK_TIMEOUT_SECONDS,
                    self.tracking_consensus_ledger_index())
        self._lost_sync()

    def _lost_sync(self) -> None:
        if self.state != HerderState.SYNCING:
            log.warning("herder out of sync at lcl=%d buffered=%s",
                        self.tracking_consensus_ledger_index(),
                        sorted(self._buffered))
            self.recovery_stats["out_of_sync"] += 1
            self._set_state(HerderState.SYNCING, "lost sync")
            self.lost_sync_hook()
            self.out_of_sync_handler()

    def _arm_trigger(self, next_seq: int) -> None:
        """Arm the ledger trigger so consensus rounds pace at
        EXP_LEDGER_TIMESPAN_SECONDS.  Reference: HerderImpl::
        ledgerClosed → triggerNextLedger timer."""
        if not self.is_validator:
            return
        if self._trigger_timer is not None:
            self._trigger_timer.cancel()
        due = self._last_trigger_at + self.ledger_timespan
        delay = max(0.0, due - self.clock.now())  # corelint: disable=float-discipline -- local timer pacing; close time stays integer
        self._trigger_timer = VirtualTimer(self.clock)
        self._trigger_timer.expires_from_now(
            delay, lambda: self.trigger_next_ledger(next_seq))

    # ------------------------------------------------------------------
    # SCP state persistence (reference: HerderImpl::persistSCPState /
    # restoreSCPState via HerderPersistence + PersistentState)
    # ------------------------------------------------------------------
    def attach_persistence(self, db) -> None:
        self.db = db

    def _persist_scp_state(self, slot: int, sv, txset) -> None:
        """Durably record the externalized slot's SCP messages, referenced
        quorum sets and tx set, so a restarted node can re-serve its last
        consensus state to peers."""
        if self.db is None:
            return
        from ..database import PersistentState
        from .pending_envelopes import statement_qset_hash
        envs = self.scp.slots[slot].get_current_state() \
            if slot in self.scp.slots else []
        qsets = []
        seen = set()
        for env in envs:
            qh = statement_qset_hash(env.statement)
            if qh not in seen:
                seen.add(qh)
                qs = self.pending.get_qset(qh)
                if qs is not None:
                    qsets.append(qs)
        self.db.save_scp_history(slot, envs, qsets)
        self.db.save_txset(sv.txSetHash, slot, txset.to_xdr())
        self.db.set_state(PersistentState.LAST_SCP_DATA, str(slot))
        if slot > MAX_SLOTS_TO_REMEMBER:
            self.db.prune_scp(slot - MAX_SLOTS_TO_REMEMBER)
        self.db.commit()

    def restore_scp_state(self) -> None:
        """Reload the persisted slot's tx sets, quorum sets and envelopes
        after a restart.  Envelopes re-enter through the normal intake so
        SCP slot state is rebuilt exactly as if received from peers."""
        if self.db is None:
            return
        from ..database import PersistentState
        val = self.db.get_state(PersistentState.LAST_SCP_DATA)
        if val is None:
            return
        for h, blob in self.db.load_txsets():
            try:
                txset = decode_tx_set(blob)
                frames = [self.lm.make_frame(e)
                          for e in tx_set_envelopes(txset)]
            except Exception:
                log.warning("dropping undecodable stored txset %s", h.hex())
                continue
            self.pending.add_txset(h, txset, frames)
        for qs in self.db.load_scp_quorums():
            self.pending.add_qset(qs)
        for env in self.db.load_scp_history(int(val)):
            self.recv_scp_envelope(env)
        log.info("restored SCP state for slot %s", val)

    # ------------------------------------------------------------------
    # SCP state sync (peer (re)connect / out-of-sync recovery)
    # ------------------------------------------------------------------
    def get_scp_state(self, from_seq: int) -> List:
        """Latest envelopes for every remembered slot >= from_seq, for
        bringing a lagging peer up to date.  Reference:
        HerderImpl::getSCPState / sendSCPStateToPeer (on peer auth) and
        getMoreSCPState (out-of-sync node pulling)."""
        out: List = []
        for idx in sorted(self.scp.slots):
            if idx >= from_seq:
                out.extend(self.scp.slots[idx].get_current_state())
        return out

    # ------------------------------------------------------------------
    # introspection (CLI/HTTP)
    # ------------------------------------------------------------------
    def get_state_human(self) -> str:
        return self.state

    def quorum_map(self) -> Dict[bytes, Optional[object]]:
        m = {}
        for nid in self.quorum_tracker.known_map():
            m[nid] = self._qset_of_node(nid)
        return m
