"""Quorum intersection checker — CPU branch-and-bound oracle.

Determines whether every two quorums of the observed network configuration
intersect (an NP-hard subset-enumeration problem), and if not produces the
two disjoint quorums as a witness.

Reference: src/herder/QuorumIntersectionChecker.h —
QuorumIntersectionChecker::create; src/herder/QuorumIntersectionCheckerImpl
.{h,cpp} — QuorumIntersectionCheckerImpl, MinQuorumEnumerator, QBitSet,
TarjanSCCCalculator (src/util).  Re-designed for this framework: node sets
are arbitrary-width Python int bitmasks (the reference uses fixed-width
QBitSet over a bitset library); the enumeration is the same
committed/remaining branch-and-bound over minimal quorums with
max-quorum-contraction pruning.  The TPU enumerator in accel/quorum.py
shares the flattened two-level bitmask encoding produced by
:func:`flatten_qmap` and is differentially tested against this oracle.

Algorithm facts (same as the reference):
 - every minimal quorum is strongly connected in the dependency graph
   (node -> nodes named by its qset), so if two distinct SCCs each contain
   a quorum the network trivially splits, and otherwise enumeration can be
   restricted to the unique "main" SCC that contains quorums;
 - the network has disjoint quorums iff some *minimal* quorum has a quorum
   inside its complement, so it suffices to enumerate minimal quorums.
"""

from __future__ import annotations

import os as _os
import struct as _struct
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

# Native enumeration core (native/cquorum.c — the framework's equivalent
# of the reference's native C++ checker, SURVEY §2.4).  The pure-Python
# methods below stay the semantic source of truth; the C core is a
# faithful port differentially tested to produce identical verdicts,
# split witnesses and max_quorums_found.  Set STELLAR_TPU_NO_CQUORUM to
# force the pure-Python enumeration (the differential test does).
try:
    if _os.environ.get("STELLAR_TPU_NO_CQUORUM"):
        raise ImportError("cquorum disabled by STELLAR_TPU_NO_CQUORUM")
    from stellar_core_tpu import _cquorum  # built via `make native`
except ImportError:
    _cquorum = None

NodeIDb = bytes


class InterruptedError_(Exception):
    """Raised inside the enumeration when the interrupt flag is set.
    Reference: QuorumIntersectionChecker — InterruptedException."""


# ---------------------------------------------------------------------------
# Bitmask quorum-set encoding
# ---------------------------------------------------------------------------

@dataclass
class QBitSet:
    """A quorum set over node indexes, encoded as bitmasks.

    Reference: QuorumIntersectionCheckerImpl.h — QBitSet (threshold,
    nodes bitset, innerSets, successors cache).
    """
    threshold: int
    nodes: int                      # bitmask of direct validator members
    inner: List["QBitSet"] = field(default_factory=list)
    successors: int = 0             # nodes | union of inner successors

    @staticmethod
    def build(threshold: int, nodes: int, inner: List["QBitSet"]) -> "QBitSet":
        succ = nodes
        for i in inner:
            succ |= i.successors
        return QBitSet(threshold, nodes, inner, succ)


def qset_to_qbitset(qset, index: Dict[NodeIDb, int]) -> QBitSet:
    """Convert an xdr SCPQuorumSet to a QBitSet using `index` (node id ->
    bit position).  Unknown validators (not in the quorum map) are dropped
    from the mask but still count against the threshold, mirroring the
    reference's treatment of unknown nodes as permanently failed."""
    mask = 0
    for v in qset.validators:
        bit = index.get(v.value)
        if bit is not None:
            mask |= 1 << bit
    inner = [qset_to_qbitset(i, index) for i in qset.innerSets]
    return QBitSet.build(qset.threshold, mask, inner)


def slice_satisfied(qb: QBitSet, mask: int) -> bool:
    """True iff `mask` contains at least one slice of qb."""
    count = (qb.nodes & mask).bit_count()
    if count >= qb.threshold:
        return True
    for i in qb.inner:
        if slice_satisfied(i, mask):
            count += 1
            if count >= qb.threshold:
                return True
    return False


# ---------------------------------------------------------------------------
# Tarjan SCC over the qset dependency graph
# ---------------------------------------------------------------------------

def tarjan_sccs(succs: Sequence[int], n: int) -> List[int]:
    """SCCs of the graph node i -> bits of succs[i], as bitmasks.
    Reference: src/util/TarjanSCCCalculator.{h,cpp} (iterative here; the
    reference recursion overflows for no reason we need to copy)."""
    index = [0] * n
    low = [0] * n
    on_stack = [False] * n
    visited = [False] * n
    stack: List[int] = []
    sccs: List[int] = []
    counter = [1]

    for root in range(n):
        if visited[root]:
            continue
        # iterative DFS: work items (node, iterator state via child bit list)
        work = [(root, 0)]
        while work:
            v, pi = work[-1]
            if pi == 0:
                visited[v] = True
                index[v] = low[v] = counter[0]
                counter[0] += 1
                stack.append(v)
                on_stack[v] = True
            advanced = False
            m = succs[v] >> pi
            while m:
                if m & 1:
                    w = pi
                    if not visited[w]:
                        work[-1] = (v, pi + 1)
                        work.append((w, 0))
                        advanced = True
                        break
                    elif on_stack[w]:
                        low[v] = min(low[v], index[w])
                m >>= 1
                pi += 1
            if advanced:
                continue
            work.pop()
            if low[v] == index[v]:
                scc = 0
                while True:
                    w = stack.pop()
                    on_stack[w] = False
                    scc |= 1 << w
                    if w == v:
                        break
                sccs.append(scc)
            if work:
                p, _ = work[-1]
                low[p] = min(low[p], low[v])
    return sccs


# ---------------------------------------------------------------------------
# The checker
# ---------------------------------------------------------------------------

@dataclass
class QuorumIntersectionResult:
    intersects: bool
    # On failure: the two disjoint quorums, as node-id lists.
    split: Optional[Tuple[List[NodeIDb], List[NodeIDb]]] = None
    # Diagnostics
    node_count: int = 0
    main_scc_size: int = 0
    max_quorums_found: int = 0


class QuorumIntersectionChecker:
    """Exact intersection check over a quorum map {node_id: SCPQuorumSet}.

    Reference: QuorumIntersectionCheckerImpl::networkEnumerateAndCheck
    MinQuorums.  `interrupt` is a zero-arg callable polled inside the
    enumeration (reference: std::atomic<bool>& interruptFlag).
    """

    def __init__(self, qmap: Dict[NodeIDb, object],
                 interrupt: Optional[Callable[[], bool]] = None):
        # Nodes with no known qset are treated as failed (excluded) but
        # still referenced by others' masks as absent bits.
        self.node_ids: List[NodeIDb] = sorted(n for n, q in qmap.items()
                                              if q is not None)
        self.index: Dict[NodeIDb, int] = {n: i
                                          for i, n in enumerate(self.node_ids)}
        self.n = len(self.node_ids)
        self.qbs: List[QBitSet] = [qset_to_qbitset(qmap[nid], self.index)
                                   for nid in self.node_ids]
        self.interrupt = interrupt or (lambda: False)
        self.max_quorums_found = 0

    # -- quorum primitives over bitmasks ---------------------------------
    def contract_to_max_quorum(self, mask: int) -> int:
        """Greatest quorum contained in `mask`, or 0.
        Reference: QuorumIntersectionCheckerImpl::contractToMaximalQuorum."""
        while True:
            new = 0
            m = mask
            while m:
                bit = m & -m
                i = bit.bit_length() - 1
                if slice_satisfied(self.qbs[i], mask):
                    new |= bit
                m ^= bit
            if new == mask:
                return mask
            mask = new

    def is_quorum(self, mask: int) -> bool:
        return mask != 0 and self.contract_to_max_quorum(mask) == mask

    def is_minimal_quorum(self, mask: int) -> bool:
        """No proper subset of `mask` is a quorum.  It suffices to drop each
        single member and contract.  Reference: MinQuorumEnumerator —
        hasDisjointQuorum path checks via isMinimalQuorum."""
        m = mask
        while m:
            bit = m & -m
            if self.contract_to_max_quorum(mask & ~bit):
                return False
            m ^= bit
        return True

    # -- enumeration ------------------------------------------------------
    def _check_interrupt(self) -> None:
        if self.interrupt():
            raise InterruptedError_()

    def _pick_split_node(self, remaining: int) -> int:
        """Branch on the highest-in-degree remaining node (helps pruning —
        same heuristic family as the reference's pickSplitNode, which picks
        the max-indegree node of the remaining graph)."""
        best, best_deg = 0, -1
        m = remaining
        while m:
            bit = m & -m
            i = bit.bit_length() - 1
            deg = (self._indegree[i])
            if deg > best_deg:
                best, best_deg = bit, deg
            m ^= bit
        return best

    def _enumerate(self, committed: int, remaining: int,
                   scc: int) -> Optional[Tuple[int, int]]:
        """Find a minimal quorum inside committed|remaining that contains
        `committed` and whose complement (within scc) contains a quorum.
        Returns (min_quorum, disjoint_quorum) or None.
        Reference: MinQuorumEnumerator::anyMinQuorumHasDisjointQuorum."""
        self._check_interrupt()
        perimeter = committed | remaining
        mq = self.contract_to_max_quorum(perimeter)
        if committed & ~mq:
            return None                 # committed can't be inside any quorum here
        if not mq:
            return None
        if committed and self.is_quorum(committed):
            # Any further descent only yields supersets => non-minimal.
            self.max_quorums_found += 1
            if self.is_minimal_quorum(committed):
                disjoint = self.contract_to_max_quorum(scc & ~committed)
                if disjoint:
                    return (committed, disjoint)
            return None
        if not remaining:
            return None
        bit = self._pick_split_node(remaining)
        rest = remaining & ~bit
        # exclude-first order mirrors the reference (explores small quorums
        # of the rest before committing the split node)
        r = self._enumerate(committed, rest, scc)
        if r is not None:
            return r
        return self._enumerate(committed | bit, rest, scc)

    def check(self) -> QuorumIntersectionResult:
        """Run the full check.  Reference call path: HerderImpl::
        checkAndMaybeReanalyzeQuorumMap -> QuorumIntersectionChecker::create
        -> networkEnumerateAndCheckMinQuorums.  Dispatches to the native
        enumeration core when available (n <= 128 bitmask width); the
        pure-Python enumeration below is the fallback and the semantic
        source of truth."""
        if _cquorum is not None and 0 < self.n <= 128:
            try:
                return self._check_native()
            except ValueError:
                # The native parser enforces bounds (e.g. >4096 inner sets)
                # that the Python enumeration — the semantic source of
                # truth — handles fine; degrade rather than refuse.
                pass
        return self._check_python()

    def _blob(self) -> bytes:
        """Serialize the qset forest for the native core (little-endian:
        u32 n, then per node u32 threshold / 16-byte mask / u32 n_inner /
        children recursively)."""
        out = [_struct.pack("<I", self.n)]

        def ser(qb: QBitSet) -> None:
            out.append(_struct.pack("<I", qb.threshold))
            out.append(qb.nodes.to_bytes(16, "little"))
            out.append(_struct.pack("<I", len(qb.inner)))
            for i in qb.inner:
                ser(i)

        for qb in self.qbs:
            ser(qb)
        return b"".join(out)

    def _check_native(self) -> QuorumIntersectionResult:
        code, a, b, main_scc_size, max_q = _cquorum.check(
            self._blob(), self.interrupt)
        if code == -1:
            raise InterruptedError_()
        self.max_quorums_found = max_q
        if code == 1:
            return QuorumIntersectionResult(
                True, node_count=self.n, main_scc_size=main_scc_size,
                max_quorums_found=max_q)
        return QuorumIntersectionResult(
            False,
            split=(self._names(int.from_bytes(a, "little")),
                   self._names(int.from_bytes(b, "little"))),
            node_count=self.n, main_scc_size=main_scc_size,
            max_quorums_found=max_q)

    def _check_python(self) -> QuorumIntersectionResult:
        n = self.n
        if n == 0:
            return QuorumIntersectionResult(True, node_count=0)

        # in-degree for the split heuristic
        self._indegree = [0] * n
        for qb in self.qbs:
            m = qb.successors
            while m:
                bit = m & -m
                self._indegree[bit.bit_length() - 1] += 1
                m ^= bit

        sccs = tarjan_sccs([qb.successors for qb in self.qbs], n)
        quorum_sccs = []
        for scc in sccs:
            mq = self.contract_to_max_quorum(scc)
            if mq:
                quorum_sccs.append((scc, mq))
        if not quorum_sccs:
            # No quorum anywhere: vacuously intersecting (reference reports
            # "no quorums found" and treats as enjoying intersection).
            return QuorumIntersectionResult(True, node_count=n,
                                            main_scc_size=0)
        if len(quorum_sccs) > 1:
            (_, q1), (_, q2) = quorum_sccs[0], quorum_sccs[1]
            return QuorumIntersectionResult(
                False, split=(self._names(q1), self._names(q2)),
                node_count=n, main_scc_size=0)
        scc, _ = quorum_sccs[0]
        r = self._enumerate(0, scc, scc)
        result = QuorumIntersectionResult(
            r is None,
            split=None if r is None else (self._names(r[0]),
                                          self._names(r[1])),
            node_count=n,
            main_scc_size=scc.bit_count(),
            max_quorums_found=self.max_quorums_found)
        return result

    def _names(self, mask: int) -> List[NodeIDb]:
        out = []
        m = mask
        while m:
            bit = m & -m
            out.append(self.node_ids[bit.bit_length() - 1])
            m ^= bit
        return out


def _try_symmetric_org_contraction(qmap: Dict[NodeIDb, object]
                                   ) -> Optional[QuorumIntersectionResult]:
    """Tier-1-shaped fast path: when EVERY node shares one identical qset
    of the form `t of k disjoint flat inner sets (orgs) covering exactly
    the node set`, the validator-level question contracts to the org level
    (the symmetric-cluster contraction from the FBAS analysis literature;
    the real pubnet tier-1 has exactly this shape).

    Soundness: all nodes share qset Q, so U is a quorum iff the orgs
    satisfied by U (>= thr_o members present) satisfy Q's outer threshold.
    - If the org-level projection (k flat nodes, threshold t) enjoys
      intersection, any two quorums share an org o; with 2*thr_o > n_o two
      thr_o-subsets of o must overlap, so the quorums intersect.
    - If the org-level projection splits, taking thr_o members per org on
      each side yields two disjoint validator-level quorums.
    Requires 2*thr_o > n_o for every org; returns None (fall back to full
    enumeration) when any condition fails."""
    values = list(qmap.values())  # corelint: disable=iteration-order -- all-equal homogeneity check, order-free
    if not values or any(q is None for q in values):
        return None  # nodes with unknown qsets: full checker handles them
    first = values[0]
    first_xdr = first.to_xdr()
    if any(q.to_xdr() != first_xdr for q in values[1:]):
        return None
    if first.validators or not first.innerSets:
        return None
    orgs: List[Tuple[int, List[NodeIDb]]] = []
    seen: Set[NodeIDb] = set()
    for inner in first.innerSets:
        if inner.innerSets or not inner.validators:
            return None
        members = [v.value for v in inner.validators]
        if len(set(members)) != len(members):
            return None  # duplicate members within an org
        if any(m in seen or m not in qmap for m in members):
            return None
        seen.update(members)
        if not 0 < inner.threshold <= len(members):
            return None  # unsatisfiable / degenerate org
        if 2 * inner.threshold <= len(members):
            return None  # two minimal org picks may not overlap
        orgs.append((inner.threshold, members))
    if seen != set(qmap):
        return None

    # the projection is always flat `t of k orgs` here (guaranteed by the
    # shape checks above), so org-level intersection has a closed form:
    # two org quorums of size >= t overlap iff 2t > k
    k = len(orgs)
    t = first.threshold
    if not 1 <= t <= k:
        return None
    if 2 * t > k:
        return QuorumIntersectionResult(
            True, node_count=len(qmap), main_scc_size=len(qmap))
    # split witness: thr_o members from each of the first t orgs vs the
    # last t orgs (disjoint because 2t <= k)
    side_a: List[NodeIDb] = []
    side_b: List[NodeIDb] = []
    for thr, members in orgs[:t]:
        side_a.extend(members[:thr])
    for thr, members in orgs[k - t:]:
        side_b.extend(members[:thr])
    return QuorumIntersectionResult(
        False, split=(side_a, side_b), node_count=len(qmap),
        main_scc_size=len(qmap))


def check_intersection(qmap: Dict[NodeIDb, object],
                       interrupt: Optional[Callable[[], bool]] = None
                       ) -> QuorumIntersectionResult:
    """Convenience one-shot API (reference: QuorumIntersectionChecker::
    create(...)->networkEnumerateAndCheckMinQuorums()).  Applies the
    symmetric-org contraction when the topology allows (pubnet tier-1
    shape: the exact enumeration is exponential in orgs; the contraction
    answers at org granularity), falling back to full branch-and-bound."""
    contracted = _try_symmetric_org_contraction(qmap)
    if contracted is not None:
        return contracted
    return QuorumIntersectionChecker(qmap, interrupt).check()


# ---------------------------------------------------------------------------
# Critical-groups analysis
# ---------------------------------------------------------------------------

def project_out_faulty(qset, faulty: Set[NodeIDb]):
    """Project a qset onto the honest nodes, under the model that `faulty`
    nodes vote for anything: each faulty validator is removed AND counts as
    an automatic threshold hit (threshold decremented); an inner set whose
    projected threshold reaches 0 is auto-satisfied and likewise becomes a
    threshold hit on its parent.  A resulting threshold of 0 means the
    node's slices can be satisfied by faulty nodes alone."""
    from ..xdr import scp as SX
    thr = qset.threshold
    validators = []
    for v in qset.validators:
        if v.value in faulty:
            thr -= 1
        else:
            validators.append(v)
    inner = []
    for i in qset.innerSets:
        pi = project_out_faulty(i, faulty)
        if pi.threshold <= 0:
            thr -= 1
        else:
            inner.append(pi)
    return SX.SCPQuorumSet(threshold=max(thr, 0), validators=validators,
                           innerSets=inner)


def intersection_critical_groups(
        qmap: Dict[NodeIDb, object],
        groups: Sequence[Set[NodeIDb]],
        interrupt: Optional[Callable[[], bool]] = None
        ) -> List[Set[NodeIDb]]:
    """Which of `groups` are intersection-critical: groups whose nodes, if
    they turned Byzantine, would break quorum intersection *among the honest
    nodes*.  Model: two original-system quorums intersecting only inside the
    faulty group is a split, which is equivalent to checking intersection of
    the honest-projected system (faulty nodes deleted from every slice with
    thresholds decremented — they vote for both halves).

    Reference: QuorumIntersectionChecker::getIntersectionCriticalGroups
    (the reference auto-derives candidate groups from homonymous orgs; here
    the caller supplies the grouping, and the CLI groups by qset equality).
    """
    critical: List[Set[NodeIDb]] = []
    for group in groups:
        faulty = set(group)
        honest_map = {n: (project_out_faulty(q, faulty)
                          if q is not None else None)
                      for n, q in qmap.items() if n not in faulty}
        res = check_intersection(honest_map, interrupt)
        if not res.intersects:
            critical.append(set(group))
    return critical


# ---------------------------------------------------------------------------
# Flattened two-level encoding shared with the TPU enumerator
# ---------------------------------------------------------------------------

def flatten_qmap(qmap: Dict[NodeIDb, object]):
    """Flatten a quorum map to the fixed two-level form consumed by
    accel/quorum.py: per node, a top threshold, a direct-validator bitmask
    and K inner (threshold, bitmask) pairs.  Returns (node_ids, tops,
    top_masks, inner_thrs, inner_masks) with Python-int masks; deeper
    nesting (rare; reference caps at MAXIMUM_QUORUM_NESTING_LEVEL=4) is
    rejected with ValueError so callers fall back to the CPU oracle."""
    node_ids = sorted(n for n, q in qmap.items() if q is not None)
    index = {n: i for i, n in enumerate(node_ids)}
    tops, top_masks, inner_thrs, inner_masks = [], [], [], []
    for nid in node_ids:
        qb = qset_to_qbitset(qmap[nid], index)
        for i in qb.inner:
            if i.inner:
                raise ValueError("qset nesting deeper than 2 levels; "
                                 "TPU path requires the flattened org form")
        tops.append(qb.threshold)
        top_masks.append(qb.nodes)
        inner_thrs.append([i.threshold for i in qb.inner])
        inner_masks.append([i.nodes for i in qb.inner])
    return node_ids, tops, top_masks, inner_thrs, inner_masks
