"""TransactionQueue — pending transactions between submission and inclusion.

Reference: src/herder/TransactionQueue.{h,cpp} — tryAdd (checkValid gating,
one pending tx per source account, fee-bump replace-by-fee at >=10x), ban
list with ban depth, size limiting with lowest-fee eviction, removeApplied /
shift after ledger close; src/herder/TxSetUtils — surge pricing (sort by
fee-per-op, trim to the ledger's op limit).
"""

from __future__ import annotations

from fractions import Fraction
from typing import Callable, Dict, List, Optional, Sequence, Set

from .. import xdr as X
from ..ledger.ledger_txn import LedgerTxn
from ..transactions.frame import TransactionFrame
from ..util import logging as slog
from ..util.metrics import registry as _registry

log = slog.get("Herder")

# Reference: TransactionQueue.h
FEE_MULTIPLIER = 10          # replace-by-fee bump factor
BAN_DEPTH = 10               # ledgers a banned tx stays banned
QUEUE_SIZE_MULTIPLIER = 4    # pool size = multiplier * max ledger ops


class AddResult:
    # Reference: TransactionQueue::AddResult::Code
    STATUS_PENDING = "pending"
    STATUS_DUPLICATE = "duplicate"
    STATUS_ERROR = "error"
    STATUS_TRY_AGAIN_LATER = "try-again-later"
    STATUS_BANNED = "banned"
    STATUS_FILTERED = "filtered"

    def __init__(self, code: str, result=None):
        self.code = code
        self.result = result

    def __repr__(self):
        return f"AddResult({self.code})"


def fee_per_op(frame: TransactionFrame) -> Fraction:
    """Exact rational fee rate.  Consensus-adjacent ordering must not go
    through floats: the reference compares fee rates by int128
    cross-multiplication (TxSetUtils feeRate3WayCompare); Fraction gives the
    same exact ordering."""
    return Fraction(frame.fee_bid, max(frame.num_operations(), 1))


def surge_sort_key(frame: TransactionFrame):
    """Surge pricing order: highest fee-per-op first, tx hash as the
    deterministic tiebreak (reference: TxSetUtils — feeRate comparison)."""
    return (-fee_per_op(frame), frame.content_hash())


# Eviction order = exact REVERSE of the surge sort BY CONSTRUCTION: max()
# over the same key picks the lowest fee-per-op tx, largest content hash
# among equal rates — precisely the tx surge pricing would include last
# (a bare min-by-fee left equal-rate ties to dict insertion order).  One
# key function so the two orders can never drift apart.
eviction_key = surge_sort_key


class TransactionQueue:
    def __init__(self, ledger_manager, pool_ledger_multiplier: int =
                 QUEUE_SIZE_MULTIPLIER):
        self.lm = ledger_manager
        self.pool_multiplier = pool_ledger_multiplier
        # source account id bytes -> frame (ONE pending tx per account)
        self.by_account: Dict[bytes, TransactionFrame] = {}
        self.by_hash: Dict[bytes, TransactionFrame] = {}
        # banned tx hash -> ledgers remaining
        self.banned: Dict[bytes, int] = {}
        # eviction-victim cache: (mutation counter, victim frame).  The
        # victim scan is O(queue); under overload the admission prefilter
        # and try_add both need it for every submission against an
        # unchanged full queue — cache until by_hash actually mutates
        self._mutations = 0
        self._victim_cache: Optional[tuple] = None
        # depth gauges: registry is process-global, so the last-created
        # queue wins (multi-node simulations share one registry; per-node
        # depth stays in /metrics' herder section); weak_gauge so a
        # torn-down node's graph is not pinned
        _registry().weak_gauge("herder.tx-queue.depth", self,
                               lambda q: q.size)
        _registry().weak_gauge("herder.tx-queue.banned", self,
                               lambda q: len(q.banned))

    # ------------------------------------------------------------------
    def _account_key(self, frame: TransactionFrame) -> bytes:
        return frame.source_account_id().to_xdr()

    def _max_queue_size(self) -> int:
        return self.pool_multiplier * max(
            self.lm.lcl_header.maxTxSetSize, 1)

    def try_add(self, frame: TransactionFrame,
                close_time: Optional[int] = None) -> AddResult:
        """Validate and enqueue.  Reference: TransactionQueue::tryAdd."""
        h = frame.content_hash()
        if h in self.banned:
            return AddResult(AddResult.STATUS_BANNED)
        if h in self.by_hash:
            return AddResult(AddResult.STATUS_DUPLICATE)

        akey = self._account_key(frame)
        existing = self.by_account.get(akey)
        if existing is not None:
            # replace-by-fee: same account needs >= FEE_MULTIPLIER x fee
            # (full fee comparison; reference compares fee bids)
            if frame.fee_bid < FEE_MULTIPLIER * existing.fee_bid:
                return AddResult(AddResult.STATUS_TRY_AGAIN_LATER)

        ct = close_time if close_time is not None \
            else self.lm.lcl_header.scpValue.closeTime
        with LedgerTxn(self.lm.root) as ltx:  # read-only: rolls back on exit
            res = frame.check_valid(ltx, ct)
        if res.result.switch != X.TransactionResultCode.txSUCCESS:
            return AddResult(AddResult.STATUS_ERROR, res)

        if existing is not None:
            self._drop(existing)
        elif len(self.by_hash) >= self._max_queue_size():
            victim = self._eviction_victim()
            if fee_per_op(victim) >= fee_per_op(frame):
                return AddResult(AddResult.STATUS_TRY_AGAIN_LATER)
            self._drop(victim)
            self.banned[victim.content_hash()] = BAN_DEPTH

        self.by_account[akey] = frame
        self.by_hash[h] = frame
        self._mutations += 1
        return AddResult(AddResult.STATUS_PENDING)

    def _eviction_victim(self) -> TransactionFrame:
        """The frame a full queue evicts first (see eviction_key), cached
        across the admission prefilter -> try_add double lookup and across
        submissions that leave the queue untouched."""
        cached = self._victim_cache
        if cached is not None and cached[0] == self._mutations:
            return cached[1]
        victim = max(self.by_hash.values(), key=eviction_key)
        self._victim_cache = (self._mutations, victim)
        return victim

    def _drop(self, frame: TransactionFrame) -> None:
        self.by_hash.pop(frame.content_hash(), None)
        self._mutations += 1
        akey = self._account_key(frame)
        if self.by_account.get(akey) is frame:
            del self.by_account[akey]

    # ------------------------------------------------------------------
    def remove_applied(self, frames: Sequence[TransactionFrame]) -> None:
        """Drop txs included in the last closed ledger.
        Reference: TransactionQueue::removeApplied."""
        for f in frames:
            got = self.by_hash.get(f.content_hash())
            if got is not None:
                self._drop(got)
            else:
                # a different tx from the same account was applied: ours is
                # now stale (bad seq) — drop it too
                mine = self.by_account.get(self._account_key(f))
                if mine is not None and mine.seq_num <= f.seq_num:
                    self._drop(mine)

    def ban(self, frames: Sequence[TransactionFrame]) -> None:
        for f in frames:
            self.banned[f.content_hash()] = BAN_DEPTH
            got = self.by_hash.get(f.content_hash())
            if got is not None:
                self._drop(got)

    def shift(self) -> None:
        """Age the ban list one ledger.  Reference: TransactionQueue::shift."""
        for h in list(self.banned):
            self.banned[h] -= 1
            if self.banned[h] <= 0:
                del self.banned[h]

    def is_banned(self, tx_hash: bytes) -> bool:
        return tx_hash in self.banned

    def below_fee_floor(self, frame: TransactionFrame) -> bool:
        """True when a FULL queue would refuse this tx on fee grounds
        alone: it does not beat the current eviction victim's fee rate
        (and is not a replace-by-fee candidate for its own account's
        pending tx).  The admission pipeline applies this surge-pricing
        economics check BEFORE spending signature verification on a tx
        that try_add would reject anyway."""
        if len(self.by_hash) < self._max_queue_size():
            return False
        if self._account_key(frame) in self.by_account:
            return False  # replace-by-fee path decides, not eviction
        return fee_per_op(self._eviction_victim()) >= fee_per_op(frame)

    # ------------------------------------------------------------------
    def get_transactions(self) -> List[TransactionFrame]:
        return list(self.by_hash.values())

    def tx_set_frames(self, max_ops: Optional[int] = None
                      ) -> List[TransactionFrame]:
        """Candidate tx set under surge pricing: best fee-per-op first,
        trimmed to the ledger operation limit.  Reference:
        TxSetUtils/TxSetFrame — surge pricing + trimInvalid."""
        header = self.lm.lcl_header
        limit = max_ops if max_ops is not None else header.maxTxSetSize
        # protocol >= 11 counts operations; earlier protocols count txs
        count_ops = header.ledgerVersion >= 11
        out: List[TransactionFrame] = []
        used = 0
        for f in sorted(self.by_hash.values(), key=surge_sort_key):
            cost = f.num_operations() if count_ops else 1
            if used + cost > limit:
                continue
            out.append(f)
            used += cost
        return out

    @property
    def size(self) -> int:
        return len(self.by_hash)
