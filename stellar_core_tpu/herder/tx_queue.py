"""TransactionQueue — pending transactions between submission and inclusion.

Reference: src/herder/TransactionQueue.{h,cpp} — tryAdd (checkValid gating,
one pending tx per source account, fee-bump replace-by-fee at >=10x), ban
list with ban depth, size limiting with lowest-fee eviction, removeApplied /
shift after ledger close; src/herder/TxSetUtils — surge pricing (sort by
fee-per-op, trim to the ledger's op limit).
"""

from __future__ import annotations

import heapq
from fractions import Fraction
from typing import Callable, Dict, List, Optional, Sequence, Set

from .. import xdr as X
from ..ledger.ledger_txn import LedgerTxn
from ..transactions.frame import TransactionFrame
from ..util import logging as slog
from ..util.metrics import registry as _registry
from ..util.racetrace import race_checked

log = slog.get("Herder")

# Reference: TransactionQueue.h
FEE_MULTIPLIER = 10          # replace-by-fee bump factor
BAN_DEPTH = 10               # ledgers a banned tx stays banned
QUEUE_SIZE_MULTIPLIER = 4    # pool size = multiplier * max ledger ops


class AddResult:
    # Reference: TransactionQueue::AddResult::Code
    STATUS_PENDING = "pending"
    STATUS_DUPLICATE = "duplicate"
    STATUS_ERROR = "error"
    STATUS_TRY_AGAIN_LATER = "try-again-later"
    STATUS_BANNED = "banned"
    STATUS_FILTERED = "filtered"

    def __init__(self, code: str, result=None):
        self.code = code
        self.result = result

    def __repr__(self):
        return f"AddResult({self.code})"


def fee_per_op(frame: TransactionFrame) -> Fraction:
    """Exact rational fee rate.  Consensus-adjacent ordering must not go
    through floats: the reference compares fee rates by int128
    cross-multiplication (TxSetUtils feeRate3WayCompare); Fraction gives the
    same exact ordering."""
    return Fraction(frame.fee_bid, max(frame.num_operations(), 1))


def surge_sort_key(frame: TransactionFrame):
    """Surge pricing order: highest fee-per-op first, tx hash as the
    deterministic tiebreak (reference: TxSetUtils — feeRate comparison)."""
    return (-fee_per_op(frame), frame.content_hash())


# Eviction order = exact REVERSE of the surge sort BY CONSTRUCTION: max()
# over the same key picks the lowest fee-per-op tx, largest content hash
# among equal rates — precisely the tx surge pricing would include last
# (a bare min-by-fee left equal-rate ties to dict insertion order).  One
# key function so the two orders can never drift apart.
eviction_key = surge_sort_key


def _heap_key(frame: TransactionFrame):
    """Min-heap key whose MINIMUM is the eviction victim: lowest
    fee-per-op first, LARGEST content hash among equal rates (the
    negated-int hash inverts the byte order) — element-for-element the
    reverse of `surge_sort_key`, so `heap[0]` is exactly what
    `max(..., key=eviction_key)` used to scan for."""
    return (fee_per_op(frame),
            -int.from_bytes(frame.content_hash(), "big"))


@race_checked
class TransactionQueue:
    def __init__(self, ledger_manager, pool_ledger_multiplier: int =
                 QUEUE_SIZE_MULTIPLIER):
        self.lm = ledger_manager
        self.pool_multiplier = pool_ledger_multiplier
        # Queue state is owned by the main crank loop: http_admin
        # marshals /tx onto it and the admission pipeline runs as clock
        # actions, so mutation is single-threaded BY DESIGN; admin-thread
        # gauge reads (depth/banned) are GIL-atomic len() snapshots.  The
        # owned-by attestation is what the thread-safety lint checks, and
        # the race sanitizer proves it at runtime in `make race`.
        # source account id bytes -> frame (ONE pending tx per account)
        self.by_account: Dict[bytes, TransactionFrame] = {}  # corelint: owned-by=main -- mutated only on the crank loop; see class note
        self.by_hash: Dict[bytes, TransactionFrame] = {}  # corelint: owned-by=main -- mutated only on the crank loop; gauge reads are GIL-atomic
        # banned tx hash -> ledgers remaining
        self.banned: Dict[bytes, int] = {}  # corelint: owned-by=main -- mutated only on the crank loop; gauge reads are GIL-atomic
        # fee-ordered eviction index (ROADMAP 3a): a lazy-deletion
        # min-heap on `_heap_key` makes victim selection O(log n)
        # amortized instead of the old cached O(n) rescan per mutation —
        # under 2x overload every successful add evicts, so the rescan
        # was the sustained-TPS bottleneck.  Dropped frames stay in the
        # heap until they surface (identity-checked against by_hash) or
        # a compaction rebuilds it.  Entries carry a monotonic push
        # counter between key and frame: a banned-then-resubmitted
        # identical tx gives two entries with EQUAL (fee, hash) keys,
        # and without the counter heap sifts would fall through to
        # comparing TransactionFrames (TypeError).
        self._evict_heap: List[tuple] = []
        self._evict_seq = 0
        # depth gauges: registry is process-global, so the last-created
        # queue wins (multi-node simulations share one registry; per-node
        # depth stays in /metrics' herder section); weak_gauge so a
        # torn-down node's graph is not pinned
        _registry().weak_gauge("herder.tx-queue.depth", self,
                               lambda q: q.size)
        _registry().weak_gauge("herder.tx-queue.banned", self,
                               lambda q: len(q.banned))

    # ------------------------------------------------------------------
    def _account_key(self, frame: TransactionFrame) -> bytes:
        return frame.source_account_id().to_xdr()

    def _max_queue_size(self) -> int:
        return self.pool_multiplier * max(
            self.lm.lcl_header.maxTxSetSize, 1)

    def try_add(self, frame: TransactionFrame,
                close_time: Optional[int] = None) -> AddResult:
        """Validate and enqueue.  Reference: TransactionQueue::tryAdd."""
        h = frame.content_hash()
        if h in self.banned:
            return AddResult(AddResult.STATUS_BANNED)
        if h in self.by_hash:
            return AddResult(AddResult.STATUS_DUPLICATE)

        akey = self._account_key(frame)
        existing = self.by_account.get(akey)
        if existing is not None:
            # replace-by-fee: same account needs >= FEE_MULTIPLIER x fee
            # (full fee comparison; reference compares fee bids)
            if frame.fee_bid < FEE_MULTIPLIER * existing.fee_bid:
                return AddResult(AddResult.STATUS_TRY_AGAIN_LATER)

        ct = close_time if close_time is not None \
            else self.lm.lcl_header.scpValue.closeTime
        with LedgerTxn(self.lm.root) as ltx:  # read-only: rolls back on exit
            res = frame.check_valid(ltx, ct)
        if res.result.switch != X.TransactionResultCode.txSUCCESS:
            return AddResult(AddResult.STATUS_ERROR, res)

        if existing is not None:
            self._drop(existing)
        elif len(self.by_hash) >= self._max_queue_size():
            victim = self._eviction_victim()
            if fee_per_op(victim) >= fee_per_op(frame):
                return AddResult(AddResult.STATUS_TRY_AGAIN_LATER)
            self._drop(victim)
            self.banned[victim.content_hash()] = BAN_DEPTH

        self.by_account[akey] = frame
        self.by_hash[h] = frame
        self._heap_push(frame)
        return AddResult(AddResult.STATUS_PENDING)

    def _heap_push(self, frame: TransactionFrame) -> None:
        self._evict_seq += 1
        heapq.heappush(self._evict_heap,
                       (*_heap_key(frame), self._evict_seq, frame))

    def _eviction_victim(self) -> TransactionFrame:
        """The frame a full queue evicts first (see eviction_key) in
        O(log n) amortized: pop heap entries whose frame is no longer
        queued (lazy deletion — identity check, not just hash presence,
        so a re-added equal-bytes tx can never resurrect a stale entry),
        then peek.  Callers guarantee the queue is non-empty."""
        heap = self._evict_heap
        while heap:
            frame = heap[0][3]
            if self.by_hash.get(frame.content_hash()) is frame:
                return frame
            heapq.heappop(heap)
        # unreachable when by_hash is non-empty and every add pushed;
        # rebuild defensively rather than corrupt eviction economics
        self._rebuild_heap()
        return self._evict_heap[0][3]

    def _rebuild_heap(self) -> None:
        self._evict_heap = []
        for f in self.by_hash.values():
            self._heap_push(f)

    def _drop(self, frame: TransactionFrame) -> None:
        self.by_hash.pop(frame.content_hash(), None)
        akey = self._account_key(frame)
        if self.by_account.get(akey) is frame:
            del self.by_account[akey]
        # lazy heap deletion, bounded: when stale entries dominate the
        # live set, compact so heap memory stays O(queue)
        if len(self._evict_heap) > 64 \
                and len(self._evict_heap) > 2 * len(self.by_hash):
            self._rebuild_heap()

    # ------------------------------------------------------------------
    def remove_applied(self, frames: Sequence[TransactionFrame]) -> None:
        """Drop txs included in the last closed ledger.
        Reference: TransactionQueue::removeApplied."""
        for f in frames:
            got = self.by_hash.get(f.content_hash())
            if got is not None:
                self._drop(got)
            else:
                # a different tx from the same account was applied: ours is
                # now stale (bad seq) — drop it too
                mine = self.by_account.get(self._account_key(f))
                if mine is not None and mine.seq_num <= f.seq_num:
                    self._drop(mine)

    def ban(self, frames: Sequence[TransactionFrame]) -> None:
        for f in frames:
            self.banned[f.content_hash()] = BAN_DEPTH
            got = self.by_hash.get(f.content_hash())
            if got is not None:
                self._drop(got)

    def shift(self) -> None:
        """Age the ban list one ledger.  Reference: TransactionQueue::shift."""
        for h in list(self.banned):
            self.banned[h] -= 1
            if self.banned[h] <= 0:
                del self.banned[h]

    def is_banned(self, tx_hash: bytes) -> bool:
        return tx_hash in self.banned

    def below_fee_floor(self, frame: TransactionFrame) -> bool:
        """True when a FULL queue would refuse this tx on fee grounds
        alone: it does not beat the current eviction victim's fee rate
        (and is not a replace-by-fee candidate for its own account's
        pending tx).  The admission pipeline applies this surge-pricing
        economics check BEFORE spending signature verification on a tx
        that try_add would reject anyway."""
        if len(self.by_hash) < self._max_queue_size():
            return False
        if self._account_key(frame) in self.by_account:
            return False  # replace-by-fee path decides, not eviction
        return fee_per_op(self._eviction_victim()) >= fee_per_op(frame)

    # ------------------------------------------------------------------
    def get_transactions(self) -> List[TransactionFrame]:
        return list(self.by_hash.values())  # corelint: disable=iteration-order -- arrival-order inspection snapshot; canonical order is tx_set_frames()

    def tx_set_frames(self, max_ops: Optional[int] = None
                      ) -> List[TransactionFrame]:
        """Candidate tx set under surge pricing: best fee-per-op first,
        trimmed to the ledger operation limit.  Reference:
        TxSetUtils/TxSetFrame — surge pricing + trimInvalid.

        Soroban txs ride a separate lane (reference: SurgePricingLaneConfig
        with a dedicated Soroban lane): they are capped by the network
        config's per-ledger tx count and declared-instruction total, and do
        NOT consume classic tx-set operations."""
        from ..soroban import is_soroban_frame, network_config
        header = self.lm.lcl_header
        limit = max_ops if max_ops is not None else header.maxTxSetSize
        # protocol >= 11 counts operations; earlier protocols count txs
        count_ops = header.ledgerVersion >= 11
        net = network_config()
        out: List[TransactionFrame] = []
        used = 0
        sb_count = 0
        sb_insns = 0
        for f in sorted(self.by_hash.values(), key=surge_sort_key):
            if is_soroban_frame(f):
                sd = f.soroban_data()
                insns = int(sd.resources.instructions) if sd is not None else 0
                if sb_count + 1 > net.ledger_max_tx_count or \
                        sb_insns + insns > net.ledger_max_instructions:
                    continue
                out.append(f)
                sb_count += 1
                sb_insns += insns
                continue
            cost = f.num_operations() if count_ops else 1
            if used + cost > limit:
                continue
            out.append(f)
            used += cost
        return out

    @property
    def size(self) -> int:
        return len(self.by_hash)
