"""Fair multi-queue action scheduler with load-shedding.

Reference: src/util/Scheduler.{h,cpp} — actions posted to named queues;
the scheduler runs queues fairly (least-total-service first) and can shed
DROPPABLE actions when overloaded.
"""

from __future__ import annotations

import collections
from typing import Callable, Deque, Dict, Tuple

ACTION_NORMAL = 0
ACTION_DROPPABLE = 1

MAX_QUEUE_DEPTH = 10_000


class Scheduler:
    def __init__(self) -> None:
        self._queues: Dict[str, Deque[Tuple[Callable[[], None], int]]] = {}
        self._service: Dict[str, int] = collections.defaultdict(int)
        self.dropped = 0

    def enqueue(self, fn: Callable[[], None], name: str = "", queue_type: int = ACTION_NORMAL) -> None:
        q = self._queues.setdefault(name, collections.deque())
        if queue_type == ACTION_DROPPABLE and len(q) >= MAX_QUEUE_DEPTH:
            self.dropped += 1
            return
        q.append((fn, queue_type))

    def empty(self) -> bool:
        return all(not q for q in self._queues.values())

    def size(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def run_one_batch(self, max_actions: int = 100) -> int:
        """Run up to max_actions, serving the least-serviced nonempty queue
        first (the reference's fairness discipline)."""
        ran = 0
        while ran < max_actions:
            nonempty = [n for n, q in self._queues.items() if q]
            if not nonempty:
                break
            name = min(nonempty, key=lambda n: self._service[n])
            fn, _ = self._queues[name].popleft()
            self._service[name] += 1
            fn()
            ran += 1
        return ran
