"""Runtime deterministic-context guard: fail-stop on nondeterminism.

The determinism lint rules (lint/rules/determinism.py) prove consensus
*source* never reaches for wall-clock, unseeded RNG or hash-ordered
primitives; this module proves the same property *dynamically*, in the
racetrace/lockorder tradition (static rule + runtime sanitizer + a
differential tier).  Consensus entry points arm a guarded region::

    with detguard.region("ledger-close"):
        ...  # close path

and while any region is active on the current thread, the guarded
primitives — ``time.time``/``time.monotonic`` (and the ``_ns`` twins),
``os.urandom``, every module-level ``random.*`` draw, and builtin
``hash()`` on str/bytes (the primitive that makes set iteration
PYTHONHASHSEED-sensitive) — fail-stop with a flight event and a crash
bundle (same discipline as ``DataRaceError``) instead of silently
forking the replicated state machine.

Zero overhead while disarmed: ``region()`` is a cheap no-op and no
primitive is patched.  Arm with ``STPU_DETGUARD=1`` in the environment
at import (how the hash-seed differential harness runs campaigns, see
simulation/hashseed_diff.py) or ``enable()`` in-process.

Attribution: the wrappers resolve the *caller* frame.  Only calls from
``stellar_core_tpu`` code trip — stdlib infrastructure (threading,
queue, logging's LogRecord timestamps) schedules with monotonic time
without producing protocol-visible values — and the repo's own
observability plane (util/clock, util/perf, util/metrics, tracing,
eventlog, sampleprof, slo) plus the process-local bucket page filter
(bucket/index, reasoned hash-order suppression) are allowlisted for the
same reason.  Seeded ``random.Random`` *instances* are untouched: their
methods do not route through the patched module-level functions, which
is exactly the injected-RNG shape rng-discipline mandates.
"""

from __future__ import annotations

import builtins
import os
import random
import sys
import threading
import time
import traceback
from contextlib import contextmanager
from typing import Dict, Optional, Tuple

_armed = False
_tls = threading.local()
# counters only; a raw lock keeps the guard invisible to the traced-lock
# machinery it may run inside of
_stats_mu = threading.Lock()  # corelint: disable=raw-lock -- guard internals must stay invisible to lockorder's held stack
_stats = {"regions": 0, "trips": 0}
# (module, attr) -> original callable, populated by enable()
_originals: Dict[Tuple[int, str], Tuple[object, str, object]] = {}

# only calls originating from these path fragments trip (repo code, not
# stdlib scheduling); tests widen this to exercise the fail-stop
_TRIPPING_ROOTS = ("stellar_core_tpu",)
# caller paths allowed to touch guarded primitives inside a region
_EXEMPT_CALLERS = (
    "util/clock", "util/perf", "util/tracing", "util/metrics",
    "util/eventlog", "util/sampleprof", "util/slo", "util/logging",
    "util/detguard", "bucket/index",
)


class DeterminismError(AssertionError):
    """A guarded region touched a nondeterministic primitive."""


# ---------------------------------------------------------------------------
# regions
# ---------------------------------------------------------------------------

@contextmanager
def region(name: str):
    """Mark the dynamic extent of a consensus computation.  No-op while
    the guard is disarmed; nestable (soroban-apply inside ledger-close)."""
    if not _armed:
        yield
        return
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(name)
    with _stats_mu:
        _stats["regions"] += 1
    try:
        yield
    finally:
        stack.pop()


def current_region() -> Optional[str]:
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


def stats() -> dict:
    with _stats_mu:
        return dict(_stats)


def reset_stats() -> None:
    with _stats_mu:
        _stats["regions"] = 0
        _stats["trips"] = 0


# ---------------------------------------------------------------------------
# the tripwire
# ---------------------------------------------------------------------------

def _caller_trips() -> bool:
    """True when the frame that called the patched primitive is repo
    consensus code (not stdlib scheduling, not the observability plane)."""
    try:
        fn = sys._getframe(2).f_code.co_filename.replace(os.sep, "/")
    except ValueError:
        return False
    if not any(r in fn for r in _TRIPPING_ROOTS):
        return False
    return not any(s in fn for s in _EXEMPT_CALLERS)


def _trip(primitive: str) -> None:
    if getattr(_tls, "busy", False):
        return  # reporting plumbing is the guard's own, not the program's
    _tls.busy = True
    try:
        reg = current_region()
        stack = "".join(traceback.format_stack(limit=12)[:-2])
        msg = (f"nondeterministic primitive {primitive} inside guarded "
               f"region '{reg}' — consensus code must use VirtualClock / "
               f"an injected seeded Random / sorted iteration")
        with _stats_mu:
            _stats["trips"] += 1
        try:
            from . import eventlog
            eventlog.record("Process", "ERROR",
                            "determinism guard tripped",
                            region=reg, primitive=primitive,
                            caller_stack=stack)
            eventlog.write_crash_bundle(f"DeterminismError: {msg}")
        except Exception:  # corelint: disable=exception-hygiene -- the fail-stop below must never be masked by dump plumbing
            pass
        raise DeterminismError(msg)
    finally:
        _tls.busy = False


def _guard(orig, primitive: str, only_types: Optional[tuple] = None):
    def wrapper(*args, **kwargs):
        if _armed and getattr(_tls, "stack", None) \
                and (only_types is None
                     or (args and isinstance(args[0], only_types))) \
                and _caller_trips():
            _trip(primitive)
        return orig(*args, **kwargs)
    wrapper.__wrapped__ = orig
    wrapper.__name__ = getattr(orig, "__name__", primitive)
    return wrapper


def _targets():
    out = [
        (time, "time", "time.time", None),
        (time, "time_ns", "time.time_ns", None),
        (time, "monotonic", "time.monotonic", None),
        (time, "monotonic_ns", "time.monotonic_ns", None),
        (os, "urandom", "os.urandom", None),
        (builtins, "hash", "builtin hash() on str/bytes", (str, bytes)),
    ]
    for fname in ("random", "randint", "randrange", "choice", "choices",
                  "shuffle", "sample", "uniform", "getrandbits",
                  "randbytes", "seed"):
        if hasattr(random, fname):
            out.append((random, fname, f"random.{fname}", None))
    return out


def enable() -> None:
    """Patch the guarded primitives.  Idempotent; regions armed from now
    on.  Seeded random.Random instances keep their unpatched methods."""
    global _armed
    if _armed:
        return
    for mod, attr, primitive, only in _targets():
        orig = getattr(mod, attr)
        _originals[(id(mod), attr)] = (mod, attr, orig)
        setattr(mod, attr, _guard(orig, primitive, only))
    _armed = True


def disable() -> None:
    """Restore every patched primitive."""
    global _armed
    _armed = False
    for mod, attr, orig in list(_originals.values()):
        setattr(mod, attr, orig)
    _originals.clear()


def enabled() -> bool:
    return _armed


if os.environ.get("STPU_DETGUARD"):
    enable()
