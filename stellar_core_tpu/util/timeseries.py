"""In-process time-series store: the retrospective tier of /metrics.

Reference shape: stellar-core's retained medida history ("what did close
p99 look like over the last hour?") — the live registry (util/metrics)
answers only "what is it now".  A capture tick snapshots the registry
into bounded per-metric rings so a node can answer "when did this start
degrading, and what co-moved with it?" after the fact:

* **Delta encoding**: each ring entry stores only the snapshot fields
  that CHANGED since the previous tick, with a periodic keyframe
  carrying the full field set; readers reconstruct full points by
  replaying deltas from the per-metric base.  Idle metrics cost a few
  bytes per tick instead of a full snapshot row.
* **Tiered retention**: a dense recent window (every tick) plus a
  downsampled tail — points evicted from the dense ring survive at
  1-in-``downsample`` resolution in a second bounded ring, so a
  30-minute-old inflection is still visible after the dense window
  rolled past it.
* **Watermark export**: ``doc(since)`` mirrors tracing.tracespans_doc —
  every capture tick gets a monotonically increasing ``seq`` and the
  document carries ``next_since``, so /timeseries?since= readers (and
  the fleet scraper) pull incrementally without re-shipping history.

Capture is driven two ways, both OUTSIDE detguard regions (this is
observability-plane infrastructure, same exemption as sampleprof):
a VirtualTimer armed by the Application under VIRTUAL_TIME (tests crank
it deterministically), or the ``start()`` wall-cadence daemon thread on
real nodes.  The capture tick re-resolves ``registry()`` every time —
tests swap the whole registry object via reset_registry() and a cached
handle would snapshot a dead registry forever.

``dump()`` persists the full document next to crash bundles
($STPU_CRASH_DIR) and the ``stellar-core-tpu tsdump`` subcommand reads
it back offline.
"""

from __future__ import annotations

import json
import os
import threading
from collections import deque
from typing import Callable, Dict, List, Optional

from .clock import monotonic_now, wall_now
from .lockorder import make_lock
from .metrics import Histogram
from .metrics import registry as _metrics_registry
from .racetrace import race_checked

# Dense window: every capture tick; at the default 1 s cadence this is
# ~8.5 minutes of full-resolution history per metric.
DENSE_POINTS = int(os.environ.get("STPU_TIMESERIES_DENSE", "512"))
# Downsampled tail: 1 in DOWNSAMPLE of the points evicted from the dense
# ring — another ~68 minutes at 1 s cadence, bounded in count.
TAIL_POINTS = int(os.environ.get("STPU_TIMESERIES_TAIL", "512"))
DOWNSAMPLE = 8
# Full-field keyframe cadence inside the delta stream: bounds the replay
# work a read does and makes the stream robust to any base drift.
KEY_INTERVAL = 16

_NUMERIC = (int, float)


def _fields_of(snap: dict) -> Dict[str, float]:
    """The numeric fields of one metric snapshot (type tag dropped;
    dead-gauge None dropped — absence encodes it)."""
    return {k: v for k, v in snap.items()
            if k != "type" and isinstance(v, _NUMERIC) and v == v}


@race_checked
class TimeSeriesStore:
    """Bounded per-metric history of registry snapshots.  Fed by the
    capture tick (clock timer or wall daemon) and drained by
    /timeseries readers, the anomaly detector and dump files — every
    access is under ``_lock``."""

    def __init__(self, cadence_s: float = 1.0,
                 dense_points: int = DENSE_POINTS,
                 tail_points: int = TAIL_POINTS,
                 downsample: int = DOWNSAMPLE,
                 key_interval: int = KEY_INTERVAL) -> None:
        self.cadence_s = cadence_s
        self._dense_points = max(2, dense_points)
        self._tail_points = max(1, tail_points)
        self._downsample = max(1, downsample)
        self._key_interval = max(1, key_interval)
        self._lock = make_lock("timeseries.store")
        # per metric: dense delta ring of (seq, t, delta, is_key), the
        # full-field base as of just-before-the-oldest dense entry, the
        # full fields as of the newest entry (delta source), and the
        # downsampled tail ring of (seq, t, full_fields)
        self._dense: Dict[str, deque] = {}
        self._base: Dict[str, Dict[str, float]] = {}
        self._last: Dict[str, Dict[str, float]] = {}
        self._tail: Dict[str, deque] = {}
        self._seq = 0
        self._reg_box: List[object] = [None]
        # last-seen update count per Timer/Histogram — capture-thread
        # private (never read outside capture()), keyed like _last
        self._hist_counts: Dict[str, int] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()

    # -- capture ------------------------------------------------------------
    def capture(self, now: Optional[float] = None) -> int:
        """Snapshot the live registry into the rings; returns the tick's
        seq.  ``now`` lets virtual-time drivers stamp virtual seconds;
        wall-cadence capture stamps monotonic seconds."""
        t0 = monotonic_now()
        reg = _metrics_registry()
        if self._reg_box[0] is not reg:  # corelint: owned-by=timeseries-capture -- capture()-private cache; one capture driver per store (wall daemon OR clock timer), never both
            # registry swapped (reset_registry): re-home the self gauges
            self._reg_box[0] = reg
            self._hist_counts.clear()  # corelint: owned-by=timeseries-capture -- capture()-private cache; single capture driver per store
            reg.weak_gauge("timeseries.points.retained", self,
                           TimeSeriesStore.point_count)
            reg.weak_gauge("timeseries.capture.seq", self,
                           lambda s: s.seq)
        # Change-aware snapshot: a Timer/Histogram's fields derive only
        # from state mutated by update()/reset(), and both move `count`,
        # so an unchanged count means a bit-identical snapshot — skip
        # the percentile recompute (sorting a 1028-sample reservoir) and
        # reuse the last captured fields.  On a fleet-sim registry
        # (51 nodes sharing one process, thousands of timers) this is
        # the difference between ~80ms and ~2ms per tick — the <2%
        # ride-along budget the bench `telemetry` section asserts.
        snapshot: Dict[str, Optional[Dict[str, float]]] = {}
        for name, m in reg.items():
            if isinstance(m, Histogram):
                c = m.count
                if c == self._hist_counts.get(name):
                    snapshot[name] = None    # unchanged: reuse _last
                    continue
                self._hist_counts[name] = c
            snapshot[name] = _fields_of(m.snapshot())
        if now is None:
            now = t0
        with self._lock:
            self._seq += 1
            seq = self._seq
            for name, snap in snapshot.items():
                fields = snap if snap is not None \
                    else self._last.get(name, {})
                dq = self._dense.get(name)
                if dq is None:
                    dq = self._dense[name] = deque()
                    self._tail[name] = deque(maxlen=self._tail_points)
                    self._base[name] = {}
                    self._last[name] = {}
                last = self._last[name]
                is_key = seq % self._key_interval == 0
                if is_key:
                    delta = dict(fields)
                else:
                    delta = {k: v for k, v in fields.items()
                             if last.get(k) != v}
                if len(dq) >= self._dense_points:
                    self._base[name] = self._evict(
                        dq, self._base[name], self._tail[name])
                dq.append((seq, now, delta, is_key))
                self._last[name] = fields
        dur = monotonic_now() - t0
        reg.counter("timeseries.capture.ticks").inc()
        reg.timer("timeseries.capture.tick-time").update(dur)
        return seq

    def _evict(self, dq: deque, base: dict, tail: deque) -> dict:
        """Roll the oldest dense entry into the base (returned for the
        caller — who holds _lock — to store); 1 in downsample of evicted
        points survives as a full point in the tail ring."""
        seq, t, delta, is_key = dq.popleft()
        if is_key:
            base = dict(delta)
        else:
            base = dict(base)
            base.update(delta)
        if seq % self._downsample == 0:
            tail.append((seq, t, base))
        return base

    # -- wall-cadence capture thread (real nodes) ---------------------------
    def start(self, cadence_s: Optional[float] = None) -> None:
        """Start the wall-cadence capture daemon.  Idempotent.  Sims use
        a VirtualTimer driving capture() instead (Application wiring)."""
        if self._thread is not None and self._thread.is_alive():
            return
        if cadence_s is not None:
            self.cadence_s = cadence_s  # corelint: owned-by=main -- set before the daemon starts; daemon/export reads are GIL-atomic float snapshots
        self._stop_evt = threading.Event()  # corelint: owned-by=main -- rebound before thread start; Event is its own synchronizer
        self._thread = threading.Thread(
            target=self._run, name="timeseries-capture", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        evt = self._stop_evt
        while not evt.wait(self.cadence_s):
            try:
                self.capture()
            except Exception:  # corelint: disable=exception-hygiene -- capture must never kill its own daemon; next tick retries
                pass

    def stop(self) -> None:
        """Stop the capture daemon (no-op for timer-driven stores)."""
        t = self._thread
        if t is None:
            return
        self._stop_evt.set()
        t.join(timeout=5.0)
        self._thread = None

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    @property
    def seq(self) -> int:
        with self._lock:
            return self._seq

    def point_count(self) -> int:
        with self._lock:
            return (sum(len(d) for d in self._dense.values())
                    + sum(len(d) for d in self._tail.values()))

    def metric_names(self) -> List[str]:
        with self._lock:
            return sorted(self._dense)

    # -- export -------------------------------------------------------------
    def doc(self, since: int = 0,
            metric: Optional[str] = None) -> dict:
        """The /timeseries document: reconstructed full points with
        ``seq > since``, tail + dense merged per metric, plus the
        ``next_since`` watermark (same contract as tracespans_doc)."""
        series: Dict[str, List[dict]] = {}
        with self._lock:
            names = [metric] if metric else sorted(self._dense)
            for name in names:
                dq = self._dense.get(name)
                if dq is None:
                    continue
                points: List[dict] = []
                for seq, t, fields in self._tail.get(name, ()):
                    if seq > since:
                        points.append({"seq": seq, "t": round(t, 6),
                                       "v": dict(fields)})
                full = dict(self._base.get(name, {}))
                for seq, t, delta, is_key in dq:
                    if is_key:
                        full = dict(delta)
                    else:
                        full.update(delta)
                    if seq > since:
                        points.append({"seq": seq, "t": round(t, 6),
                                       "v": dict(full)})
                if points:
                    series[name] = points
            next_since = max(since, self._seq)
        return {"series": series, "next_since": next_since,
                "cadence_s": self.cadence_s}

    def latest(self, metric: str) -> Optional[dict]:
        """The newest full point for one metric, or None."""
        with self._lock:
            dq = self._dense.get(metric)
            if not dq:
                return None
            seq, t, _, _ = dq[-1]
            return {"seq": seq, "t": round(t, 6),
                    "v": dict(self._last.get(metric, {}))}

    def window(self, metric: str, ticks: int) -> List[dict]:
        """The trailing ``ticks`` full points of one metric — the
        breaching-window slice an anomaly bundle ships."""
        with self._lock:
            floor = self._seq - ticks
        return self.doc(since=max(0, floor),
                        metric=metric)["series"].get(metric, [])

    def bundle(self, ticks: int = 64) -> dict:
        """Flight-bundle source: the trailing window of every series."""
        with self._lock:
            floor = max(0, self._seq - ticks)
        out = self.doc(since=floor)
        out["captures"] = out.pop("next_since")
        return out

    # -- persistence --------------------------------------------------------
    def dump(self, path: Optional[str] = None,
             reason: str = "manual") -> str:
        """Persist the full document as JSON next to crash bundles
        ($STPU_CRASH_DIR, cwd fallback); returns the path written."""
        doc = self.doc(0)
        doc["kind"] = "timeseries-dump"
        doc["reason"] = reason
        doc["wall_time"] = wall_now()
        if path is None:
            out_dir = os.environ.get("STPU_CRASH_DIR", ".")
            os.makedirs(out_dir, exist_ok=True)
            path = os.path.join(
                out_dir,
                f"timeseries-{os.getpid()}-{doc['next_since']}.json")
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path


def load_dump(path: str) -> dict:
    """Read back a dump() file (the tsdump subcommand's loader);
    raises ValueError on files that are not time-series dumps."""
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or doc.get("kind") != "timeseries-dump" \
            or not isinstance(doc.get("series"), dict):
        raise ValueError(f"{path}: not a timeseries dump file")
    return doc
