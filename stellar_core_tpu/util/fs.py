"""Filesystem utilities: TmpDir, durable writes, lockfile.

Reference: src/util/{Fs,TmpDir}.{h,cpp} — mkpath, durableRename,
lockFile/unlockFile (single-process-per-DB guard), TmpDirManager's
per-activity scratch dirs cleaned on close.
"""

from __future__ import annotations

import os
import shutil
import tempfile
from typing import Optional

from . import logging as slog

log = slog.get("Fs")


def mkpath(path: str) -> None:
    os.makedirs(path, exist_ok=True)


def durable_write(path: str, data: bytes) -> None:
    """Atomic + power-loss-durable file write: tmp, fsync, rename, fsync
    dir (reference: Fs::durableRename discipline)."""
    d = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp-")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        dfd = os.open(d, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def lock_file(path: str) -> int:
    """Take an exclusive advisory lock (reference: Fs::lockFile guards one
    process per database).  Returns the fd; raises if already locked."""
    import fcntl
    fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o644)
    try:
        fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
    except OSError:
        os.close(fd)
        raise RuntimeError(f"{path} is locked by another process")
    os.write(fd, str(os.getpid()).encode())
    return fd


def unlock_file(fd: int) -> None:
    import fcntl
    fcntl.flock(fd, fcntl.LOCK_UN)
    os.close(fd)


class TmpDir:
    """Scoped scratch directory (reference: TmpDir via TmpDirManager)."""

    def __init__(self, base: Optional[str] = None, prefix: str = "work"):
        self.path = tempfile.mkdtemp(prefix=f"{prefix}-", dir=base)

    def __enter__(self) -> "TmpDir":
        return self

    def __exit__(self, *exc) -> None:
        self.cleanup()

    def cleanup(self) -> None:
        if os.path.isdir(self.path):
            shutil.rmtree(self.path, ignore_errors=True)
