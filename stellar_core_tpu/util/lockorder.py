"""Runtime lock-order tracer: record the real acquisition DAG, fail-stop
on inversion.

The static side (corelint's lock-order rule) proves the *lexical*
acquisition graph acyclic; this module is the runtime complement for the
orders statics can't see (callbacks, cross-module paths).  The five
lock-bearing modules (bucket/manager, bucket/snapshot, util/metrics,
util/tracing, crypto/keys) create their locks through `make_lock` /
`make_rlock` with a lock-class name; with tracing OFF (the default) the
factory returns a plain `threading.Lock` — zero per-acquisition
overhead.  With tracing ON (`STPU_LOCK_TRACE=1` in the environment at
lock-creation time, or `enable()` before the subsystem is built) each
acquisition records held->acquired edges into a process-global graph and
raises `LockOrderError` BEFORE acquiring if the new edge would close a
cycle — turning a potential ABBA deadlock into an immediate, attributed
failure (reference shape: the invariant fail-stop discipline).

Identity is the lock *class* (the name passed to the factory), not the
instance: all `metrics.histogram` locks are one node, which is the
granularity deadlock analysis needs.  Re-acquiring the same class while
holding it is tolerated for RLocks and self-edges are never recorded.
The tracer assumes each acquisition is released by the acquiring thread
(true for all `with`-scoped usage, which is the only form in this
tree): a cross-thread release — legal for a bare `threading.Lock` —
would leave a stale held-stack entry on the acquiring thread and skew
its subsequent edges.

Overhead when enabled: one thread-local list append + a dict probe per
acquisition, and a DFS over the (tiny) class graph only when a NEW edge
appears; see PROFILE.md.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Set, Tuple

# STPU_RACE_TRACE implies lock tracing: the race sanitizer
# (util/racetrace.py) computes per-field locksets from this module's
# thread-local held stack, which only fills when locks are traced
_enabled = bool(os.environ.get("STPU_LOCK_TRACE")) \
    or bool(os.environ.get("STPU_RACE_TRACE"))
_graph_mu = threading.Lock()
# observed acquisition edges: held-class -> set of acquired-classes
_edges: Dict[str, Set[str]] = {}
_tls = threading.local()


class LockOrderError(AssertionError):
    """A lock acquisition inverted the observed acquisition DAG."""


def _fail_lock_order(msg: str) -> None:
    """Fail-stop with a post-mortem: record the inversion as a flight
    event and write a crash bundle (util/eventlog → $STPU_CRASH_DIR)
    before raising.  Called with NO locks held (the caller releases
    _graph_mu first) so bundle assembly — which snapshots metrics and the
    event ring under their own locks — cannot add edges to the graph
    being reported on, let alone deadlock against it."""
    try:
        from . import eventlog
        eventlog.record("Process", "ERROR", "lock-order inversion",
                        detail=msg)
        eventlog.write_crash_bundle(f"LockOrderError: {msg}")
    except Exception:  # corelint: disable=exception-hygiene -- the fail-stop below must never be masked by dump plumbing
        pass
    raise LockOrderError(msg)


def enable() -> None:
    """Trace locks created from now on (locks made before stay plain)."""
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


def make_lock(name: str) -> "threading.Lock":
    """A `threading.Lock`, traced under `name` when tracing is enabled."""
    lock = threading.Lock()
    return _TracedLock(lock, name) if _enabled else lock


def make_rlock(name: str) -> "threading.RLock":
    lock = threading.RLock()
    return _TracedLock(lock, name, reentrant=True) if _enabled else lock


def observed_edges() -> Dict[str, Set[str]]:
    """Copy of the acquisition DAG recorded so far."""
    with _graph_mu:
        return {k: set(v) for k, v in _edges.items()}


def reset_observed() -> None:
    with _graph_mu:
        _edges.clear()


def held_locks() -> Tuple[str, ...]:
    """Lock classes the CALLING thread currently holds, innermost last
    (reentrant re-acquisitions appear once per acquire).  The race
    sanitizer's lockset source; empty when tracing is off or the thread
    holds only untraced locks."""
    return tuple(_held_stack())


def _held_stack() -> List[str]:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def _would_cycle(src: str, dst: str) -> List[str]:
    """Path dst ~> src in the edge graph (so adding src->dst closes a
    cycle), or [] — caller holds _graph_mu."""
    path = [dst]
    seen = {dst}

    def dfs(u: str) -> bool:
        if u == src:
            return True
        for v in _edges.get(u, ()):
            if v not in seen:
                seen.add(v)
                path.append(v)
                if dfs(v):
                    return True
                path.pop()
        return False

    return path if dfs(dst) else []


class _TracedLock:
    """Lock proxy recording acquisition order by lock class."""

    __slots__ = ("_lock", "name", "_reentrant")

    def __init__(self, lock, name: str, reentrant: bool = False):
        self._lock = lock
        self.name = name
        self._reentrant = reentrant

    def _before_acquire(self) -> None:
        held = _held_stack()
        if not held:
            return
        if self.name in held:
            if self._reentrant:
                return  # same-class re-entry: no edge, no inversion
            _fail_lock_order(
                f"non-reentrant lock class '{self.name}' re-acquired "
                f"while already held (held: {held})")
        new_edges: List[Tuple[str, str]] = []
        inversion = None
        with _graph_mu:
            for h in held:
                if self.name not in _edges.get(h, ()):
                    cyc = _would_cycle(h, self.name)
                    if cyc:
                        inversion = (
                            f"lock-order inversion: acquiring "
                            f"'{self.name}' while holding '{h}', but the "
                            f"observed DAG already orders "
                            f"{' -> '.join(cyc)}")
                        break
                    new_edges.append((h, self.name))
            if inversion is None:
                for h, n in new_edges:
                    _edges.setdefault(h, set()).add(n)
        if inversion is not None:
            # raised OUTSIDE _graph_mu: the crash-bundle dump acquires
            # other (traced) locks and must not nest under the graph lock
            _fail_lock_order(inversion)

    def acquire(self, *a, **kw) -> bool:
        self._before_acquire()
        got = self._lock.acquire(*a, **kw)
        if got:
            _held_stack().append(self.name)
        return got

    def release(self) -> None:
        self._lock.release()
        stack = _held_stack()
        # remove the innermost matching frame (not necessarily the top:
        # out-of-order releases are legal for locks)
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == self.name:
                del stack[i]
                break

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        # deliberate delegation with no fallback: a traced lock exposes
        # exactly the wrapped lock's API (RLock grows .locked() only in
        # Python 3.14) — tracing must not change what code can call
        return self._lock.locked()
