"""Perf tracing: slow-execution logging + JAX profiler hook.

Reference: src/util/LogSlowExecution.{h,cpp} (warn when a scope exceeds a
threshold) and the Perf log partition.  Timing data itself lands in the
util.metrics registry (one timer surface); this module adds the
slow-threshold warning and the device profiler wrapper.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Optional

from . import logging as slog
from .metrics import registry

log = slog.get("Perf")

DEFAULT_SLOW_THRESHOLD = 1.0  # seconds (reference: LogSlowExecution 1s)

# Per-name slow-threshold overrides: hot scopes (ledger close, ~ms) and
# slow-by-nature scopes (checkpoint download, tens of seconds) need
# different budgets than the 1s default.
_slow_thresholds: Dict[str, float] = {}

_USE_DEFAULT = object()  # sentinel: caller passed nothing (None = disabled)


def set_slow_threshold(name: str, threshold: Optional[float]) -> None:
    """Set (or with None, clear back to default) the slow budget for one
    scope name.  Applies to scoped_timer calls that don't pass an explicit
    threshold."""
    if threshold is None:
        _slow_thresholds.pop(name, None)
    else:
        _slow_thresholds[name] = threshold


def slow_threshold_for(name: str) -> float:
    return _slow_thresholds.get(name, DEFAULT_SLOW_THRESHOLD)


@contextlib.contextmanager
def scoped_timer(name: str, slow_threshold=_USE_DEFAULT):
    """Time a scope into the metrics registry's timer of the same name
    (ONE timer surface — util.metrics) and warn when the scope ran slow
    (reference: LogSlowExecution dtor + medida Timer::Update).

    slow_threshold: seconds; omit to use the per-name override (or the 1s
    default), pass None to disable the warning for this call."""
    if slow_threshold is _USE_DEFAULT:
        slow_threshold = slow_threshold_for(name)
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        registry().timer(name).update(dt)
        if slow_threshold is not None and dt > slow_threshold:
            log.warning("'%s' took %.3fs (threshold %.3fs)",
                        name, dt, slow_threshold)


@contextlib.contextmanager
def jax_profile(log_dir: str):
    """Device-level profiler trace around a scope (the TPU analog of the
    reference's perf instrumentation); no-op if JAX is unavailable."""
    try:
        import jax
    except ImportError:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield
