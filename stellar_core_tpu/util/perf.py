"""Perf tracing: slow-execution logging + JAX profiler hook.

Reference: src/util/LogSlowExecution.{h,cpp} (warn when a scope exceeds a
threshold) and the Perf log partition.  Timing data itself lands in the
util.metrics registry (one timer surface); this module adds the
slow-threshold warning and the device profiler wrapper.
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional

from . import logging as slog

log = slog.get("Perf")

DEFAULT_SLOW_THRESHOLD = 1.0  # seconds (reference: LogSlowExecution 1s)


@contextlib.contextmanager
def scoped_timer(name: str,
                 slow_threshold: Optional[float] = DEFAULT_SLOW_THRESHOLD):
    """Time a scope into the metrics registry's timer of the same name
    (ONE timer surface — util.metrics) and warn when the scope ran slow
    (reference: LogSlowExecution dtor + medida Timer::Update)."""
    from .metrics import registry
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        registry().timer(name).update(dt)
        if slow_threshold is not None and dt > slow_threshold:
            log.warning("'%s' took %.3fs (threshold %.3fs)",
                        name, dt, slow_threshold)


@contextlib.contextmanager
def jax_profile(log_dir: str):
    """Device-level profiler trace around a scope (the TPU analog of the
    reference's perf instrumentation); no-op if JAX is unavailable."""
    try:
        import jax
    except ImportError:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield
