"""ProcessManager: async subprocess execution on the clock loop.

Reference: src/process/ProcessManager{,Impl}.{h,cpp} — runCommand returning
a ProcessExitEvent whose completion posts back onto the main loop; bounded
concurrency (MAX_CONCURRENT_SUBPROCESSES); kill-on-shutdown.  The reference
uses it for history get/put command templates (curl, gzip, aws cp); here
the same surface drives external archive commands.

Implementation: subprocess.Popen polled from a clock IO pump — no threads,
completion callbacks fire inside crank like every other event.
"""

from __future__ import annotations

import shlex
import subprocess
from typing import Callable, Deque, List, Optional
from collections import deque

from . import logging as slog
from .clock import VirtualClock

log = slog.get("Process")

MAX_CONCURRENT_SUBPROCESSES = 8


class ProcessExitEvent:
    """Handle for one running (or queued) command."""

    def __init__(self, cmdline: str,
                 on_exit: Callable[[int], None],
                 output_path: Optional[str] = None):
        self.cmdline = cmdline
        self.on_exit = on_exit
        self.output_path = output_path   # combined stdout+stderr capture
        self.proc: Optional[subprocess.Popen] = None
        self.exit_code: Optional[int] = None
        self.cancelled = False
        self._out_fh = None

    def _close_output(self) -> None:
        if self._out_fh is not None:
            self._out_fh.close()
            self._out_fh = None

    @property
    def running(self) -> bool:
        return self.proc is not None and self.exit_code is None

    @property
    def done(self) -> bool:
        return self.exit_code is not None


class ProcessManager:
    def __init__(self, clock: VirtualClock,
                 max_concurrent: int = MAX_CONCURRENT_SUBPROCESSES):
        self.clock = clock
        self.max_concurrent = max_concurrent
        self._running: List[ProcessExitEvent] = []
        self._pending: Deque[ProcessExitEvent] = deque()
        self._shutdown = False
        clock.add_io_pump(self._pump)

    def run_command(self, cmdline: str,
                    on_exit: Callable[[int], None],
                    output_path: Optional[str] = None) -> ProcessExitEvent:
        """Queue a shell-less command; on_exit(code) fires on the clock loop
        (reference: ProcessManagerImpl::runProcess).  With `output_path`
        the child's stdout+stderr append to that file (the parallel-catchup
        range workers' post-mortem trail) instead of being discarded."""
        ev = ProcessExitEvent(cmdline, on_exit, output_path=output_path)
        self._pending.append(ev)
        self._maybe_start()
        return ev

    def cancel(self, ev: ProcessExitEvent) -> None:
        ev.cancelled = True
        if ev in self._pending:
            self._pending.remove(ev)
            ev.exit_code = -1
            return
        if ev.proc is not None and ev.exit_code is None:
            ev.proc.kill()

    def _maybe_start(self) -> None:
        while (not self._shutdown and self._pending
               and len(self._running) < self.max_concurrent):
            ev = self._pending.popleft()
            try:
                out = subprocess.DEVNULL
                if ev.output_path is not None:
                    ev._out_fh = open(ev.output_path, "ab")
                    out = ev._out_fh
                ev.proc = subprocess.Popen(
                    shlex.split(ev.cmdline),
                    stdout=out,
                    stderr=subprocess.STDOUT if ev.output_path is not None
                    else subprocess.DEVNULL)
            except OSError as e:
                log.warning("spawn failed: %s (%s)", ev.cmdline, e)
                ev._close_output()
                ev.exit_code = 127
                self.clock.post_action(lambda ev=ev: ev.on_exit(127),
                                       name="process-exit")
                continue
            self._running.append(ev)

    def _pump(self) -> int:
        progressed = 0
        for ev in list(self._running):
            code = ev.proc.poll()
            if code is None:
                continue
            ev.exit_code = code
            ev._close_output()
            self._running.remove(ev)
            progressed += 1
            if not ev.cancelled:
                self.clock.post_action(lambda ev=ev, c=code: ev.on_exit(c),
                                       name="process-exit")
        if progressed:
            self._maybe_start()
        return progressed

    def shutdown(self) -> None:
        """Kill everything (reference: ProcessManagerImpl::shutdown)."""
        self._shutdown = True
        self.clock.remove_io_pump(self._pump)
        for ev in self._pending:
            ev.exit_code = -1
        self._pending.clear()
        for ev in self._running:
            if ev.proc is not None and ev.exit_code is None:
                ev.proc.kill()
                ev.proc.wait()
                ev.exit_code = ev.proc.returncode
            ev._close_output()
        self._running.clear()

    @property
    def num_running(self) -> int:
        return len(self._running)
