"""ProcessManager: async subprocess execution on the clock loop.

Reference: src/process/ProcessManager{,Impl}.{h,cpp} — runCommand returning
a ProcessExitEvent whose completion posts back onto the main loop; bounded
concurrency (MAX_CONCURRENT_SUBPROCESSES); kill-on-shutdown.  The reference
uses it for history get/put command templates (curl, gzip, aws cp); here
the same surface drives external archive commands.

Implementation: subprocess.Popen polled from a clock IO pump — no threads,
completion callbacks fire inside crank like every other event.
"""

from __future__ import annotations

import shlex
import signal
import subprocess
import time as _time
from typing import Callable, Deque, List, Optional
from collections import deque

from . import logging as slog
from .clock import VirtualClock, monotonic_now

log = slog.get("Process")

MAX_CONCURRENT_SUBPROCESSES = 8

# Default SIGTERM -> SIGKILL escalation window (reference:
# ProcessManagerImpl::shutdown kills outright; real node fleets need the
# children — themselves full nodes flushing sqlite/bucket state — a grace
# period to exit cleanly before the hard kill guarantees no orphans).
DEFAULT_GRACE_S = 5.0


class ProcessExitEvent:
    """Handle for one running (or queued) command."""

    def __init__(self, cmdline: str,
                 on_exit: Callable[[int], None],
                 output_path: Optional[str] = None):
        self.cmdline = cmdline
        self.on_exit = on_exit
        self.output_path = output_path   # combined stdout+stderr capture
        self.proc: Optional[subprocess.Popen] = None
        self.exit_code: Optional[int] = None
        self.cancelled = False
        self._out_fh = None
        self._kill_timer = None   # armed by ProcessManager.stop escalation

    def _close_output(self) -> None:
        if self._out_fh is not None:
            self._out_fh.close()
            self._out_fh = None

    @property
    def running(self) -> bool:
        return self.proc is not None and self.exit_code is None

    @property
    def done(self) -> bool:
        return self.exit_code is not None


class ProcessManager:
    def __init__(self, clock: VirtualClock,
                 max_concurrent: int = MAX_CONCURRENT_SUBPROCESSES):
        self.clock = clock
        self.max_concurrent = max_concurrent
        self._running: List[ProcessExitEvent] = []
        self._pending: Deque[ProcessExitEvent] = deque()
        self._shutdown = False
        clock.add_io_pump(self._pump)

    def run_command(self, cmdline: str,
                    on_exit: Callable[[int], None],
                    output_path: Optional[str] = None) -> ProcessExitEvent:
        """Queue a shell-less command; on_exit(code) fires on the clock loop
        (reference: ProcessManagerImpl::runProcess).  With `output_path`
        the child's stdout+stderr append to that file (the parallel-catchup
        range workers' post-mortem trail) instead of being discarded."""
        ev = ProcessExitEvent(cmdline, on_exit, output_path=output_path)
        self._pending.append(ev)
        self._maybe_start()
        return ev

    def cancel(self, ev: ProcessExitEvent) -> None:
        ev.cancelled = True
        if ev in self._pending:
            self._pending.remove(ev)
            ev.exit_code = -1
            return
        if ev.proc is not None and ev.exit_code is None:
            ev.proc.kill()

    def stop(self, ev: ProcessExitEvent,
             grace_s: float = DEFAULT_GRACE_S) -> None:
        """Graceful stop with escalation: SIGTERM now; if the child is
        still alive after `grace_s` a clock timer SIGKILLs it.  Unlike
        cancel(), on_exit still fires (callers observe the exit code) —
        this is how a fleet harness rolls a node without orphaning it.
        grace_s=0 escalates immediately."""
        if ev in self._pending:
            # never started: report the stop as an exit so callers
            # waiting on on_exit (the documented contract) still wake
            self._pending.remove(ev)
            ev.exit_code = -1
            self.clock.post_action(lambda ev=ev: ev.on_exit(-1),
                                   name="process-exit")
            return
        if ev.proc is None or ev.exit_code is not None:
            return
        if grace_s <= 0:
            ev.proc.kill()
            return
        try:
            ev.proc.send_signal(signal.SIGTERM)
        except OSError:
            return   # already gone; the pump reaps it
        from .clock import VirtualTimer
        timer = VirtualTimer(self.clock)
        ev._kill_timer = timer   # pin: a collected timer never fires

        def escalate() -> None:
            if ev.proc is not None and ev.exit_code is None \
                    and ev.proc.poll() is None:
                log.warning("process ignored SIGTERM for %.1fs; killing: %s",
                            grace_s, ev.cmdline)
                ev.proc.kill()

        timer.expires_from_now(grace_s, escalate)

    def _maybe_start(self) -> None:
        while (not self._shutdown and self._pending
               and len(self._running) < self.max_concurrent):
            ev = self._pending.popleft()
            try:
                out = subprocess.DEVNULL
                if ev.output_path is not None:
                    ev._out_fh = open(ev.output_path, "ab")
                    out = ev._out_fh
                ev.proc = subprocess.Popen(
                    shlex.split(ev.cmdline),
                    stdout=out,
                    stderr=subprocess.STDOUT if ev.output_path is not None
                    else subprocess.DEVNULL)
            except OSError as e:
                log.warning("spawn failed: %s (%s)", ev.cmdline, e)
                ev._close_output()
                ev.exit_code = 127
                self.clock.post_action(lambda ev=ev: ev.on_exit(127),
                                       name="process-exit")
                continue
            self._running.append(ev)

    def _pump(self) -> int:
        progressed = 0
        for ev in list(self._running):
            code = ev.proc.poll()
            if code is None:
                continue
            ev.exit_code = code
            ev._close_output()
            if ev._kill_timer is not None:
                ev._kill_timer.cancel()
                ev._kill_timer = None
            self._running.remove(ev)
            progressed += 1
            if not ev.cancelled:
                self.clock.post_action(lambda ev=ev, c=code: ev.on_exit(c),
                                       name="process-exit")
        if progressed:
            self._maybe_start()
        return progressed

    def shutdown(self, grace_s: float = 0.0) -> None:
        """Stop everything (reference: ProcessManagerImpl::shutdown).
        grace_s=0 keeps the historical hard-kill semantics; with a grace
        period every running child first gets SIGTERM, the whole set is
        polled for up to `grace_s`, and only the survivors are SIGKILLed —
        fleet teardown never leaks orphan nodes either way, but graceful
        children (flushing databases, closing sockets) get to exit 0."""
        self._shutdown = True
        self.clock.remove_io_pump(self._pump)
        for ev in self._pending:
            ev.exit_code = -1
        self._pending.clear()
        alive = [ev for ev in self._running
                 if ev.proc is not None and ev.exit_code is None]
        if grace_s > 0 and alive:
            for ev in alive:
                try:
                    ev.proc.send_signal(signal.SIGTERM)
                except OSError:
                    pass
            deadline = monotonic_now() + grace_s
            while monotonic_now() < deadline \
                    and any(ev.proc.poll() is None for ev in alive):
                _time.sleep(0.02)
        for ev in self._running:
            if ev.proc is not None and ev.exit_code is None:
                if ev.proc.poll() is None:
                    ev.proc.kill()
                ev.proc.wait()
                ev.exit_code = ev.proc.returncode
            ev._close_output()
        self._running.clear()

    @property
    def num_running(self) -> int:
        return len(self._running)
