"""Always-on assertions (reference: src/util/GlobalChecks.h —
releaseAssert / releaseAssertOrThrow).

The reference never uses plain `assert` for consensus-critical conditions:
release builds keep the checks (crash-only/fail-stop philosophy, SURVEY.md
§5.2-5.3).  Python's `assert` disappears under ``-O`` — these don't.
Plain `assert` statements remain the marker for strippable hot-loop
sanity checks.
"""

from __future__ import annotations


class ReleaseAssertError(AssertionError):
    """An always-on invariant failed — the process state is suspect
    (callers are expected NOT to catch this; fail-stop)."""


def release_assert(cond: bool, msg: str = "release assertion failed") -> None:
    """Fail-stop check that survives ``python -O`` (reference:
    releaseAssert)."""
    if not cond:
        raise ReleaseAssertError(msg)


def release_assert_or_throw(cond: bool, exc_type=None,
                            msg: str = "invariant violated") -> None:
    """Like release_assert but raising a caller-chosen exception type
    (reference: releaseAssertOrThrow)."""
    if not cond:
        raise (exc_type or ReleaseAssertError)(msg)

# For strippable hot-loop sanity checks, use a plain `assert` statement at
# the call site — a helper function cannot avoid evaluating the condition.
