"""Bounded caches.

* ``RandomEvictionCache`` — fixed-size map evicting a random entry when
  full.  Reference: src/util/RandomEvictionCache.h.  Used by the
  signature-verify cache (src/crypto/SecretKey.cpp) and bucket-entry
  caches.  Random eviction (not LRU) keeps adversaries from
  deterministically flushing hot entries.
* ``LRUCache`` — classic least-recently-used map.  Backs the
  BucketListDB entry cache in ``LedgerTxnRoot`` (reference: the
  InMemorySorobanState-adjacent entry cache of LedgerTxnRoot /
  BucketListDB's RandomEvictionCache — LRU here because replay's access
  pattern is hot-account dominated, not adversarial).
"""

from __future__ import annotations

import random
from collections import OrderedDict
from typing import Dict, Generic, Hashable, List, Optional, TypeVar

K = TypeVar("K", bound=Hashable)
V = TypeVar("V")


class LRUCache(Generic[K, V]):
    """Least-recently-used bounded map.  ``get`` distinguishes a cached
    None from a miss via the `default` sentinel, so callers can cache
    negative lookups ("this key is definitively absent") — the
    BucketListDB root does, to spare repeated 22-bucket probe chains."""

    __slots__ = ("_max", "_map", "hits", "misses")

    def __init__(self, max_size: int) -> None:
        if max_size <= 0:
            raise ValueError("max_size must be positive")
        self._max = max_size
        self._map: "OrderedDict[K, V]" = OrderedDict()
        self.hits = 0
        self.misses = 0

    def __len__(self) -> int:
        return len(self._map)

    def __contains__(self, key: K) -> bool:
        return key in self._map

    @property
    def max_size(self) -> int:
        return self._max

    def get(self, key: K, default=None):
        try:
            v = self._map[key]
        except KeyError:
            self.misses += 1
            return default
        self._map.move_to_end(key)
        self.hits += 1
        return v

    def put(self, key: K, value: V) -> None:
        m = self._map
        if key in m:
            m[key] = value
            m.move_to_end(key)
            return
        if len(m) >= self._max:
            m.popitem(last=False)
        m[key] = value

    def pop(self, key: K) -> None:
        self._map.pop(key, None)

    def clear(self) -> None:
        self._map.clear()

    def hit_rate(self) -> float:
        t = self.hits + self.misses
        return self.hits / t if t else 0.0


class RandomEvictionCache(Generic[K, V]):
    def __init__(self, max_size: int, rng: Optional[random.Random] = None) -> None:
        if max_size <= 0:
            raise ValueError("max_size must be positive")
        self._max = max_size
        self._map: Dict[K, V] = {}
        self._keys: List[K] = []
        self._pos: Dict[K, int] = {}
        self._rng = rng or random.Random(0)
        self.hits = 0
        self.misses = 0

    def __len__(self) -> int:
        return len(self._map)

    def __contains__(self, key: K) -> bool:
        return key in self._map

    def put(self, key: K, value: V) -> None:
        if key in self._map:
            self._map[key] = value
            return
        if len(self._map) >= self._max:
            i = self._rng.randrange(len(self._keys))
            evicted = self._keys[i]
            last = self._keys[-1]
            self._keys[i] = last
            self._pos[last] = i
            self._keys.pop()
            del self._pos[evicted]
            del self._map[evicted]
        self._pos[key] = len(self._keys)
        self._keys.append(key)
        self._map[key] = value

    def get(self, key: K) -> Optional[V]:
        v = self._map.get(key)
        if v is None and key not in self._map:
            self.misses += 1
            return None
        self.hits += 1
        return v

    def maybe_get(self, key: K) -> Optional[V]:
        return self._map.get(key)

    def clear(self) -> None:
        self._map.clear()
        self._keys.clear()
        self._pos.clear()
