"""Partitioned logging. Reference: src/util/Logging.{h,cpp} — CLOG_* macros
with per-partition runtime-settable levels (Fs, SCP, Bucket, Overlay, History,
Ledger, Herder, Tx, Database, Process, Work, Invariant, Perf), plus the
spdlog-backed structured mode: ``LOG_FORMAT=json`` (config, or live via
``/ll?format=json``) switches every handler to one-JSON-object-per-line
records that carry the current span id from util/tracing — so a slow
``ledger.close`` span can be joined against every log line it emitted.

Every WARNING+ record is also bridged into the flight recorder
(util/eventlog) for post-mortem bundles; records below the bridge level
never reach the handler (stdlib level filtering — zero cost).
"""

from __future__ import annotations

import json
import logging
import sys
from typing import Dict

PARTITIONS = (
    "Fs", "SCP", "Bucket", "Overlay", "History", "Ledger", "Herder", "Tx",
    "Database", "Process", "Work", "Invariant", "Perf", "Main",
    "CommandHandler", "Fuzz", "Sim",
)

LOG_FORMATS = ("text", "json")

_loggers: Dict[str, logging.Logger] = {}
_configured = False
_format = "text"
# fleet-wide attribution: the node name this process (or in-sim node)
# runs as.  Provisioned per node by simulation/fleet (NODE_NAME config
# key) and stamped into JSON log records, flight-event exports and
# rate-limit keys so aggregated soak logs stay attributable.
_node_id: str | None = None


def set_node_id(name: str | None) -> None:
    """Configure the node name stamped into structured output (JSON log
    records, flight-event exports, /tracespans documents).  None clears."""
    global _node_id
    _node_id = name or None


def node_id() -> str | None:
    """The configured node name, or None when unset (single-node runs)."""
    return _node_id

_TEXT_FORMATTER = logging.Formatter(
    "%(asctime)s [%(name)s %(levelname)s] %(message)s")


class JsonFormatter(logging.Formatter):
    """One JSON object per line: ts (epoch seconds), partition, level,
    msg — and the id of the span open in the emitting context, the
    correlation key against /trace exports and flight events."""

    def format(self, rec: logging.LogRecord) -> str:
        from . import tracing
        name = rec.name
        doc = {
            "ts": round(rec.created, 3),
            "partition": name.rsplit(".", 1)[-1] if "." in name else "root",
            "level": rec.levelname,
            "msg": rec.getMessage(),
        }
        if _node_id is not None:
            doc["node"] = _node_id
        span_id = tracing.current_span_id()
        if span_id is not None:
            doc["span"] = span_id
        if rec.exc_info:
            doc["exc"] = self.formatException(rec.exc_info)
        return json.dumps(doc)


_JSON_FORMATTER = JsonFormatter()


def _configure() -> None:
    global _configured
    if _configured:
        return
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(_JSON_FORMATTER if _format == "json"
                         else _TEXT_FORMATTER)
    root = logging.getLogger("stellar")
    root.addHandler(handler)
    # flight-recorder bridge: WARNING+ records become flight events
    # (lazy import — eventlog imports PARTITIONS from this module)
    from . import eventlog
    root.addHandler(eventlog.bridge_handler())
    root.setLevel(logging.INFO)
    _configured = True


def get(partition: str) -> logging.Logger:
    if partition not in PARTITIONS:
        raise ValueError(f"unknown log partition {partition!r}")
    _configure()
    if partition not in _loggers:
        _loggers[partition] = logging.getLogger(f"stellar.{partition}")
    return _loggers[partition]


def set_level(level: str, partition: str | None = None) -> None:
    """Runtime level control (reference: /ll?level=&partition= endpoint)."""
    _configure()
    lvl = getattr(logging, level.upper())
    if partition is None:
        logging.getLogger("stellar").setLevel(lvl)
    else:
        get(partition).setLevel(lvl)


def set_format(fmt: str) -> None:
    """Switch structured output on ("json") or off ("text") at runtime
    (reference semantics: the spdlog pattern swap behind /ll).  Applies to
    every current stream/file handler of the stellar root."""
    global _format
    if fmt not in LOG_FORMATS:
        raise ValueError(f"unknown log format {fmt!r} (expected one of "
                         f"{LOG_FORMATS})")
    _configure()
    _format = fmt
    formatter = _JSON_FORMATTER if fmt == "json" else _TEXT_FORMATTER
    for h in logging.getLogger("stellar").handlers:
        if isinstance(h, logging.StreamHandler):
            h.setFormatter(formatter)


def current_format() -> str:
    return _format


def current_levels() -> dict:
    """Effective level per partition (reference: /ll with no args)."""
    _configure()
    out = {"(root)": logging.getLevelName(
        logging.getLogger("stellar").getEffectiveLevel())}
    for p in PARTITIONS:
        out[p] = logging.getLevelName(get(p).getEffectiveLevel())
    return out


def rotate() -> None:
    """Close+reopen file handlers (reference: /logrotate).  Stream handlers
    have nothing to rotate; file handlers re-open their path so an external
    rotator can move the old file first."""
    _configure()
    for h in logging.getLogger("stellar").handlers:
        if isinstance(h, logging.FileHandler):
            h.close()
            h.stream = h._open()


# ---------------------------------------------------------------------------
# rate limiting: first + every-Nth at the loud level, the rest quiet
# ---------------------------------------------------------------------------

_rate_counts: Dict[str, int] = {}


def rate_limited(log: logging.Logger, key: str, every_n: int):
    """Pick the emit function for one occurrence of a repeating warning:
    the FIRST occurrence and every ``every_n``-th emit at WARNING, the
    rest at DEBUG — the interesting signal is the first hit plus the
    trend, which a counter metric carries exactly either way.  Returns
    ``(emit, occurrence)`` where ``emit`` is ``log.warning`` or
    ``log.debug`` and ``occurrence`` the 1-based count for ``key``.

    Replaces hand-rolled every-Nth counters at call sites (the catchup
    preverify collect-fallback warning was the first).  Keys are scoped
    by the configured node id so in-process multi-node simulations don't
    share one occurrence counter across nodes."""
    if _node_id is not None:
        key = f"{_node_id}:{key}"
    n = _rate_counts.get(key, 0) + 1
    _rate_counts[key] = n
    emit = log.warning if n == 1 or n % every_n == 0 else log.debug
    return emit, n


def discard_rate_limit(key: str) -> None:
    """Drop one key's counter — call when the subsystem that owned the
    key is torn down, so per-instance keys don't accumulate for process
    lifetime."""
    if _node_id is not None:
        key = f"{_node_id}:{key}"
    _rate_counts.pop(key, None)


def reset_rate_limits() -> None:
    """Test seam: forget all rate-limit counters."""
    _rate_counts.clear()
