"""Partitioned logging. Reference: src/util/Logging.{h,cpp} — CLOG_* macros
with per-partition runtime-settable levels (Fs, SCP, Bucket, Overlay, History,
Ledger, Herder, Tx, Database, Process, Work, Invariant, Perf)."""

from __future__ import annotations

import logging
import sys
from typing import Dict

PARTITIONS = (
    "Fs", "SCP", "Bucket", "Overlay", "History", "Ledger", "Herder", "Tx",
    "Database", "Process", "Work", "Invariant", "Perf", "Main",
    "CommandHandler", "Fuzz",
)

_loggers: Dict[str, logging.Logger] = {}
_configured = False


def _configure() -> None:
    global _configured
    if _configured:
        return
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(logging.Formatter(
        "%(asctime)s [%(name)s %(levelname)s] %(message)s"))
    root = logging.getLogger("stellar")
    root.addHandler(handler)
    root.setLevel(logging.INFO)
    _configured = True


def get(partition: str) -> logging.Logger:
    if partition not in PARTITIONS:
        raise ValueError(f"unknown log partition {partition!r}")
    _configure()
    if partition not in _loggers:
        _loggers[partition] = logging.getLogger(f"stellar.{partition}")
    return _loggers[partition]


def set_level(level: str, partition: str | None = None) -> None:
    """Runtime level control (reference: /ll?level=&partition= endpoint)."""
    _configure()
    lvl = getattr(logging, level.upper())
    if partition is None:
        logging.getLogger("stellar").setLevel(lvl)
    else:
        get(partition).setLevel(lvl)


def current_levels() -> dict:
    """Effective level per partition (reference: /ll with no args)."""
    _configure()
    out = {"(root)": logging.getLevelName(
        logging.getLogger("stellar").getEffectiveLevel())}
    for p in PARTITIONS:
        out[p] = logging.getLevelName(get(p).getEffectiveLevel())
    return out


def rotate() -> None:
    """Close+reopen file handlers (reference: /logrotate).  Stream handlers
    have nothing to rotate; file handlers re-open their path so an external
    rotator can move the old file first."""
    _configure()
    for h in logging.getLogger("stellar").handlers:
        if isinstance(h, logging.FileHandler):
            h.close()
            h.stream = h._open()
