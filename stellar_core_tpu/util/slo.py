"""Declarative SLOs with burn-rate tracking.

Reference shape: SRE burn-rate alerting (error-budget consumption over a
trailing window) applied to the node's own /metrics surface.  A raw
point threshold ("close p99 < 2 s at the end of the soak") converts a
single bad window into a campaign failure and a slowly-degrading node
into a pass; a *burn budget* ("at most 10% of evaluation windows may
breach") is what the fleet and chaos soaks actually mean.

An ``Objective`` names one metric field and a threshold; ``SLOTracker``
evaluates a set of objectives against registry snapshots on a cadence
(the Application's local timer, or util/fleettrace.FleetScraper for the
fleet-wide view), remembers a bounded window of verdicts per objective,
and derives ``burn_rate = breaches / evaluations`` over that window.
Crossing the budget in either direction flips a ``burning`` latch and
records a flight event (util/eventlog) — so the moment an SLO started
burning is in every crash bundle — plus ``slo.burn.flips`` /
``slo.objective.<name>`` metrics for the scraper curves.

The /slo admin endpoint serves ``SLOTracker.report()``.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .clock import monotonic_now
from .lockorder import make_lock
from .metrics import registry as _registry

# Evaluations remembered per objective: at the default 1 s fleet scrape
# cadence this is a 2-minute trailing window.
DEFAULT_WINDOW = 120


@dataclass(frozen=True)
class Objective:
    """One service-level objective over a single metric field.

    ``comparison`` is the HEALTHY direction: "<=" means values at or
    under ``threshold`` meet the objective (latencies); ">=" means
    values at or over it do (rates/throughput).  ``budget`` is the
    allowed breach *fraction* of the trailing evaluation window."""
    name: str               # kebab-case; becomes slo.objective.<name>
    metric: str             # registry name, e.g. "ledger.ledger.close"
    field: str              # snapshot field, e.g. "p99_s"
    threshold: float
    comparison: str = "<="  # "<=" or ">="
    budget: float = 0.10
    window: int = DEFAULT_WINDOW

    def met(self, value: float) -> bool:
        if self.comparison == "<=":
            return value <= self.threshold
        if self.comparison == ">=":
            return value >= self.threshold
        raise ValueError(f"unknown comparison {self.comparison!r}")


class _ObjectiveState:
    __slots__ = ("verdicts", "values", "burning", "last_value")

    def __init__(self, window: int):
        # verdicts: deque of (mono_s, breached) — the burn window
        self.verdicts: deque = deque(maxlen=window)
        self.values: deque = deque(maxlen=window)
        self.burning = False
        self.last_value: Optional[float] = None


class SLOTracker:
    """Evaluates objectives against metric snapshots and tracks per-
    objective burn rates.  Thread-safe: the fleet scraper thread and an
    admin /slo read may interleave."""

    def __init__(self, objectives: List[Objective],
                 source: str = "local"):
        self.objectives = list(objectives)
        self.source = source
        self._states: Dict[str, _ObjectiveState] = {
            o.name: _ObjectiveState(o.window) for o in self.objectives}
        self._lock = make_lock("slo.tracker")
        # optional leading indicator: a zero-arg callable returning the
        # currently-active anomaly series names (util/anomaly) — anomalies
        # flag departures from the node's OWN baseline, usually before an
        # absolute SLO threshold is crossed
        self._anomaly_source = None
        reg = _registry()
        reg.counter("slo.eval.windows")
        reg.counter("slo.burn.flips")
        for o in self.objectives:
            # weak source: a torn-down tracker reads as null, never pins
            reg.weak_gauge(f"slo.objective.{o.name}", self,
                           _burn_gauge_source(o.name))

    def attach_anomaly_source(self, fn) -> None:
        """Wire an anomaly reader (e.g. AnomalyDetector.active) as a
        leading indicator: report() surfaces the active series so a /slo
        read shows WHY budget is about to burn, not just that it did."""
        with self._lock:
            self._anomaly_source = fn

    # -- evaluation ---------------------------------------------------------
    def evaluate(self, snapshot: Optional[Dict[str, dict]] = None,
                 now: Optional[float] = None) -> dict:
        """Evaluate every objective against ``snapshot`` (defaulting to
        the process registry).  An objective whose metric/field is
        absent or None is SKIPPED (no verdict recorded) — a node that
        never did catchup must not count as breaching a catchup SLO.
        Returns {objective: burning} for the objectives evaluated."""
        if snapshot is None:
            snapshot = _registry().snapshot()
        if now is None:
            now = monotonic_now()
        _registry().counter("slo.eval.windows").inc()
        flips: List[tuple] = []
        out: Dict[str, bool] = {}
        with self._lock:
            for o in self.objectives:
                snap = snapshot.get(o.metric)
                if snap is None:
                    continue
                value = snap.get(o.field)
                if value is None:
                    continue
                st = self._states[o.name]
                breached = not o.met(float(value))
                st.verdicts.append((now, breached))
                st.values.append((now, float(value)))
                st.last_value = float(value)
                rate = self._burn_rate_locked(o.name)
                burning = rate > o.budget
                if burning != st.burning:
                    st.burning = burning
                    flips.append((o, rate, burning))
                out[o.name] = burning
        # flight events OUTSIDE the tracker lock: record() takes the
        # eventlog leaf lock and we must not nest ours above it
        for o, rate, burning in flips:
            _registry().counter("slo.burn.flips").inc()
            from . import eventlog
            eventlog.record(
                "Perf", "WARNING" if burning else "INFO",
                "slo burn started" if burning else "slo burn cleared",
                objective=o.name, burn_rate=round(rate, 4),
                budget=o.budget, threshold=o.threshold,
                source=self.source)
        return out

    def _burn_rate_locked(self, name: str) -> float:
        st = self._states[name]
        if not st.verdicts:
            return 0.0
        breaches = sum(1 for _, b in st.verdicts if b)
        return breaches / len(st.verdicts)

    # -- readers ------------------------------------------------------------
    def burn_rate(self, name: str) -> float:
        with self._lock:
            return self._burn_rate_locked(name)

    def burning(self, name: str) -> bool:
        with self._lock:
            return self._states[name].burning

    def within_budget(self) -> bool:
        """True when NO objective currently burns its budget — what a
        soak asserts instead of raw end-of-run point thresholds."""
        with self._lock:
            return not any(st.burning for st in self._states.values())

    def report(self) -> dict:
        """The /slo document: per-objective verdict history summary and
        value curve (bounded by the objective window)."""
        objectives = {}
        with self._lock:
            for o in self.objectives:
                st = self._states[o.name]
                breaches = sum(1 for _, b in st.verdicts if b)
                objectives[o.name] = {
                    "metric": o.metric, "field": o.field,
                    "threshold": o.threshold,
                    "comparison": o.comparison,
                    "budget": o.budget,
                    "evaluations": len(st.verdicts),
                    "breaches": breaches,
                    "burn_rate": round(
                        breaches / len(st.verdicts), 4)
                    if st.verdicts else 0.0,
                    "burning": st.burning,
                    "last_value": st.last_value,
                    "curve": [[round(t, 3), v]
                              for t, v in st.values],
                }
            ok = not any(st.burning for st in self._states.values())
            anomaly_source = self._anomaly_source
        doc = {"source": self.source, "ok": ok,
               "objectives": objectives}
        if anomaly_source is not None:
            # read OUTSIDE our lock: the detector takes its own lock and
            # must stay a leaf relative to slo.tracker
            try:
                doc["anomalies"] = list(anomaly_source())
            except Exception:  # corelint: disable=exception-hygiene -- a torn-down detector must not break /slo
                doc["anomalies"] = []
        return doc


def _burn_gauge_source(name: str):
    def read(tracker: "SLOTracker") -> float:
        return tracker.burn_rate(name)  # raises on None → gauge null
    return read


def default_objectives(close_p99_s: float = 2.0,
                       admission_p99_s: float = 0.5,
                       catchup_rate: float = 20.0,
                       budget: float = 0.10,
                       window: int = DEFAULT_WINDOW) -> List[Objective]:
    """The node's standing objectives: close latency, admission intake
    latency, and catchup throughput (evaluated only while the metrics
    exist — an in-sync node records no catchup rate)."""
    return [
        Objective("close-p99", "ledger.ledger.close", "p99_s",
                  close_p99_s, "<=", budget, window),
        Objective("admission-p99", "herder.admission.latency", "p99_s",
                  admission_p99_s, "<=", budget, window),
        Objective("catchup-rate", "catchup.parallel.range-rate", "p50",
                  catchup_rate, ">=", budget, window),
    ]
