"""Flight recorder: a bounded ring of structured events + post-mortem
crash bundles.

Reference shape: the reference keeps per-category status lines
(src/main/StatusManager) and an unstructured log stream; when a node
fail-stops, the only artifacts are whatever stderr captured.  This module
answers "what was the node doing in the 30 seconds before it died": a
bounded, lock-ordered ring of structured events (monotonic + wall time,
log partition, severity, key=value fields, current span id) fed by

- explicit ``record()`` calls at lifecycle edges (ledger close seal, SCP
  phase transitions, catchup checkpoint verdicts, bucket merge adopt/GC,
  overlay connect/drop/ban, invariant failures), and
- a logging bridge: every WARNING+ record emitted through the partitioned
  logger (util/logging) lands here automatically.  Records below the
  bridge level cost nothing — stdlib logging filters them before the
  handler runs.

On a fail-stop (LockOrderError, InvariantDoesNotHold, unhandled thread
exception) ``write_crash_bundle()`` dumps ONE JSON bundle — recent flight
events, the active span stack (util/tracing), a full metric snapshot and
any registered bundle sources (herder/SCP state, config fingerprint) —
to ``$STPU_CRASH_DIR``.  The same bundle is served live at the
``/dumpflight`` admin endpoint.

Lock order: the event-log lock is a LEAF — ``record()`` acquires nothing
else while holding it, so it can be called from inside any subsystem's
critical section (including the logging bridge firing under another
lock) without creating new lock-order edges.
"""

from __future__ import annotations

import json
import logging as _pylogging
import os
import threading
from collections import deque
from typing import Callable, Dict, List, Optional

from .clock import monotonic_now, wall_now
from .lockorder import make_lock
from .metrics import registry as _registry
from .racetrace import race_checked
from . import tracing as _tracing

# Ring capacity: ~30s of a busy node (a replay close records one event
# per ledger; live nodes far fewer).  Bounded in count, not time.
EVENTLOG_CAPACITY = int(os.environ.get("STPU_EVENTLOG_CAPACITY", "1024"))


class FlightEvent:
    __slots__ = ("mono_s", "wall_s", "partition", "severity", "msg",
                 "fields", "span_id")

    def __init__(self, partition: str, severity: str, msg: str,
                 fields: Optional[Dict], span_id: Optional[str]):
        self.mono_s = monotonic_now()
        self.wall_s = wall_now()
        self.partition = partition
        self.severity = severity
        self.msg = msg
        self.fields = fields
        self.span_id = span_id

    def to_dict(self) -> dict:
        out = {"mono_s": round(self.mono_s, 6),
               "wall_s": round(self.wall_s, 3),
               "partition": self.partition,
               "severity": self.severity,
               "msg": self.msg}
        # node attribution happens at EXPORT time (zero hot-path cost):
        # the record path stays on its <2 µs budget and a late
        # set_node_id() still stamps earlier events correctly for the
        # common fleet case (id configured once at startup)
        node = _node_id()
        if node is not None:
            out["node"] = node
        if self.fields:
            out["fields"] = _tracing.jsonable_args(self.fields)
        if self.span_id is not None:
            out["span_id"] = self.span_id
        return out


@race_checked
class EventLog:
    """Bounded ring of FlightEvents (newest kept).  Fed from every
    thread (main crank, admin workers via the log bridge, device worker
    fail paths) and drained by /dumpflight — the canonical race-sanitizer
    subject, which is why every access below is under ``_lock``."""

    def __init__(self, capacity: int = EVENTLOG_CAPACITY):
        self._events: deque = deque(maxlen=capacity)
        self._lock = make_lock("eventlog.buffer")

    def record(self, partition: str, severity: str, msg: str,
               fields: Optional[Dict] = None) -> FlightEvent:
        ev = FlightEvent(partition, severity, msg, fields or None,
                         _tracing.current_span_id())
        with self._lock:
            self._events.append(ev)
        return ev

    def events(self) -> List[FlightEvent]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def snapshot(self) -> List[dict]:
        return [ev.to_dict() for ev in self.events()]


_log = EventLog()
_partitions: Optional[frozenset] = None


def event_log() -> EventLog:
    """The process-wide flight recorder."""
    return _log


def _known_partitions() -> frozenset:
    # lazy: util/logging attaches the bridge from its _configure(), so a
    # top-level import here would be circular
    global _partitions
    if _partitions is None:
        from .logging import PARTITIONS
        _partitions = frozenset(PARTITIONS)
    return _partitions


def _node_id():
    # same circular-import constraint as _known_partitions
    from .logging import node_id
    return node_id()


# counter cached per registry INSTANCE: reset_registry() (tests) swaps
# the whole registry, so a bare cached counter would go stale — the
# identity check re-resolves it after a swap at one `is` per record
_counter_box: list = [None, None]


def record(partition: str, severity: str, msg: str, **fields) -> None:
    """Record one structured flight event.  ``partition`` must be a
    util/logging partition (corelint's eventlog-partitions rule checks
    literals statically; this is the runtime backstop for dynamic
    callers).  Hot-path budget: <2 µs/record (PROFILE.md) — record() sits
    inside every replay close."""
    if partition not in _known_partitions():
        raise ValueError(f"unknown log partition {partition!r}")
    reg = _registry()
    if _counter_box[0] is not reg:
        _counter_box[0] = reg
        _counter_box[1] = reg.counter("eventlog.record.count")
    _counter_box[1].inc()
    if not severity.isupper():
        severity = severity.upper()
    _log.record(partition, severity, msg, fields)


# ---------------------------------------------------------------------------
# logging bridge: WARNING+ partitioned-log records land in the recorder
# ---------------------------------------------------------------------------

class FlightRecorderBridge(_pylogging.Handler):
    """Attached to the ``stellar`` root logger (util/logging._configure)
    at WARNING: a record below that level never reaches emit() — the
    zero-cost-when-not-met guarantee is stdlib logging's level check."""

    def __init__(self, level: int = _pylogging.WARNING):
        super().__init__(level)

    def emit(self, rec: _pylogging.LogRecord) -> None:
        try:
            name = rec.name
            partition = name.rsplit(".", 1)[-1] if "." in name else "Main"
            _registry().counter("log.bridge.records").inc()
            _log.record(partition, rec.levelname, rec.getMessage())
        except Exception:  # corelint: disable=exception-hygiene -- a logging handler must never raise into callers
            pass


def bridge_handler() -> FlightRecorderBridge:
    return FlightRecorderBridge()


# ---------------------------------------------------------------------------
# post-mortem bundles
# ---------------------------------------------------------------------------

# name -> zero-arg callable returning a JSON-compatible dict; registered
# by the Application (herder/SCP state, config fingerprint).  A source
# that raises reports its error instead of sinking the whole bundle.
_bundle_sources: Dict[str, Callable[[], dict]] = {}
_bundle_lock = make_lock("eventlog.bundle-sources")
# re-entrancy latch: a fail-stop inside bundle writing (e.g. a metric
# lock inverting while we snapshot) must not recurse forever
_dumping = threading.local()


def register_bundle_source(name: str, fn: Callable[[], dict]) -> None:
    with _bundle_lock:
        _bundle_sources[name] = fn


def unregister_bundle_source(name: str) -> None:
    with _bundle_lock:
        _bundle_sources.pop(name, None)


def flight_bundle(reason: str) -> dict:
    """The post-mortem document: recent flight events, the active span
    stack of the calling thread, a full metric snapshot, and every
    registered bundle source."""
    from . import tracing
    bundle = {
        "reason": reason,
        "node": _node_id(),
        "wall_s": round(wall_now(), 3),
        "mono_s": round(monotonic_now(), 6),
        "thread": threading.current_thread().name,
        "events": _log.snapshot(),
        "span_stack": tracing.active_span_stack(),
        "metrics": _registry().snapshot(),
    }
    with _bundle_lock:
        sources = dict(_bundle_sources)
    for name, fn in sources.items():
        try:
            bundle[name] = fn()
        except Exception as e:  # corelint: disable=exception-hygiene -- a dead source reports its error, never sinks the bundle
            bundle[name] = {"error": str(e)}
    return bundle


def write_crash_bundle(reason: str,
                       crash_dir: Optional[str] = None) -> Optional[str]:
    """Write the flight bundle to ``crash_dir`` (defaulting to
    ``$STPU_CRASH_DIR``; one JSON file per incident); returns the path, or
    None when no directory is configured or the write fails — a crash dump
    must never mask the original fail-stop.  The explicit parameter lets
    in-process harnesses (the chaos campaign runner) route bundles into a
    per-campaign artifact directory without mutating process environment."""
    if getattr(_dumping, "active", False):
        return None
    if crash_dir is None:
        crash_dir = os.environ.get("STPU_CRASH_DIR")
    if not crash_dir:
        return None
    _dumping.active = True
    try:
        bundle = flight_bundle(reason)
        os.makedirs(crash_dir, exist_ok=True)
        path = os.path.join(
            crash_dir,
            f"flight-{int(wall_now() * 1000)}-{os.getpid()}.json")
        with open(path, "w") as f:
            json.dump(bundle, f, indent=1, default=str)
        return path
    except Exception as e:  # corelint: disable=exception-hygiene -- dump failure must not mask the fail-stop being reported
        try:
            from . import logging as slog
            slog.get("Main").error("crash bundle write failed: %s", e)
        except Exception:  # corelint: disable=exception-hygiene -- last-resort: nothing left to report to
            pass
        return None
    finally:
        _dumping.active = False


_prev_threading_excepthook = None


def install_thread_excepthook() -> None:
    """Route unhandled thread exceptions through a crash bundle before
    the default report (reference shape: printErrorAndAbort).  Idempotent."""
    global _prev_threading_excepthook
    if _prev_threading_excepthook is not None:
        return
    prev = threading.excepthook
    _prev_threading_excepthook = prev

    def hook(args) -> None:
        try:
            record("Process", "ERROR",
                   "unhandled exception in thread",
                   thread=args.thread.name if args.thread else "?",
                   exc_type=getattr(args.exc_type, "__name__",
                                    str(args.exc_type)),
                   exc=str(args.exc_value))
            write_crash_bundle(
                f"unhandled thread exception: "
                f"{getattr(args.exc_type, '__name__', args.exc_type)}: "
                f"{args.exc_value}")
        except Exception:  # corelint: disable=exception-hygiene -- excepthook must always reach the default reporter
            pass
        prev(args)

    threading.excepthook = hook
