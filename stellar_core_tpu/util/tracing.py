"""Hierarchical span tracing with Chrome trace-event export.

Reference shape: the reference's LogSlowExecution + medida timers only
aggregate; this module keeps the *structure* of recent hot operations —
a ledger close is `ledger.close` > `ledger.tx-apply` > one `tx.apply` per
transaction; a catchup crank is `catchup.apply-checkpoint` above all of
that — so an operator can open one slow close in `chrome://tracing` (or
`ui.perfetto.dev`) instead of inferring shape from percentiles.

Design:
- `span("name", key=value)` is a context manager; the current span is
  context-local (contextvars), so nesting is automatic and thread/async
  safe — each thread traces its own tree.
- finished ROOT spans land in a bounded ring buffer (newest wins); child
  spans attach to their parent and cost two perf_counter calls + one
  object.
- `to_chrome_trace()` renders the buffer as Chrome trace-event JSON
  (`{"traceEvents": [...]}`, "X" complete events, microsecond units);
  `dump_trace(path)` writes it to a file; the `/trace` admin endpoint
  serves it over HTTP.

Tracing is always on: the buffer is bounded in ALL dimensions —
TRACE_BUFFER_SPANS roots, MAX_CHILD_SPANS children per span, and
MAX_TREE_SPANS total spans per root tree (the elided tail is counted in
each span's `truncated_children` arg) — and span overhead is far below
the operations instrumented (ledger close, checkpoint download, bucket
merge).
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import json
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from .clock import wall_now
from .lockorder import make_lock

TRACE_BUFFER_SPANS = 64
# Per-parent child cap: a replay crank can hold thousands of tx.apply
# leaves per ledger; beyond this the tail is elided (the span records how
# many were dropped).  256 leaves is more than chrome://tracing is
# readable at anyway.
MAX_CHILD_SPANS = 256
# Total-span budget per root tree: the per-parent cap alone is
# multiplicative (64 ledgers x 256 leaves each), so a whole tree is also
# budgeted — once exhausted, further spans are elided and counted in
# their parent's truncated tally.  Worst case the ring then pins
# TRACE_BUFFER_SPANS * MAX_TREE_SPANS spans (~a few MB), a real bound.
MAX_TREE_SPANS = 2048

_current: contextvars.ContextVar[Optional["Span"]] = \
    contextvars.ContextVar("stpu_current_span", default=None)
# span count of the current root tree ([n] so children mutate in place)
_tree_count: contextvars.ContextVar[Optional[list]] = \
    contextvars.ContextVar("stpu_tree_count", default=None)

# one wall-clock anchor so ts values in an export share an epoch
_EPOCH_WALL = wall_now()
_EPOCH_PERF = time.perf_counter()

# shared export sequence over phase marks AND finished root spans: an
# incremental consumer (/tracespans?since=) names one watermark and gets
# exactly the new data of both kinds (GIL-atomic counter)
_EXPORT_SEQ = itertools.count(1)

# Phase-mark ring capacity: a 5-node soak emits ~6 marks/slot/node; 4096
# covers hundreds of slots between collector scrapes.
MARK_BUFFER_MARKS = 4096


def clock_anchor() -> dict:
    """A fresh monotonic↔wall pairing for this process: perf_counter and
    wall clock sampled back-to-back.  A cross-node collector uses the
    pair to map each node's perf-epoch timestamps onto one wall timebase
    (util/fleettrace aligns residual wall skew via matched slot marks)."""
    return {"perf_s": time.perf_counter(), "wall_s": wall_now()}

# process-unique span ids (GIL-atomic counter).  The id is what a
# structured log line carries (util/logging LOG_FORMAT=json) so a slow
# span can be joined against every record it emitted.
_SPAN_IDS = itertools.count(1)


class Span:
    __slots__ = ("name", "start_s", "dur_s", "args", "children", "tid",
                 "truncated", "span_id", "parent", "export_seq")

    def __init__(self, name: str, args: Optional[Dict] = None,
                 parent: Optional["Span"] = None):
        self.name = name
        self.start_s = time.perf_counter()
        self.dur_s: Optional[float] = None
        self.args = args or None
        self.children: List["Span"] = []
        self.tid = threading.get_ident()
        self.truncated = 0  # children elided past MAX_CHILD_SPANS
        self.span_id = f"{next(_SPAN_IDS):x}"
        self.parent = parent
        self.export_seq: Optional[int] = None  # set when a root is recorded

    def finish(self) -> None:
        self.dur_s = time.perf_counter() - self.start_s

    def depth(self) -> int:
        """Nesting levels including self (a leaf is 1)."""
        return 1 + max((c.depth() for c in self.children), default=0)

    def to_dict(self) -> dict:
        return {"name": self.name, "start_s": self.start_s,
                "dur_s": self.dur_s, "args": self.args,
                "children": [c.to_dict() for c in self.children]}


class TraceBuffer:
    """Bounded ring of finished root spans (newest kept)."""

    def __init__(self, maxlen: int = TRACE_BUFFER_SPANS):
        self._roots: deque = deque(maxlen=maxlen)
        self._lock = make_lock("tracing.buffer")

    def record(self, root: Span) -> None:
        root.export_seq = next(_EXPORT_SEQ)
        with self._lock:
            self._roots.append(root)

    def roots(self) -> List[Span]:
        with self._lock:
            return list(self._roots)

    def clear(self) -> None:
        with self._lock:
            self._roots.clear()


_buffer = TraceBuffer()


def trace_buffer() -> TraceBuffer:
    return _buffer


# ---------------------------------------------------------------------------
# slot-keyed phase marks: the cross-node lifecycle skeleton
# ---------------------------------------------------------------------------

class PhaseMark:
    """One point on a slot's lifecycle: admission-flush, tx-flood,
    nominate, externalize, close-seal, checkpoint-publish.  Cheap (one
    object + two clock reads), node-attributed at record time so an
    in-process multi-node simulation can still split marks per node."""
    __slots__ = ("seq", "phase", "slot", "perf_s", "wall_s", "node",
                 "tid", "args")

    def __init__(self, phase: str, slot: int, node: Optional[str],
                 args: Optional[Dict]):
        self.seq = next(_EXPORT_SEQ)
        self.phase = phase
        self.slot = slot
        self.perf_s = time.perf_counter()
        self.wall_s = wall_now()
        self.node = node
        self.tid = threading.get_ident()
        self.args = args or None

    def to_dict(self) -> dict:
        out = {"seq": self.seq, "phase": self.phase, "slot": self.slot,
               "perf_s": self.perf_s, "wall_s": round(self.wall_s, 6)}
        if self.node is not None:
            out["node"] = self.node
        if self.args:
            out["args"] = jsonable_args(self.args)
        return out


class MarkBuffer:
    """Bounded ring of PhaseMarks (newest kept)."""

    def __init__(self, maxlen: int = MARK_BUFFER_MARKS):
        self._marks: deque = deque(maxlen=maxlen)
        self._lock = make_lock("tracing.marks")

    def record(self, mark: PhaseMark) -> None:
        with self._lock:
            self._marks.append(mark)

    def marks(self) -> List[PhaseMark]:
        with self._lock:
            return list(self._marks)

    def clear(self) -> None:
        with self._lock:
            self._marks.clear()


_marks = MarkBuffer()

# counter cached per registry INSTANCE (same pattern as eventlog.record):
# reset_registry() in tests swaps the registry, so the identity check
# re-resolves the cached counter at one `is` per mark
_mark_counter_box: list = [None, None]


def mark_buffer() -> MarkBuffer:
    return _marks


def mark_phase(phase: str, slot: int, node: Optional[str] = None,
               **args) -> PhaseMark:
    """Record a slot-keyed lifecycle mark.  ``node`` defaults to the
    process node id (util/logging.set_node_id); in-process simulations
    pass it explicitly so one process can attribute marks to many
    nodes."""
    if node is None:
        from . import logging as _slog  # lazy: logging imports tracing
        node = _slog.node_id()
    mark = PhaseMark(phase, slot, node, args or None)
    _marks.record(mark)
    from .metrics import registry as _registry
    reg = _registry()
    if _mark_counter_box[0] is not reg:
        _mark_counter_box[0] = reg
        _mark_counter_box[1] = reg.counter("fleet.trace.marks")
    _mark_counter_box[1].inc()
    return mark


@contextlib.contextmanager
def span(name: str, **args):
    """Open a span under the context-local current span; finished roots
    are recorded in the process trace buffer."""
    parent = _current.get()
    counter = _tree_count.get()
    ctoken = None
    if parent is None or counter is None:
        counter = [1]
        ctoken = _tree_count.set(counter)
    else:
        counter[0] += 1
    s = Span(name, args, parent=parent)
    token = _current.set(s)
    try:
        yield s
    finally:
        s.finish()
        _current.reset(token)
        if parent is not None:
            if len(parent.children) < MAX_CHILD_SPANS \
                    and counter[0] <= MAX_TREE_SPANS:
                parent.children.append(s)
            else:
                parent.truncated += 1
        else:
            _buffer.record(s)
        if ctoken is not None:
            _tree_count.reset(ctoken)


def current_span() -> Optional[Span]:
    return _current.get()


def jsonable_args(args: Optional[Dict]) -> Optional[Dict]:
    """Span/event key=value fields coerced to JSON-clean scalars (the one
    serialization rule shared by Chrome trace export, span stacks and
    flight-event bundles): scalars pass through, everything else
    stringifies."""
    if not args:
        return None
    return {k: (v if isinstance(v, (int, float, str, bool, type(None)))
                else str(v))
            for k, v in args.items()}


def current_span_id() -> Optional[str]:
    """Id of the innermost open span in this thread/context, or None —
    the correlation key structured log records carry."""
    s = _current.get()
    return s.span_id if s is not None else None


def active_span_stack() -> List[dict]:
    """The open span chain of the current context, innermost first —
    what a post-mortem bundle captures as "what was this thread doing".
    Each entry: name, span_id, elapsed_s so far, and the span args."""
    out: List[dict] = []
    s = _current.get()
    now = time.perf_counter()
    while s is not None:
        out.append({"name": s.name, "span_id": s.span_id,
                    "elapsed_s": round(now - s.start_s, 6),
                    "args": jsonable_args(s.args)})
        s = s.parent
    return out


def annotate(**args) -> None:
    """Attach key=value data to the current span (no-op outside one)."""
    s = _current.get()
    if s is not None:
        if s.args is None:
            s.args = {}
        s.args.update(args)


# ---------------------------------------------------------------------------
# Chrome trace-event export
# ---------------------------------------------------------------------------

def _emit(events: List[dict], s: Span, pid: int) -> None:
    ts_us = (_EPOCH_WALL + (s.start_s - _EPOCH_PERF)) * 1e6
    ev = {
        "name": s.name,
        "ph": "X",
        "ts": round(ts_us, 3),
        "dur": round((s.dur_s or 0.0) * 1e6, 3),
        "pid": pid,
        "tid": s.tid,
        "cat": s.name.split(".", 1)[0],
    }
    if s.args:
        # values must be JSON-serializable; coerce the rest to str
        ev["args"] = jsonable_args(s.args)
    if s.truncated:
        ev.setdefault("args", {})["truncated_children"] = s.truncated
    events.append(ev)
    for c in s.children:
        _emit(events, c, pid)


_SLOT_ARG_KEYS = ("slot", "seq", "ledger", "checkpoint")


def _tree_mentions_slot(s: Span, slot: int) -> bool:
    if s.args:
        for k in _SLOT_ARG_KEYS:
            if s.args.get(k) == slot:
                return True
    return any(_tree_mentions_slot(c, slot) for c in s.children)


def to_chrome_trace(roots: Optional[List[Span]] = None,
                    pid: int = 1,
                    slot: Optional[int] = None) -> dict:
    """The trace buffer (or explicit roots) as a Chrome trace-event JSON
    document — load it in chrome://tracing or ui.perfetto.dev.  With
    ``slot``, only root trees mentioning that slot/seq in any span's args
    are emitted (the /trace?slot=N view of one ledger's close)."""
    events: List[dict] = []
    for root in (roots if roots is not None else _buffer.roots()):
        if slot is not None and not _tree_mentions_slot(root, slot):
            continue
        _emit(events, root, pid)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def mark_chrome_events(marks: List[PhaseMark], pid: int = 1,
                       wall_offset_s: float = 0.0,
                       anchor: Optional[dict] = None) -> List[dict]:
    """Phase marks as Chrome instant events ("i", thread scope).  When
    ``anchor`` (a clock_anchor() dict from the emitting process) is
    given, each mark's perf timestamp is mapped through it onto the wall
    timebase; otherwise the process-local epoch applies.
    ``wall_offset_s`` shifts the result (fleettrace skew correction)."""
    events: List[dict] = []
    for m in marks:
        if anchor is not None:
            wall = anchor["wall_s"] + (m.perf_s - anchor["perf_s"])
        else:
            wall = _EPOCH_WALL + (m.perf_s - _EPOCH_PERF)
        ev = {"name": f"{m.phase}@{m.slot}",
              "ph": "i", "s": "t",
              "ts": round((wall + wall_offset_s) * 1e6, 3),
              "pid": pid, "tid": m.tid,
              "cat": "mark",
              "args": {"slot": m.slot, "phase": m.phase}}
        if m.node is not None:
            ev["args"]["node"] = m.node
        if m.args:
            ev["args"].update(jsonable_args(m.args))
        events.append(ev)
    return events


def tracespans_doc(since: int = 0,
                   slot: Optional[int] = None) -> dict:
    """The /tracespans?since=N incremental export: everything recorded
    after watermark ``since`` — phase marks (raw dicts, perf+wall
    stamped) and finished root spans (Chrome events) — plus a FRESH
    clock anchor and the node id, so a cross-node collector can align
    this process onto a shared timebase.  ``next_since`` is the new
    watermark to pass on the next poll."""
    from . import logging as _slog  # lazy: logging imports tracing
    marks = [m for m in _marks.marks() if m.seq > since
             and (slot is None or m.slot == slot)]
    roots = [r for r in _buffer.roots()
             if r.export_seq is not None and r.export_seq > since]
    span_events: List[dict] = []
    for root in roots:
        if slot is not None and not _tree_mentions_slot(root, slot):
            continue
        _emit(span_events, root, pid=1)
    next_since = max(
        [since] + [m.seq for m in marks]
        + [r.export_seq for r in roots])
    return {"node": _slog.node_id(),
            "anchor": clock_anchor(),
            "epoch": {"wall_s": _EPOCH_WALL, "perf_s": _EPOCH_PERF},
            "marks": [m.to_dict() for m in marks],
            "spans": span_events,
            "next_since": next_since}


def dump_trace(path: str, roots: Optional[List[Span]] = None) -> int:
    """Write the Chrome trace JSON to `path`; returns the event count."""
    doc = to_chrome_trace(roots)
    with open(path, "w") as f:
        json.dump(doc, f)
    return len(doc["traceEvents"])
