"""Metrics registry: counters, meters, gauges, histograms and timers,
medida-style.

Reference: lib/libmedida as used throughout the reference
(`app.getMetrics().NewTimer({"ledger", "ledger", "close"})`, CommandHandler
/metrics endpoint).  Names are dotted strings ("ledger.ledger.close");
`registry().snapshot()` is the /metrics JSON surface and
`render_prometheus()` the `/metrics?format=prometheus` text exposition.

Naming scheme: dotted lowercase `layer.subsystem.event`; segments after the
first may use `-` (`herder.tx-queue.depth`).  Enforced by METRIC_NAME_RE and
the lint test (tests/test_observability.py); every instrumented name must be
in CANONICAL_METRICS or start with a CANONICAL_PREFIXES entry.

Timers/histograms sample through an exponential-decay reservoir (medida's
ExpDecaySample: size 1028, alpha 0.015 ≈ the trailing 5 minutes dominate),
so snapshots report p50/p90/p99 that track recent behavior, not the whole
process lifetime.
"""

from __future__ import annotations

import heapq
import math
import random
import re
import time
import weakref
from typing import Callable, Dict, List, Optional

from .clock import monotonic_now
from .lockorder import make_lock

METRIC_NAME_RE = re.compile(r"^[a-z0-9]+(\.[a-z0-9-]+)+$")

# The documented metric list (README.md §Observability).  The lint test
# walks the live registry after a simulated ledger close + catchup and
# asserts every recorded name is canonical; keep README and this list in
# sync when instrumenting new code.
CANONICAL_METRICS = frozenset({
    # ledger
    "ledger.ledger.close",
    "ledger.transaction.apply",
    "ledger.fee.process",
    # native live close (ledger/native_close.py): closes through the C
    # engine, per-close Python fallbacks/degrades, differential
    # spot-checks run — a silent fallback regression shows here
    "ledger.native.closes",
    "ledger.native.fallbacks",
    "ledger.native.differential-checks",
    # scp / herder
    "scp.envelope.receive",
    "scp.envelope.nominate",
    "scp.envelope.prepare",
    "scp.envelope.confirm",
    "scp.envelope.externalize",
    "scp.slot.externalize",
    "herder.ledger.externalize",
    "herder.tx-queue.depth",
    "herder.tx-queue.banned",
    "herder.scp.envelope-discarded",
    # admission (batched intake verification, herder/admission.py)
    "herder.admission.depth",
    "herder.admission.latency",
    "herder.admission.batch-size",
    "herder.admission.flush",
    "herder.admission.admitted",
    "herder.admission.rejected",
    "herder.admission.overload",
    "herder.admission.sigs-offloaded",
    # overlay
    "overlay.peer.drop",
    "overlay.peer.authenticated",
    "overlay.message.flood",
    "overlay.byte.read",
    "overlay.byte.write",
    "overlay.message.read",
    "overlay.message.write",
    "overlay.flood.duplicate",
    "overlay.flood.grant-deferred",
    # batched authenticated transport (overlay/peer.py): messages carried
    # in BATCHED_AUTH frames, coalesced-run flushes, and batch frame
    # bytes on the wire
    "overlay.batch.messages",
    "overlay.batch.flush",
    "overlay.batch.bytes",
    # catchup / historywork
    "catchup.download.checkpoint",
    "catchup.apply.checkpoint",
    "catchup.apply.ledger",
    "catchup.preverify.dispatch",
    "catchup.preverify.collect-wait",
    "catchup.preverify.sigs-total",
    "catchup.preverify.sigs-shipped",
    "catchup.preverify.fallback",
    # offload-miss watermark split (ISSUE 14): dispatched-but-late vs
    # never-dispatched — the two causes that used to share one counter —
    # plus groups whose verdicts ripened after their first checkpoint
    "catchup.preverify.race-lost",
    "catchup.preverify.not-dispatched",
    "catchup.preverify.late-seeded",
    # native-engine checkpoint outcomes (works.py): applied in C vs
    # probe-rejected to the Python oracle
    "catchup.native.checkpoint",
    "catchup.native.fallback",
    # range-parallel catchup (catchup/parallel.py)
    "catchup.parallel.ranges-inflight",
    "catchup.parallel.range-retry",
    "catchup.parallel.range-rate",
    "catchup.parallel.stitch-verified",
    # checkpoint-granular work stealing (ISSUE 14): accepted steals
    "catchup.parallel.steal",
    # bucket
    "bucket.merge.time",
    "bucket.merge.stream",
    "bucket.merge.bytes",
    # close-blocked-on-merge: time add_batch spent waiting for an
    # unresolved background merge before a spill commit (ISSUE 20
    # read-path contention observability)
    "bucket.merge.stall",
    "bucket.batch.addtime",
    "bucket.rehydrate",
    "bucket.rehydrate.entries",
    "bucket.resident.entries",
    # bucketlistdb (disk-backed ledger-entry reads)
    "bucketlistdb.load",
    "bucketlistdb.prefetch",
    "bucketlistdb.cache.hit",
    "bucketlistdb.cache.miss",
    # read-path contention counters (ISSUE 20): reader-held pin time per
    # snapshot, live pin count, and bulk-read key volume
    "bucketlistdb.pin.held",
    "bucketlistdb.pin.active",
    "bucketlistdb.read.keys",
    # accel
    "accel.ed25519.batch-size",
    "accel.ed25519.table-sigs",
    "accel.ed25519.generic-sigs",
    "accel.ed25519.rejected-prep",
    "accel.ed25519.tables-built",
    "accel.quorum.checks",
    "accel.quorum.nodes",
    "accel.quorum.frontier-peak",
    "accel.quorum.quorum-hits",
    # crypto
    "crypto.verify.cache-hit",
    "crypto.verify.recompute",
    # incident observability (flight recorder / health)
    "node.health",
    "eventlog.record.count",
    "log.bridge.records",
    # fleet observability plane (ISSUE 16): slot phase marks + the
    # cross-node collector/scraper (util/tracing, util/fleettrace)
    "fleet.trace.marks",
    "fleet.trace.merge",
    "fleet.scrape.polls",
    "fleet.scrape.errors",
    # retention bound (ISSUE 20): nodes absent beyond the scraper's
    # retention window get their history evicted
    "fleet.scrape.evicted",
    # always-on sampling profiler (util/sampleprof)
    "profile.sampler.samples",
    "profile.sampler.dropped",
    "profile.sampler.running",
    # SLO burn tracking (util/slo)
    "slo.eval.windows",
    "slo.burn.flips",
    # Soroban execution subsystem (ISSUE 17): bounded host, TTL
    # archival, footprint-clustered parallel apply
    "soroban.host.invoke",
    "soroban.host.trap",
    "soroban.host.budget-exceeded",
    "soroban.host.cpu-insns",
    "soroban.ttl.extend",
    "soroban.ttl.restore",
    "soroban.ttl.evicted",
    "soroban.apply.clusters",
    "soroban.apply.phase",
    "soroban.transaction.apply",
})

# Prefixes for families whose tail is data-dependent (one meter per overlay
# message type; one probe counter per bucket-list level; one burn-rate
# gauge per declared SLO objective; the retrospective-telemetry plane —
# time-series store, per-close cost ledger, anomaly detector — whose
# gauge tails carry series names).
CANONICAL_PREFIXES = ("overlay.recv.", "bucketlistdb.probe.",
                      "slo.objective.", "timeseries.", "closecost.",
                      "anomaly.")


class Counter:
    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n

    def reset(self) -> None:
        self.value = 0

    def snapshot(self) -> dict:
        return {"type": "counter", "count": self.value}


class Gauge:
    """Callable-backed instantaneous value (reference: medida gauges /
    the CommandHandler's point-in-time fields).  `set_source` replaces the
    callable — last registration wins, which is what multi-node simulations
    want (the registry is process-global)."""
    __slots__ = ("_fn",)

    def __init__(self, fn: Optional[Callable[[], float]] = None) -> None:
        self._fn = fn

    def set_source(self, fn: Callable[[], float]) -> None:
        self._fn = fn

    def value(self) -> Optional[float]:
        """Current value, or None when the source is missing/raises — a
        gauge outliving its subsystem must not break the whole /metrics
        surface (and must not leak NaN into strict-JSON consumers)."""
        if self._fn is None:
            return None
        try:
            return float(self._fn())
        except Exception:  # corelint: disable=exception-hygiene -- dead gauge reads as null, never breaks /metrics
            return None

    def reset(self) -> None:
        pass  # gauges carry no recorded samples

    def snapshot(self) -> dict:
        return {"type": "gauge", "value": self.value()}


class Meter:
    """Event rate: count + events/sec over the process lifetime and a
    recent sliding window (medida meters' 1m rate approximated)."""
    __slots__ = ("count", "_t0", "_win_start", "_win_count", "_last_rate",
                 "_have_window")

    WINDOW = 60.0

    def __init__(self) -> None:
        self.reset()

    def reset(self) -> None:
        self.count = 0
        self._t0 = monotonic_now()
        self._win_start = self._t0
        self._win_count = 0
        self._last_rate = 0.0
        self._have_window = False

    def mark(self, n: int = 1) -> None:
        self.count += n
        self._win_count += n
        now = monotonic_now()
        if now - self._win_start >= self.WINDOW:
            self._last_rate = self._win_count / (now - self._win_start)
            self._win_start = now
            self._win_count = 0
            self._have_window = True

    def _recent_rate(self) -> float:
        """Rate over the trailing window, INCLUDING the in-progress one:
        the old behavior reported 0.0 until a full 60s window elapsed and
        then froze between marks."""
        now = monotonic_now()
        elapsed = now - self._win_start
        if elapsed >= self.WINDOW:
            # window overdue (no mark rolled it): everything we know about
            # the trailing period is the in-progress count
            return self._win_count / elapsed
        if not self._have_window:
            # first window: partial-window rate, elapsed floored at 1s so
            # a scrape landing moments after start (or /clearmetrics)
            # can't inflate one event into a ~1000/s spike
            return self._win_count / max(elapsed, 1.0)
        # blend the completed window with the in-progress fraction
        return (self._win_count
                + self._last_rate * (self.WINDOW - elapsed)) / self.WINDOW

    def snapshot(self) -> dict:
        lifetime = monotonic_now() - self._t0
        return {"type": "meter", "count": self.count,
                "mean_rate": round(self.count / lifetime, 3)
                if lifetime > 0 else 0.0,
                "recent_rate": round(self._recent_rate(), 3)}


class _ExpDecayReservoir:
    """Exponential-decay sample (medida ExpDecaySample / Cormode et al.):
    a fixed-size priority sample where newer values win with exponentially
    growing weight, so percentiles track recent behavior."""
    __slots__ = ("size", "alpha", "_heap", "_t0", "_next_rescale", "_rng")

    RESCALE_INTERVAL = 3600.0

    def __init__(self, size: int = 1028, alpha: float = 0.015) -> None:
        self.size = size
        self.alpha = alpha
        self._heap: List = []  # (priority, tiebreak, value)
        self._t0 = monotonic_now()
        self._next_rescale = self._t0 + self.RESCALE_INTERVAL
        self._rng = random.Random(0x5747)

    def update(self, value: float) -> None:
        now = monotonic_now()
        if now >= self._next_rescale:
            self._rescale(now)
        priority = math.exp(self.alpha * (now - self._t0)) \
            / max(self._rng.random(), 1e-12)
        item = (priority, self._rng.random(), value)
        if len(self._heap) < self.size:
            heapq.heappush(self._heap, item)
        elif priority > self._heap[0][0]:
            heapq.heapreplace(self._heap, item)

    def _rescale(self, now: float) -> None:
        # renormalize priorities so exp() stays in range on long uptimes
        factor = math.exp(-self.alpha * (now - self._t0))
        self._heap = [(p * factor, t, v) for p, t, v in self._heap]
        heapq.heapify(self._heap)
        self._t0 = now
        self._next_rescale = now + self.RESCALE_INTERVAL

    def values(self) -> List[float]:
        return [v for _, _, v in self._heap]

    def clear(self) -> None:
        self._heap = []


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = q * (len(sorted_vals) - 1)
    lo = int(idx)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = idx - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


class Histogram:
    """Value distribution with exponential-decay percentiles."""
    __slots__ = ("count", "total", "max", "min", "_reservoir", "_lock")

    def __init__(self) -> None:
        self._lock = make_lock("metrics.histogram")
        self._init_state()

    def _init_state(self) -> None:
        self.count = 0
        self.total = 0.0
        self.max = 0.0
        self.min = float("inf")
        self._reservoir = _ExpDecayReservoir()

    def reset(self) -> None:
        with self._lock:
            self._init_state()

    def update(self, value: float) -> None:
        with self._lock:
            self.count += 1
            self.total += value
            if value > self.max:
                self.max = value
            if value < self.min:
                self.min = value
            self._reservoir.update(value)

    def quantiles(self) -> dict:
        with self._lock:
            vals = sorted(self._reservoir.values())
        return {"p50": _percentile(vals, 0.50),
                "p90": _percentile(vals, 0.90),
                "p99": _percentile(vals, 0.99)}

    def snapshot(self) -> dict:
        q = self.quantiles()
        return {"type": "histogram", "count": self.count,
                "mean": round(self.total / self.count, 6) if self.count
                else 0.0,
                "sum": round(self.total, 6),
                "max": round(self.max, 6),
                "min": round(self.min, 6) if self.count else 0.0,
                "p50": round(q["p50"], 6), "p90": round(q["p90"], 6),
                "p99": round(q["p99"], 6)}


class Timer(Histogram):
    """Histogram of durations in seconds; snapshot keys carry the _s unit
    suffix (the shape apply_load and the bench record expect)."""
    __slots__ = ()

    def time(self):
        return _TimerCtx(self)

    def snapshot(self) -> dict:
        q = self.quantiles()
        return {"type": "timer", "count": self.count,
                "mean_s": round(self.total / self.count, 6)
                if self.count else 0.0,
                "sum_s": round(self.total, 6),
                "max_s": round(self.max, 6),
                "min_s": round(self.min, 6) if self.count else 0.0,
                "p50_s": round(q["p50"], 6), "p90_s": round(q["p90"], 6),
                "p99_s": round(q["p99"], 6)}


class _TimerCtx:
    __slots__ = ("_timer", "_t0")

    def __init__(self, t: Timer):
        self._timer = t

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._timer.update(time.perf_counter() - self._t0)


class MetricsRegistry:
    def __init__(self) -> None:
        self._metrics: Dict[str, object] = {}
        # creation is check-then-act and metrics record from background
        # threads (worker-pool bucket merges, the preverify device
        # worker): without the lock, concurrent first-touch of a name
        # makes two objects and silently drops one's samples
        self._lock = make_lock("metrics.registry")

    def _get(self, name: str, cls, exact: bool = False):
        m = self._metrics.get(name)
        if m is None:
            with self._lock:
                m = self._metrics.get(name)
                if m is None:
                    m = self._metrics[name] = cls()
        ok = type(m) is cls if exact else isinstance(m, cls)
        assert ok, f"{name} already a {type(m).__name__}"
        return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def meter(self, name: str) -> Meter:
        return self._get(name, Meter)

    def timer(self, name: str) -> Timer:
        return self._get(name, Timer)

    def histogram(self, name: str) -> Histogram:
        # exact: a Timer IS-A Histogram but has a different snapshot shape
        return self._get(name, Histogram, exact=True)

    def gauge(self, name: str,
              fn: Optional[Callable[[], float]] = None) -> Gauge:
        g = self._get(name, Gauge)
        if fn is not None:
            g.set_source(fn)
        return g

    def weak_gauge(self, name: str, obj, fn: Callable) -> Gauge:
        """Gauge reading `fn(obj)` WITHOUT pinning `obj` in the
        process-global registry: once the subsystem is torn down the
        source reads null (fn(None) raises, Gauge.value() catches).
        This is how per-node gauges must register — a strong closure
        would retain a dead node's whole object graph for process
        lifetime."""
        ref = weakref.ref(obj)
        return self.gauge(name, lambda: fn(ref()))

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def items(self) -> List[tuple]:
        """Sorted (name, metric) pairs — the change-aware capture path
        (util/timeseries) walks metric objects directly so it can skip
        snapshot recompute for provably-unchanged reservoirs."""
        with self._lock:
            return sorted(self._metrics.items())

    def snapshot(self, prefix: Optional[str] = None) -> Dict[str, dict]:
        with self._lock:
            items = sorted(self._metrics.items())
        return {k: m.snapshot() for k, m in items
                if prefix is None or k.startswith(prefix)}

    def clear(self) -> None:
        """Reset every metric IN PLACE (reference: /clearmetrics).

        Deliberately not a dict replacement: call sites hold direct metric
        references (hot paths cache `registry().timer(...)` lookups), and
        replacing the mapping orphaned those objects — every sample after a
        /clearmetrics silently vanished."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            m.reset()


_registry = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-wide registry (reference: medida::MetricsRegistry owned
    by the Application; module-global here because LedgerManager and friends
    are constructible without an Application)."""
    return _registry


def reset_registry() -> None:
    global _registry
    _registry = MetricsRegistry()


# ---------------------------------------------------------------------------
# Prometheus text exposition (reference shape: the v20+ CommandHandler
# /metrics alternatives; format per prometheus.io/docs/instrumenting/
# exposition_formats).
# ---------------------------------------------------------------------------

def _prom_name(name: str) -> str:
    return re.sub(r"[^a-zA-Z0-9_:]", "_", name)


def _prom_val(v) -> str:
    if v is None or v != v:  # dead gauge / NaN
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    return repr(float(v)) if not float(v).is_integer() else str(int(v))


def render_prometheus(snapshot: Dict[str, dict],
                      namespace: str = "stellar_core_tpu") -> str:
    """Render a registry snapshot in Prometheus text exposition format.

    counters/meters -> `<ns>_<name>_total` counters (meters also export a
    `_rate` gauge); gauges -> gauges; timers/histograms -> summaries with
    quantile labels plus `_sum`/`_count` (timers in seconds)."""
    lines: List[str] = []

    def emit(name: str, mtype: str, samples: List) -> None:
        lines.append(f"# TYPE {name} {mtype}")
        for labels, value in samples:
            lines.append(f"{name}{labels} {_prom_val(value)}")

    for raw_name, snap in sorted(snapshot.items()):
        base = f"{namespace}_{_prom_name(raw_name)}"
        t = snap.get("type")
        if t == "counter":
            emit(base + "_total", "counter", [("", snap["count"])])
        elif t == "meter":
            emit(base + "_total", "counter", [("", snap["count"])])
            emit(base + "_rate", "gauge", [("", snap["recent_rate"])])
        elif t == "gauge":
            emit(base, "gauge", [("", snap["value"])])
        elif t == "timer":
            emit(base + "_seconds", "summary", [
                ('{quantile="0.5"}', snap["p50_s"]),
                ('{quantile="0.9"}', snap["p90_s"]),
                ('{quantile="0.99"}', snap["p99_s"]),
            ])
            # exact accumulated total, NOT mean*count — rounded means
            # drift non-monotonically at high sample counts and Prometheus
            # rate() reads a decreasing _sum as a counter reset
            lines.append(f"{base}_seconds_sum {_prom_val(snap['sum_s'])}")
            lines.append(f"{base}_seconds_count {snap['count']}")
            emit(base + "_seconds_max", "gauge", [("", snap["max_s"])])
        elif t == "histogram":
            emit(base, "summary", [
                ('{quantile="0.5"}', snap["p50"]),
                ('{quantile="0.9"}', snap["p90"]),
                ('{quantile="0.99"}', snap["p99"]),
            ])
            lines.append(f"{base}_sum {_prom_val(snap['sum'])}")
            lines.append(f"{base}_count {snap['count']}")
            emit(base + "_max", "gauge", [("", snap["max"])])
    return "\n".join(lines) + "\n"
