"""Metrics registry: counters, meters and timers, medida-style.

Reference: lib/libmedida as used throughout the reference
(`app.getMetrics().NewTimer({"ledger", "ledger", "close"})`, CommandHandler
/metrics endpoint).  Names are dotted strings ("ledger.ledger.close");
`registry().snapshot()` is the /metrics JSON surface.
"""

from __future__ import annotations

import time
from typing import Dict, Optional


class Counter:
    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n

    def snapshot(self) -> dict:
        return {"type": "counter", "count": self.value}


class Meter:
    """Event rate: count + events/sec over the process lifetime and a
    recent window (medida meters' 1m rate approximated by a sliding
    window)."""
    __slots__ = ("count", "_t0", "_win_start", "_win_count", "_last_rate")

    WINDOW = 60.0

    def __init__(self) -> None:
        self.count = 0
        self._t0 = time.monotonic()
        self._win_start = self._t0
        self._win_count = 0
        self._last_rate = 0.0

    def mark(self, n: int = 1) -> None:
        self.count += n
        self._win_count += n
        now = time.monotonic()
        if now - self._win_start >= self.WINDOW:
            self._last_rate = self._win_count / (now - self._win_start)
            self._win_start = now
            self._win_count = 0

    def snapshot(self) -> dict:
        lifetime = time.monotonic() - self._t0
        return {"type": "meter", "count": self.count,
                "mean_rate": round(self.count / lifetime, 3)
                if lifetime > 0 else 0.0,
                "recent_rate": round(self._last_rate, 3)}


class Timer:
    __slots__ = ("count", "total", "max", "min")

    def __init__(self) -> None:
        self.count = 0
        self.total = 0.0
        self.max = 0.0
        self.min = float("inf")

    def update(self, dt: float) -> None:
        self.count += 1
        self.total += dt
        if dt > self.max:
            self.max = dt
        if dt < self.min:
            self.min = dt

    def time(self):
        return _TimerCtx(self)

    def snapshot(self) -> dict:
        return {"type": "timer", "count": self.count,
                "mean_s": round(self.total / self.count, 6)
                if self.count else 0.0,
                "max_s": round(self.max, 6),
                "min_s": round(self.min, 6) if self.count else 0.0}


class _TimerCtx:
    __slots__ = ("_timer", "_t0")

    def __init__(self, t: Timer):
        self._timer = t

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._timer.update(time.perf_counter() - self._t0)


class MetricsRegistry:
    def __init__(self) -> None:
        self._metrics: Dict[str, object] = {}

    def _get(self, name: str, cls):
        m = self._metrics.get(name)
        if m is None:
            m = self._metrics[name] = cls()
        assert isinstance(m, cls), f"{name} already a {type(m).__name__}"
        return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def meter(self, name: str) -> Meter:
        return self._get(name, Meter)

    def timer(self, name: str) -> Timer:
        return self._get(name, Timer)

    def snapshot(self, prefix: Optional[str] = None) -> Dict[str, dict]:
        return {k: m.snapshot() for k, m in sorted(self._metrics.items())
                if prefix is None or k.startswith(prefix)}

    def clear(self) -> None:
        """Drop all recorded metrics (reference: /clearmetrics)."""
        self._metrics.clear()


_registry = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-wide registry (reference: medida::MetricsRegistry owned
    by the Application; module-global here because LedgerManager and friends
    are constructible without an Application)."""
    return _registry


def reset_registry() -> None:
    global _registry
    _registry = MetricsRegistry()
