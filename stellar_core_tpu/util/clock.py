"""VirtualClock / VirtualTimer — the event loop and determinism keystone.

Reference: src/util/Timer.{h,cpp} — VirtualClock (REAL_TIME vs VIRTUAL_TIME
modes), VirtualTimer, and the crank loop that the whole node lives in;
the fair action Scheduler is in scheduler.py.

VIRTUAL_TIME is what makes multi-node in-process simulation deterministic:
tests crank simulated time forward; timers fire in order with no wall-clock
dependency (SURVEY.md §4 "determinism backbone").
"""

from __future__ import annotations

import heapq
import itertools
import time as _time
from enum import Enum
from typing import Callable, List, Optional, Tuple

from .scheduler import Scheduler


class ClockMode(Enum):
    REAL_TIME = 0
    VIRTUAL_TIME = 1


def monotonic_now() -> float:
    """Real monotonic seconds — the blessed escape hatch for *infra*
    timing (metric rate windows, reservoir decay) that must track the
    host clock even under VIRTUAL_TIME.  Subsystem logic must go through
    a VirtualClock; corelint's clock-discipline rule enforces that this
    module (plus util/perf.py and bench.py) is the only wall-clock seam."""
    return _time.monotonic()


def wall_now() -> float:
    """Real wall-clock epoch seconds — the infra-level counterpart of
    system_now() for export timestamps (Chrome trace epochs, bench
    cache ages).  Same discipline as monotonic_now()."""
    return _time.time()


class VirtualClock:
    def __init__(self, mode: ClockMode = ClockMode.VIRTUAL_TIME) -> None:
        self.mode = mode
        self._virtual_now = 0.0
        self._heap: List[Tuple[float, int, "VirtualTimer", Callable[[], None]]] = []
        self._seq = itertools.count()
        self.scheduler = Scheduler()
        self._stopped = False
        # IO pumps: polled at the top of every crank (the asio-socket
        # integration point; reference: VirtualClock owns the io_context)
        self._io_pumps: List[Callable[[], int]] = []

    def add_io_pump(self, pump: Callable[[], int]) -> None:
        self._io_pumps.append(pump)

    def remove_io_pump(self, pump: Callable[[], int]) -> None:
        if pump in self._io_pumps:
            self._io_pumps.remove(pump)

    # -- time ---------------------------------------------------------------
    def now(self) -> float:
        if self.mode is ClockMode.REAL_TIME:
            return _time.monotonic()
        return self._virtual_now

    def system_now(self) -> int:
        """Wall-clock seconds (ledger close time source). In virtual mode the
        virtual offset is used so tests are reproducible."""
        if self.mode is ClockMode.REAL_TIME:
            return int(_time.time())
        return int(self._virtual_now)

    # -- scheduling ---------------------------------------------------------
    def post_action(self, fn: Callable[[], None], name: str = "", queue_type: int = 0) -> None:
        self.scheduler.enqueue(fn, name=name, queue_type=queue_type)

    def _schedule(self, when: float, timer: "VirtualTimer", fn: Callable[[], None]) -> None:
        heapq.heappush(self._heap, (when, next(self._seq), timer, fn))

    # -- cranking -----------------------------------------------------------
    def crank(self, block: bool = False) -> int:
        """Run one batch of due work; returns number of events processed.
        In VIRTUAL_TIME, if nothing is runnable, time advances to the next
        timer deadline (reference: VirtualClock::crank advancing virtual time
        when the io_context is idle)."""
        if self._stopped:
            return 0
        progressed = 0
        for pump in list(self._io_pumps):
            progressed += pump()
        progressed += self.scheduler.run_one_batch()
        now = self.now()
        while self._heap and self._heap[0][0] <= now:
            _, _, timer, fn = heapq.heappop(self._heap)
            if not timer.cancelled:
                timer._pending -= 1
                fn()
                progressed += 1
        if progressed == 0 and self.mode is ClockMode.VIRTUAL_TIME and self._heap:
            # advance virtual time to the next deadline
            self._virtual_now = max(self._virtual_now, self._heap[0][0])
            progressed += self.crank()
        return progressed

    def crank_until(self, pred: Callable[[], bool], timeout: float) -> bool:
        """Crank until pred() or (virtual) timeout elapsed. Reference:
        Simulation::crankUntil."""
        deadline = self.now() + timeout
        while self.now() <= deadline:
            if pred():
                return True
            if self.crank() == 0 and not self._heap and self.scheduler.empty():
                if self.mode is ClockMode.VIRTUAL_TIME:
                    return pred()
                _time.sleep(0.001)
        return pred()

    def crank_for(self, duration: float) -> None:
        deadline = self.now() + duration
        while self.now() < deadline:
            if self.crank() == 0 and not self._heap and self.scheduler.empty():
                if self.mode is ClockMode.VIRTUAL_TIME:
                    self._virtual_now = deadline
                    return
                _time.sleep(0.001)

    def stop(self) -> None:
        self._stopped = True


class VirtualTimer:
    """One-shot/repeating timer bound to a VirtualClock.
    Reference: src/util/Timer.h — VirtualTimer::expires_from_now + async_wait."""

    def __init__(self, clock: VirtualClock) -> None:
        self._clock = clock
        self.cancelled = False
        self._pending = 0

    def expires_from_now(self, delay: float, fn: Callable[[], None],
                         on_cancel: Optional[Callable[[], None]] = None) -> None:
        self.cancelled = False
        self._pending += 1
        self._clock._schedule(self._clock.now() + delay, self, fn)

    def expires_at(self, when: float, fn: Callable[[], None]) -> None:
        self.cancelled = False
        self._pending += 1
        self._clock._schedule(when, self, fn)

    def cancel(self) -> None:
        self.cancelled = True

    @property
    def seated(self) -> bool:
        return self._pending > 0 and not self.cancelled
