"""Fleet-wide observability: cross-node trace collection/merging and a
central metrics scraper.

The per-process planes (util/tracing spans + phase marks, util/metrics
snapshots) see ONE node.  A fleet soak (simulation/fleet — N real
processes over TCP) or a chaos campaign needs the cross-node picture:
did node-3's close seal lag the quorum's externalize, did the rejoining
node's catchup overlap the others' closes, did close p99 degrade slowly
or collapse at the kill.  Two collectors provide it:

``FleetTraceCollector``
    Polls every node's ``/tracespans?since=`` incremental export,
    accumulates marks + span events per node, aligns the nodes onto one
    timebase (each node's monotonic clock is mapped through its
    reported clock anchor; residual wall-clock skew between nodes is
    corrected by matching slot-keyed ``externalize`` marks — the same
    slot externalizes within ms across a healthy quorum, so the median
    per-slot delta IS the skew), and merges everything into ONE Chrome
    trace: one process row per node, phase marks as instant events,
    slot-spanning flow arrows.  ``Fleet.finalize()`` and ChaosRunner
    write the merged file next to their reports.

``FleetScraper``
    A daemon thread polling every node's ``/metrics`` snapshot on a
    cadence into a bounded ring of timestamped snapshots per node —
    fleet SLOs become *curves* (close p99 over time, admission depth,
    shed rate) instead of end-of-run points, with per-node divergence
    deltas; each sweep optionally feeds a util/slo.SLOTracker so burn
    rates are evaluated fleet-wide (every node's window counts).
"""

from __future__ import annotations

import json
import statistics
import threading
from collections import deque
from typing import Callable, Dict, List, Optional

from .clock import monotonic_now
from .lockorder import make_lock
from .metrics import registry as _registry

# Default scrape ring: 600 snapshots/node at the 1 s default cadence =
# a 10-minute window, each snapshot a few KB — bounded by count.
SCRAPE_RING = 600
SCRAPE_CADENCE_S = 1.0

# The phase used for inter-node skew estimation: externalize is the one
# mark every in-sync node emits for every slot within ms of the quorum.
ALIGN_PHASE = "externalize"


def _mark_wall(mark: dict, anchor: Optional[dict]) -> float:
    """A mark's timestamp on the node's anchor-mapped wall timebase.
    The anchor (one monotonic↔wall pairing per node) is authoritative:
    per-event wall stamps would smear NTP steps across the trace."""
    if anchor and "perf_s" in mark:
        return anchor["wall_s"] + (mark["perf_s"] - anchor["perf_s"])
    return mark.get("wall_s", 0.0)


class FleetTraceCollector:
    """Accumulates /tracespans documents per node and merges them into
    one aligned Chrome trace."""

    def __init__(self):
        self._since: Dict[str, int] = {}
        self._marks: Dict[str, List[dict]] = {}
        self._spans: Dict[str, List[dict]] = {}
        self._anchors: Dict[str, dict] = {}
        self._lock = make_lock("fleettrace.collector")

    # -- collection ---------------------------------------------------------
    def since(self, node: str) -> int:
        with self._lock:
            return self._since.get(node, 0)

    def ingest(self, node: str, doc: dict) -> int:
        """Fold one /tracespans response in; returns the number of new
        marks+spans.  ``node`` is the collector-side name — it wins over
        the document's self-reported id (a node misconfigured with a
        duplicate name must not silently merge rows)."""
        marks = doc.get("marks") or []
        spans = doc.get("spans") or []
        with self._lock:
            self._marks.setdefault(node, []).extend(marks)
            self._spans.setdefault(node, []).extend(spans)
            if doc.get("anchor"):
                self._anchors[node] = doc["anchor"]
            nxt = doc.get("next_since")
            if isinstance(nxt, int):
                self._since[node] = max(
                    self._since.get(node, 0), nxt)
        return len(marks) + len(spans)

    def poll(self, node: str,
             fetch: Callable[[str], dict]) -> int:
        """One incremental scrape of ``node`` via ``fetch(path)`` (e.g.
        FleetNode.http_json); raises whatever fetch raises."""
        doc = fetch(f"/tracespans?since={self.since(node)}")
        return self.ingest(node, doc)

    def nodes(self) -> List[str]:
        with self._lock:
            return sorted(set(self._marks) | set(self._spans))

    def marks(self, node: str) -> List[dict]:
        with self._lock:
            return list(self._marks.get(node, []))

    # -- alignment ----------------------------------------------------------
    def align_offsets(self, phase: str = ALIGN_PHASE) -> Dict[str, float]:
        """Per-node wall-clock offsets (seconds to ADD to a node's
        anchor-mapped timestamps) that bring all nodes onto the first
        node's timebase.  For each slot marked ``phase`` on both the
        reference node and another node, the timestamp delta estimates
        that node's skew; the median over shared slots is robust to the
        genuine ms-scale spread of externalization."""
        nodes = self.nodes()
        if not nodes:
            return {}
        ref = nodes[0]
        with self._lock:
            per_node_slot: Dict[str, Dict[int, float]] = {}
            for node in nodes:
                anchor = self._anchors.get(node)
                slots: Dict[int, float] = {}
                for m in self._marks.get(node, []):
                    if m.get("phase") == phase and "slot" in m:
                        # first mark per slot wins (re-marks are noise)
                        slots.setdefault(m["slot"],
                                         _mark_wall(m, anchor))
                per_node_slot[node] = slots
        offsets = {ref: 0.0}
        ref_slots = per_node_slot[ref]
        for node in nodes[1:]:
            deltas = [ref_slots[s] - t
                      for s, t in per_node_slot[node].items()
                      if s in ref_slots]
            offsets[node] = statistics.median(deltas) if deltas else 0.0
        return offsets

    # -- merging ------------------------------------------------------------
    def merge_chrome_trace(self) -> dict:
        """ONE Chrome trace document: pid per node (row-per-node in
        chrome://tracing / perfetto), span events + mark instant events
        shifted onto the aligned timebase, and per-slot flow arrows
        connecting each slot's marks across nodes."""
        with _registry().timer("fleet.trace.merge").time():
            return self._merge()

    def _merge(self) -> dict:
        nodes = self.nodes()
        offsets = self.align_offsets()
        events: List[dict] = []
        # slot -> [(ts_us, pid, tid)] for flow arrows
        slot_points: Dict[int, List[tuple]] = {}
        for pid, node in enumerate(nodes, start=1):
            events.append({"name": "process_name", "ph": "M",
                           "pid": pid, "tid": 0,
                           "args": {"name": node}})
            events.append({"name": "process_sort_index", "ph": "M",
                           "pid": pid, "tid": 0,
                           "args": {"sort_index": pid}})
            off_us = offsets.get(node, 0.0) * 1e6
            with self._lock:
                anchor = self._anchors.get(node)
                spans = list(self._spans.get(node, []))
                marks = list(self._marks.get(node, []))
            for ev in spans:
                ev = dict(ev)
                ev["pid"] = pid
                ev["ts"] = round(ev.get("ts", 0.0) + off_us, 3)
                events.append(ev)
            for m in marks:
                ts_us = (_mark_wall(m, anchor)
                         + offsets.get(node, 0.0)) * 1e6
                tid = m.get("tid", 0)
                ev = {"name": f"{m.get('phase')}@{m.get('slot')}",
                      "ph": "i", "s": "t",
                      "ts": round(ts_us, 3),
                      "pid": pid, "tid": tid, "cat": "mark",
                      "args": {"slot": m.get("slot"),
                               "phase": m.get("phase"),
                               "node": node}}
                if m.get("args"):
                    ev["args"].update(m["args"])
                events.append(ev)
                if isinstance(m.get("slot"), int):
                    slot_points.setdefault(m["slot"], []).append(
                        (ev["ts"], pid, tid))
        # slot-spanning flow arrows: start at the slot's earliest mark,
        # step through every later mark (usually on other nodes)
        for slot, points in sorted(slot_points.items()):
            if len(points) < 2:
                continue
            points.sort()
            for i, (ts, pid, tid) in enumerate(points):
                ph = "s" if i == 0 else "f" if i == len(points) - 1 \
                    else "t"
                ev = {"name": "slot", "cat": "slot-flow", "ph": ph,
                      "id": slot, "ts": ts, "pid": pid, "tid": tid}
                if ph == "f":
                    ev["bp"] = "e"
                events.append(ev)
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "metadata": {"nodes": nodes,
                             "offsets_s": {n: round(o, 6)
                                           for n, o in offsets.items()}}}

    def write_merged_trace(self, path: str) -> int:
        """Write the merged trace JSON to ``path``; returns the event
        count."""
        doc = self.merge_chrome_trace()
        with open(path, "w") as f:
            json.dump(doc, f)
        return len(doc["traceEvents"])


def merge_local_trace(path: str) -> int:
    """In-process fleet (chaos campaigns: N SimNodes in ONE process)
    variant: split THIS process's phase-mark buffer by each mark's node
    attribution into per-node rows, keep spans on a shared ``sim`` row,
    and write the same merged Chrome trace shape Fleet.finalize emits.
    Returns the event count."""
    from . import tracing
    doc = tracing.tracespans_doc(0)
    anchor = doc.get("anchor")
    coll = FleetTraceCollector()
    by_node: Dict[str, List[dict]] = {}
    for mark in doc.get("marks") or []:
        by_node.setdefault(mark.get("node") or "sim", []).append(mark)
    for node, marks in sorted(by_node.items()):
        coll.ingest(node, {"marks": marks, "anchor": anchor})
    if doc.get("spans"):
        coll.ingest("sim", {"spans": doc["spans"], "anchor": anchor})
    return coll.write_merged_trace(path)


# ---------------------------------------------------------------------------
# central metrics scraper
# ---------------------------------------------------------------------------

class FleetScraper:
    """Polls every node's metric snapshot on a cadence into a bounded
    ring per node (timestamped), derives SLO curves and per-node
    divergence deltas, and optionally drives a util/slo.SLOTracker with
    every node's snapshot (fleet-wide burn windows).

    With ``anomaly=True`` every scraped node gets its OWN
    util/anomaly.AnomalyDetector (gauge registration off — N nodes in
    one coordinator process must not fight over anomaly.active.*), fed
    each sweep; ``report()`` then carries per-node anomaly verdicts.
    ``retention_s`` bounds memory against nodes that leave the fleet
    for good: a node whose last successful scrape is older than the
    window is EVICTED (ring + detector dropped, ``fleet.scrape.evicted``
    counted).  A node that merely restarts inside the window keeps its
    history; an evicted node that re-appears starts a fresh ring."""

    # the standing fleet curves: (label, metric, field).  The last three
    # are the read-path contention axes (ISSUE 20): merge stall inside
    # close, reader-held pin time, and bulk-read key throughput — the
    # inputs to the close-p99-vs-read-QPS story.
    CURVES = (
        ("close_p99_s", "ledger.ledger.close", "p99_s"),
        ("admission_depth", "herder.admission.depth", "value"),
        ("shed_count", "herder.admission.overload", "count"),
        ("merge_stall_p99_s", "bucket.merge.stall", "p99_s"),
        ("pin_held_p99_s", "bucketlistdb.pin.held", "p99_s"),
        ("read_qps", "bucketlistdb.read.keys", "recent_rate"),
    )

    def __init__(self,
                 fetchers: Dict[str, Callable[[], dict]],
                 cadence_s: float = SCRAPE_CADENCE_S,
                 ring: int = SCRAPE_RING,
                 tracker=None,
                 retention_s: Optional[float] = None,
                 anomaly: bool = False):
        self._fetchers = dict(fetchers)
        self.cadence_s = cadence_s
        self.tracker = tracker
        self.retention_s = retention_s
        self.anomaly = anomaly
        self._ring_len = ring
        self._rings: Dict[str, deque] = {
            name: deque(maxlen=ring) for name in self._fetchers}
        # per-node last SUCCESSFUL scrape time (scraper-relative);
        # retention measures from scraper start for never-seen nodes
        self._last_ok: Dict[str, float] = {
            name: 0.0 for name in self._fetchers}
        self._detectors: Dict[str, object] = {}
        self._evicted = 0
        self._lock = make_lock("fleettrace.scraper")
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._polls = 0
        self._errors = 0
        self._t0 = monotonic_now()

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "FleetScraper":
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._stop_evt = threading.Event()
                self._thread = threading.Thread(
                    target=self._run, name="fleet-scraper", daemon=True)
                self._thread.start()
        return self

    def stop(self) -> None:
        with self._lock:
            self._stop_evt.set()
            t = self._thread
            self._thread = None
        if t is not None:
            t.join(timeout=max(2.0, 2 * self.cadence_s))

    def _run(self) -> None:
        with self._lock:
            evt = self._stop_evt
        while not evt.wait(self.cadence_s):
            self.sweep()

    # -- scraping -----------------------------------------------------------
    def sweep(self) -> int:
        """One pass over every node; returns the number of successful
        scrapes.  A node that fails to answer (killed by chaos, mid-
        restart) counts an error and keeps its ring as-is until the
        retention window (when set) expires it."""
        ok = 0
        reg = _registry()
        for name, fetch in self._fetchers.items():
            try:
                snap = fetch()
            except Exception:  # corelint: disable=exception-hygiene -- a killed node must not stop the sweep; the error counter carries the signal
                with self._lock:
                    self._errors += 1
                reg.counter("fleet.scrape.errors").inc()
                continue
            now = monotonic_now() - self._t0
            det = None
            with self._lock:
                # setdefault: an evicted node that re-appears rebuilds
                # its ring (and detector) from scratch
                self._rings.setdefault(
                    name, deque(maxlen=self._ring_len)).append((now, snap))
                self._last_ok[name] = now
                self._polls += 1
                if self.anomaly:
                    det = self._detectors.get(name)
                    if det is None:
                        from .anomaly import (AnomalyDetector,
                                              default_tracked)
                        det = AnomalyDetector(default_tracked(),
                                              source=name,
                                              register_gauges=False)
                        self._detectors[name] = det
            reg.counter("fleet.scrape.polls").inc()
            ok += 1
            # detector + tracker evaluate OUTSIDE the scraper lock (each
            # takes its own lock; ours must stay above theirs, not hold
            # them nested through fetch-heavy sweeps)
            if det is not None:
                det.evaluate(snap, now=now)
            if self.tracker is not None:
                self.tracker.evaluate(snap, now=now)
        self._evict_stale(monotonic_now() - self._t0)
        return ok

    def _evict_stale(self, now: float) -> None:
        """Drop ring + detector state for nodes whose last successful
        scrape is beyond the retention window — the memory bound against
        permanently-departed fleet members."""
        if self.retention_s is None:
            return
        reg = _registry()
        with self._lock:
            stale = [name for name, ring in self._rings.items()
                     if now - self._last_ok.get(name, 0.0)
                     > self.retention_s]
            for name in stale:
                del self._rings[name]
                self._detectors.pop(name, None)
                self._last_ok.pop(name, None)
                self._evicted += 1
        for _ in stale:
            reg.counter("fleet.scrape.evicted").inc()

    # -- readers ------------------------------------------------------------
    def ring(self, node: str) -> List[tuple]:
        with self._lock:
            return list(self._rings.get(node, ()))

    @staticmethod
    def _field(snap: dict, metric: str, field: str):
        m = snap.get(metric)
        return m.get(field) if isinstance(m, dict) else None

    def curve(self, metric: str, field: str) -> Dict[str, List[list]]:
        """Per-node [t_s, value] series for one metric field (points
        where the metric was absent are skipped)."""
        out: Dict[str, List[list]] = {}
        with self._lock:
            rings = {n: list(r) for n, r in self._rings.items()}
        for node, ring in rings.items():
            series = []
            for t, snap in ring:
                v = self._field(snap, metric, field)
                if v is not None:
                    series.append([round(t, 3), v])
            out[node] = series
        return out

    def curves(self) -> dict:
        return {label: self.curve(metric, field)
                for label, metric, field in self.CURVES}

    def divergence(self, metric: str, field: str) -> Optional[dict]:
        """Latest-snapshot spread of one metric field across nodes: the
        per-node values plus max-min delta — a straggler detector."""
        values: Dict[str, float] = {}
        with self._lock:
            for node, ring in self._rings.items():
                if not ring:
                    continue
                v = self._field(ring[-1][1], metric, field)
                if v is not None:
                    values[node] = v
        if not values:
            return None
        return {"values": values,
                "delta": round(max(values.values())
                               - min(values.values()), 6)}

    @property
    def polls(self) -> int:
        with self._lock:
            return self._polls

    @property
    def errors(self) -> int:
        with self._lock:
            return self._errors

    @property
    def evicted(self) -> int:
        with self._lock:
            return self._evicted

    def tracked_nodes(self) -> List[str]:
        with self._lock:
            return sorted(self._rings)

    def node_anomalies(self) -> Dict[str, dict]:
        """Per-node anomaly verdicts (empty when anomaly=False)."""
        with self._lock:
            detectors = dict(self._detectors)
        # report() takes each detector's own lock — outside ours
        return {name: det.report()
                for name, det in sorted(detectors.items())}

    def report(self) -> dict:
        """The fleet-report section: curves, divergence deltas, scrape
        accounting, per-node anomaly verdicts, and (when a tracker is
        attached) the SLO report."""
        out = {
            "cadence_s": self.cadence_s,
            "polls": self.polls,
            "errors": self.errors,
            "evicted": self.evicted,
            "nodes": self.tracked_nodes(),
            "curves": self.curves(),
            "divergence": {
                label: self.divergence(metric, field)
                for label, metric, field in self.CURVES},
        }
        if self.anomaly:
            out["anomalies"] = self.node_anomalies()
        if self.tracker is not None:
            out["slo"] = self.tracker.report()
        return out
