"""Runtime data-race sanitizer: Eraser-style per-field locksets.

The lock-order tracer (util/lockorder.py) proves the locks we DO take are
taken in a consistent order; this module proves shared fields are covered
by a lock at all.  Classes opt in with the ``@race_checked`` decorator —
zero overhead while tracing is off (the decorator returns the class
unchanged; same contract as ``make_lock``).  With tracing ON
(``STPU_RACE_TRACE=1`` in the environment at import, or ``enable()``
before the subsystem is built) every registered class's attribute access
is instrumented and each instance field runs the classic Eraser state
machine [Savage et al., SOSP '97]:

  Virgin --first access--> Exclusive(owner thread)
  Exclusive --access by 2nd thread--> Shared (read) / SharedMod (write),
           candidate lockset := locks the 2nd thread holds
  Shared/SharedMod: lockset := lockset INTERSECT locks held at the access
           (a write promotes Shared -> SharedMod)

The Exclusive state gives the init-then-publish pattern a free pass: a
field hammered by its creating thread carries no lockset obligation until
a second thread actually touches it.  A WRITE from a non-owner thread
that leaves the candidate lockset EMPTY is a data race: the access raises
``DataRaceError`` after flight-recording the event and writing a crash
bundle naming the field, both threads, and the shrinking lockset history
(util/eventlog -> $STPU_CRASH_DIR).  First-owner writes with concurrent
readers are deliberately not fail-stopped: the repo's GIL-atomic
monitoring reads (gauge callbacks, /metrics snapshots from the admin
threads) are exactly that shape — they surface in the lockset history,
not as crashes.

Granularity: the proxy sees BINDING accesses (``obj.field`` get/set),
not memory accesses — an in-place container mutation from a second
thread (``obj.d[k] = v``, ``obj.l.append(x)``) registers as a *read* of
the binding and therefore refines the lockset without fail-stopping.
That shape is the static rule's job: corelint's `thread-safety` counts
subscript stores and mutator-method calls through a field as writes, so
the two layers cover each other's blind spots.

Locksets come from lockorder's thread-local held stack, so the sanitizer
only sees locks created through ``make_lock``/``make_rlock`` — which the
``raw-lock`` lint rule makes all of them.  ``STPU_RACE_TRACE=1`` implies
lock tracing (lockorder checks both variables); in-process ``enable()``
calls ``lockorder.enable()`` itself, and must run BEFORE the subsystems
under test create their locks, or every lockset reads empty.

Overhead when enabled: one dict probe + set intersection per tracked
attribute access on registered classes (measured in PROFILE.md and the
bench ``racetrace`` rows); exactly zero when off.
"""

from __future__ import annotations

import os
import threading
import traceback
import types
from collections import deque
from typing import Dict, Optional, Tuple

from . import lockorder

_enabled = bool(os.environ.get("STPU_RACE_TRACE"))
# bumped by every enable(): field state from an earlier tracing session
# is stale (ownership may have legitimately moved while tracing was off)
# and is re-owned on first access instead of raising a false positive
_epoch = 1
# serializes the per-field state machine: two second-threads arriving
# concurrently must INTERSECT their locksets, not overwrite each other's.
# Deliberately a RAW lock, not make_lock: a traced lock acquired inside
# _on_access would push onto the held stack mid-access and pollute every
# candidate lockset with itself.
_state_mu = threading.Lock()  # corelint: disable=raw-lock -- must stay invisible to the held stack it samples
# classes that asked for instrumentation: cls -> ignore frozenset
_registered: Dict[type, frozenset] = {}
# instrumented classes -> (prev __setattr__, prev __getattribute__) from
# cls.__dict__ (None = inherited, restore by deletion)
_instrumented: Dict[type, Tuple[Optional[object], Optional[object]]] = {}
_tls = threading.local()

_HISTORY_CAP = 16        # lockset-history entries kept per field
_STATE_ATTR = "_race_fields_"

_EXCLUSIVE, _SHARED, _SHARED_MOD = "exclusive", "shared", "shared-modified"


class DataRaceError(AssertionError):
    """A second thread wrote a field whose candidate lockset is empty."""


class _FieldState:
    __slots__ = ("state", "owner_ident", "owner_name", "lockset",
                 "history", "reported", "epoch")

    def __init__(self, owner_ident: int, owner_name: str, epoch: int):
        self.state = _EXCLUSIVE
        self.owner_ident = owner_ident
        self.owner_name = owner_name
        self.lockset: Optional[set] = None   # None until first 2nd-thread access
        # newest-first post-mortem: the racing access itself must be in
        # the bundle, so the deque evicts the OLDEST entries
        self.history: deque = deque(maxlen=_HISTORY_CAP)
        self.reported = False
        self.epoch = epoch


def enable() -> None:
    """Instrument every registered class from now on.  Call BEFORE the
    code under test creates its locks/objects (same ordering contract as
    lockorder.enable).  Starts a fresh epoch: field state tracked by an
    earlier enable() is re-owned on first access, because ownership may
    have legitimately moved while tracing was off."""
    global _enabled, _epoch
    _epoch += 1
    _enabled = True
    lockorder.enable()
    for cls in list(_registered):
        _instrument(cls)


def disable() -> None:
    """De-instrument every class.  Per-instance field state is left on
    the instances but carries the old epoch, so a later enable() re-owns
    it instead of trusting stale ownership."""
    global _enabled
    _enabled = False
    for cls in list(_instrumented):
        _deinstrument(cls)


def enabled() -> bool:
    return _enabled


def race_checked(cls: Optional[type] = None, *, ignore: Tuple[str, ...] = ()):
    """Class decorator opting into the race sanitizer.

    ``ignore`` names fields excluded from tracking (use sparingly, with
    the static ``# corelint: owned-by=`` annotation carrying the reason).
    With tracing off this returns ``cls`` unchanged — zero overhead.
    A ``__slots__`` class must list ``_race_fields_`` in its slots, or
    its fields silently go untracked (nowhere to hang the state).
    """
    def wrap(c: type) -> type:
        _registered[c] = frozenset(ignore)
        if _enabled:
            _instrument(c)
        return c
    return wrap if cls is None else wrap(cls)


# ---------------------------------------------------------------------------
# instrumentation
# ---------------------------------------------------------------------------

def _instrument(cls: type) -> None:
    if cls in _instrumented:
        return
    _instrumented[cls] = (cls.__dict__.get("__setattr__"),
                          cls.__dict__.get("__getattribute__"))
    base_set = cls.__setattr__      # resolved through the MRO, pre-wrap
    base_get = cls.__getattribute__

    def __setattr__(self, name, value, _base=base_set):
        _on_access(self, name, True)
        _base(self, name, value)

    def __getattribute__(self, name, _base=base_get):
        value = _base(self, name)
        if name.startswith("_race") or name.startswith("__"):
            return value
        try:
            d = object.__getattribute__(self, "__dict__")
        except AttributeError:
            d = None                 # __slots__ class
        # instance fields only, never methods: dict membership for
        # ordinary classes, a member descriptor for __slots__ ones
        if (d is not None and name in d) or (
                d is None and isinstance(
                    getattr(type(self), name, None),
                    types.MemberDescriptorType)):
            _on_access(self, name, False)
        return value

    cls.__setattr__ = __setattr__
    cls.__getattribute__ = __getattribute__


def _deinstrument(cls: type) -> None:
    prev_set, prev_get = _instrumented.pop(cls)
    if prev_set is None:
        del cls.__setattr__
    else:
        cls.__setattr__ = prev_set
    if prev_get is None:
        del cls.__getattribute__
    else:
        cls.__getattribute__ = prev_get


# ---------------------------------------------------------------------------
# the lockset state machine
# ---------------------------------------------------------------------------

def _on_access(obj, name: str, is_write: bool) -> None:
    if not _enabled or name.startswith("__"):
        return
    if getattr(_tls, "busy", False):
        # re-entrancy latch: reporting/bundle assembly touches decorated
        # objects (the flight recorder IS one) — those accesses are the
        # sanitizer's own, not the program's
        return
    ignore = type(obj).__dict__.get("_race_ignore_cache_")
    if ignore is None:
        ignore = _ignore_for(type(obj))
    if name in ignore:
        return
    _tls.busy = True
    try:
        me = threading.get_ident()
        report = None
        # the state machine runs under _state_mu: concurrent second
        # threads must intersect locksets, not overwrite each other's
        # (held_locks() only reads a thread-local — safe under the mutex)
        with _state_mu:
            try:
                fields = object.__getattribute__(obj, _STATE_ATTR)
            except AttributeError:
                fields = {}
                try:
                    object.__setattr__(obj, _STATE_ATTR, fields)
                except AttributeError:
                    return           # __slots__ instance: nowhere to track
            st = fields.get(name)
            if st is None or st.epoch != _epoch:
                fields[name] = _FieldState(
                    me, threading.current_thread().name, _epoch)
                return
            if st.state == _EXCLUSIVE and st.owner_ident == me:
                return               # init-then-publish: no obligation yet
            held = lockorder.held_locks()
            if st.state == _EXCLUSIVE:
                # second thread arrived: the candidate lockset is born
                st.lockset = set(held)
                st.state = _SHARED_MOD if is_write else _SHARED
            else:
                st.lockset &= set(held)
                if is_write:
                    st.state = _SHARED_MOD
            st.history.append({
                "thread": threading.current_thread().name,
                "op": "write" if is_write else "read",
                "held": list(held),
                "lockset": sorted(st.lockset),
            })
            if is_write and st.owner_ident != me and not st.lockset \
                    and not st.reported:
                st.reported = True
                report = st
        if report is not None:
            # raised OUTSIDE _state_mu: bundle assembly walks decorated
            # objects and must not nest under the state lock
            _report(obj, name, report)
    finally:
        _tls.busy = False


def _ignore_for(cls: type) -> frozenset:
    """Union of every registered ancestor's ignore set, cached on the
    class (decorated subclasses of decorated classes compose)."""
    out = frozenset()
    for c in cls.__mro__:
        out |= _registered.get(c, frozenset())
    cls._race_ignore_cache_ = out
    return out


def _report(obj, name: str, st: _FieldState) -> None:
    """Fail-stop with a post-mortem: the race becomes a flight event and
    a crash bundle before the raise (the lock-order tracer's discipline —
    an attributed failure beats a corrupted queue)."""
    writer = threading.current_thread().name
    stack = "".join(traceback.format_stack(limit=12)[:-2])
    msg = (f"data race on {type(obj).__name__}.{name}: write from thread "
           f"'{writer}' with empty lockset (field first owned by "
           f"'{st.owner_name}'); lockset history: {list(st.history)}")
    try:
        from . import eventlog
        eventlog.record("Process", "ERROR", "data race detected",
                        field=f"{type(obj).__name__}.{name}",
                        writer=writer, owner=st.owner_name,
                        lockset_history=list(st.history),
                        writer_stack=stack)
        eventlog.write_crash_bundle(f"DataRaceError: {msg}")
    except Exception:  # corelint: disable=exception-hygiene -- the fail-stop below must never be masked by dump plumbing
        pass
    raise DataRaceError(msg)


def field_state(obj, name: str) -> Optional[dict]:
    """Introspection for tests/diagnostics: the field's current Eraser
    state, or None if never tracked."""
    try:
        st = object.__getattribute__(obj, _STATE_ATTR).get(name)
    except AttributeError:
        return None
    if st is None:
        return None
    return {"state": st.state, "owner": st.owner_name,
            "lockset": sorted(st.lockset) if st.lockset is not None
            else None,
            "history": list(st.history)}
