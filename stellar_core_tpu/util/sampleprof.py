"""Always-on sampling profiler: periodic thread-stack sampling into
per-subsystem self-time buckets and folded flamegraph stacks.

Reference shape: the reference ships LogSlowExecution + medida timers —
aggregate latencies with no attribution of where wall time actually
went.  This module answers "which subsystem is this node burning CPU
in" continuously and cheaply enough to leave on for a whole soak:

- a daemon thread wakes ~67 times/second (``STPU_SAMPLEPROF_HZ``) and
  snapshots every thread's Python stack via ``sys._current_frames()`` —
  no signals (SIGPROF only reaches the main thread and is unusable
  under embedded interpreters), no per-call instrumentation;
- each sample attributes the LEAF frame's module path to a subsystem
  bucket (``stellar_core_tpu/<pkg>/...`` → ``<pkg>``; everything else →
  ``other``) — self-time, not cumulative, so the buckets sum to the
  sampled wall time;
- whole stacks aggregate into bounded folded-stack counts
  (``a;b;c <n>`` — feed to any flamegraph renderer).

Exported at the ``/profile`` admin endpoint; the folded stacks ride
along in every crash bundle (a registered util/eventlog bundle source)
so a post-mortem shows where the node was spending CPU when it died.
``STPU_SAMPLEPROF=1`` starts the profiler at Application startup;
overhead is asserted < 5% on the replay microbench (bench.py
``sampleprof`` row).
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional

from .lockorder import make_lock
from .metrics import registry as _registry

DEFAULT_HZ = 67.0          # deliberately co-prime-ish with 10ms timers
MAX_STACK_DEPTH = 48       # frames kept per folded stack
MAX_FOLDED_STACKS = 2000   # unique stacks kept; overflow → dropped


def _subsystem_of(filename: str) -> str:
    """Map a code object's file path to its bucket: the package directly
    under stellar_core_tpu/ (util, herder, ledger, catchup, overlay,
    bucket, history, main, simulation, ...); anything outside the tree
    (stdlib, site-packages, test files) is ``other``."""
    parts = filename.replace("\\", "/").split("/")
    try:
        i = len(parts) - 1 - parts[::-1].index("stellar_core_tpu")
    except ValueError:
        return "other"
    if i + 1 >= len(parts):
        return "other"
    nxt = parts[i + 1]
    return nxt[:-3] if nxt.endswith(".py") else nxt


class SamplingProfiler:
    """The process sampler.  start()/stop() are idempotent; all mutable
    state is guarded by one leaf lock (the sampler thread writes, admin
    /profile + crash bundles read)."""

    def __init__(self, hz: float = DEFAULT_HZ):
        self.hz = float(hz)
        self._lock = make_lock("sampleprof.state")
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._samples = 0
        self._dropped = 0
        self._subsystems: Dict[str, int] = {}
        self._folded: Dict[str, int] = {}
        # per-sample fast paths: filename -> subsystem memo (stacks
        # resample the same code objects thousands of times) and the
        # counter pair, re-resolved when tests swap the registry
        self._sub_cache: Dict[str, str] = {}
        self._counters = (None, None, None)  # (registry, samples, dropped)
        reg = _registry()
        reg.counter("profile.sampler.samples")
        reg.counter("profile.sampler.dropped")
        reg.weak_gauge("profile.sampler.running", self,
                       lambda p: 1.0 if p.running() else 0.0)

    def _counter_pair(self):
        reg = _registry()
        cached_reg, c_samples, c_dropped = self._counters
        if cached_reg is not reg:
            c_samples = reg.counter("profile.sampler.samples")
            c_dropped = reg.counter("profile.sampler.dropped")
            self._counters = (reg, c_samples, c_dropped)
        return c_samples, c_dropped

    # -- lifecycle ----------------------------------------------------------
    def running(self) -> bool:
        with self._lock:
            return self._thread is not None and self._thread.is_alive()

    def start(self) -> bool:
        """Start sampling; returns True if a new sampler thread was
        started, False if one was already running (idempotent)."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return False
            self._stop_evt = threading.Event()
            self._thread = threading.Thread(
                target=self._run, name="sampleprof", daemon=True)
            self._thread.start()
        from . import eventlog
        eventlog.register_bundle_source("profile", self.bundle)
        return True

    def stop(self) -> bool:
        """Stop sampling; returns True if a running sampler was stopped
        (idempotent — stopping a stopped profiler is a no-op)."""
        with self._lock:
            t = self._thread
            if t is None:
                return False
            self._stop_evt.set()
            self._thread = None
        t.join(timeout=2.0)
        from . import eventlog
        eventlog.unregister_bundle_source("profile")
        return True

    # -- sampling loop ------------------------------------------------------
    def _run(self) -> None:
        interval = 1.0 / self.hz
        own = threading.get_ident()
        with self._lock:
            evt = self._stop_evt
        while not evt.wait(interval):
            self._sample_once(own)

    def _sample_once(self, skip_tid: int) -> None:
        frames = sys._current_frames()
        dropped = 0
        with self._lock:
            c_samples, c_dropped = self._counter_pair()
            sub_cache = self._sub_cache
            for tid, frame in frames.items():
                if tid == skip_tid:
                    continue
                # leaf-frame self-time bucket (filename memoized — stacks
                # resample the same code objects thousands of times)
                fn = frame.f_code.co_filename
                sub = sub_cache.get(fn)
                if sub is None:
                    sub = _subsystem_of(fn)
                    if len(sub_cache) < 4096:
                        sub_cache[fn] = sub
                self._subsystems[sub] = self._subsystems.get(sub, 0) + 1
                self._samples += 1
                # folded stack, root-first
                names: List[str] = []
                f = frame
                depth = 0
                while f is not None and depth < MAX_STACK_DEPTH:
                    names.append(f.f_code.co_name)
                    f = f.f_back
                    depth += 1
                folded = ";".join(reversed(names))
                if folded in self._folded:
                    self._folded[folded] += 1
                elif len(self._folded) < MAX_FOLDED_STACKS:
                    self._folded[folded] = 1
                else:
                    self._dropped += 1
                    dropped += 1
        n = len(frames) - (1 if skip_tid in frames else 0)
        if n > 0:
            c_samples.inc(n)
        if dropped:
            c_dropped.inc(dropped)

    # -- readers ------------------------------------------------------------
    def snapshot(self) -> dict:
        """The /profile document: per-subsystem self-time (sample counts
        and estimated seconds at the configured rate) plus the heaviest
        folded stacks."""
        with self._lock:
            samples = self._samples
            dropped = self._dropped
            subs = dict(self._subsystems)
            top = sorted(self._folded.items(),
                         key=lambda kv: -kv[1])[:50]
        return {
            "running": self.running(),
            "hz": self.hz,
            "samples": samples,
            "dropped_stacks": dropped,
            "subsystems": {
                name: {"samples": n,
                       "self_s": round(n / self.hz, 3)}
                for name, n in sorted(subs.items(),
                                      key=lambda kv: -kv[1])},
            "top_stacks": [{"stack": s, "count": c} for s, c in top],
        }

    def folded(self) -> str:
        """Folded-stack dump, one ``frame;frame;frame count`` line each —
        the flamegraph.pl / speedscope input format."""
        with self._lock:
            items = sorted(self._folded.items())
        return "\n".join(f"{stack} {count}" for stack, count in items)

    def bundle(self) -> dict:
        """Crash-bundle source: compact profile + folded stacks."""
        snap = self.snapshot()
        return {"hz": snap["hz"], "samples": snap["samples"],
                "subsystems": snap["subsystems"],
                "folded": self.folded()}

    def reset(self) -> None:
        with self._lock:
            self._samples = 0
            self._dropped = 0
            self._subsystems.clear()
            self._folded.clear()


_profiler: Optional[SamplingProfiler] = None
_profiler_lock = make_lock("sampleprof.singleton")


def profiler() -> SamplingProfiler:
    """The process-wide sampler (created on first use, stopped)."""
    global _profiler
    with _profiler_lock:
        if _profiler is None:
            hz = float(os.environ.get("STPU_SAMPLEPROF_HZ", DEFAULT_HZ))
            _profiler = SamplingProfiler(hz=hz)
        return _profiler


def start_if_configured() -> bool:
    """``STPU_SAMPLEPROF=1`` (or any truthy value) starts the sampler —
    called from Application startup; safe to call repeatedly."""
    flag = os.environ.get("STPU_SAMPLEPROF", "")
    if flag.lower() in ("", "0", "false", "off", "no"):
        return False
    return profiler().start()
