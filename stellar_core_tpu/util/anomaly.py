"""Adaptive anomaly baselines: EWMA mean + MAD band regression alerts.

Fixed SLO thresholds (util/slo) catch "worse than the contract"; this
module catches "worse than *yourself*" — the leading indicator.  Each
tracked series keeps an exponentially-weighted mean and an
exponentially-weighted mean absolute deviation (a robust stand-in for
the MAD proper that needs no sample window); the healthy band is
``mean ± k·max(ewmad, floor)``.  A value outside the band on the bad
side is a *breach*; ``breach_n`` consecutive breaches flip the series
ACTIVE (sustained departure, not a one-tick spike), ``clear_n``
consecutive in-band evaluations flip it back.  The baseline only adapts
on in-band samples once warmed up — otherwise a sustained regression
drags its own baseline along and self-clears without recovering.

On detection the detector records an ``anomaly-detected`` flight event,
bumps ``anomaly.active.<series>`` (weak gauges; ``anomaly.active`` is
the total), and writes an **anomaly bundle**: the breaching time-series
window (util/timeseries), the surrounding CloseCostRecords
(ledger/costs) and the sampling profiler's folded stacks — the
post-mortem a human would have assembled by hand, written at the moment
the regression is still live.  ``anomaly-cleared`` closes the episode.

Two feeding modes share the state machine: ``evaluate()`` pulls the
live registry on the Application's timer (outside detguard regions,
observability-plane exemption), and ``observe()`` pushes explicit
values — how FleetScraper runs one detector per scraped node.
SLOTracker consumes ``active()`` as its leading indicator.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from .clock import monotonic_now, wall_now
from .lockorder import make_lock
from .metrics import registry as _registry
from .racetrace import race_checked

# Bundles written per ACTIVE episode (one at detection, not per eval)
BUNDLE_TS_WINDOW = 64        # breaching time-series ticks shipped
BUNDLE_COST_ROWS = 64        # surrounding CloseCostRecords shipped


@dataclass(frozen=True)
class TrackedSeries:
    """One adaptively-baselined series.

    ``direction`` is the BAD side: "high" flags upward departures
    (latencies, stall times), "low" flags downward ones (hit rates,
    throughput).  ``floor`` is a minimum band half-width in the value's
    own units so a near-constant warm-up (MAD ~ 0) doesn't make every
    later wiggle an anomaly."""
    name: str                 # kebab-case; becomes anomaly.active.<name>
    metric: str               # registry name, e.g. "ledger.ledger.close"
    field: str                # snapshot field, e.g. "p99_s"
    direction: str = "high"   # "high" | "low"
    k: float = 5.0            # band half-width in EWMA-MADs
    floor: float = 0.0        # minimum band half-width (value units)
    min_samples: int = 8      # baseline warm-up before any flagging
    breach_n: int = 3         # consecutive breaches to flag
    clear_n: int = 3          # consecutive in-band evals to clear


class _SeriesState:
    __slots__ = ("n", "mean", "ewmad", "breaches", "clears", "active",
                 "last_value", "last_band", "episodes")

    def __init__(self) -> None:
        self.n = 0
        self.mean = 0.0
        self.ewmad = 0.0
        self.breaches = 0
        self.clears = 0
        self.active = False
        self.last_value: Optional[float] = None
        self.last_band: Optional[float] = None
        self.episodes = 0


@race_checked
class AnomalyDetector:
    """Per-series EWMA/MAD state machine.  Thread-safe: the evaluation
    timer, FleetScraper sweeps and admin /metrics gauge reads may
    interleave — every state access is under ``_lock``; flight events
    and bundle writes happen OUTSIDE it (eventlog's lock is a leaf)."""

    def __init__(self, tracked: List[TrackedSeries],
                 alpha: float = 0.2,
                 timeseries: Optional[Callable[[], object]] = None,
                 closecosts: Optional[Callable[[], object]] = None,
                 source: str = "local",
                 register_gauges: bool = True) -> None:
        self.tracked = list(tracked)
        self.alpha = alpha
        self.source = source
        # zero-arg providers so the detector never pins the app graph
        # (Application wires weakref-backed lambdas)
        self._timeseries = timeseries
        self._closecosts = closecosts
        self._lock = make_lock("anomaly.detector")
        self._states: Dict[str, _SeriesState] = {
            t.name: _SeriesState() for t in self.tracked}
        self._by_name: Dict[str, TrackedSeries] = {
            t.name: t for t in self.tracked}
        # cache.hit/.miss lifetime counts from the previous evaluation —
        # the derived hit-rate series is computed over per-eval deltas
        self._cache_prev: Optional[tuple] = None
        self._bundle_n = 0
        if register_gauges:
            reg = _registry()
            reg.counter("anomaly.flags")
            reg.counter("anomaly.clears")
            reg.weak_gauge("anomaly.active", self,
                           AnomalyDetector.active_count)
            for t in self.tracked:
                reg.weak_gauge(f"anomaly.active.{t.name}", self,
                               _active_gauge_source(t.name))

    # -- state machine ------------------------------------------------------
    def _observe_locked(self, t: TrackedSeries, st: _SeriesState,
                        value: float) -> Optional[bool]:
        """Returns True/False when the ACTIVE latch flips, else None."""
        st.last_value = value
        if st.n < t.min_samples:
            # warm-up: adapt unconditionally, never flag
            self._adapt_locked(st, value)
            st.n += 1
            st.last_band = t.k * max(st.ewmad, t.floor)
            return None
        band = t.k * max(st.ewmad, t.floor)
        st.last_band = band
        if t.direction == "high":
            breached = value > st.mean + band
        else:
            breached = value < st.mean - band
        flip: Optional[bool] = None
        if breached:
            st.breaches += 1
            st.clears = 0
            if not st.active and st.breaches >= t.breach_n:
                st.active = True
                st.episodes += 1
                flip = True
        else:
            st.clears += 1
            st.breaches = 0
            # adapt only in-band: a sustained regression must not drag
            # its own baseline along and silently self-clear
            self._adapt_locked(st, value)
            st.n += 1
            if st.active and st.clears >= t.clear_n:
                st.active = False
                flip = False
        return flip

    def _adapt_locked(self, st: _SeriesState, value: float) -> None:
        if st.n == 0:
            st.mean = value
            st.ewmad = 0.0
            return
        dev = abs(value - st.mean)
        st.mean += self.alpha * (value - st.mean)
        st.ewmad += self.alpha * (dev - st.ewmad)

    # -- feeding ------------------------------------------------------------
    def observe(self, name: str, value: float) -> bool:
        """Push one sample into a tracked series (FleetScraper mode).
        Returns the series' ACTIVE state after the sample."""
        t = self._by_name[name]
        with self._lock:
            st = self._states[name]
            flip = self._observe_locked(t, st, float(value))
            active = st.active
        if flip is not None:
            self._emit([(t, self._snap_state(name), flip)])
        return active

    def evaluate(self, snapshot: Optional[Dict[str, dict]] = None,
                 now: Optional[float] = None) -> Dict[str, bool]:
        """Pull mode: evaluate every tracked series against a registry
        snapshot (defaulting to the live registry).  Series whose
        metric/field is absent are SKIPPED — a node with no admission
        pipeline must not warm an admission baseline on nulls."""
        if snapshot is None:
            snapshot = _registry().snapshot()
        snapshot = dict(snapshot)
        self._inject_derived(snapshot)
        flips: List[tuple] = []
        out: Dict[str, bool] = {}
        with self._lock:
            for t in self.tracked:
                snap = snapshot.get(t.metric)
                if snap is None:
                    continue
                value = snap.get(t.field)
                if value is None:
                    continue
                st = self._states[t.name]
                flip = self._observe_locked(t, st, float(value))
                if flip is not None:
                    flips.append((t, None, flip))
                out[t.name] = st.active
        if flips:
            self._emit([(t, self._snap_state(t.name), flip)
                        for t, _, flip in flips])
        return out

    def _inject_derived(self, snapshot: Dict[str, dict]) -> None:
        """Synthesize the entry-cache hit-rate series from the hit/miss
        lifetime counters (per-evaluation deltas; no traffic = skip)."""
        hit = snapshot.get("bucketlistdb.cache.hit")
        miss = snapshot.get("bucketlistdb.cache.miss")
        if hit is None or miss is None:
            return
        cur = (hit.get("count", 0), miss.get("count", 0))
        with self._lock:
            prev = self._cache_prev
            self._cache_prev = cur
        if prev is None:
            return
        dh, dm = cur[0] - prev[0], cur[1] - prev[1]
        if dh + dm <= 0 or dh < 0 or dm < 0:
            return
        snapshot["bucketlistdb.cache.hit-rate"] = {
            "type": "gauge", "value": dh / (dh + dm)}

    # -- episode plumbing ---------------------------------------------------
    def _snap_state(self, name: str) -> dict:
        with self._lock:
            st = self._states[name]
            return {"value": st.last_value, "mean": round(st.mean, 6),
                    "band": round(st.last_band or 0.0, 6),
                    "episodes": st.episodes}

    def _emit(self, flips: List[tuple]) -> None:
        """Flight events + bundle writes for latch flips — OUTSIDE the
        detector lock (eventlog's is a leaf; bundle writes do file IO)."""
        from . import eventlog
        reg = _registry()
        for t, state, became_active in flips:
            if became_active:
                reg.counter("anomaly.flags").inc()
                bundle_path = None
                try:
                    bundle_path = self.write_bundle(
                        t.name, reason="anomaly-detected")
                except Exception:  # corelint: disable=exception-hygiene -- a failed dump must not mask the detection event
                    pass
                eventlog.record(
                    "Perf", "WARNING", "anomaly-detected",
                    series=t.name, metric=t.metric, field=t.field,
                    source=self.source, bundle=bundle_path, **state)
            else:
                reg.counter("anomaly.clears").inc()
                eventlog.record(
                    "Perf", "INFO", "anomaly-cleared",
                    series=t.name, metric=t.metric, field=t.field,
                    source=self.source, **state)

    def write_bundle(self, series_name: str,
                     reason: str = "manual",
                     out_dir: Optional[str] = None) -> str:
        """Write the anomaly bundle for one series: breaching
        time-series window + surrounding CloseCostRecords + profiler
        folded stacks.  Returns the path written."""
        t = self._by_name[series_name]
        doc = {"kind": "anomaly-bundle", "series": series_name,
               "metric": t.metric, "field": t.field,
               "reason": reason, "source": self.source,
               "wall_time": wall_now(),
               "state": self._snap_state(series_name)}
        ts = self._timeseries() if self._timeseries else None
        if ts is not None:
            doc["timeseries"] = {
                t.metric: ts.window(t.metric, BUNDLE_TS_WINDOW)}
        cc = self._closecosts() if self._closecosts else None
        if cc is not None:
            doc["closecosts"] = cc.recent(BUNDLE_COST_ROWS)
        from . import sampleprof
        prof = sampleprof.profiler()
        if prof.running():
            doc["profile_folded"] = prof.folded()
        if out_dir is None:
            out_dir = os.environ.get("STPU_CRASH_DIR", ".")
        os.makedirs(out_dir, exist_ok=True)
        with self._lock:
            self._bundle_n += 1
            n = self._bundle_n
        path = os.path.join(
            out_dir, f"anomaly-{series_name}-{os.getpid()}-{n}.json")
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path

    # -- readers ------------------------------------------------------------
    def active(self) -> List[str]:
        """Names of currently-ACTIVE series (SLOTracker's leading
        indicator; sorted for determinism)."""
        with self._lock:
            return sorted(n for n, st in self._states.items()
                          if st.active)

    def active_count(self) -> int:
        with self._lock:
            return sum(1 for st in self._states.values() if st.active)

    def is_active(self, name: str) -> bool:
        with self._lock:
            return self._states[name].active

    def report(self) -> dict:
        """Per-series verdicts (the fleet scraper's per-node doc and the
        'anomaly' flight-bundle source)."""
        series = {}
        with self._lock:
            for t in self.tracked:
                st = self._states[t.name]
                series[t.name] = {
                    "metric": t.metric, "field": t.field,
                    "direction": t.direction,
                    "active": st.active,
                    "episodes": st.episodes,
                    "samples": st.n,
                    "mean": round(st.mean, 6),
                    "band": round(st.last_band or 0.0, 6),
                    "last_value": st.last_value,
                }
        return {"source": self.source, "series": series,
                "active": sorted(n for n, d in series.items()
                                 if d["active"])}


def _active_gauge_source(name: str):
    def read(det: "AnomalyDetector") -> float:
        return 1.0 if det.is_active(name) else 0.0
    return read


def default_tracked() -> List[TrackedSeries]:
    """The node's standing regression watches: close p99, admission
    latency, merge stall, entry-cache hit rate (the four axes ROADMAP
    item 4's read-serving soak degrades first)."""
    return [
        TrackedSeries("close-p99", "ledger.ledger.close", "p99_s",
                      direction="high", floor=0.005),
        TrackedSeries("admission-latency", "herder.admission.latency",
                      "p99_s", direction="high", floor=0.005),
        TrackedSeries("merge-stall", "bucket.merge.stall", "p99_s",
                      direction="high", floor=0.002),
        TrackedSeries("cache-hit-rate", "bucketlistdb.cache.hit-rate",
                      "value", direction="low", floor=0.05),
    ]
