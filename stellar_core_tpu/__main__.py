import sys

from .main.commandline import main

sys.exit(main())
