"""Deterministic fuzz harnesses for the tx-apply engine and the overlay.

Reference: src/test/fuzz.{h,cpp} + FuzzerImpl.{h,cpp} — stellar-core ships
two AFL-style persistent fuzz targets: `TransactionFuzzer` (XDR-mutated
operations applied against a small prepared ledger universe) and
`OverlayFuzzer` (mutated wire bytes fed to a peer connection).  This module
is the same idea with a seeded PRNG instead of AFL (no corpus/coverage
feedback in this environment): every crash is a genuine finding because the
engine's contract is that arbitrary input produces a result code or a
controlled drop — never an unhandled exception.

CLI: ``python -m stellar_core_tpu fuzz --mode tx|overlay|xdr --iters N``.
"""

from __future__ import annotations

import enum
import random
from typing import List, Optional, Tuple

from . import xdr as X
from .crypto.keys import SecretKey
from .util import logging as slog
from .xdr import codec as C

log = slog.get("Fuzz")


# ---------------------------------------------------------------------------
# generic structured-random XDR generation (the mutation engine)
# ---------------------------------------------------------------------------

_INTERESTING_INTS = (0, 1, -1, 2, 7, 100, 255, 256, 2**31 - 1, -2**31,
                     2**32 - 1, 2**63 - 1, -2**63, 10**7, 10**15)


def random_xdr_value(t, rng: random.Random, depth: int = 0):
    """Generate a random instance of any declared XDR type by introspecting
    the codec adapters — every struct field, union arm and array length is
    reachable.  Depth-bounded so recursive types (SCPQuorumSet) terminate."""
    t = C._as_type(t)
    if isinstance(t, C._EnumAdapter):
        return rng.choice(list(t.enum_cls))
    if isinstance(t, C.Opaque):
        return bytes(rng.getrandbits(8) for _ in range(t.n))
    if isinstance(t, (C.VarOpaque, C.XdrString)):
        if isinstance(t, C.XdrString):
            t = t._op
        n = rng.randrange(min(t.max_len, 64) + 1)
        return bytes(rng.getrandbits(8) for _ in range(n))
    if isinstance(t, C.FixedArray):
        return [random_xdr_value(t.elem, rng, depth + 1)
                for _ in range(t.n)]
    if isinstance(t, C.VarArray):
        cap = 0 if depth > 4 else min(t.max_len, 3)
        return [random_xdr_value(t.elem, rng, depth + 1)
                for _ in range(rng.randrange(cap + 1))]
    if isinstance(t, C.Optional):
        if depth > 4 or rng.random() < 0.5:
            return None
        return random_xdr_value(t.elem, rng, depth + 1)
    if isinstance(t, C._StructAdapter):
        return t.cls(**{fname: random_xdr_value(ftype, rng, depth + 1)
                        for fname, ftype in t.cls._spec})
    if isinstance(t, C._UnionAdapter):
        arms = list(t.cls._arms.items())
        sw, (name, arm_t) = rng.choice(arms)
        val = None if arm_t is None else random_xdr_value(arm_t, rng,
                                                          depth + 1)
        return t.cls(sw, val)
    if isinstance(t, C._Void):
        return None
    # forward-reference wrappers (recursive types like SCPQuorumSet)
    target = getattr(t, "_target", None)
    if target is not None:
        if depth > 5:
            # bottom out: a leaf instance with no recursion
            return random_xdr_value(target, rng, depth + 10)
        return random_xdr_value(target, rng, depth + 1)
    # integer primitives
    if isinstance(t, (C._Uint32,)):
        return rng.choice(_INTERESTING_INTS) % 2**32 \
            if rng.random() < 0.5 else rng.getrandbits(32)
    if isinstance(t, (C._Uint64,)):
        return rng.choice(_INTERESTING_INTS) % 2**64 \
            if rng.random() < 0.5 else rng.getrandbits(64)
    if isinstance(t, (C._Int32,)):
        v = rng.choice(_INTERESTING_INTS) if rng.random() < 0.5 \
            else rng.getrandbits(32) - 2**31
        return max(-2**31, min(2**31 - 1, v))
    if isinstance(t, (C._Int64,)):
        v = rng.choice(_INTERESTING_INTS) if rng.random() < 0.5 \
            else rng.getrandbits(64) - 2**63
        return max(-2**63, min(2**63 - 1, v))
    if isinstance(t, C._Bool):
        return bool(rng.getrandbits(1))
    raise TypeError(f"random_xdr_value: unhandled type {t!r}")


def mutate_bytes(data: bytes, rng: random.Random) -> bytes:
    """AFL-style byte mutations: flips, splices, truncation, extension."""
    buf = bytearray(data)
    for _ in range(rng.randrange(1, 8)):
        choice = rng.random()
        if not buf or choice < 0.5:
            if buf:
                buf[rng.randrange(len(buf))] ^= 1 << rng.randrange(8)
        elif choice < 0.7:
            pos = rng.randrange(len(buf) + 1)
            buf[pos:pos] = bytes(rng.getrandbits(8)
                                 for _ in range(rng.randrange(1, 5)))
        elif choice < 0.9:
            pos = rng.randrange(len(buf))
            del buf[pos:pos + rng.randrange(1, 5)]
        else:
            buf = buf[:rng.randrange(len(buf) + 1)]
    return bytes(buf)


# ---------------------------------------------------------------------------
# transaction fuzzer
# ---------------------------------------------------------------------------

class TransactionFuzzer:
    """Apply structured-random / byte-mutated transactions against a small
    prepared ledger (reference: FuzzerImpl::TransactionFuzzer — initialize
    a universe of accounts, then inject mutated Operation XDR).  Invariants
    are ON: a fuzz case that corrupts state trips them and is a finding."""

    NUM_ACCOUNTS = 8

    def __init__(self, seed: int = 0):
        from .ledger.manager import LedgerManager
        from .testutils import TestAccount, build_tx, create_account_op

        self.rng = random.Random(seed ^ 0xF022)
        self.network_id = b"\x42" * 32
        self.mgr = LedgerManager(self.network_id)
        self.mgr.start_new_ledger()
        root_secret = self.mgr.root_account_secret()
        root_entry = self.mgr.root.get_entry(
            X.LedgerKey.account(X.LedgerKeyAccount(
                accountID=X.AccountID.ed25519(
                    root_secret.public_key.ed25519))).to_xdr())
        root = TestAccount(self.mgr, root_secret,
                           root_entry.data.value.seqNum)
        self.accounts: List[TestAccount] = []
        ops, secrets = [], []
        for i in range(self.NUM_ACCOUNTS):
            sk = SecretKey(bytes([0xA0 + i]) * 32)
            secrets.append(sk)
            ops.append(create_account_op(
                X.AccountID.ed25519(sk.public_key.ed25519), 10_000_000_000))
        arts = self.mgr.close_ledger([root.tx(ops)], close_time=1000)
        seq_base = self.mgr.last_closed_ledger_seq << 32
        for sk in secrets:
            self.accounts.append(TestAccount(self.mgr, sk, seq_base))
        self._build_tx = build_tx
        self.crashes: List[Tuple[str, BaseException]] = []

    def _rand_account(self):
        return self.rng.choice(self.accounts)

    def _remap_into_universe(self, op: X.Operation) -> X.Operation:
        """Point random account fields at fuzz-universe accounts some of the
        time (reference: FuzzerImpl remaps generated IDs into its small
        address space so ops hit real state instead of all-NO_ACCOUNT)."""
        body = op.body.value
        if body is None or self.rng.random() < 0.3:
            return op
        known = self._rand_account().account_id
        for fname in ("destination", "trustor", "accountID"):
            if hasattr(body, fname) and self.rng.random() < 0.7:
                cur = getattr(body, fname)
                if isinstance(cur, X.MuxedAccount) or (
                        hasattr(cur, "switch")
                        and type(cur).__name__ == "MuxedAccount"):
                    setattr(body, fname, X.muxed_from_account_id(known))
                elif type(cur).__name__ in ("AccountID", "PublicKey"):
                    setattr(body, fname, known)
        return op

    def one_case(self, i: int) -> None:
        rng = self.rng
        kind = rng.random()
        try:
            if kind < 0.55:
                # structured-random ops in a well-signed tx from a real
                # account — reaches the op-apply layer
                n_ops = rng.randrange(1, 4)
                ops = []
                for _ in range(n_ops):
                    op = random_xdr_value(X.Operation, rng)
                    ops.append(self._remap_into_universe(op))
                acct = self._rand_account()
                frame = self._build_tx(self.network_id, acct.secret,
                                       acct.next_seq(), ops)
                self.mgr.close_ledger([frame], close_time=2000 + i)
            elif kind < 0.8:
                # byte-mutated valid envelope — exercises decode + apply
                acct = self._rand_account()
                from .testutils import native_payment_op
                frame = self._build_tx(
                    self.network_id, acct.secret, acct.seq_num + 1,
                    [native_payment_op(self._rand_account().account_id,
                                       rng.randrange(1, 1000))])
                blob = mutate_bytes(frame.envelope.to_xdr(), rng)
                try:
                    env = X.TransactionEnvelope.from_xdr(blob)
                except C.XdrError:
                    return  # rejected at decode — controlled
                except OverflowError:
                    return  # length prefix beyond buffer — controlled
                frame2 = self.mgr.make_frame(env)
                self.mgr.close_ledger([frame2], close_time=2000 + i)
            else:
                # fully random envelope (usually fails sig/seq checks)
                env = random_xdr_value(X.TransactionEnvelope, rng)
                frame = self.mgr.make_frame(env)
                self.mgr.close_ledger([frame], close_time=2000 + i)
        except Exception as e:  # noqa: BLE001 — the fuzz oracle
            self.crashes.append((f"case {i}", e))
            log.error("tx fuzz crash at case %d: %r", i, e)

    def run(self, iters: int = 500) -> List[Tuple[str, BaseException]]:
        for i in range(iters):
            self.one_case(i)
        return self.crashes


# ---------------------------------------------------------------------------
# overlay fuzzer
# ---------------------------------------------------------------------------

class OverlayFuzzer:
    """Feed mutated wire bytes / structured-random messages into an
    authenticated loopback pair (reference: FuzzerImpl::OverlayFuzzer).
    The receiving node must drop the peer or ignore the message — any
    escaping exception is a finding."""

    def __init__(self, seed: int = 0):
        from .herder.herder import Herder
        from .ledger.manager import LedgerManager
        from .overlay.overlay_manager import OverlayManager
        from .overlay.peer import make_loopback_pair
        from .simulation.simulation import qset_of
        from .util.clock import ClockMode, VirtualClock

        self.rng = random.Random(seed ^ 0x0E21A7)
        self.clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        nid = b"\x77" * 32
        self.nodes = []
        sks = [SecretKey(bytes([0x61 + i]) * 32) for i in range(2)]
        qset = qset_of([sk.public_key.ed25519 for sk in sks], 2)
        for i, sk in enumerate(sks):
            lm = LedgerManager(nid)
            lm.start_new_ledger()
            herder = Herder(self.clock, lm, sk, qset)
            overlay = OverlayManager(self.clock, herder, nid, sk,
                                     auth_seed=bytes([0x51 + i]) * 32)
            self.nodes.append(overlay)
        self._pair = make_loopback_pair(*self.nodes)
        self._crank()
        assert self._pair[0].is_authenticated()
        self.crashes: List[Tuple[str, BaseException]] = []

    def _crank(self, n: int = 30) -> None:
        for _ in range(n):
            self.clock.crank()

    def _ensure_pair(self) -> None:
        from .overlay.peer import make_loopback_pair
        pa, pb = self._pair
        if not (pa.is_authenticated() and pb.is_authenticated()):
            self._pair = make_loopback_pair(*self.nodes)
            self._crank()

    def one_case(self, i: int) -> None:
        rng = self.rng
        self._ensure_pair()
        pa, pb = self._pair   # pa: node A's view (sender), pb: node B's
        try:
            choice = rng.random()
            if choice < 0.35:
                # raw garbage into the frame decoder
                blob = bytes(rng.getrandbits(8)
                             for _ in range(rng.randrange(1, 200)))
                pb.data_received(blob)
            elif choice < 0.6:
                # structured-random message through the real channel
                msg = random_xdr_value(X.StellarMessage, rng)
                try:
                    msg.to_xdr()
                except C.XdrError:
                    return
                pa.send_message(msg)
            else:
                # byte-mutated frame of a valid message
                msg = X.StellarMessage.getSCPLedgerSeq(rng.getrandbits(16))
                from .overlay.peer import frame_encode
                mac = X.HmacSha256Mac(mac=b"\x00" * 32)
                am = X.AuthenticatedMessage.v0(X.AuthenticatedMessageV0(
                    sequence=pb._recv_seq, message=msg, mac=mac))
                blob = mutate_bytes(frame_encode(am.to_xdr()), rng)
                pb.data_received(blob)
            self._crank(10)
        except Exception as e:  # noqa: BLE001
            self.crashes.append((f"case {i}", e))
            log.error("overlay fuzz crash at case %d: %r", i, e)

    def run(self, iters: int = 300) -> List[Tuple[str, BaseException]]:
        for i in range(iters):
            self.one_case(i)
        return self.crashes


# ---------------------------------------------------------------------------
# xdr round-trip fuzzer
# ---------------------------------------------------------------------------

def fuzz_xdr_roundtrip(seed: int = 0, iters: int = 2000) -> List[str]:
    """Every structured-random value must survive pack→unpack→pack
    byte-identically, and mutated bytes must either fail to parse or
    re-serialize canonically (the quiet risk SURVEY.md §7 flags: ledger
    hashes depend on byte-exact XDR)."""
    rng = random.Random(seed ^ 0xD8)
    roots = [X.TransactionEnvelope, X.LedgerEntry, X.StellarMessage,
             X.SCPEnvelope, X.LedgerHeader, X.BucketEntry]
    failures: List[str] = []
    for i in range(iters):
        cls = rng.choice(roots)
        val = random_xdr_value(cls, rng)
        try:
            blob = val.to_xdr()
        except C.XdrError:
            continue  # unrepresentable randoms (e.g. over-long) are fine
        back = cls.from_xdr(blob)
        if back.to_xdr() != blob:
            failures.append(f"case {i}: {cls.__name__} not canonical")
        mut = mutate_bytes(blob, rng)
        try:
            re_parsed = cls.from_xdr(mut)
        except (C.XdrError, OverflowError):
            continue  # rejected — controlled
        if re_parsed.to_xdr() != mut:
            # parsed-but-noncanonical mutants must NOT appear: unpack
            # enforces canonical form (padding, lengths); a mutant that
            # parses yet re-encodes differently would break content
            # addressing.  Trailing-byte truncation is the one allowed
            # case: from_xdr requires full consumption, so this is dead
            # unless a decoder bug exists.
            failures.append(f"case {i}: {cls.__name__} mutant "
                            "parsed non-canonically")
    return failures
