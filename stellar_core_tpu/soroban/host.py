"""Bounded deterministic Soroban host.

No wasm toolchain exists in this environment (SURVEY §2.4), so contracts
are drawn from a sanctioned table of BUILT-IN host functions selected by
``InvokeContractArgs.functionName`` — contract-data get/put/has/del/bump,
emit-event, checked arithmetic, sha256, and two adversarial helpers
(``fail`` traps, ``burn`` drains the cpu budget).  Every built-in runs
under a real resource Budget: each operation charges deterministic
cpu-instruction and memory costs up front, and the first charge past the
per-tx limit raises BudgetExceeded → the structured
RESOURCE_LIMIT_EXCEEDED result (fee charged, state untouched).

Determinism contract: host results depend only on (args, storage state,
budget limits) — no clocks, no iteration over unordered containers, no
float arithmetic — so serial and footprint-parallel apply produce
byte-identical results (asserted end-to-end in tests/test_soroban.py).
"""

from __future__ import annotations

import hashlib
from typing import Callable, Dict, List, Optional, Tuple

from .. import xdr as X

__all__ = ["Budget", "BudgetExceeded", "FootprintViolation", "HostError",
           "HOST_FUNCTIONS", "invoke_host_function", "result_hash"]


class HostError(Exception):
    """Structured host failure; `code` is the InvokeHostFunctionResultCode
    the op result carries (the tx fail-stops, the node does not)."""

    code = X.InvokeHostFunctionResultCode.INVOKE_HOST_FUNCTION_TRAPPED

    def __init__(self, msg: str, code=None):
        super().__init__(msg)
        if code is not None:
            self.code = code


class BudgetExceeded(HostError):
    code = X.InvokeHostFunctionResultCode.\
        INVOKE_HOST_FUNCTION_RESOURCE_LIMIT_EXCEEDED


class FootprintViolation(HostError):
    """Out-of-footprint access: the tx declared a footprint and touched a
    key outside it.  Fail-stops the TX (trap), never the node."""

    code = X.InvokeHostFunctionResultCode.INVOKE_HOST_FUNCTION_TRAPPED


class EntryArchived(HostError):
    code = X.InvokeHostFunctionResultCode.\
        INVOKE_HOST_FUNCTION_ENTRY_ARCHIVED


# ---------------------------------------------------------------------------
# Budget: cpu-instruction + memory metering
# ---------------------------------------------------------------------------

# Deterministic cost model (instruction / byte charges per host op).
# Values are scaled from soroban-env-host's calibrated cost types; the
# absolute numbers matter less than their being fixed and documented.
COST = {
    "dispatch": (500, 0),             # per host-function call
    "storage_read": (5_000, 0),       # + per-byte below
    "storage_write": (7_500, 0),
    "storage_has": (2_500, 0),
    "storage_del": (3_000, 0),
    "read_byte": (4, 1),              # per entry byte materialized
    "write_byte": (6, 1),
    "event": (2_000, 0),              # + per-byte of topics/data
    "event_byte": (4, 1),
    "u64_arith": (80, 0),
    "sha256_base": (3_000, 32),
    "sha256_byte": (30, 0),
    "scval_byte": (2, 1),             # per byte of SCVal (de)serialization
}


class Budget:
    """Per-transaction cpu-instruction and memory budget.  charge() is
    check-then-commit: a charge that would cross either limit raises
    BudgetExceeded WITHOUT recording partial spend, so the failure
    path is deterministic regardless of charge order granularity."""

    __slots__ = ("cpu_limit", "mem_limit", "cpu_used", "mem_used")

    def __init__(self, cpu_limit: int, mem_limit: int):
        self.cpu_limit = int(cpu_limit)
        self.mem_limit = int(mem_limit)
        self.cpu_used = 0
        self.mem_used = 0

    def charge(self, kind: str, units: int = 1) -> None:
        cpu, mem = COST[kind]
        ncpu = self.cpu_used + cpu * units
        nmem = self.mem_used + mem * units
        if ncpu > self.cpu_limit:
            raise BudgetExceeded(
                f"cpu budget exceeded: {ncpu} > {self.cpu_limit} ({kind})")
        if nmem > self.mem_limit:
            raise BudgetExceeded(
                f"mem budget exceeded: {nmem} > {self.mem_limit} ({kind})")
        self.cpu_used = ncpu
        self.mem_used = nmem

    def charge_raw(self, instructions: int) -> None:
        n = self.cpu_used + int(instructions)
        if n > self.cpu_limit:
            raise BudgetExceeded(
                f"cpu budget exceeded: {n} > {self.cpu_limit} (raw)")
        self.cpu_used = n


# ---------------------------------------------------------------------------
# SCVal argument helpers (strict: malformed args trap deterministically)
# ---------------------------------------------------------------------------

_U64_MAX = (1 << 64) - 1


def _want(args, n: int):
    if len(args) != n:
        raise HostError(f"expected {n} args, got {len(args)}")


def _as_u64(v) -> int:
    if v.switch != X.SCValType.SCV_U64:
        raise HostError(f"expected u64, got {v.switch!r}")
    return int(v.value)


def _as_sym(v) -> str:
    if v.switch != X.SCValType.SCV_SYMBOL:
        raise HostError(f"expected symbol, got {v.switch!r}")
    s = v.value
    return s.decode("ascii") if isinstance(s, bytes) else str(s)


def _as_bytes(v) -> bytes:
    if v.switch != X.SCValType.SCV_BYTES:
        raise HostError(f"expected bytes, got {v.switch!r}")
    return bytes(v.value)


def _durability(v):
    name = _as_sym(v)
    if name == "temp":
        return X.ContractDataDurability.TEMPORARY
    if name == "persistent":
        return X.ContractDataDurability.PERSISTENT
    raise HostError(f"bad durability symbol {name!r}")


def _u64(n: int):
    return X.SCVal.u64(n)


def _void():
    return X.SCVal.void()


# ---------------------------------------------------------------------------
# The built-in host-function table
# ---------------------------------------------------------------------------

def _fn_put(host, args):
    _want(args, 3)
    host.storage.put(args[0], _durability(args[2]), args[1])
    return _void()


def _fn_get(host, args):
    _want(args, 2)
    got = host.storage.get(args[0], _durability(args[1]))
    return got if got is not None else _void()


def _fn_has(host, args):
    _want(args, 2)
    return X.SCVal.b(host.storage.has(args[0], _durability(args[1])))


def _fn_del(host, args):
    _want(args, 2)
    host.storage.delete(args[0], _durability(args[1]))
    return _void()


def _fn_bump(host, args):
    """Read-modify-write a u64 counter (created at 0 when absent).  The
    workhorse of the loadgen mix: shared-counter traffic forces write-set
    overlap, so the footprint scheduler's clustering is exercised by
    REAL contention, not synthetic partitions."""
    _want(args, 3)
    dur = _durability(args[2])
    host.budget.charge("u64_arith")
    cur = host.storage.get(args[0], dur)
    base = 0 if cur is None or cur.switch != X.SCValType.SCV_U64 \
        else int(cur.value)
    n = (base + _as_u64(args[1])) & _U64_MAX
    host.storage.put(args[0], dur, _u64(n))
    return _u64(n)


def _fn_emit(host, args):
    _want(args, 2)
    host.emit_event(args[0], args[1])
    return _void()


def _fn_add(host, args):
    _want(args, 2)
    host.budget.charge("u64_arith")
    n = _as_u64(args[0]) + _as_u64(args[1])
    if n > _U64_MAX:
        raise HostError("u64 add overflow")
    return _u64(n)


def _fn_mul(host, args):
    _want(args, 2)
    host.budget.charge("u64_arith")
    n = _as_u64(args[0]) * _as_u64(args[1])
    if n > _U64_MAX:
        raise HostError("u64 mul overflow")
    return _u64(n)


def _fn_sha256(host, args):
    _want(args, 1)
    data = _as_bytes(args[0])
    host.budget.charge("sha256_base")
    host.budget.charge("sha256_byte", len(data))
    return X.SCVal.bytes(hashlib.sha256(data).digest())


def _fn_fail(host, args):
    raise HostError("contract called fail()")


def _fn_burn(host, args):
    """Spend `n` raw cpu instructions — the budget-differential helper:
    a burn past the declared instruction count MUST surface as the
    structured RESOURCE_LIMIT_EXCEEDED failure with state untouched."""
    _want(args, 1)
    host.budget.charge_raw(_as_u64(args[0]))
    return _void()


HOST_FUNCTIONS: Dict[str, Callable] = {
    "put": _fn_put,
    "get": _fn_get,
    "has": _fn_has,
    "del": _fn_del,
    "bump": _fn_bump,
    "emit": _fn_emit,
    "add": _fn_add,
    "mul": _fn_mul,
    "sha256": _fn_sha256,
    "fail": _fn_fail,
    "burn": _fn_burn,
}


class Host:
    """One invocation context: storage view + budget + event log."""

    def __init__(self, storage, budget: Budget, contract):
        self.storage = storage
        self.budget = budget
        self.contract = contract
        self.events: List[Tuple] = []

    def emit_event(self, topic, data) -> None:
        blob = topic.to_xdr() + data.to_xdr()
        self.budget.charge("event")
        self.budget.charge("event_byte", len(blob))
        self.events.append((self.contract, topic, data))


def invoke_host_function(invoke_args, storage, budget: Budget):
    """Execute one InvokeContractArgs against the built-in table.

    Returns (return_scval, events, host).  Raises HostError subclasses
    for every failure mode; callers map `.code` onto the op result."""
    name = invoke_args.functionName
    if isinstance(name, bytes):
        name = name.decode("ascii", "replace")
    fn = HOST_FUNCTIONS.get(name)
    if fn is None:
        raise HostError(f"unknown host function {name!r}")
    budget.charge("dispatch")
    for a in invoke_args.args:
        budget.charge("scval_byte", len(a.to_xdr()))
    host = Host(storage, budget, invoke_args.contractAddress)
    ret = fn(host, list(invoke_args.args))
    return ret, host.events, host


def result_hash(ret, events) -> bytes:
    """The success-arm Hash: sha256 over the XDR of the return value and
    every emitted event, in order — a deterministic commitment that the
    serial-vs-parallel differential can compare."""
    h = hashlib.sha256()
    h.update(ret.to_xdr())
    for contract, topic, data in events:
        h.update(contract.to_xdr())
        h.update(topic.to_xdr())
        h.update(data.to_xdr())
    return h.digest()
