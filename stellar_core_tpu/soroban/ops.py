"""The three Soroban operation frames.

Reference: src/transactions/InvokeHostFunctionOpFrame.cpp,
ExtendFootprintTTLOpFrame.cpp, RestoreFootprintOpFrame.cpp.  All three
run at LOW threshold and require protocol 20+ plus a Soroban tx
(exactly one op, SorobanTransactionData present — enforced at the
transaction level, see transactions/frame.py).

Failure discipline: every host failure maps to the op's structured
result code and the per-op LedgerTxn rolls back — fee charged, state
untouched, node unharmed.  Only genuine infrastructure bugs escape as
exceptions (and those fail-stop the node by design).
"""

from __future__ import annotations

from .. import xdr as X
from ..transactions.operations import (OperationFrame, register_op_class,
                                       THRESHOLD_LOW)
from ..util.metrics import registry as _registry
from .config import network_config
from .host import Budget, HostError, invoke_host_function, result_hash
from .storage import FootprintStorage, ttl_key_for_xdr, make_ttl_entry

OT = X.OperationType
IHC = X.InvokeHostFunctionResultCode
EXC = X.ExtendFootprintTTLResultCode
RSC = X.RestoreFootprintResultCode

SOROBAN_PROTOCOL_VERSION = 20

_DATA_KEY_TYPES = (X.LedgerEntryType.CONTRACT_DATA,
                   X.LedgerEntryType.CONTRACT_CODE)


class _SorobanOpFrame(OperationFrame):
    MIN_PROTOCOL_VERSION = SOROBAN_PROTOCOL_VERSION

    def threshold_level(self) -> int:
        return THRESHOLD_LOW

    def _soroban_data(self):
        return self.tx.soroban_data()


class InvokeHostFunctionOpFrame(_SorobanOpFrame):
    OP_TYPE = OT.INVOKE_HOST_FUNCTION
    RESULT_CLS = X.InvokeHostFunctionResult

    def do_check_valid(self, ltx):
        if self.body.hostFunction.switch != \
                X.HostFunctionType.HOST_FUNCTION_TYPE_INVOKE_CONTRACT:
            # upload/create need the wasm host; the bounded host only
            # dispatches invoke-contract (PARITY.md Soroban rows)
            return self.result(IHC.INVOKE_HOST_FUNCTION_MALFORMED)
        return self.success(b"\x00" * 32)

    def do_apply(self, ltx):
        sd = self._soroban_data()
        net = network_config()
        resources = sd.resources
        budget = Budget(
            cpu_limit=min(int(resources.instructions),
                          net.tx_max_instructions),
            mem_limit=net.tx_max_memory_bytes)
        invoke_args = self.body.hostFunction.value
        storage = FootprintStorage(
            ltx, invoke_args.contractAddress, resources, net, budget,
            ledger_seq=ltx.get_header().ledgerSeq)
        reg = _registry()
        try:
            with reg.timer("soroban.host.invoke").time():
                ret, events, _host = invoke_host_function(
                    invoke_args, storage, budget)
        except HostError as e:
            if e.code == IHC.INVOKE_HOST_FUNCTION_RESOURCE_LIMIT_EXCEEDED:
                reg.meter("soroban.host.budget-exceeded").mark()
            else:
                reg.meter("soroban.host.trap").mark()
            return self.result(e.code)
        reg.histogram("soroban.host.cpu-insns").update(budget.cpu_used)
        return self.success(result_hash(ret, events))


class ExtendFootprintTTLOpFrame(_SorobanOpFrame):
    OP_TYPE = OT.EXTEND_FOOTPRINT_TTL
    RESULT_CLS = X.ExtendFootprintTTLResult

    def do_check_valid(self, ltx):
        sd = self._soroban_data()
        fp = sd.resources.footprint
        if fp.readWrite or not fp.readOnly:
            # reference: extended keys ride in readOnly ONLY (the op
            # mutates TTL entries, never the data entries themselves)
            return self.result(EXC.EXTEND_FOOTPRINT_TTL_MALFORMED)
        if int(self.body.extendTo) > network_config().max_entry_ttl:
            return self.result(EXC.EXTEND_FOOTPRINT_TTL_MALFORMED)
        if any(k.switch not in _DATA_KEY_TYPES for k in fp.readOnly):
            return self.result(EXC.EXTEND_FOOTPRINT_TTL_MALFORMED)
        return self.success()

    def do_apply(self, ltx):
        sd = self._soroban_data()
        seq = int(ltx.get_header().ledgerSeq)
        extend_to = int(self.body.extendTo)
        read_bytes = 0
        for key in sorted(sd.resources.footprint.readOnly,
                          key=lambda k: k.to_xdr()):
            key_xdr = key.to_xdr()
            entry = ltx.load_by_bytes(key_xdr)
            if entry is None:
                continue
            read_bytes += len(entry.to_xdr())
            if read_bytes > int(sd.resources.readBytes):
                return self.result(
                    EXC.EXTEND_FOOTPRINT_TTL_RESOURCE_LIMIT_EXCEEDED)
            tk = ttl_key_for_xdr(key_xdr)
            ttl_entry = ltx.load(tk)
            if ttl_entry is None:
                continue
            live_until = int(ttl_entry.data.value.liveUntilLedgerSeq)
            if live_until < seq:
                continue                   # expired: restore, not extend
            new_live = min(seq + extend_to,
                           seq + network_config().max_entry_ttl)
            if new_live > live_until:
                ltx.put(make_ttl_entry(key_xdr, new_live,
                                       last_modified=seq))
        _registry().meter("soroban.ttl.extend").mark()
        return self.success()


class RestoreFootprintOpFrame(_SorobanOpFrame):
    OP_TYPE = OT.RESTORE_FOOTPRINT
    RESULT_CLS = X.RestoreFootprintResult

    def do_check_valid(self, ltx):
        sd = self._soroban_data()
        fp = sd.resources.footprint
        if fp.readOnly or not fp.readWrite:
            # reference: restored keys ride in readWrite ONLY
            return self.result(RSC.RESTORE_FOOTPRINT_MALFORMED)
        if any(k.switch not in _DATA_KEY_TYPES for k in fp.readWrite):
            return self.result(RSC.RESTORE_FOOTPRINT_MALFORMED)
        return self.success()

    def do_apply(self, ltx):
        sd = self._soroban_data()
        net = network_config()
        seq = int(ltx.get_header().ledgerSeq)
        write_bytes = 0
        for key in sorted(sd.resources.footprint.readWrite,
                          key=lambda k: k.to_xdr()):
            key_xdr = key.to_xdr()
            entry = ltx.load_by_bytes(key_xdr)
            if entry is None:
                continue                   # fully evicted: nothing left
            if key.switch == X.LedgerEntryType.CONTRACT_DATA and \
                    entry.data.value.durability != \
                    X.ContractDataDurability.PERSISTENT:
                return self.result(RSC.RESTORE_FOOTPRINT_MALFORMED)
            tk = ttl_key_for_xdr(key_xdr)
            ttl_entry = ltx.load(tk)
            live_until = None if ttl_entry is None else \
                int(ttl_entry.data.value.liveUntilLedgerSeq)
            if live_until is not None and live_until >= seq:
                continue                   # still live: nothing to restore
            write_bytes += len(entry.to_xdr())
            if write_bytes > int(sd.resources.writeBytes):
                return self.result(
                    RSC.RESTORE_FOOTPRINT_RESOURCE_LIMIT_EXCEEDED)
            ltx.put(make_ttl_entry(
                key_xdr, seq + net.min_persistent_entry_ttl - 1,
                last_modified=seq))
        _registry().meter("soroban.ttl.restore").mark()
        return self.success()


register_op_class(OT.INVOKE_HOST_FUNCTION, InvokeHostFunctionOpFrame)
register_op_class(OT.EXTEND_FOOTPRINT_TTL, ExtendFootprintTTLOpFrame)
register_op_class(OT.RESTORE_FOOTPRINT, RestoreFootprintOpFrame)
