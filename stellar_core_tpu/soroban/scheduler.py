"""Footprint scheduler: write-set clustering + parallel batch apply.

Declared footprints make a Soroban phase *declaratively parallelizable*
(PAPER.md §2.2): two transactions whose write sets are disjoint — and
that don't read each other's writes — cannot observe each other, so
they can apply concurrently with serial-equivalent results.

Clustering (union-find over footprint keys):
  * a tx's WRITE set = its footprint readWrite keys + every source
    account it can touch outside the footprint (tx source, fee source,
    per-op sources — seq bumps / one-time-signer removal write those);
  * all writers of a key are unioned;
  * every reader of a key is unioned with that key's writers (a read
    must see the same value it would have seen serially);
  * readers-only of a shared key do NOT union with each other.

Parallel apply reproduces the serial close BYTE-IDENTICALLY (bucket
hashes included).  Two mechanisms make that true:
  1. the footprint-enforcing storage layer guarantees no tx touches
     keys outside its declared sets (out-of-footprint → tx trap);
  2. cluster deltas are merged on the coordinating thread in the exact
     key-insertion order a serial apply would have produced (the close
     delta's dict order feeds the bucket batch, so insertion order is
     consensus-relevant, not cosmetic).

Each cluster applies under a `_ClusterBase` — an AbstractLedgerTxnParent
shim over the shared post-classic-phase LedgerTxn: reads delegate under
a lock, get_header serves a captured copy, and a cluster's commit lands
in a private buffer instead of the shared delta.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..util.lockorder import make_lock

__all__ = ["cluster_footprints", "tx_rw_keys", "apply_clusters_parallel"]


def tx_rw_keys(frame) -> Tuple[frozenset, frozenset]:
    """(write_keys, read_keys) for clustering, as LedgerKey XDR bytes."""
    from ..xdr import account_key_xdr, muxed_to_account_id
    writes = set()
    reads = set()
    writes.add(account_key_xdr(frame.source_account_id().value))
    fee_src = getattr(frame, "fee_source_account_id", None)
    if fee_src is not None:
        writes.add(account_key_xdr(fee_src().value))
    for op in frame.tx.operations:
        if op.sourceAccount is not None:
            writes.add(account_key_xdr(
                muxed_to_account_id(op.sourceAccount).value))
    sd = frame.soroban_data()
    if sd is not None:
        fp = sd.resources.footprint
        for k in fp.readWrite:
            writes.add(k.to_xdr())
        for k in fp.readOnly:
            reads.add(k.to_xdr())
    return frozenset(writes), frozenset(reads)


class _UnionFind:
    def __init__(self, n: int):
        self.parent = list(range(n))

    def find(self, i: int) -> int:
        while self.parent[i] != i:
            self.parent[i] = self.parent[self.parent[i]]
            i = self.parent[i]
        return i

    def union(self, a: int, b: int) -> None:
        ra, rb = self.find(a), self.find(b)
        if ra != rb:
            # root at the smaller index: cluster identity is then the
            # minimum member index — deterministic across runs
            if rb < ra:
                ra, rb = rb, ra
            self.parent[rb] = ra


def cluster_footprints(frames: Sequence) -> List[List]:
    """Partition `frames` (already in canonical apply order) into
    disjoint write-set clusters.  Cluster list is ordered by each
    cluster's first frame; frames keep their relative order."""
    n = len(frames)
    uf = _UnionFind(n)
    writers: Dict[bytes, int] = {}
    rw = [tx_rw_keys(f) for f in frames]
    for i, (writes, _) in enumerate(rw):
        for k in writes:
            if k in writers:
                uf.union(writers[k], i)
            else:
                writers[k] = i
    for i, (_, reads) in enumerate(rw):
        for k in reads:
            if k in writers:
                uf.union(writers[k], i)
    clusters: Dict[int, List] = {}
    for i, f in enumerate(frames):
        clusters.setdefault(uf.find(i), []).append(f)
    return [clusters[root] for root in sorted(clusters)]


class _ClusterBase:
    """AbstractLedgerTxnParent over the shared close LedgerTxn for ONE
    cluster's private LedgerTxn chain.  Reads delegate (locked — the
    underlying root may maintain caches); writes land in
    `self.delta`/`self.header` at commit instead of the shared state.
    Accepts any number of sequential children (the per-tx inner txns
    attach to the CLUSTER ltx, not here, so plain last-wins tracking
    suffices)."""

    def __init__(self, shared_ltx, shared_lock, header):
        self._shared = shared_ltx
        self._lock = shared_lock
        self._header = header
        self.delta: Optional[dict] = None
        self.committed_header = None
        self._child = None

    def get_entry(self, key_bytes: bytes):
        with self._lock:
            return self._shared.get_entry(key_bytes)

    def get_header(self):
        return self._header

    def _attach_child(self, child) -> None:
        self._child = child

    def _detach_child(self) -> None:
        self._child = None

    def all_keys(self):
        with self._lock:
            return iter(list(self._shared.all_keys()))

    def _apply_delta(self, delta: dict, header) -> None:
        # the cluster LedgerTxn's commit() lands here (we are not a
        # LedgerTxn, so commit takes the root-style path)
        self.delta = dict(delta)
        self.committed_header = header


def _apply_cluster(base: "_ClusterBase", cluster: Sequence,
                   apply_fn: Callable, out: dict, idx: int) -> None:
    """Worker: apply one cluster's frames in order against a private
    LedgerTxn over `base`; record per-tx results and the serial
    key-insertion order (first-writer order) for the merge."""
    from ..ledger.ledger_txn import LedgerTxn
    from ..util import detguard
    results = []
    insertion: List[Tuple[int, List[bytes]]] = []
    seen = set()
    try:
        # regions are thread-local: each cluster worker arms its own
        with detguard.region("soroban-cluster"), \
                LedgerTxn(base) as ltx:    # exit without commit == rollback
            for j, frame in enumerate(cluster):
                results.append(apply_fn(frame, ltx))
                new_keys = [k for k in ltx._delta if k not in seen]
                seen.update(new_keys)
                insertion.append((j, new_keys))
            ltx.commit()                   # → base._apply_delta
        out[idx] = (results, base.delta or {}, insertion, None)
    except BaseException as e:  # corelint: disable=exception-hygiene -- captured into `out` and re-raised on the coordinating thread after join
        out[idx] = (None, None, None, e)


def apply_clusters_parallel(shared_ltx, clusters: Sequence[Sequence],
                            apply_fn: Callable, positions: dict):
    """Apply `clusters` concurrently against `shared_ltx` and merge the
    buffered deltas back in serial-equivalent order.

    `apply_fn(frame, ltx)` applies one tx against the cluster's private
    LedgerTxn and returns its result pair.  `positions` maps id(frame)
    to its index in the canonical apply order (drives the merge).
    Returns a dict mapping id(frame) -> result so the caller can
    re-interleave results into the canonical order.  Worker exceptions
    re-raise here (fail-stop — an infrastructure error must never
    half-apply a phase)."""
    shared_lock = make_lock("soroban.cluster-read")
    header = shared_ltx.get_header()
    bases = [_ClusterBase(shared_ltx, shared_lock, header) for _ in clusters]
    out: dict = {}
    threads = []
    for i, cluster in enumerate(clusters):
        t = threading.Thread(
            target=_apply_cluster,
            args=(bases[i], cluster, apply_fn, out, i),
            name=f"soroban-cluster-{i}", daemon=True)
        threads.append(t)
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i in range(len(clusters)):
        err = out[i][3]
        if err is not None:
            raise err
    # Serial-equivalent merge: walk txs in canonical order (clusters
    # preserve relative order and the canonical order interleaves them
    # deterministically), inserting each tx's first-written keys in its
    # cluster-local order with the cluster's FINAL value for that key.
    order = sorted(
        ((cluster[j], i, keys)
         for i, cluster in enumerate(clusters)
         for j, keys in out[i][2]),
        key=lambda item: positions[id(item[0])])
    for _frame, i, keys in order:
        final_delta = out[i][1]
        for k in keys:
            shared_ltx._delta[k] = final_delta[k]
    results = {}
    for i, cluster in enumerate(clusters):
        for frame, res in zip(cluster, out[i][0]):
            results[id(frame)] = res
    return results
