"""Soroban network configuration (resource limits + fee model).

Real stellar-core carries these in ConfigSettingEntry ledger entries
(upgradable via SCP).  Here they live in a process-wide object set from
Config at application startup: threading them through the ledger would
change genesis hashes and break every golden-hash fixture for zero
modelling benefit (the repo's ConfigSettingEntry is still the opaque
carrier from ledger_entries.py).  The values below mirror the pubnet
Phase-1 settings scaled to the simulated host's cost model.
"""

from dataclasses import dataclass

__all__ = ["SorobanNetworkConfig", "network_config", "set_network_config"]


@dataclass(frozen=True)
class SorobanNetworkConfig:
    # per-transaction budgets
    tx_max_instructions: int = 100_000_000
    tx_max_memory_bytes: int = 40 * 1024 * 1024
    tx_max_read_entries: int = 40
    tx_max_write_entries: int = 25
    tx_max_read_bytes: int = 200 * 1024
    tx_max_write_bytes: int = 128 * 1024
    # per-ledger (phase) admission limits
    ledger_max_tx_count: int = 100
    ledger_max_instructions: int = 500_000_000
    # fee model: deterministic price per resource unit (stroops)
    fee_per_instruction_increment: int = 25     # per 10k instructions
    fee_per_read_entry: int = 6_250
    fee_per_write_entry: int = 10_000
    fee_per_read_kb: int = 1_786
    fee_per_write_kb: int = 11_800
    # TTL / state archival
    min_temp_entry_ttl: int = 16
    min_persistent_entry_ttl: int = 120
    max_entry_ttl: int = 3_110_400

    def min_resource_fee(self, resources) -> int:
        """Deterministic model minimum for a SorobanResources declaration
        (the declared resourceFee must cover this or the tx is invalid)."""
        fp = resources.footprint
        fee = 0
        fee += (resources.instructions + 9_999) // 10_000 \
            * self.fee_per_instruction_increment
        fee += (len(fp.readOnly) + len(fp.readWrite)) * self.fee_per_read_entry
        fee += len(fp.readWrite) * self.fee_per_write_entry
        fee += (resources.readBytes + 1023) // 1024 * self.fee_per_read_kb
        fee += (resources.writeBytes + 1023) // 1024 * self.fee_per_write_kb
        return fee


_CONFIG = SorobanNetworkConfig()


def network_config() -> SorobanNetworkConfig:
    return _CONFIG


def set_network_config(cfg: SorobanNetworkConfig) -> None:
    global _CONFIG
    _CONFIG = cfg
