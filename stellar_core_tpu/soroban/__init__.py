"""Soroban execution subsystem (ISSUE 17).

A bounded deterministic host for the sanctioned built-in host-function
subset (no wasm toolchain in this environment, per SURVEY §2.4), real
resource metering (cpu-instruction + memory budgets, per-tx resource
fees), full ExtendFootprintTTL / RestoreFootprint semantics over
CONTRACT_DATA / CONTRACT_CODE / TTL entries in BucketListDB,
generalized transaction sets (TransactionSetV1 phases with per-phase
surge pricing), and a footprint scheduler that partitions a Soroban
phase into disjoint write-set clusters applied as parallel batches.

Layout:
  config.py     SorobanNetworkConfig (process-wide resource limits)
  host.py       Budget + the built-in host-function table
  storage.py    footprint-enforcing storage view over a LedgerTxn
  ops.py        the three op frames (registered with operations.py)
  txset.py      generalized tx-set build / inspect helpers
  scheduler.py  write-set clustering + parallel batch apply
"""

from .config import SorobanNetworkConfig, network_config, set_network_config
from .host import Budget, BudgetExceeded, FootprintViolation, HostError
from .txset import (build_generalized_tx_set, decode_tx_set, is_generalized,
                    is_soroban_frame, tx_set_envelopes, tx_set_phases,
                    tx_set_previous_hash)
from .scheduler import cluster_footprints

__all__ = [
    "SorobanNetworkConfig", "network_config", "set_network_config",
    "Budget", "BudgetExceeded", "FootprintViolation", "HostError",
    "build_generalized_tx_set", "decode_tx_set", "is_generalized",
    "is_soroban_frame", "tx_set_envelopes", "tx_set_phases",
    "tx_set_previous_hash", "cluster_footprints",
]
