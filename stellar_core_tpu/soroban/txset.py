"""Generalized transaction sets (TransactionSetV1) — build + inspect.

A generalized set carries PHASES (reference: TxSetFrame /
GeneralizedTransactionSet in stellar-core): phase 0 is classic, phase 1
is Soroban.  Each phase is a list of TxSetComponents whose optional
baseFee records the per-phase surge-pricing floor the nominator applied.
The repo nominates a generalized set only when the Soroban phase is
non-empty — pure-classic ledgers keep the legacy TransactionSet shape
(and its hashes) byte-for-byte.
"""

from __future__ import annotations

import hashlib
from typing import List, Optional, Sequence, Tuple

from .. import xdr as X

__all__ = ["SOROBAN_OP_TYPES", "build_generalized_tx_set", "decode_tx_set",
           "is_generalized", "is_soroban_frame", "is_soroban_envelope",
           "tx_set_envelopes", "tx_set_phases", "tx_set_previous_hash",
           "phase_base_fees"]

SOROBAN_OP_TYPES = frozenset((
    X.OperationType.INVOKE_HOST_FUNCTION,
    X.OperationType.EXTEND_FOOTPRINT_TTL,
    X.OperationType.RESTORE_FOOTPRINT,
))


def is_soroban_envelope(envelope: X.TransactionEnvelope) -> bool:
    tx = envelope.value.tx
    if hasattr(tx, "innerTx"):          # fee bump: inspect the inner tx
        tx = tx.innerTx.value.tx
    return any(op.body.switch in SOROBAN_OP_TYPES for op in tx.operations)


def is_soroban_frame(frame) -> bool:
    return is_soroban_envelope(frame.envelope)


def is_generalized(tx_set) -> bool:
    return isinstance(tx_set, X.GeneralizedTransactionSet)


def _component(envelopes: Sequence[X.TransactionEnvelope],
               base_fee: Optional[int]) -> X.TxSetComponent:
    return X.TxSetComponent.txsMaybeDiscountedFee(
        X.TxSetComponentTxsMaybeDiscountedFee(
            baseFee=base_fee, txs=list(envelopes)))


def _phase(envelopes: Sequence[X.TransactionEnvelope],
           base_fee: Optional[int]) -> X.TransactionPhase:
    comps = [] if not envelopes else [_component(envelopes, base_fee)]
    return X.TransactionPhase.v0Components(comps)


def build_generalized_tx_set(
        previous_ledger_hash: bytes,
        classic_frames: Sequence,
        soroban_frames: Sequence,
        classic_base_fee: Optional[int] = None,
        soroban_base_fee: Optional[int] = None,
) -> Tuple[X.GeneralizedTransactionSet, bytes]:
    """Build the two-phase set; frames are hash-sorted per phase exactly
    like make_tx_set sorts the legacy shape.  Returns (set, sha256)."""
    classic = sorted(classic_frames, key=lambda f: f.content_hash())
    soroban = sorted(soroban_frames, key=lambda f: f.content_hash())
    gts = X.GeneralizedTransactionSet.v1TxSet(X.TransactionSetV1(
        previousLedgerHash=previous_ledger_hash,
        phases=[
            _phase([f.envelope for f in classic], classic_base_fee),
            _phase([f.envelope for f in soroban], soroban_base_fee),
        ]))
    return gts, hashlib.sha256(gts.to_xdr()).digest()


def tx_set_phases(tx_set) -> List[List[X.TransactionEnvelope]]:
    """Per-phase envelope lists.  Legacy sets read as one classic phase
    with an empty Soroban phase, so close-side code has ONE shape."""
    if not is_generalized(tx_set):
        return [list(tx_set.txs), []]
    out: List[List[X.TransactionEnvelope]] = []
    for phase in tx_set.value.phases:
        envs: List[X.TransactionEnvelope] = []
        for comp in phase.value:
            envs.extend(comp.value.txs)
        out.append(envs)
    while len(out) < 2:
        out.append([])
    return out


def phase_base_fees(tx_set) -> List[Optional[int]]:
    """The declared per-phase discounted base fees (None = no discount)."""
    if not is_generalized(tx_set):
        return [None, None]
    fees: List[Optional[int]] = []
    for phase in tx_set.value.phases:
        fee = None
        for comp in phase.value:
            if comp.value.baseFee is not None:
                fee = int(comp.value.baseFee)
        fees.append(fee)
    while len(fees) < 2:
        fees.append(None)
    return fees


def tx_set_envelopes(tx_set) -> List[X.TransactionEnvelope]:
    return [e for phase in tx_set_phases(tx_set) for e in phase]


def tx_set_previous_hash(tx_set) -> bytes:
    return (tx_set.value.previousLedgerHash if is_generalized(tx_set)
            else tx_set.previousLedgerHash)


def decode_tx_set(blob: bytes):
    """Decode a persisted/peer-sent tx set of either shape.  The
    generalized union has exactly one arm, so its wire form starts with
    the 4-byte discriminant 1; a legacy set starts with a previous-ledger
    hash, for which those bytes are vanishingly unlikely.  The misparse
    direction is guarded anyway: whichever decode is tried must consume
    the whole blob or XdrError propagates to the fallback."""
    if blob[:4] == (1).to_bytes(4, "big"):
        try:
            return X.GeneralizedTransactionSet.from_xdr(blob)
        except X.XdrError:
            pass
    return X.TransactionSet.from_xdr(blob)
