"""Footprint-enforcing storage view over a LedgerTxn.

Every contract-data access the host performs goes through this layer,
which enforces three distinct disciplines:

1. **Footprint membership** — reads must hit readOnly ∪ readWrite,
   writes must hit readWrite.  An out-of-footprint access raises
   FootprintViolation (the tx traps; the node keeps closing).  This is
   what makes the footprint scheduler SOUND: a tx physically cannot
   touch state outside the cluster it was assigned to.
2. **Declared-resource caps** — materialized entry bytes are counted
   against the SorobanResources the tx declared (readBytes/writeBytes);
   crossing a declared cap is the structured RESOURCE_LIMIT_EXCEEDED
   failure, exactly like blowing the cpu budget.
3. **TTL liveness** — each CONTRACT_DATA/CONTRACT_CODE entry is paired
   with a TTL entry keyed by sha256 of the data key's XDR.  An expired
   TEMPORARY entry reads as absent; an expired PERSISTENT entry raises
   EntryArchived until RestoreFootprint brings it back.
"""

from __future__ import annotations

import hashlib
from typing import Optional

from .. import xdr as X
from .host import Budget, BudgetExceeded, EntryArchived, FootprintViolation

__all__ = ["FootprintStorage", "contract_data_key", "ttl_key",
           "ttl_key_for_xdr", "make_contract_data_entry", "make_ttl_entry"]


def contract_data_key(contract, key_scval, durability) -> X.LedgerKey:
    return X.LedgerKey.contractData(X.LedgerKeyContractData(
        contract=contract, key=key_scval, durability=durability))


def ttl_key_for_xdr(data_key_xdr: bytes) -> X.LedgerKey:
    return X.LedgerKey.ttl(X.LedgerKeyTtl(
        keyHash=hashlib.sha256(data_key_xdr).digest()))


def ttl_key(data_key: X.LedgerKey) -> X.LedgerKey:
    return ttl_key_for_xdr(data_key.to_xdr())


def make_contract_data_entry(contract, key_scval, durability, val,
                             last_modified: int = 0) -> X.LedgerEntry:
    return X.LedgerEntry(
        lastModifiedLedgerSeq=last_modified,
        data=X.LedgerEntryData.contractData(X.ContractDataEntry(
            ext=X.ExtensionPoint.v0(), contract=contract, key=key_scval,
            durability=durability, val=val)))


def make_ttl_entry(data_key_xdr: bytes, live_until: int,
                   last_modified: int = 0) -> X.LedgerEntry:
    return X.LedgerEntry(
        lastModifiedLedgerSeq=last_modified,
        data=X.LedgerEntryData.ttl(X.TTLEntry(
            keyHash=hashlib.sha256(data_key_xdr).digest(),
            liveUntilLedgerSeq=live_until)))


class FootprintStorage:
    """One transaction's storage lens: a LedgerTxn scoped by the declared
    LedgerFootprint, metering reads/writes against `resources`."""

    def __init__(self, ltx, contract, resources, net_cfg, budget: Budget,
                 ledger_seq: int):
        self.ltx = ltx
        self.contract = contract
        self.resources = resources
        self.net = net_cfg
        self.budget = budget
        self.ledger_seq = ledger_seq
        fp = resources.footprint
        self._ro = frozenset(k.to_xdr() for k in fp.readOnly)
        self._rw = frozenset(k.to_xdr() for k in fp.readWrite)
        self.read_bytes_used = 0
        self.write_bytes_used = 0
        self._read_keys = set()

    # -- footprint + metering gates ------------------------------------

    def _check_read(self, key_xdr: bytes) -> None:
        if key_xdr not in self._ro and key_xdr not in self._rw:
            raise FootprintViolation("read outside declared footprint")

    def _check_write(self, key_xdr: bytes) -> None:
        if key_xdr not in self._rw:
            raise FootprintViolation("write outside declared footprint")

    def _meter_read(self, nbytes: int) -> None:
        self.budget.charge("read_byte", nbytes)
        self.read_bytes_used += nbytes
        if self.read_bytes_used > self.resources.readBytes:
            raise BudgetExceeded(
                f"declared readBytes exceeded: {self.read_bytes_used} > "
                f"{self.resources.readBytes}")

    def _meter_write(self, nbytes: int) -> None:
        self.budget.charge("write_byte", nbytes)
        self.write_bytes_used += nbytes
        if self.write_bytes_used > self.resources.writeBytes:
            raise BudgetExceeded(
                f"declared writeBytes exceeded: {self.write_bytes_used} > "
                f"{self.resources.writeBytes}")

    # -- TTL -----------------------------------------------------------

    def _live_until(self, data_key_xdr: bytes) -> Optional[int]:
        got = self.ltx.load_by_bytes(ttl_key_for_xdr(data_key_xdr).to_xdr())
        return None if got is None else int(got.data.value.liveUntilLedgerSeq)

    def _load_live(self, key: X.LedgerKey, durability):
        """Load a data entry honoring TTL: expired TEMPORARY → None,
        expired PERSISTENT → EntryArchived."""
        key_xdr = key.to_xdr()
        entry = self.ltx.load_by_bytes(key_xdr)
        if entry is None:
            return None
        live_until = self._live_until(key_xdr)
        if live_until is not None and live_until < self.ledger_seq:
            if durability == X.ContractDataDurability.TEMPORARY:
                return None
            raise EntryArchived(
                f"persistent entry expired at {live_until} "
                f"(now {self.ledger_seq}); RestoreFootprint required")
        return entry

    def _min_ttl(self, durability) -> int:
        if durability == X.ContractDataDurability.TEMPORARY:
            return self.net.min_temp_entry_ttl
        return self.net.min_persistent_entry_ttl

    # -- host-facing API ----------------------------------------------

    def get(self, key_scval, durability):
        key = contract_data_key(self.contract, key_scval, durability)
        key_xdr = key.to_xdr()
        self._check_read(key_xdr)
        self.budget.charge("storage_read")
        entry = self._load_live(key, durability)
        if entry is None:
            return None
        if key_xdr not in self._read_keys:
            self._read_keys.add(key_xdr)
            self._meter_read(len(entry.to_xdr()))
        return entry.data.value.val

    def has(self, key_scval, durability) -> bool:
        key = contract_data_key(self.contract, key_scval, durability)
        self._check_read(key.to_xdr())
        self.budget.charge("storage_has")
        return self._load_live(key, durability) is not None

    def put(self, key_scval, durability, val) -> None:
        key = contract_data_key(self.contract, key_scval, durability)
        key_xdr = key.to_xdr()
        self._check_write(key_xdr)
        self.budget.charge("storage_write")
        existing = self.ltx.load_by_bytes(key_xdr)
        live_until = self._live_until(key_xdr)
        expired = live_until is not None and live_until < self.ledger_seq
        if existing is not None and expired \
                and durability == X.ContractDataDurability.PERSISTENT:
            raise EntryArchived("cannot overwrite archived persistent entry")
        entry = make_contract_data_entry(
            self.contract, key_scval, durability, val,
            last_modified=self.ledger_seq)
        self._meter_write(len(entry.to_xdr()))
        if existing is None:
            self.ltx.create(entry)
        else:
            self.ltx.update(entry)
        # (re)arm the TTL: new entries get the durability minimum; an
        # overwrite of an expired TEMPORARY is a logical re-create
        if live_until is None or expired:
            ttl_entry = make_ttl_entry(
                key_xdr, self.ledger_seq + self._min_ttl(durability) - 1,
                last_modified=self.ledger_seq)
            self.ltx.put(ttl_entry)

    def delete(self, key_scval, durability) -> None:
        key = contract_data_key(self.contract, key_scval, durability)
        key_xdr = key.to_xdr()
        self._check_write(key_xdr)
        self.budget.charge("storage_del")
        entry = self._load_live(key, durability)
        if entry is None:
            return
        self.ltx.erase(key)
        if self.ltx.load_by_bytes(ttl_key_for_xdr(key_xdr).to_xdr()) \
                is not None:
            self.ltx.erase(ttl_key_for_xdr(key_xdr))
