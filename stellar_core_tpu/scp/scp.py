"""SCP — top-level protocol object owning slots.

Reference: src/scp/SCP.{h,cpp} — receiveEnvelope, nominate,
getLatestMessagesSend, purgeSlots, empty envelope/state accessors.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .driver import SCPDriver
from .local_node import LocalNode
from .slot import Slot


class EnvelopeState:
    INVALID = 0
    VALID = 1


class SCP:
    def __init__(self, driver: SCPDriver, node_id: bytes, is_validator: bool,
                 qset):
        self.driver = driver
        self.local_node = LocalNode(node_id, qset, is_validator)
        self.slots: Dict[int, Slot] = {}

    def get_slot(self, slot_index: int, create: bool = True) -> Optional[Slot]:
        s = self.slots.get(slot_index)
        if s is None and create:
            s = Slot(slot_index, self)
            self.slots[slot_index] = s
        return s

    # --- envelope intake ---------------------------------------------------
    def receive_envelope(self, env) -> int:
        if not self.driver.verify_envelope(env):
            return EnvelopeState.INVALID
        slot = self.get_slot(env.statement.slotIndex)
        ok = slot.process_envelope(env)
        return EnvelopeState.VALID if ok else EnvelopeState.INVALID

    # --- consensus drive ---------------------------------------------------
    def nominate(self, slot_index: int, value: bytes,
                 previous_value: bytes) -> bool:
        if not self.local_node.is_validator:
            # watchers never cast votes (reference: SCP::nominate returns
            # false after logging)
            return False
        return self.get_slot(slot_index).nominate(value, previous_value)

    def stop_nomination(self, slot_index: int) -> None:
        s = self.get_slot(slot_index, create=False)
        if s is not None:
            s.stop_nomination()

    # --- state access ------------------------------------------------------
    def update_local_quorum_set(self, qset) -> None:
        self.local_node.update_qset(qset)

    def get_latest_messages_send(self, slot_index: int) -> List:
        s = self.get_slot(slot_index, create=False)
        return s.get_latest_messages_send() if s is not None else []

    def get_current_state(self, slot_index: int) -> List:
        s = self.get_slot(slot_index, create=False)
        return s.get_current_state() if s is not None else []

    def get_externalized_value(self, slot_index: int) -> Optional[bytes]:
        s = self.get_slot(slot_index, create=False)
        return s.externalized_value() if s is not None else None

    def get_high_slot_index(self) -> int:
        return max(self.slots) if self.slots else 0

    def get_low_slot_index(self) -> int:
        return min(self.slots) if self.slots else 0

    def purge_slots(self, max_slot_index: int, keep: int = 0) -> None:
        """Drop state for slots below max_slot_index (reference:
        SCP::purgeSlots; `keep` retains some history for getMoreSCPState)."""
        cutoff = max_slot_index - keep
        for idx in [i for i in self.slots if i < cutoff]:
            del self.slots[idx]

    def empty(self) -> bool:
        return not self.slots
