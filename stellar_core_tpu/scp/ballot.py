"""Ballot protocol: PREPARE → CONFIRM → EXTERNALIZE via federated voting.

Reference: src/scp/BallotProtocol.{h,cpp} — processEnvelope, bumpState,
attemptAcceptPrepared/ConfirmPrepared/AcceptCommit/ConfirmCommit, attemptBump,
checkHeardFromQuorum, emitCurrentStateStatement.  Ballots are (counter, value)
tuples internally; SCPBallot at the XDR boundary.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from ..xdr import scp as SX
from . import quorum as Q
from .driver import BALLOT_PROTOCOL_TIMER, ValidationLevel

StType = SX.SCPStatementType
INT32_MAX = 2**31 - 1

Ballot = Tuple[int, bytes]  # (counter, value)

PHASE_PREPARE = 0
PHASE_CONFIRM = 1
PHASE_EXTERNALIZE = 2


def _b(xb) -> Ballot:
    return (xb.counter, xb.value)


def _xb(b: Ballot):
    return SX.SCPBallot(counter=b[0], value=b[1])


def compatible(a: Ballot, b: Ballot) -> bool:
    return a[1] == b[1]


def less_and_compatible(a: Ballot, b: Ballot) -> bool:
    return a <= b and compatible(a, b)


def less_and_incompatible(a: Ballot, b: Ballot) -> bool:
    return a <= b and not compatible(a, b)


class BallotProtocol:
    def __init__(self, slot):
        self.slot = slot
        self.phase = PHASE_PREPARE
        self.b: Optional[Ballot] = None       # current ballot
        self.p: Optional[Ballot] = None       # highest accepted prepared
        self.pp: Optional[Ballot] = None      # p' (incompatible with p)
        self.h: Optional[Ballot] = None       # highest confirmed prepared
        self.c: Optional[Ballot] = None       # lowest commit
        self.z: Optional[bytes] = None        # value override
        self.latest_envelopes: Dict[bytes, object] = {}
        self.last_envelope = None
        self.heard_from_quorum = False
        self._advancing = 0
        self.timer_armed_counter = -1
        # incremental per-slot quorum state (reference: Slot's cached
        # mHeardFromQuorum edge): per-node counters + compiled qsets +
        # epoch-keyed verdict memo, maintained in process_envelope
        self.index = Q.StatementIndex()
        # node -> compiled statement summary (see _summarize), kept in
        # lockstep with latest_envelopes
        self._summaries: Dict[bytes, tuple] = {}

    # ------------------------------------------------------------------
    # statement summaries + predicates
    #
    # Every federated-voting predicate runs per NODE per quorum question —
    # the inner loop of the whole protocol.  Evaluating them against raw
    # XDR statements pays the lazy-decode descriptor machinery on every
    # field access (measured: ~25% of a 51-node campaign inside codec
    # __get__/arm), so each statement is compiled ONCE at intake into a
    # plain tuple and the predicates read tuples:
    #
    #   PREPARE:     (0, ballot, prepared|None, preparedPrime|None, nC, nH)
    #   CONFIRM:     (1, ballot, nPrepared, nCommit, nH)
    #   EXTERNALIZE: (2, commit, nH)
    #
    # where ballot/commit are (counter, value) tuples.  Same move as
    # compile_qset for quorum slices (scp/quorum.py).
    # ------------------------------------------------------------------
    @staticmethod
    def _summarize(st) -> tuple:
        pl = st.pledges
        if pl.type == StType.SCP_ST_PREPARE:
            pr = pl.prepare
            return (0, _b(pr.ballot),
                    _b(pr.prepared) if pr.prepared is not None else None,
                    _b(pr.preparedPrime) if pr.preparedPrime is not None
                    else None, pr.nC, pr.nH)
        if pl.type == StType.SCP_ST_CONFIRM:
            co = pl.confirm
            return (1, _b(co.ballot), co.nPrepared, co.nCommit, co.nH)
        ex = pl.externalize
        return (2, _b(ex.commit), ex.nH)

    @staticmethod
    def _counter_of(ss: tuple) -> int:
        return ss[1][0] if ss[0] != 2 else INT32_MAX

    @staticmethod
    def _votes_prepare(cand: Ballot, ss: tuple) -> bool:
        if ss[0] == 0:
            return less_and_compatible(cand, ss[1])
        return compatible(cand, ss[1])   # CONFIRM ballot / EXTERNALIZE commit

    @staticmethod
    def _accepts_prepared(cand: Ballot, ss: tuple) -> bool:
        k = ss[0]
        if k == 0:
            p, pp = ss[2], ss[3]
            return ((p is not None and less_and_compatible(cand, p)) or
                    (pp is not None and less_and_compatible(cand, pp)))
        if k == 1:
            return less_and_compatible(cand, (ss[2], ss[1][1]))
        return compatible(cand, ss[1])

    @staticmethod
    def _votes_commit(value: bytes, n: int, ss: tuple) -> bool:
        k = ss[0]
        if k == 0:
            return ss[4] != 0 and ss[1][1] == value and ss[4] <= n <= ss[5]
        if k == 1:
            return ss[1][1] == value and ss[3] <= n
        return ss[1][1] == value and ss[1][0] <= n

    @staticmethod
    def _accepts_commit(value: bytes, n: int, ss: tuple) -> bool:
        k = ss[0]
        if k == 0:
            return False
        if k == 1:
            return ss[1][1] == value and ss[3] <= n <= ss[4]
        return ss[1][1] == value and ss[1][0] <= n

    @staticmethod
    def _prepare_candidates(hint) -> List[Ballot]:
        pl = hint.pledges
        out: Set[Ballot] = set()
        if pl.type == StType.SCP_ST_PREPARE:
            out.add(_b(pl.prepare.ballot))
            if pl.prepare.prepared is not None:
                out.add(_b(pl.prepare.prepared))
            if pl.prepare.preparedPrime is not None:
                out.add(_b(pl.prepare.preparedPrime))
        elif pl.type == StType.SCP_ST_CONFIRM:
            v = pl.confirm.ballot.value
            out.add((pl.confirm.nPrepared, v))
            out.add((INT32_MAX, v))
        else:
            out.add((INT32_MAX, pl.externalize.commit.value))
        return sorted(out, reverse=True)

    def _st_order(self, st):
        pl = st.pledges
        if pl.type == StType.SCP_ST_PREPARE:
            pr = pl.prepare
            return (0, _b(pr.ballot),
                    _b(pr.prepared) if pr.prepared is not None else (0, b""),
                    _b(pr.preparedPrime) if pr.preparedPrime is not None
                    else (0, b""), pr.nH)
        if pl.type == StType.SCP_ST_CONFIRM:
            co = pl.confirm
            return (1, _b(co.ballot), co.nPrepared, co.nCommit, co.nH)
        return (2, (INT32_MAX, b""), 0, 0, 0)

    def _is_newer(self, st, old) -> bool:
        return self._st_order(st) > self._st_order(old)

    @staticmethod
    def _sane(st, self_st: bool = False) -> bool:
        """Reference: BallotProtocol::isStatementSane.  A self statement may
        carry ballot counter 0 (never emitted; see _emit_current_state)."""
        pl = st.pledges
        if pl.type == StType.SCP_ST_PREPARE:
            pr = pl.prepare
            if not self_st and pr.ballot.counter == 0:
                return False
            if pr.prepared is not None and pr.preparedPrime is not None:
                # p' < p and incompatible
                if not (_b(pr.preparedPrime) < _b(pr.prepared)
                        and not compatible(_b(pr.preparedPrime),
                                           _b(pr.prepared))):
                    return False
            if pr.nH != 0 and (pr.prepared is None
                               or pr.nH > pr.prepared.counter):
                return False
            if pr.nC != 0 and not (pr.nH != 0
                                   and pr.ballot.counter >= pr.nH >= pr.nC):
                return False
            return True
        if pl.type == StType.SCP_ST_CONFIRM:
            co = pl.confirm
            return (co.ballot.counter > 0
                    and co.nCommit <= co.nH <= co.ballot.counter)
        ex = pl.externalize
        return 0 < ex.commit.counter <= ex.nH

    # ------------------------------------------------------------------
    # state mutation helpers
    # ------------------------------------------------------------------
    def _stmt_map(self) -> Dict[bytes, tuple]:
        """node -> compiled statement summary (the map every federated
        predicate runs over); maintained incrementally, never rebuilt."""
        return self._summaries

    def _bump_to_ballot(self, ballot: Ballot, require_ge: bool) -> None:
        got_bumped = self.b is None or self.b[0] != ballot[0]
        if self.b is None:
            self.slot.driver.started_ballot_protocol(self.slot.slot_index,
                                                     _xb(ballot))
        self.b = ballot
        if got_bumped:
            self.heard_from_quorum = False

    def _update_current_if_needed(self, h: Ballot) -> bool:
        if self.b is None or self.b < h:
            self._bump_to_ballot(h, True)
            return True
        return False

    def _set_prepared(self, ballot: Ballot) -> bool:
        did = False
        if self.p is None:
            self.p = ballot
            did = True
        elif self.p < ballot:
            if not compatible(self.p, ballot):
                self.pp = self.p
            self.p = ballot
            did = True
        elif ballot < self.p and not compatible(ballot, self.p):
            if self.pp is None or self.pp < ballot:
                self.pp = ballot
                did = True
        return did

    # ------------------------------------------------------------------
    # protocol steps (reference: BallotProtocol::attempt*)
    # ------------------------------------------------------------------
    def _attempt_accept_prepared(self, hint) -> bool:
        if self.phase not in (PHASE_PREPARE, PHASE_CONFIRM):
            return False
        ln, stmt_map = self.slot.local_node, self._stmt_map()
        qset_of = self.slot.qset_of_statement
        for cand in self._prepare_candidates(hint):
            if self.phase == PHASE_CONFIRM:
                if not (self.p is not None
                        and less_and_compatible(self.p, cand)):
                    continue
            # nothing new?
            if ((self.p is not None and less_and_compatible(cand, self.p)) or
                    (self.pp is not None
                     and less_and_compatible(cand, self.pp))):
                continue
            if ln.federated_accept(
                    lambda st, c=cand: self._votes_prepare(c, st),
                    lambda st, c=cand: self._accepts_prepared(c, st),
                    stmt_map, qset_of,
                    index=self.index, key=("acc-prep", cand)):
                return self._set_accept_prepared(cand)
        return False

    def _set_accept_prepared(self, ballot: Ballot) -> bool:
        did = self._set_prepared(ballot)
        # accepting prepared(p) with p > c incompatible aborts commit c
        if self.c is not None and self.h is not None:
            if ((self.p is not None
                 and less_and_incompatible(self.h, self.p)) or
                    (self.pp is not None
                     and less_and_incompatible(self.h, self.pp))):
                self.c = None
                did = True
        if did:
            self.slot.driver.accepted_ballot_prepared(self.slot.slot_index,
                                                      _xb(ballot))
            self._emit_current_state()
        return did

    def _attempt_confirm_prepared(self, hint) -> bool:
        if self.phase != PHASE_PREPARE or self.p is None:
            return False
        ln, stmt_map = self.slot.local_node, self._stmt_map()
        qset_of = self.slot.qset_of_statement
        candidates = self._prepare_candidates(hint)
        new_h = None
        for cand in candidates:
            if self.h is not None and cand <= self.h:
                break
            if ln.federated_ratify(
                    lambda st, c=cand: self._accepts_prepared(c, st),
                    stmt_map, qset_of,
                    index=self.index, key=("rat-prep", cand)):
                new_h = cand
                break
        if new_h is None:
            return False
        new_c = None
        if (self.c is None
                and not (self.p is not None
                         and less_and_incompatible(new_h, self.p))
                and not (self.pp is not None
                         and less_and_incompatible(new_h, self.pp))):
            for cand in sorted(candidates):
                if self.b is not None and cand < self.b:
                    continue
                if not less_and_compatible(cand, new_h):
                    continue
                if ln.federated_ratify(
                        lambda st, c=cand: self._accepts_prepared(c, st),
                        stmt_map, qset_of,
                        index=self.index, key=("rat-prep", cand)):
                    new_c = cand
                    break
        self.z = new_h[1]
        if self.h is None or self.h < new_h:
            self.h = new_h
        if new_c is not None:
            self.c = new_c
        self._update_current_if_needed(self.h)
        self.slot.driver.confirmed_ballot_prepared(self.slot.slot_index,
                                                   _xb(new_h))
        self._emit_current_state()
        return True

    def _commit_boundaries(self, value: bytes) -> List[int]:
        out: Set[int] = set()
        for ss in self._summaries.values():
            k = ss[0]
            if k == 0:
                if ss[1][1] == value and ss[4] != 0:
                    out.update((ss[4], ss[5]))
            elif k == 1:
                if ss[1][1] == value:
                    out.update((ss[3], ss[4]))
            else:
                if ss[1][1] == value:
                    out.update((ss[1][0], ss[2]))
        return sorted(out, reverse=True)

    @staticmethod
    def _find_extended_interval(boundaries: List[int], pred) -> Tuple[int, int]:
        """Largest [lo, hi] (by hi, extended down) where pred holds.
        Reference: BallotProtocol::findExtendedInterval."""
        cur = (0, 0)
        for b in boundaries:  # descending
            cand = (b, b) if cur == (0, 0) else (b, cur[1])
            if pred(cand):
                cur = cand
            elif cur != (0, 0):
                break
        return cur

    def _attempt_accept_commit(self, hint) -> bool:
        if self.phase not in (PHASE_PREPARE, PHASE_CONFIRM):
            return False
        pl = hint.pledges
        if pl.type == StType.SCP_ST_PREPARE:
            if pl.prepare.nC == 0:
                return False
            ballot = (pl.prepare.nH, pl.prepare.ballot.value)
        elif pl.type == StType.SCP_ST_CONFIRM:
            ballot = (pl.confirm.nH, pl.confirm.ballot.value)
        else:
            ballot = (pl.externalize.nH, pl.externalize.commit.value)
        if self.phase == PHASE_CONFIRM:
            if not compatible(ballot, self.h):
                return False
        ln, stmt_map = self.slot.local_node, self._stmt_map()
        qset_of = self.slot.qset_of_statement
        value = ballot[1]

        def pred(interval):
            lo, hi = interval
            return ln.federated_accept(
                lambda st: self._votes_commit(value, lo, st)
                and self._votes_commit(value, hi, st),
                lambda st: self._accepts_commit(value, lo, st)
                and self._accepts_commit(value, hi, st),
                stmt_map, qset_of,
                index=self.index, key=("acc-commit", value, lo, hi))

        lo, hi = self._find_extended_interval(self._commit_boundaries(value),
                                              pred)
        if lo == 0:
            return False
        if self.phase == PHASE_CONFIRM and hi <= self.h[0] and self.c is not None:
            return False
        return self._set_accept_commit((lo, value), (hi, value))

    def _set_accept_commit(self, c: Ballot, h: Ballot) -> bool:
        did = False
        self.z = h[1]
        if self.h != h or self.c != c:
            self.c, self.h = c, h
            did = True
        if self.phase == PHASE_PREPARE:
            self.phase = PHASE_CONFIRM
            if self.b is not None and not less_and_compatible(h, self.b):
                self._bump_to_ballot(h, False)
            # accepting commit(c..h) implies prepared(h): keep the CONFIRM-
            # phase invariant that p is set (the CONFIRM statement carries
            # nPrepared)
            self._set_prepared(h)
            self.pp = None
            did = True
        if did:
            self._update_current_if_needed(self.h)
            self.slot.driver.accepted_commit(self.slot.slot_index, _xb(h))
            self._emit_current_state()
        return did

    def _attempt_confirm_commit(self, hint) -> bool:
        if self.phase != PHASE_CONFIRM or self.h is None or self.c is None:
            return False
        pl = hint.pledges
        if pl.type == StType.SCP_ST_PREPARE:
            return False
        elif pl.type == StType.SCP_ST_CONFIRM:
            ballot = (pl.confirm.nH, pl.confirm.ballot.value)
        else:
            ballot = (pl.externalize.nH, pl.externalize.commit.value)
        if not compatible(ballot, self.c):
            return False
        ln, stmt_map = self.slot.local_node, self._stmt_map()
        qset_of = self.slot.qset_of_statement
        value = ballot[1]

        def pred(interval):
            lo, hi = interval
            return ln.federated_ratify(
                lambda st: self._votes_commit(value, lo, st)
                and self._votes_commit(value, hi, st),
                stmt_map, qset_of,
                index=self.index, key=("rat-commit", value, lo, hi))

        lo, hi = self._find_extended_interval(self._commit_boundaries(value),
                                              pred)
        if lo == 0:
            return False
        return self._set_confirm_commit((lo, value), (hi, value))

    def _set_confirm_commit(self, c: Ballot, h: Ballot) -> bool:
        self.c, self.h = c, h
        self._update_current_if_needed(self.h)
        self.phase = PHASE_EXTERNALIZE
        self._emit_current_state()
        self.slot.stop_nomination()
        self.slot.driver.value_externalized(self.slot.slot_index, c[1])
        return True

    def _attempt_bump(self) -> bool:
        """Counter catch-up: if a v-blocking set is ahead of our counter,
        jump to the lowest counter that is still v-blocking-ahead."""
        if self.phase not in (PHASE_PREPARE, PHASE_CONFIRM):
            return False
        ln = self.slot.local_node
        target = self.b[0] if self.b is not None else 0
        counters = self.index.node_counter   # read-only view, no rebuild
        ahead = sorted({c for c in counters.values() if c > target})
        # v-blocking-ness is monotone in the node set, so only the smallest
        # ahead counter (largest node set) can qualify; the verdict runs
        # over compiled qsets and LATCHES through the StatementIndex
        # (counters only grow — a regression drops the latches)
        for n in ahead:
            if Q.v_blocking_ahead(ln.qset, ln.qset_hash, self.index, n):
                # abandon_ballot owns the value selection (z, then the
                # nomination composite, then the current ballot's value)
                return self.abandon_ballot(n)
            break
        return False

    def _check_heard_from_quorum(self) -> None:
        if self.b is None:
            return
        ln = self.slot.local_node
        heard = Q.heard_from_quorum(ln.qset, ln.qset_hash, self.index,
                                    self.b[0])
        if heard:
            was = self.heard_from_quorum
            self.heard_from_quorum = True
            if not was:
                self.slot.driver.ballot_did_hear_from_quorum(
                    self.slot.slot_index, _xb(self.b))
            if (self.phase != PHASE_EXTERNALIZE
                    and self.timer_armed_counter != self.b[0]):
                counter = self.b[0]
                self.timer_armed_counter = counter
                self.slot.driver.setup_timer(
                    self.slot.slot_index, BALLOT_PROTOCOL_TIMER,
                    self.slot.driver.compute_timeout(counter),
                    lambda: self._on_timeout(counter))
        else:
            self.heard_from_quorum = False

    def _on_timeout(self, counter: int) -> None:
        """Ballot timer expiry → abandon the current ballot counter."""
        self.timer_armed_counter = -1
        if self.phase == PHASE_EXTERNALIZE:
            return
        if self.b is not None and self.b[0] != counter:
            return
        self.abandon_ballot(0)

    def abandon_ballot(self, n: int) -> bool:
        value = self.z
        if value is None:
            comp = self.slot.nomination.latest_composite
            if comp is not None:
                value = comp
            elif self.b is not None:
                value = self.b[1]
        if value is None:
            return False
        if n == 0:
            return self.bump_state(value, force=True)
        return self._bump_state(value, n)

    # ------------------------------------------------------------------
    # entry points
    # ------------------------------------------------------------------
    def bump_state(self, value: bytes, force: bool) -> bool:
        if not force and self.b is not None:
            return False
        n = (self.b[0] + 1) if self.b is not None else 1
        return self._bump_state(value, n)

    def _bump_state(self, value: bytes, n: int) -> bool:
        if self.phase not in (PHASE_PREPARE, PHASE_CONFIRM):
            return False
        new_b = (n, self.z if self.z is not None else value)
        if not self._update_current_value(new_b):
            return False
        self._emit_current_state()
        self._check_heard_from_quorum()
        return True

    def _update_current_value(self, ballot: Ballot) -> bool:
        if self.phase not in (PHASE_PREPARE, PHASE_CONFIRM):
            return False
        if self.phase == PHASE_CONFIRM and not compatible(ballot, self.h):
            return False
        if self.b is None or self.b < ballot:
            self._bump_to_ballot(ballot, True)
            return True
        return False

    def process_envelope(self, env, self_env: bool = False) -> bool:
        st = env.statement
        nid = st.nodeID.value
        if not self._sane(st, self_st=self_env):
            return False
        if not self._validate_values(st):
            return False
        old = self.latest_envelopes.get(nid)
        if old is not None and not self._is_newer(st, old.statement):
            return False
        self.latest_envelopes[nid] = env
        ss = self._summarize(st)
        self._summaries[nid] = ss
        self.index.note_statement(nid, self._counter_of(ss),
                                  self.slot.qset_of_statement(st),
                                  Q.statement_qset_hash(st))
        self._advance_slot(st, from_self=self_env)
        return True

    def _validate_values(self, st) -> bool:
        pl = st.pledges
        values = []
        if pl.type == StType.SCP_ST_PREPARE:
            if pl.prepare.ballot.counter:
                values.append(pl.prepare.ballot.value)
            if pl.prepare.prepared is not None:
                values.append(pl.prepare.prepared.value)
        elif pl.type == StType.SCP_ST_CONFIRM:
            values.append(pl.confirm.ballot.value)
        else:
            values.append(pl.externalize.commit.value)
        for v in values:
            lvl = self.slot.driver.validate_value(self.slot.slot_index, v,
                                                  nomination=False)
            if lvl == ValidationLevel.INVALID:
                return False
        return True

    def _advance_slot(self, hint, from_self: bool = False) -> None:
        self._advancing += 1
        try:
            if self._advancing > 10:  # reference: mCurrentMessageLevel cap
                return
            did = False
            did |= self._attempt_accept_prepared(hint)
            did |= self._attempt_confirm_prepared(hint)
            did |= self._attempt_accept_commit(hint)
            did |= self._attempt_confirm_commit(hint)
            if self._advancing == 1:
                while self._attempt_bump():
                    did = True
                self._check_heard_from_quorum()
        finally:
            self._advancing -= 1

    # ------------------------------------------------------------------
    # statement emission
    # ------------------------------------------------------------------
    def _build_statement(self):
        ln = self.slot.local_node
        if self.phase == PHASE_PREPARE:
            pledges = SX.SCPStatementPledges.prepare(SX.SCPPrepare(
                quorumSetHash=ln.qset_hash,
                ballot=_xb(self.b),
                prepared=_xb(self.p) if self.p is not None else None,
                preparedPrime=_xb(self.pp) if self.pp is not None else None,
                nC=self.c[0] if self.c is not None else 0,
                nH=min(self.h[0], self.b[0]) if self.h is not None else 0))
        elif self.phase == PHASE_CONFIRM:
            pledges = SX.SCPStatementPledges.confirm(SX.SCPConfirm(
                ballot=_xb(self.b),
                nPrepared=self.p[0],
                nCommit=self.c[0],
                nH=self.h[0],
                quorumSetHash=ln.qset_hash))
        else:
            pledges = SX.SCPStatementPledges.externalize(SX.SCPExternalize(
                commit=_xb(self.c),
                nH=self.h[0],
                commitQuorumSetHash=ln.qset_hash))
        return SX.SCPStatement(nodeID=self.slot.local_node_xdr_id(),
                               slotIndex=self.slot.slot_index,
                               pledges=pledges)

    def _emit_current_state(self) -> None:
        if self.b is None:
            return
        st = self._build_statement()
        env = self.slot.create_envelope(st)
        if not self.process_envelope(env, self_env=True):
            # Rejection for "not newer than our previous statement" is
            # benign (don't re-emit); rejection for sanity/validation means
            # protocol state corruption.  Reference: emitCurrentStateStatement
            # throws "moved to a bad state (ballot protocol)".
            if not (self._sane(st, self_st=True)
                    and self._validate_values(st)):
                raise RuntimeError("moved to a bad state (ballot protocol)")
            return
        if (self.last_envelope is None
                or self._is_newer(st, self.last_envelope.statement)):
            self.last_envelope = env
            if self.slot.fully_validated:
                self.slot.driver.emit_envelope(env)

    def get_latest_message(self, node_id: bytes):
        return self.latest_envelopes.get(node_id)

    def current_state(self) -> List:
        return [self.last_envelope] if self.last_envelope else []

    def externalized_value(self) -> Optional[bytes]:
        if self.phase == PHASE_EXTERNALIZE:
            return self.c[1]
        return None
