"""Slot — one consensus round (ledger sequence number): nomination + ballot
protocol plus envelope signing/bookkeeping.

Reference: src/scp/Slot.{h,cpp} — processEnvelope, getLatestMessagesSend,
createEnvelope, federated voting delegated to LocalNode.
"""

from __future__ import annotations

from typing import List, Optional

from ..xdr import scp as SX
from ..xdr import types as XT
from .ballot import BallotProtocol
from .nomination import NominationProtocol
from .quorum import statement_qset_hash

StType = SX.SCPStatementType


class Slot:
    def __init__(self, slot_index: int, scp):
        self.slot_index = slot_index
        self.scp = scp
        self.driver = scp.driver
        self.local_node = scp.local_node
        self.nomination = NominationProtocol(self)
        self.ballot = BallotProtocol(self)
        self.fully_validated = scp.local_node.is_validator
        self.got_v_blocking = False
        self._historical: List = []  # all envelopes seen (for debugging)

    # --- helpers used by sub-protocols ------------------------------------
    def local_node_xdr_id(self):
        return XT.node_id(self.local_node.node_id)

    def qset_of_statement(self, st):
        """Quorum set referenced by a statement (None if unknown)."""
        h = statement_qset_hash(st)
        if st.nodeID.value == self.local_node.node_id \
                and h == self.local_node.qset_hash:
            return self.local_node.qset
        return self.driver.get_qset(h)

    def create_envelope(self, statement):
        env = SX.SCPEnvelope(statement=statement, signature=b"\x00" * 64)
        self.driver.sign_envelope(env)
        return env

    # --- entry points ------------------------------------------------------
    def process_envelope(self, env, self_env: bool = False) -> bool:
        st = env.statement
        assert st.slotIndex == self.slot_index
        if self.qset_of_statement(st) is None:
            return False  # herder fetches the qset first (PendingEnvelopes)
        self._historical.append(env)
        if st.pledges.type == StType.SCP_ST_NOMINATE:
            ok = self.nomination.process_envelope(env, self_env)
        else:
            ok = self.ballot.process_envelope(env, self_env)
        if ok and not self_env:
            self._maybe_fully_validate()
        return ok

    def _maybe_fully_validate(self) -> None:
        """A non-validator slot becomes fully validated once a v-blocking set
        has issued ballot statements (reference: Slot::maybeSetGotVBlocking —
        simplified)."""
        if self.fully_validated:
            return
        nodes = set(self.ballot.latest_envelopes)
        if self.local_node.is_v_blocking(nodes):
            self.got_v_blocking = True
            self.fully_validated = True

    def nominate(self, value: bytes, previous_value: bytes,
                 timed_out: bool = False) -> bool:
        return self.nomination.nominate(value, previous_value, timed_out)

    def stop_nomination(self) -> None:
        self.nomination.stop_nomination()

    def bump_state(self, value: bytes, force: bool) -> bool:
        return self.ballot.bump_state(value, force)

    def abandon_ballot(self, n: int = 0) -> bool:
        return self.ballot.abandon_ballot(n)

    # --- state access ------------------------------------------------------
    def get_latest_messages_send(self) -> List:
        """Messages to (re)broadcast for this slot."""
        if not self.fully_validated:
            return []
        return self.nomination.current_state() + self.ballot.current_state()

    def get_latest_message(self, node_id: bytes):
        env = self.ballot.get_latest_message(node_id)
        if env is None:
            env = self.nomination.get_latest_message(node_id)
        return env

    def get_current_state(self) -> List:
        out = []
        # sorted(): the union iterates in hash order, and this list is
        # handed to the overlay as broadcast/pull order
        for n in sorted(set(self.nomination.latest_nominations) | set(
                self.ballot.latest_envelopes)):
            e = self.ballot.latest_envelopes.get(n)
            if e is not None:
                out.append(e)
            e = self.nomination.latest_nominations.get(n)
            if e is not None:
                out.append(e)
        return out

    def externalized_value(self) -> Optional[bytes]:
        return self.ballot.externalized_value()
