"""SCPDriver — the abstract callback seam between the pure SCP library and
the application (herder).

Reference: src/scp/SCPDriver.{h,cpp} — validateValue, combineCandidates,
emitEnvelope, getQSet, setupTimer, computeHashNode, computeValueHash,
computeTimeout, signEnvelope/verifyEnvelope.
"""

from __future__ import annotations

import struct
from enum import Enum
from typing import List, Optional

from ..crypto.sha import sha256


class ValidationLevel(Enum):
    INVALID = 0
    MAYBE_VALID = 1          # valid signature-wise but can't fully check yet
    FULLY_VALIDATED = 2
    VOTE_TO_NOMINATE = 3     # fully validated and worth nominating


# timer slot ids (reference: Slot::timerIDs)
NOMINATION_TIMER = 0
BALLOT_PROTOCOL_TIMER = 1

_HASH_N = 1  # isPriority=false → neighborhood hash
_HASH_P = 2  # isPriority=true  → priority hash
_HASH_K = 3  # value hash

MAX_TIMEOUT_SECONDS = 30 * 60


class SCPDriver:
    """Subclass and implement; all values are opaque bytes."""

    # --- value semantics -------------------------------------------------
    def validate_value(self, slot_index: int, value: bytes,
                       nomination: bool) -> ValidationLevel:
        return ValidationLevel.MAYBE_VALID

    def extract_valid_value(self, slot_index: int,
                            value: bytes) -> Optional[bytes]:
        """Try to repair an invalid value into a valid one (or None)."""
        return None

    def combine_candidates(self, slot_index: int,
                           candidates: List[bytes]) -> Optional[bytes]:
        raise NotImplementedError

    # --- quorum sets ------------------------------------------------------
    def get_qset(self, qset_hash: bytes):
        """Return the SCPQuorumSet with this hash, or None if unknown."""
        raise NotImplementedError

    # --- I/O --------------------------------------------------------------
    def emit_envelope(self, envelope) -> None:
        raise NotImplementedError

    def sign_envelope(self, envelope) -> None:
        pass

    def verify_envelope(self, envelope) -> bool:
        return True

    # --- notifications (optional overrides) ------------------------------
    def value_externalized(self, slot_index: int, value: bytes) -> None:
        pass

    def nominating_value(self, slot_index: int, value: bytes) -> None:
        pass

    def updated_candidate_value(self, slot_index: int, value: bytes) -> None:
        pass

    def started_ballot_protocol(self, slot_index: int, ballot) -> None:
        pass

    def accepted_ballot_prepared(self, slot_index: int, ballot) -> None:
        pass

    def confirmed_ballot_prepared(self, slot_index: int, ballot) -> None:
        pass

    def accepted_commit(self, slot_index: int, ballot) -> None:
        pass

    def ballot_did_hear_from_quorum(self, slot_index: int, ballot) -> None:
        pass

    # --- timers -----------------------------------------------------------
    def setup_timer(self, slot_index: int, timer_id: int, timeout: float,
                    callback) -> None:
        """Arm (or, with callback=None, cancel) a per-slot timer."""
        raise NotImplementedError

    def stop_timer(self, slot_index: int, timer_id: int) -> None:
        self.setup_timer(slot_index, timer_id, 0.0, None)  # corelint: disable=float-discipline -- timer-cancel sentinel delay, local pacing

    def compute_timeout(self, round_number: int,
                        is_nomination: bool = False) -> float:
        """Reference: SCPDriver::computeTimeout — linear backoff, capped."""
        return float(min(round_number + 1, MAX_TIMEOUT_SECONDS))  # corelint: disable=float-discipline -- timer backoff seconds, local pacing; float(int) exact

    # --- deterministic hashing for leader election ------------------------
    def _hash_expr(self, slot_index: int, prev: bytes, tag: int,
                   extra: bytes) -> int:
        h = sha256(struct.pack(">QI", slot_index, tag) + prev + extra)
        return int.from_bytes(h[:8], "big")

    def compute_hash_node(self, slot_index: int, prev: bytes,
                          is_priority: bool, round_number: int,
                          node_id: bytes) -> int:
        tag = _HASH_P if is_priority else _HASH_N
        return self._hash_expr(slot_index, prev, tag,
                               struct.pack(">i", round_number) + node_id)

    def compute_value_hash(self, slot_index: int, prev: bytes,
                           round_number: int, value: bytes) -> int:
        return self._hash_expr(slot_index, prev, _HASH_K,
                               struct.pack(">i", round_number) + value)
