"""Nomination protocol: weighted-leader value proposal + federated voting to
confirm nomination candidates.

Reference: src/scp/NominationProtocol.{h,cpp} — processEnvelope, nominate,
updateRoundLeaders, getNewValueFromNomination.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set

from ..xdr import scp as SX
from . import quorum as Q
from .driver import NOMINATION_TIMER, ValidationLevel

StType = SX.SCPStatementType


def _newer_by_summary(votes_f: frozenset, accepted_f: frozenset,
                      new_total: int, old_summary: tuple,
                      old_total: int) -> bool:
    """Registry form of _is_newer: the old statement's frozensets come
    from the per-node summary map instead of a fresh XDR walk + set()
    build per envelope.  Growth is measured on the RAW vote-list lengths
    (a hostile statement may carry duplicates; collapsing them here would
    change which replays get rejected)."""
    old_votes_f, old_accepted_f = old_summary
    if not (old_votes_f <= votes_f and old_accepted_f <= accepted_f):
        return False
    return new_total > old_total


class NominationProtocol:
    def __init__(self, slot):
        self.slot = slot
        self.round_number = 0
        self.votes: Set[bytes] = set()
        self.accepted: Set[bytes] = set()
        self.candidates: Set[bytes] = set()
        self.latest_nominations: Dict[bytes, object] = {}  # node -> envelope
        # node -> (votes frozenset, accepted frozenset), in lockstep with
        # latest_nominations
        self._summaries: Dict[bytes, tuple] = {}
        # node -> len(votes) + len(accepted) of the RAW lists (the
        # _is_newer growth measure; kept separately so the summary tuple
        # shape stays (votes, accepted) for every existing consumer)
        self._summary_sizes: Dict[bytes, int] = {}
        # per-value voter registries, updated with each statement's DELTA
        # (sound because _is_newer guarantees vote sets only grow): the
        # federated accept/ratify calls below take these materialized
        # sets instead of sweeping every statement per value per envelope
        self._voted_nom: Dict[bytes, set] = {}      # value -> voters
        self._accepted_nom: Dict[bytes, set] = {}   # value -> accepters
        # incremental per-slot quorum state over the nomination statement
        # map; nomination vote sets only ever grow (_is_newer), so
        # accept/ratify verdicts LATCH per value (quorum.StatementIndex)
        self.index = Q.StatementIndex()
        self.last_envelope = None            # last nomination we emitted
        self.round_leaders: Set[bytes] = set()
        self.nomination_started = False
        self.latest_composite: Optional[bytes] = None
        self.previous_value = b""
        # leader-candidate set cache: normalize_qset + qset_nodes build
        # fresh XDR trees per round otherwise (keyed by local qset hash
        # so a mid-slot qset change recomputes)
        self._cand_qset_hash: Optional[bytes] = None
        self._leader_candidates: Set[bytes] = set()

    # --- statement access -------------------------------------------------
    def _stmt_map(self) -> Dict[bytes, tuple]:
        """node -> (votes frozenset, accepted frozenset) summary — the
        map the federated predicates run over.  Compiled once per
        statement at intake (set membership instead of XDR list scans —
        same move as ballot.py's statement summaries) and maintained
        incrementally."""
        return self._summaries

    @staticmethod
    def _nom(st):
        return st.pledges.nominate

    def _is_newer(self, st, old_st) -> bool:
        """Old statement is subsumed if votes+accepted grew."""
        a, b = self._nom(old_st), self._nom(st)
        if not (set(a.votes) <= set(b.votes)):
            return False
        if not (set(a.accepted) <= set(b.accepted)):
            return False
        return (len(b.votes) + len(b.accepted)
                > len(a.votes) + len(a.accepted))

    @staticmethod
    def _sane(st) -> bool:
        nom = st.pledges.nominate
        return (len(nom.votes) + len(nom.accepted)) > 0

    # --- leader election --------------------------------------------------
    def _node_priority(self, node_id: bytes) -> int:
        d, ln = self.slot.driver, self.slot.local_node
        w = (ln.node_weight(node_id) if node_id != ln.node_id
             else (1 << 64) - 1)  # local node always max weight (reference)
        if d.compute_hash_node(self.slot.slot_index, self.previous_value,
                               False, self.round_number, node_id) < w:
            return d.compute_hash_node(self.slot.slot_index,
                                       self.previous_value, True,
                                       self.round_number, node_id)
        return 0

    def update_round_leaders(self) -> None:
        ln = self.slot.local_node
        if self._cand_qset_hash != ln.qset_hash:
            qset = Q.normalize_qset(ln.qset, remove=ln.node_id)
            self._leader_candidates = {ln.node_id} | Q.qset_nodes(qset)
            self._cand_qset_hash = ln.qset_hash
        candidates = self._leader_candidates
        top_priority, leaders = 0, set()
        for n in candidates:
            p = self._node_priority(n)
            if p > top_priority:
                top_priority, leaders = p, {n}
            elif p == top_priority and p > 0:
                leaders.add(n)
        self.round_leaders |= leaders  # leaders accumulate across rounds

    # --- value adoption ---------------------------------------------------
    def _validate(self, value: bytes) -> Optional[bytes]:
        lvl = self.slot.driver.validate_value(self.slot.slot_index, value,
                                              nomination=True)
        if lvl in (ValidationLevel.FULLY_VALIDATED,
                   ValidationLevel.VOTE_TO_NOMINATE):
            return value
        # Any non-fully-valid value (INVALID included) goes through
        # extract_valid_value, which may repair it by stripping unwanted
        # upgrades (reference: getNewValueFromNomination calls
        # extractValidValue for every non-fully-valid value).
        return self.slot.driver.extract_valid_value(self.slot.slot_index,
                                                    value)

    def _value_from_nomination(self, nom) -> Optional[bytes]:
        """Highest-value-hash valid value from one nomination statement.
        Reference: NominationProtocol::getNewValueFromNomination."""
        d = self.slot.driver
        best, best_hash = None, -1
        for v in list(nom.votes) + list(nom.accepted):
            vv = self._validate(v)
            if vv is None:
                continue
            h = d.compute_value_hash(self.slot.slot_index,
                                     self.previous_value,
                                     self.round_number, vv)
            if h > best_hash:
                best, best_hash = vv, h
        return best

    def _new_value_from_leaders(self) -> Optional[bytes]:
        d = self.slot.driver
        best, best_hash = None, -1
        for leader in self.round_leaders:
            env = self.latest_nominations.get(leader)
            if env is None:
                continue
            v = self._value_from_nomination(self._nom(env.statement))
            if v is None:
                continue
            h = d.compute_value_hash(self.slot.slot_index,
                                     self.previous_value,
                                     self.round_number, v)
            if h > best_hash:
                best, best_hash = v, h
        return best

    # --- emission ---------------------------------------------------------
    def _emit_nomination(self) -> None:
        st = SX.SCPStatement(
            nodeID=self.slot.local_node_xdr_id(),
            slotIndex=self.slot.slot_index,
            pledges=SX.SCPStatementPledges.nominate(SX.SCPNomination(
                quorumSetHash=self.slot.local_node.qset_hash,
                votes=sorted(self.votes),
                accepted=sorted(self.accepted))))
        env = self.slot.create_envelope(st)
        # process our own statement first (reference: emits only if valid)
        if self.process_envelope(env, self_env=True):
            if (self.last_envelope is None
                    or self._is_newer(env.statement,
                                      self.last_envelope.statement)):
                self.last_envelope = env
                if self.slot.fully_validated:
                    self.slot.driver.emit_envelope(env)

    # --- protocol entry points -------------------------------------------
    def nominate(self, value: bytes, previous_value: bytes,
                 timed_out: bool) -> bool:
        """Called by herder (round 1) and by the round timer (timed_out)."""
        if timed_out and not self.nomination_started:
            return False
        self.nomination_started = True
        self.previous_value = previous_value
        self.round_number += 1
        self.update_round_leaders()

        updated = False
        if self.slot.local_node.node_id in self.round_leaders:
            if value not in self.votes:
                vv = self._validate(value)
                if vv is not None:
                    self.votes.add(vv)
                    updated = True
        # always also adopt this round's best value from every leader's stored
        # nomination — votes only grow, and without this, rounds where every
        # node is its own (accumulated) leader would stop exchanging values
        # and nomination would livelock.
        v = self._new_value_from_leaders()
        if v is not None and v not in self.votes:
            self.votes.add(v)
            updated = True

        d = self.slot.driver
        timeout = d.compute_timeout(self.round_number, is_nomination=True)
        d.nominating_value(self.slot.slot_index, value)
        d.setup_timer(
            self.slot.slot_index, NOMINATION_TIMER, timeout,
            lambda: self.slot.nominate(value, previous_value, timed_out=True))
        if updated:
            self._emit_nomination()
        return updated

    def stop_nomination(self) -> None:
        self.nomination_started = False
        self.slot.driver.stop_timer(self.slot.slot_index, NOMINATION_TIMER)

    def process_envelope(self, env, self_env: bool = False) -> bool:
        """Returns True if the envelope was valid and processed."""
        st = env.statement
        nid = st.nodeID.value
        if not self._sane(st):
            return False
        old = self.latest_nominations.get(nid)
        nom_st = self._nom(st)
        votes_f = frozenset(nom_st.votes)
        accepted_f = frozenset(nom_st.accepted)
        new_total = len(nom_st.votes) + len(nom_st.accepted)
        old_summary = self._summaries.get(nid)
        if old is not None:
            # newer-statement check against the compiled-frozenset
            # registry — no XDR re-walk of the superseded statement
            if old_summary is not None:
                if not _newer_by_summary(votes_f, accepted_f, new_total,
                                         old_summary,
                                         self._summary_sizes[nid]):
                    return False
            elif not self._is_newer(st, old.statement):
                return False
        self.latest_nominations[nid] = env
        self._summaries[nid] = (votes_f, accepted_f)
        self._summary_sizes[nid] = new_total
        for v in (votes_f if old_summary is None
                  else votes_f - old_summary[0]):
            self._voted_nom.setdefault(v, set()).add(nid)
        for v in (accepted_f if old_summary is None
                  else accepted_f - old_summary[1]):
            self._accepted_nom.setdefault(v, set()).add(nid)
        self.index.note_statement(nid, 0, self.slot.qset_of_statement(st),
                                  Q.statement_qset_hash(st))
        if not self.nomination_started:
            return True

        ln = self.slot.local_node
        nom = self._nom(st)
        modified = new_candidates = False
        empty: set = set()

        for v in list(nom.votes) + list(nom.accepted):
            if v in self.accepted:
                continue
            if ln.federated_accept_sets(
                    self._voted_nom.get(v, empty),
                    self._accepted_nom.get(v, empty),
                    index=self.index, key=("nom-acc", v), latch=True):
                vv = self._validate(v)
                if vv is None:
                    continue
                self.accepted.add(v)
                self.votes.add(v)
                modified = True
        for v in self.accepted - self.candidates:
            if ln.federated_ratify_sets(
                    self._accepted_nom.get(v, empty),
                    index=self.index, key=("nom-rat", v), latch=True):
                self.candidates.add(v)
                new_candidates = True

        # a round leader's nomination arrived: adopt its best value
        # (reference: processEnvelope → getNewValueFromNomination)
        if not self_env and nid in self.round_leaders:
            v = self._value_from_nomination(nom)
            if v is not None and v not in self.votes:
                self.votes.add(v)
                modified = True

        if modified:
            # also on self_env: accepting values while processing our own
            # statement must still be announced (the recursion terminates —
            # votes/accepted only grow, and unchanged state isn't re-emitted)
            self._emit_nomination()
        if new_candidates:
            composite = self.slot.driver.combine_candidates(
                self.slot.slot_index, sorted(self.candidates))
            if composite is not None:
                self.latest_composite = composite
                self.slot.driver.updated_candidate_value(
                    self.slot.slot_index, composite)
                self.slot.bump_state(composite, force=False)
        return True

    def get_latest_message(self, node_id: bytes):
        return self.latest_nominations.get(node_id)

    def current_state(self) -> List:
        return [self.last_envelope] if self.last_envelope else []
