"""Quorum-set logic: slices, v-blocking sets, transitive quorum discovery.

Reference: src/scp/LocalNode.{h,cpp} — LocalNode::{isQuorumSlice, isVBlocking,
isQuorum, forAllNodes}; src/scp/QuorumSetUtils.{h,cpp} — isQuorumSetSane,
normalizeQSet.  Re-designed as free functions over frozen node-id sets (the
TPU quorum-intersection enumerator in accel/quorum.py shares the same bitmask
encoding produced by QGraph below).
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, Optional, Set

from ..crypto.sha import sha256
from ..xdr import scp as SX

# Node ids are the raw 32-byte ed25519 key (hashable); X.NodeID <-> bytes
# conversion happens at the SCP envelope boundary (slot.py).
NodeIDb = bytes

MAX_NESTING_LEVEL = 4  # reference: QuorumSetUtils.cpp — MAXIMUM_QUORUM_NESTING_LEVEL


def qset_hash(qset) -> bytes:
    """SHA-256 of the XDR encoding (content address used in SCP statements)."""
    return sha256(qset.to_xdr())


def for_all_nodes(qset, fn: Callable[[NodeIDb], None]) -> None:
    for v in qset.validators:
        fn(v.value)
    for inner in qset.innerSets:
        for_all_nodes(inner, fn)


def qset_nodes(qset) -> Set[NodeIDb]:
    out: Set[NodeIDb] = set()
    for_all_nodes(qset, out.add)
    return out


def is_quorum_slice(qset, nodes: Set[NodeIDb]) -> bool:
    """True iff `nodes` contains at least one slice of `qset`."""
    count = 0
    for v in qset.validators:
        if v.value in nodes:
            count += 1
    for inner in qset.innerSets:
        if is_quorum_slice(inner, nodes):
            count += 1
    return count >= qset.threshold


def is_v_blocking(qset, nodes: Set[NodeIDb]) -> bool:
    """True iff `nodes` intersects every slice of `qset` (can block quorum)."""
    if qset.threshold == 0:
        return False
    left = len(qset.validators) + len(qset.innerSets) - qset.threshold + 1
    for v in qset.validators:
        if v.value in nodes:
            left -= 1
            if left <= 0:
                return True
    for inner in qset.innerSets:
        if is_v_blocking(inner, nodes):
            left -= 1
            if left <= 0:
                return True
    return False


def compile_qset(qset) -> tuple:
    """Flatten a qset into plain nested tuples ``(threshold,
    (validator_bytes, ...), (inner, ...))`` — slice checks over the
    compiled form skip the per-field XDR descriptor machinery, which
    dominates `is_quorum` wall time on large simulated networks (a
    51-node hierarchical sim spent 21s of a 37s consensus run inside
    `is_quorum_slice` before this)."""
    return (qset.threshold,
            tuple(v.value for v in qset.validators),
            tuple(compile_qset(i) for i in qset.innerSets))


# id(qset) -> (qset, compiled form).  XDR structs are __slots__-bound (no
# per-instance memo field) and hashing the canonical encoding per lookup
# costs more than the walk it would save, so the cache key is the object
# id — made safe by pinning a strong reference to the keyed object in the
# value (an id is only ever reused after its object is collected, and a
# pinned object never is).  SCP treats quorum sets as immutable once
# announced; mutating a cached instance in place would go unseen.
# Bounded: distinct qset instances per process are few (one per herder
# per topology shape), but a long fuzz run must not grow this without
# limit — on overflow the cache is dropped wholesale, unpinning ids.
_COMPILED_CACHE_MAX = 4096
_compiled_cache: Dict[int, tuple] = {}


def compile_qset_cached(qset) -> tuple:
    got = _compiled_cache.get(id(qset))
    if got is not None:
        return got[1]
    if len(_compiled_cache) >= _COMPILED_CACHE_MAX:
        _compiled_cache.clear()
    cq = compile_qset(qset)
    _compiled_cache[id(qset)] = (qset, cq)
    return cq


def _compiled_slice_ok(cq: tuple, nodes: Set[NodeIDb]) -> bool:
    threshold, validators, inners = cq
    if threshold <= 0:
        # is_quorum_slice returns count >= 0 == True for a threshold-0
        # set; the early-exit walk below would return False when no
        # member matches, silently diverging on (insane but legal-to-
        # construct) inputs is_qset_sane never vetted
        return True
    count = 0
    for v in validators:
        if v in nodes:
            count += 1
            if count >= threshold:
                return True
    for inner in inners:
        if _compiled_slice_ok(inner, nodes):
            count += 1
            if count >= threshold:
                return True
    return False


def is_quorum(local_qset, stmt_map: Dict[NodeIDb, object],
              qset_of: Callable[[object], Optional[object]],
              voted: Callable[[object], bool]) -> bool:
    """True iff the nodes whose statement satisfies `voted` contain a quorum
    that includes a slice of local_qset.

    Transitive fixpoint: repeatedly drop nodes whose own quorum set (looked up
    from their statement via `qset_of`) has no slice inside the surviving set.
    Reference: LocalNode::isQuorum.

    Nodes sharing one qset object (the common case: every validator in a
    tier-1-shaped network announces the same hierarchical set) share ONE
    compiled form and ONE slice evaluation per fixpoint iteration instead
    of re-walking the XDR tree per node.
    """
    nodes = {n for n, st in stmt_map.items() if voted(st)}
    node_cq: Dict[NodeIDb, Optional[tuple]] = {}
    for n in nodes:
        q = qset_of(stmt_map[n])
        node_cq[n] = None if q is None else compile_qset_cached(q)
    while True:
        verdicts: Dict[int, bool] = {}  # id(compiled) -> slice-in-`nodes`
        keep = set()
        for n in nodes:
            cq = node_cq[n]
            if cq is None:
                continue
            ok = verdicts.get(id(cq))
            if ok is None:
                ok = verdicts[id(cq)] = _compiled_slice_ok(cq, nodes)
            if ok:
                keep.add(n)
        if keep == nodes:
            break
        nodes = keep
    return _compiled_slice_ok(compile_qset_cached(local_qset), nodes)


def find_closest_v_blocking(qset, nodes: Set[NodeIDb],
                            excluded: Optional[NodeIDb] = None) -> Set[NodeIDb]:
    """A small v-blocking subset of `nodes` w.r.t. qset (greedy heuristic).
    Reference: LocalNode::findClosestVBlocking."""
    left = qset.threshold
    members = []
    for v in qset.validators:
        nid = v.value
        if nid == excluded:
            continue
        if nid in nodes:
            members.append({nid})
        else:
            left -= 1
    for inner in qset.innerSets:
        sub = find_closest_v_blocking(inner, nodes, excluded)
        if sub:
            members.append(sub)
        else:
            left -= 1
    # need to hit (n - threshold + 1) slices; the non-member slots already
    # "hit" themselves by failing.
    needed = len(members) - left + 1
    if needed <= 0:
        return set()
    members.sort(key=len)
    out: Set[NodeIDb] = set()
    for m in members[:needed]:
        out |= m
    return out


def is_qset_sane(qset, extra_checks: bool = False, depth: int = 0) -> bool:
    """Reference: QuorumSetUtils.cpp — isQuorumSetSane.  Thresholds within
    range, nesting bounded, no duplicate nodes."""
    if depth > MAX_NESTING_LEVEL:
        return False
    n = len(qset.validators) + len(qset.innerSets)
    if n == 0 or qset.threshold < 1 or qset.threshold > n:
        return False
    if extra_checks and qset.threshold < 1 + (n + 1) // 2:  # require majority
        return False
    for inner in qset.innerSets:
        if not is_qset_sane(inner, extra_checks, depth + 1):
            return False
    seen: Set[NodeIDb] = set()

    ok = [True]

    def check(nid):
        if nid in seen:
            ok[0] = False
        seen.add(nid)

    for_all_nodes(qset, check)
    return ok[0]


def normalize_qset(qset, remove: Optional[NodeIDb] = None):
    """Flatten trivial inner sets (threshold==n==1) and drop `remove`,
    decrementing the threshold per removed member (removal models "that
    node always agrees", e.g. removing self from the local qset).
    Reference: QuorumSetUtils.cpp — normalizeQSet.  Returns a new qset."""
    validators = []
    threshold = qset.threshold
    for v in qset.validators:
        if v.value == remove:
            threshold -= 1
        else:
            validators.append(v)
    inner = []
    for i in qset.innerSets:
        ni = normalize_qset(i, remove)
        n = len(ni.validators) + len(ni.innerSets)
        if n == 0 or ni.threshold <= 0:
            # inner set auto-satisfied (or emptied) by the removal
            threshold -= 1
            continue
        if ni.threshold == 1 and len(ni.validators) == 1 and not ni.innerSets:
            validators.append(ni.validators[0])
        else:
            inner.append(ni)
    return SX.SCPQuorumSet(threshold=max(threshold, 0), validators=validators,
                           innerSets=inner)


def singleton_qset(node_id: NodeIDb):
    from ..xdr import types as XT
    return SX.SCPQuorumSet(threshold=1, validators=[XT.node_id(node_id)],
                           innerSets=[])
