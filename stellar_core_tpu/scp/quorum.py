"""Quorum-set logic: slices, v-blocking sets, transitive quorum discovery.

Reference: src/scp/LocalNode.{h,cpp} — LocalNode::{isQuorumSlice, isVBlocking,
isQuorum, forAllNodes}; src/scp/QuorumSetUtils.{h,cpp} — isQuorumSetSane,
normalizeQSet.  Re-designed as free functions over frozen node-id sets (the
TPU quorum-intersection enumerator in accel/quorum.py shares the same bitmask
encoding produced by QGraph below).
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, Optional, Set

from ..crypto.sha import sha256
from ..xdr import scp as SX

# Node ids are the raw 32-byte ed25519 key (hashable); X.NodeID <-> bytes
# conversion happens at the SCP envelope boundary (slot.py).
NodeIDb = bytes

MAX_NESTING_LEVEL = 4  # reference: QuorumSetUtils.cpp — MAXIMUM_QUORUM_NESTING_LEVEL


def qset_hash(qset) -> bytes:
    """SHA-256 of the XDR encoding (content address used in SCP statements)."""
    return sha256(qset.to_xdr())


def statement_qset_hash(st) -> bytes:
    """The quorum-set hash a statement pledges under (every pledge type
    carries one)."""
    pl = st.pledges
    t = pl.type
    if t == SX.SCPStatementType.SCP_ST_NOMINATE:
        return pl.nominate.quorumSetHash
    if t == SX.SCPStatementType.SCP_ST_PREPARE:
        return pl.prepare.quorumSetHash
    if t == SX.SCPStatementType.SCP_ST_CONFIRM:
        return pl.confirm.quorumSetHash
    return pl.externalize.commitQuorumSetHash


def for_all_nodes(qset, fn: Callable[[NodeIDb], None]) -> None:
    for v in qset.validators:
        fn(v.value)
    for inner in qset.innerSets:
        for_all_nodes(inner, fn)


def qset_nodes(qset) -> Set[NodeIDb]:
    out: Set[NodeIDb] = set()
    for_all_nodes(qset, out.add)
    return out


def is_quorum_slice(qset, nodes: Set[NodeIDb]) -> bool:
    """True iff `nodes` contains at least one slice of `qset`."""
    count = 0
    for v in qset.validators:
        if v.value in nodes:
            count += 1
    for inner in qset.innerSets:
        if is_quorum_slice(inner, nodes):
            count += 1
    return count >= qset.threshold


def is_v_blocking(qset, nodes: Set[NodeIDb]) -> bool:
    """True iff `nodes` intersects every slice of `qset` (can block quorum)."""
    if qset.threshold == 0:
        return False
    left = len(qset.validators) + len(qset.innerSets) - qset.threshold + 1
    for v in qset.validators:
        if v.value in nodes:
            left -= 1
            if left <= 0:
                return True
    for inner in qset.innerSets:
        if is_v_blocking(inner, nodes):
            left -= 1
            if left <= 0:
                return True
    return False


def is_v_blocking_compiled(cq: tuple, nodes: Set[NodeIDb]) -> bool:
    """is_v_blocking over a compile_qset form — the v-blocking arm of
    every federated_accept runs per envelope, and the XDR descriptor walk
    was the last per-envelope qset traversal left after the round-11
    slice compilation (same move as _compiled_slice_ok)."""
    threshold, validators, inners = cq
    if threshold == 0:
        return False
    left = len(validators) + len(inners) - threshold + 1
    for v in validators:
        if v in nodes:
            left -= 1
            if left <= 0:
                return True
    for inner in inners:
        if is_v_blocking_compiled(inner, nodes):
            left -= 1
            if left <= 0:
                return True
    return False


def compile_qset(qset) -> tuple:
    """Flatten a qset into plain nested tuples ``(threshold,
    (validator_bytes, ...), (inner, ...))`` — slice checks over the
    compiled form skip the per-field XDR descriptor machinery, which
    dominates `is_quorum` wall time on large simulated networks (a
    51-node hierarchical sim spent 21s of a 37s consensus run inside
    `is_quorum_slice` before this)."""
    return (qset.threshold,
            tuple(v.value for v in qset.validators),
            tuple(compile_qset(i) for i in qset.innerSets))


# id(qset) -> (qset, compiled form).  XDR structs are __slots__-bound (no
# per-instance memo field) and hashing the canonical encoding per lookup
# costs more than the walk it would save, so the cache key is the object
# id — made safe by pinning a strong reference to the keyed object in the
# value (an id is only ever reused after its object is collected, and a
# pinned object never is).  SCP treats quorum sets as immutable once
# announced; mutating a cached instance in place would go unseen.
# Bounded: distinct qset instances per process are few (one per herder
# per topology shape), but a long fuzz run must not grow this without
# limit — on overflow the cache is dropped wholesale, unpinning ids.
_COMPILED_CACHE_MAX = 4096
_compiled_cache: Dict[int, tuple] = {}


def compile_qset_cached(qset) -> tuple:
    got = _compiled_cache.get(id(qset))
    if got is not None:
        return got[1]
    if len(_compiled_cache) >= _COMPILED_CACHE_MAX:
        _compiled_cache.clear()
    cq = compile_qset(qset)
    _compiled_cache[id(qset)] = (qset, cq)
    return cq


def _compiled_slice_ok(cq: tuple, nodes: Set[NodeIDb]) -> bool:
    threshold, validators, inners = cq
    if threshold <= 0:
        # is_quorum_slice returns count >= 0 == True for a threshold-0
        # set; the early-exit walk below would return False when no
        # member matches, silently diverging on (insane but legal-to-
        # construct) inputs is_qset_sane never vetted
        return True
    count = 0
    for v in validators:
        if v in nodes:
            count += 1
            if count >= threshold:
                return True
    for inner in inners:
        if _compiled_slice_ok(inner, nodes):
            count += 1
            if count >= threshold:
                return True
    return False


class StatementIndex:
    """Incremental per-slot quorum state (reference: ``Slot``'s cached
    ``mHeardFromQuorum`` edge + ``BallotProtocol::checkHeardFromQuorum``).

    The owning protocol (ballot or nomination) calls ``note_statement``
    every time a node's latest statement is replaced, which keeps three
    incrementally-maintained views the quorum walks would otherwise
    re-derive from XDR on EVERY envelope (the ~n^2 cost that kept the
    300-node soak at offline scale):

    - ``node_counter`` — each node's ballot counter (INT32_MAX for
      EXTERNALIZE, 0 for nominations), replacing a per-envelope
      ``{n: _counter_of(st)}`` rebuild;
    - ``node_cq`` — each node's COMPILED quorum set, replacing the
      per-``is_quorum``-call ``qset_of(stmt)`` + compile lookup per node;
    - a verdict memo keyed by the statement-map **epoch** (bumped on
      every mutation), so repeated quorum questions against an unchanged
      map answer from cache.

    Monotone verdicts (heard-from-quorum at a fixed counter, nomination
    accept/ratify of a fixed value) may additionally be **latched**: once
    True they stay True, because statements only ever get *newer* —
    counters are non-decreasing and nomination vote sets only grow, so a
    satisfied quorum predicate cannot be un-satisfied.  The two events
    that CAN invalidate a latch are handled explicitly: a node changing
    its announced quorum set mid-slot, and a ballot-counter regression
    (possible across a PREPARE→CONFIRM phase edge, and cheap insurance
    against Byzantine statement orderings) — both bump ``qset_epoch``
    and drop every latch, falling back to a full recompute.
    """

    __slots__ = ("epoch", "qset_epoch", "node_counter", "node_cq",
                 "node_qhash", "_memo", "_latched")

    # stale-epoch memo entries never hit; cap the dict so a pathological
    # slot (many candidate ballots) cannot grow it without bound
    MEMO_MAX = 8192

    def __init__(self):
        self.epoch = 0
        self.qset_epoch = 0
        self.node_counter: Dict[NodeIDb, int] = {}
        self.node_cq: Dict[NodeIDb, Optional[tuple]] = {}
        self.node_qhash: Dict[NodeIDb, bytes] = {}
        self._memo: Dict[tuple, tuple] = {}    # key -> (epoch, verdict)
        self._latched: set = set()

    def note_statement(self, node_id: NodeIDb, counter: int,
                       qset, qhash: bytes) -> None:
        """Record that `node_id`'s latest statement is now (counter,
        qset).  `qset` may be None when the referenced set is not yet
        fetched — the quorum walks then skip the node, exactly as the
        uncached path did."""
        self.epoch += 1
        if len(self._memo) > self.MEMO_MAX:
            self._memo.clear()
        pc = self.node_counter.get(node_id)
        oh = self.node_qhash.get(node_id)
        if (pc is not None and counter < pc) or \
                (oh is not None and oh != qhash):
            self.qset_epoch += 1
            self._latched.clear()
        self.node_counter[node_id] = counter
        self.node_cq[node_id] = None if qset is None \
            else compile_qset_cached(qset)
        self.node_qhash[node_id] = qhash

    def lookup(self, key: tuple) -> Optional[bool]:
        if key in self._latched:
            return True
        got = self._memo.get(key)
        if got is not None and got[0] == self.epoch:
            return got[1]
        return None

    def store(self, key: tuple, verdict: bool, latch: bool = False) -> None:
        if latch and verdict:
            self._latched.add(key)
        else:
            self._memo[key] = (self.epoch, verdict)


def quorum_survivors(nodes: Set[NodeIDb],
                     node_cq: Dict[NodeIDb, Optional[tuple]]
                     ) -> Set[NodeIDb]:
    """Transitive fixpoint over compiled qsets: repeatedly drop nodes
    whose own quorum set has no slice inside the surviving set (the core
    of LocalNode::isQuorum).  Nodes sharing one compiled qset share ONE
    slice evaluation per iteration."""
    while True:
        verdicts: Dict[int, bool] = {}
        keep = set()
        for n in nodes:
            cq = node_cq.get(n)
            if cq is None:
                continue
            ok = verdicts.get(id(cq))
            if ok is None:
                ok = verdicts[id(cq)] = _compiled_slice_ok(cq, nodes)
            if ok:
                keep.add(n)
        if keep == nodes:
            return nodes
        nodes = keep


def quorum_contains(local_qset, nodes: Set[NodeIDb],
                    node_cq: Dict[NodeIDb, Optional[tuple]]) -> bool:
    """is_quorum over an ALREADY-MATERIALIZED voting-node set (callers
    that maintain per-value voter registries incrementally skip the
    per-call O(n) predicate sweep entirely)."""
    return _compiled_slice_ok(compile_qset_cached(local_qset),
                              quorum_survivors(set(nodes), node_cq))


def heard_from_quorum(local_qset, local_qset_hash: bytes,
                      index: StatementIndex, min_counter: int) -> bool:
    """Latched heard-from-quorum: do the voting nodes (ballot counter >=
    `min_counter`) contain a transitively-closed quorum with a slice of
    `local_qset`?  Verdicts latch per (counter, local qset) — see
    StatementIndex."""
    key = ("hfq", min_counter, local_qset_hash)
    got = index.lookup(key)
    if got is not None:
        return got
    voted = {n for n, c in index.node_counter.items() if c >= min_counter}
    res = _compiled_slice_ok(compile_qset_cached(local_qset),
                             quorum_survivors(voted, index.node_cq))
    index.store(key, res, latch=True)
    return res


def v_blocking_ahead(local_qset, local_qset_hash: bytes,
                     index: StatementIndex, counter: int) -> bool:
    """Latched counter catch-up check (BallotProtocol::_attempt_bump): is
    a v-blocking set announcing ballot counters >= `counter`?  The
    voting-node set only grows and counters are non-decreasing (a
    regression bumps qset_epoch and drops every latch — see
    StatementIndex), so a True verdict is monotone for the slot and
    latches exactly like heard_from_quorum."""
    key = ("vba", counter, local_qset_hash)
    got = index.lookup(key)
    if got is not None:
        return got
    nodes = {n for n, c in index.node_counter.items() if c >= counter}
    res = is_v_blocking_compiled(compile_qset_cached(local_qset), nodes)
    index.store(key, res, latch=True)
    return res


def is_quorum(local_qset, stmt_map: Dict[NodeIDb, object],
              qset_of: Callable[[object], Optional[object]],
              voted: Callable[[object], bool],
              index: Optional[StatementIndex] = None) -> bool:
    """True iff the nodes whose statement satisfies `voted` contain a quorum
    that includes a slice of local_qset.

    Transitive fixpoint: repeatedly drop nodes whose own quorum set (looked up
    from their statement via `qset_of`) has no slice inside the surviving set.
    Reference: LocalNode::isQuorum.

    Nodes sharing one qset object (the common case: every validator in a
    tier-1-shaped network announces the same hierarchical set) share ONE
    compiled form and ONE slice evaluation per fixpoint iteration instead
    of re-walking the XDR tree per node.

    With an `index` (StatementIndex maintained by the owning protocol),
    each node's compiled qset comes from the incremental per-slot view
    instead of a `qset_of` lookup + compile per node per call.
    """
    nodes = {n for n, st in stmt_map.items() if voted(st)}
    if index is not None:
        node_cq = index.node_cq
    else:
        node_cq = {}
        for n in nodes:
            q = qset_of(stmt_map[n])
            node_cq[n] = None if q is None else compile_qset_cached(q)
    return _compiled_slice_ok(compile_qset_cached(local_qset),
                              quorum_survivors(nodes, node_cq))


def find_closest_v_blocking(qset, nodes: Set[NodeIDb],
                            excluded: Optional[NodeIDb] = None) -> Set[NodeIDb]:
    """A small v-blocking subset of `nodes` w.r.t. qset (greedy heuristic).
    Reference: LocalNode::findClosestVBlocking."""
    left = qset.threshold
    members = []
    for v in qset.validators:
        nid = v.value
        if nid == excluded:
            continue
        if nid in nodes:
            members.append({nid})
        else:
            left -= 1
    for inner in qset.innerSets:
        sub = find_closest_v_blocking(inner, nodes, excluded)
        if sub:
            members.append(sub)
        else:
            left -= 1
    # need to hit (n - threshold + 1) slices; the non-member slots already
    # "hit" themselves by failing.
    needed = len(members) - left + 1
    if needed <= 0:
        return set()
    members.sort(key=len)
    out: Set[NodeIDb] = set()
    for m in members[:needed]:
        out |= m
    return out


def is_qset_sane(qset, extra_checks: bool = False, depth: int = 0) -> bool:
    """Reference: QuorumSetUtils.cpp — isQuorumSetSane.  Thresholds within
    range, nesting bounded, no duplicate nodes."""
    if depth > MAX_NESTING_LEVEL:
        return False
    n = len(qset.validators) + len(qset.innerSets)
    if n == 0 or qset.threshold < 1 or qset.threshold > n:
        return False
    if extra_checks and qset.threshold < 1 + (n + 1) // 2:  # require majority
        return False
    for inner in qset.innerSets:
        if not is_qset_sane(inner, extra_checks, depth + 1):
            return False
    seen: Set[NodeIDb] = set()

    ok = [True]

    def check(nid):
        if nid in seen:
            ok[0] = False
        seen.add(nid)

    for_all_nodes(qset, check)
    return ok[0]


def normalize_qset(qset, remove: Optional[NodeIDb] = None):
    """Flatten trivial inner sets (threshold==n==1) and drop `remove`,
    decrementing the threshold per removed member (removal models "that
    node always agrees", e.g. removing self from the local qset).
    Reference: QuorumSetUtils.cpp — normalizeQSet.  Returns a new qset."""
    validators = []
    threshold = qset.threshold
    for v in qset.validators:
        if v.value == remove:
            threshold -= 1
        else:
            validators.append(v)
    inner = []
    for i in qset.innerSets:
        ni = normalize_qset(i, remove)
        n = len(ni.validators) + len(ni.innerSets)
        if n == 0 or ni.threshold <= 0:
            # inner set auto-satisfied (or emptied) by the removal
            threshold -= 1
            continue
        if ni.threshold == 1 and len(ni.validators) == 1 and not ni.innerSets:
            validators.append(ni.validators[0])
        else:
            inner.append(ni)
    return SX.SCPQuorumSet(threshold=max(threshold, 0), validators=validators,
                           innerSets=inner)


def singleton_qset(node_id: NodeIDb):
    from ..xdr import types as XT
    return SX.SCPQuorumSet(threshold=1, validators=[XT.node_id(node_id)],
                           innerSets=[])
