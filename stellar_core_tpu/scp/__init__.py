"""Pure SCP (Stellar Consensus Protocol) library — federated Byzantine
agreement with open membership via quorum slices.

Reference: src/scp/ — reusable library depending only on XDR + crypto + util
(SURVEY.md §1 layer 7).  No app dependencies; the herder implements SCPDriver.
"""

from .ballot import (PHASE_CONFIRM, PHASE_EXTERNALIZE,  # noqa: F401
                     PHASE_PREPARE, BallotProtocol)
from .driver import (BALLOT_PROTOCOL_TIMER, NOMINATION_TIMER,  # noqa: F401
                     SCPDriver, ValidationLevel)
from .local_node import LocalNode  # noqa: F401
from .nomination import NominationProtocol  # noqa: F401
from .quorum import (find_closest_v_blocking, is_qset_sane,  # noqa: F401
                     is_quorum, is_quorum_slice, is_v_blocking,
                     normalize_qset, qset_hash, qset_nodes, singleton_qset)
from .scp import SCP, EnvelopeState  # noqa: F401
from .slot import Slot  # noqa: F401
