"""LocalNode — this node's identity + quorum set, and the federated-voting
primitives evaluated against a map of latest statements.

Reference: src/scp/LocalNode.{h,cpp} — getNodeWeight, federatedAccept/
federatedRatify live on Slot in the reference; here they sit with the node
since they only need the local qset + a statement map.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Set

from . import quorum as Q

UINT64_MAX = (1 << 64) - 1


class LocalNode:
    def __init__(self, node_id: bytes, qset, is_validator: bool = True):
        self.node_id = node_id
        self.qset = qset
        self.qset_hash = Q.qset_hash(qset)
        self.is_validator = is_validator

    def update_qset(self, qset) -> None:
        self.qset = qset
        self.qset_hash = Q.qset_hash(qset)

    # --- leader-election weight ------------------------------------------
    def node_weight(self, node_id: bytes, qset=None) -> int:
        """Fraction of slices containing node_id, in units of 2^64-1.
        Reference: LocalNode::getNodeWeight (bigDivide, round-down)."""
        qset = qset if qset is not None else self.qset
        n = len(qset.validators) + len(qset.innerSets)
        t = qset.threshold
        for v in qset.validators:
            if v.value == node_id:
                return UINT64_MAX * t // n
        for inner in qset.innerSets:
            w = self.node_weight(node_id, inner)
            if w:
                return w * t // n
        return 0

    # --- federated voting -------------------------------------------------
    def federated_accept(self, voted: Callable[[object], bool],
                         accepted: Callable[[object], bool],
                         stmt_map: Dict[bytes, object],
                         qset_of: Callable[[object], Optional[object]],
                         index=None, key=None, latch: bool = False) -> bool:
        """vote→accept: a v-blocking set accepted it, or a quorum voted-or-
        accepted it.

        `index`/`key`/`latch`: per-slot incremental quorum state (see
        quorum.StatementIndex) — the whole verdict is memoized under the
        statement-map epoch, and `latch=True` (monotone predicates only:
        nomination votes) pins a True verdict for the slot."""
        k = None
        if index is not None and key is not None:
            k = ("fa", key, self.qset_hash)
            got = index.lookup(k)
            if got is not None:
                return got
        accepted_nodes = {n for n, st in stmt_map.items() if accepted(st)}
        if Q.is_v_blocking_compiled(Q.compile_qset_cached(self.qset),
                                    accepted_nodes):
            res = True
        else:
            res = Q.is_quorum(self.qset, stmt_map, qset_of,
                              lambda st: voted(st) or accepted(st),
                              index=index)
        if k is not None:
            index.store(k, res, latch)
        return res

    def federated_ratify(self, voted: Callable[[object], bool],
                         stmt_map: Dict[bytes, object],
                         qset_of: Callable[[object], Optional[object]],
                         index=None, key=None, latch: bool = False) -> bool:
        """accept→confirm: a quorum accepted it."""
        k = None
        if index is not None and key is not None:
            k = ("fr", key, self.qset_hash)
            got = index.lookup(k)
            if got is not None:
                return got
        res = Q.is_quorum(self.qset, stmt_map, qset_of, voted, index=index)
        if k is not None:
            index.store(k, res, latch)
        return res

    def is_v_blocking(self, nodes: Set[bytes]) -> bool:
        return Q.is_v_blocking_compiled(Q.compile_qset_cached(self.qset),
                                        nodes)

    # --- set-based fast paths ---------------------------------------------
    # Callers that maintain per-value voter registries incrementally
    # (nomination: vote sets only grow, so each envelope contributes its
    # DELTA) pass materialized node sets instead of predicates — the
    # per-call O(n) statement sweep was the last n^2 term per envelope
    # at 300 simulated nodes.  Verdicts are memoized/latched through the
    # same StatementIndex discipline as the predicate forms.
    def federated_accept_sets(self, voted_nodes: Set[bytes],
                              accepted_nodes: Set[bytes],
                              index, key, latch: bool = False) -> bool:
        k = ("fa", key, self.qset_hash)
        got = index.lookup(k)
        if got is not None:
            return got
        if Q.is_v_blocking_compiled(Q.compile_qset_cached(self.qset),
                                    accepted_nodes):
            res = True
        else:
            res = Q.quorum_contains(self.qset,
                                    voted_nodes | accepted_nodes,
                                    index.node_cq)
        index.store(k, res, latch)
        return res

    def federated_ratify_sets(self, accepted_nodes: Set[bytes],
                              index, key, latch: bool = False) -> bool:
        k = ("fr", key, self.qset_hash)
        got = index.lookup(k)
        if got is not None:
            return got
        res = Q.quorum_contains(self.qset, accepted_nodes, index.node_cq)
        index.store(k, res, latch)
        return res
