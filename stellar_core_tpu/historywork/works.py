"""Historywork: Work units for archive I/O and catchup.

Reference: src/historywork/{GetAndUnzipRemoteFileWork, BatchDownloadWork,
VerifyLedgerChainWork}.cpp and src/catchup/{CatchupWork,
DownloadApplyTxsWork, ApplyCheckpointWork}.cpp — catchup as a DAG of
retryable work units, with checkpoint k+1's download/verify overlapping
checkpoint k's apply (double-buffering, SURVEY.md §5.8).  The TPU
pre-verify is double-buffered the same way: as soon as a checkpoint's
download completes, its signature batch is DISPATCHED (async, no device
sync) while earlier checkpoints still apply; the verdicts are collected
only when that checkpoint's own apply starts.  Small checkpoints are
coalesced into one device batch (the tunnel's per-dispatch latency
dominates below ~100k sigs — BASELINE.md).  Signer pairing against the
then-current ledger state stays exact because SetOptions-added signers are
harvested cumulatively across dispatched checkpoints
(catchup.PreverifyPipeline).

The archive reads are synchronous file IO here (no subprocess curl), but
the unit boundaries, retry semantics and pipelining match the reference's
shape: a failed download/verify retries with backoff without restarting
the whole catchup; apply is strictly sequential and cooperative (a few
ledgers per crank) so downloads interleave on the same clock.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from .. import xdr as X
from ..catchup.catchup import (CatchupError, PreverifyPipeline,
                               verify_ledger_chain)
from ..crypto.sha import sha256
from ..history.archive import (CATEGORY_LEDGER, CATEGORY_TRANSACTIONS,
                               FileHistoryArchive, category_path,
                               checkpoint_containing, checkpoint_frequency)
from ..transactions.frame import TransactionFrame
import time

from ..util import eventlog
from ..util import logging as slog
from ..util import perf
from ..util import tracing
from ..util.clock import VirtualClock
from ..util.metrics import registry as _registry
from ..work.work import (RETRY_A_FEW, RETRY_NEVER, BasicWork, State, Work)

# checkpoint downloads are slow by nature — the 1s LogSlowExecution
# default would warn on every archive fetch (per-name override surface:
# util.perf.set_slow_threshold)
perf.set_slow_threshold("catchup.download.checkpoint", 30.0)

log = slog.get("History")

_LHHE = X.LedgerHeaderHistoryEntry._xdr_adapter()
_THE = X.TransactionHistoryEntry._xdr_adapter()


class GetAndVerifyCheckpointWork(BasicWork):
    """Download one checkpoint's ledger + transactions files and verify the
    header hash chain.  Retries with backoff on missing/corrupt data
    (reference: BatchDownloadWork unit + VerifyLedgerChainWork merged per
    checkpoint).

    When `network_id` is given, every envelope is also decoded into a
    TransactionFrame here, ONCE — both the accel pre-verify dispatch and
    the apply consume these same frames (the frame memoizes its
    content_hash), instead of each re-decoding the whole stream
    (VERDICT r3 weak #2: the double XDR decode was most of the gap between
    the 1.14x accel margin and its ~1.3x verify-share bound)."""

    def __init__(self, clock: VirtualClock, archive: FileHistoryArchive,
                 checkpoint: int, network_id: Optional[bytes] = None,
                 decode_txs: bool = True, keep_raw: bool = False):
        """decode_txs=False keeps the transaction records RAW (for the
        native apply engine, which parses them itself; each record is
        strict-scanned by the C parser at download so corrupt archives
        keep their retry-with-backoff contract) — the decoded txs/frames
        views are then built lazily by ensure_decoded() on the
        Python-fallback path only.  keep_raw retains the raw records even
        when decoding (the accel+native path needs both)."""
        super().__init__(clock, f"get-verify-{checkpoint:08x}",
                         max_retries=RETRY_A_FEW)
        self.archive = archive
        self.checkpoint = checkpoint
        self.network_id = network_id
        self.decode_txs = decode_txs
        self.keep_raw = keep_raw or not decode_txs
        self.headers: List[X.LedgerHeaderHistoryEntry] = []
        self.raw_headers: List[bytes] = []
        self.raw_txs: Dict[int, bytes] = {}
        self.txs: Dict[int, X.TransactionHistoryEntry] = {}
        self.frames: Dict[int, List[TransactionFrame]] = {}

    def on_reset(self) -> None:
        self.headers = []
        self.raw_headers = []
        self.raw_txs = {}
        self.txs = {}
        self.frames = {}

    def ensure_decoded(self) -> None:
        """Decode any raw tx records not yet decoded (the download may have
        decoded only the scan-rejected ones) — the Python-fallback apply
        path and the accel pairing need objects."""
        for seq, raw in self.raw_txs.items():
            if seq not in self.txs:
                self.txs[seq] = _THE.unpack(raw)
            if self.network_id is not None and seq not in self.frames:
                self.frames[seq] = [
                    TransactionFrame.make_from_wire(self.network_id, env)
                    for env in self.txs[seq].txSet.txs]

    def all_frames(self) -> List[TransactionFrame]:
        """Every decoded frame of the checkpoint in ledger order (the
        pre-verify dispatch batch)."""
        out: List[TransactionFrame] = []
        for seq in sorted(self.frames):
            out.extend(self.frames[seq])
        return out

    def on_run(self) -> State:
        with tracing.span("catchup.download", checkpoint=self.checkpoint), \
                perf.scoped_timer("catchup.download.checkpoint"):
            return self._download_and_verify()

    def _download_and_verify(self) -> State:
        try:
            recs = self.archive.get_xdr_file(
                category_path(CATEGORY_LEDGER, self.checkpoint))
            if recs is None:
                log.warning("%s: ledger file missing", self.name)
                return State.FAILURE
            headers = [_LHHE.unpack(r) for r in recs]
            verify_ledger_chain(headers)
            raw_txs: Dict[int, bytes] = {}
            txs: Dict[int, X.TransactionHistoryEntry] = {}
            frames: Dict[int, List[TransactionFrame]] = {}
            scan = None
            if not self.decode_txs and self.network_id is not None:
                try:
                    from stellar_core_tpu import _capply
                    scan = _capply.scan_tx_record
                    scan_err = _capply.Error
                except ImportError:
                    pass
            for r in self.archive.get_xdr_file(
                    category_path(CATEGORY_TRANSACTIONS,
                                  self.checkpoint)) or []:
                # TransactionHistoryEntry leads with its u32 ledgerSeq
                if len(r) < 4:
                    raise CatchupError("truncated tx record")
                if self.keep_raw:
                    raw_txs[int.from_bytes(r[:4], "big")] = r
                if self.decode_txs:
                    e = _THE.unpack(r)
                    txs[e.ledgerSeq] = e
                    if self.network_id is not None:
                        frames[e.ledgerSeq] = [
                            TransactionFrame.make_from_wire(
                                self.network_id, env)
                            for env in e.txSet.txs]
                elif scan is not None:
                    try:
                        rc, _ = scan(self.network_id, r)
                    except scan_err as exc:
                        raise CatchupError(str(exc)) from exc
                    if rc != 0:
                        # well-formed but outside the native set: decode
                        # NOW (strict, retryable) so the fallback apply
                        # never hits a first-time decode error
                        e = _THE.unpack(r)
                        txs[e.ledgerSeq] = e
        except (X.XdrError, CatchupError, ValueError, OSError) as e:
            # corrupt OR hostile archive data (bad gzip, truncated record
            # mark/body, inflate-cap bomb, XDR decode failure): retry with
            # backoff, then the catchup fails with a localized error
            log.warning("%s: %s", self.name, e)
            return State.FAILURE
        self.headers = headers
        self.raw_headers = recs
        self.raw_txs = raw_txs
        self.txs = txs
        self.frames = frames
        return State.SUCCESS


class ApplyCheckpointWork(BasicWork):
    """Apply one downloaded checkpoint's ledgers, a few per crank
    (cooperative — downloads for later checkpoints interleave).  With
    accel, the checkpoint's signature verdicts were dispatched earlier by
    CatchupWork (possibly coalesced with neighbours); the first crank only
    COLLECTS them — by then the device has had the previous checkpoints'
    apply time to compute (reference: ApplyCheckpointWork; the async
    collect is the TPU double-buffering seam)."""

    LEDGERS_PER_CRANK = 8

    def __init__(self, clock: VirtualClock, mgr,
                 download: GetAndVerifyCheckpointWork, target: int,
                 network_id: bytes,
                 pipeline: Optional[PreverifyPipeline] = None):
        super().__init__(clock, f"apply-{download.checkpoint:08x}",
                         max_retries=RETRY_NEVER)
        self.mgr = mgr
        self.download = download
        self.target = target
        self.network_id = network_id
        self.pipeline = pipeline
        self._idx = 0
        self._preverified = False
        self._native_rejected = False
        self._t_first_crank: Optional[float] = None
        self.error_detail = None

    def _fail(self, detail: str) -> State:
        self.error_detail = detail
        log.error("%s: %s", self.name, detail)
        return State.FAILURE

    def _run_native(self, bridge) -> Optional[State]:
        """Apply the whole checkpoint through the native engine.  Returns
        the work State, or None to fall back to the Python path (probe
        rejected — unsupported tx shapes in this checkpoint)."""
        mgr = self.mgr
        headers = self.download.headers
        raw_headers = self.download.raw_headers
        raw_txs = self.download.raw_txs
        # pending rows only (resume semantics mirror the Python loop)
        rows = [(entry, raw_headers[i])
                for i, entry in enumerate(headers)
                if entry.header.ledgerSeq > mgr.last_closed_ledger_seq]
        rows = [rw for rw in rows if rw[0].header.ledgerSeq <= self.target]
        if not rows:
            return State.SUCCESS
        tx_recs = [raw_txs.get(e.header.ledgerSeq) for e, _ in rows]
        if not bridge.probe(tx_recs):
            # fallback forfeit accounting: every checkpoint that leaves
            # the native engine gives up its ~3x apply rate — make a
            # silent regression visible in stats + the bench trajectory
            bridge.fallback_checkpoints += 1
            _registry().meter("catchup.native.fallback").mark()
            if bridge.active:
                bridge.export_to_manager(mgr)
            try:
                self.download.ensure_decoded()
            except Exception as e:
                return self._fail(f"tx decode failed on fallback: {e}")
            if self.pipeline is not None:
                # honest hit-rate denominator: the raw extraction did not
                # count records the C parser rejected — re-count this
                # checkpoint from the decoded frames
                python_total = sum(
                    len(f.signatures)
                    for frames in self.download.frames.values()
                    for f in frames)
                self.pipeline.correct_total_for_fallback(
                    self.download.checkpoint, python_total)
            return None
        if not bridge.active:
            bridge.import_from(mgr)
        try:
            bridge.apply_checkpoint([raw for _, raw in rows], tx_recs,
                                    self.target)
        except Exception as e:
            return self._fail(f"native apply failed: {e}")
        bridge.native_checkpoints += 1
        _registry().meter("catchup.native.checkpoint").mark()
        _registry().meter("catchup.apply.ledger").mark(len(rows))
        # bookkeeping: the manager's LCL view advances with the engine
        # (full state stays in C until export); the engine verified these
        # hashes against its own serialization fail-stop
        # the engine verified every applied header hash against its own
        # serialization (fail-stop in close_one_ledger); mirror its LCL
        seq, lcl_hash = bridge.lcl()
        tail = next(e for e, _ in reversed(rows)
                    if e.header.ledgerSeq == seq)
        mgr.lcl_header = tail.header
        mgr.lcl_hash = lcl_hash
        return State.SUCCESS

    def _checkpoint_frames(self) -> List[TransactionFrame]:
        if self.download.frames or not self.download.txs:
            return self.download.all_frames()
        # download ran without a network id: decode here, ONCE — store back
        # on the download so the apply loop below reuses these same frames
        for seq, the in self.download.txs.items():
            self.download.frames[seq] = [
                TransactionFrame.make_from_wire(self.network_id, env)
                for env in the.txSet.txs]
        return self.download.all_frames()

    def on_run(self) -> State:
        if self._t_first_crank is None:
            self._t_first_crank = time.perf_counter()
        with tracing.span("catchup.apply-checkpoint",
                          checkpoint=self.download.checkpoint):
            state = self._run_crank()
        if state == State.SUCCESS:
            # wall-clock from first crank to completion — includes the
            # preverify collect and any cooperative-yield gaps, which is
            # the honest per-checkpoint apply latency
            dur_s = time.perf_counter() - self._t_first_crank
            _registry().timer("catchup.apply.checkpoint").update(dur_s)
            # checkpoint verdict: one flight event per checkpoint keeps
            # post-mortems cheap even on thousand-checkpoint replays
            eventlog.record("History", "INFO", "checkpoint applied",
                            checkpoint=self.download.checkpoint,
                            lcl=self.mgr.last_closed_ledger_seq,
                            dur_ms=round(dur_s * 1e3, 1))
            tracing.mark_phase("checkpoint-apply",
                               self.download.checkpoint,
                               lcl=self.mgr.last_closed_ledger_seq,
                               dur_ms=round(dur_s * 1e3, 1))
        elif state == State.FAILURE:
            eventlog.record("History", "ERROR", "checkpoint apply FAILED",
                            checkpoint=self.download.checkpoint,
                            detail=self.error_detail or "?")
        return state

    def _run_crank(self) -> State:
        mgr = self.mgr
        headers = self.download.headers
        if self.pipeline is not None and not self._preverified:
            self._preverified = True
            cp = self.download.checkpoint
            if not self.pipeline.dispatched(cp):
                # CatchupWork dispatches ahead; this is the standalone /
                # degenerate path (e.g. the work used outside CatchupWork)
                if self.pipeline.pair_extractor is not None:
                    self.pipeline.dispatch_raw(
                        {cp: [self.download.raw_txs[seq]
                              for seq in sorted(self.download.raw_txs)]})
                else:
                    self.pipeline.dispatch({cp: self._checkpoint_frames()},
                                           ledger_state=mgr.root)
            self.pipeline.collect(cp)
            return State.RUNNING
        bridge = getattr(mgr, "native_bridge", None)
        if bridge is not None and not self._native_rejected:
            state = self._run_native(bridge)
            if state is not None:
                return state
            # probe rejected the checkpoint (memoized): state was exported
            # back to the Python manager; the oracle path below applies
            # this checkpoint on every subsequent crank
            self._native_rejected = True
        applied = 0
        while self._idx < len(headers) and applied < self.LEDGERS_PER_CRANK:
            entry = headers[self._idx]
            seq = entry.header.ledgerSeq
            if seq <= mgr.last_closed_ledger_seq:
                self._idx += 1
                continue
            if seq > self.target:
                return State.SUCCESS
            if seq != mgr.last_closed_ledger_seq + 1:
                return self._fail(f"gap in headers at {seq}")
            the = self.download.txs.get(seq)
            tx_set = the.txSet if the is not None else X.TransactionSet(
                previousLedgerHash=mgr.lcl_hash, txs=[])
            if sha256(tx_set.to_xdr()) != entry.header.scpValue.txSetHash:
                return self._fail(f"tx set hash mismatch at ledger {seq}")
            # frames were decoded once at download (and already carried the
            # accel pre-verify batch); re-decode only on the degenerate
            # standalone path where the download ran without a network id
            frames = self.download.frames.get(seq)
            if frames is None:
                frames = [TransactionFrame.make_from_wire(
                    self.network_id, env) for env in tx_set.txs]
            try:
                mgr.close_ledger(frames, entry.header.scpValue.closeTime,
                                 tx_set=tx_set,
                                 expected_ledger_hash=entry.hash,
                                 stellar_value=entry.header.scpValue)
            except Exception as e:
                return self._fail(f"apply failed at ledger {seq}: {e}")
            _registry().meter("catchup.apply.ledger").mark()
            self._idx += 1
            applied += 1
        if self._idx >= len(headers) \
                or mgr.last_closed_ledger_seq >= self.target:
            return State.SUCCESS
        return State.RUNNING


class CatchupWork(Work):
    """Pipelined complete-replay catchup: downloads run `lookahead`
    checkpoints ahead of the sequential apply cursor (reference:
    CatchupWork + DownloadApplyTxsWork's download-ahead of one checkpoint
    while the previous applies).  With accel, completed downloads are
    additionally PRE-DISPATCHED to the device in checkpoint order —
    coalescing up to 2*`coalesce` checkpoints per device batch once
    `coalesce` are ready, or immediately when the apply cursor is about to
    need them — so device compute overlaps host apply (SURVEY §5.8)."""

    def __init__(self, clock: VirtualClock, mgr, archive: FileHistoryArchive,
                 target: int, network_id: bytes, accel: bool = False,
                 accel_chunk: int = 8192, lookahead: int = 2,
                 stats: Optional[dict] = None, coalesce: int = 4,
                 accel_hot_threshold: int = 1 << 62,
                 decode_txs: bool = True, keep_raw: bool = False,
                 verdict_sink=None, pair_extractor=None,
                 accel_profile: Optional[str] = None,
                 checkpoint_hook=None):
        super().__init__(clock, "catchup", max_retries=RETRY_NEVER)
        self.mgr = mgr
        self.archive = archive
        self.target = target
        self.network_id = network_id
        self.accel = accel
        self.decode_txs = decode_txs
        self.keep_raw = keep_raw
        self.verdict_sink = verdict_sink
        self.accel_chunk = accel_chunk
        self.coalesce = max(1, coalesce)
        # after every applied checkpoint: checkpoint_hook(lcl) may return
        # a LOWER published boundary to truncate the target mid-replay —
        # the work-stealing seam (a range worker that accepted a steal
        # limit stops at the split boundary; catchup/parallel.py)
        self.checkpoint_hook = checkpoint_hook
        self.pipeline = (PreverifyPipeline(network_id, accel_chunk,
                                           stats if stats is not None
                                           else {},
                                           hot_threshold=accel_hot_threshold,
                                           verdict_sink=verdict_sink,
                                           pair_extractor=pair_extractor,
                                           profile=accel_profile)
                         if accel else None)
        # poll/sig-only profiles auto-tune the coalesce depth against the
        # measured consumer rate (PreverifyPipeline.recommended_coalesce)
        self.auto_coalesce = (self.pipeline is not None
                              and self.pipeline.profile
                              != PreverifyPipeline.PROFILE_RACE)
        # the download window must run ahead of the dispatch groups for
        # coalescing to ever trigger (sized for the auto-tune's ceiling)
        max_coalesce = (PreverifyPipeline.MAX_COALESCE if self.auto_coalesce
                        else self.coalesce)
        self.lookahead = max(1, lookahead,
                             2 * max_coalesce if accel else 0)
        self.stats = self.pipeline.stats if self.pipeline is not None \
            else (stats if stats is not None else {})
        self._downloads: Dict[int, GetAndVerifyCheckpointWork] = {}
        self._apply: Optional[ApplyCheckpointWork] = None
        self._apply_checkpoint = 0
        self._next_dispatch = 0
        self._prev_tail: Optional[X.LedgerHeaderHistoryEntry] = None
        self.error_detail = None

    def on_reset(self) -> None:
        super().on_reset()
        self._downloads = {}
        self._apply = None
        # resume from wherever the manager already is: complete catchup
        # starts at genesis's checkpoint, CATCHUP_RECENT at the first
        # checkpoint past the assumed bucket state (CatchupRange)
        self._apply_checkpoint = checkpoint_containing(
            max(2, self.mgr.last_closed_ledger_seq + 1))
        self._next_dispatch = self._apply_checkpoint
        self._prev_tail = None

    def _close_pipeline(self) -> None:
        if self.pipeline is not None:
            self.pipeline.close()

    def on_failure_raise(self) -> None:
        self._close_pipeline()

    def on_aborted(self) -> None:
        self._close_pipeline()

    def _maybe_dispatch(self, last_cp: int) -> None:
        """Feed the device: walk completed, not-yet-dispatched downloads in
        checkpoint order (in-order dispatch keeps the cumulative SetOptions
        harvest a superset of every signer the apply will try) and enqueue
        them as one coalesced batch when enough are ready — or right away
        when the apply cursor is within one checkpoint of the group, where
        waiting would stall the pipeline."""
        ready = []
        c = self._next_dispatch
        while c <= last_cp:
            dl = self._downloads.get(c)
            if dl is None or not dl.done or dl.failed:
                break
            ready.append(c)
            c += checkpoint_frequency()
        if not ready:
            return
        urgent = ready[0] <= self._apply_checkpoint + checkpoint_frequency()
        if not urgent and len(ready) < self.coalesce:
            return
        # collect() blocks on a whole group's batch, so the group about to
        # be awaited must be SMALL (1 checkpoint) while the lookahead tail
        # coalesces into `coalesce`-sized batches that the device chews
        # through during earlier applies
        groups: List[List[int]] = []
        i = 0
        if urgent:
            groups.append(ready[:1])
            i = 1
        while i < len(ready):
            groups.append(ready[i:i + self.coalesce])
            i += self.coalesce
        for g in groups:
            if self.pipeline.pair_extractor is not None:
                self.pipeline.dispatch_raw(
                    {cp: [self._downloads[cp].raw_txs[seq]
                          for seq in sorted(self._downloads[cp].raw_txs)]
                     for cp in g})
            else:
                self.pipeline.dispatch(
                    {cp: self._downloads[cp].all_frames() for cp in g},
                    ledger_state=self.mgr.root)
        self._next_dispatch = ready[-1] + checkpoint_frequency()

    def on_run(self) -> State:
        if self.mgr.last_closed_ledger_seq >= self.target:
            self._close_pipeline()
            return State.SUCCESS
        if self.auto_coalesce:
            self.coalesce = self.pipeline.recommended_coalesce(self.coalesce)
        # keep the download window full (never past the target checkpoint)
        cp = self._apply_checkpoint
        last_cp = checkpoint_containing(self.target)
        for k in range(self.lookahead):
            c = cp + k * checkpoint_frequency()
            if c > last_cp:
                break
            if c not in self._downloads:
                w = GetAndVerifyCheckpointWork(self.clock, self.archive, c,
                                               network_id=self.network_id,
                                               decode_txs=self.decode_txs,
                                               keep_raw=self.keep_raw)
                self._downloads[c] = w
                self.add_work(w)
        if self.pipeline is not None:
            self._maybe_dispatch(last_cp)
        dl = self._downloads.get(cp)
        if dl is None or not dl.done:
            return State.WAITING
        if dl.failed:
            self.error_detail = f"checkpoint {cp} download unrecoverable"
            log.error("catchup: %s", self.error_detail)
            return State.FAILURE
        # cross-checkpoint chain continuity
        if self._apply is None:
            prev_hash = (self._prev_tail.hash if self._prev_tail is not None
                         else self.mgr.lcl_hash)  # assumed-state anchor
            if prev_hash is not None and dl.headers and \
                    dl.headers[0].header.ledgerSeq \
                    == self.mgr.last_closed_ledger_seq + 1 and \
                    dl.headers[0].header.previousLedgerHash != prev_hash:
                self.error_detail = f"chain broken across checkpoint {cp}"
                log.error("catchup: %s", self.error_detail)
                return State.FAILURE
            self._apply = ApplyCheckpointWork(
                self.clock, self.mgr, dl, self.target, self.network_id,
                pipeline=self.pipeline)
            self.add_work(self._apply)
            return State.WAITING
        if not self._apply.done:
            return State.WAITING
        if self._apply.failed:
            self.error_detail = self._apply.error_detail \
                or f"apply of checkpoint {cp} failed"
            return State.FAILURE
        if dl.headers:
            self._prev_tail = dl.headers[-1]
        del self._downloads[cp]
        self._apply = None
        self._apply_checkpoint = cp + checkpoint_frequency()
        if self.checkpoint_hook is not None:
            # work-stealing seam: the hook reports progress and may hand
            # back a lower published boundary (>= the LCL we just reached)
            # that truncates this replay — the stolen tail is someone
            # else's range now
            new_target = self.checkpoint_hook(self.mgr.last_closed_ledger_seq)
            if new_target is not None \
                    and self.mgr.last_closed_ledger_seq <= new_target \
                    < self.target:
                log.info("catchup target truncated %d -> %d (checkpoint "
                         "hook)", self.target, new_target)
                self.target = new_target
        if self.mgr.last_closed_ledger_seq >= self.target:
            self._close_pipeline()
            return State.SUCCESS
        return State.RUNNING
