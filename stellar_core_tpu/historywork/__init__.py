"""Work units for history archive I/O and catchup (reference: src/historywork/)."""

from .works import (ApplyCheckpointWork, CatchupWork,
                    GetAndVerifyCheckpointWork)

__all__ = ["ApplyCheckpointWork", "CatchupWork",
           "GetAndVerifyCheckpointWork"]
