"""Hashing primitives. Reference: src/crypto/SHA.{h,cpp} — sha256, SHA256 (streaming);
src/crypto/ShortHash.h — shortHash (SipHash-2-4, used for cache keys/hints)."""

from __future__ import annotations

import hashlib
import hmac as _hmac
import struct


def sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def sha512(data: bytes) -> bytes:
    return hashlib.sha512(data).digest()


class SHA256:
    """Streaming SHA-256 (reference: src/crypto/SHA.h — class SHA256)."""

    def __init__(self) -> None:
        self._h = hashlib.sha256()

    def add(self, data: bytes) -> "SHA256":
        self._h.update(data)
        return self

    def finish(self) -> bytes:
        return self._h.digest()


def hmac_sha256(key: bytes, data: bytes) -> bytes:
    return _hmac.new(key, data, hashlib.sha256).digest()


def hmac_sha256_verify(key: bytes, data: bytes, mac: bytes) -> bool:
    return _hmac.compare_digest(hmac_sha256(key, data), mac)


def hkdf_extract(key: bytes) -> bytes:
    """Reference overlay key derivation (src/crypto/ECDH.cpp — hkdfExtract):
    HMAC with a zero salt."""
    return hmac_sha256(b"\x00" * 32, key)


def hkdf_expand(key: bytes, info: bytes) -> bytes:
    return hmac_sha256(key, info + b"\x01")


def _sipround(v0: int, v1: int, v2: int, v3: int) -> tuple[int, int, int, int]:
    M = 0xFFFFFFFFFFFFFFFF
    v0 = (v0 + v1) & M
    v1 = ((v1 << 13) | (v1 >> 51)) & M
    v1 ^= v0
    v0 = ((v0 << 32) | (v0 >> 32)) & M
    v2 = (v2 + v3) & M
    v3 = ((v3 << 16) | (v3 >> 48)) & M
    v3 ^= v2
    v0 = (v0 + v3) & M
    v3 = ((v3 << 21) | (v3 >> 43)) & M
    v3 ^= v0
    v2 = (v2 + v1) & M
    v1 = ((v1 << 17) | (v1 >> 47)) & M
    v1 ^= v2
    v2 = ((v2 << 32) | (v2 >> 32)) & M
    return v0, v1, v2, v3


def siphash24(key: bytes, data: bytes) -> int:
    """SipHash-2-4 → uint64 (reference: lib/siphash, src/crypto/ShortHash.cpp)."""
    assert len(key) == 16
    k0, k1 = struct.unpack("<QQ", key)
    v0 = k0 ^ 0x736F6D6570736575
    v1 = k1 ^ 0x646F72616E646F6D
    v2 = k0 ^ 0x6C7967656E657261
    v3 = k1 ^ 0x7465646279746573
    b = len(data) & 0xFF
    i = 0
    while i + 8 <= len(data):
        (m,) = struct.unpack_from("<Q", data, i)
        v3 ^= m
        v0, v1, v2, v3 = _sipround(v0, v1, v2, v3)
        v0, v1, v2, v3 = _sipround(v0, v1, v2, v3)
        v0 ^= m
        i += 8
    tail = data[i:] + b"\x00" * (8 - len(data[i:]))
    (m,) = struct.unpack("<Q", tail[:8])
    m = (m & ((1 << 56) - 1)) | (b << 56)
    v3 ^= m
    v0, v1, v2, v3 = _sipround(v0, v1, v2, v3)
    v0, v1, v2, v3 = _sipround(v0, v1, v2, v3)
    v0 ^= m
    v2 ^= 0xFF
    for _ in range(4):
        v0, v1, v2, v3 = _sipround(v0, v1, v2, v3)
    return v0 ^ v1 ^ v2 ^ v3
