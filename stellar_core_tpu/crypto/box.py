"""Curve25519 sealed boxes for survey-response encryption.

Reference: src/overlay/SurveyManager uses libsodium ``crypto_box_seal`` —
an anonymous-sender ECIES over Curve25519 — so only the surveyor (holder of
the ephemeral Curve25519 secret in the request) can read a survey response.

This environment has libsodium at runtime but without headers, and the
framework only declares a handful of prototypes (SURVEY.md §7), so the seal
is composed from the primitives already wrapped: X25519 ECDH
(``crypto_scalarmult_curve25519``) + an HMAC-SHA256 keystream and tag.
Same security shape (ephemeral-static DH, key-committing MAC), not
byte-compatible with libsodium's box — both ends of a survey run this
framework, so wire compatibility is internal.

Layout: ``eph_pk(32) || tag(32) || ciphertext``.
"""

from __future__ import annotations

import hmac
import os
from hashlib import sha256 as _sha256

from . import sodium


def keypair(seed: bytes = None) -> tuple:
    """(public, secret) Curve25519 keypair; random unless seeded."""
    sk = bytearray(seed if seed is not None else os.urandom(32))
    # standard X25519 clamping
    sk[0] &= 248
    sk[31] &= 127
    sk[31] |= 64
    sk = bytes(sk)
    return sodium.scalarmult_curve25519_base(sk), sk


def _keystream(key: bytes, n: int) -> bytes:
    out = bytearray()
    counter = 0
    while len(out) < n:
        out += hmac.new(key, b"stream%d" % counter, _sha256).digest()
        counter += 1
    return bytes(out[:n])


def _derive(shared: bytes, eph_pk: bytes, recip_pk: bytes) -> tuple:
    base = _sha256(b"scb-seal" + shared + eph_pk + recip_pk).digest()
    enc_key = _sha256(base + b"enc").digest()
    mac_key = _sha256(base + b"mac").digest()
    return enc_key, mac_key


def seal(recipient_pk: bytes, plaintext: bytes) -> bytes:
    eph_pk, eph_sk = keypair()
    shared = sodium.scalarmult_curve25519(eph_sk, recipient_pk)
    enc_key, mac_key = _derive(shared, eph_pk, recipient_pk)
    ct = bytes(a ^ b for a, b in
               zip(plaintext, _keystream(enc_key, len(plaintext))))
    tag = hmac.new(mac_key, ct, _sha256).digest()
    return eph_pk + tag + ct


def seal_open(recipient_sk: bytes, blob: bytes) -> bytes:
    """Decrypt; raises ValueError on malformed input or MAC mismatch."""
    if len(blob) < 64:
        raise ValueError("sealed box too short")
    eph_pk, tag, ct = blob[:32], blob[32:64], blob[64:]
    recipient_pk = sodium.scalarmult_curve25519_base(recipient_sk)
    shared = sodium.scalarmult_curve25519(recipient_sk, eph_pk)
    enc_key, mac_key = _derive(shared, eph_pk, recipient_pk)
    if not hmac.compare_digest(tag, hmac.new(mac_key, ct, _sha256).digest()):
        raise ValueError("sealed box MAC mismatch")
    return bytes(a ^ b for a, b in zip(ct, _keystream(enc_key, len(ct))))
