"""StrKey: Stellar's human-readable key encoding.

Reference: src/crypto/StrKey.{h,cpp} — base32 (RFC 4648 alphabet, no padding
in the canonical form) over [version byte | payload | CRC16-XModem(LE)].

Version bytes (reference: src/crypto/StrKey.h — STRKEY_PUBKEY etc.):
  G = 6  << 3   ed25519 public key
  S = 18 << 3   ed25519 seed
  T = 19 << 3   pre-auth tx hash
  X = 23 << 3   sha256 hash-x signer
  M = 12 << 3   muxed account (ed25519 + 8-byte id)
  C = 2  << 3   contract id
"""

from __future__ import annotations

import base64
from enum import IntEnum


class StrKeyVersion(IntEnum):
    PUBKEY_ED25519 = 6 << 3        # 'G'
    SEED_ED25519 = 18 << 3         # 'S'
    PRE_AUTH_TX = 19 << 3          # 'T'
    HASH_X = 23 << 3               # 'X'
    MUXED_ED25519 = 12 << 3        # 'M'
    SIGNED_PAYLOAD = 15 << 3       # 'P'
    CONTRACT = 2 << 3              # 'C'


_PAYLOAD_LEN = {
    StrKeyVersion.PUBKEY_ED25519: (32,),
    StrKeyVersion.SEED_ED25519: (32,),
    StrKeyVersion.PRE_AUTH_TX: (32,),
    StrKeyVersion.HASH_X: (32,),
    StrKeyVersion.MUXED_ED25519: (40,),
    StrKeyVersion.CONTRACT: (32,),
    StrKeyVersion.SIGNED_PAYLOAD: tuple(range(32 + 4 + 4, 32 + 4 + 64 + 1)),
}


def crc16_xmodem(data: bytes) -> int:
    """CRC16/XMODEM (poly 0x1021, init 0): matches reference src/crypto/StrKey.cpp."""
    crc = 0
    for b in data:
        crc ^= b << 8
        for _ in range(8):
            crc = ((crc << 1) ^ 0x1021) & 0xFFFF if crc & 0x8000 else (crc << 1) & 0xFFFF
    return crc


def encode(version: StrKeyVersion, payload: bytes) -> str:
    raw = bytes([version]) + payload
    crc = crc16_xmodem(raw)
    raw += bytes([crc & 0xFF, crc >> 8])  # little-endian checksum
    enc = base64.b32encode(raw).decode("ascii")
    return enc.rstrip("=")


def decode(version: StrKeyVersion, s: str) -> bytes:
    payload, got_version = decode_any(s)
    if got_version != version:
        raise ValueError(f"strkey version mismatch: want {version}, got {got_version}")
    return payload


def decode_any(s: str) -> tuple[bytes, StrKeyVersion]:
    if not s or s != s.upper():
        raise ValueError("strkey must be upper-case base32")
    # b32decode needs padding restored; canonical strkeys carry none.
    pad = (-len(s)) % 8
    if pad == 1 or pad == 3 or pad == 6:
        raise ValueError("invalid strkey length")
    try:
        raw = base64.b32decode(s + "=" * pad)
    except Exception as e:
        raise ValueError(f"invalid base32: {e}") from e
    if len(raw) < 3:
        raise ValueError("strkey too short")
    body, crc_bytes = raw[:-2], raw[-2:]
    crc = crc16_xmodem(body)
    if crc_bytes != bytes([crc & 0xFF, crc >> 8]):
        raise ValueError("strkey checksum mismatch")
    try:
        version = StrKeyVersion(body[0])
    except ValueError as e:
        raise ValueError(f"unknown strkey version byte {body[0]}") from e
    payload = body[1:]
    if len(payload) not in _PAYLOAD_LEN[version]:
        raise ValueError("bad strkey payload length")
    # Reject non-canonical encodings (trailing bits / over-padding), as the
    # reference does: re-encode must round-trip.
    if encode(version, payload) != s:
        raise ValueError("non-canonical strkey")
    return payload, version


def encode_public_key(raw: bytes) -> str:
    return encode(StrKeyVersion.PUBKEY_ED25519, raw)


def decode_public_key(s: str) -> bytes:
    return decode(StrKeyVersion.PUBKEY_ED25519, s)


def encode_seed(raw: bytes) -> str:
    return encode(StrKeyVersion.SEED_ED25519, raw)


def decode_seed(s: str) -> bytes:
    return decode(StrKeyVersion.SEED_ED25519, s)
