"""Key types and signature verification with the verify-result cache.

Reference: src/crypto/SecretKey.{h,cpp} — SecretKey, PublicKey,
PubKeyUtils::verifySig (libsodium verify + RandomEvictionCache keyed by
hash(sig‖key‖msg)), KeyUtils; src/crypto/SignerKey.h.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional

from . import sodium, strkey
from .sha import sha256
from ..util.cache import RandomEvictionCache
from ..util.lockorder import make_lock
from ..util.metrics import registry as _registry

VERIFY_CACHE_SIZE = 0x10000  # reference: 64k-entry verify cache


@dataclass(frozen=True)
class PublicKey:
    """Ed25519 public key (XDR: PublicKey{PUBLIC_KEY_TYPE_ED25519, uint256})."""

    ed25519: bytes  # 32 bytes

    def __post_init__(self) -> None:
        if len(self.ed25519) != 32:
            raise ValueError("ed25519 public key must be 32 bytes")

    def to_strkey(self) -> str:
        return strkey.encode_public_key(self.ed25519)

    @staticmethod
    def from_strkey(s: str) -> "PublicKey":
        return PublicKey(strkey.decode_public_key(s))

    def hint(self) -> bytes:
        """Signature hint: last 4 bytes of the key (XDR SignatureHint).
        Reference: src/crypto/SignerKeyUtils / SignatureUtils — getHint."""
        return self.ed25519[28:32]

    def __repr__(self) -> str:
        return f"PublicKey({self.to_strkey()})"


class SecretKey:
    """Reference: src/crypto/SecretKey.h — SecretKey (seed + expanded key)."""

    __slots__ = ("_seed", "_sk", "public_key")

    def __init__(self, seed: bytes) -> None:
        if len(seed) != 32:
            raise ValueError("seed must be 32 bytes")
        pk, sk = sodium.sign_seed_keypair(seed)
        self._seed = seed
        self._sk = sk
        self.public_key = PublicKey(pk)

    @staticmethod
    def random() -> "SecretKey":
        return SecretKey(os.urandom(32))

    @staticmethod
    def pseudo_random_for_testing(rng) -> "SecretKey":
        return SecretKey(bytes(rng.randrange(256) for _ in range(32)))

    @staticmethod
    def from_strkey_seed(s: str) -> "SecretKey":
        return SecretKey(strkey.decode_seed(s))

    def to_strkey_seed(self) -> str:
        return strkey.encode_seed(self._seed)

    def sign(self, msg: bytes) -> bytes:
        return sodium.sign_detached(msg, self._sk)

    def __repr__(self) -> str:
        return f"SecretKey({self.public_key.to_strkey()})"


class _VerifyCache:
    def __init__(self) -> None:
        self._cache: RandomEvictionCache[tuple, bool] = RandomEvictionCache(VERIFY_CACHE_SIZE)
        self._lock = make_lock("crypto.verify-cache")

    @staticmethod
    def key(sig: bytes, pk: bytes, msg: bytes) -> tuple:
        """Tuple key, not a whole-entry digest: CPython caches each bytes
        object's hash, and the replay path looks up the very same
        sig/pk/msg objects it seeded (frames are decoded once), so keying
        costs ~one cached-hash tuple combine instead of a 128-byte SHA-256
        per probe — measured as a top-5 accel-pass line on the 1-core
        bench host.  Large messages (SCP envelope payloads etc.) are
        digested so a full cache never pins megabytes of dropped-envelope
        bytes; replay content-hashes are exactly 32 bytes and stay raw."""
        if len(msg) > 64:
            msg = sha256(msg)
        return (sig, pk, msg)

    def get(self, k: tuple) -> Optional[bool]:
        with self._lock:
            return self._cache.maybe_get(k)

    def put(self, k: tuple, verdict: bool) -> None:
        with self._lock:
            self._cache.put(k, verdict)

    def put_many(self, entries) -> None:
        """Bulk insert of (pk, sig, msg, verdict) under ONE lock
        acquisition (the replay pipeline seeds tens of thousands of
        verdicts per collect on the apply thread)."""
        key = self.key
        with self._lock:
            put = self._cache.put
            for pk, sig, msg, verdict in entries:
                put(key(sig, pk, msg), bool(verdict))

    def clear(self) -> None:
        with self._lock:
            self._cache.clear()


_verify_cache = _VerifyCache()


def verify_sig(pk: PublicKey, sig: bytes, msg: bytes) -> bool:
    """PubKeyUtils::verifySig equivalent: cached libsodium-exact verdict.

    The TPU batch path (accel.backend.TPUCryptoBackend) pre-verifies whole
    work units and seeds this cache, so per-tx checks hit without recompute —
    same observable semantics, hoisted compute.
    """
    k = _VerifyCache.key(sig, pk.ed25519, msg)
    hit = _verify_cache.get(k)
    if hit is not None:
        _registry().counter("crypto.verify.cache-hit").inc()
        return hit
    # cache miss: the verdict is recomputed by libsodium on the host —
    # during an accel catchup this counter is the un-offloaded remainder
    # (unpairable hints + wedge/race fallbacks)
    _registry().counter("crypto.verify.recompute").inc()
    verdict = sodium.verify_detached(sig, msg, pk.ed25519)
    _verify_cache.put(k, verdict)
    return verdict


def seed_verify_cache(entries) -> None:
    """Bulk-insert (pk32, sig, msg, verdict) tuples (TPU backend hook)."""
    _verify_cache.put_many(entries)


def clear_verify_cache() -> None:
    _verify_cache.clear()
