"""Key types and signature verification with the verify-result cache.

Reference: src/crypto/SecretKey.{h,cpp} — SecretKey, PublicKey,
PubKeyUtils::verifySig (libsodium verify + RandomEvictionCache keyed by
hash(sig‖key‖msg)), KeyUtils; src/crypto/SignerKey.h.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Optional

from . import sodium, strkey
from .sha import sha256
from ..util.cache import RandomEvictionCache

VERIFY_CACHE_SIZE = 0x10000  # reference: 64k-entry verify cache


@dataclass(frozen=True)
class PublicKey:
    """Ed25519 public key (XDR: PublicKey{PUBLIC_KEY_TYPE_ED25519, uint256})."""

    ed25519: bytes  # 32 bytes

    def __post_init__(self) -> None:
        if len(self.ed25519) != 32:
            raise ValueError("ed25519 public key must be 32 bytes")

    def to_strkey(self) -> str:
        return strkey.encode_public_key(self.ed25519)

    @staticmethod
    def from_strkey(s: str) -> "PublicKey":
        return PublicKey(strkey.decode_public_key(s))

    def hint(self) -> bytes:
        """Signature hint: last 4 bytes of the key (XDR SignatureHint).
        Reference: src/crypto/SignerKeyUtils / SignatureUtils — getHint."""
        return self.ed25519[28:32]

    def __repr__(self) -> str:
        return f"PublicKey({self.to_strkey()})"


class SecretKey:
    """Reference: src/crypto/SecretKey.h — SecretKey (seed + expanded key)."""

    __slots__ = ("_seed", "_sk", "public_key")

    def __init__(self, seed: bytes) -> None:
        if len(seed) != 32:
            raise ValueError("seed must be 32 bytes")
        pk, sk = sodium.sign_seed_keypair(seed)
        self._seed = seed
        self._sk = sk
        self.public_key = PublicKey(pk)

    @staticmethod
    def random() -> "SecretKey":
        return SecretKey(os.urandom(32))

    @staticmethod
    def pseudo_random_for_testing(rng) -> "SecretKey":
        return SecretKey(bytes(rng.randrange(256) for _ in range(32)))

    @staticmethod
    def from_strkey_seed(s: str) -> "SecretKey":
        return SecretKey(strkey.decode_seed(s))

    def to_strkey_seed(self) -> str:
        return strkey.encode_seed(self._seed)

    def sign(self, msg: bytes) -> bytes:
        return sodium.sign_detached(msg, self._sk)

    def __repr__(self) -> str:
        return f"SecretKey({self.public_key.to_strkey()})"


class _VerifyCache:
    def __init__(self) -> None:
        self._cache: RandomEvictionCache[bytes, bool] = RandomEvictionCache(VERIFY_CACHE_SIZE)
        self._lock = threading.Lock()

    @staticmethod
    def key(sig: bytes, pk: bytes, msg: bytes) -> bytes:
        return sha256(sig + pk + msg)

    def get(self, k: bytes) -> Optional[bool]:
        with self._lock:
            return self._cache.maybe_get(k)

    def put(self, k: bytes, verdict: bool) -> None:
        with self._lock:
            self._cache.put(k, verdict)

    def clear(self) -> None:
        with self._lock:
            self._cache.clear()


_verify_cache = _VerifyCache()


def verify_sig(pk: PublicKey, sig: bytes, msg: bytes) -> bool:
    """PubKeyUtils::verifySig equivalent: cached libsodium-exact verdict.

    The TPU batch path (accel.backend.TPUCryptoBackend) pre-verifies whole
    work units and seeds this cache, so per-tx checks hit without recompute —
    same observable semantics, hoisted compute.
    """
    k = _VerifyCache.key(sig, pk.ed25519, msg)
    hit = _verify_cache.get(k)
    if hit is not None:
        return hit
    verdict = sodium.verify_detached(sig, msg, pk.ed25519)
    _verify_cache.put(k, verdict)
    return verdict


def seed_verify_cache(entries) -> None:
    """Bulk-insert (pk32, sig, msg, verdict) tuples (TPU backend hook)."""
    for pk, sig, msg, verdict in entries:
        _verify_cache.put(_VerifyCache.key(sig, pk, msg), bool(verdict))


def clear_verify_cache() -> None:
    _verify_cache.clear()
