"""ctypes binding to the system libsodium (runtime library only, no headers).

Reference seam: src/crypto/SecretKey.cpp — PubKeyUtils::verifySig wraps
libsodium ``crypto_sign_verify_detached``; SecretKey::sign wraps
``crypto_sign_detached``.  We declare the handful of prototypes we need
ourselves and load the versioned soname directly (``libsodium.so.23``).

All functions take/return ``bytes``; sizes are validated here so callers can
rely on hard guarantees.  This module is the CPU oracle that the TPU batch
verifier (accel/ed25519.py) must match bit-for-bit.
"""

from __future__ import annotations

import ctypes
import ctypes.util
from typing import Optional, Tuple

_SONAMES = ("libsodium.so.23", "libsodium.so", "libsodium.dylib")


def _load() -> Optional[ctypes.CDLL]:
    for name in _SONAMES:
        try:
            return ctypes.CDLL(name)
        except OSError:
            continue
    found = ctypes.util.find_library("sodium")
    if found:
        try:
            return ctypes.CDLL(found)
        except OSError:
            pass
    return None


_lib = _load()

SIGN_BYTES = 64
SIGN_PUBLICKEYBYTES = 32
SIGN_SECRETKEYBYTES = 64
SIGN_SEEDBYTES = 32
SCALARMULT_BYTES = 32

if _lib is not None:
    _lib.sodium_init.restype = ctypes.c_int
    _lib.sodium_init()

    _lib.crypto_sign_verify_detached.restype = ctypes.c_int
    _lib.crypto_sign_verify_detached.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_ulonglong, ctypes.c_char_p]
    _lib.crypto_sign_detached.restype = ctypes.c_int
    _lib.crypto_sign_detached.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_ulonglong),
        ctypes.c_char_p, ctypes.c_ulonglong, ctypes.c_char_p]
    _lib.crypto_sign_seed_keypair.restype = ctypes.c_int
    _lib.crypto_sign_seed_keypair.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p]
    _lib.crypto_scalarmult_curve25519.restype = ctypes.c_int
    _lib.crypto_scalarmult_curve25519.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p]
    _lib.crypto_scalarmult_curve25519_base.restype = ctypes.c_int
    _lib.crypto_scalarmult_curve25519_base.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p]


def available() -> bool:
    return _lib is not None


def sign_seed_keypair(seed: bytes) -> Tuple[bytes, bytes]:
    """(public_key 32B, secret_key 64B) from a 32-byte seed."""
    if len(seed) != SIGN_SEEDBYTES:
        raise ValueError("seed must be 32 bytes")
    if _lib is None:
        return _fallback_seed_keypair(seed)
    pk = ctypes.create_string_buffer(SIGN_PUBLICKEYBYTES)
    sk = ctypes.create_string_buffer(SIGN_SECRETKEYBYTES)
    if _lib.crypto_sign_seed_keypair(pk, sk, seed) != 0:
        raise RuntimeError("crypto_sign_seed_keypair failed")
    return pk.raw, sk.raw


def sign_detached(msg: bytes, sk: bytes) -> bytes:
    """64-byte Ed25519 signature of msg under 64-byte secret key."""
    if len(sk) != SIGN_SECRETKEYBYTES:
        raise ValueError("secret key must be 64 bytes")
    if _lib is None:
        return _fallback_sign(msg, sk)
    sig = ctypes.create_string_buffer(SIGN_BYTES)
    siglen = ctypes.c_ulonglong(0)
    if _lib.crypto_sign_detached(sig, ctypes.byref(siglen), msg, len(msg), sk) != 0:
        raise RuntimeError("crypto_sign_detached failed")
    return sig.raw


def verify_detached(sig: bytes, msg: bytes, pk: bytes) -> bool:
    """libsodium-exact Ed25519 verification verdict (the CPU oracle)."""
    if len(sig) != SIGN_BYTES or len(pk) != SIGN_PUBLICKEYBYTES:
        return False
    if _lib is None:
        return _fallback_verify(sig, msg, pk)
    return _lib.crypto_sign_verify_detached(sig, msg, len(msg), pk) == 0


def scalarmult_curve25519_base(sk: bytes) -> bytes:
    if _lib is None:
        raise RuntimeError("libsodium unavailable")
    out = ctypes.create_string_buffer(SCALARMULT_BYTES)
    if _lib.crypto_scalarmult_curve25519_base(out, sk) != 0:
        raise RuntimeError("crypto_scalarmult_curve25519_base failed")
    return out.raw


def scalarmult_curve25519(sk: bytes, pk: bytes) -> bytes:
    if _lib is None:
        raise RuntimeError("libsodium unavailable")
    out = ctypes.create_string_buffer(SCALARMULT_BYTES)
    if _lib.crypto_scalarmult_curve25519(out, sk, pk) != 0:
        raise RuntimeError("crypto_scalarmult_curve25519 failed (low order?)")
    return out.raw


# ---------------------------------------------------------------------------
# Fallback path (no libsodium): python `cryptography`.  NOTE: `cryptography`'s
# Ed25519 (OpenSSL) and libsodium agree on all honestly-generated signatures
# but may differ on adversarial edge cases (small-order keys); libsodium is
# the verdict of record when present.
# ---------------------------------------------------------------------------

def _fallback_seed_keypair(seed: bytes) -> Tuple[bytes, bytes]:
    from cryptography.hazmat.primitives.asymmetric.ed25519 import Ed25519PrivateKey
    from cryptography.hazmat.primitives import serialization
    priv = Ed25519PrivateKey.from_private_bytes(seed)
    pk = priv.public_key().public_bytes(
        serialization.Encoding.Raw, serialization.PublicFormat.Raw)
    return pk, seed + pk


def _fallback_sign(msg: bytes, sk: bytes) -> bytes:
    from cryptography.hazmat.primitives.asymmetric.ed25519 import Ed25519PrivateKey
    return Ed25519PrivateKey.from_private_bytes(sk[:32]).sign(msg)


def _fallback_verify(sig: bytes, msg: bytes, pk: bytes) -> bool:
    from cryptography.hazmat.primitives.asymmetric.ed25519 import Ed25519PublicKey
    from cryptography.exceptions import InvalidSignature
    try:
        Ed25519PublicKey.from_public_bytes(pk).verify(sig, msg)
        return True
    except (InvalidSignature, ValueError):
        return False
