"""Adaptive anomaly detection (ISSUE 20): the EWMA+MAD band state
machine, derived series injection, bundle writes, and the end-to-end
injected-regression proof — a throttled close degrades mid-soak, the
close-p99 anomaly flags within the window, the bundle carries the
breaching telemetry, and the flag clears after recovery.
"""

import glob
import json

import pytest

from stellar_core_tpu.main.config import Config
from stellar_core_tpu.util import eventlog, metrics
from stellar_core_tpu.util.anomaly import (AnomalyDetector, TrackedSeries,
                                           default_tracked)


@pytest.fixture(autouse=True)
def _fresh_state():
    metrics.reset_registry()
    eventlog.event_log().clear()
    yield
    metrics.reset_registry()
    eventlog.event_log().clear()


def _series(**kw):
    kw.setdefault("name", "lat")
    kw.setdefault("metric", "ledger.ledger.close")
    kw.setdefault("field", "p99_s")
    kw.setdefault("floor", 0.01)
    kw.setdefault("min_samples", 4)
    kw.setdefault("breach_n", 3)
    kw.setdefault("clear_n", 3)
    return TrackedSeries(**kw)


class TestStateMachine:
    def test_warmup_never_flags(self):
        det = AnomalyDetector([_series(min_samples=8)])
        for v in (0.01, 5.0, 0.01, 9.0, 0.02, 7.0, 0.01, 8.0):
            assert det.observe("lat", v) is False
        assert det.active() == []

    def test_sustained_breach_flags_spike_does_not(self):
        det = AnomalyDetector([_series()])
        for _ in range(8):
            det.observe("lat", 0.01)
        # one-tick spike: breach_n=3 consecutive required
        det.observe("lat", 5.0)
        det.observe("lat", 0.01)
        assert not det.is_active("lat")
        # sustained departure flips the latch
        det.observe("lat", 5.0)
        det.observe("lat", 5.0)
        assert not det.is_active("lat")
        det.observe("lat", 5.0)
        assert det.is_active("lat")
        assert det.active() == ["lat"]

    def test_clears_after_consecutive_inband(self):
        det = AnomalyDetector([_series()])
        for _ in range(8):
            det.observe("lat", 0.01)
        for _ in range(3):
            det.observe("lat", 5.0)
        assert det.is_active("lat")
        det.observe("lat", 0.01)
        det.observe("lat", 0.01)
        assert det.is_active("lat")
        det.observe("lat", 0.01)
        assert not det.is_active("lat")
        rep = det.report()["series"]["lat"]
        assert rep["episodes"] == 1

    def test_low_direction_flags_downward(self):
        det = AnomalyDetector([_series(name="hit", direction="low",
                                       floor=0.05)])
        for _ in range(8):
            det.observe("hit", 0.95)
        for _ in range(3):
            det.observe("hit", 0.10)
        assert det.is_active("hit")
        # upward departure on a low-direction series is fine
        det2 = AnomalyDetector([_series(name="hit", direction="low",
                                        floor=0.05)])
        for _ in range(8):
            det2.observe("hit", 0.5)
        for _ in range(5):
            det2.observe("hit", 0.99)
        assert not det2.is_active("hit")

    def test_floor_suppresses_constant_series_noise(self):
        """A near-constant warm-up (MAD ~ 0) must not make every later
        wiggle an anomaly — the floor keeps a minimum band width."""
        det = AnomalyDetector([_series(floor=0.01)])
        for _ in range(10):
            det.observe("lat", 0.002)
        for _ in range(10):
            det.observe("lat", 0.004)  # wiggle far inside the floor band
        assert not det.is_active("lat")

    def test_baseline_freezes_while_breaching(self):
        """A sustained regression must NOT drag its own baseline along
        and self-clear without recovering."""
        det = AnomalyDetector([_series()])
        for _ in range(8):
            det.observe("lat", 0.01)
        for _ in range(50):
            det.observe("lat", 5.0)
        assert det.is_active("lat")
        assert det.report()["series"]["lat"]["mean"] < 0.1

    def test_flag_clear_counters(self):
        det = AnomalyDetector([_series()])
        for _ in range(8):
            det.observe("lat", 0.01)
        for _ in range(3):
            det.observe("lat", 5.0)
        for _ in range(3):
            det.observe("lat", 0.01)
        snap = metrics.registry().snapshot()
        assert snap["anomaly.flags"]["count"] == 1
        assert snap["anomaly.clears"]["count"] == 1
        msgs = [e.msg for e in eventlog.event_log().events()]
        assert "anomaly-detected" in msgs
        assert "anomaly-cleared" in msgs


class TestEvaluate:
    def test_pull_mode_reads_snapshot_fields(self):
        det = AnomalyDetector([_series()])
        for _ in range(8):
            det.evaluate({"ledger.ledger.close": {"p99_s": 0.01}})
        for _ in range(3):
            out = det.evaluate({"ledger.ledger.close": {"p99_s": 5.0}})
        assert out == {"lat": True}

    def test_absent_metric_is_skipped(self):
        det = AnomalyDetector([_series()])
        out = det.evaluate({"scp.value.sign": {"count": 1}})
        assert out == {}
        assert det.report()["series"]["lat"]["samples"] == 0

    def test_derived_cache_hit_rate(self):
        """The hit-rate series is synthesized from per-eval hit/miss
        count deltas; a sustained drop flags cache-hit-rate."""
        det = AnomalyDetector(default_tracked())
        hits, misses = 0, 0
        for _ in range(12):
            hits += 95
            misses += 5
            det.evaluate({
                "bucketlistdb.cache.hit": {"count": hits},
                "bucketlistdb.cache.miss": {"count": misses}})
        st = det.report()["series"]["cache-hit-rate"]
        assert st["samples"] > 0
        assert st["last_value"] == pytest.approx(0.95)
        for _ in range(4):
            hits += 5
            misses += 95
            det.evaluate({
                "bucketlistdb.cache.hit": {"count": hits},
                "bucketlistdb.cache.miss": {"count": misses}})
        assert det.is_active("cache-hit-rate")

    def test_no_traffic_skips_hit_rate(self):
        det = AnomalyDetector(default_tracked())
        for _ in range(3):
            det.evaluate({"bucketlistdb.cache.hit": {"count": 10},
                          "bucketlistdb.cache.miss": {"count": 10}})
        # first eval seeds the delta base; later no-traffic evals skip
        assert det.report()["series"]["cache-hit-rate"]["samples"] == 0


class TestBundles:
    def test_bundle_carries_window_costs_and_state(self, tmp_path):
        from stellar_core_tpu.ledger.costs import CloseCostLedger
        from stellar_core_tpu.util.timeseries import TimeSeriesStore
        c = metrics.registry().counter("ledger.ledger.close")
        ts = TimeSeriesStore()
        cc = CloseCostLedger()
        for i in range(10):
            c.inc()
            ts.capture(now=float(i))
            cc.add(seq=i + 1, txs=1, total_s=0.01, fee_s=0.001,
                   apply_s=0.005, seal_s=0.002, merge_stall_s=0.0,
                   cache_hits=1, cache_misses=0, pin_count=0,
                   resident_entries=5, resident_delta=0, gc_backlog=0)
        det = AnomalyDetector([_series()], timeseries=lambda: ts,
                              closecosts=lambda: cc, source="n1")
        path = det.write_bundle("lat", reason="test",
                                out_dir=str(tmp_path))
        doc = json.loads(open(path).read())
        assert doc["kind"] == "anomaly-bundle"
        assert doc["series"] == "lat"
        assert doc["source"] == "n1"
        pts = doc["timeseries"]["ledger.ledger.close"]
        assert pts and all("seq" in p and "v" in p for p in pts)
        assert len(doc["closecosts"]) == 10
        assert doc["closecosts"][-1]["seq"] == 10
        assert "state" in doc

    def test_bundle_without_providers(self, tmp_path):
        det = AnomalyDetector([_series()])
        path = det.write_bundle("lat", out_dir=str(tmp_path))
        doc = json.loads(open(path).read())
        assert "timeseries" not in doc
        assert "closecosts" not in doc


class TestRegressionProof:
    """The acceptance proof: a throttle seam degrades close latency
    mid-soak; the anomaly flags within the detection window, writes a
    bundle holding the breaching telemetry, and clears after the
    throttle lifts and enough healthy closes dilute the p99 tail."""

    def test_injected_close_regression_flags_and_clears(
            self, tmp_path, monkeypatch):
        from stellar_core_tpu.main.application import Application
        from stellar_core_tpu.util.clock import ClockMode, VirtualClock

        monkeypatch.setenv("STPU_CRASH_DIR", str(tmp_path))
        cfg = Config.from_dict({
            "NETWORK_PASSPHRASE": "anomaly proof net",
            "RUN_STANDALONE": True,
            "PEER_PORT": 0,
            "TIMESERIES_CADENCE_S": 1.0,
            "ANOMALY_EVAL_CADENCE_S": 1.0,
        })
        clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        app = Application(cfg, clock=clock, listen=False)
        app.start()
        try:
            det = app.anomaly
            assert det is not None and app.timeseries is not None
            # healthy baseline: enough evals to warm the close-p99 series
            assert clock.crank_until(
                lambda: det.report()["series"]["close-p99"]["samples"]
                >= 10, timeout=120)
            assert not det.is_active("close-p99")
            baseline_seq = app.lm.last_closed_ledger_seq

            # inject the regression: every close spins an extra 150 ms
            app.lm.debug_close_throttle_s = 0.15
            assert clock.crank_until(
                lambda: det.is_active("close-p99"), timeout=120), \
                "throttled closes never flagged the close-p99 anomaly"
            assert app.lm.last_closed_ledger_seq > baseline_seq

            # the detection wrote a bundle with the breaching evidence
            bundles = glob.glob(str(tmp_path / "anomaly-close-p99-*.json"))
            assert bundles, "no anomaly bundle written at detection"
            doc = json.loads(open(bundles[0]).read())
            assert doc["kind"] == "anomaly-bundle"
            assert doc["reason"] == "anomaly-detected"
            pts = doc["timeseries"]["ledger.ledger.close"]
            assert pts, "bundle missing the breaching time-series window"
            assert any(p["v"].get("p99_s", 0) > 0.1 for p in pts)
            costs = doc["closecosts"]
            assert costs, "bundle missing the CloseCostRecords"
            assert any(r["total_s"] > 0.1 for r in costs)

            # flight events + gauges carry the episode
            msgs = [e.msg for e in eventlog.event_log().events()]
            assert "anomaly-detected" in msgs
            assert metrics.registry().snapshot()[
                "anomaly.active"]["value"] >= 1

            # recovery: lift the throttle; healthy closes dilute the
            # decaying p99 reservoir until the series re-enters band,
            # then clear_n consecutive in-band evals clear the latch
            app.lm.debug_close_throttle_s = 0.0
            assert clock.crank_until(
                lambda: not det.is_active("close-p99"), timeout=3600), \
                "anomaly never cleared after the throttle lifted"
            msgs = [e.msg for e in eventlog.event_log().events()]
            assert "anomaly-cleared" in msgs
        finally:
            app.stop()

    def test_close_costs_recorded_during_soak(self):
        """The per-close cost ledger fills during a normal standalone
        soak (either close engine) and serves watermarked reads."""
        from stellar_core_tpu.main.application import Application
        from stellar_core_tpu.util.clock import ClockMode, VirtualClock
        cfg = Config.from_dict({
            "NETWORK_PASSPHRASE": "closecost net",
            "RUN_STANDALONE": True,
            "PEER_PORT": 0,
        })
        clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        app = Application(cfg, clock=clock, listen=False)
        app.start()
        try:
            assert clock.crank_until(
                lambda: len(app.lm.close_costs) >= 5, timeout=60)
            doc = app.lm.close_costs.doc()
            recs = doc["records"]
            assert [r["export_seq"] for r in recs] \
                == sorted(r["export_seq"] for r in recs)
            assert all(r["total_s"] > 0 for r in recs)
            # ledger seqs are consecutive closes
            seqs = [r["seq"] for r in recs]
            assert seqs == list(range(seqs[0], seqs[0] + len(seqs)))
            # watermark: incremental read picks up only new rows
            mark = doc["next_since"]
            assert clock.crank_until(
                lambda: app.lm.close_costs.next_since > mark, timeout=60)
            incr = app.lm.close_costs.doc(since=mark)
            assert incr["records"]
            assert all(r["export_seq"] > mark for r in incr["records"])
        finally:
            app.stop()
