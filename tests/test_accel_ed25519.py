"""Differential tests: TPU/JAX batch Ed25519 verifier vs libsodium.

The contract (BASELINE.json north star): bit-identical accept/reject with
``crypto_sign_verify_detached`` for EVERY input, including adversarial
encodings — small-order points, non-canonical S/pk, undecodable keys,
torsion-mixed keys (mirrors reference differential strategy, SURVEY.md §4).
"""

import random

import numpy as np
import pytest

from stellar_core_tpu.crypto import sodium

ed = pytest.importorskip("stellar_core_tpu.accel.ed25519")

CHUNK = 32
P = (1 << 255) - 19
L = (1 << 252) + 27742317777372353535851937790883648493


def _keypair(rng):
    seed = bytes(rng.randrange(256) for _ in range(32))
    return sodium.sign_seed_keypair(seed)


def _run_and_compare(cases):
    """cases: list of (pk, sig, msg). Asserts JAX verdicts == libsodium."""
    pks = [c[0] for c in cases]
    sigs = [c[1] for c in cases]
    msgs = [c[2] for c in cases]
    expect = np.array([sodium.verify_detached(s, m, p)
                       for p, s, m in cases])
    got = ed.verify_batch(pks, sigs, msgs, chunk_size=CHUNK)
    mism = np.nonzero(got != expect)[0]
    assert len(mism) == 0, (
        f"verdict mismatch at {mism.tolist()}: "
        f"expect {expect[mism].tolist()} got {got[mism].tolist()}")
    return expect


def test_honest_and_corrupted_signatures():
    rng = random.Random(42)
    cases = []
    for i in range(24):
        pk, sk = _keypair(rng)
        msg = bytes(rng.randrange(256) for _ in range(rng.randrange(0, 150)))
        sig = sodium.sign_detached(msg, sk)
        kind = i % 6
        if kind == 1:
            sig = bytes([sig[0] ^ 1]) + sig[1:]          # corrupt R
        elif kind == 2:
            sig = sig[:32] + bytes([sig[32] ^ 1]) + sig[33:]  # corrupt S
        elif kind == 3:
            msg = msg + b"!"                              # wrong message
        elif kind == 4:
            pk2, _ = _keypair(rng)
            pk = pk2                                      # wrong key
        cases.append((pk, sig, msg))
    exp = _run_and_compare(cases)
    assert exp.sum() >= 4  # the honest ones accepted


def test_scalar_malleability_rejected():
    """S' = S + L verifies in naive impls; libsodium (and we) must reject."""
    rng = random.Random(43)
    cases = []
    for _ in range(4):
        pk, sk = _keypair(rng)
        msg = b"malleability"
        sig = sodium.sign_detached(msg, sk)
        s_int = int.from_bytes(sig[32:], "little")
        mall = sig[:32] + (s_int + L).to_bytes(32, "little")
        cases.append((pk, sig, msg))   # sanity: original accepted
        cases.append((pk, mall, msg))  # malleated: rejected by both
    exp = _run_and_compare(cases)
    assert list(exp) == [True, False] * 4


def test_high_bit_s_rejected():
    rng = random.Random(44)
    pk, sk = _keypair(rng)
    sig = sodium.sign_detached(b"m", sk)
    bad = sig[:63] + bytes([sig[63] | 0xE0])
    _run_and_compare([(pk, bad, b"m")])


def test_small_order_R_and_pk():
    """All 14 small-order encodings in both the R and pk positions."""
    rng = random.Random(45)
    pk, sk = _keypair(rng)
    sig = sodium.sign_detached(b"torsion", sk)
    encodings = []
    for base in (0, 1, ed._Y8A, ed._Y8B, P - 1, P, P + 1):
        for sign in (0, 0x80):
            b = bytearray(base.to_bytes(32, "little"))
            b[31] |= sign
            encodings.append(bytes(b))
    cases = []
    for enc in encodings:
        cases.append((pk, enc + sig[32:], b"torsion"))  # small-order R
        cases.append((enc, sig, b"torsion"))            # small-order pk
    exp = _run_and_compare(cases)
    assert not exp.any()


def test_noncanonical_and_undecodable_pk():
    rng = random.Random(46)
    _, sk = _keypair(rng)
    sig = sodium.sign_detached(b"x", sk)
    cases = []
    # y >= p but not in the small-order blocklist: p+2, p+3
    for y in (P + 2, P + 3):
        cases.append((y.to_bytes(32, "little"), sig, b"x"))
    # undecodable y (no sqrt): scan for small y with no x
    found = 0
    y = 2
    while found < 3:
        from stellar_core_tpu.accel.curve import _recover_x
        if _recover_x(y, 0) is None:
            cases.append((y.to_bytes(32, "little"), sig, b"x"))
            found += 1
        y += 1
    exp = _run_and_compare(cases)
    assert not exp.any()


def test_torsion_mixed_pk_matches_libsodium():
    """pk' = A + (order-8 point): mixed-order key. Whatever libsodium says,
    we must say the same."""
    from stellar_core_tpu.accel.curve import _recover_x
    from stellar_core_tpu.accel.ed25519 import (_edwards_add_affine,
                                                _scalar_mul_affine)
    rng = random.Random(47)
    cases = []
    t8 = (_recover_x(ed._Y8A, 0), ed._Y8A)
    for _ in range(4):
        pk, sk = _keypair(rng)
        msg = b"mixed order"
        sig = sodium.sign_detached(msg, sk)
        y = int.from_bytes(pk, "little") & ((1 << 255) - 1)
        x = _recover_x(y, pk[31] >> 7)
        mixed = _edwards_add_affine((x, y), t8)
        enc = bytearray(mixed[1].to_bytes(32, "little"))
        enc[31] |= (mixed[0] & 1) << 7
        cases.append((bytes(enc), sig, msg))
        cases.append((pk, sig, msg))
    _run_and_compare(cases)


def test_batch_padding_and_duplicates():
    rng = random.Random(48)
    pk, sk = _keypair(rng)
    sig = sodium.sign_detached(b"dup", sk)
    cases = [(pk, sig, b"dup")] * (CHUNK + 3)  # force a padded second chunk
    exp = _run_and_compare(cases)
    assert exp.all()


def test_wrong_length_inputs():
    rng = random.Random(49)
    pk, sk = _keypair(rng)
    sig = sodium.sign_detached(b"z", sk)
    got = ed.verify_batch([pk, pk[:31], pk], [sig[:63], sig, sig],
                          [b"z", b"z", b"z"], chunk_size=CHUNK)
    assert list(got) == [False, False, True]


def test_verifier_shards_over_test_mesh():
    """Under the suite's 8-virtual-device mesh the production verifier
    must take the shard_map path (v5e-8 topology analog) and still agree
    with libsodium."""
    jax = pytest.importorskip("jax")
    from stellar_core_tpu.accel.ed25519 import Ed25519BatchVerifier
    from stellar_core_tpu.crypto import sodium

    if len(jax.devices()) < 2:
        pytest.skip("single-device backend: no mesh to shard over")
    v = Ed25519BatchVerifier(chunk_size=512, tail_floor=256)
    assert v._mesh is not None and v._ndev == len(jax.devices())
    pks, sigs, msgs = [], [], []
    for i in range(40):
        pk, sk = sodium.sign_seed_keypair(bytes([i % 5 + 1]) * 32)
        m = bytes([i]) * 33
        pks.append(pk)
        sigs.append(sodium.sign_detached(m, sk))
        msgs.append(m)
    sigs[7] = sigs[7][:32] + bytes(32)  # one corrupted signature
    out = v.verify(pks, sigs, msgs)
    expected = [sodium.verify_detached(s, m, p)
                for p, s, m in zip(pks, sigs, msgs)]
    assert out.tolist() == expected


def test_sharded_kernel_under_load_counts_dispatches():
    """Scaling-shape test (VERDICT r2 next #9): a batch many times the
    per-dispatch width must stream through the sharded kernel in multiple
    uniform-width dispatches (each a multiple of the device count, so
    shard_map splits evenly), with verdicts equal to libsodium's."""
    jax = pytest.importorskip("jax")
    from stellar_core_tpu.accel import ed25519 as ed
    from stellar_core_tpu.crypto import sodium

    if len(jax.devices()) < 2:
        pytest.skip("single-device backend: no mesh to shard over")
    ndev = len(jax.devices())
    v = ed.Ed25519BatchVerifier(chunk_size=64, tail_floor=64,
                                hot_threshold=1 << 62)  # generic path only
    shapes = []
    inner = v._kernel_raw

    def spy(s_raw, hh, kidx, ucx, ucy, uct, rb):
        shapes.append(int(s_raw.shape[0]))
        return inner(s_raw, hh, kidx, ucx, ucy, uct, rb)

    v._kernel_raw = spy
    n = 600   # >> 8x chunk width
    keys = [sodium.sign_seed_keypair(bytes([i + 1]) * 32) for i in range(6)]
    pks, sigs, msgs = [], [], []
    for i in range(n):
        pk, sk = keys[i % len(keys)]
        m = i.to_bytes(4, "big") * 8
        pks.append(pk)
        sigs.append(sodium.sign_detached(m, sk))
        msgs.append(m)
    sigs[13] = bytes([sigs[13][0] ^ 1]) + sigs[13][1:]
    out = v.verify(pks, sigs, msgs)
    assert len(shapes) == (n + 63) // 64
    assert all(w % ndev == 0 for w in shapes), shapes
    assert int(out.sum()) == n - 1 and not out[13]
    assert v.stats["generic_sigs"] == n


def test_sharded_quorum_frontier_spills_multiple_prune_steps():
    """The sharded quorum enumerator must stay exact when the frontier
    exceeds one device batch, i.e. a single depth's pruning spills over
    several sharded dispatches (VERDICT r2 next #9)."""
    jax = pytest.importorskip("jax")
    from jax.sharding import Mesh
    import numpy as np

    from stellar_core_tpu.accel import quorum as AQ
    from stellar_core_tpu.xdr import scp as SX
    from stellar_core_tpu.xdr import types as XT

    if len(jax.devices()) < 2:
        pytest.skip("single-device backend: no mesh to shard over")
    mesh = Mesh(np.array(jax.devices()), ("data",))

    def qnid(i):
        return bytes([i]) + bytes(31)

    orgs = [[qnid(10 * o + i) for i in range(3)] for o in range(6)]

    def mk(thr):
        return SX.SCPQuorumSet(
            threshold=thr, validators=[],
            innerSets=[SX.SCPQuorumSet(
                threshold=2,
                validators=[XT.node_id(v) for v in org],
                innerSets=[]) for org in orgs])

    for thr, expect in ((4, True), (3, False)):
        qmap = {v: mk(thr) for org in orgs for v in org}
        checker = AQ.TPUQuorumIntersectionChecker(
            qmap, batch_size=2 * len(jax.devices()), mesh=mesh)
        calls = []
        orig_prune = checker._prune

        def spy(children, rem, _orig=orig_prune, _calls=calls):
            _calls.append(len(children))
            return _orig(children, rem)

        checker._prune = spy
        r = checker.check()
        assert r.intersects is expect
        # at least one depth's children set exceeded the batch width, so
        # _prune chunked it into several sharded dispatches
        assert any(c > checker.batch_size for c in calls), calls
