"""Differential tests: TPU/JAX batch Ed25519 verifier vs libsodium.

The contract (BASELINE.json north star): bit-identical accept/reject with
``crypto_sign_verify_detached`` for EVERY input, including adversarial
encodings — small-order points, non-canonical S/pk, undecodable keys,
torsion-mixed keys (mirrors reference differential strategy, SURVEY.md §4).
"""

import random

import numpy as np
import pytest

from stellar_core_tpu.crypto import sodium

ed = pytest.importorskip("stellar_core_tpu.accel.ed25519")

CHUNK = 32
P = (1 << 255) - 19
L = (1 << 252) + 27742317777372353535851937790883648493


def _keypair(rng):
    seed = bytes(rng.randrange(256) for _ in range(32))
    return sodium.sign_seed_keypair(seed)


def _run_and_compare(cases):
    """cases: list of (pk, sig, msg). Asserts JAX verdicts == libsodium."""
    pks = [c[0] for c in cases]
    sigs = [c[1] for c in cases]
    msgs = [c[2] for c in cases]
    expect = np.array([sodium.verify_detached(s, m, p)
                       for p, s, m in cases])
    got = ed.verify_batch(pks, sigs, msgs, chunk_size=CHUNK)
    mism = np.nonzero(got != expect)[0]
    assert len(mism) == 0, (
        f"verdict mismatch at {mism.tolist()}: "
        f"expect {expect[mism].tolist()} got {got[mism].tolist()}")
    return expect


def test_honest_and_corrupted_signatures():
    rng = random.Random(42)
    cases = []
    for i in range(24):
        pk, sk = _keypair(rng)
        msg = bytes(rng.randrange(256) for _ in range(rng.randrange(0, 150)))
        sig = sodium.sign_detached(msg, sk)
        kind = i % 6
        if kind == 1:
            sig = bytes([sig[0] ^ 1]) + sig[1:]          # corrupt R
        elif kind == 2:
            sig = sig[:32] + bytes([sig[32] ^ 1]) + sig[33:]  # corrupt S
        elif kind == 3:
            msg = msg + b"!"                              # wrong message
        elif kind == 4:
            pk2, _ = _keypair(rng)
            pk = pk2                                      # wrong key
        cases.append((pk, sig, msg))
    exp = _run_and_compare(cases)
    assert exp.sum() >= 4  # the honest ones accepted


def test_scalar_malleability_rejected():
    """S' = S + L verifies in naive impls; libsodium (and we) must reject."""
    rng = random.Random(43)
    cases = []
    for _ in range(4):
        pk, sk = _keypair(rng)
        msg = b"malleability"
        sig = sodium.sign_detached(msg, sk)
        s_int = int.from_bytes(sig[32:], "little")
        mall = sig[:32] + (s_int + L).to_bytes(32, "little")
        cases.append((pk, sig, msg))   # sanity: original accepted
        cases.append((pk, mall, msg))  # malleated: rejected by both
    exp = _run_and_compare(cases)
    assert list(exp) == [True, False] * 4


def test_high_bit_s_rejected():
    rng = random.Random(44)
    pk, sk = _keypair(rng)
    sig = sodium.sign_detached(b"m", sk)
    bad = sig[:63] + bytes([sig[63] | 0xE0])
    _run_and_compare([(pk, bad, b"m")])


def test_small_order_R_and_pk():
    """All 14 small-order encodings in both the R and pk positions."""
    rng = random.Random(45)
    pk, sk = _keypair(rng)
    sig = sodium.sign_detached(b"torsion", sk)
    encodings = []
    for base in (0, 1, ed._Y8A, ed._Y8B, P - 1, P, P + 1):
        for sign in (0, 0x80):
            b = bytearray(base.to_bytes(32, "little"))
            b[31] |= sign
            encodings.append(bytes(b))
    cases = []
    for enc in encodings:
        cases.append((pk, enc + sig[32:], b"torsion"))  # small-order R
        cases.append((enc, sig, b"torsion"))            # small-order pk
    exp = _run_and_compare(cases)
    assert not exp.any()


def test_noncanonical_and_undecodable_pk():
    rng = random.Random(46)
    _, sk = _keypair(rng)
    sig = sodium.sign_detached(b"x", sk)
    cases = []
    # y >= p but not in the small-order blocklist: p+2, p+3
    for y in (P + 2, P + 3):
        cases.append((y.to_bytes(32, "little"), sig, b"x"))
    # undecodable y (no sqrt): scan for small y with no x
    found = 0
    y = 2
    while found < 3:
        from stellar_core_tpu.accel.curve import _recover_x
        if _recover_x(y, 0) is None:
            cases.append((y.to_bytes(32, "little"), sig, b"x"))
            found += 1
        y += 1
    exp = _run_and_compare(cases)
    assert not exp.any()


def test_torsion_mixed_pk_matches_libsodium():
    """pk' = A + (order-8 point): mixed-order key. Whatever libsodium says,
    we must say the same."""
    from stellar_core_tpu.accel.curve import _recover_x
    from stellar_core_tpu.accel.ed25519 import (_edwards_add_affine,
                                                _scalar_mul_affine)
    rng = random.Random(47)
    cases = []
    t8 = (_recover_x(ed._Y8A, 0), ed._Y8A)
    for _ in range(4):
        pk, sk = _keypair(rng)
        msg = b"mixed order"
        sig = sodium.sign_detached(msg, sk)
        y = int.from_bytes(pk, "little") & ((1 << 255) - 1)
        x = _recover_x(y, pk[31] >> 7)
        mixed = _edwards_add_affine((x, y), t8)
        enc = bytearray(mixed[1].to_bytes(32, "little"))
        enc[31] |= (mixed[0] & 1) << 7
        cases.append((bytes(enc), sig, msg))
        cases.append((pk, sig, msg))
    _run_and_compare(cases)


def test_batch_padding_and_duplicates():
    rng = random.Random(48)
    pk, sk = _keypair(rng)
    sig = sodium.sign_detached(b"dup", sk)
    cases = [(pk, sig, b"dup")] * (CHUNK + 3)  # force a padded second chunk
    exp = _run_and_compare(cases)
    assert exp.all()


def test_wrong_length_inputs():
    rng = random.Random(49)
    pk, sk = _keypair(rng)
    sig = sodium.sign_detached(b"z", sk)
    got = ed.verify_batch([pk, pk[:31], pk], [sig[:63], sig, sig],
                          [b"z", b"z", b"z"], chunk_size=CHUNK)
    assert list(got) == [False, False, True]


def test_verifier_shards_over_test_mesh():
    """Under the suite's 8-virtual-device mesh the production verifier
    must take the shard_map path (v5e-8 topology analog) and still agree
    with libsodium."""
    jax = pytest.importorskip("jax")
    from stellar_core_tpu.accel.ed25519 import Ed25519BatchVerifier
    from stellar_core_tpu.crypto import sodium

    if len(jax.devices()) < 2:
        pytest.skip("single-device backend: no mesh to shard over")
    v = Ed25519BatchVerifier(chunk_size=512, tail_floor=256)
    assert v._mesh is not None and v._ndev == len(jax.devices())
    pks, sigs, msgs = [], [], []
    for i in range(40):
        pk, sk = sodium.sign_seed_keypair(bytes([i % 5 + 1]) * 32)
        m = bytes([i]) * 33
        pks.append(pk)
        sigs.append(sodium.sign_detached(m, sk))
        msgs.append(m)
    sigs[7] = sigs[7][:32] + bytes(32)  # one corrupted signature
    out = v.verify(pks, sigs, msgs)
    expected = [sodium.verify_detached(s, m, p)
                for p, s, m in zip(pks, sigs, msgs)]
    assert out.tolist() == expected
