"""Incident-observability suite (ISSUE 5): flight recorder + logging
bridge, crash bundles on forced fail-stops, StatusManager semantics,
/health degradation, and trace-correlated structured (JSON) logging.

Acceptance criteria exercised here:
- a forced LockOrderError produces a crash bundle whose JSON contains
  >=1 flight event from each of three different partitions, the active
  span stack, and a metric snapshot;
- /health flips from "ok" to degraded when the ledger age exceeds the
  close target in a simulated stall;
- with LOG_FORMAT=json, a log line emitted inside a ledger.close span
  carries that span's id.
"""

import io
import json
import logging as pylog
import threading
import urllib.error
import urllib.request

import pytest

from stellar_core_tpu import xdr as X
from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.main.config import Config
from stellar_core_tpu.util import eventlog, lockorder, metrics, tracing
from stellar_core_tpu.util import logging as slog


@pytest.fixture(autouse=True)
def _clean_recorder():
    eventlog.event_log().clear()
    yield
    eventlog.event_log().clear()


# ---------------------------------------------------------------------------
# flight recorder core
# ---------------------------------------------------------------------------

class TestFlightRecorder:
    def test_record_captures_structure_and_span(self):
        with tracing.span("ledger.close", seq=7) as s:
            eventlog.record("Ledger", "info", "close sealed", seq=7, txs=3)
        evs = eventlog.event_log().events()
        ev = next(e for e in evs if e.msg == "close sealed")
        assert ev.partition == "Ledger"
        assert ev.severity == "INFO"
        assert ev.fields == {"seq": 7, "txs": 3}
        assert ev.span_id == s.span_id
        assert ev.mono_s > 0 and ev.wall_s > 0

    def test_unknown_partition_rejected(self):
        with pytest.raises(ValueError):
            eventlog.record("NotAPartition", "INFO", "x")

    def test_ring_is_bounded_newest_kept(self):
        log = eventlog.EventLog(capacity=8)
        for i in range(20):
            log.record("Ledger", "INFO", f"e{i}")
        evs = log.events()
        assert len(evs) == 8
        assert evs[0].msg == "e12" and evs[-1].msg == "e19"

    def test_bridge_records_warning_not_info(self):
        slog.get("Overlay").warning("connection storm from %s", "peer-x")
        slog.get("Overlay").info("all quiet")
        msgs = [e.msg for e in eventlog.event_log().events()]
        assert any("connection storm from peer-x" in m for m in msgs)
        assert not any("all quiet" in m for m in msgs)

    def test_bridge_level_gate_means_zero_work_below(self):
        # the zero-overhead claim: the bridge handler's level filters
        # records before emit() — stdlib logging never calls it
        bridge = next(h for h in pylog.getLogger("stellar").handlers
                      if isinstance(h, eventlog.FlightRecorderBridge))
        assert bridge.level == pylog.WARNING

    def test_snapshot_coerces_fields(self):
        eventlog.record("Bucket", "INFO", "adopt", raw=b"\x01\x02")
        snap = eventlog.event_log().snapshot()
        ev = next(e for e in snap if e["msg"] == "adopt")
        assert isinstance(ev["fields"]["raw"], str)
        json.dumps(snap)  # whole snapshot is JSON-clean


# ---------------------------------------------------------------------------
# crash bundles
# ---------------------------------------------------------------------------

def _force_lock_inversion_in_span():
    """Build an A->B order, then invert it inside a ledger.close span."""
    lockorder.enable()
    lockorder.reset_observed()
    a = lockorder.make_lock("crashtest.a")
    b = lockorder.make_lock("crashtest.b")
    try:
        with a:
            with b:
                pass
        with tracing.span("ledger.close", seq=99):
            with tracing.span("ledger.seal"):
                with b:
                    with a:
                        pass
    finally:
        lockorder.disable()
        lockorder.reset_observed()


class TestCrashBundle:
    def test_lock_order_error_writes_bundle(self, tmp_path, monkeypatch):
        monkeypatch.setenv("STPU_CRASH_DIR", str(tmp_path))
        # populate three partitions through the real paths: an explicit
        # lifecycle record, the logging bridge, and a catchup-style event
        eventlog.record("Ledger", "INFO", "ledger close sealed", seq=12)
        slog.get("Overlay").warning("peer %s dropped: timeout", "ab12")
        eventlog.record("History", "INFO", "checkpoint applied",
                        checkpoint=63)
        with pytest.raises(lockorder.LockOrderError):
            _force_lock_inversion_in_span()

        bundles = list(tmp_path.glob("flight-*.json"))
        assert len(bundles) == 1
        doc = json.loads(bundles[0].read_text())
        assert doc["reason"].startswith("LockOrderError")
        partitions = {e["partition"] for e in doc["events"]}
        # the acceptance bar: >= 3 distinct partitions present
        assert {"Ledger", "Overlay", "History"} <= partitions
        # Process carries the inversion event itself
        assert "Process" in partitions
        # active span stack, innermost first
        names = [s["name"] for s in doc["span_stack"]]
        assert names == ["ledger.seal", "ledger.close"]
        assert all(s["span_id"] for s in doc["span_stack"])
        # full metric snapshot rides along
        assert doc["metrics"], "metric snapshot missing"
        assert "eventlog.record.count" in doc["metrics"]

    def test_invariant_failstop_writes_bundle(self, tmp_path, monkeypatch):
        monkeypatch.setenv("STPU_CRASH_DIR", str(tmp_path))
        from stellar_core_tpu.invariant.invariants import (
            InvariantDoesNotHold, _fail_invariant)
        with pytest.raises(InvariantDoesNotHold):
            _fail_invariant("ConservationOfLumens: 7 stroops vanished")
        bundles = list(tmp_path.glob("flight-*.json"))
        assert len(bundles) == 1
        doc = json.loads(bundles[0].read_text())
        assert "ConservationOfLumens" in doc["reason"]
        assert any(e["partition"] == "Invariant" for e in doc["events"])

    def test_no_crash_dir_means_no_write(self, tmp_path, monkeypatch):
        monkeypatch.delenv("STPU_CRASH_DIR", raising=False)
        assert eventlog.write_crash_bundle("test") is None

    def test_bundle_sources_and_errors_localized(self, monkeypatch):
        eventlog.register_bundle_source("good", lambda: {"x": 1})
        eventlog.register_bundle_source(
            "bad", lambda: (_ for _ in ()).throw(RuntimeError("boom")))
        try:
            doc = eventlog.flight_bundle("live")
        finally:
            eventlog.unregister_bundle_source("good")
            eventlog.unregister_bundle_source("bad")
        assert doc["good"] == {"x": 1}
        assert doc["bad"] == {"error": "boom"}

    @pytest.mark.filterwarnings(
        "ignore::pytest.PytestUnhandledThreadExceptionWarning")
    def test_thread_excepthook_writes_bundle(self, tmp_path, monkeypatch):
        monkeypatch.setenv("STPU_CRASH_DIR", str(tmp_path))
        eventlog.install_thread_excepthook()

        def die():
            raise RuntimeError("worker exploded")

        t = threading.Thread(target=die, name="doomed")
        t.start()
        t.join(10)
        bundles = list(tmp_path.glob("flight-*.json"))
        assert len(bundles) == 1
        doc = json.loads(bundles[0].read_text())
        assert "worker exploded" in doc["reason"]
        ev = next(e for e in doc["events"]
                  if e["msg"] == "unhandled exception in thread")
        assert ev["fields"]["thread"] == "doomed"


# ---------------------------------------------------------------------------
# structured (JSON) logging + span correlation
# ---------------------------------------------------------------------------

class TestJsonLogging:
    def _capture(self):
        buf = io.StringIO()
        h = pylog.StreamHandler(buf)
        h.setFormatter(slog.JsonFormatter())
        pylog.getLogger("stellar").addHandler(h)
        return buf, h

    def test_log_inside_close_span_carries_span_id(self):
        buf, h = self._capture()
        try:
            with tracing.span("ledger.close", seq=5) as s:
                slog.get("Ledger").warning("slow close at seq %d", 5)
                span_id = s.span_id
        finally:
            pylog.getLogger("stellar").removeHandler(h)
        line = [ln for ln in buf.getvalue().splitlines()
                if "slow close" in ln][0]
        doc = json.loads(line)
        assert doc["span"] == span_id
        assert doc["partition"] == "Ledger"
        assert doc["level"] == "WARNING"
        assert doc["msg"] == "slow close at seq 5"
        assert isinstance(doc["ts"], float)

    def test_log_outside_span_has_no_span_key(self):
        buf, h = self._capture()
        try:
            slog.get("Ledger").warning("no span here")
        finally:
            pylog.getLogger("stellar").removeHandler(h)
        doc = json.loads([ln for ln in buf.getvalue().splitlines()
                          if "no span here" in ln][0])
        assert "span" not in doc

    def test_set_format_roundtrip(self):
        assert slog.current_format() == "text"
        slog.set_format("json")
        try:
            assert slog.current_format() == "json"
            with pytest.raises(ValueError):
                slog.set_format("xml")
        finally:
            slog.set_format("text")

    def test_config_log_format_plumbs(self):
        cfg = Config.from_dict({"LOG_FORMAT": "json"})
        assert cfg.LOG_FORMAT == "json"
        assert Config().LOG_FORMAT == "text"

    def test_node_id_stamps_json_lines(self):
        """ISSUE 16: a named node (fleet NODE_NAME) stamps every JSON
        log line with `node` so interleaved fleet logs attribute."""
        buf, h = self._capture()
        slog.set_node_id("node-3")
        try:
            slog.get("Ledger").warning("who said this")
        finally:
            slog.set_node_id(None)
            pylog.getLogger("stellar").removeHandler(h)
        doc = json.loads([ln for ln in buf.getvalue().splitlines()
                          if "who said this" in ln][0])
        assert doc["node"] == "node-3"
        assert slog.node_id() is None


# ---------------------------------------------------------------------------
# rate_limited helper
# ---------------------------------------------------------------------------

class TestRateLimited:
    def test_first_and_every_nth_are_loud(self):
        slog.reset_rate_limits()
        log = slog.get("History")
        levels = []
        for _ in range(12):
            emit, n = slog.rate_limited(log, "test-key", 5)
            levels.append("warn" if emit == log.warning else "debug")
        # 1st, 5th and 10th loud; everything else quiet
        assert [i + 1 for i, lv in enumerate(levels) if lv == "warn"] \
            == [1, 5, 10]

    def test_keys_are_independent(self):
        slog.reset_rate_limits()
        log = slog.get("History")
        slog.rate_limited(log, "k1", 10)
        emit, n = slog.rate_limited(log, "k2", 10)
        assert n == 1 and emit == log.warning

    def test_keys_include_node_id(self):
        """ISSUE 16: the same logical key on different nodes (in-process
        multi-node tests) rate-limits independently, and discard uses
        the same node-scoped key."""
        slog.reset_rate_limits()
        log = slog.get("History")
        slog.set_node_id("node-a")
        try:
            slog.rate_limited(log, "shared", 10)
            slog.set_node_id("node-b")
            emit, n = slog.rate_limited(log, "shared", 10)
            assert n == 1 and emit == log.warning   # fresh per node
            slog.discard_rate_limit("shared")
            emit, n = slog.rate_limited(log, "shared", 10)
            assert n == 1   # discard removed node-b's counter
        finally:
            slog.set_node_id(None)


# ---------------------------------------------------------------------------
# StatusManager + /health
# ---------------------------------------------------------------------------

class TestStatusManager:
    def test_newest_status_per_category_and_clear(self):
        from stellar_core_tpu.main.status import StatusManager
        sm = StatusManager()
        sm.set_status("history-catchup", "downloading checkpoint 63")
        sm.set_status("history-catchup", "applying checkpoint 63")
        assert sm.get_status("history-catchup") == "applying checkpoint 63"
        assert sm.status_lines() == \
            ["[history-catchup] applying checkpoint 63"]
        sm.clear_status("history-catchup")
        assert sm.status_lines() == []
        with pytest.raises(ValueError):
            sm.set_status("nope", "x")


@pytest.fixture()
def app_node(tmp_path):
    """A standalone in-process node with a live admin HTTP server."""
    from stellar_core_tpu.main.application import Application
    from stellar_core_tpu.main.http_admin import CommandHandler
    from stellar_core_tpu.util.clock import ClockMode, VirtualClock

    metrics.reset_registry()
    cfg = Config.from_dict({
        "NETWORK_PASSPHRASE": "eventlog test net",
        "RUN_STANDALONE": True,
        "PEER_PORT": 0,
    })
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    app = Application(cfg, clock=clock, listen=False)
    http = CommandHandler(app, 0)
    http.start()
    app.start()
    assert clock.crank_until(
        lambda: app.lm.last_closed_ledger_seq >= 3, timeout=60)
    try:
        yield app, clock, http.port
    finally:
        http.stop()
        app.stop()


def _get(port, path):
    """GET returning (status_code, parsed_json) — 4xx/5xx included."""
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class TestHealth:
    def test_health_ok_then_degraded_on_stall(self, app_node):
        from stellar_core_tpu.main.status import evaluate_health
        app, clock, port = app_node
        code, doc = _get(port, "/health")
        assert code == 200 and doc["status"] == "ok", doc
        assert doc["checks"]["herder_state"] == "tracking"
        # node.health gauge reads 1.0 while healthy
        snap = metrics.registry().snapshot()
        assert snap["node.health"]["value"] == 1.0

        # simulated stall: consensus stops closing ledgers while virtual
        # time advances well past the close target
        app.herder.is_validator = False
        seq = app.lm.last_closed_ledger_seq
        clock.crank_for(10 * app.herder.ledger_timespan)
        assert app.lm.last_closed_ledger_seq == seq  # genuinely stalled

        code, doc = _get(port, "/health")
        assert code == 503 and doc["status"] == "degraded", doc
        assert any("ledger age" in r for r in doc["reasons"])
        assert metrics.registry().snapshot()["node.health"]["value"] == 0.0
        # direct evaluation agrees with the endpoint
        assert evaluate_health(app)["status"] == "degraded"

    def test_info_carries_status_lines(self, app_node):
        app, clock, port = app_node
        app.status.set_status("history-publish", "uploading checkpoint 127")
        code, doc = _get(port, "/info")
        assert code == 200
        assert "[history-publish] uploading checkpoint 127" \
            in doc["info"]["status"]
        app.status.clear_status("history-publish")


class TestAdminErrorPathsAndDumpflight:
    def test_unknown_endpoint_404_lists_endpoints(self, app_node):
        from stellar_core_tpu.main.http_admin import _ENDPOINTS
        app, clock, port = app_node
        code, doc = _get(port, "/definitely-not-real")
        assert code == 404
        assert doc["error"] == "unknown endpoint"
        assert doc["endpoints"] == sorted(_ENDPOINTS)
        assert "/health" in doc["endpoints"]
        assert "/dumpflight" in doc["endpoints"]

    @pytest.mark.parametrize("path", [
        "/unban?node=not-hex",
        "/ban?node=zz",
        "/ban",                      # missing required param
        "/droppeer?node=0xnope",
        "/connect?peer=h&port=eleven",
        "/getledgerentry?key=nothex",
        "/ll?level=shouty",
        "/ll?level=info&partition=Nope",
        "/ll?format=xml",
        "/upgrades?mode=set&upgradetime=tomorrow",
    ])
    def test_malformed_params_return_400(self, app_node, path):
        app, clock, port = app_node
        code, doc = _get(port, path)
        assert code == 400, (path, code, doc)
        assert "error" in doc

    def test_ll_rejected_request_is_side_effect_free(self, app_node):
        # a 400 must not have half-applied: format stays untouched when
        # the level (validated after it in the old code) is bogus
        app, clock, port = app_node
        assert slog.current_format() == "text"
        code, doc = _get(port, "/ll?format=json&level=shouty")
        assert code == 400
        assert slog.current_format() == "text"

    def test_ll_format_switch_roundtrip(self, app_node):
        app, clock, port = app_node
        try:
            code, doc = _get(port, "/ll?format=json")
            assert code == 200 and doc["format"] == "json"
            assert slog.current_format() == "json"
            code, doc = _get(port, "/ll")
            assert doc["format"] == "json"
        finally:
            _get(port, "/ll?format=text")
        assert slog.current_format() == "text"

    def test_dumpflight_roundtrip(self, app_node):
        app, clock, port = app_node
        eventlog.record("Main", "INFO", "marker for dumpflight")
        code, doc = _get(port, "/dumpflight")
        assert code == 200
        assert doc["reason"] == "live dump via /dumpflight"
        assert any(e["msg"] == "marker for dumpflight"
                   for e in doc["events"])
        assert "metrics" in doc and "span_stack" in doc
        # the application's registered sources ride along
        assert doc["herder"]["state"] == "tracking"
        assert doc["config"]["network_passphrase"] == "eventlog test net"

    def test_health_gauge_null_after_teardown(self, tmp_path):
        # weak_gauge: a torn-down node must read null, not resurrect
        from stellar_core_tpu.main.application import Application
        from stellar_core_tpu.util.clock import ClockMode, VirtualClock
        metrics.reset_registry()
        cfg = Config.from_dict({"NETWORK_PASSPHRASE": "gone net",
                                "RUN_STANDALONE": True, "PEER_PORT": 0})
        app = Application(cfg, clock=VirtualClock(ClockMode.VIRTUAL_TIME),
                          listen=False)
        assert metrics.registry().snapshot()["node.health"]["value"] \
            is not None
        app.stop()
        del app
        import gc
        gc.collect()
        assert metrics.registry().snapshot()["node.health"]["value"] is None


# ---------------------------------------------------------------------------
# lifecycle-edge instrumentation (the sweep actually fires)
# ---------------------------------------------------------------------------

class TestLifecycleEvents:
    def test_ledger_close_and_scp_events_from_live_node(self, app_node):
        app, clock, port = app_node
        evs = eventlog.event_log().events()
        assert any(e.partition == "Ledger"
                   and e.msg == "ledger close sealed" for e in evs)
        assert any(e.partition == "SCP"
                   and e.msg == "slot externalized" for e in evs)
        assert any(e.partition == "SCP"
                   and e.msg == "herder state transition" for e in evs)

    def test_ban_events(self, app_node):
        app, clock, port = app_node
        nid = SecretKey(b"\x42" * 32).public_key.ed25519
        app.overlay.ban_manager.ban_node(nid)
        app.overlay.ban_manager.unban_node(nid)
        msgs = [e.msg for e in eventlog.event_log().events()
                if e.partition == "Overlay"]
        assert "node banned" in msgs and "node unbanned" in msgs
