"""ISSUE 14: un-inverted accel replay + mesh catchup + work stealing.

Covers the three tentpole layers from the outside in:

* the never-wait preverify profiles (poll default / race opt-in /
  sig-only) and the watermark accounting that splits "device lost the
  race" from "never dispatched";
* device-per-range mesh pinning — per-worker visible-device env threaded
  through the subprocess cmdline, proven to actually reduce a worker's
  JAX device count to 1 on the CPU-simulated mesh;
* checkpoint-granular work stealing — the steal plan (fairness, boundary
  alignment, no overlap), the limit/ack handshake, the forged-steal-seam
  fail-stop, and the straggler-injected e2e proving stealing beats the
  no-steal wall clock with bit-identical hashes.

`make catchup-mesh` runs this file under the explicit 8-device
CPU-simulated mesh; plain tier-1 runs it too (conftest forces the same
mesh), so the pinning path runs in every verify, not only on-chip.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from stellar_core_tpu.accel.mesh import (ENV_DEVICE_COUNT,
                                         ENV_DEVICE_INDEX,
                                         assigned_device_index,
                                         worker_device_env)
from stellar_core_tpu.catchup.catchup import (CatchupError, CatchupManager,
                                              PreverifyPipeline)
from stellar_core_tpu.catchup.parallel import (ParallelCatchup, RangeControl,
                                               RangeSpec, plan_parallel_ranges,
                                               plan_steal,
                                               remaining_checkpoint_units,
                                               verify_stitches)
from stellar_core_tpu.history.archive import (CHECKPOINT_FREQUENCY,
                                              FileHistoryArchive)
from stellar_core_tpu.history.manager import HistoryManager
from stellar_core_tpu.ledger.manager import LedgerManager
from stellar_core_tpu.main.config import Config
from stellar_core_tpu.simulation.loadgen import LoadGenerator
from stellar_core_tpu.testutils import network_id
from stellar_core_tpu.util.metrics import registry

PASSPHRASE = "mesh catchup test network"
NID = network_id(PASSPHRASE)


@pytest.fixture(scope="module")
def published(tmp_path_factory):
    """A 6-checkpoint archive with payment traffic in every checkpoint —
    enough checkpoints that a 2-worker plan leaves a stealable tail."""
    archive_dir = tmp_path_factory.mktemp("mesh-archive")
    mgr = LedgerManager(NID)
    mgr.start_new_ledger()
    archive = FileHistoryArchive(str(archive_dir))
    history = HistoryManager(mgr, PASSPHRASE, [archive])
    gen = LoadGenerator(mgr, history, seed=23)
    gen.create_accounts(12, per_ledger=6)
    gen.run_checkpoints(6, txs_per_ledger=2)
    assert len(history.published_checkpoints) >= 6
    return str(archive_dir), archive, mgr, history


# ---------------------------------------------------------------------------
# steal planning
# ---------------------------------------------------------------------------

class TestStealPlan:
    def test_remaining_units_counts_boundaries_and_tail(self):
        f = CHECKPOINT_FREQUENCY
        assert remaining_checkpoint_units(1, f - 1) == 1
        assert remaining_checkpoint_units(f - 1, 2 * f - 1) == 1
        assert remaining_checkpoint_units(f - 1, 2 * f + 5) == 2  # + tail
        assert remaining_checkpoint_units(100, 100) == 0
        assert remaining_checkpoint_units(200, 100) == 0

    def test_split_fairness_half_rounded_down_to_thief(self):
        f = CHECKPOINT_FREQUENCY
        for units in range(2, 12):
            progress = f - 1
            replay_to = progress + units * f
            b = plan_steal(progress, replay_to)
            assert b is not None
            assert (b + 1) % f == 0, "split must sit on a boundary"
            keep = remaining_checkpoint_units(progress, b)
            stolen = remaining_checkpoint_units(b, replay_to)
            assert keep + stolen == units, "no overlap, full coverage"
            assert stolen == units // 2, "thief adopts half, rounded down"
            assert abs(keep - stolen) <= 1, "split is fair"

    def test_partial_tail_counts_as_a_unit(self):
        f = CHECKPOINT_FREQUENCY
        # progress at a boundary, 3 full checkpoints + a partial tail
        progress = f - 1
        replay_to = progress + 3 * f + 7
        b = plan_steal(progress, replay_to)
        assert b is not None
        assert remaining_checkpoint_units(b, replay_to) == 2  # 4 // 2

    def test_too_small_remainders_refuse(self):
        f = CHECKPOINT_FREQUENCY
        assert plan_steal(f - 1, 2 * f - 1) is None      # one unit
        assert plan_steal(f - 1, f + 10) is None          # partial only
        assert plan_steal(500, 400) is None               # nothing left

    def test_victim_never_rewinds(self):
        f = CHECKPOINT_FREQUENCY
        progress = 5 * f - 1
        b = plan_steal(progress, 11 * f - 1)
        assert b is not None and b > progress


# ---------------------------------------------------------------------------
# the limit/ack handshake (worker side)
# ---------------------------------------------------------------------------

class TestRangeControl:
    def _limit(self, ctl: RangeControl, boundary: int) -> None:
        with open(os.path.join(ctl.dir, RangeControl.LIMIT), "w") as f:
            json.dump({"replay_to": boundary}, f)

    def test_heartbeat_without_limit(self, tmp_path):
        ctl = RangeControl(str(tmp_path / "ctl"))
        assert ctl.checkpoint_hook(127) is None
        doc = json.load(open(os.path.join(ctl.dir, RangeControl.PROGRESS)))
        assert doc["lcl"] == 127
        assert not os.path.exists(os.path.join(ctl.dir, RangeControl.ACK))

    def test_accept_is_sticky_and_acked(self, tmp_path):
        ctl = RangeControl(str(tmp_path / "ctl"))
        self._limit(ctl, 191)
        assert ctl.checkpoint_hook(127) == 191
        ack = json.load(open(os.path.join(ctl.dir, RangeControl.ACK)))
        assert ack == {"accepted": 191}
        # a second (lower) limit must NOT take effect: one steal per
        # victim, or the already-spawned thief's seam would tear
        self._limit(ctl, 63)
        assert ctl.checkpoint_hook(163) == 191

    def test_progress_past_limit_rejects(self, tmp_path):
        ctl = RangeControl(str(tmp_path / "ctl"))
        self._limit(ctl, 100)
        assert ctl.checkpoint_hook(150) is None
        ack = json.load(open(os.path.join(ctl.dir, RangeControl.ACK)))
        assert ack == {"rejected": 150}
        # rejection is sticky too (no re-ack churn per checkpoint)
        assert ctl.checkpoint_hook(250) is None

    def test_throttle_env_injects_straggler_delay(self, tmp_path,
                                                  monkeypatch):
        monkeypatch.setenv("STPU_CATCHUP_THROTTLE_S", "0.15")
        ctl = RangeControl(str(tmp_path / "ctl"))
        t0 = time.perf_counter()
        ctl.checkpoint_hook(63)
        assert time.perf_counter() - t0 >= 0.15


# ---------------------------------------------------------------------------
# never-wait preverify (poll profile) + watermark accounting
# ---------------------------------------------------------------------------

class TestPollProfile:
    def test_default_profile_is_poll(self):
        pipe = PreverifyPipeline(NID, 256)
        assert pipe.profile == PreverifyPipeline.PROFILE_POLL
        pipe.close()

    def test_unknown_profile_rejected(self):
        with pytest.raises(ValueError):
            PreverifyPipeline(NID, 256, profile="eager")

    def _synthetic_group(self, pipe, release, n=2, cps=(63, 127)):
        def job():
            release.wait(10.0)
            return np.ones(n, dtype=bool), 0.01

        jb = pipe._submit(job)
        group = {"job": jb,
                 "pks": [bytes([i + 1]) * 32 for i in range(n)],
                 "sigs": [bytes([i + 9]) * 64 for i in range(n)],
                 "msgs": [b"m%d" % i for i in range(n)],
                 "checkpoints": list(cps),
                 "pairs_by_cp": {cps[0]: n, cps[1]: 1},
                 "collected_cps": set()}
        for cp in cps:
            pipe._groups[cp] = group
        pipe._live_groups.append(group)
        return jb, group

    def test_poll_collect_never_waits_then_late_seeds(self):
        pipe = PreverifyPipeline(NID, 256)   # poll default
        sink = []
        pipe.verdict_sink = lambda pks, sigs, msgs, v: sink.append(len(pks))
        release = threading.Event()
        jb, group = self._synthetic_group(pipe, release)
        race_lost = registry().counter("catchup.preverify.race-lost").value
        t0 = time.perf_counter()
        pipe.collect(63)               # device parked: must NOT block
        assert time.perf_counter() - t0 < 0.5
        assert pipe.stats.get("sigs_race_lost") == 2
        assert pipe.stats.get("collect_race_misses") == 1
        assert registry().counter("catchup.preverify.race-lost").value \
            - race_lost == 2
        assert not sink and not pipe.stats.get("sigs_shipped")
        # the group ripens; the NEXT collect harvests and seeds it —
        # checkpoint 63's sigs count as late (its apply already ran)
        release.set()
        assert jb[1].wait(5.0)
        pipe.collect(127)
        assert pipe.stats.get("sigs_shipped") == 2
        assert sink == [2]
        assert pipe.stats.get("sigs_late_seeded") == 2
        assert not pipe._disabled
        pipe.close()

    def test_poll_disables_after_sustained_silence_but_sig_only_never(self):
        for profile, expect_disabled in (("poll", True), ("sig-only", False)):
            pipe = PreverifyPipeline(NID, 256, profile=profile)
            pipe._harvested_once = True   # past the compile-grace window
            release = threading.Event()
            n_groups = PreverifyPipeline.MAX_POLL_MISS_COLLECTS + 2
            for i in range(n_groups):
                cp = 63 + 64 * i
                self._synthetic_group(pipe, release, cps=(cp, cp + 32))
                pipe.collect(cp)
            assert pipe._disabled is expect_disabled, profile
            release.set()
            pipe.close()

    def test_disabled_dispatch_counts_not_dispatched(self):
        pipe = PreverifyPipeline(NID, 256)
        pipe._disabled = True

        class F:
            signatures = [object(), object(), object()]

        before = registry().counter("catchup.preverify.not-dispatched").value
        pipe.dispatch({63: [F()]})
        assert pipe.dispatched(63)
        pipe.collect(63)   # no-op, no wait, no crash
        assert pipe.stats.get("sigs_total") == 3
        assert pipe.stats.get("sigs_not_dispatched") == 3
        assert registry().counter(
            "catchup.preverify.not-dispatched").value - before == 3
        pipe.close()

    def test_recommended_coalesce_tracks_consumer_rate(self):
        pipe = PreverifyPipeline(NID, 256)
        # no measurements yet: identity
        assert pipe.recommended_coalesce(4) == 4
        # device behind the consumer: grow toward the ceiling
        pipe._apply_s_per_cp = 0.1
        pipe._device_s_per_pair = 0.01
        pipe._pairs_per_cp = 100.0     # 1.0s of device work per cp
        assert pipe.recommended_coalesce(4) == 8
        assert pipe.recommended_coalesce(8) == 8   # clamped
        # device comfortably ahead: shrink for freshness
        pipe._device_s_per_pair = 0.0001   # 0.01s per cp vs 0.1s apply
        assert pipe.recommended_coalesce(4) == 3
        assert pipe.recommended_coalesce(1) == 1   # floor
        # in between: hold
        pipe._device_s_per_pair = 0.0008   # 0.08s per cp
        assert pipe.recommended_coalesce(4) == 4
        pipe.close()


# ---------------------------------------------------------------------------
# device-per-range mesh pinning
# ---------------------------------------------------------------------------

class TestMeshPinning:
    def test_env_shapes_per_platform(self):
        cpu = worker_device_env(2, 8, "cpu")
        assert cpu[ENV_DEVICE_INDEX] == "2"
        assert cpu[ENV_DEVICE_COUNT] == "8"
        assert "xla_force_host_platform_device_count=1" in cpu["XLA_FLAGS"]
        tpu = worker_device_env(3, 8, "tpu")
        assert tpu["TPU_VISIBLE_DEVICES"] == "3"
        assert tpu["TPU_PROCESS_BOUNDS"] == "1,1,1"
        gpu = worker_device_env(1, 4, "cuda")
        assert gpu["CUDA_VISIBLE_DEVICES"] == "1"

    def test_assigned_device_index_roundtrip(self, monkeypatch):
        monkeypatch.delenv(ENV_DEVICE_INDEX, raising=False)
        assert assigned_device_index() is None
        monkeypatch.setenv(ENV_DEVICE_INDEX, "5")
        assert assigned_device_index() == 5

    def test_cpu_mesh_env_actually_pins_one_device(self):
        """The make-or-break property: a subprocess under the worker env
        sees exactly ONE device while this (conftest-meshed) process sees
        8 — the same visible-device threading the on-chip mesh uses."""
        jax = pytest.importorskip("jax")
        if len(jax.devices()) < 2:
            pytest.skip("needs the multi-device CPU mesh (conftest)")
        env = dict(os.environ)
        env.update(worker_device_env(1, 4, "cpu"))
        code = ("import jax; jax.config.update('jax_platforms', 'cpu');"
                "print(len(jax.devices()))")
        r = subprocess.run([sys.executable, "-c", code], env=env,
                           capture_output=True, timeout=180)
        assert r.returncode == 0, r.stderr[-800:]
        assert r.stdout.strip() == b"1", r.stdout

    def test_mesh_env_threads_through_worker_cmdline(self, tmp_path):
        pc = ParallelCatchup(str(tmp_path / "a"), PASSPHRASE, workers=2,
                             workdir=str(tmp_path / "w"),
                             mesh_devices=2, mesh_platform="cpu")
        pc._specs = plan_parallel_ranges(255, 2)
        pc._target = 255
        cmd = pc._worker_cmdline(pc._specs[1])
        assert f"{ENV_DEVICE_INDEX}=1" in cmd
        assert "xla_force_host_platform_device_count=1" in cmd
        assert "--persist-target 255" in cmd
        assert "--ctl-dir" in cmd
        # round-robin wraps past the device count
        pc2 = ParallelCatchup(str(tmp_path / "a"), PASSPHRASE, workers=3,
                              workdir=str(tmp_path / "w2"),
                              mesh_devices=2, mesh_platform="cpu")
        pc2._specs = plan_parallel_ranges(400, 3)
        pc2._target = 400
        assert f"{ENV_DEVICE_INDEX}=0" in \
            pc2._worker_cmdline(pc2._specs[2])

    def test_config_keys_roundtrip(self):
        cfg = Config.from_dict({"CATCHUP_MESH_DEVICES": 4,
                                "CATCHUP_WORK_STEALING": False,
                                "ACCEL_OFFLOAD_PROFILE": "sig-only"})
        assert cfg.CATCHUP_MESH_DEVICES == 4
        assert cfg.CATCHUP_WORK_STEALING is False
        assert cfg.ACCEL_OFFLOAD_PROFILE == "sig-only"
        # defaults: stealing on, no pinning, poll profile
        dflt = Config()
        assert dflt.CATCHUP_WORK_STEALING is True
        assert dflt.CATCHUP_MESH_DEVICES == 0
        assert dflt.ACCEL_OFFLOAD_PROFILE == "poll"


# ---------------------------------------------------------------------------
# forged steal seam: fail-stop with crash bundle
# ---------------------------------------------------------------------------

def test_forged_steal_seam_failstops_with_bundle(tmp_path):
    """A steal splices a thief into the chain at the split boundary; its
    seam is proven exactly like a planned one, so a FORGED thief seed
    header (a poisoned worker claiming a seam it never verified) must
    kill the catchup with a crash bundle naming the boundary."""
    victim_end = 191
    results = [
        {"index": 0, "seed_checkpoint": None, "seed_header_hash": None,
         "replay_to": 255, "final_ledger_seq": victim_end,
         "final_hash": "aa" * 32, "ledgers_replayed": 190},
        {"index": 2, "seed_checkpoint": victim_end,   # the thief
         "seed_header_hash": "ff" * 32,               # FORGED
         "replay_to": 255, "final_ledger_seq": 255,
         "final_hash": "bb" * 32, "ledgers_replayed": 64},
    ]
    crash_dir = tmp_path / "crash"
    with pytest.raises(CatchupError, match=f"boundary {victim_end}"):
        verify_stitches(results, crash_dir=str(crash_dir))
    bundles = list(crash_dir.glob("flight-*.json"))
    assert bundles, "forged steal seam must write a crash bundle"
    doc = json.loads(bundles[0].read_text())
    assert str(victim_end) in doc["reason"] and "stitch" in doc["reason"]


# ---------------------------------------------------------------------------
# straggler-injected e2e: stealing beats no-steal, hashes identical
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def straggler_runs(published, tmp_path_factory):
    """Run the SAME straggler-injected catchup twice — steal off, steal
    on — over real subprocess workers.  Range 1 (the later half of a
    2-worker plan) sleeps per checkpoint; with 3 workers the pool has a
    spare to become the thief."""
    archive_dir, archive, mgr, history = published
    base = tmp_path_factory.mktemp("straggler")
    throttle = {1: {"STPU_CATCHUP_THROTTLE_S": "1.0"}}

    def one(steal: bool, name: str) -> dict:
        pc = ParallelCatchup(archive_dir, PASSPHRASE, workers=2,
                             workdir=str(base / name), steal=steal,
                             steal_min_checkpoints=2,
                             extra_env=throttle)
        report = pc.run()
        return report

    no_steal = one(False, "nosteal")
    with_steal = one(True, "steal")
    return mgr, no_steal, with_steal


def test_straggler_steal_beats_no_steal(straggler_runs):
    mgr, no_steal, with_steal = straggler_runs
    # correctness first: bit-identical final hashes, every seam proven
    assert no_steal["final_hash"] == mgr.lcl_hash.hex()
    assert with_steal["final_hash"] == mgr.lcl_hash.hex()
    assert with_steal["stitches_verified"] == \
        len(with_steal["ranges"]) - 1
    assert no_steal["steals"] == 0
    assert with_steal["steals"] >= 1
    # the dynamic seam chains exactly like planned ones
    for a, b in zip(with_steal["ranges"], with_steal["ranges"][1:]):
        assert a["final_ledger_seq"] == b["seed_checkpoint"]
        assert a["final_hash"] == b["seed_header_hash"]
    # and the whole point: wall clock beats the straggler-bound run
    assert with_steal["wall_s"] < no_steal["wall_s"], (
        f"steal {with_steal['wall_s']}s vs no-steal {no_steal['wall_s']}s")


def test_steal_event_record_and_truncation(straggler_runs):
    mgr, _no_steal, with_steal = straggler_runs
    ev = with_steal["steal_events"][0]
    assert ev["victim"] == 1
    assert ev["thief"] >= 2
    assert (ev["boundary"] + 1) % CHECKPOINT_FREQUENCY == 0
    assert ev["checkpoints_adopted"] >= 1
    victim = next(r for r in with_steal["ranges"]
                  if r["index"] == ev["victim"])
    thief = next(r for r in with_steal["ranges"]
                 if r["index"] == ev["thief"])
    assert victim["final_ledger_seq"] == ev["boundary"]
    assert victim["truncated_to"] == ev["boundary"]
    assert thief["seed_checkpoint"] == ev["boundary"]
    assert thief["final_ledger_seq"] == with_steal["target"]
    # whoever reached the target persisted; the truncated victim did not
    assert thief["persisted"] and not victim["persisted"]
    assert registry().counter("catchup.parallel.steal").value >= 1


def test_stale_ctl_dirs_from_previous_run_are_wiped(published, tmp_path):
    """A reused workdir holding an interrupted run's steal artifacts must
    not poison the new run: a worker honoring a stale limit would
    truncate its range with no thief to cover the tail."""
    archive_dir, archive, mgr, history = published
    w = tmp_path / "w"
    for idx, boundary in ((0, 63), (1, 255)):
        ctl = w / f"ctl-{idx:02d}"
        ctl.mkdir(parents=True)
        (ctl / RangeControl.LIMIT).write_text(
            json.dumps({"replay_to": boundary}))
        (ctl / RangeControl.ACK).write_text(
            json.dumps({"accepted": boundary}))
    pc = ParallelCatchup(archive_dir, PASSPHRASE, workers=2,
                         workdir=str(w))
    report = pc.run()
    assert report["final_hash"] == mgr.lcl_hash.hex()
    assert report["steals"] == 0


def test_stolen_catchup_state_is_adoptable(published, tmp_path):
    """After a steal, load_manager() must rebuild the ledger from the
    THIEF's persisted dir (the planned-last range was the victim)."""
    archive_dir, archive, mgr, history = published
    pc = ParallelCatchup(archive_dir, PASSPHRASE, workers=2,
                         workdir=str(tmp_path / "w"), steal=True,
                         steal_min_checkpoints=2,
                         extra_env={1: {"STPU_CATCHUP_THROTTLE_S": "0.8"}})
    report = pc.run()
    assert report["steals"] >= 1
    m2 = pc.load_manager()
    assert m2.lcl_hash == mgr.lcl_hash
    assert m2.last_closed_ledger_seq == report["target"]
