"""Protocol-version gating tests (reference: for_all_versions in TxTests;
each op frame's isVersionSupported)."""

import pytest

from stellar_core_tpu import xdr as X
from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.testutils import (SUPPORTED_PROTOCOL_RANGE,
                                        TestAccount, build_tx,
                                        create_account_op, for_all_versions,
                                        make_asset, manage_buy_offer_op,
                                        native_payment_op, network_id)

NID = network_id("protocol version test net")


def _root(mgr):
    sk = mgr.root_account_secret()
    e = mgr.root.get_entry(X.LedgerKey.account(X.LedgerKeyAccount(
        accountID=X.AccountID.ed25519(sk.public_key.ed25519))).to_xdr())
    return TestAccount(mgr, sk, e.data.value.seqNum)


def _result_of(arts, frame):
    for pair in arts.result_entry.txResultSet.results:
        if pair.transactionHash == frame.content_hash():
            return pair.result
    raise AssertionError


def test_payment_works_at_every_version():
    def body(mgr, version):
        root = _root(mgr)
        dest = SecretKey(b"\x55" * 32)
        fr = root.tx([create_account_op(
            X.AccountID.ed25519(dest.public_key.ed25519), 10**10)])
        arts = mgr.close_ledger([fr], 1000)
        res = _result_of(arts, fr)
        assert res.result.switch == X.TransactionResultCode.txSUCCESS, \
            (version, res)
        assert mgr.lcl_header.ledgerVersion == version

    for_all_versions(NID, body)


OP_GATES = [
    # (min_version, op builder)
    (14, lambda root: X.Operation(
        body=X.OperationBody.createClaimableBalanceOp(
            X.CreateClaimableBalanceOp(
                asset=X.Asset.native(), amount=100,
                claimants=[X.Claimant.v0(X.ClaimantV0(
                    destination=root.account_id,
                    predicate=X.ClaimPredicate.unconditional()))])))),
    (17, lambda root: X.Operation(
        body=X.OperationBody.clawbackOp(X.ClawbackOp(
            asset=make_asset("EUR", root.account_id),
            from_=X.muxed_from_account_id(root.account_id), amount=1)))),
    (18, lambda root: X.Operation(
        body=X.OperationBody.liquidityPoolWithdrawOp(
            X.LiquidityPoolWithdrawOp(
                liquidityPoolID=b"\x01" * 32, amount=1,
                minAmountA=0, minAmountB=0)))),
    (11, lambda root: manage_buy_offer_op(
        X.Asset.native(), make_asset("EUR", root.account_id), 10, 1, 1)),
]


@pytest.mark.parametrize("min_version,build", OP_GATES,
                         ids=["claimable14", "clawback17", "pool18",
                              "buyoffer11"])
def test_op_gated_below_introduction_version(min_version, build):
    def body(mgr, version):
        root = _root(mgr)
        fr = root.tx([build(root)])
        arts = mgr.close_ledger([fr], 1000)
        res = _result_of(arts, fr)
        op_res = res.result.value[0] if res.result.value else None
        if version < min_version:
            assert res.result.switch == X.TransactionResultCode.txFAILED
            assert op_res.switch == X.OperationResultCode.opNOT_SUPPORTED, \
                (version, op_res)
        else:
            # at/after introduction the op is dispatched (it may fail for
            # state reasons, but never opNOT_SUPPORTED)
            assert op_res is None or \
                op_res.switch != X.OperationResultCode.opNOT_SUPPORTED, \
                (version, op_res)

    for_all_versions(NID, body, versions=[min_version - 1, min_version])


def test_fee_bump_gated_below_13():
    def body(mgr, version):
        root = _root(mgr)
        inner = root.tx([native_payment_op(root.account_id, 1)], fee=100)
        fb = X.FeeBumpTransaction(
            feeSource=X.MuxedAccount.ed25519(
                root.secret.public_key.ed25519),
            fee=400,
            innerTx=X.FeeBumpInnerTx.v1(inner.envelope.value),
            ext=X.FeeBumpTransaction._spec[3][1].cls(0))
        fb_env = X.TransactionEnvelope.feeBump(
            X.FeeBumpTransactionEnvelope(tx=fb, signatures=[]))
        frame = mgr.make_frame(fb_env)
        payload = frame.content_hash()
        fb_env.value.signatures.append(X.DecoratedSignature(
            hint=root.secret.public_key.hint(),
            signature=root.secret.sign(payload)))
        arts = mgr.close_ledger([frame], 1000)
        res = _result_of(arts, frame)
        if version < 13:
            assert res.result.switch == X.TransactionResultCode.txNOT_SUPPORTED
        else:
            assert res.result.switch in (
                X.TransactionResultCode.txFEE_BUMP_INNER_SUCCESS,
                X.TransactionResultCode.txFEE_BUMP_INNER_FAILED), res

    for_all_versions(NID, body, versions=[12, 13])


def test_precond_v2_gated_below_19():
    def body(mgr, version):
        root = _root(mgr)
        tx = X.Transaction(
            sourceAccount=X.MuxedAccount.ed25519(
                root.secret.public_key.ed25519),
            fee=100, seqNum=root.next_seq(),
            cond=X.Preconditions.v2(X.PreconditionsV2(
                timeBounds=None, ledgerBounds=None, minSeqNum=None,
                minSeqAge=0, minSeqLedgerGap=0, extraSigners=[])),
            memo=X.Memo.none(), operations=[
                native_payment_op(root.account_id, 1)])
        env = X.TransactionEnvelope.v1(X.TransactionV1Envelope(
            tx=tx, signatures=[]))
        frame = mgr.make_frame(env)
        env.value.signatures.append(X.DecoratedSignature(
            hint=root.secret.public_key.hint(),
            signature=root.secret.sign(frame.content_hash())))
        arts = mgr.close_ledger([frame], 1000)
        res = _result_of(arts, frame)
        if version < 19:
            assert res.result.switch == X.TransactionResultCode.txNOT_SUPPORTED
        else:
            assert res.result.switch == X.TransactionResultCode.txSUCCESS, res

    for_all_versions(NID, body, versions=[18, 19])


def test_surge_pricing_counts_txs_below_11_and_ops_after():
    from stellar_core_tpu.herder.tx_queue import TransactionQueue
    from stellar_core_tpu.ledger.manager import LedgerManager

    for version, expect in ((10, 3), (11, 1)):
        mgr = LedgerManager(NID)
        mgr.start_new_ledger(protocol_version=version)
        mgr.lcl_header.maxTxSetSize = 3
        root = _root(mgr)
        q = TransactionQueue(mgr)
        for i in range(3):
            fr = root.tx([native_payment_op(root.account_id, 1)] * 3)
            q.by_hash[fr.content_hash()] = fr  # bypass validity for unit test
        got = q.tx_set_frames()
        # v10: 3 txs fit (counted as txs); v11+: 3-op txs fill the 3-op cap
        assert len(got) == expect, (version, len(got))


def test_muxed_account_gated_below_13():
    def body(mgr, version):
        root = _root(mgr)
        muxed_dest = X.MuxedAccount.med25519(X.MuxedAccount._arms[X.CryptoKeyType.KEY_TYPE_MUXED_ED25519][1].cls(
            id=7, ed25519=root.secret.public_key.ed25519))
        op = X.Operation(body=X.OperationBody.paymentOp(X.PaymentOp(
            destination=muxed_dest, asset=X.Asset.native(), amount=1)))
        fr = root.tx([op])
        arts = mgr.close_ledger([fr], 1000)
        res = _result_of(arts, fr)
        if version < 13:
            assert res.result.switch == X.TransactionResultCode.txNOT_SUPPORTED
        else:
            assert res.result.switch == X.TransactionResultCode.txSUCCESS, res

    for_all_versions(NID, body, versions=[12, 13])


# --- systematic MIN_PROTOCOL sweep over every gated op frame --------------
# (VERDICT r5 item 7: the gate matrix applied across the op-frame suite,
# not just four hand-picked ops)

def _sponsor_begin(root):
    return X.Operation(body=X.OperationBody.beginSponsoringFutureReservesOp(
        X.BeginSponsoringFutureReservesOp(
            sponsoredID=X.AccountID.ed25519(b"\x61" * 32))))


def _sponsor_end(root):
    return X.Operation(body=X.OperationBody.endSponsoringFutureReserves())


def _sponsor_revoke(root):
    return X.Operation(body=X.OperationBody.revokeSponsorshipOp(
        X.RevokeSponsorshipOp.ledgerKey(X.LedgerKey.account(
            X.LedgerKeyAccount(accountID=root.account_id)))))


ALL_GATED_OPS = [
    (10, "bumpseq", lambda root: X.Operation(
        body=X.OperationBody.bumpSequenceOp(X.BumpSequenceOp(bumpTo=1)))),
    (11, "managebuy", lambda root: manage_buy_offer_op(
        X.Asset.native(), make_asset("EUR", root.account_id), 10, 1, 1)),
    (12, "ppstrictsend", lambda root: X.Operation(
        body=X.OperationBody.pathPaymentStrictSendOp(
            X.PathPaymentStrictSendOp(
                sendAsset=X.Asset.native(), sendAmount=10,
                destination=X.muxed_from_account_id(root.account_id),
                destAsset=make_asset("EUR", root.account_id),
                destMin=1, path=[])))),
    (14, "claimablecreate", lambda root: X.Operation(
        body=X.OperationBody.createClaimableBalanceOp(
            X.CreateClaimableBalanceOp(
                asset=X.Asset.native(), amount=100,
                claimants=[X.Claimant.v0(X.ClaimantV0(
                    destination=root.account_id,
                    predicate=X.ClaimPredicate.unconditional()))])))),
    (14, "claimableclaim", lambda root: X.Operation(
        body=X.OperationBody.claimClaimableBalanceOp(
            X.ClaimClaimableBalanceOp(
                balanceID=X.ClaimableBalanceID.v0(b"\x01" * 32))))),
    (14, "beginsponsor", _sponsor_begin),
    (14, "endsponsor", _sponsor_end),
    (14, "revokesponsor", _sponsor_revoke),
    (17, "clawback", lambda root: X.Operation(
        body=X.OperationBody.clawbackOp(X.ClawbackOp(
            asset=make_asset("EUR", root.account_id),
            from_=X.muxed_from_account_id(root.account_id), amount=1)))),
    (17, "clawbackcb", lambda root: X.Operation(
        body=X.OperationBody.clawbackClaimableBalanceOp(
            X.ClawbackClaimableBalanceOp(
                balanceID=X.ClaimableBalanceID.v0(b"\x01" * 32))))),
    (17, "settlflags", lambda root: X.Operation(
        body=X.OperationBody.setTrustLineFlagsOp(X.SetTrustLineFlagsOp(
            trustor=X.AccountID.ed25519(b"\x62" * 32),
            asset=make_asset("EUR", root.account_id),
            clearFlags=0, setFlags=1)))),
    (18, "pooldeposit", lambda root: X.Operation(
        body=X.OperationBody.liquidityPoolDepositOp(X.LiquidityPoolDepositOp(
            liquidityPoolID=b"\x01" * 32, maxAmountA=1, maxAmountB=1,
            minPrice=X.Price(n=1, d=1), maxPrice=X.Price(n=1, d=1))))),
    (18, "poolwithdraw", lambda root: X.Operation(
        body=X.OperationBody.liquidityPoolWithdrawOp(
            X.LiquidityPoolWithdrawOp(
                liquidityPoolID=b"\x01" * 32, amount=1,
                minAmountA=0, minAmountB=0)))),
]


@pytest.mark.parametrize("min_version,name,build", ALL_GATED_OPS,
                         ids=[t[1] for t in ALL_GATED_OPS])
def test_every_gated_op_rejects_below_and_dispatches_at(min_version, name,
                                                        build):
    """Below its introduction version every gated op returns
    opNOT_SUPPORTED; at it, the op is dispatched (may fail for state
    reasons, never opNOT_SUPPORTED)."""
    def body(mgr, version):
        root = _root(mgr)
        fr = root.tx([build(root)])
        arts = mgr.close_ledger([fr], 1000)
        res = _result_of(arts, fr)
        op_res = res.result.value[0] if res.result.value else None
        if version < min_version:
            assert res.result.switch in (
                X.TransactionResultCode.txFAILED,
                X.TransactionResultCode.txBAD_SPONSORSHIP), (name, version)
            if res.result.switch == X.TransactionResultCode.txFAILED:
                assert op_res.switch == X.OperationResultCode.opNOT_SUPPORTED, \
                    (name, version, op_res)
        else:
            assert op_res is None or \
                op_res.switch != X.OperationResultCode.opNOT_SUPPORTED, \
                (name, version, op_res)

    for_all_versions(NID, body, versions=[min_version - 1, min_version])


def test_starting_sequence_number_all_versions():
    """Created accounts start at ledgerSeq << 32 under every protocol
    (reference: getStartingSequenceNumber)."""
    def body(mgr, version):
        root = _root(mgr)
        dest = X.AccountID.ed25519(b"\x63" * 32)
        arts = mgr.close_ledger([root.tx([create_account_op(dest, 10**10)])],
                                1000)
        e = mgr.root.get_entry(X.LedgerKey.account(
            X.LedgerKeyAccount(accountID=dest)).to_xdr())
        assert e.data.value.seqNum == mgr.last_closed_ledger_seq << 32, \
            version

    for_all_versions(NID, body)


def test_zero_balance_create_account_gate_at_14():
    """startingBalance == 0 is MALFORMED below v14 (CAP-33) and
    LOW_RESERVE (unsponsored) from v14 on."""
    def body(mgr, version):
        root = _root(mgr)
        dest = X.AccountID.ed25519(b"\x64" * 32)
        fr = root.tx([create_account_op(dest, 0)])
        arts = mgr.close_ledger([fr], 1000)
        res = _result_of(arts, fr)
        assert res.result.switch == X.TransactionResultCode.txFAILED
        code = res.result.value[0].value.value.switch
        if version < 14:
            assert code == \
                X.CreateAccountResultCode.CREATE_ACCOUNT_MALFORMED, version
        else:
            assert code == \
                X.CreateAccountResultCode.CREATE_ACCOUNT_LOW_RESERVE, version

    for_all_versions(NID, body, versions=[13, 14])
