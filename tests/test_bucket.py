"""BucketList tests (reference: src/bucket/test/BucketListTests.cpp,
BucketTests.cpp): merge pair semantics, spill cadence, hash determinism,
golden bucket-list hash after scripted batches."""

import pytest

from stellar_core_tpu import xdr as X
from stellar_core_tpu.bucket.bucket import Bucket, merge_buckets
from stellar_core_tpu.bucket.bucket_list import (NUM_LEVELS, BucketList,
                                                 level_half, level_should_spill,
                                                 level_size)

PROTO = 23


def _acct_entry(n: int, balance: int = 100, seq: int = 1) -> X.LedgerEntry:
    return X.LedgerEntry(
        lastModifiedLedgerSeq=1,
        data=X.LedgerEntryData.account(X.AccountEntry(
            accountID=X.AccountID.ed25519(bytes([n]) * 32),
            balance=balance, seqNum=seq)))


def _key(n: int) -> X.LedgerKey:
    return X.ledger_entry_key(_acct_entry(n))


def test_fresh_bucket_sorted_and_hashed():
    b = Bucket.fresh(PROTO, [_acct_entry(3)], [_acct_entry(1)], [_key(2)])
    keys = [e.to_xdr() for e in b.entries]
    assert keys == sorted(keys)
    assert b.hash() != b"\x00" * 32
    assert Bucket.empty().hash() == b"\x00" * 32
    # deterministic
    b2 = Bucket.fresh(PROTO, [_acct_entry(3)], [_acct_entry(1)], [_key(2)])
    assert b.hash() == b2.hash()


def test_bucket_serialize_roundtrip():
    b = Bucket.fresh(PROTO, [_acct_entry(1)], [_acct_entry(2, balance=7)],
                     [_key(3)])
    rt = Bucket.deserialize(b.serialize())
    assert rt.protocol_version == PROTO
    assert [e.to_xdr() for e in rt.entries] == [e.to_xdr() for e in b.entries]
    assert rt.hash() == b.hash()


def test_merge_pair_semantics():
    init1 = Bucket.fresh(PROTO, [_acct_entry(1)], [], [])
    live1 = Bucket.fresh(PROTO, [], [_acct_entry(1, balance=50)], [])
    dead1 = Bucket.fresh(PROTO, [], [], [_key(1)])

    # INIT + LIVE -> INIT carrying new value
    m = merge_buckets(init1, live1)
    assert len(m.entries) == 1
    assert m.entries[0].switch == X.BucketEntryType.INITENTRY
    assert m.entries[0].value.data.value.balance == 50

    # INIT + DEAD -> annihilate
    m = merge_buckets(init1, dead1)
    assert m.entries == []

    # LIVE + DEAD -> tombstone kept (non-bottom)
    m = merge_buckets(live1, dead1)
    assert [e.switch for e in m.entries] == [X.BucketEntryType.DEADENTRY]

    # ... dropped at bottom
    m = merge_buckets(live1, dead1, keep_tombstones=False)
    assert m.entries == []

    # DEAD + INIT -> LIVE (resurrection collapses)
    m = merge_buckets(dead1, init1)
    assert [e.switch for e in m.entries] == [X.BucketEntryType.LIVEENTRY]

    # INIT decays to LIVE at the bottom
    m = merge_buckets(Bucket.empty(), init1, keep_tombstones=False)
    assert [e.switch for e in m.entries] == [X.BucketEntryType.LIVEENTRY]


def test_merge_disjoint_keys_union():
    a = Bucket.fresh(PROTO, [], [_acct_entry(1), _acct_entry(3)], [])
    b = Bucket.fresh(PROTO, [], [_acct_entry(2)], [])
    m = merge_buckets(a, b)
    assert len(m.entries) == 3
    keys = [e.to_xdr() for e in m.entries]
    assert keys == sorted(keys)


def test_spill_schedule():
    assert level_size(0) == 4 and level_half(0) == 2
    assert level_size(1) == 16
    # level 0 spills every 2 ledgers; never on odd
    assert level_should_spill(2, 0) and level_should_spill(4, 0)
    assert not level_should_spill(3, 0)
    # level 1 spills every 8
    assert level_should_spill(8, 1) and not level_should_spill(4, 1)
    # bottom level never spills
    assert not level_should_spill(2 ** 20, NUM_LEVELS - 1)


def test_bucketlist_add_batches_and_lookup_shape():
    bl = BucketList()
    for ledger in range(1, 65):
        bl.add_batch(ledger, PROTO, [_acct_entry(ledger % 16, seq=ledger)], [], [])
    # levels 0..2 should be populated by now; deep levels empty
    assert not bl.levels[0].curr.is_empty() or not bl.levels[0].snap.is_empty()
    assert all(bl.levels[i].curr.is_empty() for i in range(5, NUM_LEVELS))


def test_bucketlist_hash_changes_and_is_deterministic():
    def run():
        bl = BucketList()
        for ledger in range(1, 20):
            bl.add_batch(ledger, PROTO,
                         [_acct_entry(ledger, balance=ledger * 10)],
                         [], [])
        return bl
    h1 = run().hash()
    h2 = run().hash()
    assert h1 == h2
    bl = run()
    bl.add_batch(20, PROTO, [], [_acct_entry(1, balance=999, seq=20)], [])
    assert bl.hash() != h1


def test_bucketlist_golden_hash():
    """Golden hash over a scripted sequence — guards byte-level stability of
    bucket serialization, merge rules, and the level-hash tree. If this
    changes unexpectedly, ledger hash chains will fork."""
    bl = BucketList()
    for ledger in range(1, 33):
        init = [_acct_entry(ledger % 8, balance=1000 + ledger, seq=ledger)] \
            if ledger % 2 == 1 else []
        live = [_acct_entry((ledger + 1) % 8, balance=2000 + ledger, seq=ledger)] \
            if ledger % 3 == 0 else []
        dead = [_key((ledger + 3) % 8)] if ledger % 8 == 0 else []
        bl.add_batch(ledger, PROTO, init, live, dead)
    golden = bl.hash().hex()
    assert len(golden) == 64
    again = BucketList()
    for ledger in range(1, 33):
        init = [_acct_entry(ledger % 8, balance=1000 + ledger, seq=ledger)] \
            if ledger % 2 == 1 else []
        live = [_acct_entry((ledger + 1) % 8, balance=2000 + ledger, seq=ledger)] \
            if ledger % 3 == 0 else []
        dead = [_key((ledger + 3) % 8)] if ledger % 8 == 0 else []
        again.add_batch(ledger, PROTO, init, live, dead)
    assert again.hash().hex() == golden
