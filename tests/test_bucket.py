"""BucketList tests (reference: src/bucket/test/BucketListTests.cpp,
BucketTests.cpp): merge pair semantics, spill cadence, hash determinism,
golden bucket-list hash after scripted batches."""

import pytest

from stellar_core_tpu import xdr as X
from stellar_core_tpu.bucket.bucket import Bucket, merge_buckets
from stellar_core_tpu.bucket.bucket_list import (NUM_LEVELS, BucketList,
                                                 level_half, level_should_spill,
                                                 level_size)

PROTO = 23


def _acct_entry(n: int, balance: int = 100, seq: int = 1) -> X.LedgerEntry:
    return X.LedgerEntry(
        lastModifiedLedgerSeq=1,
        data=X.LedgerEntryData.account(X.AccountEntry(
            accountID=X.AccountID.ed25519(bytes([n]) * 32),
            balance=balance, seqNum=seq)))


def _key(n: int) -> X.LedgerKey:
    return X.ledger_entry_key(_acct_entry(n))


def test_fresh_bucket_sorted_and_hashed():
    b = Bucket.fresh(PROTO, [_acct_entry(3)], [_acct_entry(1)], [_key(2)])
    keys = [e.to_xdr() for e in b.entries]
    assert keys == sorted(keys)
    assert b.hash() != b"\x00" * 32
    assert Bucket.empty().hash() == b"\x00" * 32
    # deterministic
    b2 = Bucket.fresh(PROTO, [_acct_entry(3)], [_acct_entry(1)], [_key(2)])
    assert b.hash() == b2.hash()


def test_bucket_serialize_roundtrip():
    b = Bucket.fresh(PROTO, [_acct_entry(1)], [_acct_entry(2, balance=7)],
                     [_key(3)])
    rt = Bucket.deserialize(b.serialize())
    assert rt.protocol_version == PROTO
    assert [e.to_xdr() for e in rt.entries] == [e.to_xdr() for e in b.entries]
    assert rt.hash() == b.hash()


def test_merge_pair_semantics():
    init1 = Bucket.fresh(PROTO, [_acct_entry(1)], [], [])
    live1 = Bucket.fresh(PROTO, [], [_acct_entry(1, balance=50)], [])
    dead1 = Bucket.fresh(PROTO, [], [], [_key(1)])

    # INIT + LIVE -> INIT carrying new value
    m = merge_buckets(init1, live1)
    assert len(m.entries) == 1
    assert m.entries[0].switch == X.BucketEntryType.INITENTRY
    assert m.entries[0].value.data.value.balance == 50

    # INIT + DEAD -> annihilate
    m = merge_buckets(init1, dead1)
    assert m.entries == []

    # LIVE + DEAD -> tombstone kept (non-bottom)
    m = merge_buckets(live1, dead1)
    assert [e.switch for e in m.entries] == [X.BucketEntryType.DEADENTRY]

    # ... dropped at bottom
    m = merge_buckets(live1, dead1, keep_tombstones=False)
    assert m.entries == []

    # DEAD + INIT -> LIVE (resurrection collapses)
    m = merge_buckets(dead1, init1)
    assert [e.switch for e in m.entries] == [X.BucketEntryType.LIVEENTRY]

    # INIT decays to LIVE at the bottom
    m = merge_buckets(Bucket.empty(), init1, keep_tombstones=False)
    assert [e.switch for e in m.entries] == [X.BucketEntryType.LIVEENTRY]


def test_merge_disjoint_keys_union():
    a = Bucket.fresh(PROTO, [], [_acct_entry(1), _acct_entry(3)], [])
    b = Bucket.fresh(PROTO, [], [_acct_entry(2)], [])
    m = merge_buckets(a, b)
    assert len(m.entries) == 3
    keys = [e.to_xdr() for e in m.entries]
    assert keys == sorted(keys)


def test_spill_schedule():
    assert level_size(0) == 4 and level_half(0) == 2
    assert level_size(1) == 16
    # level 0 spills every 2 ledgers; never on odd
    assert level_should_spill(2, 0) and level_should_spill(4, 0)
    assert not level_should_spill(3, 0)
    # level 1 spills every 8
    assert level_should_spill(8, 1) and not level_should_spill(4, 1)
    # bottom level never spills
    assert not level_should_spill(2 ** 20, NUM_LEVELS - 1)


def test_bucketlist_add_batches_and_lookup_shape():
    bl = BucketList()
    for ledger in range(1, 65):
        bl.add_batch(ledger, PROTO, [_acct_entry(ledger % 16, seq=ledger)], [], [])
    # levels 0..2 should be populated by now; deep levels empty
    assert not bl.levels[0].curr.is_empty() or not bl.levels[0].snap.is_empty()
    assert all(bl.levels[i].curr.is_empty() for i in range(5, NUM_LEVELS))


def test_bucketlist_hash_changes_and_is_deterministic():
    def run():
        bl = BucketList()
        for ledger in range(1, 20):
            bl.add_batch(ledger, PROTO,
                         [_acct_entry(ledger, balance=ledger * 10)],
                         [], [])
        return bl
    h1 = run().hash()
    h2 = run().hash()
    assert h1 == h2
    bl = run()
    bl.add_batch(20, PROTO, [], [_acct_entry(1, balance=999, seq=20)], [])
    assert bl.hash() != h1


def test_bucketlist_golden_hash():
    """Golden hash over a scripted sequence — guards byte-level stability of
    bucket serialization, merge rules, and the level-hash tree. If this
    changes unexpectedly, ledger hash chains will fork."""
    bl = BucketList()
    for ledger in range(1, 33):
        init = [_acct_entry(ledger % 8, balance=1000 + ledger, seq=ledger)] \
            if ledger % 2 == 1 else []
        live = [_acct_entry((ledger + 1) % 8, balance=2000 + ledger, seq=ledger)] \
            if ledger % 3 == 0 else []
        dead = [_key((ledger + 3) % 8)] if ledger % 8 == 0 else []
        bl.add_batch(ledger, PROTO, init, live, dead)
    golden = bl.hash().hex()
    assert len(golden) == 64
    again = BucketList()
    for ledger in range(1, 33):
        init = [_acct_entry(ledger % 8, balance=1000 + ledger, seq=ledger)] \
            if ledger % 2 == 1 else []
        live = [_acct_entry((ledger + 1) % 8, balance=2000 + ledger, seq=ledger)] \
            if ledger % 3 == 0 else []
        dead = [_key((ledger + 3) % 8)] if ledger % 8 == 0 else []
        again.add_batch(ledger, PROTO, init, live, dead)
    assert again.hash().hex() == golden


# -- round 2: FutureBucket pipeline / BucketIndex / snapshot ----------------

def _scripted_list(executor=None, n_ledgers=40) -> BucketList:
    bl = BucketList(executor=executor)
    for ledger in range(1, n_ledgers + 1):
        init = [_acct_entry(ledger % 16, seq=ledger)]
        live = [_acct_entry((ledger + 5) % 16, balance=ledger)] \
            if ledger % 3 == 0 else []
        dead = [_key((ledger + 9) % 16)] if ledger % 7 == 0 else []
        bl.add_batch(ledger, PROTO, init, live, dead)
    return bl


def test_future_bucket_threaded_merges_match_sync():
    """Background merges must be bit-identical to synchronous ones
    (reference: FutureBucket merges are pure; only scheduling differs)."""
    from concurrent.futures import ThreadPoolExecutor
    sync = _scripted_list(None)
    with ThreadPoolExecutor(max_workers=4) as ex:
        threaded = _scripted_list(ex)
        threaded.resolve_all_merges()
    assert sync.hash() == threaded.hash()
    for ls, lt in zip(sync.levels, threaded.levels):
        assert ls.curr.hash() == lt.curr.hash()
        assert ls.snap.hash() == lt.snap.hash()
        assert (ls.next is None) == (lt.next is None)
        if ls.next is not None:
            assert ls.next.resolve().hash() == lt.next.resolve().hash()


def test_pending_merge_commits_at_next_spill():
    """The merge prepared at a spill is invisible to the hash until the next
    spill commits it (reference: BucketLevel commit/prepare timing)."""
    bl = _scripted_list(None, n_ledgers=8)
    # level 1 got spills at ledgers 2,4,6,8 — a pending merge must exist
    assert bl.levels[1].next is not None
    pending = bl.levels[1].next.resolve()
    h_before = bl.hash()
    # committing early would change curr (and hence the level hash) — the
    # pipeline must NOT have done that yet
    assert bl.levels[1].curr.hash() != pending.hash() or \
        bl.levels[1].curr.is_empty() == pending.is_empty()
    bl.add_batch(9, PROTO, [_acct_entry(1, seq=9)], [], [])
    assert bl.hash() != h_before  # batch landed
    # at ledger 10 (spill of level 0) the pending merge commits into curr
    bl.add_batch(10, PROTO, [_acct_entry(2, seq=10)], [], [])
    assert bl.levels[1].next is not None  # a NEW merge was prepared


def test_bucket_index_find_and_filter():
    from stellar_core_tpu.bucket.index import BucketIndex
    b = Bucket.fresh(PROTO, [_acct_entry(i) for i in range(8)], [], [])
    idx = b.index()
    assert isinstance(idx, BucketIndex)
    for i in range(8):
        kb = _key(i).to_xdr()
        assert idx.maybe_contains(kb)
        pos = idx.find(kb)
        assert pos is not None and b.entries[pos].value.data.value.balance == 100
    absent = _key(99).to_xdr()
    assert idx.find(absent) is None


def test_searchable_snapshot_is_point_in_time():
    bl = _scripted_list(None, n_ledgers=12)
    snap = bl.snapshot(ledger_seq=12)
    k = _key(12 % 16).to_xdr()
    before = snap.load(k)
    assert before is not None and before.data.value.seqNum == 12
    # mutate the live list: delete that key
    bl.add_batch(13, PROTO, [], [], [_key(12 % 16)])
    assert bl.lookup_latest(k) is None          # live list sees the delete
    assert snap.load(k) is not None             # snapshot does not
    # batched load + scan agree
    got = snap.load_keys([k, _key(99).to_xdr()])
    assert set(got) == {k}
    assert any(e.data.value.seqNum == 12 for e in snap.scan()
               if e.data.value.accountID.value == bytes([12 % 16]) * 32)


def test_has_next_roundtrip_and_restart_hash_continuity(tmp_path):
    """A node restarted from HAS(+next) must produce the same bucket hashes
    as one that never restarted (reference: FutureBucket FB_HASH_OUTPUT
    rehydration via makeLive)."""
    from stellar_core_tpu.history.archive import HistoryArchiveState

    bl = _scripted_list(None, n_ledgers=24)
    has = HistoryArchiveState.from_bucket_list(24, "test", bl)
    rt = HistoryArchiveState.from_json(has.to_json())
    assert rt.next_states() == has.next_states()
    assert any(n is not None for n in has.next_states())
    assert set(has.all_bucket_hashes()) >= set(has.bucket_hashes())

    # reconstruct a second list from the snapshot and replay the same
    # subsequent batches on both — hashes must stay in lockstep
    by_hash = {b.hash().hex(): b for b in bl.buckets()}
    for lvl in bl.levels:
        if lvl.next is not None:
            out = lvl.next.resolve()
            by_hash[out.hash().hex()] = out
    bl2 = BucketList()
    for i, lh in enumerate(has.level_hashes):
        bl2.levels[i].curr = by_hash.get(lh["curr"], Bucket.empty())
        bl2.levels[i].snap = by_hash.get(lh["snap"], Bucket.empty())
        bl2.levels[i].next = rt.rehydrate_next(i, by_hash.get)
    assert bl2.hash() == bl.hash()
    for ledger in range(25, 41):
        batch = ([_acct_entry(ledger % 16, seq=ledger)], [], [])
        bl.add_batch(ledger, PROTO, *batch)
        bl2.add_batch(ledger, PROTO, *batch)
        assert bl2.hash() == bl.hash(), f"diverged at ledger {ledger}"


def test_has_state2_inputs_roundtrip_rehydrates_merge():
    """A HAS captured without resolving (per-close durable form) stores a
    running merge as inputs; rehydration re-runs the merge and later
    hashes stay in lockstep (reference: FB_HASH_INPUTS makeLive path)."""
    import concurrent.futures
    from stellar_core_tpu.history.archive import HistoryArchiveState

    with concurrent.futures.ThreadPoolExecutor(2) as ex:
        bl = _scripted_list(ex, n_ledgers=24)
        # capture WITHOUT resolve: some levels may serialize as state 2
        has = HistoryArchiveState.from_bucket_list(24, "t", bl,
                                                   resolve=False)
        rt = HistoryArchiveState.from_json(has.to_json())
        by_hash = {b.hash().hex(): b for b in bl.buckets()}
        for lvl in bl.levels:
            if lvl.next is not None and lvl.next.inputs is not None:
                ci, si, _, _ = lvl.next.inputs
                by_hash[ci.hash().hex()] = ci
                by_hash[si.hash().hex()] = si
                out = lvl.next.resolve()
                by_hash[out.hash().hex()] = out
        bl2 = BucketList()
        for i, lh in enumerate(rt.level_hashes):
            bl2.levels[i].curr = by_hash.get(lh["curr"], Bucket.empty())
            bl2.levels[i].snap = by_hash.get(lh["snap"], Bucket.empty())
            bl2.levels[i].next = rt.rehydrate_next(i, by_hash.get)
        assert bl2.hash() == bl.hash()
        for ledger in range(25, 41):
            batch = ([_acct_entry(ledger % 16, seq=ledger)], [], [])
            bl.add_batch(ledger, PROTO, *batch)
            bl2.add_batch(ledger, PROTO, *batch)
        bl.resolve_all_merges()
        bl2.resolve_all_merges()
        assert bl2.hash() == bl.hash()


def test_empty_pending_merge_output_rehydrates():
    """An annihilating merge yields the EMPTY bucket (hash 000...0); its
    serialized next must rehydrate as a real empty future, not be dropped
    (regression: catchup treated the zero hash as 'no pending merge')."""
    from stellar_core_tpu.history.archive import HistoryArchiveState
    from stellar_core_tpu.bucket.future import FutureBucket

    bl = BucketList()
    init = Bucket.fresh(PROTO, [_acct_entry(1)], [], [])
    dead = Bucket.fresh(PROTO, [], [], [_key(1)])
    bl.levels[3].next = FutureBucket(init, dead, True, PROTO)  # annihilates
    assert bl.levels[3].next.resolve().is_empty()
    has = HistoryArchiveState.from_bucket_list(1, "t", bl)
    nxt = has.next_states()[3]
    assert nxt == {"state": 1, "output": "0" * 64}
    fb = has.rehydrate_next(3, lambda h: None)  # source never consulted
    assert fb is not None and fb.resolve().is_empty()
