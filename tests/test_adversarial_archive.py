"""Adversarial-archive hardening (VERDICT r3 item 8): catchup must treat
archives as UNTRUSTED input — truncated XDR streams, hostile record
lengths, decompression bombs, lying HAS `next` records and malformed HAS
json all fail-stop with a localized CatchupError; never a hang, OOM or a
raw KeyError/ValueError escaping the work DAG.

Reference model: src/historywork/ — VerifyBucketWork / fail-stop
discipline (SURVEY §5.3)."""

import gzip
import json
import shutil
import struct

import pytest

from stellar_core_tpu.catchup.catchup import CatchupError, CatchupManager
from stellar_core_tpu.history.archive import (FileHistoryArchive,
                                              HistoryArchiveBase,
                                              bucket_path, category_path)
from stellar_core_tpu.history.manager import HistoryManager
from stellar_core_tpu.ledger.manager import LedgerManager
from stellar_core_tpu.simulation.loadgen import LoadGenerator
from stellar_core_tpu.testutils import network_id

PASSPHRASE = "adversarial archive net"
NID = network_id(PASSPHRASE)


@pytest.fixture(scope="module")
def published(tmp_path_factory):
    archive_dir = tmp_path_factory.mktemp("adv_archive")
    mgr = LedgerManager(NID)
    mgr.start_new_ledger()
    archive = FileHistoryArchive(str(archive_dir))
    history = HistoryManager(mgr, PASSPHRASE, [archive])
    gen = LoadGenerator(mgr, history, seed=7)
    gen.create_accounts(12, per_ledger=6)
    gen.payment_ledgers(8, txs_per_ledger=4)
    gen.run_to_checkpoint_boundary()
    assert history.published_checkpoints
    return archive


@pytest.fixture()
def evil(published, tmp_path):
    """A mutable copy of the published archive."""
    bad_dir = tmp_path / "evil"
    shutil.copytree(published.root, bad_dir)
    return FileHistoryArchive(str(bad_dir))


def _overwrite(archive, rel, raw):
    full = archive._full(rel)
    with open(full, "wb") as f:
        f.write(raw)


def _tx_rel(archive):
    return category_path("transactions", archive.get_state().current_ledger)


def test_control_unmutated_copy_replays(evil):
    """The mutable copy itself must replay clean — proves the failures in
    the tests below come from the mutations, not the fixture."""
    cm = CatchupManager(NID, PASSPHRASE)
    out = cm.catchup_complete(evil)
    assert out.last_closed_ledger_seq == evil.get_state().current_ledger
    node = cm.catchup_minimal(evil)
    assert node.lcl_hash == out.lcl_hash


def test_truncated_record_body_rejected(evil):
    raw = gzip.decompress(evil.get_bytes(_tx_rel(evil)))
    _overwrite(evil, _tx_rel(evil), gzip.compress(raw[:-3]))
    with pytest.raises(CatchupError):
        CatchupManager(NID, PASSPHRASE).catchup_complete(evil)


def test_truncated_record_mark_rejected(evil):
    raw = gzip.decompress(evil.get_bytes(_tx_rel(evil)))
    (mark,) = struct.unpack_from(">I", raw, 0)
    first = 4 + (mark & 0x7FFFFFFF)
    # keep record 1 whole, then 2 stray bytes of a next record mark
    _overwrite(evil, _tx_rel(evil), gzip.compress(raw[:first + 2]))
    with pytest.raises(CatchupError):
        CatchupManager(NID, PASSPHRASE).catchup_complete(evil)


def test_truncated_gzip_container_rejected(evil):
    """A .gz cut mid-stream decompresses without error via zlib but never
    reaches the trailer — it must NOT be accepted as a (shorter) valid
    stream that silently drops tail transactions."""
    raw = evil.get_bytes(_tx_rel(evil))
    _overwrite(evil, _tx_rel(evil), raw[:len(raw) - 5])
    with pytest.raises(CatchupError):
        CatchupManager(NID, PASSPHRASE).catchup_complete(evil)


def test_trailing_garbage_after_gzip_rejected(evil):
    raw = evil.get_bytes(_tx_rel(evil))
    _overwrite(evil, _tx_rel(evil), raw + b"EXTRA")
    with pytest.raises(CatchupError):
        CatchupManager(NID, PASSPHRASE).catchup_complete(evil)


def test_hostile_record_length_rejected(evil):
    # a record mark claiming a ~2 GB body: must reject via bounds check
    # (no allocation of the claimed size), not crash or hang
    raw = struct.pack(">I", 0x7FFFFFF0 | 0x80000000) + b"\x00" * 64
    _overwrite(evil, _tx_rel(evil), gzip.compress(raw))
    with pytest.raises(CatchupError):
        CatchupManager(NID, PASSPHRASE).catchup_complete(evil)


def test_decompression_bomb_rejected(evil, monkeypatch):
    # a 16 KB .gz that inflates to 4 MB against a 1 MB cap: parsing must
    # stay memory-bound and fail-stop
    monkeypatch.setattr(HistoryArchiveBase, "MAX_DECOMPRESSED_BYTES",
                        1024 * 1024)
    _overwrite(evil, _tx_rel(evil), gzip.compress(b"\x00" * (4 * 1024 * 1024)))
    with pytest.raises(CatchupError):
        CatchupManager(NID, PASSPHRASE).catchup_complete(evil)


def test_garbage_gzip_rejected(evil):
    _overwrite(evil, _tx_rel(evil), b"\x1f\x8b totally not gzip \xff\xff")
    with pytest.raises(CatchupError):
        CatchupManager(NID, PASSPHRASE).catchup_complete(evil)


def _rewrite_has(archive, mutate):
    """Load the well-known HAS json, apply `mutate(dict)`, write it back
    to BOTH copies (well-known + per-checkpoint)."""
    d = json.loads(archive.get_bytes(archive.WELL_KNOWN).decode())
    mutate(d)
    raw = json.dumps(d).encode()
    _overwrite(archive, archive.WELL_KNOWN, raw)
    _overwrite(archive, category_path("history", d["currentLedger"],
                                      suffix=".json"), raw)


@pytest.mark.parametrize("bad_next", [
    {"state": 3},                                     # unknown state
    {"state": 1},                                     # output missing
    {"state": 2, "curr": "00" * 32, "snap": "00" * 32,
     "keepTombstones": True, "outputProtocol": "zzz"},  # garbage protocol
    {"state": 1, "output": "ab" * 32},                # lies: bucket absent
])
def test_lying_has_next_rejected(evil, bad_next):
    _rewrite_has(evil, lambda d: d["currentBuckets"][0].update(
        {"next": bad_next}))
    with pytest.raises(CatchupError):
        CatchupManager(NID, PASSPHRASE).catchup_minimal(evil)


def test_malformed_has_json_rejected(evil):
    _overwrite(evil, evil.WELL_KNOWN, b'{"version": 1}')
    with pytest.raises(CatchupError):
        CatchupManager(NID, PASSPHRASE).catchup_minimal(evil)
    _overwrite(evil, evil.WELL_KNOWN, b"not json at all {{{")
    with pytest.raises(CatchupError):
        CatchupManager(NID, PASSPHRASE).catchup_minimal(evil)


def test_bucket_bomb_rejected(evil, monkeypatch):
    monkeypatch.setattr(HistoryArchiveBase, "MAX_DECOMPRESSED_BYTES",
                        1024 * 1024)
    has = evil.get_state()
    victim = next(h for h in has.bucket_hashes() if h != "0" * 64)
    _overwrite(evil, bucket_path(victim),
               gzip.compress(b"\x00" * (4 * 1024 * 1024)))
    with pytest.raises(CatchupError):
        CatchupManager(NID, PASSPHRASE).catchup_minimal(evil)
