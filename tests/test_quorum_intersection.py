"""Quorum intersection checker tests.

Reference test model: src/herder/test/QuorumIntersectionTests.cpp —
interior/exterior split cases, org-level (nested) configs, critical groups,
interruption.
"""

import pytest

from stellar_core_tpu.herder.quorum_intersection import (
    InterruptedError_, QuorumIntersectionChecker, check_intersection,
    intersection_critical_groups, flatten_qmap, tarjan_sccs)
from stellar_core_tpu.xdr import scp as SX
from stellar_core_tpu.xdr import types as XT


def nid(i: int) -> bytes:
    return bytes([i]) + bytes(31)


def qset(threshold, validators=(), inner=()):
    return SX.SCPQuorumSet(threshold=threshold,
                           validators=[XT.node_id(v) for v in validators],
                           innerSets=list(inner))


def flat_qmap(n, threshold, ids=None):
    ids = ids or [nid(i) for i in range(n)]
    return {v: qset(threshold, ids) for v in ids}


class TestTarjan:
    def test_single_cycle(self):
        # 0->1->2->0
        succs = [0b010, 0b100, 0b001]
        sccs = tarjan_sccs(succs, 3)
        assert sorted(s.bit_count() for s in sccs) == [3]

    def test_two_components_and_chain(self):
        # 0<->1, 2<->3, 1->2 (cross edge, no back edge)
        succs = [0b0010, 0b0101, 0b1000, 0b0100]
        sccs = tarjan_sccs(succs, 4)
        assert sorted(s.bit_count() for s in sccs) == [2, 2]
        assert {0b0011, 0b1100} == set(sccs)

    def test_self_only(self):
        sccs = tarjan_sccs([0b1], 1)
        assert sccs == [0b1]


class TestIntersection:
    def test_majority_intersects(self):
        res = check_intersection(flat_qmap(4, 3))
        assert res.intersects
        assert res.node_count == 4
        assert res.main_scc_size == 4

    def test_below_majority_splits_same_scc(self):
        # threshold 2 of 4: {0,1} and {2,3} are disjoint quorums in one SCC
        res = check_intersection(flat_qmap(4, 2))
        assert not res.intersects
        a, b = res.split
        assert set(a) & set(b) == set()
        ck = QuorumIntersectionChecker(flat_qmap(4, 2))
        mask_of = lambda names: sum(1 << ck.index[x] for x in names)
        assert ck.is_quorum(mask_of(a))
        assert ck.is_quorum(mask_of(b))

    def test_disjoint_sccs_split(self):
        ids_a = [nid(i) for i in range(3)]
        ids_b = [nid(10 + i) for i in range(3)]
        qmap = {v: qset(2, ids_a) for v in ids_a}
        qmap.update({v: qset(2, ids_b) for v in ids_b})
        res = check_intersection(qmap)
        assert not res.intersects
        a, b = res.split
        assert set(a) & set(b) == set()

    def test_single_node(self):
        v = nid(1)
        res = check_intersection({v: qset(1, [v])})
        assert res.intersects

    def test_no_quorum_vacuous(self):
        # Node requires a peer that has no qset (treated failed) => no quorum
        a, b = nid(1), nid(2)
        res = check_intersection({a: qset(2, [a, b]), b: None})
        assert res.intersects
        assert res.main_scc_size == 0

    def test_org_config_intersects(self):
        # 3 orgs x 3 validators, top 2-of-3 orgs, inner 2-of-3: safe
        orgs = [[nid(10 * o + i) for i in range(3)] for o in range(3)]
        top = lambda: qset(2, inner=[qset(2, org) for org in orgs])
        qmap = {v: top() for org in orgs for v in org}
        res = check_intersection(qmap)
        assert res.intersects

    def test_org_config_splits(self):
        # 4 orgs, top 2-of-4: org pair {0,1} vs {2,3} => split
        orgs = [[nid(10 * o + i) for i in range(3)] for o in range(4)]
        top = lambda: qset(2, inner=[qset(2, org) for org in orgs])
        qmap = {v: top() for org in orgs for v in org}
        res = check_intersection(qmap)
        assert not res.intersects

    def test_tier1_like_config_intersects(self):
        # 7 orgs x 3, top 5-of-7 (mirrors pubnet tier-1 shape)
        orgs = [[nid(10 * o + i) for i in range(3)] for o in range(7)]
        top = lambda: qset(5, inner=[qset(2, org) for org in orgs])
        qmap = {v: top() for org in orgs for v in org}
        res = check_intersection(qmap)
        assert res.intersects

    def test_asymmetric_dependency(self):
        # leaf nodes depend on a safe core but aren't depended on
        core = [nid(i) for i in range(4)]
        leaf = nid(9)
        qmap = flat_qmap(4, 3, core)
        qmap[leaf] = qset(3, core)
        res = check_intersection(qmap)
        assert res.intersects

    def test_interrupt(self):
        with pytest.raises(InterruptedError_):
            # interrupt immediately; 16-node t=8 search is big enough that
            # the poll counter (1024 calls) trips
            check_intersection(flat_qmap(16, 8), interrupt=lambda: True)


class TestMinimalQuorums:
    def test_contract_and_minimal(self):
        ck = QuorumIntersectionChecker(flat_qmap(4, 3))
        full = 0b1111
        assert ck.contract_to_max_quorum(full) == full
        assert ck.is_quorum(0b0111)
        assert ck.is_minimal_quorum(0b0111)
        assert not ck.is_minimal_quorum(0b1111)
        assert ck.contract_to_max_quorum(0b0011) == 0


class TestCriticalGroups:
    def test_critical_org(self):
        # 3 orgs, top 2-of-3: if one org turns arbitrary it can join both
        # halves of a split of the other two => every org is critical
        orgs = [[nid(10 * o + i) for i in range(3)] for o in range(3)]
        top = lambda: qset(2, inner=[qset(2, org) for org in orgs])
        qmap = {v: top() for org in orgs for v in org}
        crit = intersection_critical_groups(qmap, [set(o) for o in orgs])
        assert len(crit) == 3

    def test_non_critical(self):
        # threshold 3-of-3 orgs: a faulty org still can't split the
        # remaining 2-of-2 requirement... (2 orgs remain, both needed in
        # any quorum => intersection holds)
        orgs = [[nid(10 * o + i) for i in range(3)] for o in range(3)]
        top = lambda: qset(3, inner=[qset(2, org) for org in orgs])
        qmap = {v: top() for org in orgs for v in org}
        crit = intersection_critical_groups(qmap, [set(o) for o in orgs])
        assert crit == []


class TestFlatten:
    def test_flatten_org_map(self):
        orgs = [[nid(10 * o + i) for i in range(3)] for o in range(3)]
        top = lambda: qset(2, inner=[qset(2, org) for org in orgs])
        qmap = {v: top() for org in orgs for v in org}
        node_ids, tops, top_masks, ithrs, imasks = flatten_qmap(qmap)
        assert len(node_ids) == 9
        assert tops == [2] * 9
        assert top_masks == [0] * 9
        assert all(len(t) == 3 for t in ithrs)
        # each inner mask covers exactly 3 nodes
        assert all(m.bit_count() == 3 for masks in imasks for m in masks)

    def test_flatten_rejects_deep_nesting(self):
        a, b = nid(1), nid(2)
        deep = qset(1, inner=[qset(1, inner=[qset(1, [a])])])
        with pytest.raises(ValueError):
            flatten_qmap({a: deep, b: deep})


class TestSymmetricOrgContraction:
    """Tier-1-shaped maps contract to the org level (the exact enumerator
    is exponential in orgs; pubnet's real shape must answer in ms)."""

    def _tier1(self, n_orgs, per_org=3, outer=None, inner_thr=2):
        from stellar_core_tpu import xdr as X
        ids = [bytes([o + 1]) * 31 + bytes([v])
               for o in range(n_orgs) for v in range(per_org)]
        inner = [X.SCPQuorumSet(
            threshold=inner_thr,
            validators=[X.NodeID.ed25519(ids[o * per_org + v])
                        for v in range(per_org)],
            innerSets=[]) for o in range(n_orgs)]
        q = X.SCPQuorumSet(
            threshold=outer if outer else (2 * n_orgs + 2) // 3,
            validators=[], innerSets=inner)
        return {n: q for n in ids}, ids

    def test_tier1_scale_intersects_fast(self):
        import time
        for n in (9, 24):
            qmap, _ = self._tier1(n)
            t0 = time.perf_counter()
            res = check_intersection(qmap)
            assert res.intersects
            assert time.perf_counter() - t0 < 1.0

    def test_tier1_split_witness_is_real(self):
        qmap, _ = self._tier1(9, outer=3)
        res = check_intersection(qmap)
        assert not res.intersects
        a, b = res.split
        assert not (set(a) & set(b))
        # each side really is a quorum: contains >= 2 members of >= 3 orgs
        for side in (a, b):
            orgs_hit = {}
            for n in side:
                orgs_hit.setdefault(n[0], set()).add(n)
            assert sum(1 for v in orgs_hit.values() if len(v) >= 2) >= 3

    def test_weak_inner_threshold_falls_back_to_enumeration(self):
        # 1-of-3 orgs: two quorums sharing an org can pick disjoint
        # members, so contraction must NOT claim intersection
        qmap, _ = self._tier1(4, inner_thr=1, outer=3)
        res = check_intersection(qmap)
        assert not res.intersects

    def test_agrees_with_enumeration_at_small_scale(self):
        for n_orgs, outer, expect in ((3, 2, True), (4, 2, False),
                                      (4, 3, True), (5, 3, True)):
            qmap, _ = self._tier1(n_orgs, outer=outer)
            fast = check_intersection(qmap)
            slow = QuorumIntersectionChecker(qmap).check()
            assert fast.intersects == slow.intersects == expect, \
                (n_orgs, outer)


class TestNativeEnumeration:
    """native/cquorum.c (SURVEY §2.4 native checker) vs the pure-Python
    enumeration: verdict, split witness, max_quorums_found and
    main_scc_size must all be identical — the C core is a port of the
    same traversal, not merely verdict-equivalent."""

    def _both(self, qmap):
        from stellar_core_tpu.herder import quorum_intersection as QI
        if QI._cquorum is None:
            pytest.skip("native extension not built")
        a = QuorumIntersectionChecker(qmap)._check_python()
        b = QuorumIntersectionChecker(qmap)._check_native()
        assert a.intersects == b.intersects
        assert a.split == b.split
        assert a.max_quorums_found == b.max_quorums_found
        assert a.main_scc_size == b.main_scc_size
        return b

    @pytest.mark.parametrize("n,thr", [(4, 3), (4, 2), (5, 3), (6, 4),
                                       (6, 3), (7, 4)])
    def test_flat_maps(self, n, thr):
        self._both(flat_qmap(n, thr))

    def test_org_maps(self):
        orgs = [[nid(10 * o + i) for i in range(3)] for o in range(4)]
        for top in (3, 2):
            q = qset(top, inner=[qset(2, org) for org in orgs])
            self._both({v: q for org in orgs for v in org})

    def test_disjoint_sccs(self):
        a, b = [nid(i) for i in range(3)], [nid(10 + i) for i in range(3)]
        qmap = {**{v: qset(2, a) for v in a}, **{v: qset(2, b) for v in b}}
        self._both(qmap)

    def test_deep_nesting(self):
        # 3-level qsets: the TPU path rejects these; the native core must
        # recurse like the Python one
        ids = [nid(i) for i in range(6)]
        inner2 = qset(2, ids[3:6])
        inner1 = qset(2, ids[0:3], inner=[inner2])
        top = qset(2, [ids[0]], inner=[inner1])
        self._both({v: top for v in ids})

    def test_random_maps(self):
        import random
        rng = random.Random(1234)
        for trial in range(25):
            n = rng.randrange(3, 10)
            ids = [nid(i) for i in range(n)]
            qmap = {}
            for v in ids:
                peers = rng.sample(ids, rng.randrange(2, n + 1))
                if v not in peers:
                    peers.append(v)
                thr = rng.randrange(1, len(peers) + 1)
                qmap[v] = qset(thr, peers)
            self._both(qmap)

    def test_interrupt_native(self):
        from stellar_core_tpu.herder import quorum_intersection as QI
        if QI._cquorum is None:
            pytest.skip("native extension not built")
        with pytest.raises(InterruptedError_):
            QuorumIntersectionChecker(
                flat_qmap(16, 8), interrupt=lambda: True)._check_native()
