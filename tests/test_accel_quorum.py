"""Differential tests: TPU quorum-intersection enumerator vs CPU oracle.

Reference test model: src/herder/test/QuorumIntersectionTests.cpp, plus the
SURVEY.md §4 rule that TPU offloads are differentially tested against the
CPU path with identical verdicts.
"""

import random

import pytest

jax = pytest.importorskip("jax")

from stellar_core_tpu.accel.quorum import (TPUQuorumIntersectionChecker,
                                           check_intersection_tpu)
from stellar_core_tpu.herder.quorum_intersection import (
    InterruptedError_, check_intersection)
from stellar_core_tpu.xdr import scp as SX
from stellar_core_tpu.xdr import types as XT


def nid(i: int) -> bytes:
    return bytes([i & 0xFF, i >> 8]) + bytes(30)


def qset(threshold, validators=(), inner=()):
    return SX.SCPQuorumSet(threshold=threshold,
                           validators=[XT.node_id(v) for v in validators],
                           innerSets=list(inner))


def org_qmap(n_orgs, org_size, top_thr, inner_thr):
    orgs = [[nid(100 * o + i) for i in range(org_size)]
            for o in range(n_orgs)]
    top = lambda: qset(top_thr, inner=[qset(inner_thr, org) for org in orgs])
    return {v: top() for org in orgs for v in org}


class TestDifferential:
    @pytest.mark.parametrize("n,thr", [(4, 3), (4, 2), (5, 3), (6, 4),
                                       (6, 3), (7, 5), (8, 4)])
    def test_flat_maps(self, n, thr):
        ids = [nid(i) for i in range(n)]
        qmap = {v: qset(thr, ids) for v in ids}
        cpu = check_intersection(qmap)
        tpu = check_intersection_tpu(qmap)
        assert cpu.intersects == tpu.intersects, (n, thr)
        if not tpu.intersects:
            a, b = tpu.split
            assert set(a) & set(b) == set()

    @pytest.mark.parametrize("n_orgs,top", [(3, 2), (4, 2), (4, 3), (5, 3),
                                            (5, 4), (7, 5)])
    def test_org_maps(self, n_orgs, top):
        qmap = org_qmap(n_orgs, 3, top, 2)
        cpu = check_intersection(qmap)
        tpu = check_intersection_tpu(qmap)
        assert cpu.intersects == tpu.intersects, (n_orgs, top)

    def test_random_maps(self):
        rng = random.Random(42)
        for trial in range(12):
            n = rng.randrange(3, 9)
            ids = [nid(i) for i in range(n)]
            qmap = {}
            for v in ids:
                peers = rng.sample(ids, rng.randrange(2, n + 1))
                if v not in peers:
                    peers.append(v)
                thr = rng.randrange(1, len(peers) + 1)
                qmap[v] = qset(thr, peers)
            cpu = check_intersection(qmap)
            tpu = check_intersection_tpu(qmap)
            assert cpu.intersects == tpu.intersects, (trial, n)

    def test_split_witness_is_two_quorums(self):
        qmap = org_qmap(4, 3, 2, 2)  # 2-of-4 orgs: splits
        tpu = check_intersection_tpu(qmap)
        assert not tpu.intersects
        from stellar_core_tpu.herder.quorum_intersection import (
            QuorumIntersectionChecker)
        ck = QuorumIntersectionChecker(qmap)
        a, b = tpu.split
        mask = lambda names: sum(1 << ck.index[x] for x in names)
        assert ck.is_quorum(mask(a)) and ck.is_quorum(mask(b))
        assert mask(a) & mask(b) == 0


class TestMeshSharded:
    def test_sharded_matches(self):
        import numpy as np
        from jax.sharding import Mesh

        devs = jax.devices()
        if len(devs) < 8:
            pytest.skip("needs 8 virtual devices (see conftest)")
        mesh = Mesh(np.array(devs[:8]), axis_names=("data",))
        qmap = org_qmap(5, 3, 3, 2)
        plain = check_intersection_tpu(qmap)
        sharded = check_intersection_tpu(qmap, mesh=mesh, batch_size=64)
        assert plain.intersects == sharded.intersects == \
            check_intersection(qmap).intersects

    def test_sharded_split_case(self):
        import numpy as np
        from jax.sharding import Mesh

        devs = jax.devices()
        if len(devs) < 8:
            pytest.skip("needs 8 virtual devices (see conftest)")
        mesh = Mesh(np.array(devs[:8]), axis_names=("data",))
        qmap = org_qmap(4, 2, 2, 2)
        res = check_intersection_tpu(qmap, mesh=mesh, batch_size=64)
        assert not res.intersects


# contraction-proof exponential family — ONE definition shared with
# bench.py config 5 (stellar_core_tpu.testutils.asym_org_qmap)
from stellar_core_tpu.testutils import asym_org_qmap


class TestResidentFrontier:
    """The device-resident segmented path (SEG_DEPTHS per dispatch,
    on-device compaction, overflow ladders) vs the CPU oracle."""

    def test_asym_org_maps_match_oracle(self):
        for n_orgs in (3, 4):
            qmap = asym_org_qmap(n_orgs)
            cpu = check_intersection(qmap)
            tpu = check_intersection_tpu(qmap)
            assert cpu.intersects == tpu.intersects, n_orgs

    def test_tiny_buckets_force_overflow_ladders(self, monkeypatch):
        """Capacity buckets far below the real frontier exercise BOTH
        fallbacks: count*2 > top bucket (host-chunked depth before the
        segment) and in-segment overflow (freeze + host-chunked resume).
        Verdict must stay oracle-identical either way."""
        monkeypatch.setattr(TPUQuorumIntersectionChecker,
                            "CAPACITY_BUCKETS", (8, 16))
        for qmap in (org_qmap(5, 3, 3, 2),      # intersects
                     org_qmap(4, 3, 2, 2),      # splits
                     asym_org_qmap(4)):
            cpu = check_intersection(qmap)
            tpu = check_intersection_tpu(qmap)
            assert cpu.intersects == tpu.intersects

    def test_split_found_inside_segment(self, monkeypatch):
        """A split whose witness quorum is found mid-segment must surface
        through the q_rows buffer (not just via the chunked path)."""
        monkeypatch.setattr(TPUQuorumIntersectionChecker,
                            "CAPACITY_BUCKETS", (4096,))
        qmap = org_qmap(4, 3, 2, 2)
        tpu = check_intersection_tpu(qmap)
        assert not tpu.intersects
        a, b = tpu.split
        assert set(a) & set(b) == set()


class TestBigMap:
    def test_tier1_shape_21_nodes(self):
        # 7 orgs x 3 validators, 5-of-7 top: the pubnet tier-1 shape
        qmap = org_qmap(7, 3, 5, 2)
        res = check_intersection_tpu(qmap)
        assert res.intersects
        assert res.node_count == 21

    def test_interrupt(self):
        qmap = org_qmap(6, 3, 4, 2)
        with pytest.raises(InterruptedError_):
            check_intersection_tpu(qmap, interrupt=lambda: True)

    def test_deep_nesting_raises(self):
        a, b = nid(1), nid(2)
        deep = qset(1, inner=[qset(1, inner=[qset(1, [a])])])
        with pytest.raises(ValueError):
            TPUQuorumIntersectionChecker({a: deep, b: deep})
