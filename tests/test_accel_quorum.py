"""Differential tests: TPU quorum-intersection enumerator vs CPU oracle.

Reference test model: src/herder/test/QuorumIntersectionTests.cpp, plus the
SURVEY.md §4 rule that TPU offloads are differentially tested against the
CPU path with identical verdicts.
"""

import random

import pytest

jax = pytest.importorskip("jax")

from stellar_core_tpu.accel.quorum import (TPUQuorumIntersectionChecker,
                                           check_intersection_tpu)
from stellar_core_tpu.herder.quorum_intersection import (
    InterruptedError_, check_intersection)
from stellar_core_tpu.xdr import scp as SX
from stellar_core_tpu.xdr import types as XT


def nid(i: int) -> bytes:
    return bytes([i & 0xFF, i >> 8]) + bytes(30)


def qset(threshold, validators=(), inner=()):
    return SX.SCPQuorumSet(threshold=threshold,
                           validators=[XT.node_id(v) for v in validators],
                           innerSets=list(inner))


def org_qmap(n_orgs, org_size, top_thr, inner_thr):
    orgs = [[nid(100 * o + i) for i in range(org_size)]
            for o in range(n_orgs)]
    top = lambda: qset(top_thr, inner=[qset(inner_thr, org) for org in orgs])
    return {v: top() for org in orgs for v in org}


class TestDifferential:
    @pytest.mark.parametrize("n,thr", [(4, 3), (4, 2), (5, 3), (6, 4),
                                       (6, 3), (7, 5), (8, 4)])
    def test_flat_maps(self, n, thr):
        ids = [nid(i) for i in range(n)]
        qmap = {v: qset(thr, ids) for v in ids}
        cpu = check_intersection(qmap)
        tpu = check_intersection_tpu(qmap)
        assert cpu.intersects == tpu.intersects, (n, thr)
        if not tpu.intersects:
            a, b = tpu.split
            assert set(a) & set(b) == set()

    @pytest.mark.parametrize("n_orgs,top", [(3, 2), (4, 2), (4, 3), (5, 3),
                                            (5, 4), (7, 5)])
    def test_org_maps(self, n_orgs, top):
        qmap = org_qmap(n_orgs, 3, top, 2)
        cpu = check_intersection(qmap)
        tpu = check_intersection_tpu(qmap)
        assert cpu.intersects == tpu.intersects, (n_orgs, top)

    def test_random_maps(self):
        rng = random.Random(42)
        for trial in range(12):
            n = rng.randrange(3, 9)
            ids = [nid(i) for i in range(n)]
            qmap = {}
            for v in ids:
                peers = rng.sample(ids, rng.randrange(2, n + 1))
                if v not in peers:
                    peers.append(v)
                thr = rng.randrange(1, len(peers) + 1)
                qmap[v] = qset(thr, peers)
            cpu = check_intersection(qmap)
            tpu = check_intersection_tpu(qmap)
            assert cpu.intersects == tpu.intersects, (trial, n)

    def test_split_witness_is_two_quorums(self):
        qmap = org_qmap(4, 3, 2, 2)  # 2-of-4 orgs: splits
        tpu = check_intersection_tpu(qmap)
        assert not tpu.intersects
        from stellar_core_tpu.herder.quorum_intersection import (
            QuorumIntersectionChecker)
        ck = QuorumIntersectionChecker(qmap)
        a, b = tpu.split
        mask = lambda names: sum(1 << ck.index[x] for x in names)
        assert ck.is_quorum(mask(a)) and ck.is_quorum(mask(b))
        assert mask(a) & mask(b) == 0


class TestMeshSharded:
    def test_sharded_matches(self):
        import numpy as np
        from jax.sharding import Mesh

        devs = jax.devices()
        if len(devs) < 8:
            pytest.skip("needs 8 virtual devices (see conftest)")
        mesh = Mesh(np.array(devs[:8]), axis_names=("data",))
        qmap = org_qmap(5, 3, 3, 2)
        plain = check_intersection_tpu(qmap)
        sharded = check_intersection_tpu(qmap, mesh=mesh, batch_size=64)
        assert plain.intersects == sharded.intersects == \
            check_intersection(qmap).intersects

    def test_sharded_split_case(self):
        import numpy as np
        from jax.sharding import Mesh

        devs = jax.devices()
        if len(devs) < 8:
            pytest.skip("needs 8 virtual devices (see conftest)")
        mesh = Mesh(np.array(devs[:8]), axis_names=("data",))
        qmap = org_qmap(4, 2, 2, 2)
        res = check_intersection_tpu(qmap, mesh=mesh, batch_size=64)
        assert not res.intersects


# contraction-proof exponential family — ONE definition shared with
# bench.py config 5 (stellar_core_tpu.testutils.asym_org_qmap)
from stellar_core_tpu.testutils import asym_org_qmap


class TestResidentFrontier:
    """The device-resident segmented path (SEG_DEPTHS per dispatch,
    on-device compaction, overflow ladders) vs the CPU oracle."""

    def test_asym_org_maps_match_oracle(self):
        for n_orgs in (3, 4):
            qmap = asym_org_qmap(n_orgs)
            cpu = check_intersection(qmap)
            tpu = check_intersection_tpu(qmap)
            assert cpu.intersects == tpu.intersects, n_orgs

    def test_tiny_buckets_force_overflow_ladders(self, monkeypatch):
        """Capacity buckets far below the real frontier exercise BOTH
        fallbacks: count*2 > top bucket (host-chunked depth before the
        segment) and in-segment overflow (freeze + host-chunked resume).
        Verdict must stay oracle-identical either way."""
        monkeypatch.setattr(TPUQuorumIntersectionChecker,
                            "CAPACITY_BUCKETS", (8, 16))
        for qmap in (org_qmap(5, 3, 3, 2),      # intersects
                     org_qmap(4, 3, 2, 2),      # splits
                     asym_org_qmap(4)):
            cpu = check_intersection(qmap)
            tpu = check_intersection_tpu(qmap)
            assert cpu.intersects == tpu.intersects

    def test_split_found_inside_segment(self, monkeypatch):
        """A split whose witness quorum is found mid-segment must surface
        through the q_rows buffer (not just via the chunked path)."""
        monkeypatch.setattr(TPUQuorumIntersectionChecker,
                            "CAPACITY_BUCKETS", (4096,))
        qmap = org_qmap(4, 3, 2, 2)
        tpu = check_intersection_tpu(qmap)
        assert not tpu.intersects
        a, b = tpu.split
        assert set(a) & set(b) == set()

    @pytest.mark.parametrize("row_kind,branch", [
        ("zeros", "not a quorum"),            # corrupt transfer shape
        ("all_nodes", "complement has no quorum"),  # real quorum, bogus claim
    ])
    def test_corrupt_device_witness_fails_stop(self, monkeypatch, row_kind,
                                               branch):
        """A device fault that fabricates a witness row must raise, never
        report a 'proven' non-intersection: process_witness re-verifies
        BOTH sides on the exact CPU oracle (the threat model is the flaky
        tunneled chip corrupting rows or counts).  Two corruptions, one
        per oracle branch: an all-zero row (committed side not a quorum)
        and a genuine-quorum row whose split claim is bogus (complement
        side empty on an intersecting map)."""
        import numpy as np

        from stellar_core_tpu.accel import quorum as AQ

        qmap = org_qmap(5, 3, 3, 2)            # intersecting, 15 nodes
        fill = 0 if row_kind == "zeros" else (1 << 15) - 1
        real_step = AQ._segment_step

        def corrupted(*args, **kw):
            fr, meta, w_rows = real_step(*args, **kw)
            meta = np.asarray(meta).copy()
            meta[AQ.SEG_DEPTHS] = 1            # claim one witness, depth 0
            rows = np.full_like(np.asarray(w_rows), fill)
            return fr, meta, rows

        monkeypatch.setattr(AQ, "_segment_step", corrupted)
        with pytest.raises(RuntimeError, match=branch):
            check_intersection_tpu(qmap)


class TestBigMap:
    def test_tier1_shape_21_nodes(self):
        # 7 orgs x 3 validators, 5-of-7 top: the pubnet tier-1 shape
        qmap = org_qmap(7, 3, 5, 2)
        res = check_intersection_tpu(qmap)
        assert res.intersects
        assert res.node_count == 21

    def test_interrupt(self):
        qmap = org_qmap(6, 3, 4, 2)
        with pytest.raises(InterruptedError_):
            check_intersection_tpu(qmap, interrupt=lambda: True)

    def test_deep_nesting_raises(self):
        a, b = nid(1), nid(2)
        deep = qset(1, inner=[qset(1, inner=[qset(1, [a])])])
        with pytest.raises(ValueError):
            TPUQuorumIntersectionChecker({a: deep, b: deep})
