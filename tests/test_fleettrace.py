"""Fleet observability plane (ISSUE 16): cross-node trace collection,
clock-anchor alignment, the merged Chrome trace, the /tracespans
incremental export, /trace?slot filtering, and the fleet metrics
scraper (ring bound, SLO curves, divergence deltas).
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from stellar_core_tpu.main.config import Config
from stellar_core_tpu.util import metrics, tracing
from stellar_core_tpu.util.fleettrace import (ALIGN_PHASE,
                                              FleetScraper,
                                              FleetTraceCollector,
                                              merge_local_trace)


@pytest.fixture(autouse=True)
def _clean_buffers():
    metrics.reset_registry()
    tracing.trace_buffer().clear()
    tracing.mark_buffer().clear()
    yield
    tracing.trace_buffer().clear()
    tracing.mark_buffer().clear()


def _node_doc(node, slots, skew_s, base_wall=1_700_000_000.0,
              base_perf=50_000.0):
    """A synthetic /tracespans document for a node whose WALL clock is
    skewed by ``skew_s`` from true time.  Phase marks for each slot:
    externalize at true time base+5*slot, close-seal 30ms later."""
    anchor = {"perf_s": base_perf, "wall_s": base_wall + skew_s}
    marks = []
    seq = 0
    for slot in slots:
        for phase, off in ((ALIGN_PHASE, 0.0), ("close-seal", 0.030)):
            seq += 1
            true_s = 5.0 * slot + off
            marks.append({
                "seq": seq, "phase": phase, "slot": slot,
                # perf clock is per-node but drift-free: the anchor maps
                # it onto the node's (skewed) wall clock
                "perf_s": base_perf + true_s,
                "wall_s": base_wall + skew_s + true_s,
                "node": node, "tid": 1})
    return {"node": node, "anchor": anchor, "marks": marks,
            "spans": [], "next_since": seq}


class TestClockAlignment:
    def test_offsets_recover_injected_skew(self):
        """Nodes whose wall clocks disagree by seconds still merge onto
        one timebase: the externalize-mark median delta IS the skew."""
        coll = FleetTraceCollector()
        skews = {"node-0": 0.0, "node-1": 2.5, "node-2": -1.75}
        for node, skew in skews.items():
            coll.ingest(node, _node_doc(node, range(2, 12), skew))
        offsets = coll.align_offsets()
        # node-0 is the reference (first sorted): offset 0 by definition
        assert offsets["node-0"] == 0.0
        # a node whose clock reads AHEAD needs a negative correction
        assert offsets["node-1"] == pytest.approx(-2.5, abs=1e-6)
        assert offsets["node-2"] == pytest.approx(1.75, abs=1e-6)

    def test_aligned_marks_order_correctly_across_nodes(self):
        """After alignment, slot N's marks on every node sit together on
        the merged timeline even with multi-second wall skew — slot
        ordering survives, which is the property the merged trace
        exists to show."""
        coll = FleetTraceCollector()
        for node, skew in (("node-0", 0.0), ("node-1", 7.0)):
            coll.ingest(node, _node_doc(node, range(2, 8), skew))
        doc = coll.merge_chrome_trace()
        marks = [e for e in doc["traceEvents"] if e.get("cat") == "mark"]
        by_slot = {}
        for e in marks:
            by_slot.setdefault(e["args"]["slot"], []).append(e["ts"])
        slots = sorted(by_slot)
        for a, b in zip(slots, slots[1:]):
            # every mark of slot a precedes every mark of slot b (slots
            # are 5s apart; unaligned 7s skew would interleave them)
            assert max(by_slot[a]) < min(by_slot[b])

    def test_no_shared_slots_means_zero_offset(self):
        coll = FleetTraceCollector()
        coll.ingest("node-0", _node_doc("node-0", [2, 3], 0.0))
        coll.ingest("node-1", _node_doc("node-1", [50, 51], 3.0))
        assert coll.align_offsets()["node-1"] == 0.0


class TestMergedTrace:
    def test_one_process_row_per_node(self):
        """Node identity: each node gets its own pid with a process_name
        metadata row, and every one of its events carries that pid."""
        coll = FleetTraceCollector()
        for i in range(3):
            coll.ingest(f"node-{i}",
                        _node_doc(f"node-{i}", [2, 3], 0.1 * i))
        doc = coll.merge_chrome_trace()
        names = {e["args"]["name"]: e["pid"]
                 for e in doc["traceEvents"]
                 if e["ph"] == "M" and e["name"] == "process_name"}
        assert sorted(names) == ["node-0", "node-1", "node-2"]
        assert len(set(names.values())) == 3
        for e in doc["traceEvents"]:
            if e.get("cat") == "mark":
                assert names[e["args"]["node"]] == e["pid"]
        assert doc["metadata"]["nodes"] == ["node-0", "node-1", "node-2"]

    def test_slot_flow_arrows_span_nodes(self):
        """Each slot seen on >1 node gets a flow (s ... t ... f) chain
        whose endpoints live on different pids — the slot-spanning
        arrow in the rendered trace."""
        coll = FleetTraceCollector()
        for i in range(2):
            coll.ingest(f"node-{i}",
                        _node_doc(f"node-{i}", [2, 3, 4], 0.0))
        doc = coll.merge_chrome_trace()
        flows = [e for e in doc["traceEvents"]
                 if e.get("cat") == "slot-flow"]
        assert flows
        for slot in (2, 3, 4):
            chain = sorted((e for e in flows if e["id"] == slot),
                           key=lambda e: e["ts"])
            assert chain[0]["ph"] == "s"
            assert chain[-1]["ph"] == "f"
            assert chain[-1]["bp"] == "e"
            assert len({e["pid"] for e in chain}) == 2

    def test_collector_side_node_name_wins(self):
        """A node misconfigured with a duplicate self-reported id must
        not silently merge rows: the collector keys by ITS name."""
        coll = FleetTraceCollector()
        doc = _node_doc("liar", [2], 0.0)
        coll.ingest("node-0", doc)
        coll.ingest("node-1", _node_doc("liar", [2], 0.0))
        assert coll.nodes() == ["node-0", "node-1"]

    def test_incremental_since_watermark(self):
        coll = FleetTraceCollector()
        docs = {"n": _node_doc("n", [2, 3], 0.0)}
        calls = []

        def fetch(path):
            calls.append(path)
            return docs["n"]

        coll.poll("n", fetch)
        assert calls[-1] == "/tracespans?since=0"
        assert coll.since("n") == docs["n"]["next_since"]
        coll.poll("n", fetch)
        assert calls[-1] == f"/tracespans?since={docs['n']['next_since']}"

    def test_merge_local_trace_splits_in_process_nodes(self, tmp_path):
        """Chaos shape: ONE process, marks attributed to many nodes —
        the local merge splits them into per-node rows."""
        for i, node in enumerate(("alpha", "beta")):
            for slot in (2, 3):
                tracing.mark_phase("externalize", slot, node=node)
        path = tmp_path / "chaos-trace.json"
        n = merge_local_trace(str(path))
        assert n > 0
        doc = json.loads(path.read_text())
        rows = sorted(e["args"]["name"] for e in doc["traceEvents"]
                      if e["ph"] == "M" and e["name"] == "process_name")
        assert rows == ["alpha", "beta"]

    def test_merge_timer_metric_records(self):
        coll = FleetTraceCollector()
        coll.ingest("n", _node_doc("n", [2], 0.0))
        coll.merge_chrome_trace()
        snap = metrics.registry().snapshot()
        assert snap["fleet.trace.merge"]["count"] >= 1


class TestMarkPhase:
    def test_mark_ring_is_bounded(self):
        for i in range(tracing.MARK_BUFFER_MARKS + 100):
            tracing.mark_phase("externalize", i)
        assert len(tracing.mark_buffer().marks()) \
            == tracing.MARK_BUFFER_MARKS

    def test_mark_counter_and_node_attribution(self):
        from stellar_core_tpu.util import logging as slog
        slog.set_node_id("node-7")
        try:
            m = tracing.mark_phase("close-seal", 42, txs=3)
        finally:
            slog.set_node_id(None)
        assert m.node == "node-7"
        assert m.to_dict()["args"] == {"txs": 3}
        snap = metrics.registry().snapshot()
        assert snap["fleet.trace.marks"]["count"] >= 1

    def test_tracespans_doc_incremental(self):
        tracing.mark_phase("externalize", 2)
        doc1 = tracing.tracespans_doc(0)
        assert [m["slot"] for m in doc1["marks"]] == [2]
        assert doc1["anchor"]["perf_s"] <= time.perf_counter()
        doc2 = tracing.tracespans_doc(doc1["next_since"])
        assert doc2["marks"] == [] and doc2["spans"] == []
        tracing.mark_phase("close-seal", 3)
        doc3 = tracing.tracespans_doc(doc1["next_since"])
        assert [m["phase"] for m in doc3["marks"]] == ["close-seal"]


class TestFleetScraper:
    def _mk(self, snaps, ring=5, tracker=None):
        return FleetScraper(
            {name: (lambda q=q: q.pop(0) if q else (_ for _ in ())
                    .throw(RuntimeError("drained")))
             for name, q in snaps.items()},
            cadence_s=0.01, ring=ring, tracker=tracker)

    def test_ring_is_bounded(self):
        snaps = {"a": [{"m": {"value": i}} for i in range(12)]}
        sc = self._mk(snaps, ring=5)
        for _ in range(12):
            sc.sweep()
        assert len(sc.ring("a")) == 5
        assert sc.polls == 12

    def test_failed_fetch_counts_error_and_keeps_ring(self):
        snaps = {"a": [{"m": {"value": 1}}]}
        sc = self._mk(snaps)
        sc.sweep()   # ok
        sc.sweep()   # queue drained -> error
        assert sc.polls == 1 and sc.errors == 1
        assert len(sc.ring("a")) == 1
        snap = metrics.registry().snapshot()
        assert snap["fleet.scrape.errors"]["count"] == 1
        assert snap["fleet.scrape.polls"]["count"] == 1

    def test_divergence_delta(self):
        snaps = {
            "a": [{"ledger.ledger.close": {"p99_s": 0.10}}],
            "b": [{"ledger.ledger.close": {"p99_s": 0.45}}],
        }
        sc = self._mk(snaps)
        sc.sweep()
        d = sc.divergence("ledger.ledger.close", "p99_s")
        assert d["values"] == {"a": 0.10, "b": 0.45}
        assert d["delta"] == pytest.approx(0.35)

    def test_curves_time_series(self):
        snaps = {"a": [{"ledger.ledger.close": {"p99_s": v}}
                       for v in (0.1, 0.2, 0.3)]}
        sc = self._mk(snaps)
        for _ in range(3):
            sc.sweep()
        series = sc.curve("ledger.ledger.close", "p99_s")["a"]
        assert [v for _, v in series] == [0.1, 0.2, 0.3]
        ts = [t for t, _ in series]
        assert ts == sorted(ts)

    def test_background_thread_start_stop(self):
        snaps = {"a": [{"m": {"value": i}} for i in range(1000)]}
        sc = self._mk(snaps)
        sc.start()
        deadline = time.time() + 5.0
        while sc.polls == 0 and time.time() < deadline:
            time.sleep(0.01)
        sc.stop()
        assert sc.polls > 0
        assert not any(t.name == "fleet-scraper" and t.is_alive()
                       for t in threading.enumerate())

    def test_scraper_drives_slo_tracker(self):
        from stellar_core_tpu.util.slo import Objective, SLOTracker
        tracker = SLOTracker([Objective(
            "close-p99", "ledger.ledger.close", "p99_s",
            threshold=0.2, budget=0.25, window=8)], source="fleet")
        snaps = {"a": [{"ledger.ledger.close": {"p99_s": 0.9}}
                       for _ in range(6)]}
        sc = self._mk(snaps, tracker=tracker)
        for _ in range(6):
            sc.sweep()
        assert tracker.burning("close-p99")
        assert "slo" in sc.report()


class TestScraperRetention:
    """Satellite (ISSUE 20): rings for nodes absent beyond the
    retention window are evicted (memory bound against permanently-
    departed fleet members); a returning node starts fresh."""

    def _mk(self, snaps, retention_s, **kw):
        dead = kw.pop("dead", set())

        def fetcher(name):
            def fetch():
                if name in dead:
                    raise RuntimeError("down")
                return snaps[name]
            return fetch
        sc = FleetScraper({n: fetcher(n) for n in snaps},
                          cadence_s=0.01, retention_s=retention_s, **kw)
        return sc, dead

    @staticmethod
    def _advance(monkeypatch, by_s):
        import stellar_core_tpu.util.fleettrace as ft
        real = ft.monotonic_now
        monkeypatch.setattr(ft, "monotonic_now", lambda: real() + by_s)

    def test_absent_node_evicted_after_window(self, monkeypatch):
        snaps = {"a": {"ledger.ledger.close": {"p99_s": 0.1}},
                 "b": {"ledger.ledger.close": {"p99_s": 0.1}}}
        sc, dead = self._mk(snaps, retention_s=5.0)
        sc.sweep()
        assert sc.tracked_nodes() == ["a", "b"]
        dead.add("b")
        self._advance(monkeypatch, 10.0)
        sc.sweep()
        assert sc.tracked_nodes() == ["a"]
        assert sc.ring("b") == []
        assert sc.evicted == 1
        assert sc.report()["evicted"] == 1
        assert metrics.registry().snapshot()[
            "fleet.scrape.evicted"]["count"] == 1

    def test_absence_inside_window_keeps_history(self, monkeypatch):
        snaps = {"a": {"m": {"value": 1}}}
        sc, dead = self._mk(snaps, retention_s=60.0)
        sc.sweep()
        dead.add("a")
        self._advance(monkeypatch, 5.0)
        sc.sweep()  # error, but well inside the window
        assert sc.tracked_nodes() == ["a"]
        assert len(sc.ring("a")) == 1
        assert sc.evicted == 0

    def test_returning_node_rebuilds_fresh_ring(self, monkeypatch):
        snaps = {"a": {"m": {"value": 1}}}
        sc, dead = self._mk(snaps, retention_s=5.0)
        for _ in range(4):
            sc.sweep()
        dead.add("a")
        self._advance(monkeypatch, 10.0)
        sc.sweep()
        assert sc.tracked_nodes() == []
        dead.discard("a")
        sc.sweep()
        assert sc.tracked_nodes() == ["a"]
        assert len(sc.ring("a")) == 1  # fresh, not the old 4-deep ring

    def test_no_retention_means_no_eviction(self, monkeypatch):
        snaps = {"a": {"m": {"value": 1}}}
        sc, dead = self._mk(snaps, retention_s=None)
        sc.sweep()
        dead.add("a")
        self._advance(monkeypatch, 10_000.0)
        sc.sweep()
        assert sc.tracked_nodes() == ["a"]
        assert sc.evicted == 0


class TestScraperAnomalies:
    """Satellite (ISSUE 20): one AnomalyDetector per scraped node,
    gauge registration off, verdicts in the fleet report."""

    def test_per_node_verdicts_in_report(self):
        vals = {"a": 0.01, "b": 0.01}
        sc = FleetScraper(
            {n: (lambda n=n: {
                "ledger.ledger.close": {"p99_s": vals[n]}})
             for n in vals},
            cadence_s=0.01, anomaly=True)
        for _ in range(10):
            sc.sweep()   # healthy baseline for both nodes
        vals["b"] = 5.0  # node b regresses; node a stays healthy
        for _ in range(4):
            sc.sweep()
        rep = sc.report()
        assert rep["anomalies"]["b"]["series"]["close-p99"]["active"]
        assert not rep["anomalies"]["a"]["series"]["close-p99"]["active"]
        assert rep["anomalies"]["b"]["source"] == "b"

    def test_per_node_detectors_do_not_register_gauges(self):
        sc = FleetScraper(
            {"a": lambda: {"ledger.ledger.close": {"p99_s": 0.01}}},
            cadence_s=0.01, anomaly=True)
        sc.sweep()
        names = metrics.registry().names()
        assert "anomaly.active" not in names
        assert not any(n.startswith("anomaly.active.") for n in names)

    def test_eviction_drops_detector_state(self, monkeypatch):
        dead = set()

        def fetch():
            if "a" in dead:
                raise RuntimeError("down")
            return {"ledger.ledger.close": {"p99_s": 0.01}}
        sc = FleetScraper({"a": fetch}, cadence_s=0.01,
                          retention_s=5.0, anomaly=True)
        for _ in range(6):
            sc.sweep()
        assert sc.node_anomalies()["a"]["series"]["close-p99"]["samples"] > 0
        dead.add("a")
        import stellar_core_tpu.util.fleettrace as ft
        real = ft.monotonic_now
        monkeypatch.setattr(ft, "monotonic_now", lambda: real() + 10.0)
        sc.sweep()
        assert sc.node_anomalies() == {}
        dead.discard("a")
        sc.sweep()
        # fresh detector: baseline restarts from zero samples
        assert sc.node_anomalies()["a"]["series"]["close-p99"]["samples"] \
            <= 1


class TestEndpoints:
    """Round-trips through the live admin HTTP server (the app_http
    fixture shape from test_observability)."""

    @pytest.fixture()
    def app_http(self, tmp_path):
        from stellar_core_tpu.main.application import Application
        from stellar_core_tpu.main.http_admin import CommandHandler
        from stellar_core_tpu.util.clock import ClockMode, VirtualClock

        metrics.reset_registry()
        tracing.trace_buffer().clear()
        tracing.mark_buffer().clear()
        cfg = Config.from_dict({
            "NETWORK_PASSPHRASE": "fleettrace test net",
            "RUN_STANDALONE": True,
            "PEER_PORT": 0,
            "NODE_NAME": "node-t",
        })
        clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        app = Application(cfg, clock=clock, listen=False)
        http = CommandHandler(app, 0)
        http.start()
        app.start()
        assert clock.crank_until(
            lambda: app.lm.last_closed_ledger_seq >= 3, timeout=60)
        try:
            yield app, clock, http.port
        finally:
            http.stop()
            app.stop()
            from stellar_core_tpu.util import logging as slog
            slog.set_node_id(None)

    def _get(self, port, path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10.0) as r:
            return r.read(), r.status

    def test_tracespans_roundtrip_and_watermark(self, app_http):
        app, clock, port = app_http
        body, status = self._get(port, "/tracespans?since=0")
        doc = json.loads(body)
        assert status == 200
        assert doc["node"] == "node-t"
        assert {"perf_s", "wall_s"} <= set(doc["anchor"])
        # standalone close loop emitted lifecycle marks, node-stamped
        assert doc["marks"], "no phase marks from the close loop"
        assert all(m["node"] == "node-t" for m in doc["marks"])
        phases = {m["phase"] for m in doc["marks"]}
        assert "close-seal" in phases
        nxt = doc["next_since"]
        body2, _ = self._get(port, f"/tracespans?since={nxt}")
        assert json.loads(body2)["marks"] == []

    def test_tracespans_slot_filter(self, app_http):
        app, clock, port = app_http
        body, _ = self._get(port, "/tracespans?since=0&slot=2")
        doc = json.loads(body)
        assert doc["marks"]
        assert all(m["slot"] == 2 for m in doc["marks"])

    def test_trace_slot_filter(self, app_http):
        app, clock, port = app_http
        full = json.loads(self._get(port, "/trace")[0])["traceEvents"]
        one = json.loads(
            self._get(port, "/trace?slot=2")[0])["traceEvents"]
        assert len(one) < len(full)
        absent = json.loads(
            self._get(port, "/trace?slot=999999")[0])["traceEvents"]
        assert absent == []

    @pytest.mark.parametrize("path", [
        "/tracespans?since=bogus",
        "/tracespans?since=0&slot=bogus",
        "/trace?slot=notanint",
    ])
    def test_malformed_params_answer_400(self, app_http, path):
        app, clock, port = app_http
        with pytest.raises(urllib.error.HTTPError) as ei:
            self._get(port, path)
        assert ei.value.code == 400
        assert "error" in json.loads(ei.value.read())
