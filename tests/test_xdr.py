"""XDR codec + protocol-type tests.

Byte-exactness matters (ledger hashes hang off it — SURVEY.md §7 'XDR
fidelity'). Primitives are checked against hand-computed RFC 4506 wire bytes;
structures get round-trip + adversarial truncation/padding tests.
"""

import random
import struct

import pytest

from stellar_core_tpu.xdr import codec as C
from stellar_core_tpu import xdr as X


# --- primitives -----------------------------------------------------------

def test_int_packing():
    assert C.Int32.pack(-1) == b"\xff\xff\xff\xff"
    assert C.Uint32.pack(1) == b"\x00\x00\x00\x01"
    assert C.Int64.pack(-2) == b"\xff\xff\xff\xff\xff\xff\xff\xfe"
    assert C.Uint64.pack(2 ** 63) == b"\x80" + b"\x00" * 7
    assert C.Bool.pack(True) == b"\x00\x00\x00\x01"


def test_opaque_padding():
    assert C.Opaque(3).pack(b"abc") == b"abc\x00"
    assert C.Opaque(4).pack(b"abcd") == b"abcd"
    assert C.VarOpaque().pack(b"abcde") == b"\x00\x00\x00\x05abcde\x00\x00\x00"
    assert C.VarOpaque().unpack(b"\x00\x00\x00\x05abcde\x00\x00\x00") == b"abcde"


def test_nonzero_padding_rejected():
    with pytest.raises(C.XdrError):
        C.VarOpaque().unpack(b"\x00\x00\x00\x05abcdeXYZ")
    with pytest.raises(C.XdrError):
        C.Opaque(3).unpack(b"abcX")


def test_bool_strictness():
    with pytest.raises(C.XdrError):
        C.Bool.unpack(b"\x00\x00\x00\x02")


def test_trailing_bytes_rejected():
    with pytest.raises(C.XdrError):
        C.Uint32.unpack(b"\x00\x00\x00\x01\x00")


def test_truncation_rejected():
    with pytest.raises(C.XdrError):
        C.Uint64.unpack(b"\x00\x00")
    with pytest.raises(C.XdrError):
        C.VarOpaque().unpack(b"\x00\x00\x00\xff")


def test_var_array_limits():
    t = C.VarArray(C.Uint32, 2)
    assert t.pack([1, 2]) == b"\x00\x00\x00\x02\x00\x00\x00\x01\x00\x00\x00\x02"
    with pytest.raises(C.XdrError):
        t.pack([1, 2, 3])
    with pytest.raises(C.XdrError):
        t.unpack(b"\x00\x00\x00\x03" + b"\x00\x00\x00\x01" * 3)


def test_optional_wire_format():
    t = C.Optional(C.Uint32)
    assert t.pack(None) == b"\x00\x00\x00\x00"
    assert t.pack(7) == b"\x00\x00\x00\x01\x00\x00\x00\x07"


def test_string_utf8():
    assert C.XdrString(10).pack("hi") == b"\x00\x00\x00\x02hi\x00\x00"


# --- stellar types --------------------------------------------------------

def _acct(n: int):
    return X.AccountID.ed25519(bytes([n]) * 32)


def test_public_key_wire_bytes():
    # PublicKey union: discriminant 0 (ED25519) + 32 raw bytes
    pk = _acct(0xAB)
    assert pk.to_xdr() == b"\x00\x00\x00\x00" + b"\xab" * 32


def test_asset_wire_bytes():
    native = X.Asset.native()
    assert native.to_xdr() == b"\x00\x00\x00\x00"
    a4 = X.Asset.alphaNum4(X.AlphaNum4(assetCode=b"USD\x00", issuer=_acct(1)))
    assert a4.to_xdr() == (b"\x00\x00\x00\x01" + b"USD\x00"
                           + b"\x00\x00\x00\x00" + b"\x01" * 32)
    assert X.Asset.from_xdr(a4.to_xdr()) == a4


def test_account_entry_roundtrip_all_extensions():
    e = X.AccountEntry(
        accountID=_acct(5), balance=10_000_000, seqNum=(5 << 32) + 1,
        numSubEntries=2, inflationDest=_acct(6), flags=1,
        homeDomain=b"example.com", thresholds=b"\x01\x02\x03\x04",
        signers=[X.Signer(key=X.SignerKey.ed25519(b"\x09" * 32), weight=5)],
        ext=X.AccountEntryExt.v1(X.AccountEntryExtensionV1(
            liabilities=X.Liabilities(buying=1, selling=2),
            ext=X.AccountEntryExtensionV1Ext.v2(X.AccountEntryExtensionV2(
                numSponsored=1, numSponsoring=0,
                signerSponsoringIDs=[None],
                ext=X.AccountEntryExtensionV2Ext.v0())))),
    )
    assert X.AccountEntry.from_xdr(e.to_xdr()) == e


def test_ledger_entry_and_key_roundtrip():
    e = X.LedgerEntry(
        lastModifiedLedgerSeq=7,
        data=X.LedgerEntryData.account(X.AccountEntry(
            accountID=_acct(1), balance=5, seqNum=1)),
        ext=X.LedgerEntryExt.v0())
    data = e.to_xdr()
    assert X.LedgerEntry.from_xdr(data) == e
    k = X.ledger_entry_key(e)
    assert k.switch == X.LedgerEntryType.ACCOUNT
    assert X.LedgerKey.from_xdr(k.to_xdr()) == k


def test_trustline_and_offer_roundtrip():
    tl = X.TrustLineEntry(
        accountID=_acct(2),
        asset=X.TrustLineAsset.alphaNum4(
            X.AlphaNum4(assetCode=b"EUR\x00", issuer=_acct(3))),
        balance=42, limit=100, flags=1, ext=X.TrustLineEntryExt.v0())
    assert X.TrustLineEntry.from_xdr(tl.to_xdr()) == tl
    off = X.OfferEntry(
        sellerID=_acct(2), offerID=9, selling=X.Asset.native(),
        buying=X.Asset.alphaNum4(X.AlphaNum4(assetCode=b"EUR\x00", issuer=_acct(3))),
        amount=1000, price=X.Price(n=3, d=2), flags=0)
    assert X.OfferEntry.from_xdr(off.to_xdr()) == off


def test_claim_predicate_recursive():
    p = X.ClaimPredicate.andPredicates([
        X.ClaimPredicate.unconditional(),
        X.ClaimPredicate.notPredicate(X.ClaimPredicate.absBefore(12345)),
    ])
    assert X.ClaimPredicate.from_xdr(p.to_xdr()) == p


def test_transaction_envelope_roundtrip():
    op = X.Operation(body=X.OperationBody.paymentOp(X.PaymentOp(
        destination=X.MuxedAccount.ed25519(b"\x02" * 32),
        asset=X.Asset.native(), amount=123)))
    tx = X.Transaction(
        sourceAccount=X.MuxedAccount.ed25519(b"\x01" * 32),
        fee=100, seqNum=42, operations=[op])
    env = X.TransactionEnvelope.v1(X.TransactionV1Envelope(
        tx=tx, signatures=[X.DecoratedSignature(hint=b"\x01\x01\x01\x01",
                                                signature=b"\x05" * 64)]))
    data = env.to_xdr()
    assert X.TransactionEnvelope.from_xdr(data) == env
    # spot-check the head of the wire image: envelope type 2, muxed tag 0, src
    assert data[:8] == b"\x00\x00\x00\x02\x00\x00\x00\x00"
    assert data[8:40] == b"\x01" * 32
    assert struct.unpack(">I", data[40:44])[0] == 100  # fee


def test_transaction_wire_layout_manual():
    """Field-by-field manual encoding of a 1-op payment tx (cond=NONE,
    memo=NONE) must equal the codec output."""
    tx = X.Transaction(
        sourceAccount=X.MuxedAccount.ed25519(b"\xaa" * 32),
        fee=200, seqNum=7, operations=[
            X.Operation(body=X.OperationBody.createAccountOp(X.CreateAccountOp(
                destination=_acct(0xBB), startingBalance=5_0000000)))])
    manual = b"".join([
        b"\x00\x00\x00\x00",          # MuxedAccount tag KEY_TYPE_ED25519
        b"\xaa" * 32,                  # source ed25519
        struct.pack(">I", 200),        # fee
        struct.pack(">q", 7),          # seqNum
        b"\x00\x00\x00\x00",          # Preconditions: PRECOND_NONE
        b"\x00\x00\x00\x00",          # Memo: MEMO_NONE
        struct.pack(">I", 1),          # operations len
        b"\x00\x00\x00\x00",          # op.sourceAccount absent
        b"\x00\x00\x00\x00",          # OperationType CREATE_ACCOUNT
        b"\x00\x00\x00\x00", b"\xbb" * 32,  # destination AccountID
        struct.pack(">q", 5_0000000),  # startingBalance
        b"\x00\x00\x00\x00",          # tx ext v0
    ])
    assert tx.to_xdr() == manual


def test_ledger_header_roundtrip_and_size():
    h = X.LedgerHeader(
        ledgerVersion=23, previousLedgerHash=b"\x01" * 32,
        scpValue=X.StellarValue(txSetHash=b"\x02" * 32, closeTime=1234),
        txSetResultHash=b"\x03" * 32, bucketListHash=b"\x04" * 32,
        ledgerSeq=100, totalCoins=10 ** 15, feePool=500, inflationSeq=0,
        idPool=9, baseFee=100, baseReserve=5000000, maxTxSetSize=1000,
        skipList=[b"\x05" * 32] * 4)
    data = h.to_xdr()
    assert X.LedgerHeader.from_xdr(data) == h
    # fixed-shape header with basic scpValue: 4+32+(32+8+4+4)+32+32+4+8+8+4+8+4+4+4+128+4
    assert len(data) == 4 + 32 + 48 + 32 + 32 + 4 + 8 + 8 + 4 + 8 + 4 + 4 + 4 + 128 + 4


def test_scp_quorum_set_recursive_roundtrip():
    qs = X.SCPQuorumSet(
        threshold=2,
        validators=[X.NodeID.ed25519(bytes([i]) * 32) for i in range(3)],
        innerSets=[X.SCPQuorumSet(threshold=1,
                                  validators=[X.NodeID.ed25519(b"\x09" * 32)])])
    assert X.SCPQuorumSet.from_xdr(qs.to_xdr()) == qs


def test_scp_envelope_roundtrip():
    env = X.SCPEnvelope(
        statement=X.SCPStatement(
            nodeID=X.NodeID.ed25519(b"\x01" * 32), slotIndex=5,
            pledges=X.SCPStatementPledges.nominate(X.SCPNomination(
                quorumSetHash=b"\x02" * 32, votes=[b"v1"], accepted=[]))),
        signature=b"\x03" * 64)
    assert X.SCPEnvelope.from_xdr(env.to_xdr()) == env


def test_bucket_entry_roundtrip():
    live = X.BucketEntry.liveEntry(X.LedgerEntry(
        lastModifiedLedgerSeq=1,
        data=X.LedgerEntryData.account(X.AccountEntry(
            accountID=_acct(1), balance=1, seqNum=1))))
    assert X.BucketEntry.from_xdr(live.to_xdr()) == live
    meta = X.BucketEntry.metaEntry(X.BucketMetadata(ledgerVersion=23))
    # METAENTRY discriminant is -1 (signed!)
    assert meta.to_xdr()[:4] == b"\xff\xff\xff\xff"
    assert X.BucketEntry.from_xdr(meta.to_xdr()) == meta


def test_transaction_result_roundtrip():
    r = X.TransactionResult(
        feeCharged=100,
        result=X.TransactionResultResult.results(
            [X.OperationResult.tr(X.OperationResultTr.paymentResult(
                X.PaymentResult(X.PaymentResultCode.PAYMENT_SUCCESS)))]))
    assert X.TransactionResult.from_xdr(r.to_xdr()) == r


def test_history_entries_roundtrip():
    the = X.TransactionHistoryEntry(ledgerSeq=64, txSet=X.TransactionSet(
        previousLedgerHash=b"\x01" * 32, txs=[]))
    assert X.TransactionHistoryEntry.from_xdr(the.to_xdr()) == the


def test_unknown_enum_rejected():
    with pytest.raises(C.XdrError):
        X.Asset.from_xdr(b"\x00\x00\x00\x63")  # asset type 99


def test_fuzz_truncation_never_crashes():
    """Every strict prefix of a valid envelope must raise XdrError, never
    crash or succeed (mirrors the reference overlay fuzzer's invariant)."""
    op = X.Operation(body=X.OperationBody.manageDataOp(X.ManageDataOp(
        dataName=b"key", dataValue=b"value")))
    tx = X.Transaction(sourceAccount=X.MuxedAccount.ed25519(b"\x01" * 32),
                       fee=100, seqNum=1, operations=[op])
    env = X.TransactionEnvelope.v1(X.TransactionV1Envelope(tx=tx, signatures=[]))
    data = env.to_xdr()
    for cut in range(len(data)):
        with pytest.raises(C.XdrError):
            X.TransactionEnvelope.from_xdr(data[:cut])


def test_fuzz_random_bytes_never_crash():
    rng = random.Random(1234)
    for _ in range(200):
        blob = bytes(rng.randrange(256) for _ in range(rng.randrange(0, 200)))
        try:
            X.TransactionEnvelope.from_xdr(blob)
        except C.XdrError:
            pass  # rejection is the expected outcome


class TestContractXdr:
    """Stellar-contract.x types (reference: SCVal round-trips in xdrpp
    generated code + InvokeHostFunctionTests' envelope handling)."""

    def test_scval_all_arms_roundtrip(self):
        vals = [
            X.SCVal.b(True), X.SCVal.void(), X.SCVal.u32(7),
            X.SCVal.i32(-7), X.SCVal.u64(2**63), X.SCVal.i64(-5),
            X.SCVal.timepoint(1234), X.SCVal.duration(60),
            X.SCVal.u128(X.UInt128Parts(hi=1, lo=2)),
            X.SCVal.i128(X.Int128Parts(hi=-1, lo=2)),
            X.SCVal.u256(X.UInt256Parts(hi_hi=1, hi_lo=2, lo_hi=3, lo_lo=4)),
            X.SCVal.i256(X.Int256Parts(hi_hi=-1, hi_lo=2, lo_hi=3, lo_lo=4)),
            X.SCVal.bytes(b"\x01\x02"), X.SCVal.str(b"hello"),
            X.SCVal.sym(b"transfer"),
            X.SCVal.vec([X.SCVal.u32(1), X.SCVal.vec(None)]),
            X.SCVal.map([X.SCMapEntry(key=X.SCVal.sym(b"k"),
                                      val=X.SCVal.u32(1))]),
            X.SCVal.address(X.SCAddress.accountId(
                X.AccountID.ed25519(b"\x03" * 32))),
            X.SCVal.address(X.SCAddress.contractId(b"\x04" * 32)),
            X.SCVal.instance(X.SCContractInstance(
                executable=X.ContractExecutable.wasm_hash(b"\x05" * 32),
                storage=[X.SCMapEntry(key=X.SCVal.sym(b"s"),
                                      val=X.SCVal.void())])),
            X.SCVal.ledger_key_contract_instance(),
            X.SCVal.nonce_key(X.SCNonceKey(nonce=-9)),
            X.SCVal.error(X.SCError.contractCode(42)),
            X.SCVal.error(X.SCError(X.SCErrorType.SCE_WASM_VM)),
            X.SCVal.error(X.SCError(X.SCErrorType.SCE_VALUE,
                                    X.SCErrorCode.SCEC_INVALID_INPUT)),
        ]
        for v in vals:
            blob = v.to_xdr()
            assert X.SCVal.from_xdr(blob).to_xdr() == blob, v

    def test_scerror_void_arms(self):
        # Upstream Stellar-contract.x: SCE_WASM_VM..SCE_BUDGET are void;
        # only SCE_VALUE/SCE_AUTH carry an SCErrorCode, SCE_CONTRACT a u32.
        for t in (X.SCErrorType.SCE_WASM_VM, X.SCErrorType.SCE_CONTEXT,
                  X.SCErrorType.SCE_STORAGE, X.SCErrorType.SCE_OBJECT,
                  X.SCErrorType.SCE_CRYPTO, X.SCErrorType.SCE_EVENTS,
                  X.SCErrorType.SCE_BUDGET):
            e = X.SCError(t)
            blob = e.to_xdr()
            # void arm: exactly the 4-byte discriminant, nothing after
            assert blob == X.pack(X.SCErrorType, t), t
            assert X.SCError.from_xdr(blob).to_xdr() == blob
        for t in (X.SCErrorType.SCE_VALUE, X.SCErrorType.SCE_AUTH):
            e = X.SCError(t, X.SCErrorCode.SCEC_INTERNAL_ERROR)
            blob = e.to_xdr()
            assert len(blob) == 8, t
            assert X.SCError.from_xdr(blob).to_xdr() == blob

    def test_deeply_nested_scval(self):
        v = X.SCVal.u32(0)
        for _ in range(40):
            v = X.SCVal.vec([v])
        blob = v.to_xdr()
        assert X.SCVal.from_xdr(blob).to_xdr() == blob

    def test_invoke_host_function_envelope_roundtrip_and_malformed(self):
        from stellar_core_tpu.ledger.manager import LedgerManager
        from stellar_core_tpu.testutils import TestAccount, build_tx

        nid = b"\x21" * 32
        mgr = LedgerManager(nid)
        mgr.start_new_ledger()
        sk = mgr.root_account_secret()
        acc = mgr.root.get_entry(X.LedgerKey.account(X.LedgerKeyAccount(
            accountID=X.AccountID.ed25519(
                sk.public_key.ed25519))).to_xdr())
        root = TestAccount(mgr, sk, acc.data.value.seqNum)
        op = X.Operation(
            sourceAccount=None,
            body=X.OperationBody.invokeHostFunctionOp(X.InvokeHostFunctionOp(
                hostFunction=X.HostFunction.invokeContract(
                    X.InvokeContractArgs(
                        contractAddress=X.SCAddress.contractId(b"\x09" * 32),
                        functionName=b"hello",
                        args=[X.SCVal.sym(b"world")])))))
        frame = root.tx([op])
        blob = frame.envelope.to_xdr()
        assert X.TransactionEnvelope.from_xdr(blob).to_xdr() == blob
        # a Soroban op without sorobanData is malformed (the resource
        # declaration is mandatory); the ledger still closes and hashes
        arts = mgr.close_ledger([frame], close_time=1000)
        res = arts.result_entry.txResultSet.results[0].result
        assert res.result.switch == X.TransactionResultCode.txMALFORMED

    def test_contract_data_in_bucket_list(self):
        from stellar_core_tpu.bucket.bucket_list import BucketList
        entry = X.LedgerEntry(
            lastModifiedLedgerSeq=1,
            data=X.LedgerEntryData.contractData(X.ContractDataEntry(
                ext=X.ExtensionPoint.v0(),
                contract=X.SCAddress.contractId(b"\x0a" * 32),
                key=X.SCVal.sym(b"counter"),
                durability=X.ContractDataDurability.PERSISTENT,
                val=X.SCVal.u64(41))))
        bl = BucketList()
        bl.add_batch(1, 23, [entry], [], [])
        key = X.ledger_entry_key(entry)
        got = bl.lookup_latest(key.to_xdr())
        assert got is not None and got.data.value.val.value == 41
        # update then delete
        entry2 = entry.deep_copy()
        entry2.data.value.val = X.SCVal.u64(42)
        bl.add_batch(2, 23, [], [entry2], [])
        assert bl.lookup_latest(key.to_xdr()).data.value.val.value == 42
        bl.add_batch(3, 23, [], [], [key])
        assert bl.lookup_latest(key.to_xdr()) is None


class TestGeneralizedTxSetXdr:
    """Generalized tx sets + SorobanTransactionData: round-trip vectors and
    the native-serializer mutation differential (ISSUE 17)."""

    @staticmethod
    def _mgr_and_root():
        from stellar_core_tpu.ledger.manager import LedgerManager
        from stellar_core_tpu.testutils import TestAccount

        mgr = LedgerManager(b"\x22" * 32)
        mgr.start_new_ledger()
        sk = mgr.root_account_secret()
        acc = mgr.root.get_entry(X.LedgerKey.account(X.LedgerKeyAccount(
            accountID=X.AccountID.ed25519(
                sk.public_key.ed25519))).to_xdr())
        return mgr, TestAccount(mgr, sk, acc.data.value.seqNum)

    @staticmethod
    def _soroban_vectors():
        from stellar_core_tpu.soroban.storage import contract_data_key
        from stellar_core_tpu.testutils import contract_address
        dk = contract_data_key(contract_address(3), X.SCVal.sym("k"),
                               X.ContractDataDurability.TEMPORARY)
        ck = X.LedgerKey.contractCode(
            X.LedgerKeyContractCode(hash=b"\x44" * 32))
        yield X.SorobanTransactionData(
            ext=X.ExtensionPoint.v0(),
            resources=X.SorobanResources(
                footprint=X.LedgerFootprint(), instructions=0,
                readBytes=0, writeBytes=0),
            resourceFee=0)
        yield X.SorobanTransactionData(
            ext=X.ExtensionPoint.v0(),
            resources=X.SorobanResources(
                footprint=X.LedgerFootprint(readOnly=[ck], readWrite=[dk]),
                instructions=2**31 - 1, readBytes=200_000,
                writeBytes=128_000),
            resourceFee=2**62)

    def test_soroban_transaction_data_roundtrip(self):
        for sd in self._soroban_vectors():
            blob = sd.to_xdr()
            assert X.SorobanTransactionData.from_xdr(blob).to_xdr() == blob

    def test_soroban_envelope_ext_roundtrip(self):
        from stellar_core_tpu.soroban.storage import contract_data_key
        from stellar_core_tpu.testutils import (contract_address, invoke_op,
                                                make_soroban_data)
        mgr, root = self._mgr_and_root()
        c = contract_address(5)
        dk = contract_data_key(c, X.SCVal.sym("x"),
                               X.ContractDataDurability.PERSISTENT)
        sd = make_soroban_data(read_write=[dk])
        frame = root.tx([invoke_op(c, "put", [X.SCVal.sym("x"),
                                              X.SCVal.u64(1),
                                              X.SCVal.sym("persistent")])],
                        fee=1000 + sd.resourceFee, soroban_data=sd)
        blob = frame.envelope.to_xdr()
        env2 = X.TransactionEnvelope.from_xdr(blob)
        assert env2.to_xdr() == blob
        assert env2.value.tx.ext.switch == 1
        # compare on the wire: the codec canonicalizes str symbols to bytes
        assert env2.value.tx.ext.value.to_xdr() == sd.to_xdr()

    def test_generalized_tx_set_roundtrip_and_phases(self):
        from stellar_core_tpu.soroban import (build_generalized_tx_set,
                                              decode_tx_set, is_generalized,
                                              tx_set_envelopes,
                                              tx_set_phases)
        from stellar_core_tpu.soroban.storage import contract_data_key
        from stellar_core_tpu.testutils import (contract_address, invoke_op,
                                                make_soroban_data,
                                                native_payment_op)
        mgr, root = self._mgr_and_root()
        classic = root.tx([native_payment_op(root.account_id, 1)])
        c = contract_address(6)
        dk = contract_data_key(c, X.SCVal.sym("y"),
                               X.ContractDataDurability.PERSISTENT)
        sd = make_soroban_data(read_write=[dk])
        soroban = root.tx([invoke_op(c, "bump", [X.SCVal.sym("y"),
                                                 X.SCVal.u64(1),
                                                 X.SCVal.sym("persistent")])],
                          fee=1000 + sd.resourceFee, soroban_data=sd)
        gts, h = build_generalized_tx_set(mgr.lcl_hash, [classic], [soroban],
                                          soroban_base_fee=100)
        assert is_generalized(gts)
        blob = gts.to_xdr()
        dec = X.GeneralizedTransactionSet.from_xdr(blob)
        assert dec.to_xdr() == blob
        assert decode_tx_set(blob).to_xdr() == blob
        phases = tx_set_phases(dec)
        assert [len(p) for p in phases] == [1, 1]
        assert phases[0][0].to_xdr() == classic.envelope.to_xdr()
        assert phases[1][0].to_xdr() == soroban.envelope.to_xdr()
        assert len(tx_set_envelopes(dec)) == 2
        # legacy sets read through the same helpers unchanged
        legacy = X.TransactionSet(previousLedgerHash=mgr.lcl_hash,
                                  txs=[classic.envelope])
        assert tx_set_phases(legacy) == [[classic.envelope], []]
        assert decode_tx_set(legacy.to_xdr()).to_xdr() == legacy.to_xdr()

    def test_generalized_tx_set_native_mutation_differential(self):
        """Byte-mutated generalized-set blobs must be judged identically
        by the native cxdr decoder and the pure-Python one: both reject,
        or both accept with identical repacked bytes."""
        if C._cxdr is None:
            pytest.skip("native _cxdr not built (make native)")
        from stellar_core_tpu.fuzz import mutate_bytes
        from stellar_core_tpu.soroban import build_generalized_tx_set
        from stellar_core_tpu.testutils import native_payment_op
        mgr, root = self._mgr_and_root()
        frames = [root.tx([native_payment_op(root.account_id, n + 1)])
                  for n in range(3)]
        gts, _ = build_generalized_tx_set(mgr.lcl_hash, frames[:2],
                                          frames[2:])
        blob = gts.to_xdr()
        adapter = X.GeneralizedTransactionSet._xdr_adapter()
        rng = random.Random(1701)
        agree = 0
        for _ in range(200):
            mut = mutate_bytes(blob, rng)
            try:
                native_val = adapter.unpack(mut)
                native_ok = True
            except (C.XdrError, OverflowError):
                native_ok = False
            try:
                py_val, off = adapter.unpack_from(mut, 0)
                py_ok = off == len(mut)
            except (C.XdrError, OverflowError):
                py_ok = False
            assert native_ok == py_ok, mut.hex()
            if native_ok:
                assert adapter.pack(native_val) == adapter.pack(py_val)
                agree += 1
        # the corpus must exercise both accept and reject paths
        assert 0 < agree < 200
