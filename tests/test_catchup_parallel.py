"""Range-parallel catchup (ISSUE 10 tentpole): N concurrent checkpoint
ranges, each seeded by `catchup_minimal` assume-state at an interior
boundary and replayed with full verification, stitched by proving range
k's final ledger hash equals range k+1's seed header hash.

Covers: the plan (contiguous, boundary-seeded, balanced), the in-process
range body, real-subprocess orchestration (hash identity with the
single-stream replay + worker logs + metrics), the per-range
retry-with-backoff, and the fail-stop discipline — a tampered interior
range (corrupted bucket in the assumed HAS, or a forged stitch record)
must kill the whole catchup with a crash bundle naming the boundary and
leave the node's authoritative ledger dir untouched.
"""

import json
import os
import sys
import time

import pytest

from stellar_core_tpu.catchup.catchup import CatchupError, CatchupManager
from stellar_core_tpu.catchup.parallel import (ParallelCatchup, RangeSpec,
                                               RangeWork,
                                               plan_parallel_ranges,
                                               run_range, verify_stitches)
from stellar_core_tpu.history.archive import (CHECKPOINT_FREQUENCY,
                                              FileHistoryArchive,
                                              bucket_path, category_path)
from stellar_core_tpu.history.manager import HistoryManager
from stellar_core_tpu.ledger.manager import LedgerManager
from stellar_core_tpu.simulation.loadgen import LoadGenerator
from stellar_core_tpu.testutils import network_id
from stellar_core_tpu.util.clock import ClockMode, VirtualClock
from stellar_core_tpu.util.metrics import registry
from stellar_core_tpu.util.process import ProcessManager

PASSPHRASE = "parallel catchup test network"
NID = network_id(PASSPHRASE)


@pytest.fixture(scope="module")
def published(tmp_path_factory):
    """A 4-checkpoint archive with payment traffic in every checkpoint."""
    archive_dir = tmp_path_factory.mktemp("par-archive")
    mgr = LedgerManager(NID)
    mgr.start_new_ledger()
    archive = FileHistoryArchive(str(archive_dir))
    history = HistoryManager(mgr, PASSPHRASE, [archive])
    gen = LoadGenerator(mgr, history, seed=11)
    gen.create_accounts(12, per_ledger=6)
    gen.run_checkpoints(4, txs_per_ledger=2)
    assert len(history.published_checkpoints) >= 4
    return str(archive_dir), archive, mgr, history


# ---------------------------------------------------------------------------
# planning
# ---------------------------------------------------------------------------

def test_plan_single_worker_is_one_genesis_range():
    specs = plan_parallel_ranges(255, 1)
    assert specs == [RangeSpec(index=0, seed_checkpoint=None, replay_to=255)]


def test_plan_ranges_contiguous_and_boundary_seeded():
    specs = plan_parallel_ranges(1000, 4)
    assert len(specs) == 4
    assert specs[0].seed_checkpoint is None
    for a, b in zip(specs, specs[1:]):
        # every seam sits on the previous range's final checkpoint ledger
        assert b.seed_checkpoint == a.replay_to
        assert (b.seed_checkpoint + 1) % CHECKPOINT_FREQUENCY == 0
    assert specs[-1].replay_to == 1000
    # balanced to within one checkpoint
    sizes = [(s.replay_to - (s.seed_checkpoint or 0))
             // CHECKPOINT_FREQUENCY for s in specs[:-1]]
    assert max(sizes) - min(sizes) <= 1


def test_plan_more_workers_than_checkpoints_caps_ranges():
    # 130 → checkpoints 63, 127, 191: at most 3 ranges regardless of workers
    specs = plan_parallel_ranges(130, 16)
    assert len(specs) == 3
    assert [s.replay_to for s in specs] == [63, 127, 130]


def test_plan_tiny_target_degenerates():
    specs = plan_parallel_ranges(40, 8)
    assert specs == [RangeSpec(index=0, seed_checkpoint=None, replay_to=40)]
    with pytest.raises(CatchupError):
        plan_parallel_ranges(1, 2)
    with pytest.raises(CatchupError):
        plan_parallel_ranges(100, 0)


def test_plan_covers_every_ledger_once():
    specs = plan_parallel_ranges(700, 5)
    covered = []
    for s in specs:
        covered.extend(range(s.replay_from, s.replay_to + 1))
    assert covered == list(range(2, 701))


# ---------------------------------------------------------------------------
# the range body (in-process)
# ---------------------------------------------------------------------------

def test_run_range_interior_seed_hash_matches_archive(published, tmp_path):
    """Worker k's seed hash is the assumed checkpoint's header hash, and
    its replay reproduces the archive's own per-ledger hashes."""
    archive_dir, archive, mgr, history = published
    cps = history.published_checkpoints
    seed_cp, end_cp = cps[1], cps[2]
    spec = RangeSpec(index=1, seed_checkpoint=seed_cp, replay_to=end_cp)
    result = run_range(archive, spec, NID, PASSPHRASE,
                       bucket_dir=str(tmp_path / "bldb"))
    from stellar_core_tpu.catchup.catchup import _LHHE
    seed_tail = _LHHE.unpack(archive.get_xdr_file(
        category_path("ledger", seed_cp))[-1])
    end_tail = _LHHE.unpack(archive.get_xdr_file(
        category_path("ledger", end_cp))[-1])
    assert result["seed_header_hash"] == seed_tail.hash.hex()
    assert result["final_hash"] == end_tail.hash.hex()
    assert result["final_ledger_seq"] == end_cp
    assert result["ledgers_replayed"] == end_cp - seed_cp


def test_catchup_range_genesis_equals_complete(published):
    archive_dir, archive, mgr, history = published
    cm = CatchupManager(NID, PASSPHRASE)
    replayed, seed_hash = cm.catchup_range(
        archive, None, history.published_checkpoints[0])
    assert seed_hash is None
    assert replayed.last_closed_ledger_seq == \
        history.published_checkpoints[0]


# ---------------------------------------------------------------------------
# stitch proof
# ---------------------------------------------------------------------------

def _fake_results(n=3):
    out = []
    prev_hash = None
    for k in range(n):
        out.append({
            "index": k,
            "seed_checkpoint": None if k == 0 else 63 + 64 * (k - 1),
            "seed_header_hash": prev_hash,
            "replay_to": 63 + 64 * k,
            "final_ledger_seq": 63 + 64 * k,
            "final_hash": f"{k:064x}",
            "ledgers_replayed": 64,
        })
        prev_hash = f"{k:064x}"
    return out


def test_verify_stitches_counts_boundaries(tmp_path):
    before = registry().counter("catchup.parallel.stitch-verified").value
    assert verify_stitches(_fake_results(3)) == 2
    after = registry().counter("catchup.parallel.stitch-verified").value
    assert after - before == 2


def test_verify_stitches_hash_mismatch_failstops_with_bundle(tmp_path):
    results = _fake_results(3)
    results[2]["seed_header_hash"] = "f" * 64   # forged seed header
    crash_dir = tmp_path / "crash"
    with pytest.raises(CatchupError, match="boundary 127"):
        verify_stitches(results, crash_dir=str(crash_dir))
    bundles = list(crash_dir.glob("flight-*.json"))
    assert bundles, "stitch mismatch must write a crash bundle"
    doc = json.loads(bundles[0].read_text())
    assert "127" in doc["reason"] and "stitch" in doc["reason"]


def test_verify_stitches_seq_gap_failstops(tmp_path):
    results = _fake_results(3)
    results[1]["final_ledger_seq"] = 130        # not the next range's seed
    with pytest.raises(CatchupError, match="seeded"):
        verify_stitches(results)


# ---------------------------------------------------------------------------
# orchestration over real subprocess workers
# ---------------------------------------------------------------------------

def test_parallel_equals_single_stream(published, tmp_path):
    """THE acceptance invariant: N-range parallel catchup produces the
    bit-identical final ledger hash of the single-stream replay, with
    every boundary's stitch asserted, interior dirs GC'd and the last
    range's state adoptable."""
    archive_dir, archive, mgr, history = published
    single = CatchupManager(NID, PASSPHRASE).catchup_complete(archive)

    stitch_before = registry().counter(
        "catchup.parallel.stitch-verified").value
    pc = ParallelCatchup(archive_dir, PASSPHRASE, workers=3,
                         workdir=str(tmp_path / "work"))
    report = pc.run()
    assert report["final_hash"] == single.lcl_hash.hex() == mgr.lcl_hash.hex()
    assert report["stitches_verified"] == len(report["ranges"]) - 1 >= 1
    assert registry().counter("catchup.parallel.stitch-verified").value \
        - stitch_before == report["stitches_verified"]
    # per-range stitch records chain seed->final
    for a, b in zip(report["ranges"], report["ranges"][1:]):
        assert a["final_hash"] == b["seed_header_hash"]
    # interior throwaway dirs GC'd; the final (adopted) range dir survives
    dirs = sorted(os.listdir(tmp_path / "work"))
    assert dirs == [f"range-{len(report['ranges']) - 1:02d}"]
    # worker log captured through the ProcessManager output redirection
    log_path = (tmp_path / "work" / dirs[0] / "worker.log")
    assert log_path.exists() and log_path.stat().st_size > 0
    # adoption: the loaded manager IS the replayed ledger
    m2 = pc.load_manager()
    assert m2.lcl_hash == single.lcl_hash
    assert m2.root.entry_count() == single.root.entry_count()


def test_parallel_single_worker_degenerate(published, tmp_path):
    archive_dir, archive, mgr, history = published
    pc = ParallelCatchup(archive_dir, PASSPHRASE, workers=1,
                         workdir=str(tmp_path / "w1"))
    report = pc.run()
    assert report["final_hash"] == mgr.lcl_hash.hex()
    assert report["stitches_verified"] == 0


def test_worker_cli_writes_result(published, tmp_path):
    archive_dir, archive, mgr, history = published
    cps = history.published_checkpoints
    result_path = tmp_path / "result.json"
    r = __import__("subprocess").run(
        [sys.executable, "-m", "stellar_core_tpu", "catchup-range",
         "--archive", archive_dir, "--passphrase", PASSPHRASE,
         "--to", str(cps[1]), "--seed-checkpoint", str(cps[0]),
         "--workdir", str(tmp_path / "wd"), "--result", str(result_path),
         "--index", "1"],
        capture_output=True, timeout=120,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert r.returncode == 0, r.stderr[-1500:]
    doc = json.loads(result_path.read_text())
    assert doc["final_ledger_seq"] == cps[1]
    assert doc["seed_checkpoint"] == cps[0]
    assert len(doc["final_hash"]) == 64


def test_worker_cli_failure_writes_error_record(tmp_path):
    """A worker pointed at a dead archive exits non-zero AND leaves a JSON
    error record — the orchestrator's retry loop reads it for diagnosis."""
    result_path = tmp_path / "result.json"
    r = __import__("subprocess").run(
        [sys.executable, "-m", "stellar_core_tpu", "catchup-range",
         "--archive", str(tmp_path / "no-such-archive"),
         "--passphrase", PASSPHRASE, "--to", "127",
         "--seed-checkpoint", "63",
         "--workdir", str(tmp_path / "wd"), "--result", str(result_path)],
        capture_output=True, timeout=120,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert r.returncode == 1
    assert "error" in json.loads(result_path.read_text())


def test_range_work_retries_with_backoff(tmp_path):
    """A transiently failing worker retries through the Work framework's
    truncated-exponential backoff (the single-stream download's machinery)
    and succeeds once the fault clears."""
    marker = tmp_path / "attempted-once"
    result_path = tmp_path / "result.json"
    script = tmp_path / "flaky.py"
    script.write_text(
        "import json, os, sys\n"
        "marker, result = sys.argv[1], sys.argv[2]\n"
        "if not os.path.exists(marker):\n"
        "    open(marker, 'w').close()\n"
        "    sys.exit(7)   # first attempt: transient archive corruption\n"
        "json.dump({'index': 0, 'seed_checkpoint': None,\n"
        "           'seed_header_hash': None, 'replay_to': 63,\n"
        "           'final_ledger_seq': 63, 'final_hash': 'aa' * 32,\n"
        "           'ledgers_replayed': 62, 'ledgers_per_s': 100.0},\n"
        "          open(result, 'w'))\n")
    clock = VirtualClock(ClockMode.REAL_TIME)
    pm = ProcessManager(clock, max_concurrent=2)
    retry_before = registry().counter("catchup.parallel.range-retry").value
    # torn state from the "crashed" first attempt: the retry must start
    # from a pristine range dir (result_path lives OUTSIDE it here, so
    # the wipe provably targets the workdir, not just result.json)
    workdir = tmp_path / "range-00"
    workdir.mkdir()
    (workdir / "state.db").write_bytes(b"torn half-written db")
    w = RangeWork(clock, pm,
                  f"{sys.executable} {script} {marker} {result_path}",
                  str(result_path),
                  RangeSpec(index=0, seed_checkpoint=None, replay_to=63),
                  log_path=str(tmp_path / "w.log"),
                  workdir=str(workdir), max_retries=3)
    w.start()
    deadline = time.monotonic() + 60
    while not w.done and time.monotonic() < deadline:
        if clock.crank() == 0:
            time.sleep(0.01)
    pm.shutdown()
    assert w.succeeded
    assert w.retries == 1
    assert w.result["final_hash"] == "aa" * 32
    assert registry().counter("catchup.parallel.range-retry").value \
        - retry_before == 1
    # the torn first-attempt state was wiped before the retry ran
    assert not (workdir / "state.db").exists()


# ---------------------------------------------------------------------------
# fail-stop: tampered interior range (ISSUE 10 satellite)
# ---------------------------------------------------------------------------

def _copy_archive(src: str, dst: str) -> None:
    import shutil
    shutil.copytree(src, dst)


def test_tampered_interior_bucket_failstops_whole_catchup(published,
                                                          tmp_path):
    """Corrupt one bucket referenced by an interior seed checkpoint's HAS:
    that range's assume-state must fail (hash verification), retries must
    exhaust, the WHOLE parallel catchup must fail-stop with a crash
    bundle, and the node's authoritative ledger dir must stay untouched."""
    archive_dir, archive, mgr, history = published
    evil_dir = str(tmp_path / "evil-archive")
    _copy_archive(archive_dir, evil_dir)
    # the interior boundary range 1 seeds from
    seed_cp = plan_parallel_ranges(
        mgr.last_closed_ledger_seq, 3)[1].seed_checkpoint
    evil = FileHistoryArchive(evil_dir)
    has = evil.get_state(seed_cp)
    victim = next(h for h in has.bucket_hashes() if h != "0" * 64)
    victim_path = os.path.join(evil_dir, bucket_path(victim))
    with open(victim_path, "wb") as f:
        f.write(b"not a gzip bucket at all")

    # a pre-existing authoritative ledger dir that must survive the abort
    auth_db = tmp_path / "node" / "state.db"
    auth_db.parent.mkdir()
    auth_db.write_bytes(b"previous ledger state")

    crash_dir = tmp_path / "crash"
    pc = ParallelCatchup(evil_dir, PASSPHRASE, workers=3,
                         workdir=str(tmp_path / "work"),
                         max_retries=1, crash_dir=str(crash_dir))
    with pytest.raises(CatchupError, match="range 1"):
        pc.run()
    bundles = list(crash_dir.glob("flight-*.json"))
    assert bundles, "range failure must write a crash bundle"
    assert "range" in json.loads(bundles[0].read_text())["reason"]
    # adoption is unreachable after a fail-stop...
    with pytest.raises(CatchupError):
        pc.load_manager()
    with pytest.raises(CatchupError):
        pc.adopt_into(str(auth_db), str(tmp_path / "node" / "buckets"))
    # ...and the authoritative dir is bit-identical untouched
    assert auth_db.read_bytes() == b"previous ledger state"


def test_tampered_headers_break_range_not_others(published, tmp_path):
    """A corrupted ledger-header file inside one range's checkpoints kills
    the catchup (after retries) without poisoning other ranges' results."""
    archive_dir, archive, mgr, history = published
    evil_dir = str(tmp_path / "evil2")
    _copy_archive(archive_dir, evil_dir)
    specs = plan_parallel_ranges(mgr.last_closed_ledger_seq, 3)
    # corrupt the LAST range's first checkpoint ledger file
    cp = specs[2].seed_checkpoint + CHECKPOINT_FREQUENCY
    path = os.path.join(evil_dir, category_path("ledger", cp))
    with open(path, "wb") as f:
        f.write(b"\x1f\x8b garbage")
    pc = ParallelCatchup(evil_dir, PASSPHRASE, workers=3,
                         workdir=str(tmp_path / "work2"), max_retries=1)
    with pytest.raises(CatchupError, match="range 2"):
        pc.run()


def test_invariant_checks_reach_every_worker(published, tmp_path):
    """Configured INVARIANT_CHECKS must not be silently dropped by the
    parallel path: patterns travel to each worker's command line, and the
    worker builds a real InvariantManager (forcing the Python apply path,
    exactly like the single stream)."""
    archive_dir, archive, mgr, history = published
    pc = ParallelCatchup(archive_dir, PASSPHRASE, workers=3,
                         workdir=str(tmp_path / "w"),
                         invariant_checks=["ConservationOfLumens"])
    pc._specs = plan_parallel_ranges(mgr.last_closed_ledger_seq, 3)
    for spec in pc._specs:
        assert "--invariant ConservationOfLumens" in \
            pc._worker_cmdline(spec)
    # and the range body honors it end to end (in-process, one range)
    from stellar_core_tpu.invariant.invariants import InvariantManager
    inv = InvariantManager.from_patterns(["ConservationOfLumens"])
    spec = pc._specs[1]
    result = run_range(archive, spec, NID, PASSPHRASE,
                       invariant_manager=inv,
                       bucket_dir=str(tmp_path / "bldb"))
    assert result["final_ledger_seq"] == spec.replay_to


def test_config_workers_do_not_break_minimal_mode(published, tmp_path):
    """CATCHUP_PARALLEL_WORKERS in node.cfg must not reject --mode
    minimal / --count commands that were valid before the key existed —
    only an EXPLICIT --parallel > 1 conflicts with them."""
    archive_dir, archive, mgr, history = published
    conf = tmp_path / "node.cfg"
    conf.write_text(f'NETWORK_PASSPHRASE = "{PASSPHRASE}"\n'
                    'CATCHUP_PARALLEL_WORKERS = 4\n')
    from stellar_core_tpu.main.commandline import main as cli_main
    assert cli_main(["catchup", "--conf", str(conf),
                     "--archive", archive_dir, "--mode", "minimal"]) == 0
    assert cli_main(["catchup", "--conf", str(conf),
                     "--archive", archive_dir, "--mode", "minimal",
                     "--parallel", "2"]) == 1


def test_storage_knobs_reach_worker_cmdline(tmp_path):
    """IN_MEMORY_LEDGER / BUCKETLISTDB_ENTRY_CACHE_SIZE /
    BUCKET_RESIDENT_LEVELS travel to each worker — the node's memory
    bounds matter most when N workers share the box."""
    pc = ParallelCatchup(str(tmp_path / "a"), PASSPHRASE, workers=2,
                         workdir=str(tmp_path / "w"),
                         in_memory=True, entry_cache_size=123,
                         resident_levels=3)
    pc._specs = plan_parallel_ranges(255, 2)
    cmd = pc._worker_cmdline(pc._specs[1])
    assert "--in-memory" in cmd
    assert "--entry-cache-size 123" in cmd
    assert "--resident-levels 3" in cmd
