"""TransactionQueue unit tests.

Reference test model: src/herder/test/TransactionQueueTests.cpp —
replace-by-fee, bans, queue limits, surge-priced tx set building.
"""

import pytest

from stellar_core_tpu import xdr as X
from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.crypto.sha import sha256
from stellar_core_tpu.herder.tx_queue import (AddResult, BAN_DEPTH,
                                              FEE_MULTIPLIER,
                                              TransactionQueue)
from stellar_core_tpu.ledger.manager import LedgerManager
from stellar_core_tpu.testutils import TestAccount, create_account_op, \
    native_payment_op


@pytest.fixture
def env():
    lm = LedgerManager(sha256(b"txq test net"))
    lm.start_new_ledger()
    root_sk = lm.root_account_secret()
    root_entry = lm.root.get_entry(
        X.LedgerKey.account(X.LedgerKeyAccount(
            accountID=X.AccountID.ed25519(
                root_sk.public_key.ed25519))).to_xdr())
    root = TestAccount(lm, root_sk, root_entry.data.value.seqNum)
    # fund two accounts
    a_sk, b_sk = SecretKey(b"\x01" * 32), SecretKey(b"\x02" * 32)
    lm.close_ledger([root.tx([
        create_account_op(X.AccountID.ed25519(a_sk.public_key.ed25519),
                          100_000_000_000),
        create_account_op(X.AccountID.ed25519(b_sk.public_key.ed25519),
                          100_000_000_000)])], close_time=100)
    def acct(sk):
        e = lm.root.get_entry(X.LedgerKey.account(X.LedgerKeyAccount(
            accountID=X.AccountID.ed25519(sk.public_key.ed25519))).to_xdr())
        return TestAccount(lm, sk, e.data.value.seqNum)
    return lm, TransactionQueue(lm), acct(a_sk), acct(b_sk), root


def payment(frm, to, amount=1_000_000, fee=None, seq_bump=1):
    op = native_payment_op(X.AccountID.ed25519(to.secret.public_key.ed25519),
                           amount)
    return frm.tx([op], fee=fee) if fee else frm.tx([op])


class TestTryAdd:
    def test_pending_then_duplicate(self, env):
        lm, q, a, b, root = env
        f = payment(a, b)
        assert q.try_add(f).code == AddResult.STATUS_PENDING
        assert q.try_add(f).code == AddResult.STATUS_DUPLICATE
        assert q.size == 1

    def test_second_tx_same_account_needs_fee_bump(self, env):
        lm, q, a, b, root = env
        f1 = payment(a, b)
        assert q.try_add(f1).code == AddResult.STATUS_PENDING
        # same account, new seq, normal fee: rejected
        f2 = payment(a, b, amount=2_000_000)
        assert q.try_add(f2).code == AddResult.STATUS_TRY_AGAIN_LATER
        # with >=10x fee: replaces (same seq as f1)
        from stellar_core_tpu.testutils import build_tx
        f3 = build_tx(lm.network_id, a.secret, f1.seq_num,
                      [native_payment_op(
                          X.AccountID.ed25519(b.secret.public_key.ed25519),
                          3_000_000)],
                      fee=f1.fee_bid * FEE_MULTIPLIER)
        assert q.try_add(f3).code == AddResult.STATUS_PENDING
        assert q.size == 1
        assert f3.content_hash() in q.by_hash

    def test_invalid_tx_rejected(self, env):
        lm, q, a, b, root = env
        from stellar_core_tpu.testutils import build_tx
        f = build_tx(lm.network_id, a.secret, a.seq_num + 1000,
                     [native_payment_op(
                         X.AccountID.ed25519(b.secret.public_key.ed25519),
                         1)])  # bad seq
        res = q.try_add(f)
        assert res.code == AddResult.STATUS_ERROR

    def test_banned_rejected(self, env):
        lm, q, a, b, root = env
        f = payment(a, b)
        q.ban([f])
        assert q.try_add(f).code == AddResult.STATUS_BANNED
        # bans age out after BAN_DEPTH shifts
        for _ in range(BAN_DEPTH):
            q.shift()
        assert q.try_add(f).code == AddResult.STATUS_PENDING


class TestLedgerInteraction:
    def test_remove_applied_drops_stale(self, env):
        lm, q, a, b, root = env
        f = payment(a, b)
        assert q.try_add(f).code == AddResult.STATUS_PENDING
        q.remove_applied([f])
        assert q.size == 0

    def test_tx_set_surge_pricing_order(self, env):
        lm, q, a, b, root = env
        fa = payment(a, b, fee=200)
        fb = payment(b, a, fee=5000)
        assert q.try_add(fa).code == AddResult.STATUS_PENDING
        assert q.try_add(fb).code == AddResult.STATUS_PENDING
        frames = q.tx_set_frames()
        assert frames[0] is fb  # higher fee-per-op first
        # trim to 1 op: only the best survives
        assert q.tx_set_frames(max_ops=1) == [fb]


class TestExactFeeRate:
    def test_fee_per_op_is_exact_rational(self, env):
        lm, q, a, b, root = env
        from fractions import Fraction
        from stellar_core_tpu.herder.tx_queue import fee_per_op, surge_sort_key
        op = lambda: native_payment_op(
            X.AccountID.ed25519(a.secret.public_key.ed25519), 1)
        hi = b.tx([op()] * 2, fee=101)          # 50.5 per op
        lo = b.tx([op()] * 4, fee=201)          # 50.25 per op
        assert fee_per_op(hi) == Fraction(101, 2)
        assert isinstance(fee_per_op(hi), Fraction)
        assert sorted([lo, hi], key=surge_sort_key)[0] is hi

    def test_equal_fee_rate_tiebreak_is_hash(self, env):
        lm, q, a, b, root = env
        from stellar_core_tpu.herder.tx_queue import fee_per_op, surge_sort_key
        op = lambda: native_payment_op(
            X.AccountID.ed25519(a.secret.public_key.ed25519), 1)
        f1 = b.tx([op()], fee=100)
        f2 = b.tx([op()] * 2, fee=200)          # exactly equal rate
        assert fee_per_op(f1) == fee_per_op(f2)
        first = sorted([f1, f2], key=surge_sort_key)[0]
        assert first is min((f1, f2), key=lambda f: f.content_hash())


class TestEvictionIndex:
    def test_equal_key_duplicate_entries_never_compare_frames(self, env):
        """Regression (PR 8 review): a dropped tx leaves a stale heap
        entry; re-adding the identical envelope pushes an entry with an
        EQUAL (fee, hash) key, and without the monotonic push counter
        the heap sift would fall through to TransactionFrame comparison
        (TypeError) on the overload hot path."""
        lm, q, a, b, root = env
        f = payment(a, b)
        assert q.try_add(f).code == AddResult.STATUS_PENDING
        q.remove_applied([f])            # stale heap entry stays (lazy)
        assert q.size == 0
        assert q.try_add(f).code == AddResult.STATUS_PENDING
        # victim query must skip the stale twin and answer, not raise
        assert q._eviction_victim() is f
        assert len(q._evict_heap) >= 2   # the stale entry really is there

    def test_victim_matches_exhaustive_scan_under_churn(self, env):
        """The lazy-deletion heap must agree with the O(n) max() scan it
        replaced, through adds, drops, bans and replace-by-fee churn."""
        from stellar_core_tpu.herder.tx_queue import eviction_key
        lm, q, a, b, root = env
        sks = [SecretKey(bytes([10 + i]) * 32) for i in range(6)]
        lm.close_ledger([root.tx([create_account_op(
            X.AccountID.ed25519(sk.public_key.ed25519), 100_000_000_000)
            for sk in sks])],
            close_time=lm.lcl_header.scpValue.closeTime + 5)
        accts = []
        for sk in sks:
            e = lm.root.get_entry(X.LedgerKey.account(X.LedgerKeyAccount(
                accountID=X.AccountID.ed25519(
                    sk.public_key.ed25519))).to_xdr())
            accts.append(TestAccount(lm, sk, e.data.value.seqNum))
        frames = [payment(acct, b, fee=100 * (1 + i % 4))
                  for i, acct in enumerate(accts)]
        for f in frames:
            assert q.try_add(f).code == AddResult.STATUS_PENDING
        q.ban([frames[1]])
        q.remove_applied([frames[4]])
        expected = max(q.by_hash.values(), key=eviction_key)
        assert q._eviction_victim() is expected
