"""Crypto layer tests. Mirrors reference src/crypto/test/CryptoTests.cpp coverage:
sign/verify round-trips, StrKey encode/decode + corruption rejection, SHA256
vectors, SipHash vectors, verify cache behavior."""

import hashlib
import random

import pytest

from stellar_core_tpu.crypto import keys, sha, sodium, strkey


def test_sodium_available():
    assert sodium.available(), "system libsodium should load via ctypes"


def test_sign_verify_roundtrip():
    sk = keys.SecretKey(b"\x01" * 32)
    msg = b"hello stellar"
    sig = sk.sign(msg)
    assert len(sig) == 64
    assert keys.verify_sig(sk.public_key, sig, msg)
    assert not keys.verify_sig(sk.public_key, sig, msg + b"!")
    bad = bytearray(sig)
    bad[0] ^= 1
    assert not keys.verify_sig(sk.public_key, bytes(bad), msg)


def test_keypair_deterministic_from_seed():
    a = keys.SecretKey(b"\x42" * 32)
    b = keys.SecretKey(b"\x42" * 32)
    assert a.public_key == b.public_key
    assert a.sign(b"m") == b.sign(b"m")


def test_verify_cache_hit_and_seed():
    keys.clear_verify_cache()
    sk = keys.SecretKey(b"\x07" * 32)
    msg = b"cached"
    sig = sk.sign(msg)
    assert keys.verify_sig(sk.public_key, sig, msg)
    # seeding a wrong verdict must be respected (proves cache consult order)
    keys.seed_verify_cache([(sk.public_key.ed25519, sig, msg, False)])
    assert not keys.verify_sig(sk.public_key, sig, msg)
    keys.clear_verify_cache()
    assert keys.verify_sig(sk.public_key, sig, msg)


def test_strkey_roundtrip_public_seed():
    raw = bytes(range(32))
    g = strkey.encode_public_key(raw)
    assert g.startswith("G")
    assert strkey.decode_public_key(g) == raw
    s = strkey.encode_seed(raw)
    assert s.startswith("S")
    assert strkey.decode_seed(s) == raw


def test_strkey_known_vector():
    # SDF network root key vector (publicly documented strkey example):
    # GBRPYHIL2CI3FNQ4BXLFMNDLFJUNPU2HY3ZMFSHONUCEOASW7QC7OX2H decodes and
    # round-trips; checksum/corruption must be rejected.
    g = "GBRPYHIL2CI3FNQ4BXLFMNDLFJUNPU2HY3ZMFSHONUCEOASW7QC7OX2H"
    raw = strkey.decode_public_key(g)
    assert strkey.encode_public_key(raw) == g
    corrupted = g[:-1] + ("A" if g[-1] != "A" else "B")
    with pytest.raises(ValueError):
        strkey.decode_public_key(corrupted)


def test_strkey_rejects_wrong_version():
    raw = b"\x00" * 32
    s = strkey.encode_seed(raw)
    with pytest.raises(ValueError):
        strkey.decode_public_key(s)


def test_strkey_rejects_lowercase_and_garbage():
    with pytest.raises(ValueError):
        strkey.decode_any("gbad")
    with pytest.raises(ValueError):
        strkey.decode_any("!!!!")
    with pytest.raises(ValueError):
        strkey.decode_any("")


def test_crc16_xmodem_vector():
    assert strkey.crc16_xmodem(b"123456789") == 0x31C3


def test_sha256_vectors():
    assert sha.sha256(b"") == bytes.fromhex(
        "e3b0c44298fc1c149afbf4c8996fb92427ae41e4649b934ca495991b7852b855")
    assert sha.sha256(b"abc") == hashlib.sha256(b"abc").digest()
    h = sha.SHA256().add(b"a").add(b"bc").finish()
    assert h == hashlib.sha256(b"abc").digest()


def test_siphash24_reference_vector():
    # Official SipHash-2-4 test vector: key 000102..0f, msg 00..3e
    key = bytes(range(16))
    vectors_first = 0x726FDB47DD0E0E31  # siphash24 of b"" per reference impl
    assert sha.siphash24(key, b"") == vectors_first
    assert sha.siphash24(key, bytes(range(1))) == 0x74F839C593DC67FD


def test_hmac_sha256():
    # RFC 4231 test case 2
    mac = sha.hmac_sha256(b"Jefe", b"what do ya want for nothing?")
    assert mac == bytes.fromhex(
        "5bdcc146bf60754e6a042426089575c75a003f089d2739839dec58b964ec3843")
    assert sha.hmac_sha256_verify(b"Jefe", b"what do ya want for nothing?", mac)


def test_curve25519_ecdh_agreement():
    if not sodium.available():
        pytest.skip("no libsodium")
    a_sk = bytes(random.Random(1).randrange(256) for _ in range(32))
    b_sk = bytes(random.Random(2).randrange(256) for _ in range(32))
    a_pk = sodium.scalarmult_curve25519_base(a_sk)
    b_pk = sodium.scalarmult_curve25519_base(b_sk)
    assert sodium.scalarmult_curve25519(a_sk, b_pk) == \
        sodium.scalarmult_curve25519(b_sk, a_pk)


def test_public_key_hint():
    pk = keys.PublicKey(bytes(range(32)))
    assert pk.hint() == bytes([28, 29, 30, 31])
