"""Transaction apply engine tests.

Mirrors reference coverage in src/transactions/test/{PaymentTests,
ChangeTrustTests, AllowTrustTests, SetOptionsTests, ManageDataTests,
BumpSequenceTests, MergeTests, ClaimableBalanceTests}.cpp at the current
protocol, driven through LedgerManager.close_ledger (full close pipeline,
not op calls in isolation).
"""

import pytest

from stellar_core_tpu import xdr as X
from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.ledger.manager import LedgerManager
from stellar_core_tpu.testutils import (TestAccount, build_tx,
                                        create_account_op, native_payment_op,
                                        network_id)

NID = network_id("tpu-core test network")


@pytest.fixture
def mgr():
    m = LedgerManager(NID)
    m.start_new_ledger()
    return m


@pytest.fixture
def root(mgr):
    sk = mgr.root_account_secret()
    acc = mgr.root.get_entry(
        X.LedgerKey.account(X.LedgerKeyAccount(
            accountID=X.AccountID.ed25519(sk.public_key.ed25519))).to_xdr())
    return TestAccount(mgr, sk, acc.data.value.seqNum)


def _close(mgr, *frames, close_time=1000):
    arts = mgr.close_ledger(list(frames), close_time)
    return arts


def _result_of(arts, frame):
    for pair in arts.result_entry.txResultSet.results:
        if pair.transactionHash == frame.content_hash():
            return pair.result
    raise AssertionError("tx not in result set")


def _acc(mgr, account_id: X.AccountID):
    e = mgr.root.get_entry(X.LedgerKey.account(
        X.LedgerKeyAccount(accountID=account_id)).to_xdr())
    return e.data.value if e else None


def _new_account(mgr, root, balance=10_000_000_000):
    sk = SecretKey.pseudo_random_for_testing(__import__("random").Random(
        mgr.last_closed_ledger_seq * 7919 + balance % 104729))
    tx = root.tx([create_account_op(
        X.AccountID.ed25519(sk.public_key.ed25519), balance)])
    arts = _close(mgr, tx)
    res = _result_of(arts, tx)
    assert res.result.switch == X.TransactionResultCode.txSUCCESS, res
    acc = _acc(mgr, X.AccountID.ed25519(sk.public_key.ed25519))
    return TestAccount(mgr, sk, acc.seqNum)


def test_genesis_state(mgr):
    assert mgr.last_closed_ledger_seq == 1
    assert mgr.lcl_header.totalCoins == 100_000_000_000 * 10_000_000
    assert mgr.root.entry_count() == 1
    assert mgr.lcl_header.bucketListHash == mgr.bucket_list.hash()


def test_create_account_and_payment(mgr, root):
    a = _new_account(mgr, root)
    b = _new_account(mgr, root)
    a0 = _acc(mgr, a.account_id).balance
    b0 = _acc(mgr, b.account_id).balance
    pay = a.tx([native_payment_op(b.account_id, 1_000_000)])
    arts = _close(mgr, pay)
    assert _result_of(arts, pay).result.switch == X.TransactionResultCode.txSUCCESS
    assert _acc(mgr, b.account_id).balance == b0 + 1_000_000
    assert _acc(mgr, a.account_id).balance == a0 - 1_000_000 - 100  # amount+fee
    assert _result_of(arts, pay).feeCharged == 100


def test_payment_to_missing_account_fails_fee_charged(mgr, root):
    a = _new_account(mgr, root)
    ghost = SecretKey(b"\x42" * 32)
    a0 = _acc(mgr, a.account_id).balance
    pay = a.tx([native_payment_op(
        X.AccountID.ed25519(ghost.public_key.ed25519), 5)])
    arts = _close(mgr, pay)
    res = _result_of(arts, pay)
    assert res.result.switch == X.TransactionResultCode.txFAILED
    op_res = res.result.value[0]
    assert op_res.value.value.switch == X.PaymentResultCode.PAYMENT_NO_DESTINATION
    # fee charged, amount not moved
    assert _acc(mgr, a.account_id).balance == a0 - 100


def test_underfunded_payment(mgr, root):
    a = _new_account(mgr, root, balance=10_000_000_000)
    b = _new_account(mgr, root)
    pay = a.tx([native_payment_op(b.account_id, 10_000_000_000)])
    arts = _close(mgr, pay)
    res = _result_of(arts, pay)
    assert res.result.switch == X.TransactionResultCode.txFAILED
    assert res.result.value[0].value.value.switch == \
        X.PaymentResultCode.PAYMENT_UNDERFUNDED


def test_bad_seq_rejected(mgr, root):
    a = _new_account(mgr, root)
    tx = build_tx(NID, a.secret, a.seq_num + 5,
                  [native_payment_op(root.account_id, 1)])
    arts = _close(mgr, tx)
    res = _result_of(arts, tx)
    assert res.result.switch == X.TransactionResultCode.txBAD_SEQ


def test_bad_signature_rejected(mgr, root):
    a = _new_account(mgr, root)
    wrong = SecretKey(b"\x07" * 32)
    tx = build_tx(NID, a.secret, a.seq_num + 1,
                  [native_payment_op(root.account_id, 1)])
    # replace signature with one from the wrong key
    tx.envelope.value.signatures[:] = [X.DecoratedSignature(
        hint=wrong.public_key.hint(),
        signature=wrong.sign(tx.content_hash()))]
    a.seq_num += 1
    arts = _close(mgr, tx)
    res = _result_of(arts, tx)
    assert res.result.switch == X.TransactionResultCode.txBAD_AUTH


def test_extra_unused_signature_rejected(mgr, root):
    a = _new_account(mgr, root)
    other = SecretKey(b"\x09" * 32)
    tx = a.tx([native_payment_op(root.account_id, 1)],
              extra_signers=[other])
    arts = _close(mgr, tx)
    res = _result_of(arts, tx)
    assert res.result.switch == X.TransactionResultCode.txBAD_AUTH_EXTRA


def test_seq_consumed_on_failed_tx(mgr, root):
    a = _new_account(mgr, root)
    bad = a.tx([native_payment_op(root.account_id, 10 ** 17)])  # underfunded
    _close(mgr, bad)
    assert _acc(mgr, a.account_id).seqNum == a.seq_num
    ok = a.tx([native_payment_op(root.account_id, 1)])
    arts = _close(mgr, ok)
    assert _result_of(arts, ok).result.switch == X.TransactionResultCode.txSUCCESS


def test_manage_data_create_update_delete(mgr, root):
    a = _new_account(mgr, root)

    def md(name, value):
        return X.Operation(body=X.OperationBody.manageDataOp(
            X.ManageDataOp(dataName=name, dataValue=value)))

    arts = _close(mgr, a.tx([md(b"k1", b"v1")]))
    key = X.LedgerKey.data(X.LedgerKeyData(accountID=a.account_id,
                                           dataName=b"k1"))
    assert mgr.root.get_entry(key.to_xdr()).data.value.dataValue == b"v1"
    assert _acc(mgr, a.account_id).numSubEntries == 1
    _close(mgr, a.tx([md(b"k1", b"v2")]))
    assert mgr.root.get_entry(key.to_xdr()).data.value.dataValue == b"v2"
    _close(mgr, a.tx([md(b"k1", None)]))
    assert mgr.root.get_entry(key.to_xdr()) is None
    assert _acc(mgr, a.account_id).numSubEntries == 0


def test_bump_sequence(mgr, root):
    a = _new_account(mgr, root)
    target = a.seq_num + 1000
    tx = a.tx([X.Operation(body=X.OperationBody.bumpSequenceOp(
        X.BumpSequenceOp(bumpTo=target)))])
    _close(mgr, tx)
    assert _acc(mgr, a.account_id).seqNum == target
    a.seq_num = target


def test_set_options_thresholds_and_multisig(mgr, root):
    a = _new_account(mgr, root)
    b = SecretKey(b"\x21" * 32)
    setop = X.Operation(body=X.OperationBody.setOptionsOp(X.SetOptionsOp(
        signer=X.Signer(key=X.SignerKey.ed25519(b.public_key.ed25519),
                        weight=1),
        medThreshold=2)))
    _close(mgr, a.tx([setop]))
    acc = _acc(mgr, a.account_id)
    assert acc.thresholds[2] == 2 and len(acc.signers) == 1
    # payment now needs both signatures (med threshold 2)
    only_master = a.tx([native_payment_op(root.account_id, 1)])
    arts = _close(mgr, only_master)
    assert _result_of(arts, only_master).result.switch == \
        X.TransactionResultCode.txFAILED  # opBAD_AUTH inside
    both = a.tx([native_payment_op(root.account_id, 1)], extra_signers=[b])
    arts = _close(mgr, both)
    assert _result_of(arts, both).result.switch == \
        X.TransactionResultCode.txSUCCESS


def test_trustline_flow(mgr, root):
    issuer = _new_account(mgr, root)
    holder = _new_account(mgr, root)
    usd = X.Asset.alphaNum4(X.AlphaNum4(assetCode=b"USD\x00",
                                        issuer=issuer.account_id))
    trust = holder.tx([X.Operation(body=X.OperationBody.changeTrustOp(
        X.ChangeTrustOp(line=X.ChangeTrustAsset.alphaNum4(usd.value),
                        limit=10 ** 12)))])
    arts = _close(mgr, trust)
    assert _result_of(arts, trust).result.switch == \
        X.TransactionResultCode.txSUCCESS
    pay = issuer.tx([X.Operation(body=X.OperationBody.paymentOp(X.PaymentOp(
        destination=X.muxed_from_account_id(holder.account_id),
        asset=usd, amount=500)))])
    arts = _close(mgr, pay)
    assert _result_of(arts, pay).result.switch == \
        X.TransactionResultCode.txSUCCESS
    tlk = X.LedgerKey.trustLine(X.LedgerKeyTrustLine(
        accountID=holder.account_id,
        asset=X.TrustLineAsset.alphaNum4(usd.value)))
    assert mgr.root.get_entry(tlk.to_xdr()).data.value.balance == 500
    # pay back to issuer burns
    back = holder.tx([X.Operation(body=X.OperationBody.paymentOp(X.PaymentOp(
        destination=X.muxed_from_account_id(issuer.account_id),
        asset=usd, amount=200)))])
    arts = _close(mgr, back)
    assert _result_of(arts, back).result.switch == \
        X.TransactionResultCode.txSUCCESS
    assert mgr.root.get_entry(tlk.to_xdr()).data.value.balance == 300


def test_account_merge(mgr, root):
    a = _new_account(mgr, root)
    b = _new_account(mgr, root)
    a_bal = _acc(mgr, a.account_id).balance
    b_bal = _acc(mgr, b.account_id).balance
    merge = a.tx([X.Operation(body=X.OperationBody(
        X.OperationType.ACCOUNT_MERGE,
        X.muxed_from_account_id(b.account_id)))])
    arts = _close(mgr, merge)
    res = _result_of(arts, merge)
    assert res.result.switch == X.TransactionResultCode.txSUCCESS
    assert _acc(mgr, a.account_id) is None
    assert _acc(mgr, b.account_id).balance == b_bal + a_bal - 100


def test_claimable_balance_roundtrip(mgr, root):
    a = _new_account(mgr, root)
    b = _new_account(mgr, root)
    create = a.tx([X.Operation(body=X.OperationBody.createClaimableBalanceOp(
        X.CreateClaimableBalanceOp(
            asset=X.Asset.native(), amount=5_000_000,
            claimants=[X.Claimant.v0(X.ClaimantV0(
                destination=b.account_id,
                predicate=X.ClaimPredicate.unconditional()))])))])
    arts = _close(mgr, create)
    res = _result_of(arts, create)
    assert res.result.switch == X.TransactionResultCode.txSUCCESS
    bid = res.result.value[0].value.value.value
    b0 = _acc(mgr, b.account_id).balance
    claim = b.tx([X.Operation(body=X.OperationBody.claimClaimableBalanceOp(
        X.ClaimClaimableBalanceOp(balanceID=bid)))])
    arts = _close(mgr, claim)
    assert _result_of(arts, claim).result.switch == \
        X.TransactionResultCode.txSUCCESS
    assert _acc(mgr, b.account_id).balance == b0 + 5_000_000 - 100


def test_ledger_hash_chain_and_determinism(root, mgr):
    """Replaying identical inputs gives identical ledger hashes (the core
    catchup invariant)."""
    a = _new_account(mgr, root)
    h1 = mgr.lcl_hash
    assert mgr.lcl_header.previousLedgerHash != h1

    # rebuild a fresh chain with the same inputs
    mgr2 = LedgerManager(NID)
    mgr2.start_new_ledger()
    sk = mgr2.root_account_secret()
    acc = mgr2.root.get_entry(X.LedgerKey.account(X.LedgerKeyAccount(
        accountID=X.AccountID.ed25519(sk.public_key.ed25519))).to_xdr())
    root2 = TestAccount(mgr2, sk, acc.data.value.seqNum)
    tx = root2.tx([create_account_op(a.account_id,
                                     10_000_000_000)])
    mgr2.close_ledger([tx], 1000)
    assert mgr2.lcl_hash == h1


def test_fee_bump(mgr, root):
    a = _new_account(mgr, root)
    sponsor = _new_account(mgr, root)
    inner = a.tx([native_payment_op(root.account_id, 1)], fee=100)
    fb = X.FeeBumpTransaction(
        feeSource=X.muxed_from_account_id(sponsor.account_id),
        fee=400,
        innerTx=X.FeeBumpInnerTx.v1(inner.envelope.value),
        ext=X.FeeBumpTransaction._spec[3][1].cls(0))
    env = X.TransactionEnvelope.feeBump(
        X.FeeBumpTransactionEnvelope(tx=fb, signatures=[]))
    from stellar_core_tpu.transactions.frame import FeeBumpTransactionFrame
    frame = FeeBumpTransactionFrame(NID, env)
    env.value.signatures.append(X.DecoratedSignature(
        hint=sponsor.secret.public_key.hint(),
        signature=sponsor.secret.sign(frame.content_hash())))
    sp0 = _acc(mgr, sponsor.account_id).balance
    a0 = _acc(mgr, a.account_id).balance
    arts = _close(mgr, frame)
    res = _result_of(arts, frame)
    assert res.result.switch == X.TransactionResultCode.txFEE_BUMP_INNER_SUCCESS
    assert _acc(mgr, sponsor.account_id).balance == sp0 - 200  # 2 ops * base
    assert _acc(mgr, a.account_id).balance == a0 - 1  # only the payment


def test_multiple_txs_same_source_one_ledger(mgr, root):
    """Apply order must run a source's txs in sequence order even though
    the tx SET is hash-ordered (reference: TxSetFrame::getTxsInApplyOrder;
    regression: hash-only ordering seq-failed all but one tx)."""
    dests = [SecretKey(bytes([0x70 + i]) * 32) for i in range(6)]
    frames = [root.tx([create_account_op(
        X.AccountID.ed25519(d.public_key.ed25519), 10_000_000_000)])
        for d in dests]
    arts = _close(mgr, *frames)
    results = arts.result_entry.txResultSet.results
    assert len(results) == 6
    for pair in results:
        assert pair.result.result.switch == X.TransactionResultCode.txSUCCESS
    for d in dests:
        k = X.LedgerKey.account(X.LedgerKeyAccount(
            accountID=X.AccountID.ed25519(d.public_key.ed25519))).to_xdr()
        assert mgr.root.get_entry(k) is not None


def test_same_source_apply_order_survives_replay(tmp_path):
    """Publisher and fresh replayer must agree on the seq-aware apply
    order (consensus-critical determinism)."""
    from stellar_core_tpu.catchup.catchup import CatchupManager
    from stellar_core_tpu.history.archive import FileHistoryArchive
    from stellar_core_tpu.history.manager import HistoryManager
    from stellar_core_tpu.simulation.loadgen import LoadGenerator
    from stellar_core_tpu.testutils import network_id

    nid = network_id("apply order replay")
    m = LedgerManager(nid)
    m.start_new_ledger()
    arch = FileHistoryArchive(str(tmp_path / "a"))
    hist = HistoryManager(m, "apply order replay", [arch])
    gen = LoadGenerator(m, hist, seed=5)
    gen.create_accounts(150, per_ledger=150)   # 2 root txs in one ledger
    gen.payment_ledgers(3, txs_per_ledger=10)
    gen.run_to_checkpoint_boundary()
    fresh = CatchupManager(nid, "apply order replay").catchup_complete(arch)
    assert fresh.lcl_hash == m.lcl_hash
