"""THE minimum end-to-end slice (SURVEY.md §7 stage 5): generate a synthetic
archive with load, replay it on a fresh node, assert exact LCL-hash equality.
Exercises XDR, crypto, ledger, tx-apply, bucket list, history, catchup.

Mirrors the reference's CatchupSimulation fixture
(src/history/test/HistoryTestsUtils) with tmp-dir file archives.
"""

import pytest

from stellar_core_tpu import xdr as X
from stellar_core_tpu.catchup.catchup import (CatchupError, CatchupManager,
                                              verify_ledger_chain)
from stellar_core_tpu.crypto import keys
from stellar_core_tpu.history.archive import (FileHistoryArchive,
                                              is_checkpoint_boundary,
                                              pack_xdr_stream,
                                              unpack_xdr_stream)
from stellar_core_tpu.history.manager import HistoryManager
from stellar_core_tpu.ledger.manager import LedgerManager
from stellar_core_tpu.simulation.loadgen import LoadGenerator
from stellar_core_tpu.testutils import network_id

PASSPHRASE = "tpu-core e2e test network"
NID = network_id(PASSPHRASE)


@pytest.fixture(scope="module")
def published(tmp_path_factory):
    """One generated+published chain shared by the tests in this module."""
    archive_dir = tmp_path_factory.mktemp("archive")
    mgr = LedgerManager(NID)
    mgr.start_new_ledger()
    archive = FileHistoryArchive(str(archive_dir))
    history = HistoryManager(mgr, PASSPHRASE, [archive])
    gen = LoadGenerator(mgr, history, seed=42)
    gen.create_accounts(30, per_ledger=10)
    gen.payment_ledgers(25, txs_per_ledger=8)
    gen.run_to_checkpoint_boundary()
    assert history.published_checkpoints, "no checkpoint published"
    return archive, mgr, history


def test_xdr_stream_roundtrip():
    recs = [b"abc", b"", b"x" * 1000]
    assert list(unpack_xdr_stream(pack_xdr_stream(recs))) == recs
    with pytest.raises(ValueError):
        list(unpack_xdr_stream(b"\x80\x00\x00\x05ab"))  # truncated body


def test_malicious_has_bucket_hashes_rejected():
    # HAS files come from untrusted archives; anything but 64 lowercase hex
    # must be rejected before it can reach shell templates or file paths
    # (reference: hexToBin256 on every HAS hash)
    import json

    from stellar_core_tpu.history.archive import (HistoryArchiveState,
                                                  bucket_path)
    good = "ab" * 32
    for evil in ("aa'; rm -rf ~ #", "../../../etc/passwd", "AB" * 32,
                 "ab" * 31, "ab" * 33, "", None, 42):
        doc = {"version": 1, "server": "x", "currentLedger": 63,
               "networkPassphrase": "p",
               "currentBuckets": [{"curr": evil, "snap": good,
                                   "next": {"state": 0}}]}
        with pytest.raises((ValueError, TypeError)):
            HistoryArchiveState.from_json(json.dumps(doc))
        if isinstance(evil, str):
            with pytest.raises(ValueError):
                bucket_path(evil)
    # a pending-merge "next" with a poisoned output hash is equally rejected
    doc = {"version": 1, "server": "x", "currentLedger": 63,
           "networkPassphrase": "p",
           "currentBuckets": [{"curr": good, "snap": good,
                               "next": {"state": 1,
                                        "output": "aa`touch /tmp/pwn`"}}]}
    with pytest.raises(ValueError):
        HistoryArchiveState.from_json(json.dumps(doc))
    # the honest shape still parses
    doc["currentBuckets"][0]["next"] = {"state": 1, "output": good}
    has = HistoryArchiveState.from_json(json.dumps(doc))
    assert has.bucket_hashes() == [good, good]


def test_checkpoint_published_and_has_readable(published):
    archive, mgr, history = published
    has = archive.get_state()
    assert has is not None
    assert has.current_ledger == history.published_checkpoints[-1]
    assert is_checkpoint_boundary(has.current_ledger)
    assert has.network_passphrase == PASSPHRASE


def test_catchup_complete_replay_identical_hash(published):
    archive, mgr, _ = published
    cm = CatchupManager(NID, PASSPHRASE)
    replayed = cm.catchup_complete(archive)
    assert replayed.last_closed_ledger_seq == \
        archive.get_state().current_ledger
    # THE invariant: bit-identical ledger hash after full replay
    target_hash_chainpoint = mgr_lcl_at_checkpoint = None
    assert replayed.lcl_hash is not None
    # the source node may have advanced past the checkpoint; compare at the
    # checkpoint ledger via the archive's own header file
    from stellar_core_tpu.catchup.catchup import _LHHE
    from stellar_core_tpu.history.archive import category_path
    recs = archive.get_xdr_file(category_path(
        "ledger", archive.get_state().current_ledger))
    tail = _LHHE.unpack(recs[-1])
    assert replayed.lcl_hash == tail.hash
    assert replayed.root.entry_count() == mgr.root.entry_count()


def test_catchup_with_accel_identical(published):
    """TPU-accelerated replay must produce the identical chain."""
    pytest.importorskip("jax")
    archive, mgr, _ = published
    keys.clear_verify_cache()
    cm = CatchupManager(NID, PASSPHRASE, accel=True, accel_chunk=256)
    replayed = cm.catchup_complete(archive)
    cm2 = CatchupManager(NID, PASSPHRASE, accel=False)
    keys.clear_verify_cache()
    replayed_cpu = cm2.catchup_complete(archive)
    assert replayed.lcl_hash == replayed_cpu.lcl_hash


def test_accel_catchup_decodes_each_envelope_once(published):
    """The accel pass must NOT decode the replay stream twice (VERDICT r3
    weak #2: PreverifyPipeline.dispatch and ApplyCheckpointWork each ran
    make_from_wire over every envelope — double XDR decode of the whole
    catchup, charged to the accel wall-clock).  Frames are decoded once at
    download and shared by dispatch and apply."""
    pytest.importorskip("jax")
    from stellar_core_tpu.transactions.frame import TransactionFrame

    archive, mgr, _ = published
    n_envelopes = 0
    from stellar_core_tpu.catchup.catchup import _THE
    from stellar_core_tpu.history.archive import category_path
    has = archive.get_state()
    cp = 63
    while cp <= has.current_ledger:
        for r in archive.get_xdr_file(
                category_path("transactions", cp)) or []:
            n_envelopes += len(_THE.unpack(r).txSet.txs)
        cp += 64

    calls = [0]
    orig = TransactionFrame.make_from_wire

    def counting(network_id, env):
        calls[0] += 1
        return orig(network_id, env)

    keys.clear_verify_cache()
    TransactionFrame.make_from_wire = staticmethod(counting)
    try:
        cm = CatchupManager(NID, PASSPHRASE, accel=True, accel_chunk=256)
        replayed = cm.catchup_complete(archive)
    finally:
        TransactionFrame.make_from_wire = staticmethod(orig)
    assert replayed.last_closed_ledger_seq == has.current_ledger
    assert n_envelopes > 0
    # the r3 regression was a DOUBLE decode (dispatch + apply each decoded
    # the stream): the invariant is at-most-once.  With the native engine
    # (r5) both apply and pairing parse raw records in C, so the count is
    # ZERO; the Python fallback engine decodes exactly once.
    assert calls[0] in (0, n_envelopes), (calls[0], n_envelopes)
    if cm.native:
        assert calls[0] == 0, calls[0]
    # the Python engine path still decodes once, never twice
    keys.clear_verify_cache()
    calls[0] = 0
    TransactionFrame.make_from_wire = staticmethod(counting)
    try:
        cm2 = CatchupManager(NID, PASSPHRASE, accel=True, accel_chunk=256,
                             native=False)
        replayed2 = cm2.catchup_complete(archive)
    finally:
        TransactionFrame.make_from_wire = staticmethod(orig)
    assert replayed2.last_closed_ledger_seq == has.current_ledger
    assert calls[0] == n_envelopes, (calls[0], n_envelopes)


def test_accel_catchup_end_to_end_on_8dev_mesh(published, no_race):
    """The PRODUCT path (CatchupWork + PreverifyPipeline), not just the
    kernel, on the 8-virtual-device mesh: every device batch shard_maps
    across all 8 devices, hashes identical, offload hit-rate 1.0
    (VERDICT r3 item 5: multi-chip evidence must cover the actual catchup,
    not only scaling-shape kernel tests)."""
    jax = pytest.importorskip("jax")
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-virtual-device CPU mesh (conftest)")
    from stellar_core_tpu.accel import ed25519 as E

    archive, mgr, _ = published
    chunk = 256
    v = E._verifier_for(chunk, chunk, 1 << 62)  # the pipeline's verifier
    assert v._mesh is not None and v._ndev == 8, \
        "pipeline verifier must shard over the full visible mesh"
    widths = []
    orig_kernel = v._kernel_raw

    def spy(s_raw, hh, kidx, ucx, ucy, uct, rb):
        widths.append(int(s_raw.shape[0]))
        return orig_kernel(s_raw, hh, kidx, ucx, ucy, uct, rb)

    keys.clear_verify_cache()
    v._kernel_raw = spy
    try:
        cm = CatchupManager(NID, PASSPHRASE, accel=True, accel_chunk=chunk)
        replayed = cm.catchup_complete(archive)
    finally:
        v._kernel_raw = orig_kernel
    assert replayed.last_closed_ledger_seq == \
        archive.get_state().current_ledger
    from stellar_core_tpu.catchup.catchup import _LHHE
    from stellar_core_tpu.history.archive import category_path
    recs = archive.get_xdr_file(category_path(
        "ledger", archive.get_state().current_ledger))
    assert replayed.lcl_hash == _LHHE.unpack(recs[-1]).hash
    assert cm.offload_hit_rate() == 1.0, cm.stats
    # every dispatched batch split evenly across the 8 devices (widths are
    # rounded to a device multiple by _tail_width; shard_map partitions
    # the batch axis), and the device actually saw work
    assert widths, "no device batches were dispatched"
    assert all(w % 8 == 0 and w // 8 > 0 for w in widths), widths


@pytest.fixture
def no_race(monkeypatch):
    """Pin the legacy race profile with a huge collect budget: tests that
    assert an EXACT offload hit rate need every collect to wait for the
    (slow CPU-jax) device instead of polling past it (the ISSUE 14
    default) or racing it."""
    from stellar_core_tpu.catchup.catchup import PreverifyPipeline
    monkeypatch.setattr(PreverifyPipeline, "DEFAULT_PROFILE",
                        PreverifyPipeline.PROFILE_RACE)
    monkeypatch.setattr(PreverifyPipeline, "RACE_CPU_S_PER_SIG", 10.0)


def test_catchup_minimal_assumes_state(published):
    archive, mgr, _ = published
    cm = CatchupManager(NID, PASSPHRASE)
    node = cm.catchup_minimal(archive)
    assert node.lcl_header.ledgerSeq == archive.get_state().current_ledger
    # assumed state must agree with a full replay
    replay = cm.catchup_complete(archive)
    assert node.lcl_hash == replay.lcl_hash
    assert node.root.entry_count() == replay.root.entry_count()
    for kb in list(replay.root._entries.keys()):
        assert node.root.get_entry(kb) == replay.root.get_entry(kb)


def test_minimal_node_can_keep_closing(published):
    """A bucket-assumed node closes subsequent ledgers identically to a
    replayed node (state equivalence under continued operation)."""
    archive, _, _ = published
    cm = CatchupManager(NID, PASSPHRASE)
    a = cm.catchup_minimal(archive)
    b = cm.catchup_complete(archive)
    arts_a = a.close_ledger([], 2_000_000_000)
    arts_b = b.close_ledger([], 2_000_000_000)
    assert a.lcl_hash == b.lcl_hash
    assert arts_a.header_entry.hash == arts_b.header_entry.hash


def test_tampered_archive_detected(published, tmp_path):
    """Corrupting a tx in the archive must break the replay (hash chain or
    tx-set hash check), mirroring the reference's fail-stop."""
    import gzip
    import os
    import shutil
    archive, _, _ = published
    bad_dir = tmp_path / "bad_archive"
    shutil.copytree(archive.root, bad_dir)
    bad = FileHistoryArchive(str(bad_dir))
    cp = bad.get_state().current_ledger
    from stellar_core_tpu.history.archive import category_path
    rel = category_path("transactions", cp)
    recs = bad.get_xdr_file(rel)
    if not recs:
        pytest.skip("no txs in final checkpoint")
    blob = bytearray(recs[0])
    blob[-1] ^= 0xFF
    recs[0] = bytes(blob)
    bad.put_xdr_file(rel, recs)
    cm = CatchupManager(NID, PASSPHRASE)
    with pytest.raises(CatchupError):
        cm.catchup_complete(bad)


def test_catchup_with_invariants_enabled_green(published):
    """Catchup (complete AND minimal) with INVARIANT_CHECKS on must agree
    with the tamper-free archive (reference: invariants honored during
    catchup, VERDICT r2 weak #6)."""
    from stellar_core_tpu.invariant.invariants import InvariantManager
    archive, mgr, _ = published
    cm = CatchupManager(NID, PASSPHRASE,
                        invariant_manager=InvariantManager())
    assert cm.catchup_complete(archive).lcl_hash == mgr.lcl_hash
    assert cm.catchup_minimal(archive).lcl_hash == mgr.lcl_hash


def test_bad_bucket_entry_localized_by_invariant(published):
    """A seeded invalid bucket entry (negative balance) must be caught by
    the bucket-apply invariant with a LOCALIZED message; without
    invariants the same corruption is only detected as a terminal
    bucket-list hash mismatch (reference: checkOnBucketApply).  The
    content-addressed archive would reject a tampered FILE before apply,
    so this drives assume_bucket_state directly — the invariant's value
    is localizing faults in whatever produced the buckets (archive or
    local apply machinery)."""
    from stellar_core_tpu.bucket.bucket import Bucket
    from stellar_core_tpu.bucket.bucket_list import BucketList
    from stellar_core_tpu.invariant.invariants import (InvariantDoesNotHold,
                                                       InvariantManager)
    from stellar_core_tpu.ledger.manager import assume_bucket_state
    archive, mgr, _ = published

    # honest bucket set from the live manager's list, then tamper one
    # account entry in-memory (hash gates bypassed on purpose)
    mgr.bucket_list.resolve_all_merges()
    buckets = []
    for lvl in mgr.bucket_list.levels:
        buckets.extend([lvl.curr, lvl.snap])
    tampered = False
    patched = []
    for b in buckets:
        if not tampered and any(
                be.switch in (X.BucketEntryType.LIVEENTRY,
                              X.BucketEntryType.INITENTRY)
                and be.value.data.switch == X.LedgerEntryType.ACCOUNT
                for be in b.entries):
            entries = [be.deep_copy() for be in b.entries]
            for be in entries:
                if be.switch in (X.BucketEntryType.LIVEENTRY,
                                 X.BucketEntryType.INITENTRY) and \
                        be.value.data.switch == X.LedgerEntryType.ACCOUNT:
                    be.value.data.value.balance = -1
                    break
            patched.append(Bucket(entries, b.protocol_version))
            tampered = True
        else:
            patched.append(b)
    assert tampered, "no account entry found in any bucket"

    def source(idx):
        return patched[idx]

    with pytest.raises(InvariantDoesNotHold, match="balance"):
        assume_bucket_state(BucketList(), mgr.lcl_header, source,
                            invariant_manager=InvariantManager())
    # without invariants: detected late and namelessly by the list hash
    with pytest.raises(RuntimeError, match="hash"):
        assume_bucket_state(BucketList(), mgr.lcl_header, source)


def test_verify_ledger_chain_rejects_fork(published):
    archive, _, _ = published
    from stellar_core_tpu.catchup.catchup import _LHHE
    from stellar_core_tpu.history.archive import category_path
    recs = archive.get_xdr_file(category_path(
        "ledger", archive.get_state().current_ledger))
    headers = [_LHHE.unpack(r) for r in recs]
    verify_ledger_chain(headers)  # sane
    headers[1].header.previousLedgerHash = b"\x13" * 32
    with pytest.raises(CatchupError):
        verify_ledger_chain(headers)


def test_catchup_replays_upgraded_ledgers(tmp_path):
    """Regression: a ledger whose externalized value carried upgrades must
    replay to the identical hash (scpValue stored verbatim, upgrades
    re-applied).  Reference: Upgrades::applyTo on the catchup path."""
    from stellar_core_tpu.crypto.sha import sha256

    archive = FileHistoryArchive(str(tmp_path / "arc"))
    mgr = LedgerManager(NID)
    mgr.start_new_ledger()
    history = HistoryManager(mgr, PASSPHRASE, [archive])
    gen = LoadGenerator(mgr, history, seed=7)
    gen.create_accounts(5, per_ledger=5)

    # close one ledger carrying a voted baseFee upgrade
    up = X.LedgerUpgrade.newBaseFee(275).to_xdr()
    tx_set, tx_set_hash, _ = mgr.make_tx_set([])
    sv = X.StellarValue(txSetHash=tx_set_hash,
                        closeTime=mgr.lcl_header.scpValue.closeTime + 5,
                        upgrades=[up])
    arts = mgr.close_ledger([], sv.closeTime, tx_set=tx_set,
                            stellar_value=sv)
    history.ledger_closed(arts)
    assert mgr.lcl_header.baseFee == 275

    gen.payment_ledgers(3, txs_per_ledger=2)
    gen.run_to_checkpoint_boundary()
    assert history.published_checkpoints

    cm = CatchupManager(NID, PASSPHRASE)
    replayed = cm.catchup_complete(archive)
    assert replayed.lcl_header.baseFee == 275
    from stellar_core_tpu.catchup.catchup import _LHHE
    from stellar_core_tpu.history.archive import category_path
    recs = archive.get_xdr_file(category_path(
        "ledger", archive.get_state().current_ledger))
    assert replayed.lcl_hash == _LHHE.unpack(recs[-1]).hash


def test_multisig_catchup_accel_pairs_all_signers(tmp_path, no_race):
    """Multisig-heavy traffic: txs signed ONLY by added (non-master)
    signers.  Accel pre-verification must pair those via the ledger-state
    signer sets (VERDICT r1 weak #4), reach 100% offload, and replay to the
    identical hash chain."""
    from stellar_core_tpu.crypto.keys import SecretKey
    from stellar_core_tpu.testutils import (TestAccount, build_tx,
                                            create_account_op,
                                            native_payment_op)

    nid = network_id("multisig accel net")
    mgr = LedgerManager(nid, invariant_manager=None)
    mgr.start_new_ledger()
    archive = FileHistoryArchive(str(tmp_path / "archive"))
    history = HistoryManager(mgr, "multisig accel net", [archive])

    root_sk = mgr.root_account_secret()
    e = mgr.root.get_entry(X.LedgerKey.account(X.LedgerKeyAccount(
        accountID=X.AccountID.ed25519(root_sk.public_key.ed25519))).to_xdr())
    root = TestAccount(mgr, root_sk, e.data.value.seqNum)

    ct = [1_600_000_000]

    def close(frames):
        ct[0] += 5
        history.ledger_closed(mgr.close_ledger(frames, ct[0]))

    # 8 accounts, each adding a distinct extra signer
    accounts, extras = [], []
    ops = []
    sks = [SecretKey(bytes([0x80 + i]) * 32) for i in range(8)]
    for sk in sks:
        ops.append(create_account_op(
            X.AccountID.ed25519(sk.public_key.ed25519), 10**11))
    close([root.tx(ops)])
    for i, sk in enumerate(sks):
        entry = mgr.root.get_entry(X.LedgerKey.account(X.LedgerKeyAccount(
            accountID=X.AccountID.ed25519(sk.public_key.ed25519))).to_xdr())
        acct = TestAccount(mgr, sk, entry.data.value.seqNum)
        extra = SecretKey(bytes([0xa0 + i]) * 32)
        accounts.append(acct)
        extras.append(extra)
        close([acct.tx([X.Operation(body=X.OperationBody.setOptionsOp(
            X.SetOptionsOp(signer=X.Signer(
                key=X.SignerKey.ed25519(extra.public_key.ed25519),
                weight=1))))])])
    # payments signed ONLY by the added signer (master key never signs)
    for round_ in range(6):
        frames = []
        for acct, extra in zip(accounts, extras):
            frames.append(build_tx(
                nid, acct.secret, acct.next_seq(),
                [native_payment_op(root.account_id, 1000 + round_)],
                signers=[extra]))
        close(frames)
    while not history.published_checkpoints:
        close([])

    keys.clear_verify_cache()
    cm = CatchupManager(nid, "multisig accel net", accel=True,
                        accel_chunk=256)
    replayed = cm.catchup_complete(archive)
    assert replayed.lcl_hash == mgr.lcl_hash
    assert cm.stats["sigs_total"] >= 57
    assert cm.offload_hit_rate() == 1.0, cm.stats

    keys.clear_verify_cache()
    cm_cpu = CatchupManager(nid, "multisig accel net", accel=False)
    assert cm_cpu.catchup_complete(archive).lcl_hash == mgr.lcl_hash


def test_coalesced_dispatch_pairs_cross_checkpoint_signers(tmp_path, no_race):
    """Double-buffered accel catchup dispatches checkpoint k+1 (and
    coalesces small checkpoints into one device batch) BEFORE checkpoint k
    applies, so pairing runs against a stale ledger state.  Signers added
    by SetOptions in checkpoint 1 and used to sign txs in checkpoint 2 must
    still pair via the cumulative harvest — offload hit-rate stays 1.0 and
    hashes identical (SURVEY §5.8 double-buffering)."""
    from stellar_core_tpu.crypto.keys import SecretKey
    from stellar_core_tpu.testutils import (TestAccount, build_tx,
                                            create_account_op,
                                            native_payment_op)

    nid = network_id("xcp accel net")
    mgr = LedgerManager(nid, invariant_manager=None)
    mgr.start_new_ledger()
    archive = FileHistoryArchive(str(tmp_path / "archive"))
    history = HistoryManager(mgr, "xcp accel net", [archive])

    root_sk = mgr.root_account_secret()
    e = mgr.root.get_entry(X.LedgerKey.account(X.LedgerKeyAccount(
        accountID=X.AccountID.ed25519(root_sk.public_key.ed25519))).to_xdr())
    root = TestAccount(mgr, root_sk, e.data.value.seqNum)

    ct = [1_700_000_000]

    def close(frames):
        ct[0] += 5
        history.ledger_closed(mgr.close_ledger(frames, ct[0]))

    sks = [SecretKey(bytes([0x90 + i]) * 32) for i in range(4)]
    close([root.tx([create_account_op(
        X.AccountID.ed25519(sk.public_key.ed25519), 10**11)
        for sk in sks])])
    accounts, extras = [], []
    for i, sk in enumerate(sks):
        entry = mgr.root.get_entry(X.LedgerKey.account(X.LedgerKeyAccount(
            accountID=X.AccountID.ed25519(sk.public_key.ed25519))).to_xdr())
        acct = TestAccount(mgr, sk, entry.data.value.seqNum)
        extra = SecretKey(bytes([0xb0 + i]) * 32)
        accounts.append(acct)
        extras.append(extra)
        close([acct.tx([X.Operation(body=X.OperationBody.setOptionsOp(
            X.SetOptionsOp(signer=X.Signer(
                key=X.SignerKey.ed25519(extra.public_key.ed25519),
                weight=1))))])])
    # run past the first checkpoint boundary: signer adds live in cp 63
    while len(history.published_checkpoints) < 1:
        close([])
    # second checkpoint: payments signed ONLY by the extras added in cp 1
    for round_ in range(4):
        frames = []
        for acct, extra in zip(accounts, extras):
            frames.append(build_tx(
                nid, acct.secret, acct.next_seq(),
                [native_payment_op(root.account_id, 500 + round_)],
                signers=[extra]))
        close(frames)
    while len(history.published_checkpoints) < 2:
        close([])

    keys.clear_verify_cache()
    cm = CatchupManager(nid, "xcp accel net", accel=True, accel_chunk=256)
    # regression guard: every checkpoint must be device-dispatched exactly
    # once (a collect() bug once dropped a whole coalesced group from the
    # registry, silently re-dispatching each member synchronously)
    from stellar_core_tpu.catchup.catchup import PreverifyPipeline
    dispatched_cps = []
    orig_dispatch = PreverifyPipeline.dispatch
    orig_dispatch_raw = PreverifyPipeline.dispatch_raw

    def spy(self, entries, ledger_state=None):
        dispatched_cps.extend(entries)
        return orig_dispatch(self, entries, ledger_state=ledger_state)

    def spy_raw(self, entries):
        dispatched_cps.extend(entries)
        return orig_dispatch_raw(self, entries)

    PreverifyPipeline.dispatch = spy
    PreverifyPipeline.dispatch_raw = spy_raw
    try:
        replayed = cm.catchup_complete(archive)
    finally:
        PreverifyPipeline.dispatch = orig_dispatch
        PreverifyPipeline.dispatch_raw = orig_dispatch_raw
    assert replayed.lcl_hash == mgr.lcl_hash
    assert sorted(dispatched_cps) == [63, 127], dispatched_cps
    assert cm.stats["sigs_total"] >= 16
    assert cm.offload_hit_rate() == 1.0, cm.stats


def test_command_template_archive_publish_and_catchup(tmp_path):
    """Archive driven by get=/put=/mkdir= shell templates (reference:
    HistoryArchive command indirection; tests use cp/mkdir exactly like
    TmpDirHistoryConfigurator)."""
    from stellar_core_tpu.catchup.catchup import CatchupManager
    from stellar_core_tpu.history.archive import (CommandHistoryArchive,
                                                  make_archive)

    root = tmp_path / "cmdarch"
    root.mkdir()
    archive = make_archive(
        get_spec=f"cp {root}/{{0}} {{1}}",
        put_spec=f"cp {{0}} {root}/{{1}}",
        mkdir_spec=f"mkdir -p {root}/{{0}}")
    assert isinstance(archive, CommandHistoryArchive)

    mgr = LedgerManager(NID)
    mgr.start_new_ledger()
    history = HistoryManager(mgr, PASSPHRASE, [archive])
    gen = LoadGenerator(mgr, history, seed=13)
    gen.create_accounts(12, per_ledger=6)
    gen.payment_ledgers(10, txs_per_ledger=5)
    gen.run_to_checkpoint_boundary()
    assert history.published_checkpoints

    # a FAILING get returns None (missing object), not an exception
    assert archive.get_bytes("no/such/object") is None

    cm = CatchupManager(NID, PASSPHRASE)
    fresh = cm.catchup_complete(archive)
    assert fresh.lcl_hash == mgr.lcl_hash


def test_catchup_recent_assumes_boundary_and_replays_tail(tmp_path):
    """CATCHUP_RECENT: bucket-apply at the newest boundary leaving >= count
    ledgers, replay the tail, identical final hash (reference:
    CatchupRange + CatchupWork with both segments)."""
    from stellar_core_tpu.catchup.catchup import (CatchupManager,
                                                  plan_catchup_range)

    # two checkpoints: 63 and 127
    mgr = LedgerManager(NID)
    mgr.start_new_ledger()
    archive = FileHistoryArchive(str(tmp_path / "arc"))
    history = HistoryManager(mgr, PASSPHRASE, [archive])
    gen = LoadGenerator(mgr, history, seed=21)
    gen.create_accounts(20, per_ledger=10)
    gen.payment_ledgers(100, txs_per_ledger=4)
    gen.run_to_checkpoint_boundary()
    assert mgr.last_closed_ledger_seq == 127
    assert history.published_checkpoints == [63, 127]

    rng = plan_catchup_range(127, count=10)
    assert rng.apply_buckets_at == 63 and rng.replay_from == 64

    cm = CatchupManager(NID, PASSPHRASE)
    fresh = cm.catchup_recent(archive, count=10)
    assert fresh.last_closed_ledger_seq == 127
    assert fresh.lcl_hash == mgr.lcl_hash

    # a count larger than the chain falls back to complete replay
    assert plan_catchup_range(127, count=500).apply_buckets_at is None
    fresh2 = cm.catchup_recent(archive, count=500)
    assert fresh2.lcl_hash == mgr.lcl_hash


def test_plan_catchup_range_boundaries():
    from stellar_core_tpu.catchup.catchup import plan_catchup_range
    assert plan_catchup_range(1000, None).apply_buckets_at is None
    r = plan_catchup_range(1000, 100)
    # newest boundary <= 900
    assert r.apply_buckets_at == 895 and r.replay_from == 896
    assert plan_catchup_range(1000, 100).replay_to == 1000
    assert plan_catchup_range(64, 10).apply_buckets_at is None  # 54 < 63
    assert plan_catchup_range(127, 64).apply_buckets_at == 63


def test_collect_race_loss_degrades_to_cpu(tmp_path, monkeypatch):
    """When the device cannot beat the group's libsodium cost, collect()
    loses the CPU race: seeding is skipped (the apply verifies on CPU —
    identical hashes), losses are counted, and repeated losses disable
    the pipeline for the rest of the catchup.

    The race is made DETERMINISTIC via the injectable DEVICE_GATE
    barrier: every group after the first blocks inside the device worker
    until the test releases it, so collect() ALWAYS times out at its
    (tiny, monkeypatched) race budget — the old version only shrank the
    budget and flaked whenever CPU-jax still finished within 0.25s."""
    import threading

    from stellar_core_tpu.catchup.catchup import PreverifyPipeline
    from stellar_core_tpu.crypto.keys import SecretKey
    from stellar_core_tpu.testutils import (TestAccount, create_account_op,
                                            native_payment_op)

    nid = network_id("race loss net")
    mgr = LedgerManager(nid, invariant_manager=None)
    mgr.start_new_ledger()
    archive = FileHistoryArchive(str(tmp_path / "archive"))
    history = HistoryManager(mgr, "race loss net", [archive])
    root_sk = mgr.root_account_secret()
    e = mgr.root.get_entry(X.LedgerKey.account(X.LedgerKeyAccount(
        accountID=X.AccountID.ed25519(root_sk.public_key.ed25519))).to_xdr())
    root = TestAccount(mgr, root_sk, e.data.value.seqNum)
    ct = [1_800_000_000]

    def close(frames):
        ct[0] += 5
        history.ledger_closed(mgr.close_ledger(frames, ct[0]))

    sk = SecretKey(bytes([0x71]) * 32)
    close([root.tx([create_account_op(
        X.AccountID.ed25519(sk.public_key.ed25519), 10**11)])])
    acct = TestAccount(mgr, sk, mgr.root.get_entry(
        X.LedgerKey.account(X.LedgerKeyAccount(
            accountID=X.AccountID.ed25519(
                sk.public_key.ed25519))).to_xdr()).data.value.seqNum)
    # several checkpoints of payments so multiple groups dispatch
    for _ in range(140):
        close([acct.tx([native_payment_op(root.account_id, 777)])])
    while len(history.published_checkpoints) < 3 or \
            history.published_checkpoints[-1] != mgr.last_closed_ledger_seq:
        close([])

    # the race profile is opt-in since ISSUE 14 (poll never waits at all)
    monkeypatch.setattr(PreverifyPipeline, "DEFAULT_PROFILE",
                        PreverifyPipeline.PROFILE_RACE)
    # minimal race budget (0.25s floor) + a barrier that HOLDS every
    # group after the first: those collects deterministically miss
    monkeypatch.setattr(PreverifyPipeline, "RACE_CPU_S_PER_SIG", 1e-12)
    released = threading.Event()

    def gate(group_idx: int) -> None:
        if group_idx >= 1:
            released.wait()

    monkeypatch.setattr(PreverifyPipeline, "DEVICE_GATE", staticmethod(gate))
    try:
        keys.clear_verify_cache()
        cm = CatchupManager(nid, "race loss net", accel=True,
                            accel_chunk=256)
        replayed = cm.catchup_complete(archive)
    finally:
        released.set()   # unblock the parked device worker
    assert replayed.lcl_hash == mgr.lcl_hash   # verdicts identical, on CPU
    assert cm.stats.get("race_losses", 0) >= 1, cm.stats
    assert cm.offload_hit_rate() < 1.0
