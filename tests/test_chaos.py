"""Chaos campaign runner tests: the fault-event scheduler, deterministic
replay, symmetric link severance, sim-node health, artifact emission, and
the 50+-node scenario catalogue (300-node soaks ride behind -m slow).

Reference test model: src/simulation/test/ + HerderTests partition cases,
composed at fleet scale with scripted fault schedules.
"""

import json
import os

import pytest

from stellar_core_tpu.simulation import chaos as C
from stellar_core_tpu.simulation.chaos import (Ban, ChaosRunner,
                                               ChaosScenario, CorruptFlood,
                                               Flap, Heal, LinkFault,
                                               Partition, RejoinNode,
                                               StallNode, run_scenario)
from stellar_core_tpu.simulation.simulation import (Simulation,
                                                    make_core_topology)
from stellar_core_tpu.util import eventlog


def _mini_core_scenario(seed, schedule, n=6, duration_s=25.0, **kw):
    return ChaosScenario(name="mini", build=C._core_build(n),
                         schedule=schedule, duration_s=duration_s,
                         seed=seed, **kw)


# ---------------------------------------------------------------------------
# scheduler mechanics
# ---------------------------------------------------------------------------

class TestFaultScheduler:
    def test_events_fire_at_their_virtual_times_in_order(self):
        # no-op link faults: pure scheduling, no consensus disturbance
        sched = [LinkFault(11.0), LinkFault(3.0), LinkFault(7.0)]
        res = run_scenario(_mini_core_scenario(1, sched, n=3,
                                               duration_s=14.0))
        fired = [(t, m) for t, m in res.event_trace
                 if m.startswith("LinkFault")]
        assert [t for t, _ in fired] == [3.0, 7.0, 11.0]
        assert res.passed, res.violations

    def test_flap_expands_into_alternating_partition_heal(self):
        flap = Flap(5.0, [[0]], period=2.0, count=3, name="f")
        expanded = flap.expand()
        kinds = [type(e).__name__ for e in expanded]
        assert kinds == ["Partition", "Heal"] * 3
        assert [e.at for e in expanded] == [5.0, 7.0, 9.0, 11.0, 13.0, 15.0]
        # partition i and heal i share a name so each flap closes itself
        assert expanded[0].name == expanded[1].name == "f-0"

    def test_overlapping_partitions_compose(self):
        sim = make_core_topology(4, seed=0)
        links = C.mesh_links(4)
        sc = _mini_core_scenario(0, [], n=4)
        runner = ChaosRunner(sc)
        runner.sim, runner.base_links = sim, links
        for key in links:
            ia, ib = tuple(key)
            sim.connect(sim.nodes[ia], sim.nodes[ib])
        sim.clock.crank_for(0.2)
        runner._start_vt = sim.clock.now()
        n = sim.nodes

        runner._apply(Partition(0.0, [[0]], name="a"))      # severs 0-*
        runner._apply(Partition(0.0, [[0, 1]], name="b"))   # severs {0,1}-*
        assert not sim.is_connected(n[0], n[1])   # cut a splits 0 from 1
        assert not sim.is_connected(n[1], n[2])   # cut b splits 1 from 2
        assert sim.is_connected(n[2], n[3])

        runner._apply(Heal(0.0, name="a"))
        # b alone: {0,1} vs {2,3} — the 0-1 link comes back, 1-2 stays cut
        assert sim.is_connected(n[0], n[1])
        assert not sim.is_connected(n[1], n[2])
        assert not sim.is_connected(n[0], n[3])

        runner._apply(Heal(0.0, name="b"))
        for i in range(4):
            for j in range(i + 1, 4):
                assert sim.is_connected(n[i], n[j])

    def test_link_faults_reapply_to_redialed_links(self):
        """A link lost to a fail-stop (or severed and healed) must come
        back with the ACTIVE LinkFault probabilities, not a clean slate —
        otherwise every redial silently erodes the declared ramp."""
        sim = make_core_topology(3, seed=0)
        links = C.mesh_links(3)
        runner = ChaosRunner(_mini_core_scenario(0, [], n=3))
        runner.sim, runner.base_links = sim, links
        for key in links:
            ia, ib = tuple(key)
            sim.connect(sim.nodes[ia], sim.nodes[ib])
        sim.clock.crank_for(0.2)
        runner._start_vt = sim.clock.now()
        runner._apply(LinkFault(0.0, drop=0.25, reorder=0.5))
        runner._apply(Partition(0.0, [[0]], name="p"))
        runner._apply(Heal(0.0, name="p"))   # 0-1 and 0-2 redialed fresh
        pair = sim._connections[
            frozenset((sim.nodes[0].node_id, sim.nodes[1].node_id))]
        for peer in pair:
            assert peer.drop_probability == 0.25
            assert peer.reorder_probability == 0.5

    def test_redial_restores_latest_link_fault_not_lowest_index(self):
        """When two per-node LinkFaults cover one link, a redial must
        restore what the LAST event left on the live link — not whichever
        endpoint happens to have the lower node index."""
        sim = make_core_topology(3, seed=0)
        links = C.mesh_links(3)
        runner = ChaosRunner(_mini_core_scenario(0, [], n=3))
        runner.sim, runner.base_links = sim, links
        for key in links:
            ia, ib = tuple(key)
            sim.connect(sim.nodes[ia], sim.nodes[ib])
        sim.clock.crank_for(0.2)
        runner._start_vt = sim.clock.now()
        runner._apply(LinkFault(0.0, node=0, drop=0.5))
        runner._apply(LinkFault(0.0, node=1, drop=0.0))  # clears 0-1 too
        runner._apply(Partition(0.0, [[0]], name="p"))
        runner._apply(Heal(0.0, name="p"))   # 0-1 redialed
        pair = sim._connections[
            frozenset((sim.nodes[0].node_id, sim.nodes[1].node_id))]
        for peer in pair:
            assert peer.drop_probability == 0.0
        # the 0-2 link is untouched by the node-1 event: still ramped
        pair02 = sim._connections[
            frozenset((sim.nodes[0].node_id, sim.nodes[2].node_id))]
        for peer in pair02:
            assert peer.drop_probability == 0.5

    def test_unmet_recovery_produces_crash_bundle_artifact(self, tmp_path):
        """A scenario whose post-heal convergence cannot happen (one node
        stays stalled through the measured heal) must emit the artifact,
        not swallow the failure."""
        sched = [
            Partition(4.0, [[0, 1]], name="a"),
            StallNode(5.0, node=0),
            Heal(8.0, name="a", measure_recovery=True),
        ]
        sc = _mini_core_scenario(9, sched, n=6, duration_s=20.0,
                                 recovery_close_targets=4.0)
        res = run_scenario(sc, artifact_dir=str(tmp_path))
        assert not res.passed
        assert {v.kind for v in res.violations} == {"recovery"}
        assert res.artifact_path and os.path.exists(res.artifact_path)
        art = json.load(open(res.artifact_path))
        assert art["seed"] == 9
        assert any("StallNode" in s for s in art["schedule"])
        assert len(art["node_records"]) == 6
        # the flight-recorder crash bundle rode along, with the chaos
        # bundle source inside, and the source was unregistered after
        assert res.crash_bundle_path and os.path.exists(res.crash_bundle_path)
        bundle = json.load(open(res.crash_bundle_path))
        assert bundle["chaos"]["seed"] == 9
        assert "events" in bundle and "metrics" in bundle
        assert "chaos" not in eventlog._bundle_sources


# ---------------------------------------------------------------------------
# deterministic fault injection / replay
# ---------------------------------------------------------------------------

class TestDeterministicReplay:
    def test_pair_rng_is_seed_and_pair_derived(self):
        sim = Simulation(b"rng net", seed=5)
        a, b = b"\x01" * 32, b"\x02" * 32
        r1 = sim._pair_rng(a, b)
        r2 = sim._pair_rng(b, a)   # order-insensitive
        assert [r1.random() for _ in range(4)] == \
            [r2.random() for _ in range(4)]
        other = sim._pair_rng(a, b"\x03" * 32)
        assert r1.random() != other.random()
        assert Simulation(b"rng net")._pair_rng(a, b) is None

    @pytest.mark.parametrize("batching", [True, False],
                             ids=["batched", "unbatched"])
    def test_same_seed_replays_identical_event_log(self, batching):
        """Replay identity must hold in BOTH transport modes: the batched
        loopback path draws its per-message fault RNG in the same order
        as the per-frame path, so a seeded campaign is bit-identical
        regardless of envelope coalescing."""
        sched = lambda: [LinkFault(4.0, drop=0.05, reorder=0.10),  # noqa: E731
                         LinkFault(10.0, damage=0.01),
                         LinkFault(16.0)]
        r1 = run_scenario(_mini_core_scenario(42, sched(), n=6,
                                              batching=batching))
        r2 = run_scenario(_mini_core_scenario(42, sched(), n=6,
                                              batching=batching))
        assert r1.event_trace == r2.event_trace
        assert r1.slot_hashes == r2.slot_hashes
        assert r1.ledgers_closed == r2.ledgers_closed
        assert r1.passed and r2.passed


# ---------------------------------------------------------------------------
# symmetric severance
# ---------------------------------------------------------------------------

class TestSymmetricDisconnect:
    def test_disconnect_closes_both_ends(self):
        from stellar_core_tpu.overlay.peer import Peer
        sim = make_core_topology(2)
        a, b = sim.nodes
        sim.connect(a, b)
        pair = sim._connections[frozenset((a.node_id, b.node_id))]
        sim.disconnect(a, b)
        assert pair[0].state == Peer.CLOSING
        assert pair[1].state == Peer.CLOSING

    def test_disconnect_after_one_end_self_dropped_closes_other(self):
        """drop() on an already-CLOSING peer is a no-op that never reaches
        its partner — the old single-ended disconnect leaked the partner
        half-open here."""
        from stellar_core_tpu.overlay.peer import Peer
        sim = make_core_topology(2)
        a, b = sim.nodes
        sim.connect(a, b)
        key = frozenset((a.node_id, b.node_id))
        pa, pb = sim._connections[key]
        # one end drops itself with the pair already unlinked (the shape a
        # ban/overlay error path produces mid-teardown)
        pa.partner = None
        pb.partner = None
        pa.drop("self drop")
        assert pb.state != Peer.CLOSING   # the would-be leak
        sim.disconnect(a, b)
        assert pa.state == Peer.CLOSING and pb.state == Peer.CLOSING
        # flapping redial replaces the severed pair instead of refusing
        sim.connect(a, b)
        assert sim.is_connected(a, b)


# ---------------------------------------------------------------------------
# sim-node health (main/status reuse)
# ---------------------------------------------------------------------------

class TestSimNodeHealth:
    def test_partitioned_minority_degrades_then_recovers(self):
        sim = make_core_topology(4, threshold=3)
        sim.start_all_nodes()
        assert sim.crank_until_ledger(2, timeout=60)
        loner, rest = sim.nodes[0], sim.nodes[1:]
        assert loner.evaluate_health()["status"] == "ok"
        sim.partition_nodes([[loner], rest])
        # stall past 2x the close target: ledger age pushes it degraded
        start = min(n.lcl for n in rest)
        assert sim.crank_until(
            lambda: all(n.lcl >= start + 3 for n in rest), timeout=120)
        health = loner.evaluate_health()
        assert health["status"] == "degraded"
        assert any("ledger age" in r for r in health["reasons"])
        assert any("peers" in r for r in health["reasons"])
        # the healthy majority stayed healthy
        assert rest[0].evaluate_health()["status"] == "ok"
        sim.heal_partitions()
        target = max(n.lcl for n in rest) + 2
        assert sim.crank_until(
            lambda: all(n.lcl >= target for n in sim.nodes), timeout=240)
        assert loner.evaluate_health()["status"] == "ok"
        assert sim.hashes_agree()


# ---------------------------------------------------------------------------
# scenario catalogue — small tier (tier-1-eligible; `make chaos`)
# ---------------------------------------------------------------------------

class TestSmallScenarios:
    def test_link_degradation_survives_fault_ramp(self):
        res = run_scenario(C.scenario_link_degradation(12))
        assert res.passed, res.violations
        # the ramp is real (faults persist across redials), so progress
        # slows — the liveness assertion inside the run already proves no
        # stall; this floor just proves consensus moved through the ramp
        assert res.ledgers_closed >= 4

    def test_stall_rejoin_reconverges(self):
        res = run_scenario(C.scenario_stall_rejoin(4, 3))
        assert res.passed, res.violations
        assert len(res.recoveries) == 1
        assert res.recoveries[0]["recovery_s"] < 60.0
        # the stalled node (index 0) actually exercised the recovery
        # machinery — it fell out of sync and/or applied buffered
        # externalize values — rather than reconverging by some route
        # that would leave the herder recovery paths untested
        stats = res.node_records[0]["recovery_stats"]
        assert stats["out_of_sync"] >= 1 or stats["buffered_applied"] >= 1, \
            stats

    def test_corrupt_flood_fail_stops_never_forks(self):
        res = run_scenario(C.scenario_corrupt_flood(4, 3))
        assert res.passed, res.violations
        # the corrupted frames actually went out
        assert any("corrupt-flood sent" in m for _, m in res.event_trace)

    def test_cycle_partition_heals(self):
        res = run_scenario(C.scenario_cycle_partition(12))
        assert res.passed, res.violations
        assert len(res.recoveries) == 1

    def test_asymmetric_tier_partition(self):
        res = run_scenario(C.scenario_asym_tier_partition(4, 3, 6))
        assert res.passed, res.violations

    def test_quorum_split_detected_as_liveness_failure(self, tmp_path):
        """The intentionally-broken scenario: a quorum-splitting partition
        must be DETECTED (liveness violation) and emit a replayable
        artifact carrying the RNG seed, the fault schedule and per-node
        flight records."""
        sc = C.scenario_quorum_split(4, 3)
        assert sc.expect_failure == "liveness"
        res = run_scenario(sc, artifact_dir=str(tmp_path))
        assert not res.passed
        assert {v.kind for v in res.violations} == {"liveness"}
        art = json.load(open(res.artifact_path))
        assert art["seed"] == sc.seed
        assert any("Partition" in s for s in art["schedule"])
        assert len(art["node_records"]) == 12
        for rec in art["node_records"]:
            assert "recent_closes" in rec and "herder_state" in rec

    def test_catalogue_entries_build_and_are_unique(self):
        """The catalogue lists are the single enumeration bench.py
        iterates: every entry must construct a valid scenario with a
        positive wall-clock estimate, names must be unique, and the
        flagship must be in the small tier — so catalogue drift breaks
        here instead of silently losing bench coverage."""
        names = []
        for make, est in C.SMALL_SCENARIOS + C.SOAK_SCENARIOS:
            sc = make()
            assert isinstance(sc, ChaosScenario) and sc.schedule
            assert est > 0.0
            names.append(sc.name)
        assert len(names) == len(set(names)), names
        assert "partition-flap-heal-51" in names
        small = [make().name for make, _ in C.SMALL_SCENARIOS]
        assert all(n not in small
                   for n in (m().name for m, _ in C.SOAK_SCENARIOS))

    def test_50_node_partition_flap_heal(self):
        """The flagship 51-validator hierarchical campaign: minority
        partition -> flapping cut -> heal; zero safety violations, the
        majority keeps closing throughout, and the fleet reconverges
        within the recovery budget with a finite measured recovery."""
        res = run_scenario(C.scenario_partition_flap_heal(17, 3))
        assert res.passed, res.violations
        assert res.nodes == 51
        assert res.ledgers_closed >= 7
        assert len(res.recoveries) == 1
        assert 0.0 <= res.recoveries[0]["recovery_s"] \
            <= 12 * 5.0   # recovery_close_targets * close target
        # every node record is healthy at campaign end
        assert all(r["health"] == "ok" for r in res.node_records)


# ---------------------------------------------------------------------------
# byzantine fault family (ISSUE 12 tentpole)
# ---------------------------------------------------------------------------

class TestByzantine:
    def test_equivocation_healthy_intersection_never_forks(self):
        """A signing validator equivocating (different value per peer
        group, same slot/ballot), another emitting conflicting
        nominations, plus stale-slot replays — in a topology where
        quorum intersection HOLDS.  SCP's safety claim: honest nodes
        never externalize divergent hashes; the runner's per-crank
        safety assertion is the proof.  Stale replays must be binned by
        the receivers' slot-memory window check (metered + flight
        recorded)."""
        from stellar_core_tpu.util.metrics import registry
        meter = registry().meter("herder.scp.envelope-discarded")
        d0 = meter.count
        res = run_scenario(C.scenario_byzantine_equivocation(4, 3))
        assert res.passed, res.violations
        byz = {r["node"]: r["byzantine"] for r in res.node_records
               if "byzantine" in r}
        assert set(byz) == {1, 3}
        assert byz[1]["equivocal_sent"] > 0
        assert byz[1]["stale_replayed"] > 0
        # every replayed stale envelope was discarded at the window
        # check — visible on the meter (satellite: the silent dead-end
        # is silent no more)
        assert meter.count - d0 >= byz[1]["stale_replayed"]
        # ... and in the flight recorder, with the reason attached
        events = [e for e in eventlog.event_log().snapshot()
                  if e["msg"] == "scp envelope discarded"]
        assert any(e["fields"].get("reason") == "below-memory-window"
                   for e in events)
        # honest nodes all finished healthy and tracking
        honest = [r for r in res.node_records if r["node"] not in byz]
        assert all(r["herder_state"] == "tracking" for r in honest)

    def test_intersection_violation_fork_flagged_with_artifact(
            self, tmp_path):
        """The generated intersection-violation axis: two disjoint
        near-quorums bridged by one equivocating signing validator MUST
        fork — and the safety checker must flag it against the honest
        nodes' divergent closes (never the adversary's own bookkeeping),
        with a replayable artifact."""
        sc = C.scenario_intersection_violation(2)
        assert sc.expect_failure == "safety"
        res = run_scenario(sc, artifact_dir=str(tmp_path))
        assert not res.passed
        assert {v.kind for v in res.violations} == {"safety"}
        # the fork is attributed to honest B-side nodes (2/3), never to
        # the byzantine bridge (node 4)
        for v in res.violations:
            assert "node 4 " not in v.detail
        art = json.load(open(res.artifact_path))
        assert any("ByzantineNode" in s for s in art["schedule"])
        bridge = art["node_records"][-1]
        assert bridge["byzantine"]["equivocal_sent"] > 0
        assert res.crash_bundle_path and os.path.exists(
            res.crash_bundle_path)

    def test_variant_statements_are_sane_and_properly_signed(self):
        """Equivocal variants must be indistinguishable from honest
        statements at the envelope layer: structurally sane and carrying
        a valid signature from the node's REAL key — otherwise receivers
        would just drop them and the fault would test nothing."""
        from stellar_core_tpu.scp.ballot import BallotProtocol
        sim = make_core_topology(4, seed=3)
        links = C.mesh_links(4)
        sc = _mini_core_scenario(3, [], n=4)
        runner = ChaosRunner(sc)
        runner.sim, runner.base_links = sim, links
        for key in links:
            ia, ib = tuple(key)
            sim.connect(sim.nodes[ia], sim.nodes[ib])
        sim.start_all_nodes(mesh=False)
        assert sim.crank_until_ledger(2, timeout=60)
        engine = C._ByzantineEngine(runner, 0)
        engine.equivocate = True
        node = sim.nodes[0]
        env = None
        for idx in sorted(node.herder.scp.slots, reverse=True):
            slot = node.herder.scp.slots[idx]
            env = slot.ballot.last_envelope or slot.nomination.last_envelope
            if env is not None:
                break
        assert env is not None
        variant = engine._variant(env, 1)
        assert variant is not env
        st = variant.statement
        if st.pledges.type != C.SX.SCPStatementType.SCP_ST_NOMINATE:
            assert BallotProtocol._sane(st)
        # a DIFFERENT statement for the same slot, same node...
        assert st.slotIndex == env.statement.slotIndex
        assert st.to_xdr() != env.statement.to_xdr()
        # ...that verifies under the node's real validator key
        assert node.herder.verify_envelope(variant)


# ---------------------------------------------------------------------------
# in-sim archive recovery (ISSUE 12 tentpole)
# ---------------------------------------------------------------------------

class TestArchiveRecovery:
    def test_stall_past_slot_memory_retracks_via_archive(self):
        """The full incident shape, asserted end to end: stall past
        MAX_SLOTS_TO_REMEMBER -> SCP-state pull dead-ends -> REAL
        archive catchup (published by the healthy fleet in-sim) ->
        adoption -> buffered-externalize bridge -> re-tracking."""
        from stellar_core_tpu.history.archive import checkpoint_frequency
        res = run_scenario(C.scenario_archive_recovery(4, 3))
        assert res.passed, res.violations
        assert len(res.recoveries) == 1
        stalled = res.node_records[-1]
        stats = stalled["recovery_stats"]
        assert stats["archive_catchups"] == 1, stats
        assert stats["out_of_sync"] >= 1
        assert stalled["herder_state"] == "tracking"
        assert stalled["health"] == "ok"
        # the campaign-scoped checkpoint cadence was restored
        assert checkpoint_frequency() == 64
        # the handoff left its flight-recorder trail
        msgs = [e["msg"] for e in eventlog.event_log().snapshot()]
        assert "sim archive catchup start" in msgs
        assert "sim archive state adopted" in msgs

    def test_recovery_via_parallel_catchup_workers(self):
        """Same handoff through the `catchup --parallel` route: real
        range-worker subprocesses seeded by assume-state, stitch-proven,
        then adopted into the live sim node."""
        res = run_scenario(C.scenario_archive_recovery(4, 3, parallel=2))
        assert res.passed, res.violations
        stalled = res.node_records[-1]
        assert stalled["recovery_stats"]["archive_catchups"] == 1
        assert stalled["herder_state"] == "tracking"

    def test_catching_up_health_status_is_distinct(self):
        """/health during archive catchup answers the DISTINCT
        "catching-up" status (vs plain degraded out-of-sync) and flips
        back to ok once the node re-tracks."""
        sim = make_core_topology(3, seed=1)
        sim.start_all_nodes()
        assert sim.crank_until_ledger(2, timeout=60)
        node = sim.nodes[0]
        assert node.evaluate_health()["status"] == "ok"
        node.status.set_status("history-catchup",
                               "catching up from archive to 64")
        doc = node.evaluate_health()
        assert doc["status"] == "catching-up"
        assert doc["checks"]["catching_up"] is True
        assert any("catching up from archive" in r for r in doc["reasons"])
        assert not node.is_healthy()   # load balancers route around it
        node.status.clear_status("history-catchup")
        assert node.evaluate_health()["status"] == "ok"

    def test_publish_floor_skips_straddled_checkpoint(self, tmp_path):
        """After adoption the recovering node has NO artifacts for the
        skipped range: HistoryManager.resume_from must skip the boundary
        whose window straddles the adoption instead of publishing a
        stream with holes (which would poison later catchups)."""
        from stellar_core_tpu.history import archive as A
        from stellar_core_tpu.history.manager import HistoryManager
        from stellar_core_tpu.simulation.loadgen import LoadGenerator
        from stellar_core_tpu.ledger.manager import LedgerManager
        from stellar_core_tpu.crypto.sha import sha256
        prev = A.checkpoint_frequency()
        A.set_checkpoint_frequency(8)
        try:
            archive = A.FileHistoryArchive(str(tmp_path))
            mgr = LedgerManager(sha256(b"floor net"))
            mgr.start_new_ledger()
            hm = HistoryManager(mgr, "floor net", [archive])
            gen = LoadGenerator(mgr, history=hm)
            while mgr.last_closed_ledger_seq < 9:
                gen.close_empty_ledger()
            assert hm.published_checkpoints == [7]
            # adoption at ledger 12: the node skipped 10..12
            hm.resume_from(13)
            while mgr.last_closed_ledger_seq < 18:
                gen.close_empty_ledger()
            # boundary 15 straddles the hole -> skipped; the NEXT full
            # window (boundary 23) publishes again
            assert hm.published_checkpoints == [7]
            while mgr.last_closed_ledger_seq < 24:
                gen.close_empty_ledger()
            assert hm.published_checkpoints == [7, 23]
        finally:
            A.set_checkpoint_frequency(prev)


# ---------------------------------------------------------------------------
# soak tier (-m slow): 100-300 nodes
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestSoaks:
    def test_100_node_hierarchical_partition_flap_heal(self):
        res = run_scenario(C.scenario_partition_flap_heal(34, 3))
        assert res.passed, res.violations
        assert res.nodes == 102
        assert len(res.recoveries) == 1
        assert res.recoveries[0]["recovery_s"] < 12 * 5.0

    def test_large_soak_every_fault_class(self):
        """150 nodes by default; STPU_CHAOS_SOAK_ORGS=100 escalates to
        the 300-node variant (offline-scale — per-envelope SCP cost grows
        ~n^2 with fleet size; see ROADMAP item 5 follow-ups)."""
        orgs = int(os.environ.get("STPU_CHAOS_SOAK_ORGS", "50"))
        res = run_scenario(C.scenario_soak(orgs, 3))
        assert res.passed, res.violations
        assert res.nodes == orgs * 3
        assert len(res.recoveries) == 1
