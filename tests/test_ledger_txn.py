"""LedgerTxn tests (reference: src/ledger/test/LedgerTxnTests.cpp):
nested commit/rollback, child sealing, header transactionality."""

import pytest

from stellar_core_tpu import xdr as X
from stellar_core_tpu.ledger.ledger_txn import (LedgerTxn, LedgerTxnError,
                                                LedgerTxnRoot)


def _header(seq=1):
    return X.LedgerHeader(
        ledgerVersion=23, previousLedgerHash=b"\x00" * 32,
        scpValue=X.StellarValue(txSetHash=b"\x00" * 32, closeTime=0),
        txSetResultHash=b"\x00" * 32, bucketListHash=b"\x00" * 32,
        ledgerSeq=seq, totalCoins=10 ** 15, feePool=0, inflationSeq=0,
        idPool=0, baseFee=100, baseReserve=100000000, maxTxSetSize=100,
        skipList=[b"\x00" * 32] * 4)


def _entry(n, balance=100):
    return X.LedgerEntry(
        lastModifiedLedgerSeq=1,
        data=X.LedgerEntryData.account(X.AccountEntry(
            accountID=X.AccountID.ed25519(bytes([n]) * 32),
            balance=balance, seqNum=1)))


def _key(n):
    return X.ledger_entry_key(_entry(n))


def test_create_commit_visible_in_root():
    root = LedgerTxnRoot(_header())
    with LedgerTxn(root) as ltx:
        ltx.create(_entry(1))
        ltx.commit()
    assert root.get_entry(_key(1).to_xdr()) is not None
    assert root.entry_count() == 1


def test_rollback_discards():
    root = LedgerTxnRoot(_header())
    with LedgerTxn(root) as ltx:
        ltx.create(_entry(1))
        ltx.rollback()
    assert root.entry_count() == 0


def test_implicit_rollback_on_scope_exit():
    root = LedgerTxnRoot(_header())
    with LedgerTxn(root) as ltx:
        ltx.create(_entry(1))
    assert root.entry_count() == 0


def test_nested_commit_and_rollback():
    root = LedgerTxnRoot(_header())
    outer = LedgerTxn(root)
    outer.create(_entry(1))
    inner = LedgerTxn(outer)
    inner.create(_entry(2))
    inner.commit()
    inner2 = LedgerTxn(outer)
    inner2.create(_entry(3))
    inner2.rollback()
    outer.commit()
    assert root.entry_count() == 2
    assert root.get_entry(_key(3).to_xdr()) is None


def test_parent_sealed_while_child_active():
    root = LedgerTxnRoot(_header())
    outer = LedgerTxn(root)
    LedgerTxn(outer)
    with pytest.raises(LedgerTxnError):
        outer.load(_key(1))
    with pytest.raises(LedgerTxnError):
        LedgerTxn(outer)  # only one child
    outer.rollback()  # cascades to child


def test_update_erase_semantics():
    root = LedgerTxnRoot(_header())
    with LedgerTxn(root) as ltx:
        ltx.create(_entry(1, balance=100))
        e = ltx.load(_key(1))
        acct = e.data.value.copy(balance=50)
        ltx.update(e.copy(data=X.LedgerEntryData.account(acct)))
        ltx.commit()
    assert root.get_entry(_key(1).to_xdr()).data.value.balance == 50
    with LedgerTxn(root) as ltx:
        ltx.erase(_key(1))
        with pytest.raises(LedgerTxnError):
            ltx.erase(_key(1))  # already gone in this view
        ltx.commit()
    assert root.entry_count() == 0


def test_load_returns_copy_not_alias():
    root = LedgerTxnRoot(_header())
    with LedgerTxn(root) as ltx:
        ltx.create(_entry(1, balance=100))
        e = ltx.load(_key(1))
        e.data.value.balance = 999  # mutate the copy only
        assert ltx.load(_key(1)).data.value.balance == 100
        ltx.rollback()


def test_header_transactional():
    root = LedgerTxnRoot(_header(seq=5))
    with LedgerTxn(root) as ltx:
        h = ltx.load_header()
        ltx.commit_header(h.copy(ledgerSeq=6))
        ltx.rollback()
    assert root.get_header().ledgerSeq == 5
    with LedgerTxn(root) as ltx:
        h = ltx.load_header()
        ltx.commit_header(h.copy(ledgerSeq=6))
        ltx.commit()
    assert root.get_header().ledgerSeq == 6


def test_create_existing_fails():
    root = LedgerTxnRoot(_header())
    with LedgerTxn(root) as ltx:
        ltx.create(_entry(1))
        with pytest.raises(LedgerTxnError):
            ltx.create(_entry(1))
        ltx.rollback()
