"""Work framework tests (virtual-time, deterministic).

Reference test model: src/work/test/WorkTests.cpp — success/failure
propagation, retries with backoff, sequences, batch concurrency bounds,
abort.
"""

from stellar_core_tpu.util.clock import ClockMode, VirtualClock
from stellar_core_tpu.work import (BasicWork, BatchWork, ConditionalWork,
                                   State, Work, WorkScheduler, WorkSequence,
                                   function_work)


def make_sched():
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    return clock, WorkScheduler(clock)


class CountedWork(BasicWork):
    """Succeeds after `steps` cranks; optionally fails `fail_times` first."""

    def __init__(self, clock, name="counted", steps=3, fail_times=0,
                 max_retries=5):
        super().__init__(clock, name, max_retries)
        self.steps = steps
        self.fail_times = fail_times
        self.runs = 0
        self.resets = 0

    def on_reset(self):
        self.runs = 0
        self.resets += 1

    def on_run(self):
        self.runs += 1
        if self.runs < self.steps:
            return State.RUNNING
        if self.fail_times > 0:
            self.fail_times -= 1
            return State.FAILURE
        return State.SUCCESS


class TestBasicWork:
    def test_simple_success(self):
        clock, sched = make_sched()
        w = CountedWork(clock, steps=4)
        assert sched.execute(w)
        assert w.state == State.SUCCESS
        assert w.runs == 4

    def test_failure_exhausts_retries(self):
        clock, sched = make_sched()
        w = CountedWork(clock, steps=1, fail_times=99, max_retries=3)
        assert not sched.execute(w)
        assert w.state == State.FAILURE
        assert w.resets == 4  # initial + 3 retries

    def test_retry_then_success(self):
        clock, sched = make_sched()
        w = CountedWork(clock, steps=2, fail_times=2, max_retries=5)
        t0 = clock.now()
        assert sched.execute(w)
        # two retries: backoff 1s + 2s of virtual time must have elapsed
        assert clock.now() - t0 >= 3.0
        assert w.resets == 3

    def test_raising_work_fails(self):
        clock, sched = make_sched()

        class Boom(BasicWork):
            def on_run(self):
                raise ValueError("boom")

        w = Boom(clock, "boom", max_retries=0)
        assert not sched.execute(w)
        assert w.state == State.FAILURE

    def test_abort(self):
        clock, sched = make_sched()
        w = CountedWork(clock, steps=10**9)
        sched.schedule(w)
        clock.crank(block=False)
        w.shutdown()
        clock.crank_until(lambda: w.done, 10)
        assert w.state == State.ABORTED


class TestWorkChildren:
    def test_parent_waits_for_children(self):
        clock, sched = make_sched()

        class Parent(Work):
            def __init__(self, clock):
                super().__init__(clock, "parent")
                self.did_own_work = False

            def do_work(self):
                self.did_own_work = True
                return State.SUCCESS

        p = Parent(clock)
        kids = [CountedWork(clock, f"kid{i}", steps=i + 2) for i in range(3)]
        sched.schedule(p)
        for k in kids:
            p.add_work(k)
        clock.crank_until(lambda: p.done, 60)
        assert p.succeeded and p.did_own_work
        assert all(k.succeeded for k in kids)

    def test_child_failure_fails_parent(self):
        clock, sched = make_sched()
        p = Work(clock, "parent", max_retries=0)
        p_ok = CountedWork(clock, "ok", steps=2)
        p_bad = CountedWork(clock, "bad", steps=1, fail_times=9, max_retries=1)
        sched.schedule(p)
        p.add_work(p_ok)
        p.add_work(p_bad)
        clock.crank_until(lambda: p.done, 60)
        assert p.failed


class TestWorkSequence:
    def test_runs_in_order(self):
        clock, sched = make_sched()
        order = []

        def step(i):
            def fn():
                order.append(i)
                return True
            return function_work(clock, f"s{i}", fn)

        seq = WorkSequence(clock, "seq", [step(i) for i in range(5)])
        assert sched.execute(seq)
        assert order == list(range(5))

    def test_stops_on_failure(self):
        clock, sched = make_sched()
        order = []

        def step(i, ok=True):
            def fn():
                order.append(i)
                return ok
            return function_work(clock, f"s{i}", fn)

        seq = WorkSequence(clock, "seq",
                           [step(0), step(1, ok=False), step(2)])
        assert not sched.execute(seq)
        assert order == [0, 1]


class TestBatchWork:
    def test_concurrency_bound(self):
        clock, sched = make_sched()
        in_flight = [0]
        peak = [0]

        class Job(BasicWork):
            def __init__(self, clock, i):
                super().__init__(clock, f"job{i}", max_retries=0)
                self.ticks = 0

            def on_reset(self):
                in_flight[0] += 1
                peak[0] = max(peak[0], in_flight[0])

            def on_run(self):
                self.ticks += 1
                if self.ticks < 3:
                    return State.RUNNING
                in_flight[0] -= 1
                return State.SUCCESS

        jobs = (Job(clock, i) for i in range(20))
        bw = BatchWork(clock, "batch", jobs, max_concurrency=4)
        assert sched.execute(bw)
        assert peak[0] <= 4
        assert in_flight[0] == 0

    def test_batch_failure(self):
        clock, sched = make_sched()
        jobs = iter([CountedWork(clock, "a", steps=1),
                     CountedWork(clock, "b", steps=1, fail_times=5,
                                 max_retries=0)])
        bw = BatchWork(clock, "batch", jobs, max_concurrency=2)
        assert not sched.execute(bw)


class TestConditionalWork:
    def test_waits_for_condition(self):
        clock, sched = make_sched()
        gate = [False]
        inner = CountedWork(clock, steps=2)
        cw = ConditionalWork(clock, "cond", lambda: gate[0], inner)
        sched.schedule(cw)
        clock.crank_for(2.0)
        assert not cw.done and inner.state == State.PENDING
        gate[0] = True
        clock.crank_until(lambda: cw.done, 30)
        assert cw.succeeded and inner.succeeded
