"""Order-book engine + offer/path-payment/pool op tests.

Mirrors reference coverage in src/transactions/test/{OfferTests,
ExchangeTests, PathPaymentTests, PathPaymentStrictSendTests,
LiquidityPoolDepositTests, LiquidityPoolWithdrawTests,
LiquidityPoolTradeTests}.cpp, driven through LedgerManager.close_ledger.
"""

import pytest

from stellar_core_tpu import xdr as X
from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.ledger.manager import LedgerManager
from stellar_core_tpu.transactions.offer_exchange import (
    ExchangeResultV10, ROUND_NORMAL, ROUND_PATH_STRICT_RECEIVE,
    ROUND_PATH_STRICT_SEND, adjust_offer, exchange_v10, pool_id_for,
    pool_swap_in_given_out, pool_swap_out_given_in)
from stellar_core_tpu.testutils import (TestAccount, change_trust_op,
                                        change_trust_pool_op,
                                        create_account_op,
                                        create_passive_sell_offer_op,
                                        liquidity_pool_deposit_op,
                                        liquidity_pool_withdraw_op,
                                        make_asset, manage_buy_offer_op,
                                        manage_sell_offer_op, network_id,
                                        path_payment_strict_receive_op,
                                        path_payment_strict_send_op,
                                        payment_op)

NID = network_id("tpu-core test network")
P = X.Price


# ---------------------------------------------------------------------------
# exchangeV10 unit tests (reference: ExchangeTests.cpp)

def test_exchange_v10_offer_bigger_than_demand():
    # offer sells 1000 wheat at 2 sheep/wheat; taker has 100 sheep
    r = exchange_v10(P(n=2, d=1), 1000, 10**10, 100, 10**10, ROUND_NORMAL)
    assert r.wheat_stays
    assert r.num_wheat_received == 50          # floor(100/2)
    assert r.num_sheep_send == 100             # exactly the price


def test_exchange_v10_rounding_favors_resting_offer():
    # price 3 sheep / 2 wheat; taker pays 100 sheep -> wheat = floor(200/3)=66
    # sheep recomputed = ceil(66*3/2) = 99 (taker never overpays the price)
    r = exchange_v10(P(n=3, d=2), 10**6, 10**10, 100, 10**10, ROUND_NORMAL)
    assert r.wheat_stays
    assert r.num_wheat_received == 66
    assert r.num_sheep_send == 99
    # effective price paid >= offer price: 99/66 >= 3/2
    assert 99 * 2 >= 3 * 66


def test_exchange_v10_offer_taken_whole():
    r = exchange_v10(P(n=3, d=2), 10, 10**10, 10**6, 10**10, ROUND_NORMAL)
    assert not r.wheat_stays
    assert r.num_wheat_received == 10
    assert r.num_sheep_send == 15              # ceil(10*3/2)


def test_exchange_v10_dust_cancelled():
    # 1 sheep at price 3/1 buys 0 wheat -> whole exchange cancelled
    r = exchange_v10(P(n=3, d=1), 1000, 10**10, 1, 10**10, ROUND_NORMAL)
    assert r.num_wheat_received == 0 and r.num_sheep_send == 0


def test_exchange_v10_strict_send_keeps_send_exact():
    r = exchange_v10(P(n=3, d=2), 10**6, 10**10, 100, 10**10,
                     ROUND_PATH_STRICT_SEND)
    assert r.num_sheep_send == 100             # send side exact
    assert r.num_wheat_received == 66


def test_adjust_offer_drops_dust():
    assert adjust_offer(P(n=3, d=1), 1000, 2) == 0
    assert adjust_offer(P(n=1, d=1), 1000, 10**10) == 1000


def test_price_error_bound_exact_thresholds():
    """checkPriceErrorBound boundary: 99*k <= 100*v <= 101*k (reference:
    OfferExchange.cpp — checkPriceErrorBound, 1% relative error)."""
    from stellar_core_tpu.transactions.offer_exchange import (
        check_price_error_bound)
    price = P(n=100, d=1)
    # k = 100*100 = 10000, v = sheep_send; 9900 <= sheep_send <= 10100
    assert check_price_error_bound(price, 100, 10100, False)
    assert not check_price_error_bound(price, 100, 10101, False)
    assert check_price_error_bound(price, 100, 9900, False)
    assert not check_price_error_bound(price, 100, 9899, False)
    # can_favor_wheat waives only the upper bound
    assert check_price_error_bound(price, 100, 10101, True)
    assert check_price_error_bound(price, 100, 10**15, True)
    assert not check_price_error_bound(price, 100, 9899, True)


def test_exchange_cancelled_when_maker_overpaid_beyond_bound():
    """Near dust, rounding up the sheep leg can overpay the maker by far
    more than 1% — NORMAL rounding must cancel the exchange (reference:
    applyPriceErrorThresholds).  price 3/2, taker wants exactly 1 wheat:
    sheep = ceil(3/2) = 2 -> realized price 2/1 = +33% over 3/2."""
    r = exchange_v10(P(n=3, d=2), 10**6, 1, 10**10, 10**10, ROUND_NORMAL)
    assert r.num_wheat_received == 0 and r.num_sheep_send == 0
    # same exchange at a non-dust size is fine: 100 wheat -> 150 sheep exact
    r = exchange_v10(P(n=3, d=2), 10**6, 100, 10**10, 10**10, ROUND_NORMAL)
    assert r.num_wheat_received == 100 and r.num_sheep_send == 150


def test_strict_receive_may_favor_wheat_beyond_bound():
    """Path strict-receive waives the upper bound: sendMax at the path
    level bounds the sender's cost, so overpaying the resting offer is
    allowed (reference: applyPriceErrorThresholds canFavorWheat)."""
    r = exchange_v10(P(n=3, d=2), 10**6, 1, 10**10, 10**10,
                     ROUND_PATH_STRICT_RECEIVE)
    assert r.num_wheat_received == 1
    assert r.num_sheep_send == 2               # +33% but allowed


def test_strict_send_no_per_exchange_bound_but_dust_cancels():
    """Path strict-send keeps the send amount exact (destMin guards the
    path), so a >1% deviation stands; but a send that buys zero wheat
    still cancels both legs."""
    # 5 sheep at price 3/2: wheat = floor(10/3) = 3, realized 5/3 = +11%
    r = exchange_v10(P(n=3, d=2), 10**6, 10**10, 5, 10**10,
                     ROUND_PATH_STRICT_SEND)
    assert r.num_wheat_received == 3 and r.num_sheep_send == 5
    # 1 sheep at price 3/1 buys 0 wheat -> both legs zero
    r = exchange_v10(P(n=3, d=1), 10**6, 10**10, 1, 10**10,
                     ROUND_PATH_STRICT_SEND)
    assert r.num_wheat_received == 0 and r.num_sheep_send == 0


def test_pool_swap_dust_rounding():
    """Adversarial dust through the constant-product pool: zero-output
    swaps and the reserve edge (reference: CAP-38 exact rounding)."""
    # tiny input into a deep pool disburses zero (floor)
    assert pool_swap_out_given_in(10**12, 10**12, 1) == 0
    # requesting the whole reserve (or more) is unfillable
    assert pool_swap_in_given_out(10**6, 10**6, 10**6) is None
    assert pool_swap_in_given_out(10**6, 10**6, 10**6 + 1) is None
    # one unit out of a deep pool costs at least one unit in (ceil)
    cost = pool_swap_in_given_out(10**12, 10**12, 1)
    assert cost >= 1
    # round-trip never profits the taker: swapping cost back in returns
    # at most the unit taken out
    assert pool_swap_out_given_in(10**12 - 1, 10**12 + cost, 1) <= cost


def test_pool_swap_formulas_round_trip():
    # CAP-38 30bp fee; depositing the strict-receive quote must actually
    # buy the requested amount per the strict-send formula
    X_, Y_ = 10**7, 2 * 10**7
    out = 10**5
    inp = pool_swap_in_given_out(X_, Y_, out)
    assert pool_swap_out_given_in(X_, Y_, inp) >= out
    assert pool_swap_out_given_in(X_, Y_, inp - 1) < out or inp == 1


# ---------------------------------------------------------------------------
# ledger-level fixtures

@pytest.fixture
def mgr():
    m = LedgerManager(NID)
    m.start_new_ledger()
    return m


@pytest.fixture
def root(mgr):
    sk = mgr.root_account_secret()
    acc = mgr.root.get_entry(
        X.LedgerKey.account(X.LedgerKeyAccount(
            accountID=X.AccountID.ed25519(sk.public_key.ed25519))).to_xdr())
    return TestAccount(mgr, sk, acc.data.value.seqNum)


def _close(mgr, *frames, close_time=1000):
    return mgr.close_ledger(list(frames), close_time)


def _result_of(arts, frame):
    for pair in arts.result_entry.txResultSet.results:
        if pair.transactionHash == frame.content_hash():
            return pair.result
    raise AssertionError("tx not in result set")


def _ok(mgr, frame):
    arts = _close(mgr, frame)
    res = _result_of(arts, frame)
    assert res.result.switch == X.TransactionResultCode.txSUCCESS, res
    return res.result.value


def _fail_op(mgr, frame):
    arts = _close(mgr, frame)
    res = _result_of(arts, frame)
    assert res.result.switch in (X.TransactionResultCode.txFAILED,), res
    return res.result.value[0]


def _acc(mgr, account_id):
    e = mgr.root.get_entry(X.LedgerKey.account(
        X.LedgerKeyAccount(accountID=account_id)).to_xdr())
    return e.data.value if e else None


def _tl(mgr, account_id, asset):
    tla = X.TrustLineAsset(asset.switch, asset.value) \
        if asset.switch != X.AssetType.ASSET_TYPE_POOL_SHARE else asset
    e = mgr.root.get_entry(X.LedgerKey.trustLine(X.LedgerKeyTrustLine(
        accountID=account_id, asset=tla)).to_xdr())
    return e.data.value if e else None


def _offers(mgr):
    out = []
    for kb in mgr.root.all_keys():
        k = X.LedgerKey.from_xdr(kb)
        if k.switch == X.LedgerEntryType.OFFER:
            out.append(mgr.root.get_entry(kb).data.value)
    return sorted(out, key=lambda o: o.offerID)


def _new_account(mgr, root, balance=10_000_000_000, tag=0):
    import random
    sk = SecretKey.pseudo_random_for_testing(
        random.Random(mgr.last_closed_ledger_seq * 7919 + tag * 104729 + 7))
    tx = root.tx([create_account_op(
        X.AccountID.ed25519(sk.public_key.ed25519), balance)])
    arts = _close(mgr, tx)
    assert _result_of(arts, tx).result.switch == X.TransactionResultCode.txSUCCESS
    acc = _acc(mgr, X.AccountID.ed25519(sk.public_key.ed25519))
    return TestAccount(mgr, sk, acc.seqNum)


@pytest.fixture
def market(mgr, root):
    """issuer + two traders with EUR/USD trustlines and balances."""
    issuer = _new_account(mgr, root, tag=1)
    a = _new_account(mgr, root, tag=2)
    b = _new_account(mgr, root, tag=3)
    eur = make_asset("EUR", issuer.account_id)
    usd = make_asset("USD", issuer.account_id)
    _ok(mgr, a.tx([change_trust_op(eur), change_trust_op(usd)]))
    _ok(mgr, b.tx([change_trust_op(eur), change_trust_op(usd)]))
    _ok(mgr, issuer.tx([payment_op(a.account_id, eur, 10_000),
                        payment_op(a.account_id, usd, 10_000),
                        payment_op(b.account_id, eur, 10_000),
                        payment_op(b.account_id, usd, 10_000)]))
    return issuer, a, b, eur, usd


# ---------------------------------------------------------------------------
# manage offer

def test_create_offer_rests_on_book(mgr, root, market):
    issuer, a, b, eur, usd = market
    res = _ok(mgr, a.tx([manage_sell_offer_op(eur, usd, 100, 2, 1)]))
    mres = res[0].value.value
    assert mres.switch == X.ManageSellOfferResultCode.MANAGE_SELL_OFFER_SUCCESS
    assert mres.value.offer.switch == X.ManageOfferEffect.MANAGE_OFFER_CREATED
    offers = _offers(mgr)
    assert len(offers) == 1
    assert offers[0].amount == 100 and offers[0].price == X.Price(n=2, d=1)
    # selling liabilities recorded on the EUR line
    tl = _tl(mgr, a.account_id, eur)
    assert tl.ext.value.liabilities.selling == 100
    # offer consumes a subentry
    assert _acc(mgr, a.account_id).numSubEntries == 3


def test_crossing_full_fill(mgr, root, market):
    issuer, a, b, eur, usd = market
    _ok(mgr, a.tx([manage_sell_offer_op(eur, usd, 100, 2, 1)]))
    # b sells 200 USD for EUR at 1/2 EUR per USD -> exactly crosses
    res = _ok(mgr, b.tx([manage_sell_offer_op(usd, eur, 200, 1, 2)]))
    mres = res[0].value.value
    assert mres.switch == X.ManageSellOfferResultCode.MANAGE_SELL_OFFER_SUCCESS
    assert mres.value.offer.switch == X.ManageOfferEffect.MANAGE_OFFER_DELETED
    claimed = mres.value.offersClaimed
    assert len(claimed) == 1
    atom = claimed[0].value
    assert atom.assetSold == eur and atom.amountSold == 100
    assert atom.amountBought == 200
    assert _offers(mgr) == []
    assert _tl(mgr, a.account_id, eur).balance == 9_900
    assert _tl(mgr, a.account_id, usd).balance == 10_200
    assert _tl(mgr, b.account_id, eur).balance == 10_100
    assert _tl(mgr, b.account_id, usd).balance == 9_800
    # liabilities fully released
    assert _acc(mgr, a.account_id).numSubEntries == 2


def test_crossing_partial_fill_keeps_residual(mgr, root, market):
    issuer, a, b, eur, usd = market
    _ok(mgr, a.tx([manage_sell_offer_op(eur, usd, 100, 2, 1)]))
    res = _ok(mgr, b.tx([manage_sell_offer_op(usd, eur, 60, 1, 2)]))
    mres = res[0].value.value
    assert mres.value.offer.switch == X.ManageOfferEffect.MANAGE_OFFER_DELETED
    offers = _offers(mgr)
    assert len(offers) == 1
    assert offers[0].sellerID == a.account_id
    assert offers[0].amount == 70       # 100 - 60/2
    assert _tl(mgr, b.account_id, eur).balance == 10_030


def test_taker_at_worse_price_does_not_cross(mgr, root, market):
    issuer, a, b, eur, usd = market
    _ok(mgr, a.tx([manage_sell_offer_op(eur, usd, 100, 2, 1)]))
    # b bids only 1.5 USD per EUR -> no cross, both offers rest
    res = _ok(mgr, b.tx([manage_sell_offer_op(usd, eur, 150, 2, 3)]))
    mres = res[0].value.value
    assert mres.value.offer.switch == X.ManageOfferEffect.MANAGE_OFFER_CREATED
    assert len(_offers(mgr)) == 2
    assert mres.value.offersClaimed == []


def test_passive_offer_does_not_cross_equal_price(mgr, root, market):
    issuer, a, b, eur, usd = market
    _ok(mgr, a.tx([manage_sell_offer_op(eur, usd, 100, 1, 1)]))
    res = _ok(mgr, b.tx([create_passive_sell_offer_op(usd, eur, 100, 1, 1)]))
    mres = res[0].value.value
    assert mres.value.offer.switch == X.ManageOfferEffect.MANAGE_OFFER_CREATED
    assert len(_offers(mgr)) == 2      # both rest
    # non-passive same-price offer crosses
    res = _ok(mgr, b.tx([manage_sell_offer_op(usd, eur, 50, 1, 1)]))
    assert len(res[0].value.value.value.offersClaimed) == 1


def test_update_and_delete_offer(mgr, root, market):
    issuer, a, b, eur, usd = market
    res = _ok(mgr, a.tx([manage_sell_offer_op(eur, usd, 100, 2, 1)]))
    oid = res[0].value.value.value.offer.value.offerID
    res = _ok(mgr, a.tx([manage_sell_offer_op(eur, usd, 40, 3, 1, offer_id=oid)]))
    assert res[0].value.value.value.offer.switch == \
        X.ManageOfferEffect.MANAGE_OFFER_UPDATED
    offers = _offers(mgr)
    assert offers[0].amount == 40 and offers[0].price == X.Price(n=3, d=1)
    assert offers[0].offerID == oid
    res = _ok(mgr, a.tx([manage_sell_offer_op(eur, usd, 0, 1, 1, offer_id=oid)]))
    assert res[0].value.value.value.offer.switch == \
        X.ManageOfferEffect.MANAGE_OFFER_DELETED
    assert _offers(mgr) == []
    assert _acc(mgr, a.account_id).numSubEntries == 2
    tl = _tl(mgr, a.account_id, eur)
    assert tl.ext.switch == 0 or tl.ext.value.liabilities.selling == 0


def test_update_missing_offer_not_found(mgr, root, market):
    issuer, a, b, eur, usd = market
    op_res = _fail_op(mgr, a.tx([manage_sell_offer_op(eur, usd, 10, 1, 1,
                                                      offer_id=999)]))
    assert op_res.value.value.switch == \
        X.ManageSellOfferResultCode.MANAGE_SELL_OFFER_NOT_FOUND


def test_cross_self_rejected(mgr, root, market):
    issuer, a, b, eur, usd = market
    _ok(mgr, a.tx([manage_sell_offer_op(eur, usd, 100, 2, 1)]))
    op_res = _fail_op(mgr, a.tx([manage_sell_offer_op(usd, eur, 200, 1, 2)]))
    assert op_res.value.value.switch == \
        X.ManageSellOfferResultCode.MANAGE_SELL_OFFER_CROSS_SELF


def test_manage_buy_offer(mgr, root, market):
    issuer, a, b, eur, usd = market
    _ok(mgr, a.tx([manage_sell_offer_op(eur, usd, 100, 2, 1)]))
    # b buys exactly 30 EUR paying USD at up to 2 USD/EUR
    res = _ok(mgr, b.tx([manage_buy_offer_op(usd, eur, 30, 2, 1)]))
    mres = res[0].value.value
    assert mres.switch == X.ManageBuyOfferResultCode.MANAGE_BUY_OFFER_SUCCESS
    assert mres.value.offer.switch == X.ManageOfferEffect.MANAGE_OFFER_DELETED
    assert _tl(mgr, b.account_id, eur).balance == 10_030
    assert _tl(mgr, b.account_id, usd).balance == 10_000 - 60
    assert _offers(mgr)[0].amount == 70


def test_offer_low_reserve(mgr, root, market):
    issuer, a, b, eur, usd = market
    base = mgr.root.get_header().baseReserve
    poor = _new_account(mgr, root, balance=4 * base + 200, tag=9)
    _ok(mgr, poor.tx([change_trust_op(eur), change_trust_op(usd)]))
    _ok(mgr, issuer.tx([payment_op(poor.account_id, eur, 100)]))
    # 2 trustlines consumed the headroom: offer trips the reserve check
    op_res = _fail_op(mgr, poor.tx([manage_sell_offer_op(eur, usd, 10, 1, 1)]))
    assert op_res.value.value.switch == \
        X.ManageSellOfferResultCode.MANAGE_SELL_OFFER_LOW_RESERVE


def test_sell_no_trust(mgr, root, market):
    issuer, a, b, eur, usd = market
    c = _new_account(mgr, root, tag=11)
    op_res = _fail_op(mgr, c.tx([manage_sell_offer_op(eur, usd, 10, 1, 1)]))
    assert op_res.value.value.switch == \
        X.ManageSellOfferResultCode.MANAGE_SELL_OFFER_SELL_NO_TRUST


# ---------------------------------------------------------------------------
# path payments

def test_path_payment_strict_receive_one_hop(mgr, root, market):
    issuer, a, b, eur, usd = market
    _ok(mgr, a.tx([manage_sell_offer_op(eur, usd, 1000, 2, 1)]))
    # b pays c 100 EUR, sending USD through the book (2 USD per EUR)
    c = _new_account(mgr, root, tag=21)
    _ok(mgr, c.tx([change_trust_op(eur)]))
    res = _ok(mgr, b.tx([path_payment_strict_receive_op(
        usd, 300, c.account_id, eur, 100)]))
    pres = res[0].value.value
    assert pres.switch == \
        X.PathPaymentStrictReceiveResultCode.PATH_PAYMENT_STRICT_RECEIVE_SUCCESS
    assert _tl(mgr, c.account_id, eur).balance == 100
    assert _tl(mgr, b.account_id, usd).balance == 10_000 - 200
    assert pres.value.last.amount == 100


def test_path_payment_over_sendmax(mgr, root, market):
    issuer, a, b, eur, usd = market
    _ok(mgr, a.tx([manage_sell_offer_op(eur, usd, 1000, 2, 1)]))
    c = _new_account(mgr, root, tag=22)
    _ok(mgr, c.tx([change_trust_op(eur)]))
    op_res = _fail_op(mgr, b.tx([path_payment_strict_receive_op(
        usd, 150, c.account_id, eur, 100)]))
    assert op_res.value.value.switch == \
        X.PathPaymentStrictReceiveResultCode.PATH_PAYMENT_STRICT_RECEIVE_OVER_SENDMAX


def test_path_payment_too_few_offers(mgr, root, market):
    issuer, a, b, eur, usd = market
    c = _new_account(mgr, root, tag=23)
    _ok(mgr, c.tx([change_trust_op(eur)]))
    op_res = _fail_op(mgr, b.tx([path_payment_strict_receive_op(
        usd, 10**9, c.account_id, eur, 100)]))
    assert op_res.value.value.switch == \
        X.PathPaymentStrictReceiveResultCode.PATH_PAYMENT_STRICT_RECEIVE_TOO_FEW_OFFERS


def test_path_payment_two_hops(mgr, root, market):
    issuer, a, b, eur, usd = market
    # books: XLM->USD (a sells USD for XLM at 1), USD->EUR (a sells EUR for USD at 2)
    xlm = X.Asset.native()
    _ok(mgr, a.tx([manage_sell_offer_op(usd, xlm, 1000, 1, 1),
                   manage_sell_offer_op(eur, usd, 1000, 2, 1)]))
    c = _new_account(mgr, root, tag=24)
    _ok(mgr, c.tx([change_trust_op(eur)]))
    res = _ok(mgr, b.tx([path_payment_strict_receive_op(
        xlm, 10**9, c.account_id, eur, 100, path=[usd])]))
    pres = res[0].value.value
    assert pres.switch == \
        X.PathPaymentStrictReceiveResultCode.PATH_PAYMENT_STRICT_RECEIVE_SUCCESS
    assert _tl(mgr, c.account_id, eur).balance == 100
    assert len(pres.value.offers) == 2


def test_path_payment_strict_send(mgr, root, market):
    issuer, a, b, eur, usd = market
    _ok(mgr, a.tx([manage_sell_offer_op(eur, usd, 1000, 2, 1)]))
    c = _new_account(mgr, root, tag=25)
    _ok(mgr, c.tx([change_trust_op(eur)]))
    res = _ok(mgr, b.tx([path_payment_strict_send_op(
        usd, 200, c.account_id, eur, 90)]))
    pres = res[0].value.value
    assert pres.switch == \
        X.PathPaymentStrictSendResultCode.PATH_PAYMENT_STRICT_SEND_SUCCESS
    assert _tl(mgr, c.account_id, eur).balance == 100
    assert pres.value.last.amount == 100


def test_path_payment_under_destmin(mgr, root, market):
    issuer, a, b, eur, usd = market
    _ok(mgr, a.tx([manage_sell_offer_op(eur, usd, 1000, 2, 1)]))
    c = _new_account(mgr, root, tag=26)
    _ok(mgr, c.tx([change_trust_op(eur)]))
    op_res = _fail_op(mgr, b.tx([path_payment_strict_send_op(
        usd, 200, c.account_id, eur, 101)]))
    assert op_res.value.value.switch == \
        X.PathPaymentStrictSendResultCode.PATH_PAYMENT_STRICT_SEND_UNDER_DESTMIN


# ---------------------------------------------------------------------------
# liquidity pools

@pytest.fixture
def pool(mgr, root, market):
    issuer, a, b, eur, usd = market
    pid = pool_id_for(*sorted([eur, usd], key=lambda x: x.to_xdr()))
    assets = sorted([eur, usd], key=lambda x: x.to_xdr())
    _ok(mgr, a.tx([change_trust_pool_op(assets[0], assets[1])]))
    res = _ok(mgr, a.tx([liquidity_pool_deposit_op(pid, 1000, 4000)]))
    dres = res[0].value.value
    assert dres.switch == \
        X.LiquidityPoolDepositResultCode.LIQUIDITY_POOL_DEPOSIT_SUCCESS
    return pid, assets[0], assets[1]


def _pool_entry(mgr, pid):
    e = mgr.root.get_entry(X.LedgerKey.liquidityPool(
        X.LedgerKeyLiquidityPool(liquidityPoolID=pid)).to_xdr())
    return e.data.value.body.value if e else None


def test_pool_create_deposit(mgr, root, market, pool):
    issuer, a, b, eur, usd = market
    pid, aa, ab = pool
    cp = _pool_entry(mgr, pid)
    assert cp.reserveA == 1000 and cp.reserveB == 4000
    assert cp.totalPoolShares == 2000          # isqrt(1000*4000)
    assert cp.poolSharesTrustLineCount == 1
    tl = _tl(mgr, a.account_id, X.TrustLineAsset.liquidityPoolID(pid))
    assert tl.balance == 2000
    # pool-share trustline costs 2 subentries (2 assets + 2 for the pool line)
    assert _acc(mgr, a.account_id).numSubEntries == 4


def test_pool_second_deposit_proportional(mgr, root, market, pool):
    issuer, a, b, eur, usd = market
    pid, aa, ab = pool
    res = _ok(mgr, a.tx([liquidity_pool_deposit_op(pid, 500, 10_000)]))
    cp = _pool_entry(mgr, pid)
    # binding side is A: 500/1000 of the pool -> shares 1000, B = 2000
    assert cp.reserveA == 1500 and cp.reserveB == 6000
    assert cp.totalPoolShares == 3000


def test_pool_withdraw(mgr, root, market, pool):
    issuer, a, b, eur, usd = market
    pid, aa, ab = pool
    res = _ok(mgr, a.tx([liquidity_pool_withdraw_op(pid, 1000)]))
    wres = res[0].value.value
    assert wres.switch == \
        X.LiquidityPoolWithdrawResultCode.LIQUIDITY_POOL_WITHDRAW_SUCCESS
    cp = _pool_entry(mgr, pid)
    assert cp.reserveA == 500 and cp.reserveB == 2000
    assert cp.totalPoolShares == 1000


def test_pool_withdraw_under_minimum(mgr, root, market, pool):
    issuer, a, b, eur, usd = market
    pid, aa, ab = pool
    op_res = _fail_op(mgr, a.tx([liquidity_pool_withdraw_op(
        pid, 1000, min_a=501)]))
    assert op_res.value.value.switch == \
        X.LiquidityPoolWithdrawResultCode.LIQUIDITY_POOL_WITHDRAW_UNDER_MINIMUM


def test_path_payment_routes_through_pool(mgr, root, market, pool):
    issuer, a, b, eur, usd = market
    pid, aa, ab = pool
    # no order book at all: the pool is the only venue
    c = _new_account(mgr, root, tag=31)
    recv_asset = aa
    send_asset = ab
    _ok(mgr, c.tx([change_trust_op(recv_asset)]))
    res = _ok(mgr, b.tx([path_payment_strict_receive_op(
        send_asset, 10**9, c.account_id, recv_asset, 100)]))
    pres = res[0].value.value
    assert pres.switch == \
        X.PathPaymentStrictReceiveResultCode.PATH_PAYMENT_STRICT_RECEIVE_SUCCESS
    assert len(pres.value.offers) == 1
    assert pres.value.offers[0].switch == \
        X.ClaimAtomType.CLAIM_ATOM_TYPE_LIQUIDITY_POOL
    assert _tl(mgr, c.account_id, recv_asset).balance == 100
    cp = _pool_entry(mgr, pid)
    # pool disbursed 100 of A, received the quoted B amount
    assert cp.reserveA == 900
    from stellar_core_tpu.transactions.offer_exchange import (
        pool_swap_in_given_out)
    assert cp.reserveB == 4000 + pool_swap_in_given_out(4000, 1000, 100)


def test_pool_beats_worse_book_price(mgr, root, market, pool):
    issuer, a, b, eur, usd = market
    pid, aa, ab = pool
    # a terrible book offer: 100 B per A; pool price ~4 B per A -> pool wins
    _ok(mgr, a.tx([manage_sell_offer_op(aa, ab, 1000, 100, 1)]))
    c = _new_account(mgr, root, tag=32)
    _ok(mgr, c.tx([change_trust_op(aa)]))
    res = _ok(mgr, b.tx([path_payment_strict_receive_op(
        ab, 10**9, c.account_id, aa, 100)]))
    pres = res[0].value.value
    assert pres.value.offers[0].switch == \
        X.ClaimAtomType.CLAIM_ATOM_TYPE_LIQUIDITY_POOL
    # the resting book offer was untouched
    assert _offers(mgr)[0].amount == 1000


def test_book_beats_worse_pool_price(mgr, root, market, pool):
    issuer, a, b, eur, usd = market
    pid, aa, ab = pool
    # generous book: 1 B per A; pool wants ~4 B per A -> book wins
    _ok(mgr, a.tx([manage_sell_offer_op(aa, ab, 1000, 1, 1)]))
    c = _new_account(mgr, root, tag=33)
    _ok(mgr, c.tx([change_trust_op(aa)]))
    res = _ok(mgr, b.tx([path_payment_strict_receive_op(
        ab, 10**9, c.account_id, aa, 100)]))
    pres = res[0].value.value
    assert pres.value.offers[0].switch == \
        X.ClaimAtomType.CLAIM_ATOM_TYPE_ORDER_BOOK
    assert _pool_entry(mgr, pid).reserveA == 1000  # pool untouched


def test_pool_share_trustline_delete(mgr, root, market, pool):
    issuer, a, b, eur, usd = market
    pid, aa, ab = pool
    _ok(mgr, a.tx([liquidity_pool_withdraw_op(pid, 2000)]))
    _ok(mgr, a.tx([change_trust_pool_op(aa, ab, limit=0)]))
    assert _pool_entry(mgr, pid) is None
    assert _tl(mgr, a.account_id, X.TrustLineAsset.liquidityPoolID(pid)) is None
    assert _acc(mgr, a.account_id).numSubEntries == 2
