"""Sustained-ingestion suite: AdmissionPipeline + TransactionQueue overload
semantics exercised THROUGH the admission path, back-pressure wiring into
overlay flow control, /health degradation, and the seed-derived load
campaign over BucketListDB.

Reference models: src/herder/test/TransactionQueueTests.cpp (surge
pricing, replace-by-fee, bans), src/overlay/FlowControl (capacity
valve), src/simulation/LoadGenerator (traffic shapes).
"""

import tempfile
from fractions import Fraction

import pytest

from stellar_core_tpu import xdr as X
from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.crypto.sha import sha256
from stellar_core_tpu.herder.admission import AdmissionPipeline
from stellar_core_tpu.herder.tx_queue import (AddResult, BAN_DEPTH,
                                              FEE_MULTIPLIER,
                                              TransactionQueue, eviction_key,
                                              fee_per_op)
from stellar_core_tpu.ledger.manager import LedgerManager
from stellar_core_tpu.testutils import (TestAccount, build_tx,
                                        create_account_op,
                                        native_payment_op)
from stellar_core_tpu.util.clock import ClockMode, VirtualClock


def _fund(lm, root, sks, balance=10**11):
    lm.close_ledger([root.tx([create_account_op(
        X.AccountID.ed25519(sk.public_key.ed25519), balance)
        for sk in sks])], close_time=lm.lcl_header.scpValue.closeTime + 5)
    out = []
    for sk in sks:
        e = lm.root.get_entry(X.LedgerKey.account(X.LedgerKeyAccount(
            accountID=X.AccountID.ed25519(
                sk.public_key.ed25519))).to_xdr())
        out.append(TestAccount(lm, sk, e.data.value.seqNum))
    return out


@pytest.fixture
def env():
    lm = LedgerManager(sha256(b"admission test net"))
    lm.start_new_ledger()
    root_sk = lm.root_account_secret()
    e = lm.root.get_entry(X.LedgerKey.account(X.LedgerKeyAccount(
        accountID=X.AccountID.ed25519(
            root_sk.public_key.ed25519))).to_xdr())
    root = TestAccount(lm, root_sk, e.data.value.seqNum)
    accts = _fund(lm, root, [SecretKey(bytes([i + 1]) * 32)
                             for i in range(12)])
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    clock.crank_for(1.0)   # move off t=0 so the burst detector is sane
    q = TransactionQueue(lm)
    adm = AdmissionPipeline(q, lm, clock, max_backlog=64)
    yield lm, clock, q, adm, accts
    adm.close()


def pay(src, dst, amount=1000, fee=None, n_ops=1):
    ops = [native_payment_op(dst.account_id, amount)] * n_ops
    return src.tx(ops, fee=fee) if fee else src.tx(ops)


def submit_burst(adm, frames, collect=None):
    """Submit without cranking (one burst), then drain; returns the final
    per-frame verdicts delivered through on_result."""
    out = {}
    for f in frames:
        adm.submit(f, on_result=lambda res, h=f.content_hash():
                   out.__setitem__(h, res))
    adm.drain()
    return out


class TestLatencyFloorAndBatching:
    def test_sparse_arrival_is_synchronous(self, env):
        lm, clock, q, adm, accts = env
        f = pay(accts[0], accts[1])
        res = adm.submit(f)
        # idle pipeline: the verdict is the REAL try_add verdict, computed
        # inline on the single-sig path — no deadline wait
        assert res.code == AddResult.STATUS_PENDING
        assert q.size == 1
        assert adm.stats["sync_path"] == 1
        assert adm.depth == 0

    def test_burst_forms_batches_and_delivers_callbacks(self, env):
        lm, clock, q, adm, accts = env
        frames = [pay(a, accts[0]) for a in accts[1:9]]
        verdicts = submit_burst(adm, frames)
        assert q.size == 8
        assert adm.stats["batches"] >= 1
        assert all(v.code == AddResult.STATUS_PENDING
                   for v in verdicts.values())
        assert len(verdicts) == 8

    def test_deadline_flush_bounds_partial_batch_wait(self, env):
        lm, clock, q, adm, accts = env
        adm.submit(pay(accts[0], accts[1]))              # sync (sparse)
        adm.submit(pay(accts[1], accts[0]))              # burst -> pending
        assert adm.depth == 1
        # nothing else arrives: the deadline timer must flush it
        clock.crank_for(adm.flush_delay_s * 2)
        assert adm.depth == 0
        assert q.size == 2

    def test_duplicate_detected_in_pending_batch(self, env):
        lm, clock, q, adm, accts = env
        adm.submit(pay(accts[0], accts[1]))              # sync
        f = pay(accts[1], accts[0])
        assert adm.submit(f).code == AddResult.STATUS_PENDING
        assert adm.submit(f).code == AddResult.STATUS_DUPLICATE
        adm.drain()
        assert q.size == 2

    def test_duplicate_detected_in_inflight_batch(self, env):
        lm, clock, q, adm, accts = env
        adm.submit(pay(accts[0], accts[1]))              # sync
        f = pay(accts[1], accts[0])
        assert adm.submit(f).code == AddResult.STATUS_PENDING
        adm._flush()                                     # dispatched, not
        assert adm._inflight and not adm._pending        # yet collected
        # the original is in flight: a replay must answer DUPLICATE, not
        # burn a second verification behind an optimistic PENDING
        assert adm.submit(f).code == AddResult.STATUS_DUPLICATE
        adm.drain()
        assert q.size == 2
        assert adm.stats["admitted"] == 2

    def test_invalid_tx_verdict_delivered_async(self, env):
        lm, clock, q, adm, accts = env
        adm.submit(pay(accts[0], accts[1]))              # make it busy
        bad = build_tx(lm.network_id, accts[1].secret,
                       accts[1].seq_num + 999,
                       [native_payment_op(accts[0].account_id, 1)])
        got = submit_burst(adm, [bad])
        assert got[bad.content_hash()].code == AddResult.STATUS_ERROR
        assert q.size == 1


class TestOverloadSemantics:
    """tx_queue overload semantics through the admission path (ISSUE 7
    satellite): surge eviction order, replace-by-fee boundary, ban
    expiry."""

    def _fill_queue(self, env, fee=200):
        """Fill the downstream queue to capacity via admission."""
        lm, clock, q, adm, accts = env
        lm.lcl_header.maxTxSetSize = 2   # pool = 4 * 2 = 8
        cap = q._max_queue_size()
        fillers = [pay(a, accts[0], fee=fee) for a in accts[1:1 + cap]]
        verdicts = submit_burst(adm, fillers)
        assert q.size == cap
        assert all(v.code == AddResult.STATUS_PENDING
                   for v in verdicts.values())
        return fillers

    def test_surge_eviction_order_exact_fraction_and_hash_tiebreak(
            self, env):
        lm, clock, q, adm, accts = env
        lm.lcl_header.maxTxSetSize = 2
        cap = q._max_queue_size()
        # graded fees, two equal-rate cheapest txs -> hash tiebreak decides
        lo_a = pay(accts[1], accts[0], fee=100)             # 100/op
        lo_b = pay(accts[2], accts[0], fee=200, n_ops=2)    # 100/op
        rest = [pay(accts[3 + i], accts[0], fee=300 + i)
                for i in range(cap - 2)]
        submit_burst(adm, [lo_a, lo_b] + rest)
        assert q.size == cap
        assert fee_per_op(lo_a) == fee_per_op(lo_b) == Fraction(100, 1)
        # the deterministic victim: lowest fee-per-op, LARGEST hash
        victim = max((lo_a, lo_b), key=lambda f: f.content_hash())
        survivor = lo_a if victim is lo_b else lo_b
        assert max(q.by_hash.values(), key=eviction_key) is victim
        newcomer = pay(accts[11], accts[0], fee=5000)
        got = submit_burst(adm, [newcomer])
        assert got[newcomer.content_hash()].code == \
            AddResult.STATUS_PENDING
        assert victim.content_hash() not in q.by_hash
        assert survivor.content_hash() in q.by_hash
        # the evicted tx is banned (reference: eviction bans)
        assert q.is_banned(victim.content_hash())

    def test_cheaper_than_floor_prefiltered_before_verification(self, env):
        lm, clock, q, adm, accts = env
        self._fill_queue(env, fee=200)
        from stellar_core_tpu.util.metrics import registry
        before = registry().counter("crypto.verify.recompute").value
        cheap = pay(accts[11], accts[0], fee=100)
        res = adm.submit(cheap)
        # surge economics BEFORE verification: try-again-later without
        # spending a single signature verify
        assert res.code == AddResult.STATUS_TRY_AGAIN_LATER
        assert adm.stats["prefiltered"] == 1
        assert registry().counter("crypto.verify.recompute").value == before

    def test_replace_by_fee_exact_10x_boundary(self, env):
        lm, clock, q, adm, accts = env
        a = accts[0]
        f1 = pay(a, accts[1], fee=100)
        assert adm.submit(f1).code == AddResult.STATUS_PENDING
        clock.crank_for(1.0)
        # 10x - 1: refused (same seq as f1 -> a real replacement attempt)
        under = build_tx(lm.network_id, a.secret, f1.seq_num,
                         [native_payment_op(accts[1].account_id, 2)],
                         fee=FEE_MULTIPLIER * 100 - 1)
        got = submit_burst(adm, [pay(accts[2], accts[0]), under])
        assert got[under.content_hash()].code == \
            AddResult.STATUS_TRY_AGAIN_LATER
        clock.crank_for(1.0)
        # exactly 10x: replaces
        exact = build_tx(lm.network_id, a.secret, f1.seq_num,
                         [native_payment_op(accts[1].account_id, 3)],
                         fee=FEE_MULTIPLIER * 100)
        got = submit_burst(adm, [pay(accts[3], accts[0]), exact])
        assert got[exact.content_hash()].code == AddResult.STATUS_PENDING
        assert exact.content_hash() in q.by_hash
        assert f1.content_hash() not in q.by_hash

    def test_ban_depth_expiry_through_admission(self, env):
        lm, clock, q, adm, accts = env
        f = pay(accts[0], accts[1])
        q.ban([f])
        assert adm.submit(f).code == AddResult.STATUS_BANNED
        for _ in range(BAN_DEPTH - 1):
            q.shift()
        assert adm.submit(f).code == AddResult.STATUS_BANNED
        q.shift()   # ban depth exhausted
        clock.crank_for(1.0)
        assert adm.submit(f).code == AddResult.STATUS_PENDING

    def test_overload_answers_try_again_later_and_bounds_depth(self, env):
        lm, clock, q, adm, accts = env
        adm.max_backlog = 8
        adm.backpressure_high = 4
        adm.backpressure_low = 2
        adm.submit(pay(accts[0], accts[1]))             # sync
        shed = 0
        for i in range(30):
            f = build_tx(lm.network_id, accts[1 + i % 10].secret,
                         1_000_000 + i,   # never admitted (bad seq) — but
                         [native_payment_op(accts[0].account_id, 1)])
            res = adm.submit(f)
            assert adm.depth <= adm.max_backlog   # NEVER unbounded
            if res.code == AddResult.STATUS_TRY_AGAIN_LATER:
                shed += 1
        assert shed > 0
        assert adm.stats["overload"] == shed
        adm.drain()
        assert adm.depth == 0


class TestBackpressureValve:
    def test_hysteresis_and_release_hook(self, env):
        lm, clock, q, adm, accts = env
        adm.max_backlog = 64
        adm.backpressure_high = 4
        adm.backpressure_low = 1
        released = []
        adm.on_backpressure_release = lambda: released.append(True)
        adm.submit(pay(accts[0], accts[1]))             # sync
        frames = [pay(accts[1 + i], accts[0]) for i in range(6)]
        for f in frames:
            adm.submit(f)
        assert adm.backpressured          # engaged at >= high
        assert not released
        adm.drain()
        assert not adm.backpressured      # drained through low watermark
        assert released == [True]

    def test_peer_grants_deferred_while_backpressured(self, env):
        """overlay/peer.py defers SEND_MORE grants while admission is
        back-pressured and ships them on release — driven through a fake
        overlay so the valve is tested in isolation."""
        lm, clock, q, adm, accts = env
        from stellar_core_tpu.overlay.peer import (
            FLOW_CONTROL_SEND_MORE_BATCH, Peer)

        class FakeOverlay:
            network_id = lm.network_id
            node_id = b"\x01" * 32
            # batched-transport knobs Peer snapshots at construction
            batching = False
            batch_max_messages = 64
            batch_max_bytes = 128 * 1024

            def __init__(self):
                self.herder = type("H", (), {"admission": adm})()
                self.peer_auth = None

            def flood_grants_paused(self):
                return adm.backpressured

            def _peer_dropped(self, peer):
                pass

        sent = []
        peer = Peer(FakeOverlay(), we_called_remote=True)
        peer.state = Peer.GOT_AUTH
        peer._send_key = b"\x02" * 32
        peer._write_bytes = lambda data: None
        peer.send_message = lambda msg: sent.append(msg)

        adm.backpressured = True
        tx = X.StellarMessage.transaction(
            pay(accts[0], accts[1]).envelope)
        for _ in range(FLOW_CONTROL_SEND_MORE_BATCH):
            peer._account_flood_processing(tx, 100)
        assert not sent                     # grant earned but DEFERRED
        assert peer._deferred_grant == [FLOW_CONTROL_SEND_MORE_BATCH,
                                        100 * FLOW_CONTROL_SEND_MORE_BATCH]
        adm.backpressured = False
        peer.release_deferred_grant()
        assert len(sent) == 1
        sm = sent[0].value
        assert sm.numMessages == FLOW_CONTROL_SEND_MORE_BATCH
        assert peer._deferred_grant is None

    def test_health_degrades_on_sustained_backlog(self, env):
        lm, clock, q, adm, accts = env
        from stellar_core_tpu.herder.herder import HerderState
        from stellar_core_tpu.main.status import (StatusManager,
                                                  evaluate_health)

        class FakeApp:
            herder = type("H", (), {
                "admission": adm, "tx_queue": q,
                "ledger_timespan": 5.0,
                "get_state_human": staticmethod(
                    lambda: HerderState.TRACKING)})()
            overlay = type("O", (), {
                "num_authenticated": staticmethod(lambda: 1)})()
            status = StatusManager()
            bucket_store = None
            config = None

        FakeApp.lm = lm
        FakeApp.clock = clock
        # keep ledger age fresh
        lm.lcl_header.scpValue.closeTime = int(clock.system_now())
        doc = evaluate_health(FakeApp)
        assert doc["status"] == "ok"
        adm.backpressured = True
        doc = evaluate_health(FakeApp)
        assert doc["status"] == "degraded"
        assert any("admission backlog" in r for r in doc["reasons"])
        assert "admission_backlog" in doc["checks"]
        adm.backpressured = False


class TestFloodViaAdmission:
    def test_admitted_frames_flood_once_verified(self, env):
        lm, clock, q, adm, accts = env
        flooded = []
        adm.on_admitted = lambda frame, origin: flooded.append(
            (frame.content_hash(), origin))
        f_sync = pay(accts[0], accts[1])
        adm.submit(f_sync, origin="overlay")
        assert flooded == [(f_sync.content_hash(), "overlay")]
        frames = [pay(accts[1 + i], accts[0]) for i in range(4)]
        bad = build_tx(lm.network_id, accts[11].secret, 999_999,
                       [native_payment_op(accts[0].account_id, 1)])
        submit_burst(adm, frames + [bad])
        hashes = {h for h, _ in flooded}
        assert {f.content_hash() for f in frames} <= hashes
        assert bad.content_hash() not in hashes   # failed admission


class TestHerderWiring:
    def test_enable_admission_routes_recv_transaction(self, env):
        lm, clock, q, adm, accts = env
        from stellar_core_tpu.herder.herder import Herder
        h = Herder(clock, lm, SecretKey(b"\x77" * 32),
                   X.SCPQuorumSet(threshold=1, validators=[
                       X.NodeID.ed25519(
                           SecretKey(b"\x77" * 32).public_key.ed25519)],
                       innerSets=[]))
        flooded = []
        h.tx_flood = lambda frame: flooded.append(frame.content_hash())
        h.enable_admission(batch_size=64, max_backlog=32)
        clock.crank_for(1.0)
        f = pay(accts[0], accts[1])
        res = h.recv_transaction(f)
        assert res.code == AddResult.STATUS_PENDING
        assert f.content_hash() in h.tx_queue.by_hash
        assert flooded == [f.content_hash()]
        h.admission.close()


class TestAccelAdmission:
    def test_accel_batches_seed_verify_cache(self, env, monkeypatch):
        """The accel path dispatches through PreverifyPipeline and seeds
        the verify cache so try_add's SignatureChecker hits instead of
        recomputing.  The device backend is faked with a sodium-exact
        stand-in (the real-kernel differential lives in
        test_accel_ed25519.py) — this test pins the PIPELINE contract:
        warmup off the critical path, dispatch-ahead, cache seeding."""
        lm, clock, q, _adm, accts = env
        from stellar_core_tpu.accel import ed25519 as aed
        from stellar_core_tpu.crypto import keys as ckeys
        from stellar_core_tpu.crypto import sodium

        calls = []

        def fake_async(pks, sigs, msgs, **kw):
            verdicts = [sodium.verify_detached(s, m, p)
                        for p, s, m in zip(pks, sigs, msgs)]
            calls.append(len(pks))
            return lambda: verdicts

        monkeypatch.setattr(aed, "verify_batch_async", fake_async)
        ckeys.clear_verify_cache()
        adm = AdmissionPipeline(q, lm, clock, accel=True,
                                accel_min_sigs=4, batch_size=64,
                                max_backlog=256)
        try:
            # warmup dispatched at construction; completes on the worker
            import time
            deadline = time.monotonic() + 10
            while not adm._preverify.job_done(adm._warm_id) \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            adm.submit(pay(accts[0], accts[1]))          # sync path
            frames = [pay(accts[1 + i], accts[0]) for i in range(8)]
            verdicts = submit_burst(adm, frames)
            assert adm._warmed
            assert all(v.code == AddResult.STATUS_PENDING
                       for v in verdicts.values())
            assert q.size == 9
            # the batch (>= accel_min_sigs) went to the fake device and
            # its verdicts were seeded: try_add hit the cache
            assert any(c >= 8 for c in calls)
            from stellar_core_tpu.util.metrics import registry
            assert adm.stats["sigs_offloaded"] >= 8
            assert registry().counter("crypto.verify.cache-hit").value >= 8
        finally:
            adm.close()


class TestCampaign:
    def test_small_campaign_over_bucketlistdb(self):
        """The tier-1 load campaign: 60k seed-derived accounts installed
        over BucketListDB in O(1) RAM, paced submission through admission,
        overload shed by try-again-later/eviction, bounded everything."""
        from stellar_core_tpu.simulation.loadgen import AdmissionCampaign
        with tempfile.TemporaryDirectory() as d:
            c = AdmissionCampaign(n_accounts=60_000, workdir=d,
                                  install_chunk=15_000,
                                  max_tx_set_ops=300, max_backlog=600)
            try:
                live = c.mgr.root.entry_count()
                assert live == 60_000 + 1   # pool + network root
                rep = c.run(n_ledgers=4, offered_per_ledger=900)
            finally:
                c.close()
            assert rep["applied"] > 0
            assert rep["sustained_tps"] > 0
            # O(1) RAM: decoded entries bounded by the install chunk and
            # the resident top levels, NOT the pool size
            assert rep["peak_decoded_entries"] <= 6 * 15_000
            # bounded queues under ~3x apply overload
            assert rep["peak_queue_depth"] <= 4 * 300
            assert rep["peak_admission_depth"] <= c.admission.max_backlog
            # batching actually happened (not a sync-path degenerate run)
            assert rep.get("batches", 0) > 0
            assert rep["admission_p99_us"] > 0

    @pytest.mark.slow
    def test_million_account_campaign(self):
        """ISSUE 7 acceptance: the million-account campaign completes over
        BucketListDB inside the RSS guard, with overload answered by
        try-again-later/eviction rather than unbounded growth."""
        import resource
        from stellar_core_tpu.simulation.loadgen import AdmissionCampaign
        # ru_maxrss is a process-lifetime high-water mark: when the full
        # suite runs first (chaos soaks, JAX warmup) the peak is already
        # polluted, so the guard bounds the CAMPAIGN'S OWN growth of the
        # peak — standalone (`make loadgen-slow`, fresh interpreter) that
        # IS the absolute guard
        rss0_mb = resource.getrusage(
            resource.RUSAGE_SELF).ru_maxrss // 1024
        with tempfile.TemporaryDirectory() as d:
            c = AdmissionCampaign(n_accounts=1_000_000, workdir=d,
                                  max_tx_set_ops=500, max_backlog=2000)
            try:
                assert c.mgr.root.entry_count() == 1_000_001
                rep = c.run(n_ledgers=4, offered_per_ledger=2500)
            finally:
                c.close()
            assert rep["applied"] > 0
            assert rep["peak_decoded_entries"] <= 6 * 20_000
            assert rep["peak_admission_depth"] <= 2000
            assert rep["peak_queue_depth"] <= 4 * 500
            # the account pool is O(1) RAM: a million accounts must not
            # grow the process past the campaign guard
            rss_mb = resource.getrusage(
                resource.RUSAGE_SELF).ru_maxrss // 1024
            grew_mb = rss_mb - rss0_mb
            assert grew_mb < 4096, (
                f"campaign grew peak RSS by {grew_mb} MB "
                f"({rss0_mb} -> {rss_mb}), exceeding the guard")


class TestOverlayAdmissionSoak:
    def test_loopback_floods_drive_hysteresis_valve_under_sanitizer(self):
        """ISSUE 9 satellite (ROADMAP 3b): the SEND_MORE hysteresis valve
        exercised by WIRE traffic — LoopbackPeer floods feed node B's
        admission pipeline until the backlog crosses the high watermark,
        B's receiving peer defers earned flow-control grants, and the
        drain releases them in one SEND_MORE_EXTENDED restoring A's
        capacity.  The whole soak runs under the race sanitizer so the
        overlay/admission/tx-queue classes are lockset-checked while real
        peer traffic drives them."""
        from stellar_core_tpu.herder.herder import Herder
        from stellar_core_tpu.overlay.overlay_manager import OverlayManager
        from stellar_core_tpu.overlay.peer import (
            PEER_FLOOD_READING_CAPACITY, make_loopback_pair)
        from stellar_core_tpu.simulation.simulation import qset_of
        from stellar_core_tpu.util import lockorder, racetrace

        prev_race = racetrace.enabled()
        prev_lock = lockorder.enabled()
        racetrace.enable()   # BEFORE nodes are built: locks must be traced
        try:
            nid = sha256(b"overlay admission soak")
            clock = VirtualClock(ClockMode.VIRTUAL_TIME)
            sk_a, sk_b = SecretKey(b"\x0a" * 32), SecretKey(b"\x0b" * 32)
            qs = qset_of([sk_a.public_key.ed25519,
                          sk_b.public_key.ed25519], 2)

            def make_node(sk, seed):
                lm = LedgerManager(nid)
                lm.start_new_ledger()
                herder = Herder(clock, lm, sk, qs)
                overlay = OverlayManager(clock, herder, nid, sk,
                                         auth_seed=seed)
                return herder, overlay

            ha, oa = make_node(sk_a, b"a" * 32)
            hb, ob = make_node(sk_b, b"b" * 32)
            # tiny backlog so wire traffic actually trips the valve
            hb.enable_admission(batch_size=100_000, flush_delay_s=30.0,
                                max_backlog=60)
            hb.admission.on_backpressure_release = ob.release_flood_grants
            pa, pb = make_loopback_pair(oa, ob)
            for _ in range(50):
                clock.crank()
            assert pa.is_authenticated() and pb.is_authenticated()

            root_sk = ha.lm.root_account_secret()
            e = ha.lm.root.get_entry(X.LedgerKey.account(
                X.LedgerKeyAccount(accountID=X.AccountID.ed25519(
                    root_sk.public_key.ed25519))).to_xdr())
            root = TestAccount(ha.lm, root_sk, e.data.value.seqNum)
            n_floods = 160
            frames = [root.tx([native_payment_op(root.account_id, 1 + i)])
                      for i in range(n_floods)]

            saw_deferred = False
            engaged = False
            for f in frames:
                pa.send_message(X.StellarMessage.transaction(f.envelope))
                clock.crank()
                saw_deferred = saw_deferred \
                    or pb._deferred_grant is not None
                engaged = engaged or hb.admission.backpressured
            for _ in range(100):
                clock.crank()
                saw_deferred = saw_deferred \
                    or pb._deferred_grant is not None
                engaged = engaged or hb.admission.backpressured
            assert engaged, "wire floods never engaged back-pressure"
            assert saw_deferred, "valve never deferred an earned grant"

            hb.admission.drain()
            for _ in range(50):
                clock.crank()
            assert not hb.admission.backpressured
            assert pb._deferred_grant is None, \
                "release must ship the deferred grant"
            # every processed flood message was eventually granted back:
            # A's capacity returns to the initial allowance
            assert pa._outbound_capacity == PEER_FLOOD_READING_CAPACITY, \
                pa._outbound_capacity
            assert hb.admission.stats["submitted"] >= n_floods // 2
            hb.admission.close()
        finally:
            if not prev_race:
                racetrace.disable()
            if not prev_lock:
                lockorder.disable()
