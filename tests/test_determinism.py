"""Determinism discipline suite (ISSUE 19): the four consensus-path
lint rules — each proven to FIRE on the banned shape and to stay QUIET
on the blessed twin — plus the detguard runtime guard (deterministic
fail-stop repro with the crash bundle asserted) and the hash-seed
divergence harness (divergence pinpointing units + a live paired-
subprocess Soroban differential smoke).
"""

import json
import os
import random
import textwrap
import time

import pytest

from stellar_core_tpu.lint import all_rules, run_paths, rules_by_id
from stellar_core_tpu.lint.rules.determinism import (CONSENSUS_SCOPE,
                                                     RNG_EXTRA_SCOPE,
                                                     in_consensus_scope)
from stellar_core_tpu.simulation import hashseed_diff
from stellar_core_tpu.util import detguard

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DET_RULES = ["iteration-order", "float-discipline", "hash-order",
             "rng-discipline"]

# a consensus-scope relpath and an out-of-scope twin: every fire
# fixture is also checked quiet outside the declared scope
IN_SCOPE = "stellar_core_tpu/scp/mod.py"
OUT_SCOPE = "stellar_core_tpu/overlay/mod.py"


def lint_src(tmp_path, relpath, src, rule_ids=None):
    p = tmp_path / relpath
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    rules = rules_by_id(rule_ids) if rule_ids else all_rules()
    return run_paths([str(tmp_path)], rules, root=str(tmp_path))


def rule_hits(report, rule_id):
    return [v for v in report.violations if v.rule == rule_id]


# ---------------------------------------------------------------------------
# scope declaration
# ---------------------------------------------------------------------------

class TestConsensusScope:
    def test_single_declaration_covers_the_consensus_modules(self):
        # THE greppable declaration: these seven directories are
        # consensus-path; rng-discipline adds the simulation layer
        assert CONSENSUS_SCOPE == (
            "stellar_core_tpu/scp/", "stellar_core_tpu/herder/",
            "stellar_core_tpu/ledger/", "stellar_core_tpu/soroban/",
            "stellar_core_tpu/transactions/", "stellar_core_tpu/bucket/",
            "stellar_core_tpu/xdr/")
        assert RNG_EXTRA_SCOPE == ("stellar_core_tpu/simulation/",)
        assert in_consensus_scope("stellar_core_tpu/scp/ballot.py")
        assert not in_consensus_scope("stellar_core_tpu/overlay/peer.py")
        # segment-aware: robust to linting from a parent root
        assert in_consensus_scope("repo/stellar_core_tpu/ledger/manager.py")

    def test_rules_registered_in_the_full_set(self):
        ids = {r.id for r in all_rules()}
        assert set(DET_RULES) <= ids


# ---------------------------------------------------------------------------
# iteration-order
# ---------------------------------------------------------------------------

class TestIterationOrder:
    FIRE_LOOP = """
        def frames(items):
            out = []
            for x in set(items):
                out.append(x.to_xdr())
            return out
        """

    def test_fires_on_set_loop_into_escaping_list(self, tmp_path):
        rep = lint_src(tmp_path, IN_SCOPE, self.FIRE_LOOP, DET_RULES)
        assert len(rule_hits(rep, "iteration-order")) == 1

    def test_quiet_sorted_twin(self, tmp_path):
        rep = lint_src(tmp_path, IN_SCOPE, """
            def frames(items):
                out = []
                for x in sorted(set(items)):
                    out.append(x.to_xdr())
                return out
            """, DET_RULES)
        assert not rule_hits(rep, "iteration-order")

    def test_quiet_when_accumulator_is_sorted_afterwards(self, tmp_path):
        rep = lint_src(tmp_path, IN_SCOPE, """
            def frames(items):
                out = []
                for x in set(items):
                    out.append(x)
                return sorted(out)
            """, DET_RULES)
        assert not rule_hits(rep, "iteration-order")

    def test_fires_on_items_view_into_yield(self, tmp_path):
        rep = lint_src(tmp_path, IN_SCOPE, """
            def entries(index):
                for k, v in index.items():
                    yield v
            """, DET_RULES)
        hits = rule_hits(rep, "iteration-order")
        assert len(hits) == 1
        assert ".items() view" in hits[0].message

    def test_fires_on_list_over_set_union_quiet_on_sorted(self, tmp_path):
        rep = lint_src(tmp_path, IN_SCOPE, """
            def merged(a, b):
                return list(set(a) | set(b))
            """, DET_RULES)
        assert len(rule_hits(rep, "iteration-order")) == 1
        rep = lint_src(tmp_path, IN_SCOPE, """
            def merged(a, b):
                return sorted(set(a) | set(b))
            """, DET_RULES)
        assert not rule_hits(rep, "iteration-order")

    def test_fires_through_set_valued_local(self, tmp_path):
        rep = lint_src(tmp_path, IN_SCOPE, """
            def flood(peers, msg):
                pending = set(peers)
                for p in pending:
                    p.send_message(msg)
            """, DET_RULES)
        assert len(rule_hits(rep, "iteration-order")) == 1

    def test_quiet_order_free_consumer(self, tmp_path):
        rep = lint_src(tmp_path, IN_SCOPE, """
            def total(fees):
                return sum(f.amount for f in set(fees))
            """, DET_RULES)
        assert not rule_hits(rep, "iteration-order")

    def test_quiet_loop_without_order_sink(self, tmp_path):
        rep = lint_src(tmp_path, IN_SCOPE, """
            def validate(entries):
                for e in set(entries):
                    e.check()
            """, DET_RULES)
        assert not rule_hits(rep, "iteration-order")

    def test_quiet_outside_consensus_scope(self, tmp_path):
        rep = lint_src(tmp_path, OUT_SCOPE, self.FIRE_LOOP, DET_RULES)
        assert not rule_hits(rep, "iteration-order")

    def test_suppression_with_reason(self, tmp_path):
        rep = lint_src(tmp_path, IN_SCOPE, """
            def frames(d):
                out = []
                for k, v in d.items():  # corelint: disable=iteration-order -- insertion order is load-bearing
                    out.append(v)
                return out
            """, DET_RULES)
        assert not rule_hits(rep, "iteration-order")
        assert len(rep.suppressed) == 1


# ---------------------------------------------------------------------------
# float-discipline
# ---------------------------------------------------------------------------

class TestFloatDiscipline:
    def test_fires_on_literal_conversion_and_division(self, tmp_path):
        rep = lint_src(tmp_path, IN_SCOPE, """
            def fee(base, n):
                rate = 0.5
                scaled = float(base)
                return base / n
            """, DET_RULES)
        assert len(rule_hits(rep, "float-discipline")) == 3

    def test_quiet_metric_and_log_sinks(self, tmp_path):
        # the exemption: floats flowing only into observability sinks
        # are monitoring, never protocol state
        rep = lint_src(tmp_path, IN_SCOPE, """
            def close(metrics, log, t0, t1):
                metrics.observe((t1 - t0) / 1000)
                log.debug("close took %s", (t1 - t0) / 1000)
                return f"took {(t1 - t0) / 1000:.2f}s"
            """, DET_RULES)
        assert not rule_hits(rep, "float-discipline")

    def test_integer_math_twin_is_quiet(self, tmp_path):
        rep = lint_src(tmp_path, IN_SCOPE, """
            def fee(base, n):
                return (base * 100) // n
            """, DET_RULES)
        assert not rule_hits(rep, "float-discipline")

    def test_sink_exemption_does_not_cross_function_boundary(self, tmp_path):
        # a float computed in a helper CALLED from a sink still fires:
        # the ancestor walk stops at the enclosing def
        rep = lint_src(tmp_path, IN_SCOPE, """
            def helper(a, b):
                return a / b
            """, DET_RULES)
        assert len(rule_hits(rep, "float-discipline")) == 1

    def test_quiet_outside_consensus_scope(self, tmp_path):
        rep = lint_src(tmp_path, OUT_SCOPE, "x = 0.5\n", DET_RULES)
        assert not rule_hits(rep, "float-discipline")


# ---------------------------------------------------------------------------
# hash-order
# ---------------------------------------------------------------------------

class TestHashOrder:
    def test_fires_on_builtin_hash(self, tmp_path):
        rep = lint_src(tmp_path, IN_SCOPE, """
            def bucket_of(key):
                return hash(key) % 64
            """, DET_RULES)
        assert len(rule_hits(rep, "hash-order")) == 1

    def test_quiet_inside_hash_protocol(self, tmp_path):
        rep = lint_src(tmp_path, IN_SCOPE, """
            class Key:
                def __hash__(self):
                    return hash(self.raw)
            """, DET_RULES)
        assert not rule_hits(rep, "hash-order")

    def test_fires_on_id_keyed_sort(self, tmp_path):
        rep = lint_src(tmp_path, IN_SCOPE, """
            def order(frames):
                frames.sort(key=lambda f: id(f))
            """, DET_RULES)
        assert len(rule_hits(rep, "hash-order")) == 1

    def test_quiet_id_as_lookup_key(self, tmp_path):
        # identity BOOKKEEPING is fine — the scheduler's positions map
        # keyed by id(frame) looks values up, it never orders by address
        rep = lint_src(tmp_path, IN_SCOPE, """
            def order(frames, positions):
                for i, f in enumerate(frames):
                    positions[id(f)] = i
                return sorted(frames, key=lambda f: positions[id(f)])
            """, DET_RULES)
        assert not rule_hits(rep, "hash-order")

    def test_quiet_outside_consensus_scope(self, tmp_path):
        rep = lint_src(tmp_path, OUT_SCOPE, "h = hash('x')\n", DET_RULES)
        assert not rule_hits(rep, "hash-order")


# ---------------------------------------------------------------------------
# rng-discipline
# ---------------------------------------------------------------------------

class TestRngDiscipline:
    def test_fires_on_module_level_draws(self, tmp_path):
        rep = lint_src(tmp_path, IN_SCOPE, """
            import os
            import random
            import uuid
            a = random.random()
            b = os.urandom(16)
            c = uuid.uuid4()
            """, DET_RULES)
        assert len(rule_hits(rep, "rng-discipline")) == 3

    def test_fires_on_aliased_and_from_imports(self, tmp_path):
        rep = lint_src(tmp_path, IN_SCOPE, """
            import random as _r
            from os import urandom
            x = _r.choice([1, 2])
            y = urandom(8)
            """, DET_RULES)
        assert len(rule_hits(rep, "rng-discipline")) == 2

    def test_fires_on_unseeded_random_instance(self, tmp_path):
        rep = lint_src(tmp_path, IN_SCOPE, """
            import random
            rng = random.Random()
            """, DET_RULES)
        assert len(rule_hits(rep, "rng-discipline")) == 1

    def test_quiet_injected_seeded_rng(self, tmp_path):
        # THE blessed shape: a seeded instance threaded through callers
        rep = lint_src(tmp_path, IN_SCOPE, """
            import random

            def build(seed):
                return random.Random(seed)

            def pick(rng, xs):
                return xs[rng.randrange(len(xs))]
            """, DET_RULES)
        assert not rule_hits(rep, "rng-discipline")

    def test_simulation_layer_is_in_rng_scope(self, tmp_path):
        rep = lint_src(tmp_path, "stellar_core_tpu/simulation/mod.py", """
            import random
            random.shuffle([])
            """, DET_RULES)
        assert len(rule_hits(rep, "rng-discipline")) == 1

    def test_quiet_outside_scope(self, tmp_path):
        rep = lint_src(tmp_path, OUT_SCOPE, """
            import random
            x = random.random()
            """, DET_RULES)
        assert not rule_hits(rep, "rng-discipline")


# ---------------------------------------------------------------------------
# whole-tree: the `make determinism` static step
# ---------------------------------------------------------------------------

class TestWholeTreeDeterminism:
    def test_tree_clean_under_the_four_rules(self):
        # mirrors `make determinism` step 1 (the full-rule-set baseline
        # gate lives in test_lint.py::TestWholeTree)
        targets = [os.path.join(REPO_ROOT, "stellar_core_tpu"),
                   os.path.join(REPO_ROOT, "bench.py")]
        rep = run_paths(targets, rules_by_id(DET_RULES), root=REPO_ROOT)
        assert rep.violations == [], \
            "\n".join(v.format() for v in rep.violations)
        # the reviewed order-free/monitoring-only sites exist as
        # reasoned suppressions (counts are pinned by the baseline gate)
        assert {s.rule for s in rep.suppressed} == set(DET_RULES) - {
            "rng-discipline"}  # every rng site was fixable outright


# ---------------------------------------------------------------------------
# detguard: the runtime complement
# ---------------------------------------------------------------------------

@pytest.fixture
def guard():
    detguard.reset_stats()
    yield detguard
    detguard.disable()
    detguard.reset_stats()


@pytest.fixture
def tripping_here(guard, monkeypatch):
    """Widen the tripping roots to THIS test file so calls made directly
    by the test body count as consensus-code calls."""
    monkeypatch.setattr(detguard, "_TRIPPING_ROOTS",
                        ("stellar_core_tpu", "tests/test_determinism"))
    return guard


class TestDetguard:
    def test_region_is_noop_while_disarmed(self, guard):
        with guard.region("ledger-close"):
            time.time()               # no patching, no trip
        assert guard.stats() == {"regions": 0, "trips": 0}
        assert not guard.enabled()

    def test_fail_stop_repro_with_crash_bundle(self, tripping_here,
                                               tmp_path, monkeypatch):
        """THE acceptance repro: a wall-clock read inside a guarded
        region raises DeterminismError and writes a crash bundle naming
        the region and the primitive."""
        monkeypatch.setenv("STPU_CRASH_DIR", str(tmp_path))
        tripping_here.enable()
        with pytest.raises(detguard.DeterminismError) as ei:
            with tripping_here.region("ledger-close"):
                time.time()
        assert "time.time" in str(ei.value)
        assert "ledger-close" in str(ei.value)
        st = tripping_here.stats()
        assert st["trips"] == 1 and st["regions"] == 1
        bundles = list(tmp_path.glob("flight-*.json"))
        assert bundles, "crash bundle must be written before the raise"
        doc = json.loads(bundles[0].read_text())
        assert doc["reason"].startswith("DeterminismError")
        assert "time.time" in doc["reason"]
        assert "ledger-close" in doc["reason"]

    def test_hash_trips_on_str_not_on_int(self, tripping_here):
        tripping_here.enable()
        with tripping_here.region("nomination"):
            assert hash(1234) == hash(1234)     # int hashes are stable
            with pytest.raises(detguard.DeterminismError) as ei:
                hash("node-key")
        assert "hash()" in str(ei.value)

    def test_urandom_and_module_rng_trip(self, tripping_here):
        tripping_here.enable()
        with tripping_here.region("soroban-apply"):
            with pytest.raises(detguard.DeterminismError):
                os.urandom(16)
            with pytest.raises(detguard.DeterminismError):
                random.random()
        assert tripping_here.stats()["trips"] == 2

    def test_seeded_random_instance_is_untouched(self, tripping_here):
        # the injected-RNG shape rng-discipline mandates stays legal at
        # runtime: instance methods never route through the patched
        # module-level functions
        tripping_here.enable()
        rng = random.Random(42)
        with tripping_here.region("ledger-close"):
            vals = [rng.random(), rng.randint(0, 9)]
            xs = [1, 2, 3]
            rng.shuffle(xs)
        assert tripping_here.stats()["trips"] == 0
        assert len(vals) == 2

    def test_no_trip_outside_a_region(self, tripping_here):
        tripping_here.enable()
        time.time()                   # armed, but no region on this thread
        os.urandom(4)
        assert tripping_here.stats()["trips"] == 0

    def test_observability_plane_is_exempt(self, guard):
        # util/clock reads monotonic time on behalf of everyone; with
        # the DEFAULT roots its frames never trip inside a region
        from stellar_core_tpu.util.clock import monotonic_now
        guard.enable()
        with guard.region("ledger-close"):
            assert monotonic_now() >= 0.0
        assert guard.stats()["trips"] == 0

    def test_nesting_and_current_region(self, guard):
        guard.enable()
        assert guard.current_region() is None
        with guard.region("ledger-close"):
            with guard.region("soroban-apply"):
                assert guard.current_region() == "soroban-apply"
            assert guard.current_region() == "ledger-close"
        assert guard.current_region() is None
        assert guard.stats()["regions"] == 2

    def test_disable_restores_originals(self, guard):
        guard.enable()
        assert guard.enabled()
        assert hasattr(time.time, "__wrapped__")
        assert hasattr(random.random, "__wrapped__")
        guard.disable()
        assert not guard.enabled()
        assert not hasattr(time.time, "__wrapped__")
        assert not hasattr(random.random, "__wrapped__")
        guard.enable()                # idempotent re-arm round-trips
        guard.disable()
        assert not hasattr(os.urandom, "__wrapped__")


# ---------------------------------------------------------------------------
# hash-seed divergence harness
# ---------------------------------------------------------------------------

class TestHashseedDiff:
    def test_first_divergence_none_when_equal(self):
        a = {"slot_hashes": {"2": "aa", "3": "bb"}}
        assert hashseed_diff._first_divergence(a, dict(a)) is None

    def test_first_divergence_pinpoints_lowest_slot(self):
        a = {"slot_hashes": {"2": "aa", "3": "bb", "10": "cc"}}
        b = {"slot_hashes": {"2": "aa", "3": "XX", "10": "YY"}}
        d = hashseed_diff._first_divergence(a, b)
        assert d == "slot_hashes[3]: bb != XX"

    def test_first_divergence_list_table_and_length(self):
        a = {"bucket_hashes": ["aa", "bb"]}
        b = {"bucket_hashes": ["aa", "XX"]}
        assert hashseed_diff._first_divergence(a, b) == \
            "bucket_hashes[1]: bb != XX"
        c = {"bucket_hashes": ["aa", "bb", "cc"]}
        assert "length: 2 != 3" in hashseed_diff._first_divergence(a, c)

    def test_first_divergence_outside_table(self):
        a = {"slot_hashes": {"2": "aa"}, "nodes": 51}
        b = {"slot_hashes": {"2": "aa"}, "nodes": 48}
        assert "outside the hash table" in \
            hashseed_diff._first_divergence(a, b)

    def test_soroban_pair_live_smoke(self):
        """Paired subprocesses under PYTHONHASHSEED 0 vs 424242: byte-
        identical bucket hashes, detguard armed in both children with
        regions entered and zero trips."""
        rep = hashseed_diff.run_pair("soroban", ledgers=4, timeout_s=300.0)
        assert rep["errors"] == []
        assert rep["identical"] and rep["divergence"] is None
        assert rep["ok"]
        assert len(rep["detguard"]) == 2
        for g in rep["detguard"]:
            assert g["armed"] and g["regions"] > 0 and g["trips"] == 0
