"""Invariant framework tests.

Reference test model: src/invariant/test/{ConservationOfLumensTests,
AccountSubEntriesCountIsValidTests, LiabilitiesMatchOffersTests,
BucketListIsConsistentWithDatabaseTests}.cpp — each invariant must catch a
deliberately broken apply, and hold on every well-formed close (the latter
is exercised implicitly: InvariantManager defaults on in every LedgerManager
test fixture in this suite).
"""

import pytest

from stellar_core_tpu import xdr as X
from stellar_core_tpu.invariant import (InvariantDoesNotHold,
                                        InvariantManager)
from stellar_core_tpu.ledger.manager import LedgerManager
from stellar_core_tpu.testutils import (TestAccount, change_trust_op,
                                        create_account_op, make_asset,
                                        manage_sell_offer_op, network_id,
                                        payment_op)
from stellar_core_tpu.transactions import operations as ops_mod
from stellar_core_tpu.transactions.offer_ops import ManageSellOfferOpFrame

NID = network_id("invariant test net")


@pytest.fixture
def mgr():
    m = LedgerManager(NID)
    m.start_new_ledger()
    return m


@pytest.fixture
def root(mgr):
    sk = mgr.root_account_secret()
    e = mgr.root.get_entry(X.LedgerKey.account(X.LedgerKeyAccount(
        accountID=X.AccountID.ed25519(sk.public_key.ed25519))).to_xdr())
    return TestAccount(mgr, sk, e.data.value.seqNum)


def test_enabled_by_default_and_pass_on_normal_close(mgr, root):
    assert mgr.invariants is not None
    assert len(mgr.invariants.invariants) == 6
    from stellar_core_tpu.crypto.keys import SecretKey
    dest = SecretKey(b"\x07" * 32)
    mgr.close_ledger([root.tx([create_account_op(
        X.AccountID.ed25519(dest.public_key.ed25519), 10**10)])], 1000)


def test_from_patterns_selects_by_regex():
    m = InvariantManager.from_patterns(["Conservation.*"])
    assert [i.NAME for i in m.invariants] == ["ConservationOfLumens"]
    assert InvariantManager.from_patterns([r"(?!.*)"]).invariants == []
    assert len(InvariantManager.from_patterns([".*"]).invariants) == 6


def test_conservation_of_lumens_catches_minting(mgr, root, monkeypatch):
    """A payment that credits the destination without debiting the source
    mints lumens out of thin air — ConservationOfLumens must fail-stop."""
    orig = ops_mod.PaymentOpFrame.do_apply

    def evil(self, ltx):
        from stellar_core_tpu.transactions.utils import (add_balance,
                                                         load_account)
        dest = X.muxed_to_account_id(self.body.destination)
        e = load_account(ltx, dest)
        assert add_balance(e.data.value, self.body.amount)
        ltx.update(e)
        return self.success()

    monkeypatch.setattr(ops_mod.PaymentOpFrame, "do_apply", evil)
    from stellar_core_tpu.crypto.keys import SecretKey
    dest = SecretKey(b"\x08" * 32)
    mgr.close_ledger([root.tx([create_account_op(
        X.AccountID.ed25519(dest.public_key.ed25519), 10**10)])], 1000)
    native = X.Asset(X.AssetType.ASSET_TYPE_NATIVE, None)
    with pytest.raises(InvariantDoesNotHold, match="ConservationOfLumens"):
        mgr.close_ledger([root.tx([payment_op(
            X.AccountID.ed25519(dest.public_key.ed25519), native, 5)])], 1001)


def test_subentries_count_catches_unbumped_count(mgr, root, monkeypatch):
    """ChangeTrust that creates a trustline without bumping numSubEntries."""
    monkeypatch.setattr(ops_mod, "add_num_entries",
                        lambda header, acc, delta: True)
    from stellar_core_tpu.crypto.keys import SecretKey
    issuer = SecretKey(b"\x09" * 32)
    mgr.close_ledger([root.tx([create_account_op(
        X.AccountID.ed25519(issuer.public_key.ed25519), 10**11)])], 1000)
    eur = make_asset("EUR", X.AccountID.ed25519(issuer.public_key.ed25519))
    with pytest.raises(InvariantDoesNotHold,
                       match="AccountSubEntriesCountIsValid"):
        mgr.close_ledger([root.tx([change_trust_op(eur)])], 1001)


def test_liabilities_match_offers_catches_unacquired(mgr, root, monkeypatch):
    """An offer resting on the book without its liabilities recorded."""
    from stellar_core_tpu.transactions import offer_ops
    monkeypatch.setattr(offer_ops, "acquire_or_release_offer_liabilities",
                        lambda ltx, offer, acquire: True)
    from stellar_core_tpu.crypto.keys import SecretKey
    issuer_sk = SecretKey(b"\x0a" * 32)
    issuer_id = X.AccountID.ed25519(issuer_sk.public_key.ed25519)
    mgr.close_ledger([root.tx([create_account_op(issuer_id, 10**11)])], 1000)
    e = mgr.root.get_entry(X.LedgerKey.account(
        X.LedgerKeyAccount(accountID=issuer_id)).to_xdr())
    issuer = TestAccount(mgr, issuer_sk, e.data.value.seqNum)
    eur = make_asset("EUR", issuer_id)
    native = X.Asset(X.AssetType.ASSET_TYPE_NATIVE, None)
    with pytest.raises(InvariantDoesNotHold, match="LiabilitiesMatchOffers"):
        mgr.close_ledger([issuer.tx([manage_sell_offer_op(
            eur, native, 100, 1, 1)])], 1001)


def test_bucket_consistency_catches_dropped_entry(mgr, root, monkeypatch):
    """add_batch that silently drops an init entry desynchronizes the bucket
    list from the ledger state."""
    orig = mgr.bucket_list.add_batch

    def lossy(seq, ver, init, live, dead):
        orig(seq, ver, list(init)[1:], live, dead)

    monkeypatch.setattr(mgr.bucket_list, "add_batch", lossy)
    from stellar_core_tpu.crypto.keys import SecretKey
    dest = SecretKey(b"\x0b" * 32)
    with pytest.raises(InvariantDoesNotHold,
                       match="BucketListIsConsistentWithDatabase"):
        mgr.close_ledger([root.tx([create_account_op(
            X.AccountID.ed25519(dest.public_key.ed25519), 10**10)])], 1000)


def test_sponsorship_count_catches_unreleased_reserve(mgr, root, monkeypatch):
    """Claiming a claimable balance without refunding the sponsor's
    numSponsoring leaks the reserve."""
    monkeypatch.setattr(ops_mod, "_release_claimable_balance_reserve",
                        lambda ltx, cb_entry, header: None)
    from stellar_core_tpu.crypto.keys import SecretKey
    native = X.Asset(X.AssetType.ASSET_TYPE_NATIVE, None)
    claimant_sk = SecretKey(b"\x0c" * 32)
    claimant_id = X.AccountID.ed25519(claimant_sk.public_key.ed25519)
    mgr.close_ledger([root.tx([create_account_op(claimant_id, 10**11)])], 1000)
    arts = mgr.close_ledger([root.tx([X.Operation(
        body=X.OperationBody.createClaimableBalanceOp(
            X.CreateClaimableBalanceOp(
                asset=native, amount=1000,
                claimants=[X.Claimant.v0(X.ClaimantV0(
                    destination=claimant_id,
                    predicate=X.ClaimPredicate.unconditional()))])))])], 1001)
    cbid = arts.result_entry.txResultSet.results[0].result.result.value[0] \
        .value.value.value
    e = mgr.root.get_entry(X.LedgerKey.account(
        X.LedgerKeyAccount(accountID=claimant_id)).to_xdr())
    claimant = TestAccount(mgr, claimant_sk, e.data.value.seqNum)
    with pytest.raises(InvariantDoesNotHold, match="SponsorshipCountIsValid"):
        mgr.close_ledger([claimant.tx([X.Operation(
            body=X.OperationBody.claimClaimableBalanceOp(
                X.ClaimClaimableBalanceOp(balanceID=cbid)))])], 1002)
