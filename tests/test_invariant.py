"""Invariant framework tests.

Reference test model: src/invariant/test/{ConservationOfLumensTests,
AccountSubEntriesCountIsValidTests, LiabilitiesMatchOffersTests,
BucketListIsConsistentWithDatabaseTests}.cpp — each invariant must catch a
deliberately broken apply, and hold on every well-formed close (the latter
is exercised implicitly: InvariantManager defaults on in every LedgerManager
test fixture in this suite).
"""

import pytest

from stellar_core_tpu import xdr as X
from stellar_core_tpu.invariant import (InvariantDoesNotHold,
                                        InvariantManager)
from stellar_core_tpu.ledger.manager import LedgerManager
from stellar_core_tpu.testutils import (TestAccount, change_trust_op,
                                        create_account_op, make_asset,
                                        manage_sell_offer_op, network_id,
                                        payment_op)
from stellar_core_tpu.transactions import operations as ops_mod
from stellar_core_tpu.transactions.offer_ops import ManageSellOfferOpFrame

NID = network_id("invariant test net")


@pytest.fixture
def mgr():
    m = LedgerManager(NID)
    m.start_new_ledger()
    return m


@pytest.fixture
def root(mgr):
    sk = mgr.root_account_secret()
    e = mgr.root.get_entry(X.LedgerKey.account(X.LedgerKeyAccount(
        accountID=X.AccountID.ed25519(sk.public_key.ed25519))).to_xdr())
    return TestAccount(mgr, sk, e.data.value.seqNum)


def test_enabled_by_default_and_pass_on_normal_close(mgr, root):
    assert mgr.invariants is not None
    assert len(mgr.invariants.invariants) == 8
    from stellar_core_tpu.crypto.keys import SecretKey
    dest = SecretKey(b"\x07" * 32)
    mgr.close_ledger([root.tx([create_account_op(
        X.AccountID.ed25519(dest.public_key.ed25519), 10**10)])], 1000)


def test_from_patterns_selects_by_regex():
    m = InvariantManager.from_patterns(["Conservation.*"])
    assert [i.NAME for i in m.invariants] == ["ConservationOfLumens"]
    assert InvariantManager.from_patterns([r"(?!.*)"]).invariants == []
    assert len(InvariantManager.from_patterns([".*"]).invariants) == 8


def test_conservation_of_lumens_catches_minting(mgr, root, monkeypatch):
    """A payment that credits the destination without debiting the source
    mints lumens out of thin air — ConservationOfLumens must fail-stop."""
    orig = ops_mod.PaymentOpFrame.do_apply

    def evil(self, ltx):
        from stellar_core_tpu.transactions.utils import (add_balance,
                                                         load_account)
        dest = X.muxed_to_account_id(self.body.destination)
        e = load_account(ltx, dest)
        assert add_balance(e.data.value, self.body.amount)
        ltx.update(e)
        return self.success()

    monkeypatch.setattr(ops_mod.PaymentOpFrame, "do_apply", evil)
    from stellar_core_tpu.crypto.keys import SecretKey
    dest = SecretKey(b"\x08" * 32)
    mgr.close_ledger([root.tx([create_account_op(
        X.AccountID.ed25519(dest.public_key.ed25519), 10**10)])], 1000)
    native = X.Asset(X.AssetType.ASSET_TYPE_NATIVE, None)
    with pytest.raises(InvariantDoesNotHold, match="ConservationOfLumens"):
        mgr.close_ledger([root.tx([payment_op(
            X.AccountID.ed25519(dest.public_key.ed25519), native, 5)])], 1001)


def test_subentries_count_catches_unbumped_count(mgr, root, monkeypatch):
    """ChangeTrust that creates a trustline without bumping numSubEntries."""
    monkeypatch.setattr(ops_mod, "add_num_entries",
                        lambda header, acc, delta: True)
    from stellar_core_tpu.crypto.keys import SecretKey
    issuer = SecretKey(b"\x09" * 32)
    mgr.close_ledger([root.tx([create_account_op(
        X.AccountID.ed25519(issuer.public_key.ed25519), 10**11)])], 1000)
    eur = make_asset("EUR", X.AccountID.ed25519(issuer.public_key.ed25519))
    with pytest.raises(InvariantDoesNotHold,
                       match="AccountSubEntriesCountIsValid"):
        mgr.close_ledger([root.tx([change_trust_op(eur)])], 1001)


def test_liabilities_match_offers_catches_unacquired(mgr, root, monkeypatch):
    """An offer resting on the book without its liabilities recorded."""
    from stellar_core_tpu.transactions import offer_ops
    monkeypatch.setattr(offer_ops, "acquire_or_release_offer_liabilities",
                        lambda ltx, offer, acquire: True)
    from stellar_core_tpu.crypto.keys import SecretKey
    issuer_sk = SecretKey(b"\x0a" * 32)
    issuer_id = X.AccountID.ed25519(issuer_sk.public_key.ed25519)
    mgr.close_ledger([root.tx([create_account_op(issuer_id, 10**11)])], 1000)
    e = mgr.root.get_entry(X.LedgerKey.account(
        X.LedgerKeyAccount(accountID=issuer_id)).to_xdr())
    issuer = TestAccount(mgr, issuer_sk, e.data.value.seqNum)
    eur = make_asset("EUR", issuer_id)
    native = X.Asset(X.AssetType.ASSET_TYPE_NATIVE, None)
    with pytest.raises(InvariantDoesNotHold, match="LiabilitiesMatchOffers"):
        mgr.close_ledger([issuer.tx([manage_sell_offer_op(
            eur, native, 100, 1, 1)])], 1001)


def test_bucket_consistency_catches_dropped_entry(mgr, root, monkeypatch):
    """add_batch that silently drops an init entry desynchronizes the bucket
    list from the ledger state."""
    orig = mgr.bucket_list.add_batch

    def lossy(seq, ver, init, live, dead):
        orig(seq, ver, list(init)[1:], live, dead)

    monkeypatch.setattr(mgr.bucket_list, "add_batch", lossy)
    from stellar_core_tpu.crypto.keys import SecretKey
    dest = SecretKey(b"\x0b" * 32)
    with pytest.raises(InvariantDoesNotHold,
                       match="BucketListIsConsistentWithDatabase"):
        mgr.close_ledger([root.tx([create_account_op(
            X.AccountID.ed25519(dest.public_key.ed25519), 10**10)])], 1000)


def test_sponsorship_count_catches_unreleased_reserve(mgr, root, monkeypatch):
    """Claiming a claimable balance without refunding the sponsor's
    numSponsoring leaks the reserve."""
    monkeypatch.setattr(ops_mod, "_release_claimable_balance_reserve",
                        lambda ltx, cb_entry, header: None)
    from stellar_core_tpu.crypto.keys import SecretKey
    native = X.Asset(X.AssetType.ASSET_TYPE_NATIVE, None)
    claimant_sk = SecretKey(b"\x0c" * 32)
    claimant_id = X.AccountID.ed25519(claimant_sk.public_key.ed25519)
    mgr.close_ledger([root.tx([create_account_op(claimant_id, 10**11)])], 1000)
    arts = mgr.close_ledger([root.tx([X.Operation(
        body=X.OperationBody.createClaimableBalanceOp(
            X.CreateClaimableBalanceOp(
                asset=native, amount=1000,
                claimants=[X.Claimant.v0(X.ClaimantV0(
                    destination=claimant_id,
                    predicate=X.ClaimPredicate.unconditional()))])))])], 1001)
    cbid = arts.result_entry.txResultSet.results[0].result.result.value[0] \
        .value.value.value
    e = mgr.root.get_entry(X.LedgerKey.account(
        X.LedgerKeyAccount(accountID=claimant_id)).to_xdr())
    claimant = TestAccount(mgr, claimant_sk, e.data.value.seqNum)
    with pytest.raises(InvariantDoesNotHold, match="SponsorshipCountIsValid"):
        mgr.close_ledger([claimant.tx([X.Operation(
            body=X.OperationBody.claimClaimableBalanceOp(
                X.ClaimClaimableBalanceOp(balanceID=cbid)))])], 1002)


# --- ConstantProductInvariant (VERDICT missing #4) --------------------------

def _pool_entry(reserve_a, reserve_b, shares, tl_count=1, seq=2):
    from stellar_core_tpu.xdr import (Asset, AssetType,
                                      LiquidityPoolConstantProductParameters)
    params = LiquidityPoolConstantProductParameters(
        assetA=Asset(AssetType.ASSET_TYPE_NATIVE, None),
        assetB=X.Asset.alphaNum4(X.AlphaNum4(
            assetCode=b"USD\x00",
            issuer=X.AccountID.ed25519(b"\x05" * 32))),
        fee=30)
    cp = X.LiquidityPoolEntryConstantProduct(
        params=params, reserveA=reserve_a, reserveB=reserve_b,
        totalPoolShares=shares, poolSharesTrustLineCount=tl_count)
    lp = X.LiquidityPoolEntry(
        liquidityPoolID=b"\x09" * 32,
        body=X.LiquidityPoolEntryBody(
            X.LiquidityPoolType.LIQUIDITY_POOL_CONSTANT_PRODUCT, cp))
    return X.LedgerEntry(lastModifiedLedgerSeq=seq,
                         data=X.LedgerEntryData.liquidityPool(lp))


def _pool_ctx(pre_entry, post_entry):
    from stellar_core_tpu.invariant import LedgerCloseContext
    kb = X.LedgerKey.liquidityPool(X.LedgerKeyLiquidityPool(
        liquidityPoolID=b"\x09" * 32)).to_xdr()
    hdr = X.LedgerHeader(
        ledgerVersion=23, previousLedgerHash=b"\x00" * 32,
        scpValue=X.StellarValue(txSetHash=b"\x00" * 32, closeTime=0),
        txSetResultHash=b"\x00" * 32, bucketListHash=b"\x00" * 32,
        ledgerSeq=2, totalCoins=0, feePool=0, inflationSeq=0, idPool=0,
        baseFee=100, baseReserve=10 ** 8, maxTxSetSize=100,
        skipList=[b"\x00" * 32] * 4)
    return LedgerCloseContext(
        pre={kb: pre_entry}, post={kb: post_entry},
        pre_header=hdr, post_header=hdr,
        root_get=lambda kb_: None, all_keys=lambda: [])


@pytest.mark.parametrize("pre,post", [
    ((1000, 1000, 100), (990, 1011, 100)),    # swap: product grew (fee)
    ((1000, 1000, 100), (1100, 1100, 110)),   # deposit adds both reserves
    ((1000, 1000, 100), (900, 900, 90)),      # withdraw pays <= share value
    (None, (0, 0, 0)),                        # pool created empty
    ((0, 0, 0), None),                        # empty pool deleted
])
def test_constant_product_holds(pre, post):
    from stellar_core_tpu.invariant.invariants import ConstantProductInvariant
    inv = ConstantProductInvariant()
    ctx = _pool_ctx(None if pre is None else _pool_entry(*pre),
                    None if post is None else _pool_entry(*post))
    assert inv.check_on_ledger_close(ctx) is None


@pytest.mark.parametrize("pre,post,needle", [
    ((1000, 1000, 100), (990, 1009, 100), "constant product shrank"),
    ((1000, 1000, 100), (990, 1100, 110), "deposit drained"),
    ((1000, 1000, 100), (1000, 1000, 200), "dilution"),  # free share mint
    ((1000, 1000, 100), (950, 1001, 90), "withdrawal grew"),
    ((1000, 1000, 100), (890, 900, 90), "more than the burned"),
    ((1000, 1000, 100), (-1, 1000, 100), "negative"),
    ((1000, 1000, 100), None, "deleted while holding"),
])
def test_constant_product_catches_violations(pre, post, needle):
    from stellar_core_tpu.invariant.invariants import ConstantProductInvariant
    inv = ConstantProductInvariant()
    ctx = _pool_ctx(_pool_entry(*pre),
                    None if post is None else _pool_entry(*post))
    msg = inv.check_on_ledger_close(ctx)
    assert msg is not None and needle in msg


def test_constant_product_passes_on_real_pool_traffic(mgr, root):
    """End-to-end: pool create/deposit/withdraw traffic closes cleanly
    with the invariant enabled (it is on by default in this fixture)."""
    from stellar_core_tpu.testutils import (change_trust_pool_op,
                                            liquidity_pool_deposit_op,
                                            liquidity_pool_withdraw_op)
    from stellar_core_tpu.transactions.offer_exchange import pool_id_for
    from stellar_core_tpu.crypto.keys import SecretKey

    issuer_sk = SecretKey(b"\x21" * 32)
    issuer_id = X.AccountID.ed25519(issuer_sk.public_key.ed25519)
    mgr.close_ledger([root.tx([create_account_op(issuer_id, 10 ** 12)])],
                     1000)
    issuer = TestAccount(mgr, issuer_sk, _entry_seq(mgr, issuer_id))
    native = X.Asset(X.AssetType.ASSET_TYPE_NATIVE, None)
    usd = make_asset("USD", issuer_id)
    pool_id = pool_id_for(native, usd, 30)
    mgr.close_ledger(
        [issuer.tx([change_trust_pool_op(native, usd)])], 1010)
    mgr.close_ledger(
        [issuer.tx([liquidity_pool_deposit_op(
            pool_id, 10 ** 8, 10 ** 8)])], 1020)
    mgr.close_ledger(
        [issuer.tx([liquidity_pool_withdraw_op(pool_id, 10 ** 7)])], 1030)


def _entry_seq(mgr, account_id):
    e = mgr.root.get_entry(X.LedgerKey.account(X.LedgerKeyAccount(
        accountID=account_id)).to_xdr())
    return e.data.value.seqNum
