"""Application / Config / CLI / HTTP admin tests.

Reference test model: src/main/test/{ApplicationTests, CommandHandlerTests,
ConfigTests}.cpp plus the acceptance bar from VERDICT round 1: a 3-node
localhost network of REAL `python -m stellar_core_tpu run` processes closes
ledgers, serves /info, and externalizes a tx submitted over HTTP /tx.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

from stellar_core_tpu import xdr as X
from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.main.config import Config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


class TestConfig:
    def test_toml_parsing(self, tmp_path):
        conf = tmp_path / "node.cfg"
        conf.write_text('''
NETWORK_PASSPHRASE = "My Test Network"
NODE_SEED = "%s"
NODE_IS_VALIDATOR = true
RUN_STANDALONE = true
PEER_PORT = 12345
HTTP_PORT = 8080
KNOWN_PEERS = ["127.0.0.1:11626"]
DATABASE = "%s"
INVARIANT_CHECKS = [".*"]
ACCEL = "tpu"

[QUORUM_SET]
THRESHOLD = 2
VALIDATORS = ["%s", "%s"]

[HISTORY.local]
get = "/tmp/archive"
put = "/tmp/archive"
''' % (SecretKey(b"\x01" * 32).to_strkey_seed(),
            tmp_path / "db.sqlite",
            SecretKey(b"\x01" * 32).public_key.to_strkey(),
            SecretKey(b"\x02" * 32).public_key.to_strkey()))
        cfg = Config.from_toml(str(conf))
        assert cfg.NETWORK_PASSPHRASE == "My Test Network"
        assert cfg.PEER_PORT == 12345 and cfg.HTTP_PORT == 8080
        assert cfg.ACCEL == "tpu"
        assert cfg.node_secret().public_key.ed25519 == \
            SecretKey(b"\x01" * 32).public_key.ed25519
        q = cfg.quorum_set()
        assert q.threshold == 2 and len(q.validators) == 2
        assert cfg.HISTORY[0].name == "local"
        assert len(cfg.INVARIANT_CHECKS) == 1

    def test_defaults_derive_node_seed_from_network(self):
        a, b = Config(), Config()
        assert a.node_secret().public_key.ed25519 == \
            b.node_secret().public_key.ed25519
        q = a.quorum_set()
        assert q.threshold == 1 and len(q.validators) == 1


class TestCli:
    def _run(self, *args):
        return subprocess.run(
            [sys.executable, "-m", "stellar_core_tpu", *args],
            capture_output=True, text=True, cwd=REPO, timeout=180)

    def test_version(self):
        r = self._run("version")
        assert r.returncode == 0 and "stellar-core-tpu" in r.stdout

    def test_gen_seed_and_sec_to_pub(self):
        r = self._run("gen-seed")
        assert r.returncode == 0
        d = json.loads(r.stdout)
        r2 = self._run("sec-to-pub", d["secret"])
        assert r2.stdout.strip() == d["public"]

    def test_new_db_creates_genesis(self, tmp_path):
        conf = tmp_path / "n.cfg"
        conf.write_text(f'DATABASE = "{tmp_path}/node.db"\n')
        r = self._run("new-db", "--conf", str(conf))
        assert r.returncode == 0, r.stderr
        assert "genesis ledger 1" in r.stdout
        assert (tmp_path / "node.db").exists()

    def test_diag_bucket_stats(self, tmp_path):
        conf = tmp_path / "n.cfg"
        conf.write_text(f'DATABASE = "{tmp_path}/node.db"\n')
        assert self._run("new-db", "--conf", str(conf)).returncode == 0
        r = self._run("diag-bucket-stats", "--conf", str(conf))
        assert r.returncode == 0, r.stderr
        doc = json.loads(r.stdout)
        assert doc["ledger"] >= 1
        assert len(doc["levels"]) == 11
        assert doc["totals"]["entries"] >= 1   # at least the root account
        lvl0 = doc["levels"][0]["curr"]
        assert len(lvl0["hash"]) == 64
        assert sum(lvl0["by_type"].values()) == lvl0["entries"]

    def test_check_quorum_intersection(self, tmp_path):
        ids = [SecretKey(bytes([i + 1]) * 32).public_key.to_strkey()
               for i in range(4)]
        good = {n: {"threshold": 3, "validators": ids} for n in ids}
        p = tmp_path / "good.json"
        p.write_text(json.dumps(good))
        assert self._run("check-quorum-intersection", str(p)).returncode == 0
        # two disjoint halves -> no intersection
        bad = {ids[0]: {"threshold": 1, "validators": ids[:2]},
               ids[1]: {"threshold": 1, "validators": ids[:2]},
               ids[2]: {"threshold": 1, "validators": ids[2:]},
               ids[3]: {"threshold": 1, "validators": ids[2:]}}
        p2 = tmp_path / "bad.json"
        p2.write_text(json.dumps(bad))
        assert self._run("check-quorum-intersection", str(p2)).returncode == 2


class TestStandaloneApp:
    def test_standalone_node_closes_ledgers_in_process(self, tmp_path):
        from stellar_core_tpu.main.application import Application
        from stellar_core_tpu.util.clock import ClockMode, VirtualClock

        cfg = Config.from_dict({
            "NETWORK_PASSPHRASE": "standalone app test",
            "RUN_STANDALONE": True,
            "PEER_PORT": 0,
            "DATABASE": str(tmp_path / "node.db"),
            "INVARIANT_CHECKS": [".*"],
        })
        clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        app = Application(cfg, clock=clock, listen=False)
        app.start()
        ok = clock.crank_until(
            lambda: app.lm.last_closed_ledger_seq >= 4, timeout=60)
        assert ok
        info = app.info()
        assert info["ledger"]["num"] >= 4
        assert info["state"] == "tracking"
        lcl = app.lm.last_closed_ledger_seq
        app.stop()
        # restart resumes from the persisted LCL
        app2 = Application(cfg, clock=VirtualClock(ClockMode.VIRTUAL_TIME),
                           listen=False)
        assert app2.lm.last_closed_ledger_seq >= lcl
        app2.stop()


def _http_json(port, path, timeout=2.0):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
        return json.loads(r.read())


@pytest.mark.slow
class TestThreeNodeNetwork:
    def test_three_real_processes_close_ledgers_and_accept_tx(self, tmp_path):
        """`python -m stellar_core_tpu run --conf` x3 over localhost TCP:
        the VERDICT round-1 acceptance bar for the application layer."""
        n = 3
        seeds = [SecretKey(bytes([0x51 + i]) * 32) for i in range(n)]
        ports = _free_ports(2 * n)
        peer_ports, http_ports = ports[:n], ports[n:]
        validators = [s.public_key.to_strkey() for s in seeds]
        procs = []
        try:
            for i in range(n):
                peers = [f"127.0.0.1:{peer_ports[j]}"
                         for j in range(n) if j != i]
                conf = tmp_path / f"node{i}.cfg"
                conf.write_text(f'''
NETWORK_PASSPHRASE = "three node tcp net"
NODE_SEED = "{seeds[i].to_strkey_seed()}"
FORCE_SCP = true
PEER_PORT = {peer_ports[i]}
HTTP_PORT = {http_ports[i]}
KNOWN_PEERS = {json.dumps(peers)}
DATABASE = "{tmp_path}/node{i}/node.db"
ARTIFICIALLY_ACCELERATE_TIME_FOR_TESTING = true
LOG_LEVEL = "WARNING"

[QUORUM_SET]
THRESHOLD = 2
VALIDATORS = {json.dumps(validators)}
''')
                procs.append(subprocess.Popen(
                    [sys.executable, "-m", "stellar_core_tpu", "run",
                     "--conf", str(conf)],
                    cwd=REPO, stdout=subprocess.DEVNULL,
                    stderr=subprocess.PIPE, text=True))

            deadline = time.time() + 60
            seqs = [0] * n
            while time.time() < deadline:
                for i in range(n):
                    if procs[i].poll() is not None:
                        raise AssertionError(
                            f"node {i} died: {procs[i].stderr.read()}")
                    try:
                        seqs[i] = _http_json(
                            http_ports[i], "/info")["info"]["ledger"]["num"]
                    except OSError:
                        pass
                if all(s >= 3 for s in seqs):
                    break
                time.sleep(0.5)
            assert all(s >= 3 for s in seqs), seqs

            # all agree on ledger 3's hash eventually (query headers via
            # /info only shows latest; use state equality: same seq+hash)
            infos = [_http_json(http_ports[i], "/info")["info"]
                     for i in range(n)]
            assert all(i["peers"]["authenticated_count"] >= 1
                       for i in infos), infos

            # submit a tx over HTTP to node 0, watch it externalize
            net_id = Config.from_dict(
                {"NETWORK_PASSPHRASE": "three node tcp net"}).network_id()
            from stellar_core_tpu.ledger.manager import LedgerManager
            from stellar_core_tpu.testutils import (TestAccount,
                                                    create_account_op)
            probe_lm = LedgerManager(net_id, invariant_manager=None)
            probe_lm.start_new_ledger()
            root_sk = probe_lm.root_account_secret()
            e = probe_lm.root.get_entry(X.LedgerKey.account(
                X.LedgerKeyAccount(accountID=X.AccountID.ed25519(
                    root_sk.public_key.ed25519))).to_xdr())
            root = TestAccount(probe_lm, root_sk, e.data.value.seqNum)
            dest = SecretKey(b"\x77" * 32)
            frame = root.tx([create_account_op(
                X.AccountID.ed25519(dest.public_key.ed25519), 10**10)])
            blob = frame.envelope.to_xdr().hex()
            res = _http_json(http_ports[0], f"/tx?blob={blob}", timeout=15)
            assert res["status"] == "PENDING", res

            # the tx lands: every node's metrics advance & queue drains
            deadline = time.time() + 30
            drained = False
            while time.time() < deadline:
                m = _http_json(http_ports[0], "/metrics")["metrics"]
                if m["herder"]["tx_queue_size"] == 0 and \
                        m["ledger"]["entries"] >= 2:
                    drained = True
                    break
                time.sleep(0.5)
            assert drained
        finally:
            for p in procs:
                if p.poll() is None:
                    p.send_signal(signal.SIGTERM)
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()


class TestFuzzers:
    """Reference: src/test/FuzzerImpl — a short deterministic campaign per
    target runs in CI; any escaping exception is a failure."""

    def test_xdr_roundtrip_fuzz(self):
        from stellar_core_tpu.fuzz import fuzz_xdr_roundtrip
        assert fuzz_xdr_roundtrip(seed=11, iters=300) == []

    def test_transaction_fuzzer(self):
        from stellar_core_tpu.fuzz import TransactionFuzzer
        tf = TransactionFuzzer(seed=11)
        assert tf.run(60) == []
        # state stayed coherent: another valid ledger closes fine
        assert tf.mgr.lcl_hash is not None

    def test_overlay_fuzzer(self):
        from stellar_core_tpu.fuzz import OverlayFuzzer
        of = OverlayFuzzer(seed=11)
        assert of.run(80) == []


class TestNewCliCommands:
    _run = TestCli._run

    def test_encode_asset_and_convert_id(self):
        r = self._run("encode-asset")
        assert r.returncode == 0 and r.stdout.strip() == "00000000"
        sk = SecretKey(b"\x09" * 32)
        r2 = self._run("convert-id", sk.public_key.to_strkey())
        assert r2.returncode == 0
        d = json.loads(r2.stdout)
        assert d["hex"] == sk.public_key.ed25519.hex()
        r3 = self._run("convert-id", d["hex"])
        assert json.loads(r3.stdout)["strkey"] == sk.public_key.to_strkey()

    def test_print_xdr_and_sign_transaction(self, tmp_path):
        from stellar_core_tpu import xdr as X
        from stellar_core_tpu.testutils import (build_tx, native_payment_op,
                                                network_id)
        nid = network_id("cli print test")
        sk = SecretKey(b"\x11" * 32)
        frame = build_tx(nid, sk, 1,
                         [native_payment_op(
                             X.AccountID.ed25519(b"\x22" * 32), 5)])
        p = tmp_path / "tx.xdr"
        p.write_bytes(frame.envelope.to_xdr())
        r = self._run("print-xdr", str(p), "--filetype", "tx-envelope")
        assert r.returncode == 0
        d = json.loads(r.stdout)
        assert d["type"] == "ENVELOPE_TYPE_TX"
        # sign-transaction appends a second decorated signature
        r2 = subprocess.run(
            [sys.executable, "-m", "stellar_core_tpu", "sign-transaction",
             str(p), "--netid", "cli print test"],
            input=SecretKey(b"\x33" * 32).to_strkey_seed(),
            capture_output=True, text=True, cwd=REPO, timeout=120)
        assert r2.returncode == 0, r2.stderr
        signed = X.TransactionEnvelope.from_xdr(
            bytes.fromhex(r2.stdout.strip()))
        assert len(signed.value.signatures) == 2

    def test_fuzz_cli_xdr_mode(self):
        r = self._run("fuzz", "--mode", "xdr", "--iters", "50")
        assert r.returncode == 0, r.stdout + r.stderr
        assert "0 findings" in r.stdout

    def test_gen_fuzz_writes_corpus(self, tmp_path):
        out = tmp_path / "corpus"
        r = self._run("gen-fuzz", "--mode", "overlay", "--output", str(out),
                      "--count", "10")
        assert r.returncode == 0
        assert len(list(out.glob("*.xdr"))) >= 5

    def test_apply_load_cli(self):
        r = self._run("apply-load", "--accounts", "20", "--ledgers", "3",
                      "--txs", "10")
        assert r.returncode == 0, r.stderr
        d = json.loads(r.stdout)
        assert d["txs"] == 30 and d["tx_per_s"] > 0


class TestNodeAdminSurface:
    def _mk_app(self, tmp_path, archive=None):
        from stellar_core_tpu.main.application import Application
        from stellar_core_tpu.util.clock import ClockMode, VirtualClock
        raw = {
            "NETWORK_PASSPHRASE": "admin surface test",
            "RUN_STANDALONE": True,
            "PEER_PORT": 0,
            "DATABASE": str(tmp_path / "node.db"),
        }
        if archive:
            raw["HISTORY"] = {"main": {"get": archive, "put": archive}}
        cfg = Config.from_dict(raw)
        clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        return Application(cfg, clock=clock, listen=False), clock

    def test_self_check_and_maintenance(self, tmp_path):
        app, clock = self._mk_app(tmp_path, str(tmp_path / "arch"))
        app.start()
        clock.crank_until(lambda: app.lm.last_closed_ledger_seq >= 66,
                          timeout=600)
        report = app.self_check()
        assert report["ok"], report
        names = {c["name"] for c in report["checks"]}
        assert {"lcl-header-hash", "bucket-list-hash", "db-header",
                "bucket-files", "archive-0"} <= names
        m = app.maintainer.perform_maintenance()
        assert m["pruned_below"] is not None
        # node still healthy after GC: restart works
        app.stop()
        app2, _ = self._mk_app(tmp_path, str(tmp_path / "arch"))
        assert app2.lm.last_closed_ledger_seq >= 66
        app2.stop()

    def test_manual_close_and_ledger_entry(self, tmp_path):
        from stellar_core_tpu import xdr as X
        app, clock = self._mk_app(tmp_path)
        app.start()
        clock.crank_until(lambda: app.lm.last_closed_ledger_seq >= 2,
                          timeout=60)
        root_key = X.LedgerKey.account(X.LedgerKeyAccount(
            accountID=X.AccountID.ed25519(
                app.lm.root_account_secret().public_key.ed25519)))
        got = app.get_ledger_entry(root_key.to_xdr())
        assert got["found"]
        entry = X.LedgerEntry.from_xdr(bytes.fromhex(got["entry_xdr"]))
        assert entry.data.value.balance > 0
        missing = app.get_ledger_entry(X.LedgerKey.account(
            X.LedgerKeyAccount(accountID=X.AccountID.ed25519(
                b"\x5e" * 32))).to_xdr())
        assert not missing["found"]
        app.stop()

    def test_upgrades_endpoint_backend(self, tmp_path):
        from stellar_core_tpu.herder.upgrades import UpgradeParameters
        app, clock = self._mk_app(tmp_path)
        assert app.herder.upgrades.pending_json()["basefee"] is None
        app.herder.upgrades.set_parameters(UpgradeParameters(
            upgrade_time=0, base_fee=200))
        assert app.herder.upgrades.pending_json()["basefee"] == 200
        app.herder.upgrades.set_parameters(None)
        assert app.herder.upgrades.pending_json()["basefee"] is None
        app.stop()


class TestHealthProbePolling:
    """`health --retries/--interval`: poll a booting node to readiness
    instead of hand-rolling sleep loops (fleet harness + operator probe)."""

    def test_unreachable_without_retries_exits_1(self, tmp_path, capsys):
        from stellar_core_tpu.main.commandline import main
        port = _free_ports(1)[0]
        conf = tmp_path / "n.cfg"
        conf.write_text(f"HTTP_PORT = {port}\n")
        assert main(["health", "--conf", str(conf), "--timeout", "0.5"]) == 1
        assert "unreachable" in capsys.readouterr().out

    def test_retries_poll_until_the_endpoint_comes_up(self, tmp_path,
                                                      capsys):
        import threading
        from http.server import BaseHTTPRequestHandler, HTTPServer
        from stellar_core_tpu.main.commandline import main

        port = _free_ports(1)[0]
        conf = tmp_path / "n.cfg"
        conf.write_text(f"HTTP_PORT = {port}\n")

        class OkHandler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_GET(self):
                body = json.dumps({"status": "ok"}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        server = HTTPServer(("127.0.0.1", port), OkHandler)

        def come_up_late():
            time.sleep(0.8)   # a few probe attempts fail first
            threading.Thread(target=server.serve_forever,
                             daemon=True).start()

        threading.Thread(target=come_up_late, daemon=True).start()
        try:
            rc = main(["health", "--conf", str(conf),
                       "--retries", "20", "--interval", "0.2",
                       "--timeout", "0.5"])
            assert rc == 0
            assert json.loads(capsys.readouterr().out)["status"] == "ok"
        finally:
            server.shutdown()
            server.server_close()


class TestInPlaceArchiveCatchup:
    def test_out_of_sync_node_catches_up_from_archive(self, tmp_path):
        """A live node whose gap exceeds peers' SCP memory replays from
        the configured archive IN PLACE (same LedgerManager), then drains
        any buffered live ledgers (reference: out-of-sync ->
        CatchupManager::startCatchup + ApplyBufferedLedgersWork)."""
        from stellar_core_tpu.history.archive import FileHistoryArchive
        from stellar_core_tpu.history.manager import HistoryManager
        from stellar_core_tpu.ledger.manager import LedgerManager
        from stellar_core_tpu.main.application import Application
        from stellar_core_tpu.simulation.loadgen import LoadGenerator
        from stellar_core_tpu.testutils import network_id
        from stellar_core_tpu.util.clock import ClockMode, VirtualClock

        passphrase = "inplace catchup net"
        nid = network_id(passphrase)
        src = LedgerManager(nid)
        src.start_new_ledger()
        archive = FileHistoryArchive(str(tmp_path / "arch"))
        hist = HistoryManager(src, passphrase, [archive])
        gen = LoadGenerator(src, hist, seed=31)
        gen.create_accounts(16, per_ledger=8)
        gen.payment_ledgers(50, txs_per_ledger=4)
        gen.run_to_checkpoint_boundary()
        tip = src.last_closed_ledger_seq

        cfg = Config.from_dict({
            "NETWORK_PASSPHRASE": passphrase,
            "PEER_PORT": 0,
            "HISTORY": {"main": {"get": str(tmp_path / "arch")}},
        })
        clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        app = Application(cfg, clock=clock, listen=False)
        app.start()
        assert app.lm.last_closed_ledger_seq == 1
        app.maybe_start_archive_catchup()
        assert app._catchup_work is not None
        ok = clock.crank_until(
            lambda: app.lm.last_closed_ledger_seq >= tip, timeout=600)
        assert ok
        assert app.lm.lcl_hash == src.lcl_hash
        app.stop()
