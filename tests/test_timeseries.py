"""Historical telemetry store (ISSUE 20): capture-tick delta encoding,
tiered retention, watermark export, dump persistence + the tsdump
subcommand, and the capture thread lifecycle.
"""

import json
import os
import threading

import pytest

from stellar_core_tpu.util import metrics
from stellar_core_tpu.util.timeseries import (DOWNSAMPLE, TimeSeriesStore,
                                              load_dump)


@pytest.fixture(autouse=True)
def _fresh_registry():
    metrics.reset_registry()
    yield
    metrics.reset_registry()


def _store(**kw):
    kw.setdefault("cadence_s", 1.0)
    return TimeSeriesStore(**kw)


class TestCaptureAndReplay:
    def test_points_reconstruct_full_fields(self):
        """Delta-encoded ticks replay back to the exact per-tick field
        values the registry reported."""
        c = metrics.registry().counter("ledger.ledger.close")
        s = _store()
        expect = []
        for i in range(10):
            c.inc()
            s.capture(now=float(i))
            expect.append(i + 1)
        pts = s.doc(metric="ledger.ledger.close")["series"][
            "ledger.ledger.close"]
        assert [p["v"]["count"] for p in pts] == expect
        assert [p["seq"] for p in pts] == list(range(1, 11))
        assert [p["t"] for p in pts] == [float(i) for i in range(10)]

    def test_idle_metric_deltas_are_empty(self):
        """An unchanged metric costs an empty delta per tick, not a full
        row — the bound that makes a 1 s cadence affordable."""
        metrics.registry().counter("ledger.ledger.close").inc()
        s = _store()
        for i in range(6):
            s.capture(now=float(i))
        dq = s._dense["ledger.ledger.close"]
        # tick 1 carries the full fields; later ticks change nothing
        deltas = [delta for _, _, delta, _ in list(dq)[1:]]
        assert all(d == {} for d in deltas)
        # replay still yields full points for every tick
        pts = s.doc(metric="ledger.ledger.close")["series"][
            "ledger.ledger.close"]
        assert len(pts) == 6
        assert all(p["v"]["count"] == 1 for p in pts)

    def test_keyframes_interleave_deltas(self):
        c = metrics.registry().counter("ledger.ledger.close")
        s = _store(key_interval=4)
        for i in range(9):
            c.inc()
            s.capture(now=float(i))
        dq = s._dense["ledger.ledger.close"]
        keys = [seq for seq, _, _, is_key in dq if is_key]
        assert keys == [4, 8]

    def test_registry_swap_is_picked_up(self):
        """reset_registry() swaps the registry object; the next capture
        must snapshot the NEW registry (and re-home the self gauges)."""
        metrics.registry().counter("ledger.ledger.close").inc()
        s = _store()
        s.capture(now=0.0)
        metrics.reset_registry()
        metrics.registry().counter("scp.value.sign").inc()
        s.capture(now=1.0)
        assert "scp.value.sign" in s.metric_names()
        assert "timeseries.points.retained" in metrics.registry().names()

    def test_capture_accounting_metrics(self):
        s = _store()
        s.capture(now=0.0)
        s.capture(now=1.0)
        names = metrics.registry().names()
        assert "timeseries.capture.ticks" in names
        assert "timeseries.capture.tick-time" in names


class TestRetention:
    def test_dense_ring_is_bounded(self):
        c = metrics.registry().counter("ledger.ledger.close")
        s = _store(dense_points=8, tail_points=4)
        for i in range(40):
            c.inc()
            s.capture(now=float(i))
        assert len(s._dense["ledger.ledger.close"]) == 8

    def test_evicted_points_survive_downsampled(self):
        """Points rolled out of the dense window stay readable at
        1-in-DOWNSAMPLE resolution, with correct replayed values."""
        c = metrics.registry().counter("ledger.ledger.close")
        s = _store(dense_points=8, tail_points=64)
        n = 64
        for i in range(n):
            c.inc()
            s.capture(now=float(i))
        pts = s.doc(metric="ledger.ledger.close")["series"][
            "ledger.ledger.close"]
        seqs = [p["seq"] for p in pts]
        # the dense window is the trailing 8 ticks...
        assert seqs[-8:] == list(range(n - 7, n + 1))
        # ...and the tail holds downsampled evicted ticks before it
        tail = seqs[:-8]
        assert tail, "no tail survived eviction"
        assert all(seq % DOWNSAMPLE == 0 for seq in tail)
        # replayed values stay exact through eviction
        assert all(p["v"]["count"] == p["seq"] for p in pts)

    def test_tail_ring_is_bounded(self):
        c = metrics.registry().counter("ledger.ledger.close")
        s = _store(dense_points=4, tail_points=3)
        for i in range(200):
            c.inc()
            s.capture(now=float(i))
        assert len(s._tail["ledger.ledger.close"]) == 3


class TestWatermark:
    def test_since_filters_and_next_since_advances(self):
        c = metrics.registry().counter("ledger.ledger.close")
        s = _store()
        for i in range(5):
            c.inc()
            s.capture(now=float(i))
        first = s.doc()
        assert first["next_since"] == 5
        for i in range(3):
            c.inc()
            s.capture(now=5.0 + i)
        incr = s.doc(since=first["next_since"])
        pts = incr["series"]["ledger.ledger.close"]
        assert [p["seq"] for p in pts] == [6, 7, 8]
        assert incr["next_since"] == 8
        # fully caught up: empty series, watermark stays put
        done = s.doc(since=8)
        assert done["series"] == {}
        assert done["next_since"] == 8

    def test_window_returns_trailing_ticks(self):
        c = metrics.registry().counter("ledger.ledger.close")
        s = _store()
        for i in range(20):
            c.inc()
            s.capture(now=float(i))
        w = s.window("ledger.ledger.close", 5)
        assert [p["seq"] for p in w] == [16, 17, 18, 19, 20]

    def test_metric_filter(self):
        metrics.registry().counter("ledger.ledger.close").inc()
        metrics.registry().counter("scp.value.sign").inc()
        s = _store()
        s.capture(now=0.0)
        doc = s.doc(metric="ledger.ledger.close")
        assert list(doc["series"]) == ["ledger.ledger.close"]


class TestCaptureThread:
    def test_start_stop_idempotent(self):
        s = _store(cadence_s=0.01)
        s.start()
        t = s._thread
        s.start()  # second start is a no-op
        assert s._thread is t
        assert s.running
        # the daemon captures on its own cadence
        deadline = 50
        while s.seq == 0 and deadline:
            threading.Event().wait(0.02)
            deadline -= 1
        assert s.seq > 0
        s.stop()
        assert not s.running
        s.stop()  # idempotent

    def test_timer_driven_store_needs_no_thread(self):
        s = _store()
        s.capture(now=0.0)
        assert not s.running
        s.stop()  # no-op


class TestDumpAndTsdump:
    def _dumped(self, tmp_path, monkeypatch):
        monkeypatch.setenv("STPU_CRASH_DIR", str(tmp_path))
        c = metrics.registry().counter("ledger.ledger.close")
        s = _store()
        for i in range(6):
            c.inc()
            s.capture(now=float(i))
        return s, s.dump(reason="test")

    def test_dump_roundtrips_through_load(self, tmp_path, monkeypatch):
        s, path = self._dumped(tmp_path, monkeypatch)
        assert os.path.dirname(path) == str(tmp_path)
        doc = load_dump(path)
        assert doc["kind"] == "timeseries-dump"
        assert doc["reason"] == "test"
        assert doc["next_since"] == s.seq
        live = s.doc(metric="ledger.ledger.close")["series"]
        assert doc["series"]["ledger.ledger.close"] \
            == live["ledger.ledger.close"]

    def test_load_rejects_non_dumps(self, tmp_path):
        p = tmp_path / "not-a-dump.json"
        p.write_text(json.dumps({"kind": "crash-bundle", "series": {}}))
        with pytest.raises(ValueError):
            load_dump(str(p))
        p2 = tmp_path / "not-json.json"
        p2.write_text("{")
        with pytest.raises(ValueError):
            load_dump(str(p2))

    def test_tsdump_summary_matches_dump(self, tmp_path, monkeypatch,
                                         capsys):
        """The tsdump subcommand's summary agrees with the persisted
        document (satellite: offline dump reader)."""
        from stellar_core_tpu.main.commandline import main
        s, path = self._dumped(tmp_path, monkeypatch)
        assert main(["tsdump", path]) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["kind"] == "timeseries-dump"
        assert out["next_since"] == s.seq
        row = next(r for r in out["series"]
                   if r["metric"] == "ledger.ledger.close")
        assert row["points"] == 6
        assert row["last_seq"] == s.seq
        assert row["last"]["count"] == 6

    def test_tsdump_single_metric_since(self, tmp_path, monkeypatch,
                                        capsys):
        from stellar_core_tpu.main.commandline import main
        _, path = self._dumped(tmp_path, monkeypatch)
        assert main(["tsdump", path, "--metric", "ledger.ledger.close",
                     "--since", "4"]) == 0
        lines = [json.loads(line) for line in
                 capsys.readouterr().out.splitlines()]
        assert [p["seq"] for p in lines] == [5, 6]

    def test_tsdump_errors_exit_nonzero(self, tmp_path, monkeypatch,
                                        capsys):
        from stellar_core_tpu.main.commandline import main
        _, path = self._dumped(tmp_path, monkeypatch)
        assert main(["tsdump", str(tmp_path / "absent.json")]) == 1
        assert main(["tsdump", path, "--metric", "no.such.metric"]) == 1
