"""ProcessManager / perf tracing / Fs / metrics / LedgerCloseMeta tests.

Reference test model: src/process/test/ProcessTests.cpp,
src/util/test (Fs, TmpDir), medida usage tests, LedgerCloseMetaStream
tests.
"""

import os

import pytest

from stellar_core_tpu import xdr as X
from stellar_core_tpu.util import fs, metrics, perf
from stellar_core_tpu.util.clock import ClockMode, VirtualClock
from stellar_core_tpu.util.process import ProcessManager


class TestProcessManager:
    def test_run_command_exit_codes_on_clock_loop(self):
        clock = VirtualClock(ClockMode.REAL_TIME)
        pm = ProcessManager(clock)
        results = []
        pm.run_command("true", lambda code: results.append(("true", code)))
        pm.run_command("false", lambda code: results.append(("false", code)))
        ok = clock.crank_until(lambda: len(results) == 2, timeout=10)
        assert ok and dict(results) == {"true": 0, "false": 1}
        pm.shutdown()

    def test_spawn_failure_reports_127(self):
        clock = VirtualClock(ClockMode.REAL_TIME)
        pm = ProcessManager(clock)
        results = []
        pm.run_command("/definitely/not/a/binary",
                       lambda code: results.append(code))
        assert clock.crank_until(lambda: results == [127], timeout=5)
        pm.shutdown()

    def test_concurrency_bound_and_queueing(self):
        clock = VirtualClock(ClockMode.REAL_TIME)
        pm = ProcessManager(clock, max_concurrent=2)
        results = []
        for i in range(5):
            pm.run_command("sleep 0.05", lambda code: results.append(code))
        assert pm.num_running <= 2
        assert clock.crank_until(lambda: len(results) == 5, timeout=15)
        assert results == [0] * 5
        pm.shutdown()

    def test_shutdown_kills_running(self):
        clock = VirtualClock(ClockMode.REAL_TIME)
        pm = ProcessManager(clock)
        ev = pm.run_command("sleep 30", lambda code: None)
        assert clock.crank_until(lambda: ev.running, timeout=5)
        pm.shutdown()
        assert ev.done and ev.exit_code != 0

    # a child that exits 0 on SIGTERM (the well-behaved fleet node)
    _POLITE = ('python3 -c "import signal,sys,time; '
               "signal.signal(signal.SIGTERM, lambda *a: sys.exit(0)); "
               '[time.sleep(0.05) for _ in range(600)]"')
    # a child that ignores SIGTERM outright (the wedged node the
    # escalation exists for)
    _STUBBORN = ('python3 -c "import signal,time; '
                 "signal.signal(signal.SIGTERM, signal.SIG_IGN); "
                 '[time.sleep(0.05) for _ in range(600)]"')

    def test_stop_graceful_child_exits_zero(self):
        clock = VirtualClock(ClockMode.REAL_TIME)
        pm = ProcessManager(clock)
        results = []
        ev = pm.run_command(self._POLITE, results.append)
        assert clock.crank_until(lambda: ev.running, timeout=10)
        import time
        time.sleep(0.3)   # let the child install its handler
        pm.stop(ev, grace_s=8.0)
        assert clock.crank_until(lambda: results != [], timeout=10)
        # SIGTERM honored inside the grace window: clean exit, no SIGKILL
        assert results == [0]
        pm.shutdown()

    def test_stop_escalates_sigkill_on_signal_ignoring_child(self):
        clock = VirtualClock(ClockMode.REAL_TIME)
        pm = ProcessManager(clock)
        results = []
        ev = pm.run_command(self._STUBBORN, results.append)
        assert clock.crank_until(lambda: ev.running, timeout=10)
        import time
        time.sleep(0.3)   # let the child ignore SIGTERM first
        pm.stop(ev, grace_s=0.5)
        assert clock.crank_until(lambda: results != [], timeout=15)
        # the grace period expired and the escalation SIGKILLed it
        assert results == [-9]
        pm.shutdown()

    def test_stop_of_pending_command_still_fires_on_exit(self):
        """stop()'s contract: unlike cancel(), on_exit fires — including
        for a command still queued behind the concurrency bound."""
        clock = VirtualClock(ClockMode.REAL_TIME)
        pm = ProcessManager(clock, max_concurrent=1)
        results = []
        blocker = pm.run_command("sleep 30", lambda code: None)
        queued = pm.run_command("true", results.append)
        assert queued.proc is None          # still pending
        pm.stop(queued, grace_s=1.0)
        assert clock.crank_until(lambda: results == [-1], timeout=5)
        assert queued.done
        pm.shutdown()

    def test_shutdown_with_grace_terms_then_kills(self):
        clock = VirtualClock(ClockMode.REAL_TIME)
        pm = ProcessManager(clock)
        polite = pm.run_command(self._POLITE, lambda code: None)
        stubborn = pm.run_command(self._STUBBORN, lambda code: None)
        assert clock.crank_until(
            lambda: polite.running and stubborn.running, timeout=10)
        import time
        time.sleep(0.3)
        pm.shutdown(grace_s=1.0)
        assert polite.done and stubborn.done
        assert polite.exit_code == 0        # honored SIGTERM
        assert stubborn.exit_code == -9     # needed the escalation
        # no orphans either way
        assert polite.proc.poll() is not None
        assert stubborn.proc.poll() is not None


class TestPerf:
    def test_scoped_timer_feeds_metrics_registry(self):
        metrics.reset_registry()
        with perf.scoped_timer("unit-test-scope", slow_threshold=None):
            pass
        with perf.scoped_timer("unit-test-scope", slow_threshold=None):
            pass
        snap = metrics.registry().snapshot()["unit-test-scope"]
        assert snap["count"] == 2 and snap["max_s"] >= 0

    def test_slow_scope_warns(self, caplog):
        import logging as pylog
        with caplog.at_level(pylog.WARNING, logger="stellar.Perf"):
            with perf.scoped_timer("slow-scope", slow_threshold=0.0):
                pass
        assert any("slow-scope" in r.message for r in caplog.records)


class TestFs:
    def test_durable_write_and_tmpdir(self, tmp_path):
        p = str(tmp_path / "f.bin")
        fs.durable_write(p, b"hello")
        assert open(p, "rb").read() == b"hello"
        fs.durable_write(p, b"world")          # overwrite is atomic
        assert open(p, "rb").read() == b"world"
        with fs.TmpDir(str(tmp_path)) as td:
            scratch = td.path
            open(os.path.join(scratch, "x"), "w").write("1")
        assert not os.path.isdir(scratch)

    def test_lockfile_excludes_second_locker(self, tmp_path):
        p = str(tmp_path / "db.lock")
        fd = fs.lock_file(p)
        with pytest.raises(RuntimeError, match="locked"):
            fs.lock_file(p)
        fs.unlock_file(fd)
        fd2 = fs.lock_file(p)
        fs.unlock_file(fd2)


class TestMetrics:
    def test_counter_meter_timer(self):
        reg = metrics.MetricsRegistry()
        reg.counter("a.b.c").inc(3)
        reg.meter("scp.envelope.receive").mark(5)
        with reg.timer("ledger.close").time():
            pass
        snap = reg.snapshot()
        assert snap["a.b.c"]["count"] == 3
        assert snap["scp.envelope.receive"]["count"] == 5
        assert snap["ledger.close"]["count"] == 1
        pref = reg.snapshot(prefix="scp.")
        assert list(pref) == ["scp.envelope.receive"]
        assert pref["scp.envelope.receive"]["count"] == 5

    def test_ledger_close_feeds_registry(self):
        from stellar_core_tpu.ledger.manager import LedgerManager
        from stellar_core_tpu.testutils import (TestAccount,
                                                create_account_op,
                                                network_id)
        from stellar_core_tpu.crypto.keys import SecretKey
        metrics.reset_registry()
        m = LedgerManager(network_id("metrics net"))
        m.start_new_ledger()
        sk = m.root_account_secret()
        e = m.root.get_entry(X.LedgerKey.account(X.LedgerKeyAccount(
            accountID=X.AccountID.ed25519(sk.public_key.ed25519))).to_xdr())
        root = TestAccount(m, sk, e.data.value.seqNum)
        m.close_ledger([root.tx([create_account_op(
            X.AccountID.ed25519(SecretKey(b"\x42" * 32).public_key.ed25519),
            10**10)])], 1000)
        snap = metrics.registry().snapshot()
        assert snap["ledger.ledger.close"]["count"] == 1
        assert snap["ledger.transaction.apply"]["count"] == 1


class TestLedgerCloseMeta:
    def test_meta_stream_emits_frames(self, tmp_path):
        from stellar_core_tpu.ledger.manager import LedgerManager
        from stellar_core_tpu.testutils import (TestAccount,
                                                create_account_op,
                                                network_id)
        from stellar_core_tpu.crypto.keys import SecretKey
        m = LedgerManager(network_id("meta net"))
        m.start_new_ledger()
        path = str(tmp_path / "meta.xdr")
        m.meta_stream = open(path, "ab")
        sk = m.root_account_secret()
        e = m.root.get_entry(X.LedgerKey.account(X.LedgerKeyAccount(
            accountID=X.AccountID.ed25519(sk.public_key.ed25519))).to_xdr())
        root = TestAccount(m, sk, e.data.value.seqNum)
        arts = m.close_ledger([root.tx([create_account_op(
            X.AccountID.ed25519(SecretKey(b"\x43" * 32).public_key.ed25519),
            10**10)])], 1000)
        m.close_ledger([], 1001)
        m.meta_stream.close()
        raw = open(path, "rb").read()
        metas = []
        off = 0
        while off < len(raw):
            n = int.from_bytes(raw[off:off + 4], "big")
            metas.append(X.LedgerCloseMeta.from_xdr(raw[off + 4:off + 4 + n]))
            off += 4 + n
        assert len(metas) == 2
        assert metas[0].value.ledgerHeader.hash == arts.header_entry.hash
        assert len(metas[0].value.txProcessing) == 1
        assert len(metas[1].value.txProcessing) == 0
