"""Admin observability surface (ISSUE 1): /metrics JSON + Prometheus
exposition, /clearmetrics continuity, /trace Chrome trace-event export,
/ll level round-trips, and the metric-name lint against the documented
canonical list.

Reference test model: src/main/test/CommandHandlerTests.cpp plus medida
exposition shape checks.
"""

import json
import re
import threading
import time
import urllib.request

import pytest

from stellar_core_tpu import xdr as X
from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.main.config import Config
from stellar_core_tpu.util import metrics, tracing

# Prometheus text exposition: every non-comment line is
# `name{labels} value`; TYPE comments carry a known metric kind.
_PROM_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? (-?[0-9.eE+-]+|NaN|[+-]Inf)$")
_PROM_TYPE = re.compile(
    r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* (counter|gauge|summary|histogram)$")


def _assert_prometheus_parses(text: str) -> int:
    samples = 0
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("#"):
            assert _PROM_TYPE.match(line), f"bad comment line: {line!r}"
            continue
        assert _PROM_SAMPLE.match(line), f"unparseable sample: {line!r}"
        samples += 1
    assert samples > 0
    return samples


def _close_ledgers_with_txs(passphrase: str, n: int = 2):
    """A standalone LedgerManager closing `n` ledgers of 1 tx each (the
    simulated ledger close the lint and trace tests observe)."""
    from stellar_core_tpu.ledger.manager import LedgerManager
    from stellar_core_tpu.testutils import (TestAccount, create_account_op,
                                            network_id)
    m = LedgerManager(network_id(passphrase))
    m.start_new_ledger()
    sk = m.root_account_secret()
    e = m.root.get_entry(X.LedgerKey.account(X.LedgerKeyAccount(
        accountID=X.AccountID.ed25519(sk.public_key.ed25519))).to_xdr())
    root = TestAccount(m, sk, e.data.value.seqNum)
    for i in range(n):
        dest = SecretKey(bytes([0x60 + i]) * 32)
        m.close_ledger([root.tx([create_account_op(
            X.AccountID.ed25519(dest.public_key.ed25519), 10**10)])],
            1000 + i)
    return m


@pytest.fixture()
def app_http(tmp_path):
    """A standalone in-process node with a live admin HTTP server on an
    ephemeral port."""
    from stellar_core_tpu.main.application import Application
    from stellar_core_tpu.main.http_admin import CommandHandler
    from stellar_core_tpu.util.clock import ClockMode, VirtualClock

    metrics.reset_registry()
    cfg = Config.from_dict({
        "NETWORK_PASSPHRASE": "observability test net",
        "RUN_STANDALONE": True,
        "PEER_PORT": 0,
    })
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    app = Application(cfg, clock=clock, listen=False)
    http = CommandHandler(app, 0)
    http.start()
    app.start()
    assert clock.crank_until(
        lambda: app.lm.last_closed_ledger_seq >= 3, timeout=60)
    try:
        yield app, clock, http.port
    finally:
        http.stop()
        app.stop()


def _http_get(port, path, timeout=10.0):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
        return r.read(), r.headers.get("Content-Type", "")


def _http_get_cranking(clock, port, path, timeout=10.0):
    """GET while cranking the clock on this thread — marshalled endpoints
    (/clearmetrics) block their HTTP thread on the main crank loop."""
    box = {}

    def go():
        try:
            box["resp"] = _http_get(port, path, timeout)
        except Exception as e:  # surfaced below
            box["err"] = e

    t = threading.Thread(target=go)
    t.start()
    deadline = time.time() + timeout
    while t.is_alive() and time.time() < deadline:
        clock.crank()
        time.sleep(0.002)
    t.join(1.0)
    assert "err" not in box, box.get("err")
    assert "resp" in box, "request did not complete"
    return box["resp"]


class TestMetricsEndpoint:
    def test_json_snapshot_has_percentiles(self, app_http):
        app, clock, port = app_http
        body, ctype = _http_get(port, "/metrics")
        assert ctype.startswith("application/json")
        doc = json.loads(body)["metrics"]
        reg = doc["registry"]
        close = reg["ledger.ledger.close"]
        assert close["count"] >= 2
        for k in ("p50_s", "p90_s", "p99_s", "max_s", "mean_s"):
            assert k in close
        assert close["p50_s"] <= close["p99_s"] <= close["max_s"] * 1.0001
        # gauges surface live values
        assert reg["herder.tx-queue.depth"]["type"] == "gauge"

    def test_prometheus_exposition_parses(self, app_http):
        app, clock, port = app_http
        body, ctype = _http_get(port, "/metrics?format=prometheus")
        assert ctype.startswith("text/plain")
        text = body.decode()
        _assert_prometheus_parses(text)
        assert "stellar_core_tpu_ledger_ledger_close_seconds" in text
        assert 'quantile="0.99"' in text
        assert "stellar_core_tpu_herder_ledger_externalize_total" in text
        assert "stellar_core_tpu_herder_tx_queue_depth" in text

    def test_clearmetrics_then_continued_recording(self, app_http):
        app, clock, port = app_http
        before = json.loads(_http_get(port, "/metrics")[0])
        assert before["metrics"]["registry"]["ledger.ledger.close"]["count"] \
            >= 2
        body, _ = _http_get_cranking(clock, port, "/clearmetrics")
        assert json.loads(body).get("status") == "cleared"
        cleared = json.loads(_http_get(port, "/metrics")[0])
        assert cleared["metrics"]["registry"]["ledger.ledger.close"]["count"] \
            == 0
        # the node keeps recording into the SAME metric objects after the
        # clear (the old clear() replaced the dict and orphaned every
        # cached call-site reference — samples vanished silently)
        seq = app.lm.last_closed_ledger_seq
        deadline = time.time() + 30
        while app.lm.last_closed_ledger_seq < seq + 2 \
                and time.time() < deadline:
            clock.crank()
        after = json.loads(_http_get(port, "/metrics")[0])
        assert after["metrics"]["registry"]["ledger.ledger.close"]["count"] \
            >= 2

    def test_ll_level_roundtrip(self, app_http):
        app, clock, port = app_http
        doc = json.loads(_http_get(port, "/ll")[0])
        assert "levels" in doc and "Ledger" in doc["levels"]
        doc = json.loads(
            _http_get(port, "/ll?level=debug&partition=Ledger")[0])
        assert doc["status"] == "ok" and doc["level"] == "DEBUG"
        levels = json.loads(_http_get(port, "/ll")[0])["levels"]
        assert levels["Ledger"] == "DEBUG"
        doc = json.loads(_http_get(port, "/ll?level=info&partition=Ledger")[0])
        assert doc["partition"] == "Ledger"
        levels = json.loads(_http_get(port, "/ll")[0])["levels"]
        assert levels["Ledger"] == "INFO"
        # partition-less set targets the root logger
        doc = json.loads(_http_get(port, "/ll?level=info")[0])
        assert doc["partition"] == "all"
        assert json.loads(_http_get(port, "/ll")[0])["levels"]["(root)"] \
            == "INFO"


class TestTraceEndpoint:
    @staticmethod
    def _nesting_depth(events):
        """Max nesting of "X" complete events by interval containment
        within each tid (how chrome://tracing stacks them)."""
        depth = 0
        by_tid = {}
        for e in events:
            by_tid.setdefault(e["tid"], []).append(
                (e["ts"], e["ts"] + e["dur"]))
        for spans in by_tid.values():
            for s0, s1 in spans:
                d = sum(1 for t0, t1 in spans if t0 <= s0 and s1 <= t1)
                depth = max(depth, d)
        return depth

    def test_trace_export_shape_and_nesting(self, app_http):
        app, clock, port = app_http
        # a non-empty ledger close traces ledger.close > ledger.tx-apply
        # > tx.apply; drive one tx through the live node
        from stellar_core_tpu.testutils import TestAccount, create_account_op
        sk = app.lm.root_account_secret()
        e = app.lm.root.get_entry(X.LedgerKey.account(X.LedgerKeyAccount(
            accountID=X.AccountID.ed25519(sk.public_key.ed25519))).to_xdr())
        root = TestAccount(app.lm, sk, e.data.value.seqNum)
        frame = root.tx([create_account_op(
            X.AccountID.ed25519(SecretKey(b"\x71" * 32).public_key.ed25519),
            10**10)])
        res = app.submit_tx(frame.envelope.to_xdr())
        assert res["status"] == "PENDING", res
        seq = app.lm.last_closed_ledger_seq
        assert clock.crank_until(
            lambda: app.lm.last_closed_ledger_seq >= seq + 2, timeout=60)

        body, ctype = _http_get(port, "/trace")
        assert ctype.startswith("application/json")
        doc = json.loads(body)
        events = doc["traceEvents"]
        assert isinstance(events, list) and events
        for ev in events:
            assert ev["ph"] == "X"
            for key in ("name", "ts", "dur", "pid", "tid"):
                assert key in ev
        names = {e["name"] for e in events}
        assert {"ledger.close", "ledger.tx-apply", "tx.apply"} <= names
        assert self._nesting_depth(events) >= 3

    def test_catchup_replay_trace_and_dump(self, tmp_path):
        """Catchup replay traces catchup.apply-checkpoint above the ledger
        close tree (>= 3 levels), and dump_trace writes valid Chrome trace
        JSON (the acceptance-criteria artifact)."""
        from stellar_core_tpu.catchup.catchup import CatchupManager
        from stellar_core_tpu.history.archive import FileHistoryArchive
        from stellar_core_tpu.history.manager import HistoryManager
        from stellar_core_tpu.ledger.manager import LedgerManager
        from stellar_core_tpu.simulation.loadgen import LoadGenerator
        from stellar_core_tpu.testutils import network_id

        passphrase = "obs catchup net"
        nid = network_id(passphrase)
        src = LedgerManager(nid)
        src.start_new_ledger()
        archive = FileHistoryArchive(str(tmp_path / "arch"))
        hist = HistoryManager(src, passphrase, [archive])
        gen = LoadGenerator(src, hist, seed=23)
        gen.create_accounts(8, per_ledger=8)
        gen.payment_ledgers(4, txs_per_ledger=2)
        gen.run_to_checkpoint_boundary()

        tracing.trace_buffer().clear()
        # native=False keeps the replay on the Python close path — the one
        # with the span tree (the C engine traces only the checkpoint span)
        cm = CatchupManager(nid, passphrase, native=False)
        mgr = cm.catchup_complete(archive)
        assert mgr.lcl_hash == src.lcl_hash

        roots = tracing.trace_buffer().roots()
        cp_roots = [r for r in roots if r.name == "catchup.apply-checkpoint"]
        assert cp_roots
        assert max(r.depth() for r in cp_roots) >= 3

        path = str(tmp_path / "trace.json")
        n = tracing.dump_trace(path)
        doc = json.load(open(path))
        assert len(doc["traceEvents"]) == n > 0
        assert self._nesting_depth(doc["traceEvents"]) >= 3


class TestMetricNameLint:
    """Satellite: every metric recorded by a simulated ledger close +
    node activity matches the naming scheme and is in the documented
    canonical list (util.metrics.CANONICAL_METRICS / README.md)."""

    def test_canonical_list_is_well_formed(self):
        for name in metrics.CANONICAL_METRICS:
            assert metrics.METRIC_NAME_RE.match(name), name
        for prefix in metrics.CANONICAL_PREFIXES:
            assert metrics.METRIC_NAME_RE.match(prefix + "x"), prefix

    def test_metric_name_lint(self, app_http):
        app, clock, port = app_http
        # add a direct simulated close so ledger/bucket/crypto families
        # are present even if the node closed only empty ledgers
        _close_ledgers_with_txs("obs lint net")
        names = metrics.registry().names()
        assert names, "registry empty — nothing was instrumented?"
        undocumented = []
        for name in names:
            assert metrics.METRIC_NAME_RE.match(name), \
                f"metric {name!r} violates layer.subsystem.event naming"
            if name not in metrics.CANONICAL_METRICS and not any(
                    name.startswith(p) for p in metrics.CANONICAL_PREFIXES):
                undocumented.append(name)
        assert not undocumented, \
            f"metrics not in the documented canonical list: {undocumented}"
        # the families the sweep promises are actually present
        for family in ("ledger.", "scp.", "herder.", "bucket.", "crypto."):
            assert any(n.startswith(family) for n in names), family


class TestMeterAndClearSemantics:
    """Satellites: Meter.snapshot staleness + clear-in-place."""

    def test_meter_recent_rate_live_before_window_rolls(self):
        m = metrics.Meter()
        m.mark(30)
        snap = m.snapshot()
        # old behavior: 0.0 until a full 60s window elapsed
        assert snap["recent_rate"] > 0.0
        assert snap["count"] == 30

    def test_meter_rate_reflects_overdue_window(self):
        m = metrics.Meter()
        m.mark(10)
        # simulate 120s elapsed with no further marks: the rate must decay
        # (the old code froze at the last completed window's value)
        m._win_start -= 120.0
        assert m.snapshot()["recent_rate"] == pytest.approx(10 / 120.0,
                                                            rel=0.2)

    def test_clear_resets_in_place(self):
        reg = metrics.MetricsRegistry()
        t = reg.timer("ledger.ledger.close")
        c = reg.counter("overlay.byte.read")
        t.update(0.5)
        c.inc(7)
        reg.clear()
        assert reg.timer("ledger.ledger.close") is t  # same object
        assert t.snapshot()["count"] == 0
        assert c.snapshot()["count"] == 0
        # call sites holding direct references keep recording
        t.update(0.25)
        c.inc(1)
        assert reg.snapshot()["ledger.ledger.close"]["count"] == 1
        assert reg.snapshot()["overlay.byte.read"]["count"] == 1

    def test_histogram_percentiles(self):
        h = metrics.Histogram()
        for v in range(1, 101):
            h.update(float(v))
        snap = h.snapshot()
        assert snap["count"] == 100
        assert 40 <= snap["p50"] <= 60
        assert 85 <= snap["p90"] <= 95
        assert snap["p99"] >= 95
        assert snap["max"] == 100.0

    def test_gauge_callable_backed(self):
        reg = metrics.MetricsRegistry()
        box = {"v": 1}
        reg.gauge("herder.tx-queue.depth", lambda: box["v"])
        assert reg.snapshot()["herder.tx-queue.depth"]["value"] == 1
        box["v"] = 42
        assert reg.snapshot()["herder.tx-queue.depth"]["value"] == 42


class TestScopedTimerThresholds:
    """Satellite: per-name slow-threshold overrides."""

    def test_override_controls_warning(self, caplog):
        import logging as pylog
        from stellar_core_tpu.util import perf
        perf.set_slow_threshold("obs-hot-scope", 0.0)
        try:
            with caplog.at_level(pylog.WARNING, logger="stellar.Perf"):
                with perf.scoped_timer("obs-hot-scope"):
                    pass
            assert any("obs-hot-scope" in r.message for r in caplog.records)
            caplog.clear()
            perf.set_slow_threshold("obs-hot-scope", 1e9)
            with caplog.at_level(pylog.WARNING, logger="stellar.Perf"):
                with perf.scoped_timer("obs-hot-scope"):
                    pass
            assert not any("obs-hot-scope" in r.message
                           for r in caplog.records)
        finally:
            perf.set_slow_threshold("obs-hot-scope", None)


class TestPrometheusCompleteness:
    """Satellite (ISSUE 20): the Prometheus exposition drops nothing —
    every name the registry holds (canonical list or prefix family)
    appears in /metrics?format=prometheus, whatever its type."""

    def test_every_registered_name_is_exported(self, app_http):
        app, clock, port = app_http
        names = metrics.registry().names()
        assert names
        body, _ = _http_get(port, "/metrics?format=prometheus")
        text = body.decode()
        missing = [
            n for n in names
            if f"stellar_core_tpu_{metrics._prom_name(n)}" not in text]
        assert not missing, \
            f"registered metrics absent from exposition: {missing}"
        # the canonical list itself is exercised, not vacuously empty
        assert any(n in metrics.CANONICAL_METRICS for n in names)

    def test_dead_gauges_export_as_nan_not_dropped(self):
        reg = metrics.MetricsRegistry()

        class _Obj:
            pass

        obj = _Obj()
        obj.v = 1.0
        reg.weak_gauge("herder.tx-queue.depth", obj, lambda o: o.v)
        del obj
        import gc
        gc.collect()
        text = metrics.render_prometheus(reg.snapshot())
        assert "stellar_core_tpu_herder_tx_queue_depth NaN" in text


@pytest.fixture()
def telemetry_http(tmp_path, monkeypatch):
    """app_http with the historical-telemetry plane enabled: capture
    timer, anomaly evaluation timer, close-cost ledger reads."""
    from stellar_core_tpu.main.application import Application
    from stellar_core_tpu.main.http_admin import CommandHandler
    from stellar_core_tpu.util.clock import ClockMode, VirtualClock

    monkeypatch.setenv("STPU_CRASH_DIR", str(tmp_path))
    metrics.reset_registry()
    cfg = Config.from_dict({
        "NETWORK_PASSPHRASE": "telemetry test net",
        "RUN_STANDALONE": True,
        "PEER_PORT": 0,
        "TIMESERIES_CADENCE_S": 1.0,
        "ANOMALY_EVAL_CADENCE_S": 1.0,
    })
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    app = Application(cfg, clock=clock, listen=False)
    http = CommandHandler(app, 0)
    http.start()
    app.start()
    assert clock.crank_until(
        lambda: app.lm.last_closed_ledger_seq >= 4
        and app.timeseries.seq >= 4, timeout=120)
    try:
        yield app, clock, http.port
    finally:
        http.stop()
        app.stop()


class TestTimeseriesEndpoint:
    """Satellite (ISSUE 20): /timeseries round-trips with the
    /tracespans watermark contract."""

    def test_roundtrip_serves_reconstructed_history(self, telemetry_http):
        app, clock, port = telemetry_http
        doc = json.loads(_http_get(port, "/timeseries")[0])
        assert doc["next_since"] == app.timeseries.seq
        assert doc["cadence_s"] == 1.0
        pts = doc["series"]["ledger.ledger.close"]
        assert len(pts) >= 4
        seqs = [p["seq"] for p in pts]
        assert seqs == sorted(seqs)
        assert all("count" in p["v"] for p in pts)

    def test_watermark_incremental(self, telemetry_http):
        app, clock, port = telemetry_http
        mark = json.loads(_http_get(port, "/timeseries")[0])["next_since"]
        assert clock.crank_until(
            lambda: app.timeseries.seq > mark, timeout=60)
        incr = json.loads(
            _http_get(port, f"/timeseries?since={mark}")[0])
        assert incr["series"], "no new points past the watermark"
        for pts in incr["series"].values():
            assert all(p["seq"] > mark for p in pts)
        # fully caught up: empty document, stable watermark
        done = json.loads(_http_get(
            port, f"/timeseries?since={incr['next_since']}")[0])
        assert done["series"] == {}

    def test_metric_filter(self, telemetry_http):
        app, clock, port = telemetry_http
        doc = json.loads(_http_get(
            port, "/timeseries?metric=ledger.ledger.close")[0])
        assert list(doc["series"]) == ["ledger.ledger.close"]

    def test_404_without_store(self, app_http):
        app, clock, port = app_http
        assert app.timeseries is None
        import urllib.error
        with pytest.raises(urllib.error.HTTPError) as ei:
            _http_get(port, "/timeseries")
        assert ei.value.code == 404

    @pytest.mark.parametrize("path", [
        "/timeseries?since=bogus",
        "/timeseries?metric=NotALegalName",
        "/timeseries?metric=nodots",
    ])
    def test_malformed_params_answer_400(self, telemetry_http, path):
        app, clock, port = telemetry_http
        import urllib.error
        with pytest.raises(urllib.error.HTTPError) as ei:
            _http_get(port, path)
        assert ei.value.code == 400
        assert "error" in json.loads(ei.value.read())


class TestClosecostsEndpoint:
    """Satellite (ISSUE 20): the per-close cost ledger's admin read."""

    def test_roundtrip_and_watermark(self, telemetry_http):
        app, clock, port = telemetry_http
        doc = json.loads(_http_get(port, "/closecosts")[0])
        recs = doc["records"]
        assert recs, "no close-cost records after closed ledgers"
        for field in ("export_seq", "seq", "txs", "total_s", "fee_s",
                      "apply_s", "seal_s", "merge_stall_s", "cache_hits",
                      "cache_misses", "pin_count", "resident_entries",
                      "resident_delta", "gc_backlog"):
            assert field in recs[0], field
        mark = doc["next_since"]
        assert mark == recs[-1]["export_seq"]
        assert clock.crank_until(
            lambda: app.lm.close_costs.next_since > mark, timeout=60)
        incr = json.loads(
            _http_get(port, f"/closecosts?since={mark}")[0])
        assert incr["records"]
        assert all(r["export_seq"] > mark for r in incr["records"])

    def test_malformed_since_answers_400(self, telemetry_http):
        app, clock, port = telemetry_http
        import urllib.error
        with pytest.raises(urllib.error.HTTPError) as ei:
            _http_get(port, "/closecosts?since=xyz")
        assert ei.value.code == 400
