"""Test harness config: force a deterministic 8-device CPU mesh for JAX.

Multi-chip sharding (the v5e-8 target topology) is tested on virtual CPU
devices via --xla_force_host_platform_device_count; the real-TPU path is
exercised by bench.py and the driver's dryrun.

NOTE: the axon PJRT plugin force-selects itself regardless of the
JAX_PLATFORMS env var (verified in-session), so we must override via
jax.config before any backend initialization — hence the eager jax import
here, before any test module loads.  jax-less environments still run the
jax-independent suites (accel tests importorskip).
"""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except ImportError:
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Native extensions are built on demand (they are not tracked in git; a
# stale binary would defeat the C-vs-Python differential tests).
from stellar_core_tpu._native_build import ensure_native  # noqa: E402

if not ensure_native(quiet=False):
    sys.stderr.write(
        "WARNING: native extensions failed to build — C-vs-Python "
        "differential tests will skip and cannot validate native/*.c\n")


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long end-to-end tests")
