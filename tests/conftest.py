"""Test harness config: force a deterministic 8-device CPU mesh for JAX.

Multi-chip sharding (the v5e-8 target topology) is tested on virtual CPU
devices via --xla_force_host_platform_device_count; the real-TPU path is
exercised by bench.py and the driver's dryrun.

NOTE: the axon PJRT plugin force-selects itself regardless of the
JAX_PLATFORMS env var (verified in-session), so we must override via
jax.config before any backend initialization — hence the eager jax import
here, before any test module loads.  jax-less environments still run the
jax-independent suites (accel tests importorskip).
"""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except ImportError:
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long end-to-end tests")
