"""Test harness config: force a deterministic 8-device CPU mesh for JAX.

Multi-chip sharding (the v5e-8 target topology) is tested on virtual CPU
devices via --xla_force_host_platform_device_count; the real-TPU path is
exercised by bench.py and the driver's dryrun. Must run before jax imports.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
