"""Exactness tests for the GF(2^255-19) limb arithmetic (fast, CPU).

Every op is checked against python big-int ground truth, including a long
mul/sub chain that stress-tests the partial-reduction invariant fe_carry
documents (the written safety argument for int64 exactness)."""

import random

import numpy as np
import pytest

F = pytest.importorskip("stellar_core_tpu.accel.field")
jnp = pytest.importorskip("jax.numpy")


def _limbs(xs):
    return jnp.asarray(F.ints_to_limbs(xs))


def test_roundtrip_int_limbs():
    for x in (0, 1, 19, F.P - 1, 2 ** 255 - 20, 12345678901234567890):
        assert F.limbs_to_int(F.int_to_limbs(x)) == x


def test_ops_match_bigint():
    rng = random.Random(7)
    xs = [rng.randrange(F.P) for _ in range(16)] + [0, 1, F.P - 1, (1 << 255) - 20]
    ys = [rng.randrange(F.P) for _ in range(len(xs))]
    ax, ay = _limbs(xs), _limbs(ys)
    mul = np.asarray(F.fe_canonical(F.fe_mul(ax, ay)))
    add = np.asarray(F.fe_canonical(F.fe_add(ax, ay)))
    sub = np.asarray(F.fe_canonical(F.fe_sub(ax, ay)))
    for i, (x, y) in enumerate(zip(xs, ys)):
        assert F.limbs_to_int(mul[i]) == x * y % F.P
        assert F.limbs_to_int(add[i]) == (x + y) % F.P
        assert F.limbs_to_int(sub[i]) == (x - y) % F.P


def test_invert():
    rng = random.Random(8)
    xs = [rng.randrange(1, F.P) for _ in range(8)]
    inv = np.asarray(F.fe_canonical(F.fe_invert(_limbs(xs))))
    for i, x in enumerate(xs):
        assert F.limbs_to_int(inv[i]) * x % F.P == 1
    # 0^(p-2) = 0 (matches ref10's branchless inversion semantics)
    z = np.asarray(F.fe_canonical(F.fe_invert(_limbs([0]))))
    assert F.limbs_to_int(z[0]) == 0


def test_long_chain_stays_exact():
    rng = random.Random(9)
    xs = [rng.randrange(F.P) for _ in range(4)]
    ys = [rng.randrange(F.P) for _ in range(4)]
    v = _limbs(xs)
    ay = _limbs(ys)
    acc = xs[:]
    for _ in range(60):
        v = F.fe_mul(v, ay)
        acc = [a * y % F.P for a, y in zip(acc, ys)]
        v = F.fe_sub(v, ay)
        acc = [(a - y) % F.P for a, y in zip(acc, ys)]
    out = np.asarray(F.fe_canonical(v))
    for i in range(4):
        assert F.limbs_to_int(out[i]) == acc[i]


def test_carry_invariant_bound():
    """After fe_carry, limbs stay below 2^16 + 2^10 (the documented closed
    invariant for subsequent ops)."""
    worst = jnp.full((4, F.NLIMB), (1 << 41), dtype=jnp.int64)
    out = np.asarray(F.fe_carry(worst))
    assert out.max() < (1 << 16) + (1 << 10)
