"""Fleet harness tests: provisioning/schedule/SLO units plus the
acceptance-bar e2e — a 5-node fleet of real `stellar-core-tpu run`
processes over real TCP sustaining loadgen traffic through a kill +
`catchup --parallel` rejoin, an overlay partition + heal, and a rolling
config change, with zero hash divergence and every SLO green.

Reference test model: the deployment shape of PAPER.md (Herder tracking a
live network while HistoryManager publishes checkpoints other nodes catch
up from), exercised as real processes — ROADMAP item 5.
"""

import json
import os

import pytest

from stellar_core_tpu.main.config import Config
from stellar_core_tpu.simulation.fleet import (Fleet, FleetSLOs,
                                               parse_schedule,
                                               run_fleet_soak,
                                               standard_schedule)


# ---------------------------------------------------------------------------
# units: provisioning
# ---------------------------------------------------------------------------

class TestProvisioning:
    def test_configs_parse_and_agree_on_the_network(self, tmp_path):
        fleet = Fleet(str(tmp_path), n_nodes=4)
        fleet.provision()
        cfgs = [Config.from_toml(n.conf_path) for n in fleet.nodes]
        # every node agrees on passphrase, quorum and checkpoint cadence
        assert len({c.NETWORK_PASSPHRASE for c in cfgs}) == 1
        assert all(c.checkpoint_frequency() == 8 for c in cfgs)
        assert all(c.QUORUM_SET_THRESHOLD == 3 for c in cfgs)  # majority of 4
        assert all(len(c.QUORUM_SET_VALIDATORS) == 4 for c in cfgs)
        # distinct identities and ports; full-mesh known peers
        seeds = {c.NODE_SEED for c in cfgs}
        assert len(seeds) == 4
        ports = {c.PEER_PORT for c in cfgs} | {c.HTTP_PORT for c in cfgs}
        assert len(ports) == 8
        for i, c in enumerate(cfgs):
            assert len(c.KNOWN_PEERS) == 3
            assert c.DATABASE.endswith(f"node-{i}/node.db")
            # shared archive: every node reads AND publishes (writes are
            # atomic + pid-unique, objects content-identical)
            assert c.HISTORY[0].get_path == fleet.archive_dir
            assert c.HISTORY[0].put_path == fleet.archive_dir
        # genesis boot bootstraps SCP; a provisioned node starts FORCE_SCP
        assert all(c.FORCE_SCP for c in cfgs)
        # every soak carries native-live-close differential spot-checks
        # (ROADMAP 1c): the cadence is provisioned into every node config
        assert all(c.NATIVE_CLOSE_DIFFERENTIAL == 8 for c in cfgs)

    def test_native_differential_cadence_configurable(self, tmp_path):
        fleet = Fleet(str(tmp_path), n_nodes=2,
                      native_close_differential=3)
        fleet.provision()
        cfgs = [Config.from_toml(n.conf_path) for n in fleet.nodes]
        assert all(c.NATIVE_CLOSE_DIFFERENTIAL == 3 for c in cfgs)
        fleet2 = Fleet(str(tmp_path / "off"), n_nodes=2,
                       native_close_differential=0)
        fleet2.provision()
        cfgs2 = [Config.from_toml(n.conf_path) for n in fleet2.nodes]
        assert all(c.NATIVE_CLOSE_DIFFERENTIAL == 0 for c in cfgs2)

    def test_quorum_is_majority_and_intersecting(self, tmp_path):
        fleet = Fleet(str(tmp_path), n_nodes=5)
        assert fleet.threshold == 3           # any two quorums intersect
        fleet2 = Fleet(str(tmp_path / "b"), n_nodes=5, threshold=4)
        assert fleet2.threshold == 4


# ---------------------------------------------------------------------------
# units: schedule
# ---------------------------------------------------------------------------

class TestSchedule:
    def test_parse_rejects_unknown_kind(self):
        with pytest.raises(ValueError, match="unknown fleet event"):
            parse_schedule([{"kind": "explode"}])

    def test_parse_rejects_missing_required_params(self):
        """Schedules are user input (`fleet --schedule`): malformed
        entries must fail at parse time with the entry index, not as a
        KeyError mid-soak after the fleet booted."""
        for bad in ({"kind": "wait-ledger"},
                    {"kind": "rolling-config"},
                    {"kind": "partition"},
                    {"kind": "kill"},
                    {}):
            with pytest.raises(ValueError,
                               match="schedule entry 1"):
                parse_schedule([{"kind": "heal"}, bad])

    def test_parse_rejects_out_of_range_node_indices(self):
        """With the fleet size known, node indices are validated at
        parse time — `fleet --nodes 2` with the standard (kill node 2)
        script must fail before anything boots."""
        with pytest.raises(ValueError, match="out of range"):
            parse_schedule([{"kind": "kill", "node": 2}], n_nodes=2)
        with pytest.raises(ValueError, match="out of range"):
            parse_schedule([{"kind": "partition",
                             "groups": [[0], [1, 5]]}], n_nodes=3)
        with pytest.raises(ValueError, match="out of range"):
            parse_schedule([{"kind": "rolling-config", "overrides": {},
                             "nodes": [0, -1]}], n_nodes=3)
        # in range passes; without n_nodes no index check applies
        assert parse_schedule([{"kind": "kill", "node": 2}], n_nodes=3)
        assert parse_schedule([{"kind": "kill", "node": 9}])

    def test_standard_schedule_keeps_quorum_for_even_fleets(self):
        """The partition's majority side must meet the n//2+1 threshold
        for EVERY fleet size, or the whole network stalls mid-script."""
        for n in (3, 4, 5, 6, 7):
            sched = standard_schedule(n_nodes=n)
            part = [e for e in sched if e["kind"] == "partition"][0]
            majority, minority = part["groups"]
            assert len(majority) >= n // 2 + 1, (n, part["groups"])
            assert 0 in majority
            assert sorted(majority + minority) == list(range(n))

    def test_standard_schedule_covers_the_three_production_events(self):
        sched = standard_schedule(n_nodes=5)
        kinds = [e["kind"] for e in sched]
        assert "kill" in kinds and "rejoin" in kinds
        assert "partition" in kinds and "heal" in kinds
        assert "rolling-config" in kinds
        # the rejoin follows its kill and targets the same node
        kill = sched[kinds.index("kill")]
        rejoin = sched[kinds.index("rejoin")]
        assert kinds.index("rejoin") > kinds.index("kill")
        assert rejoin["node"] == kill["node"]
        # the partition keeps a closing quorum on the writer's side
        part = sched[kinds.index("partition")]
        majority, minority = part["groups"]
        assert 0 in majority
        assert len(majority) >= 3     # >= threshold: ledgers keep closing
        assert kill["node"] in majority
        # every event round-trips the parser
        assert len(parse_schedule(sched)) == len(sched)

    def test_events_roundtrip_describe(self):
        events = parse_schedule(standard_schedule(n_nodes=5))
        for e in events:
            d = e.describe()
            assert d["kind"] in ("wait-ledger", "wait-s", "traffic", "kill",
                                 "rejoin", "partition", "heal",
                                 "rolling-config")


# ---------------------------------------------------------------------------
# units: SLO evaluation (no processes)
# ---------------------------------------------------------------------------

class TestSLOEvaluation:
    def _quiet_fleet(self, tmp_path, slos=None):
        fleet = Fleet(str(tmp_path), n_nodes=3, slos=slos)
        fleet.provision()
        return fleet

    def test_divergence_detected_and_reported(self, tmp_path):
        fleet = self._quiet_fleet(tmp_path)
        fleet.hash_by_seq = {
            5: {0: "aa" * 32, 1: "aa" * 32, 2: "aa" * 32},
            6: {0: "aa" * 32, 1: "bb" * 32},          # fork!
        }
        report = fleet.finalize()
        assert not report["passed"]
        assert any("HASH DIVERGENCE at ledger 6" in v
                   for v in report["violations"])
        assert report["divergence_seqs_compared"] == 2

    def test_identical_hashes_pass_and_write_report(self, tmp_path):
        fleet = self._quiet_fleet(tmp_path)
        fleet.hash_by_seq = {5: {0: "aa" * 32, 1: "aa" * 32}}
        report = fleet.finalize()
        assert report["passed"] and report["violations"] == []
        on_disk = json.load(open(report["report_path"]))
        assert on_disk["passed"] is True
        assert on_disk["nodes"] == 3
        # the artifact is replayable: it carries the schedule input and
        # per-node config/log paths
        assert "schedule" in on_disk
        assert all("conf" in n and "log" in n
                   for n in on_disk["node_artifacts"])

    def test_retracking_budget_enforced(self, tmp_path):
        fleet = self._quiet_fleet(
            tmp_path, slos=FleetSLOs(max_retracking_s=10.0))
        fleet.metrics["retracking_s"] = 55.5
        report = fleet.finalize()
        assert any("time-to-retracking 55.5s" in v
                   for v in report["violations"])

    def test_shed_rate_budget_enforced(self, tmp_path):
        fleet = self._quiet_fleet(
            tmp_path, slos=FleetSLOs(max_shed_rate=0.10))
        fleet.client.offered = 100
        fleet.client.statuses = {"PENDING": 60, "TRY-AGAIN-LATER": 40}
        report = fleet.finalize()
        assert any("shed rate" in v for v in report["violations"])
        assert report["traffic"]["shed_rate"] == 0.4


# ---------------------------------------------------------------------------
# the acceptance bar (real processes, real TCP, real archive)
# ---------------------------------------------------------------------------

class TestFleetEndToEnd:
    def test_five_nodes_kill_rejoin_partition_roll_no_divergence(
            self, tmp_path):
        """ISSUE 11 acceptance: a 5-node fleet over real TCP sustains
        loadgen traffic through a kill + `catchup --parallel` rejoin, an
        overlay partition + heal, and a rolling config change with zero
        hash divergence and all SLO assertions green."""
        report = run_fleet_soak(
            str(tmp_path), n_nodes=5, traffic_rate=25.0, n_accounts=60,
            slos=FleetSLOs(max_p99_close_s=2.0, max_shed_rate=0.35,
                           max_retracking_s=90.0, max_roll_node_s=60.0),
            timeout_s=420.0)
        assert report["passed"], report["violations"]
        # all three production events actually happened
        assert report["metrics"].get("retracking_s") is not None
        assert len(report["metrics"].get("roll_node_s", {})) == 5
        # traffic flowed and was not all shed
        assert report["traffic"]["statuses"].get("PENDING", 0) > 50
        assert report["traffic"]["shed_rate"] <= 0.35
        # divergence proof compared real multi-node samples
        assert report["divergence_seqs_compared"] >= 5
        # the rejoin really was a parallel catchup against the live
        # archive: the worker's log shows the range/stitch machinery
        node2 = os.path.join(str(tmp_path), "node-2")
        catchup_log = open(os.path.join(node2, "catchup.log")).read()
        assert "ranges" in catchup_log and "stitches verified" in \
            catchup_log, catchup_log[-500:]
        # the archive kept publishing throughout (live HistoryManager)
        assert report["archive_tip"] is not None
        assert report["archive_tip"] >= 15
        # flight records exist for every node
        for n in range(5):
            assert os.path.exists(
                os.path.join(str(tmp_path), f"node-{n}", "node.log"))
        # ISSUE 16 acceptance: finalize() merged every node's phase
        # marks into ONE Chrome trace on an aligned timebase — one row
        # per node, the rejoined node's marks against the others' closes
        obs = report["observability"]
        assert os.path.exists(obs["trace_path"])
        events = json.load(open(obs["trace_path"]))["traceEvents"]
        rows = {e["args"]["name"] for e in events
                if e.get("ph") == "M" and e.get("name") == "process_name"}
        assert {f"node-{n}" for n in range(5)} <= rows
        assert obs["trace_events"] == len(events)
        marks = [e for e in events if e.get("ph") == "i"]
        phases = {e["name"].split("@")[0] for e in marks}
        assert "close-seal" in phases and "externalize" in phases
        # clock alignment produced an offset for every scraped node
        assert set(obs["clock_offsets_s"]) == set(obs["trace_nodes"])
        assert len(obs["trace_nodes"]) == 5
        # ISSUE 16 acceptance: the SLO curve section — close p99 as a
        # time series per node, not an end-of-run point
        scr = obs["scraper"]
        assert scr["polls"] > 0
        close_curves = scr["curves"]["close_p99_s"]
        assert any(len(series) >= 2 for series in close_curves.values())
        assert scr["divergence"]["close_p99_s"] is not None
        # the fleet-wide burn tracker evaluated and stayed in budget
        assert scr["slo"]["objectives"]["close-p99"]["evaluations"] > 0


@pytest.mark.slow
class TestFleetSoak:
    def test_larger_soak_with_overload_burst(self, tmp_path):
        """The long campaign: sustained traffic at capacity, a 3x
        overload burst (shedding must engage and stay bounded), a longer
        partition that forces SCP-state recovery, and a full rolling
        config change — SLOs asserted over ~2 minutes of fleet time."""
        schedule = [
            {"kind": "traffic", "rate_per_s": 30.0},
            {"kind": "wait-ledger", "seq": 10},
            {"kind": "kill", "node": 2},
            {"kind": "rejoin", "node": 2, "parallel": 2},
            {"kind": "wait-ledger", "seq": 20},
            # overload burst: ~3x the per-close apply capacity
            {"kind": "traffic", "rate_per_s": 90.0},
            {"kind": "wait-s", "s": 8.0},
            {"kind": "traffic", "rate_per_s": 30.0},
            {"kind": "partition", "groups": [[0, 1, 2], [3, 4]]},
            {"kind": "wait-s", "s": 10.0},
            {"kind": "heal", "timeout_s": 90.0},
            {"kind": "rolling-config",
             "overrides": {"ADMISSION_BATCH_SIZE": 128,
                           "LOG_LEVEL": "WARNING"}},
            {"kind": "wait-ledger", "seq": 45},
        ]
        report = run_fleet_soak(
            str(tmp_path), n_nodes=5, schedule=schedule, n_accounts=120,
            slos=FleetSLOs(max_p99_close_s=2.0, max_shed_rate=0.5,
                           max_retracking_s=120.0, max_roll_node_s=90.0,
                           min_sustained_tps=5.0),
            timeout_s=600.0)
        assert report["passed"], report["violations"]
        assert report["max_ledger"] >= 45
        # overload engaged the shedding machinery at least once
        assert report["traffic"]["statuses"].get("TRY-AGAIN-LATER", 0) > 0
        assert report["divergence_seqs_compared"] >= 20
