"""Always-on sampling profiler (ISSUE 16): start/stop idempotence,
subsystem attribution, folded-stack export, crash-bundle ride-along,
and the /profile admin endpoint.
"""

import json
import threading
import time
import urllib.request

import pytest

from stellar_core_tpu.util import eventlog, metrics
from stellar_core_tpu.util.sampleprof import (SamplingProfiler,
                                              _subsystem_of)


@pytest.fixture(autouse=True)
def _fresh_registry():
    metrics.reset_registry()
    yield


class TestSubsystemMapping:
    @pytest.mark.parametrize("path,expected", [
        ("/root/repo/stellar_core_tpu/ledger/manager.py", "ledger"),
        ("/x/stellar_core_tpu/util/tracing.py", "util"),
        ("/x/stellar_core_tpu/herder/admission.py", "herder"),
        # a module directly under the package roots to its own name
        ("/x/stellar_core_tpu/testutils.py", "testutils"),
        ("/usr/lib/python3.11/threading.py", "other"),
        ("C:\\work\\stellar_core_tpu\\bucket\\fresh.py", "bucket"),
    ])
    def test_mapping(self, path, expected):
        assert _subsystem_of(path) == expected


class TestLifecycle:
    def test_start_stop_idempotent(self):
        p = SamplingProfiler(hz=200.0)
        assert p.start() is True
        try:
            assert p.start() is False      # already running
            assert p.running()
        finally:
            assert p.stop() is True
        assert p.stop() is False           # already stopped
        assert not p.running()

    def test_restart_after_stop(self):
        p = SamplingProfiler(hz=200.0)
        p.start()
        p.stop()
        assert p.start() is True
        p.stop()

    def test_sampler_thread_does_not_sample_itself(self):
        p = SamplingProfiler(hz=500.0)
        p.start()
        # burn CPU on this thread so samples land somewhere
        deadline = time.time() + 1.0
        while time.time() < deadline and p.snapshot()["samples"] < 5:
            sum(i * i for i in range(1000))
        p.stop()
        snap = p.snapshot()
        assert snap["samples"] >= 5
        for row in snap["top_stacks"]:
            assert "_sample_once" not in row["stack"]

    def test_running_gauge_tracks_state(self):
        p = SamplingProfiler(hz=200.0)
        assert metrics.registry().snapshot()[
            "profile.sampler.running"]["value"] == 0.0
        p.start()
        try:
            assert metrics.registry().snapshot()[
                "profile.sampler.running"]["value"] == 1.0
        finally:
            p.stop()


class TestCollection:
    def _sample_busy(self, p, min_samples=10, timeout=5.0):
        stop = threading.Event()

        def busy():
            while not stop.is_set():
                sum(i * i for i in range(500))

        t = threading.Thread(target=busy, name="busy", daemon=True)
        t.start()
        p.start()
        deadline = time.time() + timeout
        while time.time() < deadline \
                and p.snapshot()["samples"] < min_samples:
            time.sleep(0.01)
        p.stop()
        stop.set()
        t.join(2.0)

    def test_snapshot_shape_and_folded(self):
        p = SamplingProfiler(hz=500.0)
        self._sample_busy(p)
        snap = p.snapshot()
        assert snap["samples"] >= 10
        assert snap["hz"] == 500.0
        assert snap["subsystems"]
        total = sum(s["samples"] for s in snap["subsystems"].values())
        assert total == snap["samples"]
        folded = p.folded()
        assert folded
        for line in folded.splitlines():
            stack, count = line.rsplit(" ", 1)
            assert ";" in stack or stack  # root-only stacks are legal
            assert int(count) >= 1
        # the metric mirrors the in-state sample count
        assert metrics.registry().snapshot()[
            "profile.sampler.samples"]["count"] == snap["samples"]

    def test_reset_clears_state(self):
        p = SamplingProfiler(hz=500.0)
        self._sample_busy(p)
        p.reset()
        snap = p.snapshot()
        assert snap["samples"] == 0
        assert snap["subsystems"] == {}
        assert p.folded() == ""

    def test_crash_bundle_carries_folded_stacks(self, tmp_path):
        p = SamplingProfiler(hz=500.0)
        self._sample_busy(p)
        p.start()   # bundle source registered while running
        try:
            path = eventlog.write_crash_bundle(
                "test crash", crash_dir=str(tmp_path))
            bundle = json.loads(open(path).read())
            prof = bundle["profile"]
            assert prof["samples"] >= 10
            assert prof["folded"]
            assert prof["subsystems"]
        finally:
            p.stop()


class TestSingleton:
    def test_env_gate(self, monkeypatch):
        import stellar_core_tpu.util.sampleprof as sp
        monkeypatch.setattr(sp, "_profiler", None)
        monkeypatch.setenv("STPU_SAMPLEPROF", "0")
        assert sp.start_if_configured() is False
        monkeypatch.setenv("STPU_SAMPLEPROF", "1")
        monkeypatch.setenv("STPU_SAMPLEPROF_HZ", "250")
        try:
            assert sp.start_if_configured() is True
            assert sp.profiler().hz == 250.0
            assert sp.start_if_configured() is False  # already on
        finally:
            sp.profiler().stop()
            monkeypatch.setattr(sp, "_profiler", None)


class TestProfileEndpoint:
    @pytest.fixture()
    def app_http(self):
        from stellar_core_tpu.main.application import Application
        from stellar_core_tpu.main.config import Config
        from stellar_core_tpu.main.http_admin import CommandHandler
        from stellar_core_tpu.util.clock import ClockMode, VirtualClock

        cfg = Config.from_dict({
            "NETWORK_PASSPHRASE": "sampleprof test net",
            "RUN_STANDALONE": True,
            "PEER_PORT": 0,
            "SAMPLEPROF": True,
        })
        clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        app = Application(cfg, clock=clock, listen=False)
        http = CommandHandler(app, 0)
        http.start()
        app.start()
        assert clock.crank_until(
            lambda: app.lm.last_closed_ledger_seq >= 3, timeout=60)
        try:
            yield app, clock, http.port
        finally:
            http.stop()
            app.stop()
            from stellar_core_tpu.util import sampleprof
            sampleprof.profiler().stop()

    def _get(self, port, path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10.0) as r:
            return r.read(), r.headers.get("Content-Type", "")

    def test_profile_json_and_folded(self, app_http):
        app, clock, port = app_http
        body, ctype = self._get(port, "/profile")
        doc = json.loads(body)
        assert doc["running"] is True    # SAMPLEPROF config started it
        assert "subsystems" in doc and "top_stacks" in doc
        body, ctype = self._get(port, "/profile?format=folded")
        assert ctype.startswith("text/plain")
